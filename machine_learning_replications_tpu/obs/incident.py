"""Incident flight recorder: capture the evidence WHEN the rule fires.

An alert tells you *that* something broke; by the time a human reads
it, the evidence — tail samples in the flight recorder, the history
window around onset, per-replica load state, the journal context — has
aged out of the bounded rings. `IncidentCapturer` snapshots all of it
the moment a rule transitions to firing:

* rate-limited (`min_interval_s` between bundles) and single-flight
  (one capture thread at a time, later firings during a capture are
  dropped and counted) — an alert storm must not fork-bomb the host
  with capture threads or fill the disk;
* the bundle is a timestamped directory of JSON files written with
  ``persist.atomicio`` durability, and ``manifest.json`` is written
  LAST via the atomic path — **manifest presence is the completeness
  marker**. A crash mid-capture leaves a manifest-less directory that
  readers (and the next capture's retention sweep) treat as garbage;
* bounded retention: only the newest `retention` complete bundles are
  kept.

What lands in a bundle is supplied by the wiring as named zero-arg
`collectors` (router: `/debug/requests` tail, fleet trace join,
registry/load snapshot; replica: its own recorder tail + SLO state) —
this module stays generic, jax-free, and loop-free: captures run on a
short-lived daemon thread, never on an event loop.

`tools/incident_report.py` renders a bundle for humans.
"""

from __future__ import annotations

import os
import shutil
import threading
import time

from machine_learning_replications_tpu.obs import journal
from machine_learning_replications_tpu.obs.registry import REGISTRY
from machine_learning_replications_tpu.persist.atomicio import (
    atomic_json_write,
    fsync_json_dump,
)

INCIDENT_CAPTURES = REGISTRY.counter(
    "incident_captures_total",
    "Incident-bundle capture attempts by result (captured / "
    "rate_limited / in_flight / error).",
    labels=("result",),
)
for _result in ("captured", "rate_limited", "in_flight", "error"):
    INCIDENT_CAPTURES.labels(result=_result)

MANIFEST = "manifest.json"
SCHEMA_VERSION = 1


def _stamp(now: float) -> str:
    """Filesystem-safe UTC stamp (20260806T101530Z) of a wall time."""
    return time.strftime("%Y%m%dT%H%M%SZ", time.gmtime(now))


class IncidentCapturer:
    """One per process. `maybe_capture(transition)` is called by the
    sampler tick for every `fired` transition; the capture itself runs
    on its own daemon thread."""

    def __init__(
        self,
        out_dir: str | os.PathLike,
        store=None,
        collectors: dict | None = None,
        min_interval_s: float = 60.0,
        retention: int = 8,
        window_s: float = 900.0,
        say=None,
    ) -> None:
        self.out_dir = os.path.abspath(os.fspath(out_dir))
        os.makedirs(self.out_dir, exist_ok=True)
        self.store = store
        self.collectors = dict(collectors or {})
        self.min_interval_s = float(min_interval_s)
        self.retention = int(retention)
        self.window_s = float(window_s)
        self.say = say
        self.journal_tail_lines = 200
        self._lock = threading.Lock()
        self._in_flight = False
        self._last_capture_t: float | None = None  # monotonic
        self._threads: list[threading.Thread] = []

    # -- trigger side --------------------------------------------------------

    def maybe_capture(self, transition: dict) -> str | None:
        """Admission control + thread spawn. Returns the decision
        ("captured" meaning *started*; the bundle lands async)."""
        if transition.get("transition") != "fired":
            return None
        now_m = time.monotonic()
        with self._lock:
            if self._in_flight:
                INCIDENT_CAPTURES.inc(result="in_flight")
                return "in_flight"
            if (self._last_capture_t is not None
                    and now_m - self._last_capture_t
                    < self.min_interval_s):
                INCIDENT_CAPTURES.inc(result="rate_limited")
                return "rate_limited"
            self._in_flight = True
            self._last_capture_t = now_m
        t = threading.Thread(
            target=self._capture_and_release,
            args=(dict(transition),),
            name="incident-capture",
            daemon=True,
        )
        self._threads.append(t)
        t.start()
        return "captured"

    def _capture_and_release(self, transition: dict) -> None:
        try:
            self.capture(transition)
        finally:
            with self._lock:
                self._in_flight = False

    # -- capture side --------------------------------------------------------

    def capture(self, transition: dict) -> str | None:
        """Synchronous capture (the thread body; tests call it
        directly). Returns the bundle directory, or None on error."""
        at = transition.get("at")
        now = float(at) if isinstance(at, (int, float)) \
            else time.time()  # graftcheck: disable=monotonic-clock
        rule = str(transition.get("rule", "unknown"))
        name = f"incident_{_stamp(now)}_{rule}"
        bundle = os.path.join(self.out_dir, name)
        try:
            os.makedirs(bundle, exist_ok=True)
            files, errors = self._write_bundle(bundle, transition, now)
            atomic_json_write(os.path.join(bundle, MANIFEST), {
                "schema": SCHEMA_VERSION,
                "rule": rule,
                "severity": transition.get("severity"),
                "captured_at": journal.utc_now_iso(),
                "window_s": self.window_s,
                "files": sorted(files),
                "errors": errors,
            })
        except Exception:
            INCIDENT_CAPTURES.inc(result="error")
            return None
        INCIDENT_CAPTURES.inc(result="captured")
        journal.event(
            "incident_captured",
            rule=rule,
            dir=bundle,
            files=len(files),
        )
        if self.say:
            self.say(f"incident bundle captured: {bundle}")
        self._prune()
        return bundle

    def _write_bundle(self, bundle, transition, now):
        files, errors = [], {}

        def put(fname, obj):
            fsync_json_dump(os.path.join(bundle, fname), obj)
            files.append(fname)

        put("alert.json", transition)
        if self.store is not None:
            try:
                put("history.json", self.store.dump(self.window_s, now))
            except Exception as exc:
                errors["history.json"] = repr(exc)
        for cname, collect in sorted(self.collectors.items()):
            fname = f"{cname}.json"
            try:
                put(fname, collect())
            except Exception as exc:
                errors[fname] = repr(exc)
        tail = self._journal_tail()
        if tail is not None:
            path = os.path.join(bundle, "journal_tail.jsonl")
            with open(path, "w", encoding="utf-8") as fh:
                fh.write(tail)
                fh.flush()
                os.fsync(fh.fileno())
            files.append("journal_tail.jsonl")
        return files, errors

    def _journal_tail(self) -> str | None:
        jr = journal.get_journal()
        if jr is None:
            return None
        try:
            with open(jr.path, encoding="utf-8", errors="replace") as fh:
                lines = fh.readlines()
        except OSError:
            return None
        return "".join(lines[-self.journal_tail_lines:])

    # -- retention -----------------------------------------------------------

    def bundles(self) -> list[str]:
        """Complete bundles (manifest present), oldest first — the
        directory-name stamp sorts chronologically."""
        out = []
        try:
            names = sorted(os.listdir(self.out_dir))
        except OSError:
            return []
        for n in names:
            d = os.path.join(self.out_dir, n)
            if n.startswith("incident_") and \
                    os.path.exists(os.path.join(d, MANIFEST)):
                out.append(d)
        return out

    def _prune(self) -> None:
        """Keep the newest `retention` complete bundles; incomplete
        (manifest-less) directories are crash leftovers — always
        swept."""
        try:
            names = sorted(os.listdir(self.out_dir))
        except OSError:
            return
        complete, partial = [], []
        for n in names:
            if not n.startswith("incident_"):
                continue
            d = os.path.join(self.out_dir, n)
            if os.path.exists(os.path.join(d, MANIFEST)):
                complete.append(d)
            else:
                partial.append(d)
        doomed = partial + (
            complete[:-self.retention] if self.retention > 0 else []
        )
        for d in doomed:
            shutil.rmtree(d, ignore_errors=True)

    def close(self, timeout_s: float = 5.0) -> None:
        """Wait for any in-flight capture — shutdown must not truncate
        the one bundle the process crashed hard enough to need."""
        for t in self._threads:
            t.join(timeout=timeout_s)
        self._threads.clear()
