"""JSONL run journal: a manifest line, then structured events.

A multi-hour fit (or a long-lived serving process) answered "what produced
this artifact?" with nothing: ``stage_say`` printed free-text lines to
stderr with a time-of-day timestamp, and the BENCH artifacts carried
numbers with no record of the code or config that made them. The journal
fixes both:

  * **Manifest first.** The journal's first record is a run manifest —
    run id, ISO-8601 UTC start time, command, git sha (+dirty flag),
    package/jax/python versions, platform, and a sha256 hash of the
    ExperimentConfig JSON — so any journal (and any BENCH artifact, which
    embeds the same manifest) names exactly what produced it.
    ``run_manifest`` builds the dict without importing jax (versions come
    from ``importlib.metadata``): ``bench.py``'s orchestrator, which must
    never touch the TPU plugin, calls it too (enforced: graftcheck rule
    ``import-purity``; event names and required keys live in the
    ``obs.catalog`` EVENTS catalog, rule ``journal-catalog``).
  * **Structured events after.** One JSON object per line, ``ts`` in
    ISO-8601 UTC (the r4 lesson behind ``stage_say``'s timestamp fix: a
    multi-hour log with time-of-day-only local stamps is ambiguous across
    midnight and timezones), ``kind`` plus event-specific fields. The
    stage runners emit ``stage_start`` / ``stage_done`` /
    ``checkpoint_restore``; the serving batcher emits ``flush``; the
    model-quality monitor emits ``quality_status`` on every
    ``ok``/``warn``/``alert`` drift transition, and restoring a
    pre-profile checkpoint emits ``quality_profile_missing``
    (``obs.quality``, ``persist.orbax_io``).

``stage_scope`` is the deduplication point the stage runners share: the
same stderr lines ``models.pipeline._NullStages`` and
``persist.orbax_io.StageCheckpointer`` used to format independently, plus
a span and journal events, in one code path.

A process-global *active* journal (``set_journal`` / ``get_journal``)
mirrors the active tracer: call sites log unconditionally through the
module-level ``event``, which is a no-op until a journal is installed.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import subprocess
import sys
import threading
import time
import uuid
from typing import Any, Iterator

from machine_learning_replications_tpu.obs import spans


def utc_now_iso() -> str:
    """ISO-8601 UTC to millisecond precision, 'Z'-suffixed."""
    # Wall-clock by intent: this IS the human/manifest timestamp path
    # (rule monotonic-clock allows it only here, visibly).
    t = time.time()  # graftcheck: disable=monotonic-clock
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(t)) + (
        ".%03dZ" % (int(t * 1000) % 1000)
    )


def _git_sha(repo_dir: str | None = None) -> dict:
    """Best-effort git provenance (sha + dirty flag); {} outside a repo or
    without git. Never raises — a manifest must not be able to fail a run.

    The repo must BE the package's own checkout: ``git rev-parse`` walks
    upward, so a pip-installed copy whose site-packages happens to live
    inside some unrelated repository (venv-in-project layout) would
    otherwise stamp that project's HEAD into the manifest — silently wrong
    provenance is worse than none."""
    cwd = repo_dir or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    ))
    try:
        top = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"], cwd=cwd, timeout=10,
            capture_output=True, text=True,
        )
        if top.returncode != 0 or os.path.realpath(top.stdout.strip()) != \
                os.path.realpath(cwd):
            return {}
        sha = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=cwd, timeout=10,
            capture_output=True, text=True,
        )
        if sha.returncode != 0:
            return {}
        dirty = subprocess.run(
            ["git", "status", "--porcelain"], cwd=cwd, timeout=10,
            capture_output=True, text=True,
        )
        return {
            "git_sha": sha.stdout.strip(),
            "git_dirty": bool(dirty.stdout.strip())
            if dirty.returncode == 0 else None,
        }
    except (OSError, subprocess.SubprocessError):
        return {}


def _dist_version(name: str) -> str | None:
    """Package version from installed metadata — crucially WITHOUT importing
    the package (the bench orchestrator records jax's version while staying
    unable to hang on jax's backend init)."""
    try:
        from importlib.metadata import version

        return version(name)
    except Exception:
        return None


def config_hash(config_json: str | bytes | None) -> str | None:
    """sha256 of the config JSON — the manifest's binding to hyperparameters
    (the stage-checkpoint fingerprint binds to data too; this one is cheap
    and comparable across cohorts)."""
    if config_json is None:
        return None
    if isinstance(config_json, str):
        config_json = config_json.encode()
    return hashlib.sha256(config_json).hexdigest()


def run_manifest(
    command: str | None = None,
    config_json: str | None = None,
    extra: dict | None = None,
) -> dict:
    """The run-provenance record every journal starts with and every BENCH
    artifact embeds. jax-import-free by design (see module docstring)."""
    import platform

    man = {
        "kind": "manifest",
        "run_id": uuid.uuid4().hex[:12],
        "ts": utc_now_iso(),
        "command": command,
        "argv": list(sys.argv),
        "python": platform.python_version(),
        "platform": platform.platform(),
        "hostname": platform.node(),
        "pid": os.getpid(),
        "versions": {
            "machine_learning_replications_tpu":
                _dist_version("machine-learning-replications-tpu"),
            "jax": _dist_version("jax"),
            "jaxlib": _dist_version("jaxlib"),
        },
        "config_hash": config_hash(config_json),
        **_git_sha(),
    }
    if extra:
        man.update(extra)
    return man


class RunJournal:
    """Append-structured-events-to-one-file; first record is the manifest.

    Writes are line-buffered under a lock and flushed per event: a
    preempted run's journal is readable up to the last completed event
    (the same durability posture as ``stage_say``'s flush=True)."""

    def __init__(
        self,
        path: str | os.PathLike,
        command: str | None = None,
        config_json: str | None = None,
        extra: dict | None = None,
    ) -> None:
        self.path = os.path.abspath(os.fspath(path))
        parent = os.path.dirname(self.path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._lock = threading.Lock()
        self._f = open(self.path, "w")
        self.manifest = run_manifest(
            command=command, config_json=config_json, extra=extra
        )
        self._write(self.manifest)

    def _write(self, rec: dict) -> None:
        line = json.dumps(rec, separators=(",", ":"), default=str)
        with self._lock:
            if self._f.closed:
                return
            self._f.write(line + "\n")
            self._f.flush()

    def event(self, kind: str, **fields: Any) -> None:
        self._write({"ts": utc_now_iso(), "kind": kind, **fields})

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()

    def __enter__(self) -> "RunJournal":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- process-global active journal -----------------------------------------

_active: RunJournal | None = None
_active_lock = threading.Lock()


def set_journal(journal: RunJournal | None) -> None:
    """Install (or clear, with None) the process-global active journal."""
    global _active
    with _active_lock:
        _active = journal


def get_journal() -> RunJournal | None:
    return _active


def event(kind: str, **fields: Any) -> None:
    """Record an event on the active journal; no-op without one."""
    journal = _active
    if journal is not None:
        # Forwarder, not an emit site: kind is the caller's literal
        # (rule journal-catalog checks the call sites).
        journal.event(kind, **fields)  # graftcheck: disable=journal-catalog


# -- the shared stage runner scope ------------------------------------------


@contextlib.contextmanager
def stage_scope(name: str, done_suffix: str = "") -> Iterator[spans.SpanHandle]:
    """The ONE stage-timing code path for both pipeline stage runners
    (``models.pipeline._NullStages`` straight-through and
    ``persist.orbax_io.StageCheckpointer`` durable): emits the
    grep-identical ``stage_say`` stderr lines both used to format
    themselves, wraps the body in a span (``stage:<name>``), and journals
    ``stage_start`` / ``stage_done`` / ``stage_error``. ``done_suffix`` is
    the checkpointer's " (checkpointed)" tail; the yielded handle's
    ``block`` defers device completion to scope exit, inside the timing.
    """
    from machine_learning_replications_tpu.utils.trace import stage_say

    stage_say(f"stage {name!r} ...")
    event("stage_start", stage=name)
    # perf_counter, not wall clock: an NTP step mid-stage used to produce
    # negative (or hours-long) stage_done seconds (rule monotonic-clock).
    t0 = time.perf_counter()
    try:
        with spans.span(f"stage:{name}") as handle:
            yield handle
    except BaseException as exc:
        event(
            "stage_error", stage=name,
            seconds=round(time.perf_counter() - t0, 3),
            error=f"{type(exc).__name__}: {exc}",
        )
        raise
    dt = time.perf_counter() - t0
    stage_say(f"stage {name!r} done in {dt:.1f}s{done_suffix}")
    event(
        "stage_done", stage=name, seconds=round(dt, 3),
        checkpointed=bool(done_suffix),
    )
