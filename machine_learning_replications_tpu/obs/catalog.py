"""The closed catalogs of metric families and journal events.

Two registries of *names* used to live scattered across the codebase as
string literals: every ``REGISTRY.counter/gauge/histogram`` family name,
and every ``journal.event`` kind. Both are now declared here, in one
pure-literal module, and enforced statically by graftcheck
(``metrics-catalog`` / ``journal-catalog`` — docs/ANALYSIS.md):

  * a family registered in code but absent here fails CI (and vice
    versa: a catalog entry nothing registers is dead weight and fails
    too);
  * an event emitted under a name not in ``EVENTS``, or missing one of
    its required keys, fails CI — a dashboard or drill that greps the
    journal for ``fleet_rotation`` can trust the name exists and carries
    ``replica``/``direction``/``reason``.

graftcheck reads this file with ``ast.literal_eval`` — never imports it —
so BOTH dicts must stay literal (no comprehensions, no f-strings, no
calls). docs/OBSERVABILITY.md's family table is cross-checked against
``METRICS`` by the same rule.

The serving layer's fixed ``serve_*`` instruments (``serve/metrics.py``)
predate labeled families and render through their own exposition path;
they are outside ``METRICS`` by design (see docs/OBSERVABILITY.md).
"""

from __future__ import annotations

#: Every process-global metric family: name -> (kind, label names).
#: Kind is "counter" | "gauge" | "histogram".
METRICS: dict[str, tuple[str, tuple[str, ...]]] = {
    # -- serve/ --------------------------------------------------------------
    "serve_path_total": ("counter", ("path",)),
    "serve_host_fallback_total": ("counter", ()),
    "serve_warmup_seconds": ("gauge", ("path", "bucket")),
    "serve_aot_restore_seconds": ("gauge", ("path", "bucket")),
    "serve_aot_fallback_total": ("counter", ("reason",)),
    "serve_deploys_total": ("counter", ("result",)),
    "serve_model_version": ("gauge", ()),
    "serve_worker_info": ("gauge", ("worker",)),
    # -- obs/ ----------------------------------------------------------------
    "profile_captures_total": ("counter", ("outcome",)),
    "quality_feature_psi": ("gauge", ("feature",)),
    "quality_feature_ks": ("gauge", ("feature",)),
    "quality_score_psi": ("gauge", ()),
    "quality_member_disagreement": ("gauge", ()),
    "quality_window_rows": ("gauge", ()),
    "quality_status": ("gauge", ()),
    "quality_rows_total": ("counter", ()),
    "quality_status_transitions_total": ("counter", ("to",)),
    "quality_feed_dropped_rows_total": ("counter", ("reason",)),
    "quality_feed_depth": ("gauge", ()),
    "reqtrace_sampled_total": ("counter", ("reason",)),
    "reqtrace_dropped_total": ("counter", ()),
    "jax_compiles_total": ("counter", ()),
    "jax_compile_seconds_total": ("counter", ()),
    "jax_trace_seconds_total": ("counter", ()),
    "jax_compilation_cache_events_total": ("counter", ("event",)),
    "jax_transfer_bytes_total": ("counter", ("direction",)),
    "slo_requests_total": ("counter", ("slo",)),
    "slo_bad_total": ("counter", ("slo",)),
    "slo_good_ratio": ("gauge", ("slo",)),
    "slo_burn_rate": ("gauge", ("slo",)),
    "slo_error_budget_remaining_ratio": ("gauge", ("slo",)),
    "slo_target_ratio": ("gauge", ("slo",)),
    "history_samples_total": ("counter", ()),
    "history_series": ("gauge", ()),
    "alerts_active": ("gauge", ("rule", "severity")),
    "alerts_transitions_total": ("counter", ("rule", "transition")),
    "incident_captures_total": ("counter", ("result",)),
    # -- resilience/ ---------------------------------------------------------
    "fault_injected_total": ("counter", ("site",)),
    "resilience_checkpoint_rollbacks_total": ("counter", ()),
    "resilience_breaker_state": ("gauge", ()),
    "resilience_breaker_transitions_total": ("counter", ("to",)),
    "resilience_engine_restarts_total": ("counter", ("result",)),
    "resilience_watchdog_trips_total": ("counter", ()),
    "resilience_degraded_sheds_total": ("counter", ()),
    # -- fleet/ --------------------------------------------------------------
    "fleet_replicas": ("gauge", ("state",)),
    "fleet_rotations_total": ("counter", ("direction",)),
    "fleet_probe_total": ("counter", ("result",)),
    "fleet_requests_total": ("counter", ("outcome",)),
    "fleet_upstream_attempts_total": ("counter", ("result",)),
    "fleet_retries_total": ("counter", ("reason",)),
    "fleet_hedges_total": ("counter", ()),
    "fleet_hedge_wins_total": ("counter", ()),
    "fleet_replica_requests_total": ("counter", ("replica", "result")),
    "fleet_request_latency_seconds": ("histogram", ()),
    "fleet_deploys_total": ("counter", ("result",)),
    "fleet_upstream_connections_total": ("counter", ("event",)),
    "fleet_capture_dropped_total": ("counter", ()),
    "fleet_clock_offset_ms": ("gauge", ("replica",)),
    "fleet_trace_joins_total": ("counter", ("result",)),
    "fleet_scrape_total": ("counter", ("result",)),
    "fleet_scrape_stale": ("gauge", ("replica",)),
    "fleet_scrape_merge_rejected_total": ("counter", ("reason",)),
    "fleet_slo_requests_total": ("counter", ("slo",)),
    "fleet_slo_bad_total": ("counter", ("slo",)),
    "fleet_slo_good_ratio": ("gauge", ("slo",)),
    "fleet_slo_burn_rate": ("gauge", ("slo",)),
    "fleet_slo_error_budget_remaining_ratio": ("gauge", ("slo",)),
    "fleet_slo_target_ratio": ("gauge", ("slo",)),
    "lifecycle_transitions_total": ("counter", ("event",)),
    "lifecycle_replicas": ("gauge", ("state",)),
    "autoscale_decisions_total": ("counter", ("decision",)),
    "autoscale_signal": ("gauge", ("signal",)),
    "autoscale_streak": ("gauge", ("kind",)),
    "autoscale_desired_replicas": ("gauge", ()),
    # -- learn/ --------------------------------------------------------------
    "learn_capture_rows_total": ("counter", ()),
    "learn_capture_retained_rows": ("gauge", ()),
    "learn_trigger_total": ("counter", ("outcome",)),
    "learn_trigger_alert_streak": ("gauge", ()),
    "learn_retrain_total": ("counter", ("result",)),
    "learn_retrain_seconds": ("gauge", ()),
    "learn_shadow_divergence_mean": ("gauge", ()),
    "learn_shadow_divergence_p95": ("gauge", ()),
    "learn_shadow_divergence_max": ("gauge", ()),
    "learn_shadow_flip_rate": ("gauge", ()),
    "learn_shadow_score_psi": ("gauge", ()),
    "learn_shadow_candidate_worst_psi": ("gauge", ()),
    "learn_shadow_candidate_status": ("gauge", ()),
    "learn_shadow_disagreement_delta": ("gauge", ()),
    "learn_shadow_rows": ("gauge", ()),
    "learn_shadow_evaluations_total": ("counter", ("verdict",)),
    "learn_promotions_total": ("counter", ("result",)),
    # -- score/ --------------------------------------------------------------
    "score_rows_total": ("counter", ()),
    "score_quarantined_rows_total": ("counter", ()),
    "score_chunks_total": ("counter", ()),
    "score_chunk_seconds": ("histogram", ()),
    "score_queue_depth": ("gauge", ("stage",)),
    "score_stage_seconds_total": ("counter", ("stage",)),
}

#: Every journal event kind -> the keys EVERY emit site must carry.
#: (Sites may add more; ``**extra`` spreads satisfy any requirement at
#: the spread site but graftcheck still requires the kind to be listed.)
#: The run manifest record (kind="manifest") is written directly by
#: ``RunJournal.__init__``, not through ``event``, and is not an entry.
EVENTS: dict[str, tuple[str, ...]] = {
    # -- run lifecycle (cli, journal) ---------------------------------------
    "run_done": (),
    "run_error": ("error",),
    "stage_start": ("stage",),
    "stage_done": ("stage", "seconds", "checkpointed"),
    "stage_error": ("stage", "seconds", "error"),
    # -- serving (serve/) ----------------------------------------------------
    "flush": ("seq", "rows", "ok"),
    "deploy_start": ("path", "from_version", "replica"),
    "deploy_applied": (
        "path", "from_version", "to_version", "replica", "seconds",
    ),
    "deploy_failed": ("path", "error", "replica", "seconds"),
    "deploy_quality_detached": ("path",),
    # -- checkpoints (persist/) ---------------------------------------------
    "checkpoint_publish": ("path", "version"),
    "aot_export": ("path", "blobs", "seconds"),
    "aot_restore": ("role", "bucket", "seconds"),
    "aot_fallback": ("reason",),
    "checkpoint_restore": ("stage",),
    "checkpoint_corrupt": ("stage", "error"),
    "checkpoint_retain_skipped": ("path", "error"),
    "checkpoint_rollback": ("path", "lastgood", "error"),
    # -- resilience/ ---------------------------------------------------------
    "fault_armed": ("site", "spec"),
    "fault_disarmed": ("site",),
    "fault_injected": ("site", "mode", "fire", "spec"),
    "faults_reset": ("sites",),
    "breaker_open": ("reason", "wedged"),
    "breaker_close": ("attempts", "open_seconds"),
    "engine_restart": ("attempt", "ok", "seconds"),
    "engine_swap": ("warm",),
    # -- observability (obs/) ------------------------------------------------
    "profile_capture": ("ok", "seconds"),
    "quality_status": (
        "from_status", "to_status", "window_rows", "worst_feature",
        "worst_psi", "score_psi",
    ),
    "quality_rebased": ("reference_rows", "feature_bins"),
    "quality_profile_missing": ("path",),
    "quality_feed_disabled": ("error",),
    "quality_feed_reenabled": ("after",),
    "alert_fired": ("rule", "severity", "value"),
    "alert_resolved": ("rule", "severity", "seconds"),
    "incident_captured": ("rule", "dir", "files"),
    # -- fleet/ --------------------------------------------------------------
    "fleet_router_started": ("address", "replicas"),
    "fleet_replica_registered": ("replica", "url"),
    "fleet_replica_deregistered": ("replica", "url"),
    "fleet_rotation": ("replica", "direction", "reason"),
    "fleet_deploy_start": (
        "model", "target_version", "replicas", "concurrency",
    ),
    "fleet_deploy_replica": ("model",),
    "fleet_deploy_done": (
        "model", "target_version", "result", "error", "seconds",
    ),
    "fleet_trace_export": ("requests", "joined", "containment_ratio"),
    "fleet_scrape_transition": ("replica", "stale"),
    "replica_registered": ("replica", "router", "url"),
    "lifecycle_spawn": ("replica", "pid", "port", "attempt", "respawn"),
    "lifecycle_spawn_failed": (
        "replica", "reason", "attempts", "retry_in_s",
    ),
    "lifecycle_ready": ("replica", "url", "seconds", "respawn"),
    "lifecycle_crash": ("replica", "state", "detail"),
    "lifecycle_drain": ("replica", "reason", "settle_deadline_s"),
    "lifecycle_drain_error": ("replica", "error"),
    "lifecycle_term": (
        "replica", "delivered", "drained", "kill_deadline_s",
    ),
    "lifecycle_kill": ("replica", "reason"),
    "lifecycle_exit": ("replica", "code", "reason"),
    "autoscale_decision": ("decision", "reason", "ready", "desired"),
    "autoscale_tick_error": ("error",),
    # -- learn/ --------------------------------------------------------------
    "learn_trigger": ("fired", "reason"),
    "learn_settle": ("skipped",),
    "learn_retrain_start": ("family", "rows", "labels_source", "out"),
    "learn_retrain_done": (),
    "learn_retrain_failed": ("error", "rows", "seconds"),
    "learn_shadow_verdict": ("passed", "reasons"),
    "learn_promotion": ("candidate", "result"),
    "learn_candidate_published": ("candidate", "model", "version"),
    "learn_cycle_done": ("outcome",),
    "learn_recovery": ("recovered",),
    # -- score/ --------------------------------------------------------------
    "score_resume": ("chunks", "rows", "bad_rows", "lines"),
    "score_chunk": ("seq", "rows", "bad", "seconds"),
    "score_done": (
        "rows", "bad_rows", "chunks", "wall_seconds", "rows_per_second",
        "output_sha256",
    ),
}
