"""In-process time-series history: the substrate alert rules read.

The registry (`obs.registry`) answers "what is the value *now*"; alert
rules need "what has the value been doing" — a burn rate sustained for
five minutes, a gauge absent for thirty seconds, a PSI that moved 0.2
in a window. `TimeSeriesStore` closes that gap without an external TSDB:
a sampler thread snapshots the process-global registry (and, on the
router, the merged fleet page) at a fixed interval into bounded
per-series rings with tiered downsampling —

* **raw tier**: every sample at the sampling interval (default 10 s),
  kept for `raw_retention_s` (default 15 min);
* **aggregate tier**: one point per `agg_bucket_s` (default 1 min),
  kept for `agg_retention_s` (default 4 h). Each point carries the
  bucket's *average* (the right long-window summary for a gauge) and
  its *last* value (the right one for a cumulative counter — rate math
  needs the level at the bucket edge, not the mean of levels).

Scalar derivations are counter-reset-safe: `rate()` sums only positive
deltas (a restart's drop to zero contributes nothing), `delta()` reads
newest minus oldest for rate-of-change rules. Histograms keep their
cumulative bucket vectors in the raw tier only, and `quantile()`
computes a Prometheus-style interpolated quantile over the *windowed
delta* of those vectors — "p99 over the last 5 minutes", not since
process start.

Timestamps are wall-clock on purpose: history points must line up with
journal lines and incident bundles, and a query window of "the last
900 s" tolerates the same clock-step caveats Prometheus does. Tests
inject synthetic `now` values; production passes `time.time()`.

Everything here is jax-free and allocation-bounded: series count is
whatever the registry holds, each series holds at most
`raw_retention_s / interval + agg_retention_s / agg_bucket_s` points.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from machine_learning_replications_tpu.obs.registry import (
    REGISTRY,
    MetricsRegistry,
)

HISTORY_SAMPLES = REGISTRY.counter(
    "history_samples_total",
    "Sampling ticks the time-series history store has ingested.",
)
HISTORY_SERIES = REGISTRY.gauge(
    "history_series",
    "Live series (family x label combination) held by the history "
    "store.",
)

_SCALAR_KINDS = ("counter", "gauge")


def collect_registry(registry: MetricsRegistry = REGISTRY) -> dict:
    """One sampling pass over a live registry, in the same normalized
    shape ``fleetmetrics.parse_exposition`` produces — ``{family:
    {"kind", "series": {((label, value), ...): sample}}}`` — so the
    store ingests local instruments and scraped pages identically."""
    families: dict[str, dict] = {}
    for fam in registry.families():
        series: dict = {}
        for label_values, child in fam.collect():
            key = tuple(sorted(zip(fam.label_names, label_values)))
            if fam.kind == "histogram":
                series[key] = child.snapshot()
            else:
                series[key] = float(child.value)
        families[fam.name] = {"kind": fam.kind, "series": series}
    return families


class _Series:
    """One (family, label-set) stream: a raw ring plus, for scalars, the
    aggregate ring and the in-progress bucket it flushes from."""

    __slots__ = (
        "kind", "raw", "agg", "bucket_start", "bucket_sum", "bucket_n",
        "bucket_last",
    )

    def __init__(self, kind: str, raw_cap: int, agg_cap: int) -> None:
        self.kind = kind
        self.raw: deque = deque(maxlen=raw_cap)
        self.agg: deque = deque(maxlen=agg_cap)
        self.bucket_start: float | None = None
        self.bucket_sum = 0.0
        self.bucket_n = 0
        self.bucket_last = 0.0


class TimeSeriesStore:
    """Bounded, thread-safe history over normalized family snapshots.

    ``ingest(families, now)`` is the only writer (one sampler thread);
    every reader takes the same lock, copies out, and computes outside
    it — queries are served from bounded in-memory rings, never I/O."""

    def __init__(
        self,
        interval_s: float = 10.0,
        raw_retention_s: float = 900.0,
        agg_bucket_s: float = 60.0,
        agg_retention_s: float = 14400.0,
    ) -> None:
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        if agg_bucket_s < interval_s:
            raise ValueError("agg_bucket_s must be >= interval_s")
        self.interval_s = float(interval_s)
        self.raw_retention_s = float(raw_retention_s)
        self.agg_bucket_s = float(agg_bucket_s)
        self.agg_retention_s = float(agg_retention_s)
        # +2: the ring must hold the boundary sample a full-window query
        # differences against, plus one slot of scheduling jitter.
        self._raw_cap = int(raw_retention_s / interval_s) + 2
        self._agg_cap = int(agg_retention_s / agg_bucket_s) + 2
        self._lock = threading.Lock()
        self._series: dict[tuple[str, tuple], _Series] = {}
        self._last_ingest_t: float | None = None
        self._ticks = 0

    # -- write path ---------------------------------------------------------

    def ingest(self, families: dict, now: float) -> None:
        """One sampling tick: fold every series of every family in."""
        with self._lock:
            for name, fam in families.items():
                kind = fam.get("kind")
                if kind not in ("counter", "gauge", "histogram"):
                    continue
                for key, value in fam.get("series", {}).items():
                    sid = (name, tuple(key))
                    s = self._series.get(sid)
                    if s is None:
                        s = self._series[sid] = _Series(
                            kind, self._raw_cap, self._agg_cap
                        )
                    self._ingest_one(s, value, now)
            self._last_ingest_t = now
            self._ticks += 1
            n_series = len(self._series)
        HISTORY_SAMPLES.get().inc()
        HISTORY_SERIES.get().set(float(n_series))

    def _ingest_one(self, s: _Series, value, now: float) -> None:
        if s.kind == "histogram":
            s.raw.append((now, {
                "buckets": dict(value.get("buckets", {})),
                "sum": float(value.get("sum", 0.0)),
                "count": float(value.get("count", 0.0)),
            }))
            return
        v = float(value)
        if v != v:
            # A NaN gauge means "no reading this poll" (the
            # autoscale_signal convention): store nothing — absence is
            # the honest record, and NaN would poison every window
            # aggregate downstream.
            return
        s.raw.append((now, v))
        if s.bucket_start is None:
            s.bucket_start = now
        elif now - s.bucket_start >= self.agg_bucket_s:
            if s.bucket_n:
                s.agg.append((
                    s.bucket_start, s.bucket_sum / s.bucket_n,
                    s.bucket_last,
                ))
            s.bucket_start = now
            s.bucket_sum = 0.0
            s.bucket_n = 0
        s.bucket_sum += v
        s.bucket_n += 1
        s.bucket_last = v

    # -- read path ----------------------------------------------------------

    def families(self) -> dict[str, int]:
        """``{family: live series count}`` — the no-arg answer of
        ``/debug/history``."""
        with self._lock:
            out: dict[str, int] = {}
            for (name, _key) in self._series:
                out[name] = out.get(name, 0) + 1
            return dict(sorted(out.items()))

    def last_sample_age_s(self, family: str, now: float) -> float | None:
        """Seconds since the newest sample of *any* series of `family`
        (None when the family has never been sampled) — the absence
        rule's primitive."""
        newest = None
        with self._lock:
            for (name, _key), s in self._series.items():
                if name != family or not s.raw:
                    continue
                t = s.raw[-1][0]
                if newest is None or t > newest:
                    newest = t
        return None if newest is None else max(0.0, now - newest)

    def _select(self, family: str, labels: dict | None):
        """Matching (labels_dict, _Series) pairs; `labels` is a subset
        filter (every given pair must match)."""
        want = {(k, str(v)) for k, v in (labels or {}).items()}
        out = []
        for (name, key), s in self._series.items():
            if name != family:
                continue
            if want and not want <= set(key):
                continue
            out.append((dict(key), s))
        return out

    def window(
        self, family: str, window_s: float, now: float,
        labels: dict | None = None,
    ) -> list[tuple[dict, list]]:
        """Per matching series: raw points inside ``[now - window_s,
        now]``, prefixed by aggregate-tier points older than the raw
        tier still covers. Scalar points are ``(t, value)``; histogram
        points are ``(t, snapshot_dict)``."""
        t_from = now - float(window_s)
        with self._lock:
            picked = [
                (lab, s.kind, list(s.raw), list(s.agg))
                for lab, s in self._select(family, labels)
            ]
        out = []
        for lab, kind, raw, agg in picked:
            pts: list = []
            raw_start = raw[0][0] if raw else now
            if kind in _SCALAR_KINDS:
                # Aggregate points cover the span the raw ring has
                # already forgotten: average for gauges, bucket-edge
                # level for counters (rate math needs levels).
                use = 1 if kind == "gauge" else 2
                pts = [
                    (t, point[use])
                    for point in agg
                    if t_from <= (t := point[0]) < raw_start
                ]
            pts.extend(p for p in raw if p[0] >= t_from)
            if pts:
                out.append((lab, pts))
        return out

    def latest(
        self, family: str, labels: dict | None = None,
    ) -> list[tuple[dict, float, float]]:
        """Per matching scalar series: ``(labels, t, value)`` of the
        newest sample."""
        with self._lock:
            picked = [
                (lab, s.raw[-1])
                for lab, s in self._select(family, labels)
                if s.kind in _SCALAR_KINDS and s.raw
            ]
        return [(lab, t, v) for lab, (t, v) in picked]

    def avg(
        self, family: str, window_s: float, now: float,
        labels: dict | None = None,
    ) -> list[tuple[dict, float]]:
        """Per matching scalar series: mean over the window."""
        out = []
        for lab, pts in self.window(family, window_s, now, labels):
            vals = [v for _t, v in pts if isinstance(v, float)]
            if vals:
                out.append((lab, sum(vals) / len(vals)))
        return out

    def rate(
        self, family: str, window_s: float, now: float,
        labels: dict | None = None,
    ) -> list[tuple[dict, float]]:
        """Per matching counter series: increase per second over the
        window, reset-safe (only positive deltas count — a restart's
        drop to zero is a reset, not a negative rate)."""
        out = []
        for lab, pts in self.window(family, window_s, now, labels):
            pts = [(t, v) for t, v in pts if isinstance(v, float)]
            if len(pts) < 2:
                continue
            elapsed = pts[-1][0] - pts[0][0]
            if elapsed <= 0:
                continue
            inc = sum(
                max(0.0, b[1] - a[1]) for a, b in zip(pts, pts[1:])
            )
            out.append((lab, inc / elapsed))
        return out

    def delta(
        self, family: str, window_s: float, now: float,
        labels: dict | None = None,
    ) -> list[tuple[dict, float]]:
        """Per matching scalar series: newest minus oldest inside the
        window — the rate-of-change rule's primitive."""
        out = []
        for lab, pts in self.window(family, window_s, now, labels):
            pts = [(t, v) for t, v in pts if isinstance(v, float)]
            if len(pts) >= 2:
                out.append((lab, pts[-1][1] - pts[0][1]))
        return out

    def quantile(
        self, family: str, q: float, window_s: float, now: float,
        labels: dict | None = None,
    ) -> list[tuple[dict, float]]:
        """Per matching histogram series: interpolated quantile of the
        observations that landed *inside the window* (bucket-count delta
        between the window's edges), Prometheus `histogram_quantile`
        style: linear within the bucket, upper bound for +Inf."""
        if not 0.0 <= q <= 1.0:
            raise ValueError("q must be in [0, 1]")
        t_from = now - float(window_s)
        with self._lock:
            picked = [
                (lab, list(s.raw))
                for lab, s in self._select(family, labels)
                if s.kind == "histogram" and s.raw
            ]
        out = []
        for lab, raw in picked:
            newest = raw[-1][1]
            # The newest point at-or-before the window start is the
            # baseline; absent one (young series), the delta is the
            # newest cumulative state itself.
            base = None
            for t, snap in raw:
                if t <= t_from:
                    base = snap
                else:
                    break
            value = _histogram_delta_quantile(base, newest, q)
            if value is not None:
                out.append((lab, value))
        return out

    # -- dumps --------------------------------------------------------------

    def query(
        self, family: str, window_s: float | None, now: float,
        labels: dict | None = None,
    ) -> dict:
        """The ``/debug/history`` payload for one family."""
        window_s = float(window_s) if window_s else self.raw_retention_s
        series = []
        for lab, pts in self.window(family, window_s, now, labels):
            # Scalar points serialize as [t, value]; histogram points as
            # [t, count, sum] (buckets stay internal — quantile() is the
            # way to read them).
            series.append({
                "labels": lab,
                "points": [
                    [round(t, 3), v] if isinstance(v, float)
                    else [round(t, 3), v["count"], v["sum"]]
                    for t, v in pts
                ],
            })
        return {
            "family": family,
            "window_s": window_s,
            "interval_s": self.interval_s,
            "series": series,
        }

    def dump(self, window_s: float, now: float) -> dict:
        """Every family's windowed view — the incident bundle's
        ``history.json``."""
        return {
            name: self.query(name, window_s, now)
            for name in self.families()
        }

    def stats(self) -> dict:
        with self._lock:
            return {
                "series": len(self._series),
                "ticks": self._ticks,
                "interval_s": self.interval_s,
                "raw_retention_s": self.raw_retention_s,
                "agg_bucket_s": self.agg_bucket_s,
                "agg_retention_s": self.agg_retention_s,
            }


def _histogram_delta_quantile(base, newest, q: float) -> float | None:
    """Interpolated quantile of (newest - base) cumulative buckets."""
    deltas = []
    for le, cum in newest.get("buckets", {}).items():
        prev = (base or {}).get("buckets", {}).get(le, 0.0)
        d = max(0.0, float(cum) - float(prev))
        bound = float("inf") if le in ("+Inf", "inf") else float(le)
        deltas.append((bound, d))
    deltas.sort(key=lambda x: x[0])
    if not deltas:
        return None
    total = deltas[-1][1]
    if total <= 0:
        return None
    rank = q * total
    lower = 0.0
    prev_cum = 0.0
    for bound, cum in deltas:
        if cum >= rank:
            if bound == float("inf"):
                # Open-ended top bucket: report its lower edge (the
                # last finite bound) — the honest answer Prometheus
                # gives too.
                return lower
            if cum == prev_cum:
                return bound
            frac = (rank - prev_cum) / (cum - prev_cum)
            return lower + (bound - lower) * frac
        lower = 0.0 if bound == float("inf") else bound
        prev_cum = cum
    return lower


class HistorySampler:
    """The sampling thread: every `interval_s`, call `collect()` for a
    normalized family map, `ingest` it, then run `on_tick(now)` (the
    alert engine's evaluation hook). Collection failures are swallowed
    per-tick — a scrape hiccup must not kill the history plane — and
    surfaced through the absence of fresh samples, which is exactly
    what staleness rules watch."""

    def __init__(
        self,
        store: TimeSeriesStore,
        collect,
        interval_s: float | None = None,
        on_tick=None,
    ) -> None:
        self.store = store
        self.collect = collect
        self.interval_s = float(interval_s or store.interval_s)
        self.on_tick = on_tick
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "HistorySampler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="history-sampler", daemon=True
            )
            self._thread.start()
        return self

    def tick(self, now: float | None = None) -> None:
        """One synchronous sampling pass (tests and the thread body)."""
        if now is None:
            now = time.time()  # graftcheck: disable=monotonic-clock
        try:
            self.store.ingest(self.collect(), now)
        except Exception:
            pass
        if self.on_tick is not None:
            try:
                self.on_tick(now)
            except Exception:
                pass

    def _run(self) -> None:
        while not self._stop.is_set():
            self.tick()
            self._stop.wait(self.interval_s)

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
            self._thread = None
