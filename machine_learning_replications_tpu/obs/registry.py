"""Process-global metrics registry: labeled instrument families.

The primitive instruments — ``Counter`` / ``Gauge`` / ``Histogram`` —
moved here from ``serve/metrics.py`` (which re-exports them, so existing
imports and the ``serve_*`` Prometheus names are untouched). On top of
them this module adds what a whole-process metrics surface needs and the
serving layer's fixed instrument set didn't:

  * **labeled families** — one logical metric, many label-distinguished
    children (``family.labels(direction="h2d")``), the Prometheus data
    model;
  * **a registry** — named families registered once, rendered together as
    one text-exposition page. ``REGISTRY`` is the process-global instance:
    ``obs.jaxmon`` feeds compile/transfer accounting into it, and
    ``serve/server.py`` appends its exposition to ``/metrics``, so a
    scrape of a serving process sees serving *and* runtime metrics on one
    page.

Everything is stdlib + numpy and one lock per instrument, same as the
serving metrics it generalizes; ``tools/validate_metrics.py`` checks the
rendered exposition strictly.
"""

from __future__ import annotations

import threading
from typing import Sequence

import numpy as np


class Counter:
    """Monotonic counter (thread-safe). Accepts float increments so it can
    accumulate seconds as well as event counts; the value stays an ``int``
    while only ints are added (the serving exposition's existing rendering
    relies on that)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int | float = 1) -> None:
        if n < 0:
            raise ValueError(f"counter increment must be >= 0, got {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> int | float:
        with self._lock:
            return self._value


class Gauge:
    """Last-write-wins instantaneous value (thread-safe)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Cumulative-bucket histogram plus a quantile ring.

    ``buckets`` are upper bounds (``le``) in ascending order; an implicit
    +Inf bucket catches the tail. ``quantile`` interpolates over the ring
    of the most recent ``ring_size`` observations (numpy percentile,
    linear interpolation), so p50/p95/p99 track current traffic instead of
    the process's whole life.
    """

    def __init__(self, buckets: Sequence[float], ring_size: int = 8192) -> None:
        self._lock = threading.Lock()
        self._bounds = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * (len(self._bounds) + 1)  # +Inf tail
        self._sum = 0.0
        self._count = 0
        self._ring = np.empty(ring_size, np.float64)
        self._ring_n = 0  # total ever written; ring index = n % size

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            i = 0
            while i < len(self._bounds) and v > self._bounds[i]:
                i += 1
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            self._ring[self._ring_n % self._ring.shape[0]] = v
            self._ring_n += 1

    def observe_many(self, values) -> None:
        """Record a batch of observations under ONE lock acquisition —
        the serving flush path records a whole micro-batch's latencies
        and queue waits at once, and per-row lock round-trips are
        measurable at a thousand requests per second."""
        vs = [float(v) for v in values]
        if not vs:
            return
        with self._lock:
            size = self._ring.shape[0]
            for v in vs:
                i = 0
                while i < len(self._bounds) and v > self._bounds[i]:
                    i += 1
                self._counts[i] += 1
                self._sum += v
                self._ring[self._ring_n % size] = v
                self._ring_n += 1
            self._count += len(vs)

    def quantile(self, q: float | Sequence[float]):
        """Quantile(s) in [0, 1] over the recent-observation ring
        (NaN when empty)."""
        with self._lock:
            n = min(self._ring_n, self._ring.shape[0])
            window = self._ring[:n].copy()
        if n == 0:
            return (
                float("nan")
                if isinstance(q, float)
                else [float("nan")] * len(list(q))
            )
        out = np.percentile(window, np.asarray(q, np.float64) * 100.0)
        return float(out) if isinstance(q, float) else [float(x) for x in out]

    def snapshot(self) -> dict:
        with self._lock:
            cum, acc = [], 0
            for c in self._counts:
                acc += c
                cum.append(acc)
            return {
                "buckets": {
                    **{str(b): cum[i] for i, b in enumerate(self._bounds)},
                    "+Inf": cum[-1],
                },
                "sum": self._sum,
                "count": self._count,
            }


# ---------------------------------------------------------------------------
# Labeled families + registry
# ---------------------------------------------------------------------------

_NAME_OK = set(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:"
)


def _check_name(name: str, what: str) -> str:
    if not name or name[0].isdigit() or not set(name) <= _NAME_OK or (
        what == "label" and ":" in name
    ):
        raise ValueError(f"invalid {what} name {name!r}")
    return name


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: int | float) -> str:
    if isinstance(v, bool):  # bool is an int subclass; never a sample value
        raise TypeError("metric value cannot be bool")
    if isinstance(v, int):
        return str(v)
    if v != v:
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    return repr(float(v))


class Family:
    """One named metric with zero or more label dimensions; children are
    created on first ``labels(...)`` call and live for the process."""

    kind = "untyped"

    def __init__(self, name: str, help_: str,
                 label_names: Sequence[str] = ()) -> None:
        self.name = _check_name(name, "metric")
        self.help = help_.replace("\n", " ")
        self.label_names = tuple(
            _check_name(label_name, "label") for label_name in label_names
        )
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], object] = {}

    def _make_child(self):
        raise NotImplementedError

    def labels(self, **kv: str):
        """The child instrument for this label combination (created once).
        Every declared label must be supplied, no extras."""
        if set(kv) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {sorted(self.label_names)}, "
                f"got {sorted(kv)}"
            )
        key = tuple(str(kv[label_name]) for label_name in self.label_names)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make_child()
        return child

    def get(self):
        """The unlabeled singleton child (only for families declared with
        no label dimensions)."""
        if self.label_names:
            raise ValueError(f"{self.name} is labeled; use .labels(...)")
        return self.labels()

    def remove(self, **kv: str) -> bool:
        """Retire one label combination: the series disappears from the
        exposition instead of lingering forever at its last value (a
        deregistered replica's ``fleet_scrape_stale`` must not read as a
        stuck fact). Returns whether the child existed. A later
        ``labels(...)`` with the same combination starts a fresh child —
        counters restart at zero, which scrape differs must treat as a
        reset, exactly as they must across a process restart."""
        if set(kv) != set(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {sorted(self.label_names)}, "
                f"got {sorted(kv)}"
            )
        key = tuple(str(kv[label_name]) for label_name in self.label_names)
        with self._lock:
            return self._children.pop(key, None) is not None

    def collect(self) -> list[tuple[tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())

    def _series(self, label_values: tuple[str, ...],
                extra: dict[str, str] | None = None) -> str:
        pairs = list(zip(self.label_names, label_values))
        if extra:
            pairs += list(extra.items())
        if not pairs:
            return self.name
        inner = ",".join(
            f'{k}="{_escape_label_value(str(v))}"' for k, v in pairs
        )
        return f"{self.name}{{{inner}}}"

    def render(self, lines: list[str]) -> None:
        lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} {self.kind}")
        for label_values, child in self.collect():
            self._render_child(lines, label_values, child)

    def _render_child(self, lines, label_values, child) -> None:
        raise NotImplementedError

    def snapshot(self):
        # Unlabeled families snapshot as their bare value — a JSON
        # consumer should read {"jax_compiles_total": 12}, not index a
        # magic empty-string label key.
        if not self.label_names:
            return self._snap_child(self.labels())
        out = {}
        for label_values, child in self.collect():
            key = ",".join(
                f"{k}={v}" for k, v in zip(self.label_names, label_values)
            )
            out[key] = self._snap_child(child)
        return out

    def _snap_child(self, child):
        raise NotImplementedError


class CounterFamily(Family):
    kind = "counter"

    def _make_child(self) -> Counter:
        return Counter()

    def inc(self, n: int | float = 1, **kv: str) -> None:
        self.labels(**kv).inc(n)

    def _render_child(self, lines, label_values, child) -> None:
        lines.append(f"{self._series(label_values)} {_fmt_value(child.value)}")

    def _snap_child(self, child):
        return child.value


class GaugeFamily(Family):
    kind = "gauge"

    def _make_child(self) -> Gauge:
        return Gauge()

    def set(self, v: float, **kv: str) -> None:
        self.labels(**kv).set(v)

    def _render_child(self, lines, label_values, child) -> None:
        lines.append(f"{self._series(label_values)} {_fmt_value(child.value)}")

    def _snap_child(self, child):
        # NaN is the text exposition's legal "no data" gauge value
        # (obs.quality uses it before min_rows), but a bare NaN token is
        # not strict JSON — snapshots are JSON payloads, so it becomes
        # null there (the serving layer's established convention).
        v = child.value
        return None if v != v else v


class HistogramFamily(Family):
    kind = "histogram"

    def __init__(self, name, help_, buckets: Sequence[float],
                 label_names: Sequence[str] = ()) -> None:
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(float(b) for b in buckets))

    def _make_child(self) -> Histogram:
        return Histogram(self.buckets)

    def observe(self, v: float, **kv: str) -> None:
        self.labels(**kv).observe(v)

    def _render_child(self, lines, label_values, child) -> None:
        snap = child.snapshot()
        # Sample names carry the Prometheus histogram suffixes; the label
        # set (if any) rides after the suffix, with `le` appended on
        # buckets.
        labels_tail = self._series(label_values)[len(self.name):]
        for le, c in snap["buckets"].items():
            with_le = self._series(label_values, {"le": le})[len(self.name):]
            lines.append(f"{self.name}_bucket{with_le} {c}")
        lines.append(f"{self.name}_sum{labels_tail} {_fmt_value(snap['sum'])}")
        lines.append(f"{self.name}_count{labels_tail} {snap['count']}")

    def _snap_child(self, child):
        return child.snapshot()


class MetricsRegistry:
    """Named families, registered once, rendered as one exposition page.

    Re-declaring an existing name returns the existing family — provided
    kind and label set match (a process-global registry must be safe to
    declare into from several modules' import paths)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._families: dict[str, Family] = {}

    def _get_or_make(self, cls, name, help_, label_names, **kw) -> Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if not isinstance(fam, cls) or (
                    fam.label_names != tuple(label_names)
                ):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind} with labels {fam.label_names}"
                    )
                return fam
            fam = cls(name, help_, label_names=label_names, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help_: str,
                labels: Sequence[str] = ()) -> CounterFamily:
        return self._get_or_make(CounterFamily, name, help_, labels)

    def gauge(self, name: str, help_: str,
              labels: Sequence[str] = ()) -> GaugeFamily:
        return self._get_or_make(GaugeFamily, name, help_, labels)

    def histogram(self, name: str, help_: str, buckets: Sequence[float],
                  labels: Sequence[str] = ()) -> HistogramFamily:
        return self._get_or_make(
            HistogramFamily, name, help_, labels, buckets=buckets
        )

    def families(self) -> list[Family]:
        with self._lock:
            return list(self._families.values())

    def render_prometheus(self) -> str:
        """Prometheus text exposition (version 0.0.4) of every family
        (empty string when nothing has been registered — callers append
        this to other expositions)."""
        lines: list[str] = []
        for fam in self.families():
            fam.render(lines)
        return "\n".join(lines) + "\n" if lines else ""

    def snapshot(self) -> dict:
        return {
            fam.name: fam.snapshot() for fam in self.families()
        }


#: The process-global registry: jax runtime accounting (``obs.jaxmon``)
#: lands here, and the serving layer appends it to ``/metrics``.
REGISTRY = MetricsRegistry()
