"""Declarative alert rules over the in-process history store.

The telemetry plane already *exports* every signal an operator would
page on — SLO burn gauges (`slo_burn_rate` / `fleet_slo_burn_rate`),
scrape staleness, quality PSI, rotation state. This module *watches*
them: a small set of rule types evaluated against `obs.timeseries`
windows each sampling tick, with the two defenses real alerting grew
the hard way —

* **hold-down** (`for_s`): a breach must persist before the rule fires,
  so one noisy sample cannot page;
* **resolve hysteresis** (`resolve_for_s`): a firing rule must observe
  *continuous* clearance before it resolves, so a signal oscillating
  around the threshold cannot flap fire/resolve every tick.

Rule types (each a JSON-able spec, loadable from ``--alert-rules``):

``threshold``
    Aggregate of one family's matching series vs a bound —
    ``value(window avg, or latest when window_s is 0) OP threshold``.
    Breaches when ANY matching series breaches; the reading reported is
    the worst one.
``burn_rate``
    The Google-SRE multi-window shape: fires only when BOTH a fast
    window (default 5 min) and a slow window (default 1 h) of the burn
    gauge average at or above ``factor``. The fast window makes the
    alert responsive, the slow one makes it *proportional* — a burst
    that cannot meaningfully dent the budget never sustains the slow
    window. Factor 14.4 over a 30-day budget means "at this rate the
    whole month's budget is gone in ~2 days".
``absence``
    No fresh sample of the family within ``stale_after_s`` (a replica
    that stopped scraping, a probe that stopped probing). Grace-period
    guarded: never breaches before the engine itself has been running
    ``stale_after_s``.
``rate_of_change``
    ``|newest - oldest|`` over ``window_s`` at or above ``max_delta`` —
    the drift shape (quality PSI) where the *level* may be acceptable
    but the *movement* is the story.

State machine per rule::

    inactive -> pending (breach seen) -> firing (breach held for_s)
    firing -> resolving (clear seen) -> inactive (clear held
    resolve_for_s); resolving -> firing again on re-breach, without
    re-journaling.

Transitions journal ``alert_fired`` / ``alert_resolved`` and ride
``alerts_active{rule,severity}`` + ``alerts_transitions_total``; the
active set is served on ``GET /fleet/alerts`` (router) and
``GET /debug/alerts`` (replica), and summarized on ``/healthz``.
Jax-free by construction.
"""

from __future__ import annotations

import json

from machine_learning_replications_tpu.obs import journal
from machine_learning_replications_tpu.obs.registry import REGISTRY
from machine_learning_replications_tpu.obs.timeseries import TimeSeriesStore

ALERTS_ACTIVE = REGISTRY.gauge(
    "alerts_active",
    "1 while the rule is firing (0: inactive/pending/resolving). Every "
    "configured rule materializes its series at engine start — an "
    "absent series is a config mystery, a 0 is a healthy fact.",
    labels=("rule", "severity"),
)
ALERTS_TRANSITIONS = REGISTRY.counter(
    "alerts_transitions_total",
    "Rule state-machine transitions by kind (fired / resolved).",
    labels=("rule", "transition"),
)

SEVERITIES = ("info", "warn", "page")

_OPS = {
    ">=": lambda v, t: v >= t,
    ">": lambda v, t: v > t,
    "<=": lambda v, t: v <= t,
    "<": lambda v, t: v < t,
}


class Rule:
    """Shared spec plumbing; subclasses implement ``check(store, now)``
    returning ``(breached, value, detail)`` — `value` the reading that
    drove the verdict, `detail` a human-readable fragment."""

    type = "rule"

    def __init__(self, spec: dict) -> None:
        self.name = str(spec["name"])
        self.severity = str(spec.get("severity", "warn"))
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"rule {self.name!r}: severity must be one of "
                f"{SEVERITIES}, got {self.severity!r}"
            )
        self.family = str(spec["family"])
        self.labels = dict(spec.get("labels") or {})
        self.for_s = float(spec.get("for_s", 30.0))
        self.resolve_for_s = float(spec.get("resolve_for_s", 60.0))
        if self.for_s < 0 or self.resolve_for_s < 0:
            raise ValueError(
                f"rule {self.name!r}: for_s/resolve_for_s must be >= 0"
            )

    def check(self, store: TimeSeriesStore, now: float):
        raise NotImplementedError

    def describe(self) -> dict:
        return {
            "name": self.name, "type": self.type,
            "severity": self.severity, "family": self.family,
            "labels": self.labels, "for_s": self.for_s,
            "resolve_for_s": self.resolve_for_s,
        }

    @staticmethod
    def _worst(readings, op):
        """The series whose value argues hardest for the breach: max
        for >=/>, min for <=/< (readings: [(labels, value)])."""
        if not readings:
            return None, None
        pick = max if op in (">=", ">") else min
        lab, v = pick(readings, key=lambda r: r[1])
        return lab, v


class ThresholdRule(Rule):
    type = "threshold"

    def __init__(self, spec: dict) -> None:
        super().__init__(spec)
        self.op = str(spec.get("op", ">="))
        if self.op not in _OPS:
            raise ValueError(
                f"rule {self.name!r}: op must be one of {sorted(_OPS)}"
            )
        self.threshold = float(spec["threshold"])
        self.window_s = float(spec.get("window_s", 0.0))

    def check(self, store, now):
        if self.window_s > 0:
            readings = store.avg(
                self.family, self.window_s, now, labels=self.labels
            )
        else:
            readings = [
                (lab, v) for lab, _t, v in
                store.latest(self.family, labels=self.labels)
            ]
        lab, v = self._worst(readings, self.op)
        if v is None:
            return False, None, "no data"
        breached = _OPS[self.op](v, self.threshold)
        return breached, v, (
            f"{self.family}{lab or {}} = {v:.4g} "
            f"(breach when {self.op} {self.threshold:g})"
        )

    def describe(self) -> dict:
        d = super().describe()
        d.update(op=self.op, threshold=self.threshold,
                 window_s=self.window_s)
        return d


class BurnRateRule(Rule):
    type = "burn_rate"

    def __init__(self, spec: dict) -> None:
        super().__init__(spec)
        self.factor = float(spec.get("factor", 14.4))
        self.fast_s = float(spec.get("fast_s", 300.0))
        self.slow_s = float(spec.get("slow_s", 3600.0))
        if self.fast_s > self.slow_s:
            raise ValueError(
                f"rule {self.name!r}: fast_s must be <= slow_s"
            )

    def _window_worst(self, store, window_s, now):
        readings = store.avg(
            self.family, window_s, now, labels=self.labels
        )
        return self._worst(readings, ">=")

    def check(self, store, now):
        lab_f, fast = self._window_worst(store, self.fast_s, now)
        _lab_s, slow = self._window_worst(store, self.slow_s, now)
        if fast is None or slow is None:
            return False, None, "no data"
        breached = fast >= self.factor and slow >= self.factor
        return breached, fast, (
            f"{self.family}{lab_f or {}} burn x{fast:.2f} over "
            f"{self.fast_s:g}s / x{slow:.2f} over {self.slow_s:g}s "
            f"(breach when both >= x{self.factor:g})"
        )

    def describe(self) -> dict:
        d = super().describe()
        d.update(factor=self.factor, fast_s=self.fast_s,
                 slow_s=self.slow_s)
        return d


class AbsenceRule(Rule):
    type = "absence"

    def __init__(self, spec: dict) -> None:
        super().__init__(spec)
        self.stale_after_s = float(spec.get("stale_after_s", 60.0))
        self._born: float | None = None

    def check(self, store, now):
        if self._born is None:
            self._born = now
        age = store.last_sample_age_s(self.family, now)
        if age is None:
            # Never sampled: only suspicious once the engine has been
            # alive long enough that a healthy sampler must have
            # produced at least one sample.
            if now - self._born < self.stale_after_s:
                return False, None, "warming up"
            return True, None, (
                f"{self.family}: never sampled in "
                f"{now - self._born:.0f}s"
            )
        breached = age >= self.stale_after_s
        return breached, age, (
            f"{self.family}: newest sample {age:.1f}s old "
            f"(breach when >= {self.stale_after_s:g}s)"
        )

    def describe(self) -> dict:
        d = super().describe()
        d.update(stale_after_s=self.stale_after_s)
        return d


class RateOfChangeRule(Rule):
    type = "rate_of_change"

    def __init__(self, spec: dict) -> None:
        super().__init__(spec)
        self.max_delta = float(spec["max_delta"])
        self.window_s = float(spec.get("window_s", 300.0))

    def check(self, store, now):
        readings = [
            (lab, abs(d)) for lab, d in
            store.delta(self.family, self.window_s, now,
                        labels=self.labels)
        ]
        lab, v = self._worst(readings, ">=")
        if v is None:
            return False, None, "no data"
        breached = v >= self.max_delta
        return breached, v, (
            f"{self.family}{lab or {}} moved {v:.4g} over "
            f"{self.window_s:g}s (breach when >= {self.max_delta:g})"
        )

    def describe(self) -> dict:
        d = super().describe()
        d.update(max_delta=self.max_delta, window_s=self.window_s)
        return d


_RULE_TYPES = {
    cls.type: cls
    for cls in (ThresholdRule, BurnRateRule, AbsenceRule,
                RateOfChangeRule)
}


def build_rule(spec: dict) -> Rule:
    kind = spec.get("type")
    cls = _RULE_TYPES.get(kind)
    if cls is None:
        raise ValueError(
            f"unknown rule type {kind!r} (know {sorted(_RULE_TYPES)})"
        )
    return cls(spec)


def load_rules(path: str) -> list[Rule]:
    """A rules file is a JSON list of specs (see the rule classes for
    fields). Validation is eager — a typo'd rule fails startup, not the
    3 a.m. incident it was supposed to catch."""
    with open(path, encoding="utf-8") as fh:
        specs = json.load(fh)
    if not isinstance(specs, list):
        raise ValueError(f"{path}: expected a JSON list of rule specs")
    rules = [build_rule(s) for s in specs]
    names = [r.name for r in rules]
    if len(set(names)) != len(names):
        raise ValueError(f"{path}: duplicate rule names")
    return rules


def default_rules(role: str) -> list[Rule]:
    """Built-in rule set per process role. Conservative thresholds:
    these must hold zero false positives through the chaos drill's
    healthy baseline AND the saturation bench."""
    if role == "router":
        return [
            BurnRateRule({
                "name": "fleet_error_budget_burn", "severity": "page",
                "family": "fleet_slo_burn_rate", "factor": 14.4,
                "fast_s": 300.0, "slow_s": 3600.0,
                "for_s": 60.0, "resolve_for_s": 120.0,
            }),
            ThresholdRule({
                "name": "fleet_replica_stale", "severity": "warn",
                "family": "fleet_scrape_stale", "op": ">=",
                "threshold": 1.0, "window_s": 0.0,
                "for_s": 30.0, "resolve_for_s": 60.0,
            }),
            ThresholdRule({
                "name": "fleet_no_ready_replicas", "severity": "page",
                "family": "fleet_replicas",
                "labels": {"state": "ready"},
                "op": "<", "threshold": 1.0, "window_s": 0.0,
                "for_s": 15.0, "resolve_for_s": 30.0,
            }),
        ]
    if role == "replica":
        return [
            BurnRateRule({
                "name": "slo_error_budget_burn", "severity": "page",
                "family": "slo_burn_rate", "factor": 14.4,
                "fast_s": 300.0, "slow_s": 3600.0,
                "for_s": 60.0, "resolve_for_s": 120.0,
            }),
            RateOfChangeRule({
                "name": "quality_psi_drift", "severity": "warn",
                "family": "quality_psi", "max_delta": 0.2,
                "window_s": 900.0,
                "for_s": 60.0, "resolve_for_s": 300.0,
            }),
        ]
    raise ValueError(f"unknown role {role!r}")


_SEV_RANK = {s: i for i, s in enumerate(SEVERITIES)}

_INACTIVE, _PENDING, _FIRING, _RESOLVING = (
    "inactive", "pending", "firing", "resolving",
)


class _RuleState:
    __slots__ = ("state", "since", "fired_at", "value", "detail")

    def __init__(self) -> None:
        self.state = _INACTIVE
        self.since: float | None = None   # entered current state
        self.fired_at: float | None = None
        self.value = None
        self.detail = ""


class AlertEngine:
    """Evaluate every rule once per `evaluate(now)` (the history
    sampler's `on_tick`); returns the transitions this pass produced so
    the caller can forward firings to the incident capturer. Pure of
    I/O and clocks — `now` is injected, which is what makes the
    hold-down/hysteresis tests deterministic."""

    def __init__(self, rules, store: TimeSeriesStore) -> None:
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError("duplicate rule names")
        self.rules = list(rules)
        self.store = store
        self._state = {r.name: _RuleState() for r in self.rules}
        # Materialize every rule's series at 0 up front.
        for r in self.rules:
            ALERTS_ACTIVE.set(0.0, rule=r.name, severity=r.severity)

    def evaluate(self, now: float) -> list[dict]:
        transitions: list[dict] = []
        for rule in self.rules:
            st = self._state[rule.name]
            try:
                breached, value, detail = rule.check(self.store, now)
            except Exception as exc:  # a broken rule must not take
                breached, value = False, None  # down the whole pass
                detail = f"check error: {exc}"
            st.value, st.detail = value, detail
            if st.state == _INACTIVE:
                if breached:
                    st.state, st.since = _PENDING, now
                    if now - st.since >= rule.for_s:
                        self._fire(rule, st, now, transitions)
            elif st.state == _PENDING:
                if not breached:
                    st.state, st.since = _INACTIVE, None
                elif now - st.since >= rule.for_s:
                    self._fire(rule, st, now, transitions)
            elif st.state == _FIRING:
                if not breached:
                    st.state, st.since = _RESOLVING, now
                    if now - st.since >= rule.resolve_for_s:
                        self._resolve(rule, st, now, transitions)
            elif st.state == _RESOLVING:
                if breached:
                    # Re-breach during hysteresis: still the SAME
                    # incident — back to firing without re-journaling.
                    st.state, st.since = _FIRING, st.fired_at
                elif now - st.since >= rule.resolve_for_s:
                    self._resolve(rule, st, now, transitions)
        return transitions

    def _fire(self, rule, st, now, transitions) -> None:
        st.state, st.since, st.fired_at = _FIRING, now, now
        ALERTS_ACTIVE.set(1.0, rule=rule.name, severity=rule.severity)
        ALERTS_TRANSITIONS.inc(rule=rule.name, transition="fired")
        journal.event(
            "alert_fired",
            rule=rule.name,
            severity=rule.severity,
            value=(round(st.value, 6)
                   if isinstance(st.value, float) else st.value),
            detail=st.detail,
        )
        transitions.append(self._transition(rule, st, now, "fired"))

    def _resolve(self, rule, st, now, transitions) -> None:
        fired_for = now - (st.fired_at if st.fired_at is not None
                           else now)
        st.state, st.since, st.fired_at = _INACTIVE, None, None
        ALERTS_ACTIVE.set(0.0, rule=rule.name, severity=rule.severity)
        ALERTS_TRANSITIONS.inc(rule=rule.name, transition="resolved")
        journal.event(
            "alert_resolved",
            rule=rule.name,
            severity=rule.severity,
            seconds=round(fired_for, 3),
        )
        tr = self._transition(rule, st, now, "resolved")
        tr["fired_for_s"] = round(fired_for, 3)
        transitions.append(tr)

    def _transition(self, rule, st, now, kind) -> dict:
        return {
            "transition": kind,
            "rule": rule.name,
            "severity": rule.severity,
            "at": now,
            "value": st.value,
            "detail": st.detail,
            "spec": rule.describe(),
        }

    # -- read side ----------------------------------------------------------

    def active(self) -> list[dict]:
        """Firing (and still-resolving) rules, worst severity first —
        the ``/fleet/alerts`` payload."""
        out = []
        for rule in self.rules:
            st = self._state[rule.name]
            if st.state in (_FIRING, _RESOLVING):
                out.append({
                    "rule": rule.name,
                    "severity": rule.severity,
                    "state": st.state,
                    "since": st.fired_at,
                    "value": st.value,
                    "detail": st.detail,
                })
        out.sort(key=lambda a: -_SEV_RANK.get(a["severity"], 0))
        return out

    def snapshot(self) -> dict:
        """Every rule's current state (the full debug view)."""
        rules = []
        for rule in self.rules:
            st = self._state[rule.name]
            d = rule.describe()
            d.update(state=st.state, value=st.value, detail=st.detail)
            rules.append(d)
        return {"rules": rules, "active": self.active()}

    def summary(self) -> dict:
        """The /healthz block: counts plus the worst firing severity."""
        states = [self._state[r.name].state for r in self.rules]
        firing = [
            r for r in self.rules
            if self._state[r.name].state in (_FIRING, _RESOLVING)
        ]
        worst = None
        for r in firing:
            if worst is None or _SEV_RANK[r.severity] > _SEV_RANK[worst]:
                worst = r.severity
        return {
            "rules": len(self.rules),
            "firing": len(firing),
            "pending": states.count(_PENDING),
            "max_severity": worst,
        }
