"""Request-scoped tracing: per-request phase breakdown + tail-sampled
flight recorder.

Process-level telemetry (spans, counters, the journal) answers "how is the
server doing"; it cannot answer the question that matters at tail-latency
scale: *why was this specific request slow* — queue wait, batch assembly,
a cold-bucket compile, device compute, or the response write? The
standard answer is per-request causal tracing (Dapper, Sigelman et al.
2010) with tail-based retention (The Tail at Scale, Dean & Barroso 2013):
every request carries a trace context, but only the *interesting* traces
are kept.

``RequestTrace`` is the context the HTTP handler creates at admission and
threads through ``MicroBatcher.submit`` → ``_flush`` → the engine: each
layer stamps its phase boundaries (``time.perf_counter`` throughout, one
clock for the whole request) and annotations (flush sequence, bucket,
whether the flush hit a cold compile). Phases partition the server-side
request interval, so their durations sum to the end-to-end latency.

``FlightRecorder`` is the bounded ring completed traces report into, with
**tail-based sampling**: every error / timeout / shed trace is kept, and
an ok trace is kept only when its latency reaches the recorder's moving
tail quantile (default p99 over a ring of recent ok latencies — the slow
tail, exactly the traces worth a human's time). The fast majority is
dropped after updating the quantile window; sampling decisions are
counted in the global registry (``reqtrace_sampled_total{reason=…}`` /
``reqtrace_dropped_total``) so the drop rate itself is observable.

A sampled trace is also merged into the active Chrome-trace export
(``obs.spans``): its phases render on a per-request virtual lane, and a
``req:<id>`` slice lands *inside* the batcher's ``serve:flush`` span (on
the flush thread's track, within the device-compute window), so a
Perfetto timeline shows each flush with its constituent sampled requests.

Import-safe without jax (stdlib + numpy), same as ``journal``/``registry``.
"""

from __future__ import annotations

import collections
import itertools
import os
import threading
import time
from typing import Any

import numpy as np

from machine_learning_replications_tpu.obs import spans
from machine_learning_replications_tpu.obs.registry import REGISTRY

# Registered at import (rule metrics-catalog): present on the first
# scrape, before any recorder is constructed.
REQTRACE_SAMPLED = REGISTRY.counter(
    "reqtrace_sampled_total",
    "Request traces kept by the flight recorder, by keep reason.",
    labels=("reason",),
)
REQTRACE_DROPPED = REGISTRY.counter(
    "reqtrace_dropped_total",
    "Completed request traces dropped by tail sampling (fast majority).",
)

#: Phase names in request order (docs/OBSERVABILITY.md "Request traces").
#: A device-path request records parse → queue_wait → batch_assembly →
#: device_compute → respond; a host-path request (dual-path scoring,
#: docs/SERVING.md) records parse → queue_wait (host-slot wait) →
#: host_compute → respond. Every /predict trace carries a ``path``
#: annotation (``host`` | ``device``) plus the router's ``path_reason``,
#: so tail samples say not just where the time went but which engine the
#: request was routed to and why.
PHASES = (
    "parse", "queue_wait", "batch_assembly", "device_compute",
    "host_compute", "respond",
)

_ID_OK = set(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-"
)
MAX_ID_LEN = 128

# Request ids are a random per-process prefix + a monotonic counter: the
# counter guarantees in-process uniqueness, the prefix disambiguates
# SO_REUSEPORT workers sharing one port. uuid4 per request would cost an
# os.urandom syscall (~100 µs of the event loop's per-request budget);
# trace ids are correlation keys, not security tokens.
_ID_PREFIX = os.urandom(2).hex()
_ID_COUNTER = itertools.count(1)  # next() is atomic under the GIL


def new_request_id() -> str:
    return _ID_PREFIX + format(next(_ID_COUNTER) & 0xFFFFFFFFFFFF, "012x")


def sanitize_request_id(raw: str | None) -> str:
    """An inbound ``X-Request-Id`` → a safe id (hostile headers must not
    inject into JSON logs or response headers): charset-restricted,
    length-capped, regenerated when empty/invalid."""
    if not raw:
        return new_request_id()
    raw = raw.strip()
    if not raw or len(raw) > MAX_ID_LEN or not set(raw) <= _ID_OK:
        return new_request_id()
    return raw


class RequestTrace:
    """One request's causal record: id, phase boundaries, annotations.

    Stamps are raw ``time.perf_counter`` values; ``add_phase`` intervals
    may be recorded from any thread (the handler stamps parse/respond, the
    batcher's flush thread stamps queue_wait/batch_assembly/
    device_compute) — same monotonic clock, so the phases compose into one
    timeline. A small lock covers the phase/meta dicts: on the
    deadline-expiry path the handler can snapshot a trace the flush
    thread is still stamping (cancel lost the claim race), and a dict
    mutating under iteration would take the snapshot down."""

    __slots__ = (
        "request_id", "t_start", "wall_start", "phases", "meta", "status",
        "t_end", "error", "_lock",
    )

    def __init__(self, request_id: str | None = None) -> None:
        self.request_id = request_id or new_request_id()
        self.t_start = time.perf_counter()
        # Display timestamp on the exported trace; phase durations
        # use the span clock, never this.
        self.wall_start = time.time()  # graftcheck: disable=monotonic-clock
        self.phases: dict[str, tuple[float, float]] = {}
        self.meta: dict[str, Any] = {}
        self.status: str | None = None
        self.t_end: float | None = None
        self.error: str | None = None
        self._lock = threading.Lock()

    def add_phase(self, name: str, t0: float, t1: float) -> None:
        with self._lock:
            # A finished trace is immutable: on the 504 path the flush
            # thread can win the cancel race and try to stamp compute
            # phases AFTER the handler closed the trace — accepting them
            # would push phase ends past t_end and break the
            # phases-partition-the-interval invariant /debug/requests
            # publishes.
            if self.t_end is not None:
                return
            self.phases[name] = (t0, t1)

    def add_phases(self, phases: dict[str, tuple[float, float]],
                   **meta: Any) -> None:
        """Stamp several phases (and meta annotations) under ONE lock
        round-trip — the batcher stamps three flush-side phases plus its
        annotations per batch member, and per-phase locking is measurable
        at event-loop throughput. Same immutability rule as
        ``add_phase``."""
        with self._lock:
            if self.t_end is not None:
                return
            self.phases.update(phases)
            if meta:
                self.meta.update(meta)

    def drop_phases(self, *names: str) -> None:
        """Remove phases from a live trace. The host→device failure
        fallback uses this: the failed host attempt's queue_wait /
        host_compute would otherwise overlap the device path's fresh
        queue_wait (which restarts at parse end) and break the
        phases-partition-the-interval invariant — the abandoned attempt's
        time is deliberately re-attributed as device-path queueing."""
        with self._lock:
            if self.t_end is not None:
                return
            for name in names:
                self.phases.pop(name, None)

    def phase_end(self, name: str, default: float) -> float:
        """End stamp of a recorded phase (``default`` when absent) — the
        hand-off point the next phase starts from."""
        with self._lock:
            interval = self.phases.get(name)
        return interval[1] if interval is not None else default

    def note(self, **kv: Any) -> None:
        with self._lock:
            if self.t_end is not None:
                return
            self.meta.update(kv)

    def finish(self, status: str, error: str | None = None) -> "RequestTrace":
        with self._lock:
            if self.t_end is None:  # first finish wins; then immutable
                self.status = status
                self.error = error
                self.t_end = time.perf_counter()
        return self

    @property
    def total_s(self) -> float:
        end = self.t_end if self.t_end is not None else time.perf_counter()
        return end - self.t_start

    def phase_seconds(self) -> dict[str, float]:
        with self._lock:
            phases = dict(self.phases)
        return {
            name: max(t1 - t0, 0.0) for name, (t0, t1) in phases.items()
        }

    def snapshot(self) -> dict:
        """The JSON-friendly record ``/debug/requests`` serves: durations
        in seconds (6-decimal µs precision), phase start offsets from
        request start so a consumer can reconstruct the timeline."""
        with self._lock:
            phases = dict(self.phases)
            meta = dict(self.meta)
        return {
            "request_id": self.request_id,
            "status": self.status,
            "ts": self.wall_start,
            # Raw perf_counter admission stamp: the anchor the fleet
            # trace join (obs.fleettrace) maps through the per-replica
            # clock offset — offsets alone cannot place this trace on
            # another process's timeline.
            "t_start_perf": round(self.t_start, 6),
            "total_seconds": round(self.total_s, 6),
            "phases": {
                name: {
                    "offset_seconds": round(t0 - self.t_start, 6),
                    "seconds": round(max(t1 - t0, 0.0), 6),
                }
                for name, (t0, t1) in phases.items()
            },
            **({"error": self.error} if self.error else {}),
            **meta,
        }


#: Lanes for merged request timelines: a small fixed pool keeps the
#: Perfetto track count bounded no matter how many requests are sampled
#: over a long run (lanes are reused once their previous occupant ends).
_N_LANES = 8


class FlightRecorder:
    """Bounded ring of completed request traces with tail-based sampling.

    Keep policy, in order:
      * ``status != "ok"`` (error / timeout / shed / engine failure):
        always kept — failures are never sampled away;
      * ok and the latency window is still warming up (< ``min_window``
        observations): kept, so a fresh process has samples immediately;
      * ok and ``total_s`` ≥ the ``tail_quantile`` (default 0.99) of the
        recent-ok-latency ring: kept — the p99 tail;
      * otherwise dropped (counted, never stored).

    The ring holds at most ``capacity`` snapshots (dicts, not live trace
    objects); memory stays bounded for the life of the process.

    Separately from the sampled ring, EVERY completed trace is indexed by
    request id in a bounded FIFO (``index_capacity`` most recent) for
    ``lookup`` — the ``/debug/requests?id=`` exact fetch the fleet trace
    join rides on. Tail sampling alone cannot serve that join: the router
    and a replica sample independently, so a router-sampled request would
    usually be dropped replica-side. The index stores the finished (hence
    immutable) trace *objects* and snapshots only on lookup, so the hot
    path pays one dict insert, not a snapshot build per request.
    """

    def __init__(
        self,
        capacity: int = 256,
        tail_quantile: float = 0.99,
        window: int = 2048,
        min_window: int = 32,
        index_capacity: int = 4096,
    ) -> None:
        if not 0.0 < tail_quantile < 1.0:
            raise ValueError(
                f"tail_quantile must be in (0, 1), got {tail_quantile}"
            )
        if capacity < 1 or window < 1:
            raise ValueError(
                f"capacity and window must be >= 1, got {capacity}/{window}"
            )
        if index_capacity < 1:
            raise ValueError(
                f"index_capacity must be >= 1, got {index_capacity}"
            )
        self.capacity = int(capacity)
        self.tail_quantile = float(tail_quantile)
        self.min_window = int(min_window)
        self.index_capacity = int(index_capacity)
        self._lock = threading.Lock()
        self._by_id: collections.OrderedDict[str, RequestTrace] = \
            collections.OrderedDict()
        self._samples: list[dict] = []
        self._next = 0  # ring write index
        self._lat = np.empty(int(window), np.float64)
        self._lat_n = 0
        # The tail threshold is CACHED and refreshed every
        # _REFRESH_EVERY ok completions: an exact per-request percentile
        # over the window would serialize every handler thread on an
        # O(window log window) sort inside this lock — the hot path pays
        # a ring write and a float compare instead.
        self._threshold: float | None = None
        self._threshold_age = 0
        self._dropped_n = 0  # THIS recorder's drops (the registry
        # counters below are process-global and would mix recorders)
        self._lane_busy_until = [0.0] * _N_LANES
        self._sampled = REQTRACE_SAMPLED
        self._dropped = REQTRACE_DROPPED

    # -- sampling ----------------------------------------------------------

    #: ok completions between threshold refreshes (the cached quantile
    #: lags current traffic by at most this many requests).
    _REFRESH_EVERY = 64

    def _tail_threshold_locked(self) -> float | None:
        n = min(self._lat_n, self._lat.shape[0])
        if n < self.min_window:
            return None
        if self._threshold is None or self._threshold_age >= \
                self._REFRESH_EVERY:
            self._threshold = float(np.percentile(
                self._lat[:n], self.tail_quantile * 100.0
            ))
            self._threshold_age = 0
        return self._threshold

    def record(self, trace: RequestTrace) -> bool:
        """Apply the keep policy to a finished trace; returns whether it
        was kept. Kept traces are stored and merged into the active
        Chrome-trace export."""
        total = trace.total_s
        with self._lock:
            # Exact-lookup index first: EVERY completed trace, sampled or
            # not (a re-used request id overwrites — latest completion
            # wins, and re-inserting refreshes its FIFO position).
            self._by_id[trace.request_id] = trace
            self._by_id.move_to_end(trace.request_id)
            while len(self._by_id) > self.index_capacity:
                self._by_id.popitem(last=False)
            if trace.status == "ok":
                threshold = self._tail_threshold_locked()
                self._lat[self._lat_n % self._lat.shape[0]] = total
                self._lat_n += 1
                self._threshold_age += 1
                if threshold is None:
                    reason = "bootstrap"
                elif total >= threshold:
                    reason = "tail"
                else:
                    reason = None
            else:
                reason = "failure"
            if reason is None:
                keep = False
                self._dropped_n += 1
            else:
                snap = trace.snapshot()
                snap["sampled_reason"] = reason
                if len(self._samples) < self.capacity:
                    self._samples.append(snap)
                else:
                    self._samples[self._next % self.capacity] = snap
                self._next += 1
                keep = True
        if keep:
            self._sampled.inc(reason=reason)
            self._emit_to_tracer(trace)
        else:
            self._dropped.get().inc()
        return keep

    # -- inspection --------------------------------------------------------

    def snapshot(self, n: int | None = None) -> list[dict]:
        """Most-recent-first sampled traces (at most ``n``)."""
        with self._lock:
            if len(self._samples) < self.capacity:
                ordered = list(self._samples)
            else:
                i = self._next % self.capacity
                ordered = self._samples[i:] + self._samples[:i]
        ordered.reverse()
        return ordered if n is None else ordered[: max(int(n), 0)]

    def lookup(self, request_id: str) -> dict | None:
        """Exact fetch by request id over the completed-trace index (the
        ``/debug/requests?id=`` primitive). None when the id never
        completed here or has been evicted (FIFO, ``index_capacity``
        most recent)."""
        with self._lock:
            trace = self._by_id.get(request_id)
        return None if trace is None else trace.snapshot()

    def stats(self) -> dict:
        with self._lock:
            n_lat = min(self._lat_n, self._lat.shape[0])
            threshold = self._tail_threshold_locked()
            dropped = self._dropped_n
            indexed = len(self._by_id)
        return {
            "capacity": self.capacity,
            "stored": min(self._next, self.capacity),
            "kept_total": self._next,
            "dropped_total": dropped,
            "indexed": indexed,
            "index_capacity": self.index_capacity,
            "tail_quantile": self.tail_quantile,
            "tail_threshold_seconds": (
                None if threshold is None else round(threshold, 6)
            ),
            "latency_window": n_lat,
        }

    # -- Chrome-trace merge ------------------------------------------------

    def _lane(self, t0: float, t1: float) -> int:
        """First lane free at ``t0`` (its previous request already ended);
        falls back to lane 0 — overlap there is cosmetic, not data loss."""
        with self._lock:
            for i, busy_until in enumerate(self._lane_busy_until):
                if busy_until <= t0:
                    self._lane_busy_until[i] = t1
                    return i
            return 0

    def _emit_to_tracer(self, trace: RequestTrace) -> None:
        """Merge a kept trace into the active tracer: the request and its
        phases on a per-request lane, plus a ``req:<id>`` slice inside the
        flush span's device-compute window on the flush thread's track —
        the containment Perfetto renders as request-under-flush."""
        tracer = spans.get_tracer()
        if tracer is None or trace.t_end is None:
            return
        with trace._lock:
            phases = dict(trace.phases)
            meta = dict(trace.meta)
        lane = tracer.virtual_tid(
            f"req-lane-{self._lane(trace.t_start, trace.t_end)}"
        )
        args = {
            "request_id": trace.request_id,
            "status": trace.status,
            **{
                k: v for k, v in meta.items()
                if isinstance(v, (str, int, float, bool, type(None)))
            },
        }
        tracer.add_complete_event(
            f"request {trace.request_id}", trace.t_start, trace.t_end,
            tid=lane, cat="request", args=args,
        )
        for name, (t0, t1) in phases.items():
            tracer.add_complete_event(
                name, t0, t1, tid=lane, cat="request",
                args={"request_id": trace.request_id},
            )
        # Under-the-flush slice: the flush thread stamped its tid and the
        # device-compute window; each batch member owns an equal sub-slice
        # (indexed by its position in the batch) so sampled batchmates
        # render side by side inside the flush span instead of as a
        # degenerate equal-interval nesting stack.
        flush_tid = meta.get("flush_tid")
        compute = phases.get("device_compute")
        rows = meta.get("batch_rows")
        idx = meta.get("flush_index")
        if flush_tid is None or compute is None or not rows or idx is None:
            return
        c0, c1 = compute
        width = (c1 - c0) / float(rows)
        tracer.add_complete_event(
            f"req:{trace.request_id}",
            c0 + idx * width, c0 + (idx + 1) * width,
            tid=int(flush_tid), cat="request",
            args={
                "request_id": trace.request_id, "status": trace.status,
                "slice": "flush membership (width = compute/rows)",
                "compute_seconds": round(c1 - c0, 6),
            },
        )
