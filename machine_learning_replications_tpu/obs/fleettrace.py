"""Cross-process request tracing: the fleet's joined Perfetto timeline.

Per-process tracing (``obs.reqtrace``) answers "where did this request's
time go *inside this process*"; behind a router that is half the story —
the router's ``upstream`` phase is one opaque interval covering connect,
transit, the replica's whole server side, and the reply. This module
joins the two: for each router tail-sampled request it fetches the
serving replica's trace over the exact-lookup primitive
(``/debug/requests?id=`` — ``FlightRecorder.lookup``) and renders ONE
Chrome-trace timeline where the router's ``upstream`` span *contains*
the replica's server-side phases (parse / queue_wait / batch_assembly /
device_compute | host_compute / respond). "Where did the p99 go: router
queue, network, replica queue, or compute?" becomes a one-screen answer.

**Clock correction.** Router and replica both stamp ``time.perf_counter``
— monotonic clocks with *arbitrary, per-process epochs* (on Linux they
share CLOCK_MONOTONIC, but the contract does not promise it, and the
epochs diverge the moment a replica lives on another host). ``ClockSync``
estimates each replica's offset NTP-style from the probe the rotation
already pays for: the replica echoes its ``clock_perf`` on ``/readyz``,
the prober stamps send/receive, and

    offset = clock_perf_replica − (t_send + t_recv) / 2

maps replica time into router time with error bounded by half the probe
round-trip. Offsets are EWMA-smoothed (``EWMA_ALPHA``) so one delayed
probe cannot teleport a replica's spans, and published per replica on
``fleet_clock_offset_ms{replica=…}``.

**Containment.** A joined request's replica span must land inside its
router ``upstream`` span once offset-corrected — the margins are real
(connect + transit on each side) but can be smaller than the offset
estimate's error, so containment is asserted with ``CONTAINMENT_SLACK_S``
tolerance (docs/OBSERVABILITY.md "Fleet telemetry"). The export's
``otherData`` carries the joined/containment accounting, and every join
attempt lands on ``fleet_trace_joins_total{result=…}`` — a timeline that
silently dropped its misses would read as "everything joined".

Import-safe without jax (stdlib + the obs registry/journal), like the
rest of the fleet tier's dependencies — graftcheck's ``import-purity``
rule proves it transitively.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.parse
import urllib.request

from machine_learning_replications_tpu.obs import journal, spans
from machine_learning_replications_tpu.obs.registry import REGISTRY

FLEET_CLOCK_OFFSET = REGISTRY.gauge(
    "fleet_clock_offset_ms",
    "EWMA-smoothed replica perf-clock offset relative to this router "
    "(replica minus router, ms), estimated from /readyz probe echoes.",
    labels=("replica",),
)
FLEET_TRACE_JOINS = REGISTRY.counter(
    "fleet_trace_joins_total",
    "Cross-process trace join attempts by result (joined, "
    "no_replica_meta, unknown_replica, no_offset, no_replica_trace, "
    "fetch_error).",
    labels=("result",),
)
for _result in ("joined", "no_replica_meta", "unknown_replica",
                "no_offset", "no_replica_trace", "fetch_error"):
    FLEET_TRACE_JOINS.labels(result=_result)

#: Tolerance for the replica-inside-upstream containment verdict: the
#: offset estimate's error is bounded by half the probe round-trip,
#: which on a loaded loopback can exceed the sub-millisecond connect +
#: transit margins that separate the true intervals.
CONTAINMENT_SLACK_S = 0.001


class ClockSync:
    """Per-replica perf-clock offset estimator (module docstring).

    ``observe`` is called by the health prober once per probe tick per
    replica; ``offset_s`` is read by the join (and anyone mapping a
    replica-side ``perf_counter`` stamp into router time). Thread-safe:
    the prober thread writes, join threads read.
    """

    #: Same smoothing horizon as the registry's latency EWMA: ~the last
    #: 10 probes dominate, so a replica restart (new clock epoch) is
    #: re-learned within seconds while one delayed probe barely moves
    #: the estimate.
    EWMA_ALPHA = 0.2

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # replica id -> (ewma offset s, last rtt s, samples)
        self._state: dict[str, tuple[float, float, int]] = {}

    def observe(
        self, replica_id: str, t_send: float, t_recv: float,
        replica_clock: float,
    ) -> float:
        """One probe echo: fold ``replica_clock − midpoint`` into the
        replica's EWMA offset and return the smoothed value (seconds,
        replica minus router)."""
        raw = float(replica_clock) - (float(t_send) + float(t_recv)) / 2.0
        rtt = max(float(t_recv) - float(t_send), 0.0)
        with self._lock:
            prev = self._state.get(replica_id)
            if prev is None:
                offset = raw
                n = 1
            else:
                offset = prev[0] + self.EWMA_ALPHA * (raw - prev[0])
                n = prev[2] + 1
            self._state[replica_id] = (offset, rtt, n)
        FLEET_CLOCK_OFFSET.set(offset * 1000.0, replica=replica_id)
        return offset

    def forget(self, replica_id: str) -> None:
        """Drop a replica's estimate (it deregistered or was replaced —
        a successor process has a fresh clock epoch and must not inherit
        the old one's offset). The gauge series retires with it: a
        departed replica's last offset frozen on the exposition forever
        reads as a live fact."""
        with self._lock:
            self._state.pop(replica_id, None)
        FLEET_CLOCK_OFFSET.remove(replica=replica_id)

    def offset_s(self, replica_id: str) -> float | None:
        with self._lock:
            st = self._state.get(replica_id)
        return None if st is None else st[0]

    def snapshot(self) -> dict:
        """Per-replica ``{offset_ms, rtt_ms, samples}`` — the export's
        ``otherData.clock_offsets`` and the obs report's evidence that
        the correction was live, not assumed."""
        with self._lock:
            state = dict(self._state)
        return {
            rid: {
                "offset_ms": round(offset * 1000.0, 3),
                "rtt_ms": round(rtt * 1000.0, 3),
                "samples": n,
            }
            for rid, (offset, rtt, n) in sorted(state.items())
        }


def fetch_replica_trace(
    url: str, request_id: str, timeout_s: float = 1.0,
) -> tuple[dict | None, str]:
    """Exact-lookup fetch of one request's replica-side trace:
    ``(snapshot, "ok")``, ``(None, "no_replica_trace")`` on a clean 404
    (completed elsewhere or evicted), ``(None, "fetch_error")`` on
    anything else. Never raises — the join must degrade per-request,
    not abort on the first unreachable replica."""
    target = (
        url.rstrip("/") + "/debug/requests?id="
        + urllib.parse.quote(request_id, safe="")
    )
    try:
        with urllib.request.urlopen(target, timeout=timeout_s) as resp:
            body = json.loads(resp.read())
        snap = body.get("request") if isinstance(body, dict) else None
        if not isinstance(snap, dict):
            return None, "fetch_error"
        return snap, "ok"
    except urllib.error.HTTPError as exc:
        exc.read()
        return None, "no_replica_trace" if exc.code == 404 else "fetch_error"
    except Exception:
        return None, "fetch_error"


def _abs_phases(snap: dict) -> dict[str, tuple[float, float]]:
    """A trace snapshot's phases as absolute perf-clock intervals (its
    own process's clock) off the ``t_start_perf`` anchor."""
    t0 = snap.get("t_start_perf")
    phases = snap.get("phases")
    if t0 is None or not isinstance(phases, dict):
        return {}
    out = {}
    for name, ph in phases.items():
        start = float(t0) + float(ph.get("offset_seconds", 0.0))
        out[name] = (start, start + float(ph.get("seconds", 0.0)))
    return out


def join_fleet_trace(
    router_samples: list[dict],
    replica_urls: dict[str, str],
    clock: ClockSync,
    timeout_s: float = 1.0,
    fetch=fetch_replica_trace,
) -> dict:
    """Join the router's tail samples with their replica-side traces and
    render one Perfetto-loadable Chrome-trace object.

    ``router_samples`` are ``FlightRecorder.snapshot()`` dicts from the
    ROUTER's recorder (each carries ``replica`` / ``attempts`` meta and
    the ``t_start_perf`` anchor); ``replica_urls`` maps replica id →
    base url (``ReplicaRegistry.urls()``). Replica fetches are
    sequential, each bounded by ``timeout_s`` — callers run the whole
    join off the event loop (the ``/debug/profile`` pattern).
    ``fetch`` is injectable for tests.

    All timestamps render on the ROUTER's perf clock; replica intervals
    map through the replica's ``ClockSync`` offset. Every event rides
    one virtual lane per request (``tid``), so the positional-containment
    rule the trace viewers nest by puts the replica's phases inside the
    router's ``upstream`` span — when the offsets are right. The export
    never clamps a misplaced replica span into its parent: containment
    is *measured* (``otherData.containment``), not decorated.
    """
    events: list[dict] = []
    per_request: list[dict] = []
    results = {r: 0 for r in (
        "joined", "no_replica_meta", "unknown_replica", "no_offset",
        "no_replica_trace", "fetch_error",
    )}
    n_contained = 0
    worst_excess_s = 0.0
    anchors = [
        s["t_start_perf"] for s in router_samples
        if s.get("t_start_perf") is not None
    ]
    base = min(anchors) if anchors else 0.0

    def us(t_perf: float) -> float:
        return round((t_perf - base) * 1e6, 3)

    def emit(name, t0, t1, tid, cat, args) -> None:
        events.append({
            "name": name, "ph": "X", "cat": cat, "pid": 1, "tid": tid,
            "ts": us(t0), "dur": round(max(t1 - t0, 0.0) * 1e6, 3),
            "args": args,
        })

    meta_events: list[dict] = [{
        "name": "process_name", "ph": "M", "pid": 1,
        "args": {"name": "fleet-router (joined timeline)"},
    }]
    for lane, sample in enumerate(router_samples, start=1):
        rid = sample.get("request_id", "")
        anchor = sample.get("t_start_perf")
        if anchor is None:
            continue  # a pre-anchor snapshot cannot be placed at all
        replica = sample.get("replica")
        total = float(sample.get("total_seconds") or 0.0)
        meta_events.append({
            "name": "thread_name", "ph": "M", "pid": 1, "tid": lane,
            "args": {"name": f"req {rid} via {replica or '?'}"},
        })
        emit(
            f"request {rid}", anchor, anchor + total, lane, "router",
            {
                "request_id": rid, "status": sample.get("status"),
                "replica": replica, "attempts": sample.get("attempts"),
                "sampled_reason": sample.get("sampled_reason"),
            },
        )
        router_phases = _abs_phases(sample)
        for name, (t0, t1) in router_phases.items():
            emit(name, t0, t1, lane, "router", {"request_id": rid})

        if not replica:
            result = "no_replica_meta"
        elif replica not in replica_urls:
            result = "unknown_replica"
        else:
            offset = clock.offset_s(replica)
            if offset is None:
                result = "no_offset"
            else:
                snap, fetched = fetch(
                    replica_urls[replica], rid, timeout_s=timeout_s
                )
                if snap is None:
                    result = fetched
                else:
                    result = "joined"
        req_summary = {"request_id": rid, "replica": replica,
                       "result": result}
        if result == "joined":
            r_anchor = snap.get("t_start_perf")
            r_total = float(snap.get("total_seconds") or 0.0)
            if r_anchor is None:
                result = req_summary["result"] = "no_replica_trace"
            else:
                r0 = float(r_anchor) - offset
                r1 = r0 + r_total
                emit(
                    f"replica {replica}", r0, r1, lane, "replica",
                    {
                        "request_id": rid, "replica": replica,
                        "status": snap.get("status"),
                        "serve_path": snap.get("path"),
                        "offset_ms": round(offset * 1000.0, 3),
                    },
                )
                for name, (t0, t1) in _abs_phases(snap).items():
                    emit(
                        name, t0 - offset, t1 - offset, lane, "replica",
                        {"request_id": rid},
                    )
                upstream = router_phases.get("upstream")
                if upstream is not None:
                    excess = max(
                        upstream[0] - r0, r1 - upstream[1], 0.0
                    )
                    contained = excess <= CONTAINMENT_SLACK_S
                    n_contained += contained
                    worst_excess_s = max(worst_excess_s, excess)
                    req_summary["contained"] = contained
                    req_summary["containment_excess_ms"] = round(
                        excess * 1000.0, 3
                    )
        results[result] += 1
        FLEET_TRACE_JOINS.inc(result=result)
        per_request.append(req_summary)

    n = len(per_request)
    joined = results["joined"]
    containment_ratio = (n_contained / joined) if joined else None
    journal.event(
        "fleet_trace_export", requests=n, joined=joined,
        containment_ratio=containment_ratio,
    )
    return {
        "traceEvents": meta_events + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "kind": "fleet_trace",
            "requests": n,
            "results": results,
            "joined": joined,
            "containment": {
                "contained": n_contained,
                "ratio": (
                    None if containment_ratio is None
                    else round(containment_ratio, 4)
                ),
                "slack_ms": CONTAINMENT_SLACK_S * 1000.0,
                "worst_excess_ms": round(worst_excess_s * 1000.0, 3),
            },
            "clock_offsets": clock.snapshot(),
            "requests_detail": per_request,
        },
    }


def write_fleet_trace(path: str, export: dict) -> str:
    """Atomically write a joined-timeline export (Perfetto-loadable)."""
    return spans.write_trace(path, export)
