"""Aggregated fleet metrics: scrape in-rotation replicas, merge families.

Per-replica ``/metrics`` pages answer "how is replica rN doing"; capacity
planning and SLO accounting need the *service* view — one page where
``serve_requests_total`` is the fleet's throughput, not one process's.
This module is the router's control-plane aggregation layer behind
``GET /fleet/metrics``:

  * ``FleetScraper`` GETs every in-rotation replica's ``/metrics``
    (bounded per-replica timeout). A stale/unreachable replica is
    **marked, never silently omitted**: ``fleet_scrape_stale{replica=…}``
    flips to 1, the transition is journaled
    (``fleet_scrape_transition``), and the scrape result lands on
    ``fleet_scrape_total{result=…}`` — an aggregated page missing a
    replica must say so on the page itself.
  * ``merge_expositions`` folds the parsed pages into one
    strict-validator-clean exposition with the standard aggregation
    semantics per kind: **counters sum** across replicas (per label
    set), **gauges re-emit** with a ``replica`` label appended (a mean
    of queue depths is a lie; per-replica series are the truth), and
    **histograms bucket-merge** — identical ``le`` boundaries required,
    cumulative bucket counts / ``_sum`` / ``_count`` summed per label
    set. A family that cannot merge honestly (bucket boundaries differ
    across replicas mid-deploy, kinds disagree, label keys disagree) is
    dropped from the page and counted on
    ``fleet_scrape_merge_rejected_total{reason=…}`` — rejection is
    observable, not silent.
  * Families the router process itself owns (``fleet_*``,
    ``reqtrace_*``, …) are reported from the router's own registry and
    the replica-side copies are dropped from the merge
    (``reason="router_owned"``): one page, one writer per family name,
    no duplicate-family validator errors.
  * ``SLOTracker`` over the **router's own request stream** (the
    ``fleet_slo_*`` families, fed from the data path's single exit) —
    error-budget burn accounted where clients experience it, not
    per-replica. Client-fault 4xx outcomes are excluded, the same
    convention the replica-side tracker uses.

No jax anywhere (the router's import-purity rule covers this module
transitively); the parser is stdlib-only and strict enough for the pages
our own stack renders — it is a merge frontend, not a general scraper.
"""

from __future__ import annotations

import threading
import urllib.request

from machine_learning_replications_tpu.obs import journal
from machine_learning_replications_tpu.obs.registry import (
    REGISTRY,
    MetricsRegistry,
    _escape_label_value,
)
from machine_learning_replications_tpu.obs.slo import (
    SLO,
    SLOTracker,
    default_slos,
)

FLEET_SCRAPES = REGISTRY.counter(
    "fleet_scrape_total",
    "Per-replica /metrics scrapes behind /fleet/metrics by result.",
    labels=("result",),
)
FLEET_SCRAPE_STALE = REGISTRY.gauge(
    "fleet_scrape_stale",
    "1 when the replica's last /metrics scrape failed or timed out (its "
    "series on the aggregated page are stale or absent), else 0.",
    labels=("replica",),
)
FLEET_MERGE_REJECTED = REGISTRY.counter(
    "fleet_scrape_merge_rejected_total",
    "Replica metric families dropped from the aggregated page by reason "
    "(bucket_mismatch, kind_mismatch, label_mismatch, unsupported, "
    "router_owned).",
    labels=("reason",),
)
for _result in ("ok", "error"):
    FLEET_SCRAPES.labels(result=_result)

# The fleet-level SLO families: same shape as the per-process slo_*
# set (obs.slo), distinct names so a fleet page can carry BOTH the
# router-accounted fleet burn and the merged per-replica burn gauges.
FLEET_SLO_REQUESTS = REGISTRY.counter(
    "fleet_slo_requests_total",
    "Routed requests evaluated against the fleet-level SLO.",
    labels=("slo",),
)
FLEET_SLO_BAD = REGISTRY.counter(
    "fleet_slo_bad_total",
    "Routed requests that violated the fleet-level SLO.",
    labels=("slo",),
)
FLEET_SLO_GOOD = REGISTRY.gauge(
    "fleet_slo_good_ratio",
    "Fleet-level good-event ratio over the recent request window.",
    labels=("slo",),
)
FLEET_SLO_BURN = REGISTRY.gauge(
    "fleet_slo_burn_rate",
    "Fleet-level error-budget burn rate over the recent window (bad "
    "ratio / budget; 1.0 = burning exactly at the sustainable rate).",
    labels=("slo",),
)
FLEET_SLO_REMAINING = REGISTRY.gauge(
    "fleet_slo_error_budget_remaining_ratio",
    "Fleet-level lifetime error budget remaining (1 = untouched, 0 = "
    "spent, negative = blown).",
    labels=("slo",),
)
FLEET_SLO_TARGET = REGISTRY.gauge(
    "fleet_slo_target_ratio",
    "The declared fleet-level SLO target (constant).",
    labels=("slo",),
)

#: Merge-rejection reasons (the ``fleet_scrape_merge_rejected_total``
#: label space).
REJECT_REASONS = (
    "bucket_mismatch", "kind_mismatch", "label_mismatch", "unsupported",
    "router_owned",
)


def fleet_slo_tracker(
    slos: list[SLO] | None = None, window: int = 2048,
) -> SLOTracker:
    """An ``SLOTracker`` publishing on the ``fleet_slo_*`` families —
    the same evaluation/burn machinery as the per-process tracker,
    pointed at the registered fleet-level names."""
    return SLOTracker(
        slos if slos is not None else default_slos(),
        window=window,
        families={
            "requests": FLEET_SLO_REQUESTS,
            "bad": FLEET_SLO_BAD,
            "good_ratio": FLEET_SLO_GOOD,
            "burn": FLEET_SLO_BURN,
            "remaining": FLEET_SLO_REMAINING,
            "target": FLEET_SLO_TARGET,
        },
    )


# ---------------------------------------------------------------------------
# exposition parsing (text format 0.0.4, the subset our stack renders)
# ---------------------------------------------------------------------------


def _parse_value(tok: str) -> float:
    if tok in ("+Inf", "Inf"):
        return float("inf")
    if tok == "-Inf":
        return float("-inf")
    if tok == "NaN":
        return float("nan")
    return float(tok)


def _parse_labels(raw: str) -> dict[str, str]:
    """The ``{...}`` body → dict, honoring the three legal escapes."""
    out: dict[str, str] = {}
    i, n = 0, len(raw)
    while i < n:
        while i < n and raw[i] in ", ":
            i += 1
        if i >= n:
            break
        eq = raw.index("=", i)
        key = raw[i:eq].strip()
        i = eq + 1
        if i >= n or raw[i] != '"':
            raise ValueError(f"unquoted label value for {key!r}")
        i += 1
        buf: list[str] = []
        while i < n:
            c = raw[i]
            if c == "\\" and i + 1 < n:
                buf.append({"n": "\n"}.get(raw[i + 1], raw[i + 1]))
                i += 2
            elif c == '"':
                i += 1
                break
            else:
                buf.append(c)
                i += 1
        out[key] = "".join(buf)
    return out


def parse_exposition(text: str) -> dict[str, dict]:
    """One page → ``{family: {"kind", "help", "series"}}``.

    ``series`` maps a sorted ``((label, value), ...)`` key to the sample
    value for counters/gauges, and to ``{"buckets": {le: count}, "sum",
    "count"}`` for histograms (the ``le`` label lifted out of the key).
    Unparseable lines raise ``ValueError`` — a replica page that fails
    here fails its scrape, which the caller marks stale rather than
    merging garbage.
    """
    families: dict[str, dict] = {}
    types: dict[str, str] = {}

    def fam(name: str) -> dict:
        f = families.get(name)
        if f is None:
            f = families[name] = {
                "kind": types.get(name, "untyped"), "help": "",
                "series": {},
            }
        return f

    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] == "TYPE":
                types[parts[2]] = parts[3].strip() if len(parts) > 3 \
                    else "untyped"
                fam(parts[2])["kind"] = types[parts[2]]
            elif len(parts) >= 3 and parts[1] == "HELP":
                fam(parts[2])["help"] = parts[3] if len(parts) > 3 else ""
            continue
        brace = line.find("{")
        space = line.find(" ")
        if brace != -1 and (space == -1 or brace < space):
            name = line[:brace]
            end = line.rindex("}")
            labels = _parse_labels(line[brace + 1:end])
            tail = line[end + 1:].split()
        else:
            toks = line.split()
            name, labels, tail = toks[0], {}, toks[1:]
        if not tail:
            raise ValueError(f"sample without a value: {line!r}")
        value = _parse_value(tail[0])

        base, suffix = name, ""
        for sfx in ("_bucket", "_sum", "_count"):
            if name.endswith(sfx) and \
                    types.get(name[: -len(sfx)]) == "histogram":
                base, suffix = name[: -len(sfx)], sfx
                break
        f = fam(base)
        if f["kind"] == "histogram":
            key = tuple(sorted(
                (k, v) for k, v in labels.items() if k != "le"
            ))
            series = f["series"].setdefault(
                key, {"buckets": {}, "sum": 0.0, "count": 0.0}
            )
            if suffix == "_bucket":
                series["buckets"][labels.get("le", "")] = value
            elif suffix == "_sum":
                series["sum"] = value
            elif suffix == "_count":
                series["count"] = value
            else:
                raise ValueError(
                    f"bare sample {name!r} in histogram family {base!r}"
                )
        else:
            f["series"][tuple(sorted(labels.items()))] = value
    return families


# ---------------------------------------------------------------------------
# merging
# ---------------------------------------------------------------------------


def _label_names(family: dict) -> set[tuple[str, ...]]:
    """The distinct label-name tuples across a family's series (one
    element = consistent labeling)."""
    return {
        tuple(k for k, _ in key) for key in family["series"]
    }


def merge_expositions(
    pages: dict[str, dict[str, dict]],
    drop: frozenset[str] | set[str] = frozenset(),
) -> tuple[dict[str, dict], list[dict]]:
    """Merge parsed per-replica pages (``{replica: parse_exposition(…)}``)
    into one family map, applying the per-kind semantics from the module
    docstring. ``drop`` lists router-owned family names to exclude
    (``reason="router_owned"``). Returns ``(merged, rejected)`` where
    ``rejected`` is ``[{"name", "reason"}, ...]`` — also counted on
    ``fleet_scrape_merge_rejected_total``."""
    by_family: dict[str, list[tuple[str, dict]]] = {}
    for replica in sorted(pages):
        for name, family in pages[replica].items():
            if not family["series"]:
                continue  # TYPE/HELP with no samples: nothing to merge
            by_family.setdefault(name, []).append((replica, family))

    merged: dict[str, dict] = {}
    rejected: list[dict] = []

    def reject(name: str, reason: str) -> None:
        rejected.append({"name": name, "reason": reason})
        FLEET_MERGE_REJECTED.inc(reason=reason)

    for name, copies in sorted(by_family.items()):
        if name in drop:
            reject(name, "router_owned")
            continue
        kinds = {family["kind"] for _, family in copies}
        if len(kinds) > 1:
            reject(name, "kind_mismatch")
            continue
        kind = kinds.pop()
        if kind not in ("counter", "gauge", "histogram"):
            reject(name, "unsupported")
            continue
        label_names = set()
        for _, family in copies:
            label_names |= _label_names(family)
        if len(label_names) > 1 or (
            kind == "gauge" and label_names and
            "replica" in next(iter(label_names))
        ):
            # Inconsistent label keys cannot merge into one family; a
            # replica-side gauge already labeled `replica` would collide
            # with the label this merge appends.
            reject(name, "label_mismatch")
            continue
        help_ = next(
            (f["help"] for _, f in copies if f["help"]), ""
        )
        out = {"kind": kind, "help": help_, "series": {}}
        if kind == "counter":
            for _, family in copies:
                for key, value in family["series"].items():
                    out["series"][key] = out["series"].get(key, 0.0) + value
        elif kind == "gauge":
            for replica, family in copies:
                for key, value in family["series"].items():
                    out["series"][
                        tuple(sorted(key + (("replica", replica),)))
                    ] = value
        else:  # histogram: identical-boundary bucket merge
            bounds = None
            ok = True
            for _, family in copies:
                for series in family["series"].values():
                    les = tuple(sorted(series["buckets"]))
                    if bounds is None:
                        bounds = les
                    elif les != bounds:
                        ok = False
                        break
                if not ok:
                    break
            if not ok:
                reject(name, "bucket_mismatch")
                continue
            for _, family in copies:
                for key, series in family["series"].items():
                    acc = out["series"].setdefault(
                        key, {"buckets": dict.fromkeys(bounds, 0.0),
                              "sum": 0.0, "count": 0.0},
                    )
                    for le, v in series["buckets"].items():
                        acc["buckets"][le] += v
                    acc["sum"] += series["sum"]
                    acc["count"] += series["count"]
        merged[name] = out
    return merged, rejected


def _fmt(v: float) -> str:
    if v != v:
        return "NaN"
    if v in (float("inf"), float("-inf")):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _series_name(name: str, key: tuple, extra: dict | None = None) -> str:
    pairs = list(key) + list((extra or {}).items())
    if not pairs:
        return name
    inner = ",".join(
        f'{k}="{_escape_label_value(str(v))}"' for k, v in pairs
    )
    return f"{name}{{{inner}}}"


def _le_sort(le: str) -> float:
    try:
        return _parse_value(le)
    except ValueError:
        return float("inf")


def render_merged(merged: dict[str, dict]) -> str:
    """The merged family map → strict text exposition (one contiguous
    group per family, TYPE before samples, trailing newline)."""
    lines: list[str] = []
    for name, family in sorted(merged.items()):
        help_ = family["help"].replace("\n", " ")
        lines.append(f"# HELP {name} {help_}".rstrip())
        lines.append(f"# TYPE {name} {family['kind']}")
        if family["kind"] == "histogram":
            for key, series in sorted(family["series"].items()):
                for le in sorted(series["buckets"], key=_le_sort):
                    lines.append(
                        f"{_series_name(name + '_bucket', key, {'le': le})}"
                        f" {_fmt(series['buckets'][le])}"
                    )
                lines.append(
                    f"{_series_name(name + '_sum', key)} "
                    f"{_fmt(series['sum'])}"
                )
                lines.append(
                    f"{_series_name(name + '_count', key)} "
                    f"{_fmt(series['count'])}"
                )
        else:
            for key, value in sorted(family["series"].items()):
                lines.append(f"{_series_name(name, key)} {_fmt(value)}")
    return "\n".join(lines) + "\n" if lines else ""


# ---------------------------------------------------------------------------
# the scraper behind GET /fleet/metrics
# ---------------------------------------------------------------------------


class FleetScraper:
    """Scrape in-rotation replicas and render the aggregated page
    (module docstring). ``render_fleet_page`` blocks for up to
    ``timeout_s`` per replica — callers run it on a short-lived thread
    off the router's event loop (the ``/debug/profile`` pattern)."""

    def __init__(
        self,
        registry,
        metrics_registry: MetricsRegistry | None = None,
        timeout_s: float = 1.0,
    ) -> None:
        self.registry = registry  # fleet.registry.ReplicaRegistry
        self.metrics_registry = metrics_registry or REGISTRY
        self.timeout_s = float(timeout_s)
        self._lock = threading.Lock()
        self._stale: dict[str, bool] = {}

    def _mark(self, replica_id: str, stale: bool) -> None:
        FLEET_SCRAPES.inc(result="error" if stale else "ok")
        FLEET_SCRAPE_STALE.set(1.0 if stale else 0.0, replica=replica_id)
        with self._lock:
            prev = self._stale.get(replica_id)
            self._stale[replica_id] = stale
        if prev != stale and (prev is not None or stale):
            # Journal transitions (and a first-ever-stale observation);
            # a steady state repeated every scrape would drown the log.
            journal.event(
                "fleet_scrape_transition", replica=replica_id, stale=stale,
            )

    def forget(self, replica_id: str) -> None:
        """Retire a departed replica's scrape state AND its
        ``fleet_scrape_stale`` series. Wired to the registry's retire
        listeners: a deregistered (or replaced) replica must vanish
        from the exposition, not linger at its last value — a frozen
        stale=1 would page forever, a frozen stale=0 would mask that
        the replica is gone."""
        with self._lock:
            self._stale.pop(replica_id, None)
        FLEET_SCRAPE_STALE.remove(replica=replica_id)

    def scrape(self) -> tuple[dict[str, dict], dict]:
        """One scrape pass over the in-rotation membership: returns
        ``(parsed_pages, summary)``; every replica lands in exactly one
        of ``summary["scraped"]`` / ``summary["stale"]``."""
        pages: dict[str, dict] = {}
        summary: dict = {"scraped": [], "stale": []}
        for rep in self.registry.snapshot():
            if not rep["in_rotation"]:
                continue
            rid = rep["id"]
            try:
                with urllib.request.urlopen(
                    rep["url"].rstrip("/") + "/metrics",
                    timeout=self.timeout_s,
                ) as resp:
                    pages[rid] = parse_exposition(
                        resp.read().decode("utf-8", "replace")
                    )
            except Exception:
                self._mark(rid, stale=True)
                summary["stale"].append(rid)
                continue
            self._mark(rid, stale=False)
            summary["scraped"].append(rid)
        return pages, summary

    def render_fleet_page(self) -> tuple[str, dict]:
        """Scrape + merge + append the router's own families: the full
        ``/fleet/metrics`` page and its summary. The router's own
        registry render carries the scrape/staleness/SLO families
        updated by this very pass, so the page describes its own
        production."""
        pages, summary = self.scrape()
        own = frozenset(
            fam.name for fam in self.metrics_registry.families()
        )
        merged, rejected = merge_expositions(pages, drop=own)
        text = render_merged(merged) + \
            self.metrics_registry.render_prometheus()
        summary.update(
            replicas_merged=len(pages),
            families_merged=len(merged),
            rejected=rejected,
        )
        return text, summary
