"""Declarative latency/availability SLOs with error-budget burn gauges.

An SLO is a target over a ratio of *good* events: "99% of requests answer
under 250 ms", "99.9% of admitted requests don't fail server-side". The
quantity an operator alarms on is not the ratio itself but the **burn
rate** (SRE workbook ch. 5): how fast the error budget — the allowed
fraction of bad events, ``1 − target`` — is being spent. Burn rate 1.0
means bad events arrive exactly at the sustainable rate; 10× means the
budget burns ten times too fast and the pager should fire long before the
monthly window is blown.

``SLOTracker`` evaluates each completed request against every declared
``SLO`` and exports, through the existing process-global registry (so the
gauges ride the same ``/metrics`` page and validator as everything else):

  ``slo_requests_total{slo=…}``             counter — events evaluated
  ``slo_bad_total{slo=…}``                  counter — events that violated
  ``slo_good_ratio{slo=…}``                 gauge — recent-window good ratio
  ``slo_burn_rate{slo=…}``                  gauge — window bad ratio ÷ budget
  ``slo_error_budget_remaining_ratio{slo=…}`` gauge — lifetime budget left
                                            (1 = untouched, 0 = spent,
                                            negative = blown)
  ``slo_target_ratio{slo=…}``               gauge — the declared target
                                            (constant; lets a dashboard
                                            draw the objective line
                                            without configuration)

The recent window is a bounded ring of the last ``window`` events (same
bounded-over-unbounded discipline as the metrics latency ring): burn rate
tracks *current* behavior, while the budget-remaining gauge integrates
the whole process lifetime. Everything is stdlib + the registry — no jax.
"""

from __future__ import annotations

import threading
from typing import Sequence

from machine_learning_replications_tpu.obs.registry import (
    REGISTRY,
    MetricsRegistry,
)


class SLO:
    """One objective. ``kind`` is ``"latency"`` (good = ok AND latency ≤
    ``threshold_s``) or ``"availability"`` (good = ok, i.e. the server
    answered the admitted request without shedding/erroring/timing out)."""

    def __init__(
        self,
        name: str,
        target: float,
        kind: str = "latency",
        threshold_s: float | None = None,
    ) -> None:
        if not 0.0 < target < 1.0:
            raise ValueError(f"SLO target must be in (0, 1), got {target}")
        if kind not in ("latency", "availability"):
            raise ValueError(f"unknown SLO kind {kind!r}")
        if kind == "latency" and (threshold_s is None or threshold_s <= 0):
            raise ValueError("latency SLO needs a positive threshold_s")
        self.name = name
        self.kind = kind
        self.target = float(target)
        self.threshold_s = None if threshold_s is None else float(threshold_s)

    @property
    def budget(self) -> float:
        """The error budget: the allowed bad fraction, ``1 − target``."""
        return 1.0 - self.target

    def is_good(self, latency_s: float, ok: bool) -> bool:
        if self.kind == "availability":
            return ok
        return ok and latency_s <= self.threshold_s

    def describe(self) -> dict:
        return {
            "name": self.name,
            "kind": self.kind,
            "target": self.target,
            **(
                {"threshold_seconds": self.threshold_s}
                if self.threshold_s is not None else {}
            ),
        }


def default_slos(
    latency_ms: float = 250.0,
    latency_target: float = 0.99,
    availability_target: float = 0.999,
) -> list[SLO]:
    """The serving layer's stock objectives (overridable per-flag from
    ``cli.py serve``): p99-style latency under ``latency_ms``, and
    three-nines availability of admitted requests."""
    return [
        SLO(
            f"latency_le_{latency_ms:g}ms", latency_target,
            kind="latency", threshold_s=latency_ms / 1000.0,
        ),
        SLO("availability", availability_target, kind="availability"),
    ]


class _PerSLO:
    __slots__ = ("slo", "total", "bad", "ring", "ring_bad", "ring_n",
                 "c_requests", "c_bad", "g_good", "g_burn", "g_remaining")

    def __init__(self, slo: SLO, window: int) -> None:
        self.slo = slo
        self.total = 0
        self.bad = 0
        self.ring = bytearray(window)  # 1 = bad event, ring of recents
        self.ring_bad = 0
        self.ring_n = 0
        # Child instruments cached at declaration (observe() runs per
        # request on the serving flush path; resolving five label sets
        # per call is measurable at event-loop throughput).
        self.c_requests = self.c_bad = None
        self.g_good = self.g_burn = self.g_remaining = None


class SLOTracker:
    """Evaluates requests against declared SLOs and keeps the registry
    gauges current. One ``observe`` per completed admission decision."""

    def __init__(
        self,
        slos: Sequence[SLO],
        registry: MetricsRegistry | None = None,
        window: int = 2048,
        families: dict | None = None,
    ) -> None:
        names = [s.name for s in slos]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        reg = registry or REGISTRY
        self._lock = threading.Lock()
        self._state = [_PerSLO(s, int(window)) for s in slos]
        if families is not None:
            # A caller (the fleet-level tracker in obs.fleetmetrics)
            # supplies pre-registered family objects under its own
            # names; the catalog rule wants family names as literals at
            # their registration site, so the names cannot be built here.
            self._requests = families["requests"]
            self._bad = families["bad"]
            self._good_ratio = families["good_ratio"]
            self._burn = families["burn"]
            self._remaining = families["remaining"]
            self._target = families["target"]
        else:
            self._requests = reg.counter(
                "slo_requests_total", "Requests evaluated against the SLO.",
                labels=("slo",),
            )
            self._bad = reg.counter(
                "slo_bad_total", "Requests that violated the SLO.",
                labels=("slo",),
            )
            self._good_ratio = reg.gauge(
                "slo_good_ratio",
                "Good-event ratio over the recent request window.",
                labels=("slo",),
            )
            self._burn = reg.gauge(
                "slo_burn_rate",
                "Error-budget burn rate over the recent window (bad ratio "
                "/ budget; 1.0 = burning exactly at the sustainable rate).",
                labels=("slo",),
            )
            self._remaining = reg.gauge(
                "slo_error_budget_remaining_ratio",
                "Lifetime error budget remaining (1 = untouched, 0 = "
                "spent, negative = blown).",
                labels=("slo",),
            )
            self._target = reg.gauge(
                "slo_target_ratio", "The declared SLO target (constant).",
                labels=("slo",),
            )
        for st in self._state:
            s = st.slo
            # Materialize every series at declaration: a scrape taken
            # before the first request still shows the objectives. The
            # children are kept — observe() updates them without a label
            # resolution per call.
            st.c_requests = self._requests.labels(slo=s.name)
            st.c_bad = self._bad.labels(slo=s.name)
            st.g_good = self._good_ratio.labels(slo=s.name)
            st.g_burn = self._burn.labels(slo=s.name)
            st.g_remaining = self._remaining.labels(slo=s.name)
            st.g_good.set(1.0)
            st.g_burn.set(0.0)
            st.g_remaining.set(1.0)
            self._target.set(s.target, slo=s.name)

    @property
    def slos(self) -> list[SLO]:
        return [st.slo for st in self._state]

    def observe(self, latency_s: float, ok: bool) -> None:
        for st in self._state:
            good = st.slo.is_good(latency_s, ok)
            with self._lock:
                st.total += 1
                if not good:
                    st.bad += 1
                i = st.ring_n % len(st.ring)
                if st.ring_n >= len(st.ring):
                    st.ring_bad -= st.ring[i]
                st.ring[i] = 0 if good else 1
                st.ring_bad += st.ring[i]
                st.ring_n += 1
                n_window = min(st.ring_n, len(st.ring))
                bad_ratio = st.ring_bad / n_window
                lifetime_bad_ratio = st.bad / st.total
            budget = st.slo.budget
            st.c_requests.inc()
            if not good:
                st.c_bad.inc()
            st.g_good.set(1.0 - bad_ratio)
            st.g_burn.set(bad_ratio / budget)
            st.g_remaining.set(1.0 - lifetime_bad_ratio / budget)

    def snapshot(self) -> list[dict]:
        out = []
        for st in self._state:
            with self._lock:
                total, bad = st.total, st.bad
                n_window = min(st.ring_n, len(st.ring))
                ring_bad = st.ring_bad
            budget = st.slo.budget
            bad_ratio = ring_bad / n_window if n_window else 0.0
            out.append({
                **st.slo.describe(),
                "requests_total": total,
                "bad_total": bad,
                "window_good_ratio": 1.0 - bad_ratio,
                "burn_rate": bad_ratio / budget,
                "error_budget_remaining_ratio": (
                    1.0 - (bad / total) / budget if total else 1.0
                ),
            })
        return out
