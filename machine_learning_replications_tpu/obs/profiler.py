"""On-demand ``jax.profiler`` capture with a single-flight guard.

The span timeline (``obs.spans``) is host-side orchestration; when a tail
investigation needs the *device* story — which XLA ops, what overlap,
where the compile went — the tool is jax's own profiler, which writes a
TensorBoard/Perfetto-loadable capture (``plugins/profile/<ts>/*.xplane.pb``
plus a ``*.trace.json.gz``). Profiling a live serving process must be
**on demand and exclusive**: the XLA profiler is process-global state
(``start_trace`` while a trace is active raises deep inside TSL), and two
operators hitting ``/debug/profile`` at once must not corrupt each
other's capture. ``capture`` is therefore single-flight — one capture at
a time, concurrent callers get ``ProfilerBusy`` immediately (the HTTP
layer maps it to 409) instead of queueing behind a multi-second capture.

Captures are counted in the global registry (``profile_captures_total``)
and journaled (``profile_capture`` event) so a profile artifact found on
disk can be traced back to who asked for it and when. jax is imported
lazily — importing this module stays safe in jax-free orchestrators.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any

from machine_learning_replications_tpu.obs import journal
from machine_learning_replications_tpu.obs.registry import REGISTRY

#: Upper bound on one capture (seconds): /debug/profile is a blocking
#: endpoint and the profiler pauses nothing, but an unbounded capture
#: would pin the single-flight slot (and grow the artifact) forever.
MAX_SECONDS = 60.0

_lock = threading.Lock()
_seq = 0  # capture ordinal; mutated only under _lock (single-flight)

# Declared at import (the registry is jax-free), so the family is on
# /metrics from the first scrape — an absent series and a zero series
# read very differently to a dashboard.
_captures = REGISTRY.counter(
    "profile_captures_total",
    "On-demand jax.profiler captures served, by outcome.",
    labels=("outcome",),
)
_captures.labels(outcome="ok")
_captures.labels(outcome="error")


class ProfilerBusy(RuntimeError):
    """A capture is already in flight — the request was rejected, not
    queued (single-flight contract)."""


def is_busy() -> bool:
    """Whether a capture currently holds the single-flight slot (advisory
    — the authoritative answer is ``capture`` raising ``ProfilerBusy``)."""
    if _lock.acquire(blocking=False):
        _lock.release()
        return False
    return True


def _artifact_files(root: str) -> list[dict]:
    out = []
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in filenames:
            path = os.path.join(dirpath, fn)
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            out.append({"path": path, "bytes": size})
    out.sort(key=lambda f: f["path"])
    return out


def capture(seconds: float, out_dir: str) -> dict[str, Any]:
    """Run one profiler capture of ``seconds`` wall time into ``out_dir``
    and return the artifact description (directory, files, total bytes).

    Raises ``ProfilerBusy`` when another capture is in flight and
    ``ValueError`` for an out-of-range duration. The capture directory is
    timestamped under ``out_dir`` so repeated captures never clobber each
    other."""
    seconds = float(seconds)
    if not 0.0 < seconds <= MAX_SECONDS:
        raise ValueError(
            f"capture seconds must be in (0, {MAX_SECONDS:g}], got {seconds:g}"
        )
    if not _lock.acquire(blocking=False):
        raise ProfilerBusy("a profiler capture is already in flight")
    try:
        import jax

        global _seq
        _seq += 1
        # Timestamp for the human, ordinal for uniqueness: two
        # sub-second captures land in the same wall-clock second, and a
        # reused directory would list the previous capture's files as
        # this one's artifact.
        target = os.path.join(
            os.path.abspath(out_dir),
            time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
            + f"-{_seq:04d}",
        )
        os.makedirs(target, exist_ok=True)
        t0 = time.perf_counter()
        try:
            jax.profiler.start_trace(target)
            try:
                time.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
        except Exception as exc:
            _captures.inc(outcome="error")
            journal.event(
                "profile_capture", ok=False, seconds=seconds,
                error=f"{type(exc).__name__}: {exc}",
            )
            raise
        wall = time.perf_counter() - t0
        files = _artifact_files(target)
        artifact = {
            "profile_dir": target,
            "requested_seconds": seconds,
            "wall_seconds": round(wall, 3),
            "files": files,
            "total_bytes": sum(f["bytes"] for f in files),
        }
        _captures.inc(outcome="ok")
        journal.event(
            "profile_capture", ok=True, seconds=seconds,
            profile_dir=target, total_bytes=artifact["total_bytes"],
        )
        return artifact
    finally:
        _lock.release()
