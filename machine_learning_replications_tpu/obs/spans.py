"""Hierarchical, thread-aware spans with Chrome-trace-event export.

A span is a named wall-clock interval that (a) nests — each thread keeps
its own open-span stack, so concurrent HTTP handler threads and the
training main thread interleave without corrupting each other's
hierarchy — and (b) closes *honestly* under JAX's async dispatch: the body
registers device work via the yielded handle's ``block``, and span exit
``block_until_ready``-s it before the clock stops, so a span's duration is
real device work, not dispatch (the same discipline ``PhaseTimer``
established; ``PhaseTimer`` is now a thin adapter over this module).

Export is the Chrome trace-event format (``ph: "X"`` complete events with
microsecond timestamps): write the JSON with ``Tracer.write`` and open it
at https://ui.perfetto.dev (or ``chrome://tracing``). Parent/child
containment is positional — a child's ``[ts, ts+dur]`` lies inside its
parent's on the same ``tid`` — which is exactly how the viewers nest them.

A process-global *active* tracer (``set_tracer`` / ``get_tracer``) lets
call sites instrument unconditionally: the module-level ``span`` records
into the active tracer when one is set and otherwise only performs the
device-blocking contract (so timing semantics of enclosing timers hold
with tracing off, at no event-recording cost).
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Any, Iterator


def _block_pending(pending: list) -> None:
    """``jax.block_until_ready`` every registered pytree (jax imported
    lazily: journal/bench-side importers of this module must stay jax-free)."""
    if not pending:
        return
    import jax

    for x in pending:
        jax.block_until_ready(x)


class SpanHandle:
    """Yielded by ``span``: register device work to block on at exit, and
    attach key/value annotations that land in the trace event's ``args``."""

    __slots__ = ("_pending", "args")

    def __init__(self) -> None:
        self._pending: list[Any] = []
        self.args: dict[str, Any] = {}

    def block(self, x: Any) -> Any:
        """Register ``x`` (any pytree of arrays) to be blocked on when the
        span closes, and pass it through."""
        self._pending.append(x)
        return x

    def note(self, **kv: Any) -> None:
        """Attach annotations (JSON-friendly values) to the span."""
        self.args.update(kv)


class Tracer:
    """Collects span events; one instance per run (thread-safe).

    Timestamps are microseconds from tracer construction
    (``time.perf_counter`` based — monotonic, sub-µs resolution), which is
    what the trace viewers expect; the wall-clock epoch is recorded in the
    exported ``otherData`` so events can be correlated with journal lines.

    The event buffer is BOUNDED at ``max_events`` (a ring of the most
    recent events, same bounded-over-unbounded discipline as the metrics
    latency ring): a long-lived traced serving process emits one span per
    flush forever, and an unbounded list would be a slow memory leak that
    ends in a trace file Perfetto cannot load. Evictions are counted and
    reported in the export's ``otherData.dropped_events``.
    """

    def __init__(self, process_name: str = "mlr-tpu",
                 max_events: int = 250_000) -> None:
        import collections

        self._lock = threading.Lock()
        self._events: collections.deque[dict] = collections.deque()
        self._dropped = 0
        self.max_events = int(max_events)
        self._t0 = time.perf_counter()
        # Wall-clock epoch anchor for the Chrome-trace export; all
        # span math is monotonic and only display maps through this.
        self._epoch_unix = time.time()  # graftcheck: disable=monotonic-clock
        self._pid = os.getpid()
        self._tids: dict[int, int] = {}  # thread ident -> small stable tid
        self._vtids: dict[str, int] = {}  # virtual track name -> tid
        self._next_tid = 1
        self._meta: list[dict] = []  # process/thread names: tiny, kept whole
        self._tls = threading.local()
        self.process_name = process_name

    # -- internal ----------------------------------------------------------

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            tid = self._tids.get(ident)
            if tid is None:
                tid = self._tids[ident] = self._next_tid
                self._next_tid += 1
                self._meta.append({
                    "name": "thread_name", "ph": "M", "pid": self._pid,
                    "tid": tid,
                    "args": {"name": threading.current_thread().name},
                })
        return tid

    # -- merging externally-timed events ------------------------------------

    def current_tid(self) -> int:
        """The calling thread's tid in this trace (allocated on first use).
        Call sites stamp it so events recorded *later* — e.g. a sampled
        request trace emitted at completion — can land on the track where
        the work actually ran (``add_complete_event``)."""
        return self._tid()

    def virtual_tid(self, name: str) -> int:
        """A stable tid for a named *virtual* track (no OS thread behind
        it) — e.g. one lane per in-flight sampled request, so request
        timelines render as their own rows instead of interleaving with
        the handler threads that happened to carry them."""
        with self._lock:
            tid = self._vtids.get(name)
            if tid is None:
                tid = self._vtids[name] = self._next_tid
                self._next_tid += 1
                self._meta.append({
                    "name": "thread_name", "ph": "M", "pid": self._pid,
                    "tid": tid, "args": {"name": name},
                })
        return tid

    def to_trace_us(self, t_perf: float) -> float:
        """A raw ``time.perf_counter()`` stamp → this trace's µs timeline."""
        return (t_perf - self._t0) * 1e6

    def add_complete_event(
        self,
        name: str,
        t0_perf: float,
        t1_perf: float,
        tid: int | None = None,
        cat: str = "span",
        args: dict | None = None,
    ) -> None:
        """Record a ``ph: "X"`` event from raw ``perf_counter`` stamps —
        the injection point for work timed outside the ``span`` context
        manager (request phases measured across threads and emitted only
        if the completed request is tail-sampled). ``tid`` defaults to the
        calling thread's track; pass a stamped ``current_tid`` /
        ``virtual_tid`` to place the event where it belongs."""
        ev = {
            "name": name, "ph": "X", "cat": cat, "pid": self._pid,
            "tid": self._tid() if tid is None else int(tid),
            "ts": round(self.to_trace_us(t0_perf), 3),
            "dur": round(max(t1_perf - t0_perf, 0.0) * 1e6, 3),
            "args": dict(args or {}),
        }
        with self._lock:
            self._events.append(ev)
            if len(self._events) > self.max_events:
                self._events.popleft()
                self._dropped += 1

    def _stack(self) -> list[str]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    # -- recording ---------------------------------------------------------

    @contextlib.contextmanager
    def span(self, name: str, **args: Any) -> Iterator[SpanHandle]:
        handle = SpanHandle()
        handle.args.update(args)
        tid = self._tid()
        stack = self._stack()
        parent = stack[-1] if stack else None
        stack.append(name)
        ts = self._now_us()
        try:
            yield handle
        finally:
            # The stack pop and event record must run even when the
            # device blocking raises (XlaRuntimeError, debug-nans, OOM):
            # a name left on the thread-local stack would corrupt the
            # parentage of every later span on this thread.
            try:
                _block_pending(handle._pending)
            finally:
                dur = self._now_us() - ts
                stack.pop()
                ev_args = {
                    k: (v if isinstance(
                        v, (str, int, float, bool, type(None))) else str(v))
                    for k, v in handle.args.items()
                }
                if parent is not None:
                    ev_args.setdefault("parent", parent)
                ev = {
                    "name": name, "ph": "X", "cat": "span",
                    "pid": self._pid, "tid": tid,
                    "ts": round(ts, 3), "dur": round(dur, 3),
                    "args": ev_args,
                }
                with self._lock:
                    self._events.append(ev)
                    if len(self._events) > self.max_events:
                        self._events.popleft()
                        self._dropped += 1

    # -- export ------------------------------------------------------------

    def export(self) -> dict:
        """The Chrome trace-event JSON object (Perfetto-loadable)."""
        with self._lock:
            events = list(self._events)
            meta = list(self._meta)
            dropped = self._dropped
        meta.insert(0, {
            "name": "process_name", "ph": "M", "pid": self._pid,
            "args": {"name": self.process_name},
        })
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {
                "epoch_unix_s": self._epoch_unix,
                "process": self.process_name,
                "dropped_events": dropped,
            },
        }

    def write(self, path: str | os.PathLike) -> str:
        """Write the trace JSON to ``path`` (parent dirs created); returns
        the absolute path."""
        return write_trace(path, self.export())


def write_trace(path: str | os.PathLike, trace: dict) -> str:
    """Atomically write a Chrome-trace JSON object (parent dirs created);
    returns the absolute path. Shared by ``Tracer.write`` and the fleet
    trace join (``obs.fleettrace``), whose export is assembled from
    cross-process snapshots rather than a live tracer."""
    path = os.path.abspath(os.fspath(path))
    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(trace, f)
    os.replace(tmp, path)
    return path


# -- process-global active tracer ------------------------------------------

_active: Tracer | None = None
_active_lock = threading.Lock()


def set_tracer(tracer: Tracer | None) -> None:
    """Install (or clear, with None) the process-global active tracer."""
    global _active
    with _active_lock:
        _active = tracer


def get_tracer() -> Tracer | None:
    return _active


@contextlib.contextmanager
def span(name: str, **args: Any) -> Iterator[SpanHandle]:
    """A span on the active tracer; with no tracer installed, a no-event
    scope that still honors the ``block`` contract at exit (enclosing
    timers keep their block-on-device semantics with tracing off)."""
    tracer = _active
    if tracer is not None:
        with tracer.span(name, **args) as handle:
            yield handle
        return
    handle = SpanHandle()
    handle.args.update(args)
    try:
        yield handle
    finally:
        _block_pending(handle._pending)
