"""XLA compile / transfer accounting via ``jax.monitoring`` listeners.

Two properties of this stack were previously only *test* assertions or
post-hoc guesses:

  * the serving engine compiles at most once per bucket
    (``tests/test_serve.py`` counts traces in-process);
  * the training loop's jitted stages compile once and are reused (a
    recompile regression shows up only as mysteriously slow walls).

``jax.monitoring`` is jax's own instrumentation bus: the runtime calls
registered listeners at every backend compile (with its duration), every
jaxpr trace, and every persistent-compilation-cache hit/miss. ``install``
routes those into the process-global metrics registry as:

  ``jax_compiles_total``                counter — XLA backend compiles
  ``jax_compile_seconds_total``         counter — seconds inside compiles
  ``jax_trace_seconds_total``           counter — seconds tracing jaxprs
  ``jax_compilation_cache_events_total{event=...}``
                                        counter — persistent-cache traffic

so a ``/metrics`` scrape (or ``REGISTRY.snapshot()``) answers "did that
deploy start recompiling per batch?" in production, not just under pytest.

Host↔device transfer bytes have no monitoring event in this jax version,
so the accounting is at the call sites this repo owns: route uploads
through ``device_put`` here (the serve engine's param staging does) or
call ``record_transfer`` where bytes are known — both feed
``jax_transfer_bytes_total{direction=...}``.

``install`` is idempotent and the listeners never raise (an observability
hook that can fail a compile is worse than no hook); jax itself is
imported lazily so importing this module stays safe in jax-free
orchestrator processes.
"""

from __future__ import annotations

from typing import Any

from machine_learning_replications_tpu.obs.registry import (
    REGISTRY,
    MetricsRegistry,
)

# The duration-event keys jax 0.4.x emits (jax/_src/dispatch.py).
COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
LOWER_EVENT = "/jax/core/compile/jaxpr_to_mlir_module_duration"
_CACHE_PREFIX = "/jax/compilation_cache/"

_installed = False
_families: dict[str, Any] = {}
_bound_registry: MetricsRegistry | None = None


def _declare(registry: MetricsRegistry) -> dict[str, Any]:
    return {
        "compiles": registry.counter(
            "jax_compiles_total",
            "XLA backend compiles observed via jax.monitoring.",
        ),
        "compile_seconds": registry.counter(
            "jax_compile_seconds_total",
            "Seconds spent in XLA backend compilation.",
        ),
        "trace_seconds": registry.counter(
            "jax_trace_seconds_total",
            "Seconds spent tracing jaxprs (includes lowering).",
        ),
        "cache_events": registry.counter(
            "jax_compilation_cache_events_total",
            "Persistent compilation cache traffic by event.",
            labels=("event",),
        ),
        "transfer_bytes": registry.counter(
            "jax_transfer_bytes_total",
            "Host/device transfer bytes accounted at instrumented call "
            "sites (obs.jaxmon.device_put / record_transfer).",
            labels=("direction",),
        ),
    }


def install(registry: MetricsRegistry | None = None) -> dict[str, Any]:
    """Register the listeners (once per process) and return the instrument
    families. Safe to call from several wiring points — the CLI, the serve
    stack, and tests all do.

    The listeners bind to ONE registry for the process lifetime (the one
    the first ``install`` names; default the global ``REGISTRY``): the
    already-registered ``jax.monitoring`` callbacks write through the
    module-level families, so silently rebinding them on a later call
    would freeze the registry every existing ``/metrics`` page serves.
    A later call naming a *different* registry is therefore an error."""
    global _installed, _families, _bound_registry
    reg = registry or REGISTRY
    if _installed:
        if reg is not _bound_registry:
            raise ValueError(
                "obs.jaxmon is already installed against a different "
                "registry; the jax.monitoring listeners bind once per "
                "process"
            )
        return _families
    _families = _declare(reg)
    _bound_registry = reg
    import jax

    jax.monitoring.register_event_duration_secs_listener(_on_duration)
    jax.monitoring.register_event_listener(_on_event)
    _installed = True
    return _families


def _on_duration(event: str, duration: float, **kw) -> None:
    try:
        if event == COMPILE_EVENT:
            _families["compiles"].get().inc()
            _families["compile_seconds"].get().inc(float(duration))
        elif event in (TRACE_EVENT, LOWER_EVENT):
            _families["trace_seconds"].get().inc(float(duration))
    except Exception:  # noqa: BLE001 — never fail a compile from a hook
        pass


def _on_event(event: str, **kw) -> None:
    try:
        if event.startswith(_CACHE_PREFIX):
            _families["cache_events"].inc(
                event=event[len(_CACHE_PREFIX):]
            )
    except Exception:  # noqa: BLE001
        pass


def compile_count() -> int | float:
    """Current process-lifetime compile count (0 before ``install``)."""
    fam = _families.get("compiles")
    return fam.get().value if fam is not None else 0


def compile_seconds() -> float:
    fam = _families.get("compile_seconds")
    return float(fam.get().value) if fam is not None else 0.0


def record_transfer(direction: str, nbytes: int) -> None:
    """Account ``nbytes`` of host↔device traffic (direction 'h2d'/'d2h').
    No-op before ``install`` — call sites stay unconditional."""
    fam = _families.get("transfer_bytes")
    if fam is not None and nbytes:
        fam.inc(int(nbytes), direction=direction)


def _pytree_nbytes(x: Any) -> int:
    import jax

    total = 0
    for leaf in jax.tree.leaves(x):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is not None:
            total += int(nbytes)
    return total


def device_put(x: Any, *args, **kwargs) -> Any:
    """``jax.device_put`` with h2d byte accounting — the staging wrapper
    for call sites that upload params or cohorts."""
    import jax

    record_transfer("h2d", _pytree_nbytes(x))
    return jax.device_put(x, *args, **kwargs)


def device_get(x: Any) -> Any:
    """``jax.device_get`` with d2h byte accounting."""
    import jax

    out = jax.device_get(x)
    record_transfer("d2h", _pytree_nbytes(out))
    return out
