"""Model-quality observability: reference profiles, streaming drift, and
ensemble-agreement monitoring (docs/OBSERVABILITY.md "Model quality").

The serving stack's first two telemetry pillars — system spans (PR 2) and
request traces (PR 3) — can say *how fast* an answer came back, but
nothing about whether the patients being scored still look like the
cohort the ensemble was fit on. For a clinical model behind a 17-variable
contract, silent input drift (a referral-pattern change, an upstream
unit-conversion bug) or a collapsing score distribution is exactly the
failure mode latency SLOs cannot see. This module is the third pillar:

  * **Reference profile** — built at fit time by ``models.pipeline`` over
    the post-impute, post-select ``X[n, 17]`` and the training score
    distribution, and carried *inside* the checkpoint
    (``PipelineParams.quality``, a plain dict-of-arrays pytree the Orbax
    sidecar already knows how to encode), so every served model ships its
    own baseline. ``build_reference_profile`` is numpy-only: this module
    (like the rest of ``obs``) never imports jax.
  * **Streaming accumulators** — ``QualityMonitor.observe_batch`` takes
    each flushed batch's contract rows, blended probabilities, and
    per-member probabilities from the serving engine. Bin indices are
    vectorized *outside* the lock; the lock guards only bounded ring
    writes and snapshot copies (the batcher's flush thread must never
    queue behind drift math). In production serving the engine feeds the
    monitor through ``AsyncQualityFeed`` — a bounded hand-off queue plus
    one background thread — so the hot path pays array copies, not even
    the binning (the synchronous feed measured ~30% of saturated
    throughput in the r11 campaign; sampling/shed under pressure is
    counted in ``quality_feed_dropped_rows_total``).
  * **Drift statistics** — per-feature PSI and (binned) KS distance of
    the recent window vs the reference, score-distribution PSI, a
    calibration-bins snapshot, and mean pairwise member disagreement.
    Exported as ``quality_*`` families through the process-global
    registry (validator-clean) and as the ``/debug/quality`` payload;
    status transitions (``ok``/``warn``/``alert``) are journaled.

**Binning.** Feature histograms use ``DEFAULT_FEATURE_BINS`` equal-width
bins between the training min and max, with out-of-range values clipped
into the edge bins. Equal-width (rather than the decile convention some
PSI write-ups use) keeps every profile array a fixed shape — binary
clinical flags collapse deciles to two distinct edges — and makes the
serving-side bin index one vectorized multiply-clip per batch. Scores bin
on fixed edges over [0, 1].

**PSI thresholds.** The defaults follow the industry convention: PSI
below 0.1 is population noise (``ok``), 0.1–0.25 means the population is
moving and the model's operating point should be reviewed (``warn``),
above 0.25 the served cohort no longer resembles the training cohort and
scores should not be trusted without re-validation (``alert``). For this
model the clinically scary version of the failure is concrete: an EHR
feed that starts reporting wall thickness in different units, or a
referral shift toward sicker patients, silently moves every probability
while every latency dashboard stays green.

**Calibration snapshot semantics.** Serving has no labels, so true
calibration cannot be measured online. The reference profile therefore
stores, per training-score bin, the *training* positive rate; the monitor
reports serving-side count and mean predicted score per bin next to it.
A stable population scored by a calibrated model keeps the serving mass
and mean-score per bin near training; mass migrating across bins is the
score-PSI signal, and a growing gap between mean predicted score and the
training positive rate in heavily-populated bins is the label-free
calibration drift proxy.

Low-count honesty: below ``min_rows`` window rows (default 200 — with 10
bins, sampling noise alone sits near E[PSI] ≈ (B−1)/n ≈ 0.045 at n=200,
safely under the 0.1 warn line; judging at a few dozen rows was measured
to flap ok→alert→ok on pure startup noise), every drift statistic is
``None`` in JSON payloads (never NaN — the PR 1 strict-JSON convention)
and ``NaN`` on the Prometheus gauges (the idiomatic "no data" sample
value, legal for gauges under the strict validator).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Sequence

import numpy as np

from machine_learning_replications_tpu.obs import journal
from machine_learning_replications_tpu.obs.registry import (
    REGISTRY,
    MetricsRegistry,
)

# Registered at import (rule metrics-catalog): the first scrape of a
# serving process sees the feed families' metadata before any feed
# exists; the registry is idempotent across re-declares.
QUALITY_FEED_DROPPED = REGISTRY.counter(
    "quality_feed_dropped_rows_total",
    "Rows that never reached the quality monitor, by reason: "
    "sampled = thinned under queue pressure, overflow = shed at "
    "a full hand-off queue, dead = feed quarantined.",
    labels=("reason",),
)
for _reason in ("sampled", "overflow", "dead"):
    QUALITY_FEED_DROPPED.labels(reason=_reason)
QUALITY_FEED_DEPTH = REGISTRY.gauge(
    "quality_feed_depth",
    "Batches waiting in the async quality hand-off queue.",
)

PROFILE_VERSION = 1
DEFAULT_FEATURE_BINS = 10
DEFAULT_SCORE_BINS = 10
#: Quantile levels stored per feature (diagnostics for /debug/quality and
#: obs_report; the drift statistics themselves run on the histograms).
PROFILE_QUANTILES = (0.01, 0.05, 0.25, 0.5, 0.75, 0.95, 0.99)

#: Industry-convention PSI thresholds (module docstring has the rationale).
DEFAULT_WARN_PSI = 0.1
DEFAULT_ALERT_PSI = 0.25

_STATUS_LEVEL = {"ok": 0, "warn": 1, "alert": 2}

#: Status transitions remembered per monitor (the ``transitions`` ring on
#: ``/debug/quality``): enough for a trigger daemon to debounce a
#: sustained alert from ONE poll instead of re-reading the journal, small
#: enough that the payload stays a snapshot, not a log.
TRANSITION_HISTORY = 32


# ---------------------------------------------------------------------------
# Reference profile
# ---------------------------------------------------------------------------


def build_reference_profile(
    X: np.ndarray,
    scores: np.ndarray,
    y: np.ndarray | None = None,
    feature_bins: int = DEFAULT_FEATURE_BINS,
    score_bins: int = DEFAULT_SCORE_BINS,
) -> dict[str, np.ndarray]:
    """The training-time baseline a served model carries: per-feature
    equal-width histograms + moments + quantiles over ``X[n, F]`` (the
    post-impute, post-select ensemble input), the training score
    histogram over fixed [0, 1] bins, and — when training labels ``y``
    are given — the per-score-bin positive rate (the calibration
    reference; NaN-filled without labels).

    Returns a plain ``{str: np.ndarray}`` pytree (scalars as 0-d arrays)
    so the profile rides any checkpoint path that can carry a dict of
    arrays — ``persist.orbax_io``'s sidecar encodes it as a ``mapping``
    node with no new registry class.
    """
    X = np.asarray(X, np.float64)
    if X.ndim != 2 or X.shape[0] < 1:
        raise ValueError(f"profile needs a non-empty [n, F] matrix, got {X.shape}")
    if not np.isfinite(X).all():
        raise ValueError(
            "profile input must be post-impute (finite); found NaN/Inf"
        )
    scores = np.asarray(scores, np.float64).ravel()
    if scores.shape[0] != X.shape[0]:
        raise ValueError(
            f"scores length {scores.shape[0]} != rows {X.shape[0]}"
        )
    n, F = X.shape
    B, S = int(feature_bins), int(score_bins)
    if B < 2 or S < 2:
        raise ValueError("feature_bins and score_bins must be >= 2")

    mins = X.min(axis=0)
    maxs = X.max(axis=0)
    # Degenerate (constant) columns get a unit-width span so the bin
    # arithmetic stays finite; all mass lands in bin 0 on both sides and
    # the feature contributes PSI 0 until it actually moves.
    widths = np.where(maxs > mins, maxs - mins, 1.0)
    edges = mins[:, None] + widths[:, None] * (
        np.arange(B + 1, dtype=np.float64)[None, :] / B
    )
    counts = np.stack(
        [np.bincount(c, minlength=B) for c in _feature_bin_indices(X, mins, widths, B).T]
    ).astype(np.float64)

    q = np.asarray(PROFILE_QUANTILES, np.float64)
    score_edges = np.linspace(0.0, 1.0, S + 1)
    s_idx = _score_bin_indices(scores, S)
    score_counts = np.bincount(s_idx, minlength=S).astype(np.float64)
    calib_pos_rate = np.full(S, np.nan)
    calib_mean_score = np.full(S, np.nan)
    for b in range(S):
        m = s_idx == b
        if m.any():
            calib_mean_score[b] = float(scores[m].mean())
            if y is not None:
                calib_pos_rate[b] = float(np.asarray(y, np.float64)[m].mean())

    return {
        "version": np.asarray(PROFILE_VERSION, np.int64),
        "n_rows": np.asarray(n, np.int64),
        "bin_edges": edges,                      # [F, B+1]
        "bin_counts": counts,                    # [F, B]
        "mean": X.mean(axis=0),
        "std": X.std(axis=0),
        "minimum": mins,
        "maximum": maxs,
        "quantile_levels": q,
        "quantiles": np.quantile(X, q, axis=0).T,  # [F, Q]
        "score_edges": score_edges,              # [S+1]
        "score_counts": score_counts,            # [S]
        "calib_mean_score": calib_mean_score,    # [S] training mean score/bin
        "calib_pos_rate": calib_pos_rate,        # [S] training pos rate/bin
    }


def _feature_bin_indices(
    X: np.ndarray, mins: np.ndarray, widths: np.ndarray, n_bins: int
) -> np.ndarray:
    """Equal-width bin index per value, out-of-range clipped into the edge
    bins — one vectorized multiply/clip, the whole per-batch binning cost."""
    idx = np.floor((X - mins[None, :]) / widths[None, :] * n_bins)
    return np.clip(idx, 0, n_bins - 1).astype(np.int16)


def profile_bin_geometry(prof: dict) -> tuple[np.ndarray, np.ndarray]:
    """``(mins, widths)`` from a host profile's ``bin_edges``, degenerate
    (zero-width) features floored to 1.0. ONE implementation on purpose —
    the monitor's constructor, ``rebase``, and the shadow comparator's
    ``cohort_quality`` (``learn.shadow``) must bin with identical
    geometry, or the live monitor and the shadow gate would judge the
    same rows differently."""
    mins = prof["bin_edges"][:, 0]
    widths = prof["bin_edges"][:, -1] - mins
    return mins, np.where(widths > 0, widths, 1.0)


def pairwise_disagreement(members: np.ndarray) -> np.ndarray:
    """Per-row mean pairwise ``|p_i − p_j|`` over ensemble members
    (``members[n, m]``) — the ensemble-agreement statistic. ONE
    implementation on purpose: the serving monitor's window feed and the
    shadow comparator (``learn.shadow``) must judge with identical
    semantics, or a shadow verdict's disagreement delta would disagree
    with the live monitor on the same inputs. ``m < 2`` yields zeros
    (no pairs to disagree)."""
    members = np.asarray(members, np.float64)
    n, m = members.shape
    pair_sum = np.zeros(n)
    for i in range(m):
        for j in range(i + 1, m):
            pair_sum += np.abs(members[:, i] - members[:, j])
    return pair_sum / max(m * (m - 1) / 2, 1)


def _score_bin_indices(scores: np.ndarray, n_bins: int) -> np.ndarray:
    idx = np.floor(np.asarray(scores, np.float64) * n_bins)
    return np.clip(idx, 0, n_bins - 1).astype(np.int16)


def _as_host_profile(profile: Any) -> dict[str, np.ndarray]:
    """Coerce a restored profile pytree (possibly jax arrays fresh off a
    checkpoint) to host numpy and sanity-check the keys this module needs."""
    if not isinstance(profile, dict):
        raise TypeError(
            f"quality profile must be a dict pytree, got {type(profile).__name__}"
        )
    prof = {k: np.asarray(v) for k, v in profile.items()}
    needed = ("bin_edges", "bin_counts", "score_edges", "score_counts", "n_rows")
    missing = [k for k in needed if k not in prof]
    if missing:
        raise ValueError(f"quality profile missing keys: {missing}")
    version = int(prof.get("version", 1))
    if version > PROFILE_VERSION:
        raise ValueError(
            f"quality profile version {version} is newer than this build "
            f"supports ({PROFILE_VERSION})"
        )
    return prof


# ---------------------------------------------------------------------------
# Drift statistics
# ---------------------------------------------------------------------------


def psi(
    expected_counts: Sequence[float],
    actual_counts: Sequence[float],
    eps: float = 1e-4,
) -> float:
    """Population Stability Index between two histograms on shared bins:
    ``sum((p_a − p_e) · ln(p_a / p_e))``. Proportions are floored at
    ``eps`` (the standard zero-bin smoothing) so an empty bin on either
    side contributes a large-but-finite term instead of ±inf."""
    e = np.asarray(expected_counts, np.float64)
    a = np.asarray(actual_counts, np.float64)
    if e.shape != a.shape or e.ndim != 1:
        raise ValueError(f"histogram shapes differ: {e.shape} vs {a.shape}")
    if e.sum() <= 0 or a.sum() <= 0:
        raise ValueError("psi needs non-empty histograms on both sides")
    p_e = np.maximum(e / e.sum(), eps)
    p_a = np.maximum(a / a.sum(), eps)
    return float(np.sum((p_a - p_e) * np.log(p_a / p_e)))


def ks_binned(
    expected_counts: Sequence[float], actual_counts: Sequence[float]
) -> float:
    """Kolmogorov–Smirnov distance between two *binned* distributions:
    the max |CDF difference| evaluated at the shared bin edges. A lower
    bound on the exact sample KS (within-bin detail is quantized away),
    which is the right trade for a streaming monitor that stores counts,
    not rows."""
    e = np.asarray(expected_counts, np.float64)
    a = np.asarray(actual_counts, np.float64)
    if e.shape != a.shape or e.ndim != 1:
        raise ValueError(f"histogram shapes differ: {e.shape} vs {a.shape}")
    if e.sum() <= 0 or a.sum() <= 0:
        raise ValueError("ks needs non-empty histograms on both sides")
    return float(
        np.abs(np.cumsum(e) / e.sum() - np.cumsum(a) / a.sum()).max()
    )


def _psi_rows(
    expected: np.ndarray, actual: np.ndarray, eps: float = 1e-4
) -> np.ndarray:
    """Row-wise ``psi``: one PSI per feature over ``[F, B]`` histogram
    matrices, vectorized (same smoothing and math as the scalar
    function, which stays the spec and the test oracle)."""
    e = np.asarray(expected, np.float64)
    a = np.asarray(actual, np.float64)
    p_e = np.maximum(e / e.sum(axis=1, keepdims=True), eps)
    p_a = np.maximum(a / a.sum(axis=1, keepdims=True), eps)
    return np.sum((p_a - p_e) * np.log(p_a / p_e), axis=1)


def _ks_rows(expected: np.ndarray, actual: np.ndarray) -> np.ndarray:
    """Row-wise ``ks_binned`` over ``[F, B]`` histogram matrices."""
    e = np.asarray(expected, np.float64)
    a = np.asarray(actual, np.float64)
    return np.abs(
        np.cumsum(e, axis=1) / e.sum(axis=1, keepdims=True)
        - np.cumsum(a, axis=1) / a.sum(axis=1, keepdims=True)
    ).max(axis=1)


def _round(v: float | None, nd: int = 6) -> float | None:
    return None if v is None else round(float(v), nd)


def _null_if_nan(v: float) -> float | None:
    return None if v != v else float(v)


# ---------------------------------------------------------------------------
# Streaming monitor
# ---------------------------------------------------------------------------


class QualityMonitor:
    """Sliding-window drift monitor the serving engine feeds per flush.

    State is three bounded rings over the last ``window`` *real* (unpadded)
    rows: per-feature bin indices (``[window, F]`` int16), score bin index
    + raw score, and per-row mean pairwise member disagreement. Rings make
    the windowed histograms exact (no decay-factor tuning), bound memory
    explicitly (~40 bytes/row at F=17), and keep ``observe_batch`` to one
    vectorized binning pass outside the lock plus ring writes inside it —
    the same bounded-over-unbounded discipline as the admission queue.

    Drift statistics refresh at most once per ``refresh_rows`` observed
    rows AND at most once per ``refresh_interval_s`` wall seconds (and
    always on ``snapshot()``): gauges, status, and the journaled
    ``quality_status`` transition event all come from the refresh path,
    so a high-qps flush loop pays ring writes, not PSI math, per batch.
    The time floor is the r12 fix for the r11-measured ~30% saturated-
    throughput tax: at 1000 qps with 64-row flushes a rows-only policy
    re-ran the whole windowed PSI/KS pass on every single flush, burning
    real CPU for statistics that cannot meaningfully move inside a
    second — drift is a minutes-scale signal.
    """

    def __init__(
        self,
        profile: Any,
        warn_psi: float = DEFAULT_WARN_PSI,
        alert_psi: float = DEFAULT_ALERT_PSI,
        window: int = 2048,
        min_rows: int = 200,
        refresh_rows: int = 32,
        refresh_interval_s: float = 1.0,
        feature_names: Sequence[str] | None = None,
        registry: MetricsRegistry | None = None,
    ) -> None:
        self._profile = _as_host_profile(profile)
        F, B = self._profile["bin_counts"].shape
        self._F, self._B = F, B
        self._S = int(self._profile["score_counts"].shape[0])
        if not 0 < warn_psi <= alert_psi:
            raise ValueError(
                f"need 0 < warn_psi <= alert_psi, got {warn_psi} / {alert_psi}"
            )
        if window < 1 or min_rows < 1 or refresh_rows < 1:
            raise ValueError("window, min_rows, refresh_rows must be >= 1")
        if refresh_interval_s < 0:
            raise ValueError("refresh_interval_s must be >= 0")
        if window < min_rows:
            # A window that can never reach min_rows would pin every drift
            # statistic at "not enough data" forever — monitoring silently
            # off while /healthz keeps saying ok. Refuse at construction.
            raise ValueError(
                f"window ({window}) must be >= min_rows ({min_rows}), or "
                "the drift statistics can never be computed"
            )
        self.warn_psi = float(warn_psi)
        self.alert_psi = float(alert_psi)
        self.window = int(window)
        self.min_rows = int(min_rows)
        self.refresh_rows = int(refresh_rows)
        self.refresh_interval_s = float(refresh_interval_s)
        # −inf: the first due batch always refreshes, whatever the floor
        # (monotonic's epoch is arbitrary — a small absolute value could
        # sit inside a large interval on a freshly booted host).
        self._last_refresh_t = float("-inf")
        if feature_names is None:
            from machine_learning_replications_tpu.data.schema import SELECTED_17

            feature_names = (
                SELECTED_17 if len(SELECTED_17) == F
                else tuple(f"f{i}" for i in range(F))
            )
        if len(feature_names) != F:
            raise ValueError(
                f"{len(feature_names)} feature names for {F} features"
            )
        self.feature_names = tuple(str(n) for n in feature_names)
        self._mins, self._widths = profile_bin_geometry(self._profile)

        self._lock = threading.Lock()
        # Serializes whole refresh passes (copy → compute → commit): the
        # batcher flush thread and /debug/quality handler threads both
        # refresh, and unserialized passes could commit a STALE window's
        # statistics over a fresher one — overwriting real drift gauges
        # and journaling phantom recovery transitions.
        self._refresh_lock = threading.Lock()
        self._feat_ring = np.zeros((self.window, F), np.int16)
        self._score_ring = np.zeros(self.window, np.int16)
        self._score_val_ring = np.zeros(self.window, np.float64)
        self._dis_ring = np.full(self.window, np.nan)
        self._rows = 0        # ring-write cursor (truncated-batch rows)
        self._rows_total = 0  # every real row ever observed
        self._last_refresh_rows = 0
        self._status = "ok"
        # Profile generation: bumped by rebase(). Bin indices are
        # computed outside the lock against a snapshot of the profile's
        # edges; a batch whose generation is stale by ring-write time was
        # binned under a superseded profile and must be dropped, not
        # written into the fresh window.
        self._epoch = 0
        # Bounded status-transition history (newest last): what the
        # continual-learning trigger daemon debounces on — K consecutive
        # alert polls are cheap to judge when the recent arc rides the
        # snapshot itself.
        self._transitions: collections.deque = collections.deque(
            maxlen=TRANSITION_HISTORY
        )
        self._disabled_reason: str | None = None  # set by disable()
        # Last refresh's derived statistics (NaN = not computable yet).
        self._feature_psi = np.full(F, np.nan)
        self._feature_ks = np.full(F, np.nan)
        self._score_psi = float("nan")
        self._disagreement = float("nan")

        reg = registry or REGISTRY
        self._g_feature_psi = reg.gauge(
            "quality_feature_psi",
            "Windowed PSI of the feature vs its training reference "
            "histogram (NaN until min_rows).",
            labels=("feature",),
        )
        self._g_feature_ks = reg.gauge(
            "quality_feature_ks",
            "Windowed binned KS distance of the feature vs its training "
            "reference (NaN until min_rows).",
            labels=("feature",),
        )
        self._g_score_psi = reg.gauge(
            "quality_score_psi",
            "Windowed PSI of the predicted-probability distribution vs "
            "the training score distribution (NaN until min_rows).",
        )
        self._g_disagreement = reg.gauge(
            "quality_member_disagreement",
            "Windowed mean pairwise |p_i - p_j| across ensemble members "
            "(NaN until min_rows or without member outputs).",
        )
        self._g_window = reg.gauge(
            "quality_window_rows", "Real rows in the sliding drift window."
        )
        self._g_status = reg.gauge(
            "quality_status",
            "Drift status: 0 = ok, 1 = warn, 2 = alert (worst PSI vs the "
            "configured thresholds).",
        )
        self._c_rows = reg.counter(
            "quality_rows_total", "Real (unpadded) rows observed by the "
            "quality monitor."
        )
        self._c_transitions = reg.counter(
            "quality_status_transitions_total",
            "Drift status transitions, labeled by the state entered.",
            labels=("to",),
        )
        # Materialize every series now: a scrape taken before traffic (or
        # before min_rows) must show the families, with NaN marking
        # "no data yet" on the drift gauges (legal for gauges; the JSON
        # payloads render these as null).
        for name in self.feature_names:
            self._g_feature_psi.set(float("nan"), feature=name)
            self._g_feature_ks.set(float("nan"), feature=name)
        self._g_score_psi.get().set(float("nan"))
        self._g_disagreement.get().set(float("nan"))
        self._g_window.get().set(0.0)
        self._g_status.get().set(0.0)
        self._c_rows.get()
        for s in ("ok", "warn", "alert"):
            self._c_transitions.labels(to=s)

    # -- ingest -------------------------------------------------------------

    def observe_batch(
        self,
        X: np.ndarray,
        p1: np.ndarray,
        members: np.ndarray | None = None,
    ) -> None:
        """Feed one flushed batch of real rows: ``X[n, F]`` contract-space
        rows (post-impute/post-select for the pipeline route), ``p1[n]``
        blended probabilities, ``members[n, M]`` per-member probabilities
        (None when the served family has no members, e.g. a bare GBDT).
        Binning is vectorized out of the lock; the lock covers only the
        ring writes."""
        X = np.asarray(X, np.float64)
        p1 = np.asarray(p1, np.float64).ravel()
        n = X.shape[0]
        if n == 0:
            return
        if X.ndim != 2 or X.shape[1] != self._F or p1.shape[0] != n:
            raise ValueError(
                f"observe_batch shapes: X {X.shape}, p1 {p1.shape}, "
                f"expected [n, {self._F}] / [n]"
            )
        if not np.isfinite(X).all():
            # The monitored space is post-impute (finite) by contract; a
            # NaN here would turn into a garbage int16 bin index. Raise
            # loudly instead — the engine quarantines a failing feed.
            raise ValueError("observe_batch rows must be finite")
        with self._lock:
            # Snapshot the profile's edges + generation: a concurrent
            # rebase() between this binning pass and the ring write below
            # would otherwise land OLD-edge indices in the fresh window
            # (garbage histograms under the new profile's bin_counts).
            epoch = self._epoch
            mins, widths, B, S = self._mins, self._widths, self._B, self._S
        fidx = _feature_bin_indices(X, mins, widths, B)
        sidx = _score_bin_indices(p1, S)
        if members is not None:
            dis = pairwise_disagreement(members)
        else:
            dis = np.full(n, np.nan)
        n_observed = n  # the true row count — rows_total must not shrink
        # when an oversize batch is truncated to the window below
        if n > self.window:  # only the newest window rows can survive anyway
            p1 = p1[-self.window:]
            fidx, sidx, dis = (
                fidx[-self.window:], sidx[-self.window:], dis[-self.window:]
            )
            n = self.window
        with self._lock:
            if self._epoch != epoch:
                # Rebased mid-batch: these indices were binned under the
                # superseded profile's edges. Dropping the batch is
                # correct — the cleared window must hold only rows judged
                # against the new baseline.
                return
            start = self._rows % self.window
            take = min(n, self.window - start)
            self._feat_ring[start:start + take] = fidx[:take]
            self._score_ring[start:start + take] = sidx[:take]
            self._score_val_ring[start:start + take] = p1[:take]
            self._dis_ring[start:start + take] = dis[:take]
            if take < n:  # wrap
                rest = n - take
                self._feat_ring[:rest] = fidx[take:]
                self._score_ring[:rest] = sidx[take:]
                self._score_val_ring[:rest] = p1[take:]
                self._dis_ring[:rest] = dis[take:]
            self._rows += n
            self._rows_total += n_observed
            # Both throttles must agree: enough new rows to matter AND
            # the wall-clock floor elapsed (the saturated-flush-loop
            # guard — see the class docstring). snapshot() bypasses both.
            due = (
                self._rows - self._last_refresh_rows >= self.refresh_rows
                and time.monotonic() - self._last_refresh_t
                >= self.refresh_interval_s
            )
        self._c_rows.inc(n_observed)
        self._g_window.get().set(float(min(self._rows, self.window)))
        if due:
            self._refresh()

    # -- derive -------------------------------------------------------------

    def _window_copy(self):
        with self._lock:
            n = min(self._rows, self.window)
            return (
                n,
                self._feat_ring[:n].copy(),
                self._score_ring[:n].copy(),
                self._score_val_ring[:n].copy(),
                self._dis_ring[:n].copy(),
            )

    def _refresh(self) -> None:
        """Recompute drift statistics from the current window, update the
        gauges, and journal a ``quality_status`` event when the status
        crosses a threshold in either direction. Whole passes are
        serialized (``_refresh_lock``) so a slower thread can never commit
        a stale window's statistics over a fresher thread's."""
        with self._refresh_lock:
            self._refresh_locked()

    def _refresh_locked(self) -> None:
        n, fidx, sidx, _svals, dis = self._window_copy()
        with self._lock:
            self._last_refresh_rows = self._rows
            self._last_refresh_t = time.monotonic()
        if n < self.min_rows:
            return  # stats stay NaN/None until the window is meaningful
        ref_fc = self._profile["bin_counts"]
        # One flat bincount for all F feature histograms (feature f's
        # bins occupy [f·B, (f+1)·B)) and fully vectorized PSI/KS across
        # features: the per-feature python loop this replaces measured
        # ~1 ms per refresh at F=17/window=2048 — the dominant term of
        # the r11 quality throughput tax.
        flat = (
            np.arange(self._F, dtype=np.int64) * self._B
        )[None, :] + fidx
        counts = np.bincount(
            flat.ravel(), minlength=self._F * self._B
        ).reshape(self._F, self._B).astype(np.float64)
        f_psi = _psi_rows(ref_fc, counts)
        f_ks = _ks_rows(ref_fc, counts)
        s_counts = np.bincount(sidx, minlength=self._S)
        s_psi = psi(self._profile["score_counts"], s_counts)
        have_dis = np.isfinite(dis)
        disagreement = float(dis[have_dis].mean()) if have_dis.any() else float("nan")

        worst_psi = max(float(f_psi.max()), s_psi)
        new_status = (
            "alert" if worst_psi >= self.alert_psi
            else "warn" if worst_psi >= self.warn_psi
            else "ok"
        )
        with self._lock:
            self._feature_psi = f_psi
            self._feature_ks = f_ks
            self._score_psi = s_psi
            self._disagreement = disagreement
            old_status, self._status = self._status, new_status
        for f, name in enumerate(self.feature_names):
            self._g_feature_psi.set(float(f_psi[f]), feature=name)
            self._g_feature_ks.set(float(f_ks[f]), feature=name)
        self._g_score_psi.get().set(s_psi)
        self._g_disagreement.get().set(disagreement)
        self._g_status.get().set(float(_STATUS_LEVEL[new_status]))
        if new_status != old_status:
            worst_f, worst_f_psi = self._worst(f_psi, s_psi)
            self._c_transitions.inc(to=new_status)
            record = {
                "ts": journal.utc_now_iso(),
                "from_status": old_status,
                "to_status": new_status,
                "worst_feature": worst_f,
                "worst_psi": _round(worst_f_psi),
                "score_psi": _round(s_psi),
                "window_rows": n,
            }
            with self._lock:
                self._transitions.append(record)
            journal.event(
                "quality_status",
                from_status=old_status,
                to_status=new_status,
                worst_feature=worst_f,
                worst_psi=_round(worst_f_psi),
                score_psi=_round(s_psi),
                window_rows=n,
            )

    def _worst_feature(self, f_psi: np.ndarray) -> tuple[str | None, float | None]:
        if not np.isfinite(f_psi).any():
            return None, None
        i = int(np.nanargmax(f_psi))
        return self.feature_names[i], float(f_psi[i])

    def _worst(
        self, f_psi: np.ndarray, s_psi: float
    ) -> tuple[str | None, float | None]:
        """Worst offender across features AND the score distribution (the
        latter named by a ``__score__`` sentinel no contract variable can
        collide with)."""
        worst_f, worst_psi = self._worst_feature(f_psi)
        if s_psi == s_psi and (worst_psi is None or s_psi > worst_psi):
            return "__score__", float(s_psi)
        return worst_f, worst_psi

    def disable(self, reason: str) -> None:
        """Mark the monitor dead (the engine quarantines a feed whose
        ``observe_batch`` raised). A quarantined monitor must SAY so on
        every surface — frozen statistics presented as live 'ok' are the
        exact silent-monitoring-gap this module exists to close."""
        with self._lock:
            self._disabled_reason = reason
        self._g_status.get().set(float("nan"))

    def reenable(self) -> bool:
        """Clear a quarantine (``resilience.supervisor`` calls this after a
        successful engine restart rebuilds the feed): the monitor resumes
        with its windows intact and the status gauge restored. True when a
        quarantine was actually cleared — the caller journals the
        transition (``quality_feed_reenabled``) only then."""
        with self._lock:
            was_disabled = self._disabled_reason is not None
            self._disabled_reason = None
            status = self._status
        if was_disabled:
            self._g_status.get().set(float(_STATUS_LEVEL[status]))
        return was_disabled

    def rebase(self, profile: Any) -> None:
        """Adopt a NEW reference profile in place — the continual-learning
        promotion path (``serve.server.deploy_model``): a retrained
        candidate fit on the *current* cohort carries its own training
        reference, and after the warm swap the monitor must judge traffic
        against THAT baseline, not the superseded model's. Keeping the
        monitor object (rather than constructing a fresh one) keeps the
        process-global gauge families and the transition counters — the
        promotion shows up as a journaled ``alert → ok`` transition on the
        same series, which is the whole closed-loop story.

        The window rings are cleared (rows were binned under the OLD
        profile's edges — re-judging them against new edges would be
        statistics over garbage indices), and the drift statistics reset
        to not-computable until ``min_rows`` fresh rows arrive. The status
        is deliberately NOT reset: the recovery to ``ok`` must be earned
        by post-swap traffic and journaled as a real transition, never
        declared by the swap itself.

        The new profile must describe the same feature space (same F —
        the gauge label set is fixed at construction); bin counts may
        differ. Raises ``ValueError`` on a mismatched profile, leaving
        the monitor untouched.
        """
        prof = _as_host_profile(profile)
        F, B = prof["bin_counts"].shape
        if F != self._F:
            raise ValueError(
                f"rebase profile is {F} features wide, monitor is {self._F}"
            )
        with self._refresh_lock, self._lock:
            self._epoch += 1  # invalidates in-flight old-edge binnings
            self._profile = prof
            self._B = int(B)
            self._S = int(prof["score_counts"].shape[0])
            self._mins, self._widths = profile_bin_geometry(prof)
            self._feat_ring[:] = 0
            self._score_ring[:] = 0
            self._score_val_ring[:] = 0.0
            self._dis_ring[:] = np.nan
            self._rows = 0
            self._last_refresh_rows = 0
            self._last_refresh_t = float("-inf")
            self._feature_psi = np.full(self._F, np.nan)
            self._feature_ks = np.full(self._F, np.nan)
            self._score_psi = float("nan")
            self._disagreement = float("nan")
        for name in self.feature_names:
            self._g_feature_psi.set(float("nan"), feature=name)
            self._g_feature_ks.set(float("nan"), feature=name)
        self._g_score_psi.get().set(float("nan"))
        self._g_disagreement.get().set(float("nan"))
        self._g_window.get().set(0.0)
        journal.event(
            "quality_rebased",
            reference_rows=int(prof["n_rows"]),
            feature_bins=int(B),
        )

    # -- export -------------------------------------------------------------

    @property
    def n_features(self) -> int:
        """Width of the monitored row space (the reference profile's F) —
        callers validate it against what they will actually feed."""
        return self._F

    @property
    def status(self) -> str:
        with self._lock:
            return self._status

    def health(self) -> dict:
        """The compact ``/healthz`` block: status + the single worst
        offender, so an orchestrator can act on drift without scraping the
        full ``/debug/quality`` payload."""
        with self._lock:
            if self._disabled_reason is not None:
                return {"status": "disabled", "reason": self._disabled_reason}
            status = self._status
            f_psi = self._feature_psi
            s_psi = self._score_psi
        worst_f, worst_psi = self._worst(f_psi, s_psi)
        return {
            "status": status,
            "worst_feature": worst_f,
            "worst_psi": _round(worst_psi),
        }

    def snapshot(self, detail: bool = False) -> dict:
        """The ``/debug/quality`` payload. Always strict-JSON-safe: every
        not-yet-computable statistic is ``None``, never NaN."""
        with self._lock:
            disabled = self._disabled_reason
        if disabled is not None:
            return disabled_snapshot(disabled)
        self._refresh()
        n, fidx, sidx, svals, dis = self._window_copy()
        with self._lock:
            status = self._status
            f_psi = self._feature_psi.copy()
            f_ks = self._feature_ks.copy()
            s_psi = self._score_psi
            disagreement = self._disagreement
            rows_total = self._rows_total
            transitions = [dict(t) for t in self._transitions]
        worst_f, worst_psi = self._worst(f_psi, s_psi)
        out = {
            "enabled": True,
            "status": status,
            "rows_total": rows_total,
            "window_rows": n,
            "min_rows": self.min_rows,
            "thresholds": {
                "warn_psi": self.warn_psi, "alert_psi": self.alert_psi,
            },
            "score_psi": _round(_null_if_nan(s_psi)),
            "member_disagreement": _round(_null_if_nan(disagreement)),
            "worst_feature": worst_f,
            "worst_psi": _round(worst_psi),
            # The bounded recent-transition ring (newest last): the
            # continual-learning trigger debounces from this one payload
            # instead of tailing the journal (docs/CONTINUAL.md).
            "transitions": transitions,
            "reference": {
                "n_rows": int(self._profile["n_rows"]),
                "feature_bins": self._B,
                "score_bins": self._S,
                "version": int(self._profile.get("version", 1)),
            },
        }
        if not detail:
            return out
        ref_mean = self._profile.get("mean")
        features = []
        for f, name in enumerate(self.feature_names):
            counts = np.bincount(fidx[:, f], minlength=self._B) if n else None
            w_mean = None
            if n:
                # Window mean reconstructed from bin midpoints (the monitor
                # stores indices, not values) — a diagnostic, not a statistic.
                mids = 0.5 * (
                    self._profile["bin_edges"][f, :-1]
                    + self._profile["bin_edges"][f, 1:]
                )
                w_mean = float((mids * counts).sum() / counts.sum())
            features.append({
                "name": name,
                "psi": _round(_null_if_nan(float(f_psi[f]))),
                "ks": _round(_null_if_nan(float(f_ks[f]))),
                "window_mean_binned": _round(w_mean),
                "reference_mean": (
                    _round(float(ref_mean[f])) if ref_mean is not None else None
                ),
            })
        features.sort(key=lambda d: -1.0 if d["psi"] is None else d["psi"],
                      reverse=True)
        calib_count = np.bincount(sidx, minlength=self._S) if n else np.zeros(
            self._S, np.int64
        )
        calib_mean = []
        for b in range(self._S):
            m = sidx == b if n else np.zeros(0, bool)
            calib_mean.append(
                _round(float(svals[m].mean())) if n and m.any() else None
            )
        out["features"] = features
        out["calibration"] = {
            "edges": [round(float(e), 6) for e in self._profile["score_edges"]],
            "count": [int(c) for c in calib_count],
            "mean_score": calib_mean,
            "reference_pos_rate": [
                _round(_null_if_nan(float(v)))
                for v in self._profile.get(
                    "calib_pos_rate", np.full(self._S, np.nan)
                )
            ],
            "reference_count": [
                int(c) for c in self._profile["score_counts"]
            ],
        }
        return out


def disabled_snapshot(reason: str) -> dict:
    """The ``/debug/quality`` payload when no monitor is running."""
    return {"enabled": False, "status": "disabled", "reason": reason}


# ---------------------------------------------------------------------------
# Asynchronous hand-off feed
# ---------------------------------------------------------------------------


class AsyncQualityFeed:
    """Bounded hand-off queue between the serving hot path and the
    monitor, serviced by one background daemon thread.

    The r11 bench campaign measured the synchronous feed at ~30% of
    saturated serving throughput: every flush paid binning + ring writes
    + (every ``refresh_rows``) the whole PSI/KS pass *inside the flush
    thread*. This class moves all of that off the hot path:
    ``observe_batch`` now costs three array copies and a deque append —
    the monitor's math runs on the feed thread.

    Backpressure is sampling, then shedding, always counted: while the
    queue sits at or above half of ``capacity`` incoming batches are
    row-sampled (every ``sample_stride``-th row — drift statistics are
    distribution estimates, and an unbiased row subsample keeps them
    honest while cutting the backlog); at full ``capacity`` the batch is
    dropped whole. Both land in
    ``quality_feed_dropped_rows_total{reason=sampled|overflow}`` and in
    per-feed ``stats()``, so a pressured feed is visible, never silent.

    A monitor that raises on the feed thread (mis-sized profile, NaN
    rows) quarantines exactly like the old in-engine path did: one
    journaled ``quality_feed_disabled``, ``monitor.disable(...)`` so
    every surface says so, and the feed goes dead (drops counted) until
    ``reenable`` — which the supervisor calls after a successful engine
    restart, exactly as before.
    """

    def __init__(
        self,
        monitor: "QualityMonitor",
        capacity: int = 64,
        sample_stride: int = 4,
    ) -> None:
        if capacity < 2 or sample_stride < 2:
            raise ValueError("need capacity >= 2 and sample_stride >= 2")
        self.monitor = monitor
        self.capacity = int(capacity)
        self.sample_stride = int(sample_stride)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._q: list[tuple] = []
        self._dead = False
        self._closed = False
        self._busy = False  # feed thread mid-observe (drain() waits on it)
        self._dropped_rows = 0
        self._sampled_out_rows = 0
        self._observed_rows = 0
        self._c_dropped = QUALITY_FEED_DROPPED
        self._g_depth = QUALITY_FEED_DEPTH
        self._g_depth.get().set(0.0)
        self._thread = threading.Thread(
            target=self._loop, name="quality-feed", daemon=True
        )
        self._thread.start()

    # -- hot path ----------------------------------------------------------

    def observe_batch(self, X, p1, members=None) -> None:
        """Hand one batch off to the feed thread. Never raises on the hot
        path (monitor failures surface on the feed thread and quarantine
        there); array arguments are copied so the caller's buffers are
        free the moment this returns — but only for batches that are
        actually enqueued: the dead/overflow drop paths are copy-free
        (under sustained overload, exactly when the shed path runs
        hottest, a dropped batch must not cost three array copies)."""
        n = int(np.shape(X)[0]) if np.ndim(X) == 2 else 0
        drop_reason = self._drop_reason(n)
        if drop_reason is None:
            sample = None
            with self._lock:
                if len(self._q) >= self.capacity // 2 \
                        and n > self.sample_stride:
                    sample = slice(None, None, self.sample_stride)
            X = np.array(X, np.float64, copy=True)[sample or slice(None)]
            p1 = np.array(p1, np.float64, copy=True).ravel()[
                sample or slice(None)
            ]
            if members is not None:
                members = np.array(members, np.float64, copy=True)[
                    sample or slice(None)
                ]
            if sample is not None:
                kept = X.shape[0]
                with self._lock:
                    self._sampled_out_rows += n - kept
                self._c_dropped.inc(n - kept, reason="sampled")
            with self._lock:
                # Re-check under the lock: the queue may have filled (or
                # the feed died) between the cheap pre-check and the
                # copies.
                if self._dead or self._closed:
                    drop_reason = "dead"
                elif len(self._q) >= self.capacity:
                    drop_reason = "overflow"
                else:
                    self._q.append((X, p1, members))
                    self._g_depth.get().set(float(len(self._q)))
                    self._cv.notify()
                if drop_reason is not None:
                    self._dropped_rows += X.shape[0]
                    n = X.shape[0]  # sampled-out rows already accounted
        if drop_reason is not None:
            self._c_dropped.inc(n, reason=drop_reason)

    def _drop_reason(self, n: int) -> str | None:
        """Cheap pre-copy shed check; accounts the drop when it says so."""
        with self._lock:
            if self._dead or self._closed:
                self._dropped_rows += n
                return "dead"
            if len(self._q) >= self.capacity:
                self._dropped_rows += n
                return "overflow"
        return None

    # -- feed thread -------------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._lock:
                while not self._q and not self._closed:
                    self._cv.wait()
                if not self._q:
                    return  # closed and drained
                X, p1, members = self._q.pop(0)
                self._g_depth.get().set(float(len(self._q)))
                self._busy = True
            try:
                if not self._dead:
                    self.monitor.observe_batch(X, p1, members)
                    with self._lock:
                        self._observed_rows += int(X.shape[0])
                else:
                    # Batches that were already queued when the feed
                    # quarantined: discarded, but never silently — the
                    # offered = observed + sampled_out + dropped identity
                    # must hold through a quarantine too.
                    with self._lock:
                        self._dropped_rows += int(X.shape[0])
                    self._c_dropped.inc(int(X.shape[0]), reason="dead")
            except Exception as exc:
                # Same quarantine contract as the old in-engine feed:
                # telemetry must never take serving down, and a dead
                # monitor must say so on every surface. The poison
                # batch's own rows count as dropped — they never reached
                # the window.
                msg = f"{type(exc).__name__}: {exc}"
                journal.event("quality_feed_disabled", error=msg)
                self.monitor.disable(f"feed quarantined: {msg}")
                with self._lock:
                    self._dead = True
                    self._dropped_rows += int(X.shape[0])
                self._c_dropped.inc(int(X.shape[0]), reason="dead")
            finally:
                with self._lock:
                    self._busy = False
                    self._cv.notify_all()

    # -- control / inspection ----------------------------------------------

    def drain(self, timeout: float = 2.0) -> bool:
        """Block until every handed-off batch has been observed (or the
        timeout passes); True when fully drained. ``/debug/quality`` uses
        this so a snapshot taken right after traffic reflects that
        traffic — the asynchrony is a hot-path optimization, not an
        accuracy tax on debugging."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while self._q or self._busy:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(remaining)
            return True

    def disable(self, reason: str) -> None:
        """Forward a quarantine request (the engine's last-resort path if
        the hand-off itself ever raised)."""
        with self._lock:
            self._dead = True
        self.monitor.disable(reason)

    def reenable(self) -> bool:
        """Clear a quarantine (the supervisor calls this after a
        successful engine restart). True when something was cleared."""
        with self._lock:
            was_dead, self._dead = self._dead, False
        cleared = self.monitor.reenable()
        return was_dead or cleared

    def stats(self) -> dict:
        with self._lock:
            return {
                "capacity": self.capacity,
                "depth": len(self._q),
                "observed_rows": self._observed_rows,
                "sampled_out_rows": self._sampled_out_rows,
                "dropped_rows": self._dropped_rows,
                "dead": self._dead,
            }

    def close(self, timeout: float = 5.0) -> None:
        """Stop the feed thread after draining what is already queued."""
        with self._lock:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout)
