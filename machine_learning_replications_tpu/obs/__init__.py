"""obs — unified telemetry for the whole stack (docs/OBSERVABILITY.md).

The training side had a flat ``PhaseTimer`` and free-text ``stage_say``
lines; the serving side had its own private counter/gauge/histogram
classes; neither could answer "which nested stage recompiled?" or "what
config produced this artifact?". This package is the one observability
layer every other layer reports into:

  ``spans``     hierarchical, thread-aware spans that block on registered
                device work and export Chrome-trace-event JSON (open the
                file at https://ui.perfetto.dev). ``utils.trace.PhaseTimer``
                is now a thin adapter over these.
  ``registry``  process-global metrics registry: labeled counter / gauge /
                histogram families rendered as Prometheus text exposition.
                The primitive instruments moved here from
                ``serve/metrics.py`` (which re-exports them — metric names
                on ``/metrics`` are unchanged).
  ``journal``   JSONL run journal: first record is a run manifest (run id,
                git sha, jax/platform versions, config hash), then
                structured stage / checkpoint-restore / flush events.
                ``stage_scope`` is the single stage-timing code path shared
                by ``models.pipeline`` and ``persist.orbax_io``.
  ``jaxmon``    ``jax.monitoring`` listeners accounting JIT compiles,
                compile seconds, and host↔device transfer bytes into the
                global registry — the serve engine's one-compile-per-bucket
                property and training recompile regressions, measurable in
                production.
  ``reqtrace``  request-scoped tracing: per-request phase breakdown
                (parse / queue wait / batch assembly / device compute /
                respond) threaded through the serving path, and a bounded
                flight recorder with tail-based sampling (keep failures
                and the p99 tail, drop the fast majority). Sampled traces
                merge into the active Chrome-trace export.
  ``slo``       declarative latency/availability objectives with
                error-budget burn gauges exported through the registry.
  ``profiler``  on-demand ``jax.profiler`` capture with a single-flight
                guard (the serving ``/debug/profile`` endpoint).
  ``quality``   model-quality monitoring: training-time reference profiles
                (per-feature histograms/moments/quantiles + score
                distribution, carried inside the checkpoint), streaming
                PSI/KS drift vs the reference, calibration-bins snapshot,
                and ensemble-agreement tracking — ``quality_*`` registry
                families, the serving ``/debug/quality`` endpoint, and
                journaled ``ok``/``warn``/``alert`` status transitions.

Importing this package (or ``journal``/``registry``) never imports jax
(graftcheck rule ``import-purity``): ``bench.py``'s orchestrator — which
must not touch the flaky TPU plugin — builds its run manifest through
``obs.journal`` too. Metric-family and journal-event names are closed
catalogs (``obs.catalog``; rules ``metrics-catalog`` /
``journal-catalog``, docs/ANALYSIS.md).
"""

from machine_learning_replications_tpu.obs import (  # noqa: F401
    jaxmon,
    journal,
    profiler,
    quality,
    registry,
    reqtrace,
    slo,
    spans,
)

__all__ = [
    "jaxmon", "journal", "profiler", "quality", "registry", "reqtrace",
    "slo", "spans",
]
