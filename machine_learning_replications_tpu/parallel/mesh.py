"""Device-mesh construction.

One 2-D mesh serves the whole framework (axis semantics in the package
docstring). On a single chip both axes are 1 and every ``shard_map`` /
``pjit`` collapses to local compute — the same code path serves one chip,
a v5e-8 slice, and a multi-host pod (mesh shape is config, not code).
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

DATA_AXIS = "data"
MODEL_AXIS = "model"


def make_mesh(
    data: int | None = None,
    model: int = 1,
    devices: list[jax.Device] | None = None,
) -> Mesh:
    """Build a ``(data, model)`` mesh.

    ``data=None`` uses all remaining devices on the data axis. Devices are
    laid out so that the model axis is innermost (fastest-varying), keeping
    model-axis collectives on adjacent chips (ICI neighbours on a TPU slice).
    """
    devs = devices if devices is not None else jax.devices()
    if data is None:
        if len(devs) % model:
            raise ValueError(f"{len(devs)} devices not divisible by model={model}")
        data = len(devs) // model
    n = data * model
    if n > len(devs):
        raise ValueError(f"mesh {data}x{model} needs {n} devices, have {len(devs)}")
    grid = np.array(devs[:n]).reshape(data, model)
    return Mesh(grid, (DATA_AXIS, MODEL_AXIS))


def single_device_mesh() -> Mesh:
    return make_mesh(data=1, model=1)
