"""Distributed depth-1 GBDT training — the sharded version of the
replicated-sorted-layout trainer (``models.gbdt._fit_stumps``).

Mesh mapping (SURVEY.md §2.5 — promoting the reference's implicit axes):

  data  — cohort rows. Each shard holds its own rows in *locally* sorted
          order per feature; cumulative left-of-boundary sums are additive
          across shards, so the only per-stage communication is a ``psum``
          of ``[F, B-1]`` gradient/hessian partials (plus five scalars) over
          ICI. This is the "histogram partials all-reduced" design.
  model — feature tiles of the split search: each shard owns the sorted
          copies of F/model features and scores their candidate splits; the
          global argmax is recovered with one tiny ``all_gather`` of
          per-shard bests. Split routing needs the *chosen* feature's bins
          in every local sort order, which is why ``bins_x`` keeps its
          query-feature axis unsharded.

The whole boosting loop lives inside one ``shard_map``-ped ``jit``; nothing
crosses the host boundary per stage.

Padding contracts: rows padded per shard carry weight 0 and bin ``B-1``
(they sort past every candidate boundary, and all their sums are masked);
feature *sort-order slots* padded to a multiple of the model-axis size are
coherent identity-order copies of the real data with +inf thresholds — they
evolve the same raw scores as real slots but can never be selected, so every
shard (including shards owning only padded slots) computes identical
replicated outputs. Global scalar reductions additionally come from model
shard 0 only (masked two-axis psum), making replication hold by
construction rather than by the padding argument.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from machine_learning_replications_tpu.config import GBDTConfig
from machine_learning_replications_tpu.models import gbdt
from machine_learning_replications_tpu.models.tree import TreeEnsembleParams
from machine_learning_replications_tpu.ops import binning
from machine_learning_replications_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

from machine_learning_replications_tpu.ops.histogram import (  # noqa: E402
    IMPURITY_EPS,
    newton_leaf_value,
)


def _prepare_shards(
    bins: binning.BinnedFeatures, y: np.ndarray, n_data: int, n_model: int
):
    """Host-side: partition rows into contiguous shards, locally sort each,
    pad rows and features. Returns stacked arrays with leading shard axes."""
    b = np.asarray(bins.binned)
    n, F = b.shape
    B = bins.max_bins
    # Narrowest dtype holding bin ids (mirrors ops.histogram.build_stump_data:
    # uint8 for the capped 'hist' regime, wider for 'exact' enumeration).
    bin_dtype = np.uint8 if B <= 256 else np.uint16 if B <= 65536 else np.int32
    F_pad = -(-F // n_model) * n_model
    n_local = -(-n // n_data)

    # Query-feature axis needs only the F real features (fstar < F always);
    # the sort-order axis pads to F_pad for the model-axis shard split.
    bins_x = np.full((n_data, F, F_pad, n_local), B - 1, bin_dtype)
    y_sorted = np.zeros((n_data, F_pad, n_local), np.float32)
    w_sorted = np.zeros((n_data, F_pad, n_local), np.float32)
    left_count = np.zeros((n_data, F_pad, B - 1), np.int32)
    thresholds = np.full((F_pad, B - 1), np.inf, np.float64)
    thresholds[:F] = np.asarray(bins.thresholds)

    for s in range(n_data):
        rows = slice(s * n_local, min((s + 1) * n_local, n))
        bl = b[rows]
        yl = np.asarray(y)[rows]
        k = bl.shape[0]
        # pad rows: bin B-1 everywhere, weight 0
        bl = np.concatenate([bl, np.full((n_local - k, F), B - 1, bl.dtype)])
        yl = np.concatenate([yl, np.zeros(n_local - k)])
        wl = np.concatenate([np.ones(k), np.zeros(n_local - k)])
        order = np.argsort(bl, axis=0, kind="stable")  # [n_local, F]
        for fs in range(F):
            bins_x[s, :, fs, :] = bl[order[:, fs], :].T
            y_sorted[s, fs] = yl[order[:, fs]]
            w_sorted[s, fs] = wl[order[:, fs]]
            cnt = np.bincount(bl[:k, fs], minlength=B)
            left_count[s, fs] = np.cumsum(cnt)[:-1]
        # Padded sort-order slots: coherent identity-order copies of the real
        # rows. Their raw scores evolve exactly like real slots (split routing
        # reads the true bins), but left_count stays 0 and thresholds +inf so
        # their candidate splits are never valid — required so shards whose
        # every slot is padding still compute the replicated outputs.
        for fs in range(F, F_pad):
            bins_x[s, :, fs, :] = bl.T
            y_sorted[s, fs] = yl
            w_sorted[s, fs] = wl
    return bins_x, y_sorted, w_sorted, left_count, thresholds, F_pad, n_local


def _fit_raw(
    mesh: jax.sharding.Mesh,
    X: np.ndarray,
    y: np.ndarray,
    cfg: GBDTConfig,
    bins: binning.BinnedFeatures | None = None,
):
    """Prepare shards, place them on the mesh, run the sharded loop; returns
    the raw (replicated) device arrays ``(feats, thrs, vals, splits, devs)``."""
    assert cfg.max_depth == 1, "sharded trainer covers the depth-1 config"
    if bins is None:
        bins = binning.bin_features(np.asarray(X), gbdt.bin_budget(cfg))
    n_data = mesh.shape[DATA_AXIS]
    n_model = mesh.shape[MODEL_AXIS]
    bins_x, y_sorted, w_sorted, left_count, thresholds, F_pad, n_local = (
        _prepare_shards(bins, y, n_data, n_model)
    )

    def put(a, spec):
        return jax.device_put(np.asarray(a), NamedSharding(mesh, spec))

    # shard layouts: leading data-shard axis folds into rows via shard_map.
    # dtypes follow the backend (f64 under the x64 test config, f32 on TPU).
    fdt = np.float64 if jax.config.jax_enable_x64 else np.float32
    args = (
        put(bins_x, P(DATA_AXIS, None, MODEL_AXIS, None)),
        put(y_sorted.astype(fdt), P(DATA_AXIS, MODEL_AXIS, None)),
        put(w_sorted.astype(fdt), P(DATA_AXIS, MODEL_AXIS, None)),
        put(left_count, P(DATA_AXIS, MODEL_AXIS, None)),
        put(thresholds.astype(fdt), P(MODEL_AXIS, None)),
    )
    return _fit_sharded(
        mesh,
        *args,
        n_stages=cfg.n_estimators,
        learning_rate=cfg.learning_rate,
        min_samples_leaf=cfg.min_samples_leaf,
        min_samples_split=cfg.min_samples_split,
    )


def fit(
    mesh: jax.sharding.Mesh,
    X: np.ndarray,
    y: np.ndarray,
    cfg: GBDTConfig = GBDTConfig(),
    bins: binning.BinnedFeatures | None = None,
) -> tuple[TreeEnsembleParams, dict[str, Any]]:
    """Depth-1 GBDT fit sharded over ``mesh`` (axes 'data' × 'model')."""
    if bins is None:
        bins = binning.bin_features(np.asarray(X), gbdt.bin_budget(cfg))
    F = bins.binned.shape[1]
    feats, thrs, vals, splits, devs = _fit_raw(mesh, X, y, cfg, bins)
    feats = np.asarray(feats)
    # padded feature slots can never be selected; map back is identity on [0, F)
    assert feats.max() < F
    params = gbdt.forest_to_params(
        jnp.asarray(feats),
        jnp.asarray(thrs),
        jnp.asarray(vals),
        jnp.asarray(splits),
        init_raw=gbdt._prior_log_odds(y),
        learning_rate=cfg.learning_rate,
        max_depth=1,
    )
    return params, {"train_deviance": np.asarray(devs)}


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "n_stages", "learning_rate", "min_samples_leaf", "min_samples_split",
    ),
)
def _fit_sharded(
    mesh,
    bins_x,      # [S, F, F_pad, n_local] bin ids (S = data shards; query
                 #   axis unpadded — fstar always indexes a real feature)
    y_sorted,    # [S, F_pad, n_local]
    w_sorted,    # [S, F_pad, n_local]
    left_count,  # [S, F_pad, B-1] int32
    thresholds,  # [F_pad, B-1]
    *,
    n_stages: int,
    learning_rate: float,
    min_samples_leaf: int,
    min_samples_split: int,
):
    from jax import shard_map

    Bm1 = thresholds.shape[-1]

    def local_loop(bx, ys, ws, lc, thr):
        # Shapes inside shard_map (one data shard × one model shard):
        #   bx [1, F, F_loc, n_local] — query-feature axis unsharded
        #   ys/ws [1, F_loc, n_local]; lc [1, F_loc, B-1]; thr [F_loc, B-1]
        bx = bx[0]
        ys = ys[0]
        ws = ws[0]
        lc = lc[0]
        dtype = thr.dtype
        F_loc, n_local = ys.shape
        m_idx = jax.lax.axis_index(MODEL_AXIS)
        on0 = m_idx == 0

        def gsum(v):
            """Global Σ over real rows of a per-row [n_local] quantity, taken
            from model shard 0's slot-0 ordering and psum'd over BOTH axes —
            replicated on every shard by construction."""
            return jax.lax.psum(
                jnp.where(on0, jnp.sum(v), 0.0).astype(dtype),
                (DATA_AXIS, MODEL_AXIS),
            )

        n_real = gsum(ws[0])  # rows are real ⇔ w=1
        sum_y = gsum(ys[0] * ws[0])
        p1 = sum_y / n_real
        f0 = jnp.log(p1 / (1.0 - p1))

        def cumb(v):  # [F_loc, n_local] → global left-of-boundary sums [F_loc, B-1]
            from machine_learning_replications_tpu.ops.histogram import (
                cumulative_boundary_sums,
            )

            return jax.lax.psum(cumulative_boundary_sums(v, lc), DATA_AXIS)

        CL = cumb(ws)  # weights never change: hoisted out of the stage loop

        def stage(t, carry):
            raw, feats, thrs_o, vals, splits, devs = carry  # raw [F_loc, n_local]
            p = jax.scipy.special.expit(raw)
            g = (ys - p) * ws
            h = p * (1.0 - p) * ws
            GL = cumb(g)
            HL = cumb(h)
            GT = gsum(g[0])
            HT = gsum(h[0])
            G2 = gsum(g[0] * g[0])

            # local split scoring over this shard's features
            GR = GT - GL
            CR = n_real - CL
            valid = (
                (CL >= min_samples_leaf)
                & (CR >= min_samples_leaf)
                & jnp.isfinite(thr)
            )
            diff = GL / jnp.maximum(CL, 1) - GR / jnp.maximum(CR, 1)
            proxy = jnp.where(valid, diff * diff * CL * CR, -jnp.inf)
            flat = proxy.reshape(-1)
            best_local = jnp.argmax(flat).astype(jnp.int32)
            best_gain = flat[best_local]
            # global best across the model axis (tie → lower shard index, which
            # preserves first-feature-in-order tie-breaking)
            gains = jax.lax.all_gather(best_gain, MODEL_AXIS)          # [M]
            locs = jax.lax.all_gather(best_local, MODEL_AXIS)          # [M]
            winner = jnp.argmax(gains).astype(jnp.int32)
            w_loc = locs[winner]
            f_local = w_loc // Bm1
            bstar = w_loc % Bm1
            fstar = (winner * F_loc + f_local).astype(jnp.int32)       # global feature id

            # gather the winning boundary stats (every shard recomputes from
            # its replicated GL/HL? GL is sharded by feature — all_gather the
            # single winning row's scalars instead)
            on_winner = winner == m_idx
            sel = jnp.where(on_winner, 1.0, 0.0).astype(dtype)
            num_l = jax.lax.psum(GL[f_local, bstar] * sel, MODEL_AXIS)
            den_l = jax.lax.psum(HL[f_local, bstar] * sel, MODEL_AXIS)
            # thr can be +inf off-winner; inf·0 = NaN, so mask before the psum
            thr_star = jax.lax.psum(
                jnp.where(on_winner, thr[f_local, bstar], 0.0), MODEL_AXIS
            )
            gain_star = gains[winner]
            num_r, den_r = GT - num_l, HT - den_l

            mean = GT / jnp.maximum(n_real, 1)
            impurity = jnp.maximum(G2 / jnp.maximum(n_real, 1) - mean * mean, 0.0)
            do = (
                (n_real >= min_samples_split)
                & (impurity > IMPURITY_EPS)
                & jnp.isfinite(gain_star)
            )

            v_root = newton_leaf_value(GT, HT)
            v_l = newton_leaf_value(num_l, den_l)
            v_r = newton_leaf_value(num_r, den_r)

            split_bins = jax.lax.dynamic_index_in_dim(
                bx, fstar, axis=0, keepdims=False
            )  # [F_loc, n_local]
            go_left = split_bins <= bstar.astype(split_bins.dtype)
            contrib = jnp.where(do, jnp.where(go_left, v_l, v_r), v_root)
            raw = raw + learning_rate * contrib

            ll = gsum((ys[0] * raw[0] - jnp.logaddexp(0.0, raw[0])) * ws[0])
            dev = -2.0 * ll / n_real

            feat_t = jnp.where(do, fstar, 0) * jnp.array([1, 0, 0], jnp.int32)
            thr_t = jnp.stack(
                [jnp.where(do, thr_star, jnp.inf),
                 jnp.asarray(jnp.inf, dtype), jnp.asarray(jnp.inf, dtype)]
            )
            val_t = jnp.stack(
                [jnp.where(do, 0.0, v_root),
                 jnp.where(do, v_l, 0.0), jnp.where(do, v_r, 0.0)]
            ).astype(dtype)
            split_t = jnp.stack([do, jnp.array(False), jnp.array(False)])
            return (
                raw,
                feats.at[t].set(feat_t),
                thrs_o.at[t].set(thr_t),
                vals.at[t].set(val_t),
                splits.at[t].set(split_t),
                devs.at[t].set(dev),
            )

        init = (
            jnp.full((F_loc, n_local), f0, dtype),
            jnp.zeros((n_stages, 3), jnp.int32),
            jnp.full((n_stages, 3), jnp.inf, dtype),
            jnp.zeros((n_stages, 3), dtype),
            jnp.zeros((n_stages, 3), bool),
            jnp.zeros(n_stages, dtype),
        )
        _, feats, thrs_o, vals, splits, devs = jax.lax.fori_loop(
            0, n_stages, stage, init
        )
        # identical on every shard (computed from psum'd quantities)
        return feats, thrs_o, vals, splits, devs

    feats, thrs_o, vals, splits, devs = shard_map(
        local_loop,
        mesh=mesh,
        in_specs=(
            P(DATA_AXIS, None, MODEL_AXIS, None),
            P(DATA_AXIS, MODEL_AXIS, None),
            P(DATA_AXIS, MODEL_AXIS, None),
            P(DATA_AXIS, MODEL_AXIS, None),
            P(MODEL_AXIS, None),
        ),
        out_specs=(P(), P(), P(), P(), P()),
        check_vma=False,
    )(bins_x, y_sorted, w_sorted, left_count, thresholds)
    return feats, thrs_o, vals, splits, devs
