"""Distributed depth-1 GBDT training — the sharded counterpart of the
fused unsorted-histogram trainer (``models.gbdt._fit_hist1_fused``).

Mesh mapping (SURVEY.md §2.5 — promoting the reference's implicit axes):

  data  — cohort rows. Each shard accumulates gradient/hessian histograms
          of its local rows (``ops.histogram.stump_histograms`` — one-hot
          MXU contraction / Pallas VMEM kernel on TPU), and the only
          per-stage communication is a ``psum`` of ``[F_loc, B]`` partials
          (plus five scalars) over ICI. This is the "histogram partials
          all-reduced" design.
  model — feature tiles of the split search: each shard histograms and
          scores F/model features' candidate splits; the global argmax is
          recovered with one tiny ``all_gather`` of per-shard bests. Split
          routing reads the chosen feature's column from the (model-
          replicated) bin matrix — a dense dynamic slice, no gathers.

The whole boosting loop lives inside one ``shard_map``-ped ``jit``; nothing
crosses the host boundary per stage. Until r5 this trainer sharded the
replicated-sorted layout (F copies of every row vector per shard, boundary
sums per stage); the trace read in docs/SCALING.md "Roofline" showed ~70%
of each on-chip stage going to that layout's pad/reshape/copy formatting,
and its ``[F, F, n_local]`` bin tensor (2.9 GB at 10M rows on one shard)
dominated HBM. The histogram formulation keeps one ``[n_local]`` score
vector and the ``[n_local, F]`` u8 bin matrix — O(F·n/S) memory, same
math up to f32 summation regrouping.

Padding contracts: rows padded per shard carry weight 0 and bin ``B-1``
(the weighted path zeroes their statistics; the final bin never enters a
left-of-boundary sum); feature slots padded to a multiple of the
model-axis size hold constant-0 bins with +inf thresholds, so their
candidates are permanently invalid on every shard. Global scalar
reductions come from model shard 0 only (masked two-axis psum), making
replication hold by construction rather than by the padding argument.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from machine_learning_replications_tpu.config import GBDTConfig
from machine_learning_replications_tpu.models import gbdt
from machine_learning_replications_tpu.models.tree import TreeEnsembleParams
from machine_learning_replications_tpu.ops import binning
from machine_learning_replications_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

from machine_learning_replications_tpu.ops.histogram import (  # noqa: E402
    IMPURITY_EPS,
    newton_leaf_value,
)


# Per-shard budget for the trainer's working set. Since the r5 histogram
# reformulation the dominant allocation is just the model-replicated bin
# matrix (``n_local · F_pad`` bin ids) plus a handful of [n_local] f32
# vectors — O(F·n/S), ~40× under the old sorted layout's O(F²·n/S) — so
# this guard exists for pathological meshes/cardinalities, not routine
# fits. Above it the trainer refuses with sizing advice instead of
# OOM-ing mid-compile (VERDICT r2 weak #5).
MAX_LAYOUT_BYTES = 8 << 30


def _layout_plan(n: int, F: int, max_bins: int, n_data: int, n_model: int):
    """(F_pad, n_local, bin_dtype, working-set bytes per shard) for a mesh
    shape. Conservative for the backend that allocates the most: the
    'xla' stump_histograms engine materializes an int32 segment id plus a
    broadcast f64 scatter operand over the [n_local, F_loc] tile per
    stage (~24 B/element of transient), on top of the model-replicated
    bin matrix and the ~6 per-row f32/f64 vectors each stage touches.
    The B-scaled replicated arrays (thresholds, per-tile histograms and
    their cumsums) are counted too: an uncapped 'exact' candidate set at
    scale puts B ≈ n, and the same unbounded-candidate pathology that
    OOM'd the single-device member fit (gbdt._guard_stump_layout) must
    trip this guard rather than the allocator."""
    F_pad = -(-F // n_model) * n_model
    n_local = -(-n // n_data)
    F_loc = F_pad // n_model
    bin_dtype = (
        np.uint8 if max_bins <= 256
        else np.uint16 if max_bins <= 65536
        else np.int32
    )
    per_shard = n_local * (
        F_pad * np.dtype(bin_dtype).itemsize + F_loc * 24 + 6 * 8
    ) + max_bins * (F_pad + 9 * F_loc) * 8
    # 9 ≈ the peak count of simultaneous [F_loc, B(-1)] f64 arrays in a
    # stage: hist + cumsum (2 each), CL, thr slice, and the scoring
    # temporaries (GR/CR/diff/proxy overlap the first four's lifetimes).
    return F_pad, n_local, bin_dtype, per_shard


def _fit_raw(
    mesh: jax.sharding.Mesh,
    X: np.ndarray,
    y: np.ndarray,
    cfg: GBDTConfig,
    bins: binning.BinnedFeatures | None = None,
    sample_weight: np.ndarray | None = None,
    max_layout_bytes: int | None = None,
):
    """Pad + place the binned cohort on the mesh and run the sharded loop.
    Returns the raw replicated device arrays
    ``(feats, thrs, vals, splits, devs)``."""
    assert cfg.max_depth == 1, "sharded trainer covers the depth-1 config"
    if bins is None:
        bins = binning.bin_features(np.asarray(X), gbdt.bin_budget(cfg))
    n_data = mesh.shape[DATA_AXIS]
    n_model = mesh.shape[MODEL_AXIS]
    n, F = bins.binned.shape
    B = int(bins.max_bins)
    F_pad, n_local, bin_dtype, per_shard = _layout_plan(n, F, B, n_data, n_model)
    budget = MAX_LAYOUT_BYTES if max_layout_bytes is None else max_layout_bytes
    if per_shard > budget:
        raise RuntimeError(
            f"stump_trainer: per-shard working set needs {per_shard:,} bytes "
            f"(F={F}, n_local={n_local}, max_bins={B}, "
            f"bin dtype {np.dtype(bin_dtype).name}) > budget {budget:,} bytes. "
            "Use splitter='hist' (bounds the candidate count; n_bins<=256 "
            "makes bin ids uint8) or route through parallel.hist_trainer; "
            "adding data shards helps only the row-scaled portion — the "
            "candidate-scaled arrays are model-replicated and do not shard "
            "with 'data'."
        )

    import jax.numpy as jnp

    # Device-side padding: rows pad to n_data·n_local with bin B-1 / weight
    # 0 (zero-weighted statistics; B-1 never enters a left-of-boundary
    # sum); feature columns pad to F_pad with constant 0 bins and +inf
    # thresholds, making their candidates permanently invalid.
    n_pad = n_data * n_local
    bj = jnp.asarray(bins.binned).astype(bin_dtype)
    bl_ext = jnp.pad(
        bj, ((0, n_pad - n), (0, 0)), constant_values=np.asarray(B - 1, bin_dtype)
    )
    bl_ext = jnp.pad(bl_ext, ((0, 0), (0, F_pad - F)))
    fdt = np.float64 if jax.config.jax_enable_x64 else np.float32
    # Uniform weights + no padding rows ⇒ the weighted machinery is dead
    # code inside the loop (see ``weighted=`` below); don't build and ship
    # a full-length all-ones array the program never reads — at 10M rows
    # that is ~40 MB through a ~17 MB/s host link, per fit. A [n_data]
    # placeholder keeps the sharded operand shape valid at one scalar per
    # shard.
    weighted = not (sample_weight is None and n_pad == n)
    if weighted:
        w_real = (
            jnp.ones(n, fdt) if sample_weight is None
            else jnp.asarray(sample_weight).astype(fdt)
        )
        w_pad = jnp.pad(w_real, (0, n_pad - n))
    else:
        w_pad = jnp.zeros(n_data, fdt)
    thresholds = jnp.pad(
        jnp.asarray(bins.thresholds).astype(fdt), ((0, F_pad - F), (0, 0)),
        constant_values=np.inf,
    )

    def put(a, spec):
        return jax.device_put(a, NamedSharding(mesh, spec))

    # Exact-0/1 labels cross the (possibly ~18 MB/s tunneled) host→device
    # link as one byte per row — at 10M rows that is 10 MB instead of
    # 40-80 MB; the shard casts back to the compute dtype on device. Host
    # labels are checked for free; device-resident labels cost one scalar
    # fetch, still far cheaper than the wider transfer.
    from machine_learning_replications_tpu.ops.histogram import is_binary_labels

    yj = jnp.asarray(y)
    binary_y = bool(is_binary_labels(y if isinstance(y, np.ndarray) else yj))
    if binary_y:
        y_pad = jnp.pad((yj > 0.5).astype(jnp.uint8), (0, n_pad - n))
    else:
        y_pad = jnp.pad(yj.astype(fdt), (0, n_pad - n))
    return _fit_sharded(
        mesh,
        put(bl_ext, P(DATA_AXIS, None)),
        put(y_pad, P(DATA_AXIS)),
        put(w_pad, P(DATA_AXIS)),
        put(thresholds, P()),
        n_stages=cfg.n_estimators,
        learning_rate=cfg.learning_rate,
        min_samples_leaf=cfg.min_samples_leaf,
        min_samples_split=cfg.min_samples_split,
        weighted=weighted,
        max_bins=B,
        backend=gbdt.resolve_backend(cfg),
    )


def fit(
    mesh: jax.sharding.Mesh,
    X: np.ndarray,
    y: np.ndarray,
    cfg: GBDTConfig = GBDTConfig(),
    bins: binning.BinnedFeatures | None = None,
    sample_weight: np.ndarray | None = None,
    max_layout_bytes: int | None = None,
) -> tuple[TreeEnsembleParams, dict[str, Any]]:
    """Depth-1 GBDT fit sharded over ``mesh`` (axes 'data' × 'model').

    ``sample_weight`` (0/1 fold masks or real weights) rides the padding
    contract — weight-0 rows keep their slots but contribute nothing to any
    reduction — so the stacking CV's masked fold fits run through the same
    program. ``max_layout_bytes`` overrides the per-shard memory guard."""
    if bins is None:
        bins = binning.bin_features(np.asarray(X), gbdt.bin_budget(cfg))
    F = bins.binned.shape[1]
    feats, thrs, vals, splits, devs = _fit_raw(
        mesh, X, y, cfg, bins,
        sample_weight=sample_weight, max_layout_bytes=max_layout_bytes,
    )
    feats = np.asarray(feats)
    # padded feature slots can never be selected; map back is identity on [0, F)
    assert feats.max() < F
    params = gbdt.forest_to_params(
        jnp.asarray(feats),
        jnp.asarray(thrs),
        jnp.asarray(vals),
        jnp.asarray(splits),
        init_raw=gbdt._prior_log_odds(y, sample_weight),
        learning_rate=cfg.learning_rate,
        max_depth=1,
    )
    return params, {"train_deviance": np.asarray(devs)}


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "n_stages", "learning_rate", "min_samples_leaf",
        "min_samples_split", "weighted", "max_bins", "backend",
    ),
)
def _fit_sharded(
    mesh,
    bl_ext,      # [n_pad, F_pad] bin ids, rows sharded over 'data' (model-
                 #   replicated: every model shard histograms its column tile)
    y_pad,       # [n_pad] — labels, 0 at padding rows
    w_pad,       # [n_pad] — sample weights, 0 at padding rows
    thresholds,  # [F_pad, B-1] replicated (+inf on padded feature slots)
    *,
    n_stages: int,
    learning_rate: float,
    min_samples_leaf: int,
    min_samples_split: int,
    weighted: bool = True,
    max_bins: int = 256,
    backend: str = "xla",
):
    from jax import shard_map

    Bm1 = thresholds.shape[-1]
    n_model = mesh.shape[MODEL_AXIS]
    F_pad = bl_ext.shape[1]
    F_loc_s = F_pad // n_model

    def local_loop(bl, yl, wl, thr_full):
        # Shapes inside shard_map (one data shard × one model shard):
        #   bl [n_local, F_pad]; yl/wl [n_local]; thr_full [F_pad, B-1]
        dtype = thr_full.dtype
        n_local = bl.shape[0]
        m_idx = jax.lax.axis_index(MODEL_AXIS)
        on0 = m_idx == 0

        # ---- one-time per-shard prep (the stage loop touches only [n]
        # vectors and the [n_local, F_loc] column tile) ------------------
        col0 = m_idx * F_loc_s
        thr = jax.lax.dynamic_slice_in_dim(thr_full, col0, F_loc_s, axis=0)
        cols = jax.lax.dynamic_slice_in_dim(bl, col0, F_loc_s, axis=1)
        ys = yl.astype(dtype)                                 # [n_local]
        ws = wl.astype(dtype) if weighted else None
        F_loc = F_loc_s
        from machine_learning_replications_tpu.ops import histogram as hist_ops

        def gsum(v):
            """Global Σ over real rows of a per-row [n_local] quantity, taken
            from model shard 0 and psum'd over BOTH axes — replicated on
            every shard by construction."""
            return jax.lax.psum(
                jnp.where(on0, jnp.sum(v), 0.0).astype(dtype),
                (DATA_AXIS, MODEL_AXIS),
            )

        if weighted:
            n_real = gsum(ws)  # rows are real ⇔ w=1
            sum_y = gsum(ys * ws)
        else:
            n_real = gsum(jnp.ones_like(ys))
            sum_y = gsum(ys)
        p1 = sum_y / n_real
        f0 = jnp.log(p1 / (1.0 - p1))

        def hist_cum(g, h):
            """Per-stage global left-of-boundary grad/hess sums
            [2, F_loc, B-1]: local histograms over this shard's column tile
            (``stump_histograms`` — the same engine the fused single-device
            path uses), one psum of the [2, F_loc, B] partials over 'data',
            then a tiny cumsum over bins."""
            hg = hist_ops.stump_histograms(
                cols, g, h, max_bins, backend=backend
            )                                                 # [2, F_loc, B]
            hg = jax.lax.psum(hg, DATA_AXIS)
            return jnp.cumsum(hg, axis=2)[:, :, :Bm1]

        if weighted:
            # weights don't change: hoisted out of the loop (one extra
            # histogram pass at fit start)
            ones = jnp.ones_like(ys)
            CL = hist_cum(ws, ones)[0]
        else:
            # Unweighted counts are exactly the positional boundaries:
            # #rows with bin ≤ b via a chunked compare+sum over the
            # (unsorted) local columns. Padding rows carry bin B-1 > every
            # boundary so they never count; a padded feature slot's
            # constant-0 column gives lc = n_local, which its +inf
            # thresholds make unreachable (valid=False).
            bvals = jnp.arange(Bm1, dtype=cols.dtype)
            lc_mapped, _ = binning.chunked_row_reduce(
                cols,
                lambda cc: jnp.sum(
                    cc[:, None, :] <= bvals[None, :, None],
                    axis=0, dtype=jnp.int32,
                ),
                pad_value=np.asarray(Bm1, cols.dtype),
            )
            lc = jnp.sum(lc_mapped, axis=0).T.astype(jnp.int32)
            CL = jax.lax.psum(lc.astype(dtype), DATA_AXIS)

        def stage(t, carry):
            raw, feats, thrs_o, vals, splits, devs = carry    # raw [n_local]
            p = jax.scipy.special.expit(raw)
            if weighted:
                g = (ys - p) * ws
                h = p * (1.0 - p) * ws
            else:
                g = ys - p
                h = p * (1.0 - p)
            GHL = hist_cum(g, h)
            GL, HL = GHL[0], GHL[1]
            GT = gsum(g)
            HT = gsum(h)
            G2 = gsum(g * g)

            # local split scoring over this shard's features
            GR = GT - GL
            CR = n_real - CL
            valid = (
                (CL >= min_samples_leaf)
                & (CR >= min_samples_leaf)
                & jnp.isfinite(thr)
            )
            diff = GL / jnp.maximum(CL, 1) - GR / jnp.maximum(CR, 1)
            proxy = jnp.where(valid, diff * diff * CL * CR, -jnp.inf)
            flat = proxy.reshape(-1)
            best_local = jnp.argmax(flat).astype(jnp.int32)
            best_gain = flat[best_local]
            # global best across the model axis (tie → lower shard index, which
            # preserves first-feature-in-order tie-breaking)
            gains = jax.lax.all_gather(best_gain, MODEL_AXIS)          # [M]
            locs = jax.lax.all_gather(best_local, MODEL_AXIS)          # [M]
            winner = jnp.argmax(gains).astype(jnp.int32)
            w_loc = locs[winner]
            f_local = w_loc // Bm1
            bstar = w_loc % Bm1
            fstar = (winner * F_loc + f_local).astype(jnp.int32)       # global feature id

            # gather the winning boundary stats (every shard recomputes from
            # its replicated GL/HL? GL is sharded by feature — all_gather the
            # single winning row's scalars instead)
            on_winner = winner == m_idx
            sel = jnp.where(on_winner, 1.0, 0.0).astype(dtype)
            num_l = jax.lax.psum(GL[f_local, bstar] * sel, MODEL_AXIS)
            den_l = jax.lax.psum(HL[f_local, bstar] * sel, MODEL_AXIS)
            # thr can be +inf off-winner; inf·0 = NaN, so mask before the psum
            thr_star = jax.lax.psum(
                jnp.where(on_winner, thr[f_local, bstar], 0.0), MODEL_AXIS
            )
            gain_star = gains[winner]
            num_r, den_r = GT - num_l, HT - den_l

            mean = GT / jnp.maximum(n_real, 1)
            impurity = jnp.maximum(G2 / jnp.maximum(n_real, 1) - mean * mean, 0.0)
            do = (
                (n_real >= min_samples_split)
                & (impurity > IMPURITY_EPS)
                & jnp.isfinite(gain_star)
            )

            v_root = newton_leaf_value(GT, HT)
            v_l = newton_leaf_value(num_l, den_l)
            v_r = newton_leaf_value(num_r, den_r)

            split_bins = jax.lax.dynamic_index_in_dim(
                bl, fstar, axis=1, keepdims=False
            )  # [n_local] — the chosen feature's column, model-replicated
            go_left = split_bins <= bstar.astype(split_bins.dtype)
            contrib = jnp.where(do, jnp.where(go_left, v_l, v_r), v_root)
            raw = raw + learning_rate * contrib

            ll_terms = ys * raw - jnp.logaddexp(0.0, raw)
            ll = gsum(ll_terms * ws if weighted else ll_terms)
            dev = -2.0 * ll / n_real

            feat_t = jnp.where(do, fstar, 0) * jnp.array([1, 0, 0], jnp.int32)
            thr_t = jnp.stack(
                [jnp.where(do, thr_star, jnp.inf),
                 jnp.asarray(jnp.inf, dtype), jnp.asarray(jnp.inf, dtype)]
            )
            val_t = jnp.stack(
                [jnp.where(do, 0.0, v_root),
                 jnp.where(do, v_l, 0.0), jnp.where(do, v_r, 0.0)]
            ).astype(dtype)
            split_t = jnp.stack([do, jnp.array(False), jnp.array(False)])
            return (
                raw,
                feats.at[t].set(feat_t),
                thrs_o.at[t].set(thr_t),
                vals.at[t].set(val_t),
                splits.at[t].set(split_t),
                devs.at[t].set(dev),
            )

        init = (
            jnp.full((n_local,), f0, dtype),
            jnp.zeros((n_stages, 3), jnp.int32),
            jnp.full((n_stages, 3), jnp.inf, dtype),
            jnp.zeros((n_stages, 3), dtype),
            jnp.zeros((n_stages, 3), bool),
            jnp.zeros(n_stages, dtype),
        )
        _, feats, thrs_o, vals, splits, devs = jax.lax.fori_loop(
            0, n_stages, stage, init
        )
        # identical on every shard (computed from psum'd quantities)
        return feats, thrs_o, vals, splits, devs

    feats, thrs_o, vals, splits, devs = shard_map(
        local_loop,
        mesh=mesh,
        in_specs=(
            P(DATA_AXIS, None),
            P(DATA_AXIS),
            P(DATA_AXIS),
            P(),
        ),
        out_specs=(P(), P(), P(), P(), P()),
        check_vma=False,
    )(bl_ext, y_pad, w_pad, thresholds)
    return feats, thrs_o, vals, splits, devs
