"""Distributed depth-1 GBDT training — the sharded version of the
replicated-sorted-layout trainer (``models.gbdt._fit_stumps``).

Mesh mapping (SURVEY.md §2.5 — promoting the reference's implicit axes):

  data  — cohort rows. Each shard holds its own rows in *locally* sorted
          order per feature; cumulative left-of-boundary sums are additive
          across shards, so the only per-stage communication is a ``psum``
          of ``[F, B-1]`` gradient/hessian partials (plus five scalars) over
          ICI. This is the "histogram partials all-reduced" design.
  model — feature tiles of the split search: each shard owns the sorted
          copies of F/model features and scores their candidate splits; the
          global argmax is recovered with one tiny ``all_gather`` of
          per-shard bests. Split routing needs the *chosen* feature's bins
          in every local sort order, which is why ``bins_x`` keeps its
          query-feature axis unsharded.

The whole boosting loop lives inside one ``shard_map``-ped ``jit``; nothing
crosses the host boundary per stage.

Padding contracts: rows padded per shard carry weight 0 and bin ``B-1``
(they sort past every candidate boundary, and all their sums are masked);
feature *sort-order slots* padded to a multiple of the model-axis size are
coherent identity-order copies of the real data with +inf thresholds — they
evolve the same raw scores as real slots but can never be selected, so every
shard (including shards owning only padded slots) computes identical
replicated outputs. Global scalar reductions additionally come from model
shard 0 only (masked two-axis psum), making replication hold by
construction rather than by the padding argument.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from machine_learning_replications_tpu.config import GBDTConfig
from machine_learning_replications_tpu.models import gbdt
from machine_learning_replications_tpu.models.tree import TreeEnsembleParams
from machine_learning_replications_tpu.ops import binning
from machine_learning_replications_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

from machine_learning_replications_tpu.ops.histogram import (  # noqa: E402
    IMPURITY_EPS,
    newton_leaf_value,
)


# Per-shard budget for the replicated-sorted layout (``bins_x`` is the
# dominant allocation: F_pad · F_loc · n_local bin ids per (data, model)
# shard — O(F²·n/S) memory). Above this the trainer refuses with sizing
# advice instead of OOM-ing mid-compile (VERDICT r2 weak #5).
MAX_LAYOUT_BYTES = 8 << 30


def _layout_plan(n: int, F: int, max_bins: int, n_data: int, n_model: int):
    """(F_pad, n_local, bin_dtype, bins_x bytes per shard) for a mesh shape.

    The byte estimate counts F_pad+1 gathered planes: binary labels ride
    the bins matrix as one extra packed column (``_fit_raw``), and the
    guard must be conservative for exactly the configuration that
    allocates the most — an unpacked fit simply comes in ~1/F_pad under
    the estimate."""
    F_pad = -(-F // n_model) * n_model
    n_local = -(-n // n_data)
    bin_dtype = (
        np.uint8 if max_bins <= 256
        else np.uint16 if max_bins <= 65536
        else np.int32
    )
    per_shard = (
        (F_pad + 1) * (F_pad // n_model) * n_local * np.dtype(bin_dtype).itemsize
    )
    return F_pad, n_local, bin_dtype, per_shard


def _fit_raw(
    mesh: jax.sharding.Mesh,
    X: np.ndarray,
    y: np.ndarray,
    cfg: GBDTConfig,
    bins: binning.BinnedFeatures | None = None,
    sample_weight: np.ndarray | None = None,
    max_layout_bytes: int | None = None,
):
    """Pad + place the binned cohort on the mesh and run the sharded loop
    (the sorted-layout build itself happens on device, inside the
    ``shard_map`` — the host prep loop it replaces cost more than the whole
    boosting loop at bench scale). Returns the raw replicated device arrays
    ``(feats, thrs, vals, splits, devs)``."""
    assert cfg.max_depth == 1, "sharded trainer covers the depth-1 config"
    if bins is None:
        bins = binning.bin_features(np.asarray(X), gbdt.bin_budget(cfg))
    n_data = mesh.shape[DATA_AXIS]
    n_model = mesh.shape[MODEL_AXIS]
    n, F = bins.binned.shape
    B = int(bins.max_bins)
    F_pad, n_local, bin_dtype, per_shard = _layout_plan(n, F, B, n_data, n_model)
    budget = MAX_LAYOUT_BYTES if max_layout_bytes is None else max_layout_bytes
    if per_shard > budget:
        raise RuntimeError(
            f"stump_trainer: replicated-sorted layout needs {per_shard:,} bytes "
            f"per shard (F={F}, n_local={n_local}, max_bins={B}, "
            f"bin dtype {np.dtype(bin_dtype).name}) > budget {budget:,} bytes. "
            "Add data shards to the mesh, use splitter='hist' (n_bins<=256 "
            "makes bin ids uint8), or route through parallel.hist_trainer "
            "(O(n/S) memory, no sorted layout)."
        )

    import jax.numpy as jnp

    # Device-side padding: rows pad to n_data·n_local with bin B-1 / weight 0
    # (they sort past every boundary and all their sums are masked); feature
    # columns pad to F_pad with constant 0 bins, whose stable argsort is the
    # identity — the "coherent identity-order copy" the padded sort slots
    # need, with +inf thresholds making their candidates permanently invalid.
    n_pad = n_data * n_local
    bj = jnp.asarray(bins.binned).astype(bin_dtype)
    bl_ext = jnp.pad(
        bj, ((0, n_pad - n), (0, 0)), constant_values=np.asarray(B - 1, bin_dtype)
    )
    bl_ext = jnp.pad(bl_ext, ((0, 0), (0, F_pad - F)))
    fdt = np.float64 if jax.config.jax_enable_x64 else np.float32
    # Exact-0/1 labels ride the bins matrix as one extra packed column, so
    # each shard recovers them from the layout's existing row gather
    # instead of a separate scattered gather per sort order (~20% of the
    # layout wall at 10M rows). Host labels are checked here; device
    # labels cost one scalar fetch — still far cheaper than the gather.
    from machine_learning_replications_tpu.ops.histogram import is_binary_labels

    yj = jnp.asarray(y)
    binary_y = bool(is_binary_labels(y if isinstance(y, np.ndarray) else yj))
    if binary_y:
        ybit = jnp.pad((yj > 0.5).astype(bin_dtype), (0, n_pad - n))
        bl_ext = jnp.concatenate([bl_ext, ybit[:, None]], axis=1)
    # Uniform weights + no padding rows ⇒ the weighted machinery is dead
    # code inside the loop (see ``weighted=`` below); don't build and ship
    # a full-length all-ones array the program never reads — at 10M rows
    # that is ~40 MB through a ~17 MB/s host link, per fit. A [n_data]
    # placeholder keeps the sharded operand shape valid at one scalar per
    # shard.
    weighted = not (sample_weight is None and n_pad == n)
    if weighted:
        w_real = (
            jnp.ones(n, fdt) if sample_weight is None
            else jnp.asarray(sample_weight).astype(fdt)
        )
        w_pad = jnp.pad(w_real, (0, n_pad - n))
    else:
        w_pad = jnp.zeros(n_data, fdt)
    thresholds = jnp.pad(
        jnp.asarray(bins.thresholds).astype(fdt), ((0, F_pad - F), (0, 0)),
        constant_values=np.inf,
    )

    def put(a, spec):
        return jax.device_put(a, NamedSharding(mesh, spec))

    if binary_y:
        y_pad = jnp.zeros(n_data, fdt)  # dead operand; labels ride bl_ext
    else:
        y_pad = jnp.pad(yj.astype(fdt), (0, n_pad - n))
    return _fit_sharded(
        mesh,
        put(bl_ext, P(DATA_AXIS, None)),
        put(y_pad, P(DATA_AXIS)),
        put(w_pad, P(DATA_AXIS)),
        put(thresholds, P()),
        n_stages=cfg.n_estimators,
        learning_rate=cfg.learning_rate,
        min_samples_leaf=cfg.min_samples_leaf,
        min_samples_split=cfg.min_samples_split,
        weighted=weighted,
        y_in_bins=binary_y,
    )


def fit(
    mesh: jax.sharding.Mesh,
    X: np.ndarray,
    y: np.ndarray,
    cfg: GBDTConfig = GBDTConfig(),
    bins: binning.BinnedFeatures | None = None,
    sample_weight: np.ndarray | None = None,
    max_layout_bytes: int | None = None,
) -> tuple[TreeEnsembleParams, dict[str, Any]]:
    """Depth-1 GBDT fit sharded over ``mesh`` (axes 'data' × 'model').

    ``sample_weight`` (0/1 fold masks or real weights) rides the padding
    contract — weight-0 rows keep their slots but contribute nothing to any
    reduction — so the stacking CV's masked fold fits run through the same
    program. ``max_layout_bytes`` overrides the per-shard memory guard."""
    if bins is None:
        bins = binning.bin_features(np.asarray(X), gbdt.bin_budget(cfg))
    F = bins.binned.shape[1]
    feats, thrs, vals, splits, devs = _fit_raw(
        mesh, X, y, cfg, bins,
        sample_weight=sample_weight, max_layout_bytes=max_layout_bytes,
    )
    feats = np.asarray(feats)
    # padded feature slots can never be selected; map back is identity on [0, F)
    assert feats.max() < F
    params = gbdt.forest_to_params(
        jnp.asarray(feats),
        jnp.asarray(thrs),
        jnp.asarray(vals),
        jnp.asarray(splits),
        init_raw=gbdt._prior_log_odds(y, sample_weight),
        learning_rate=cfg.learning_rate,
        max_depth=1,
    )
    return params, {"train_deviance": np.asarray(devs)}


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "n_stages", "learning_rate", "min_samples_leaf",
        "min_samples_split", "weighted", "y_in_bins",
    ),
)
def _fit_sharded(
    mesh,
    bl_ext,      # [n_pad, F_pad] bin ids, rows sharded over 'data' (model-
                 #   replicated: every model shard sorts its own column tile)
    y_pad,       # [n_pad] — labels, 0 at padding rows
    w_pad,       # [n_pad] — sample weights, 0 at padding rows
    thresholds,  # [F_pad, B-1] replicated (+inf on padded feature slots)
    *,
    n_stages: int,
    learning_rate: float,
    min_samples_leaf: int,
    min_samples_split: int,
    weighted: bool = True,
    y_in_bins: bool = False,
):
    from jax import shard_map

    Bm1 = thresholds.shape[-1]
    n_model = mesh.shape[MODEL_AXIS]
    F_pad = bl_ext.shape[1] - (1 if y_in_bins else 0)
    F_loc_s = F_pad // n_model

    def local_loop(bl, yl, wl, thr_full):
        # Shapes inside shard_map (one data shard × one model shard):
        #   bl [n_local, F_pad]; yl/wl [n_local]; thr_full [F_pad, B-1]
        dtype = thr_full.dtype
        n_local = bl.shape[0]
        m_idx = jax.lax.axis_index(MODEL_AXIS)
        on0 = m_idx == 0

        # ---- device-side replicated-sorted layout for this shard --------
        # (one-time; the stage loop below touches only dense arrays)
        col0 = m_idx * F_loc_s
        thr = jax.lax.dynamic_slice_in_dim(thr_full, col0, F_loc_s, axis=0)
        cols = jax.lax.dynamic_slice_in_dim(bl, col0, F_loc_s, axis=1)
        order = jnp.argsort(cols, axis=0, stable=True)       # [n_local, F_loc]
        # bx[fq, fs, i] = bl[order[i, fs], fq] — every feature's bins in
        # every local sort order (split routing is a dense compare).
        bx = jnp.transpose(bl[order.T, :], (2, 0, 1))  # [F_pad(+1), F_loc, n]
        if y_in_bins:
            # Labels came along as bl's last column — already in every
            # local sort order via the row gather above.
            ys = bx[F_pad].astype(dtype)                      # [F_loc, n_local]
        else:
            ys = jnp.take_along_axis(
                jnp.broadcast_to(yl[None, :], order.T.shape), order.T, axis=1
            ).astype(dtype)                                   # [F_loc, n_local]
        if weighted:
            ws = jnp.take_along_axis(
                jnp.broadcast_to(wl[None, :], order.T.shape), order.T, axis=1
            ).astype(dtype)
        else:
            # No sample weights and no padding rows (n_pad == n, checked by
            # the caller): the ws layout gather (~17M scattered reads at
            # 10M rows) and the two per-stage [F, n] mask multiplies are
            # pure overhead — every row is real with weight 1.
            ws = None
        # Positional prefix boundaries: #rows with bin ≤ b, from a chunked
        # compare+sum histogram over the UNSORTED local columns — the old
        # sorted-gather + vmapped searchsorted lowered to serialized
        # dynamic gathers (the same pathology ops.binning documents).
        # Padding rows carry bin B-1 > every boundary so they never count;
        # a padded feature slot's constant-0 column gives lc = n_local,
        # which its +inf thresholds make unreachable (valid=False).
        bvals = jnp.arange(Bm1, dtype=cols.dtype)
        lc_mapped, _ = binning.chunked_row_reduce(
            cols,
            lambda cc: jnp.sum(
                cc[:, None, :] <= bvals[None, :, None], axis=0, dtype=jnp.int32
            ),
            pad_value=np.asarray(Bm1, cols.dtype),
        )
        lc = jnp.sum(lc_mapped, axis=0).T.astype(jnp.int32)   # [F_loc, B-1]
        F_loc = F_loc_s
        # NOTE: the stage loop below deliberately keeps a FLAT [F_loc,
        # n_local] carry and pays cumulative_boundary_sums' internal
        # pad+reshape per stage — the block-resident alternative was
        # ablated on v5e in r3: zero runtime gain and an O(n) compile
        # blowup when a large pad+reshape feeds a while loop
        # (docs/SCALING.md "Lowerings"; memory note tpu-stump-loop-floor).
        from machine_learning_replications_tpu.ops import histogram as hist_ops

        def gsum(v):
            """Global Σ over real rows of a per-row [n_local] quantity, taken
            from model shard 0's slot-0 ordering and psum'd over BOTH axes —
            replicated on every shard by construction."""
            return jax.lax.psum(
                jnp.where(on0, jnp.sum(v), 0.0).astype(dtype),
                (DATA_AXIS, MODEL_AXIS),
            )

        if weighted:
            n_real = gsum(ws[0])  # rows are real ⇔ w=1
            sum_y = gsum(ys[0] * ws[0])
        else:
            n_real = gsum(jnp.ones_like(ys[0]))
            sum_y = gsum(ys[0])
        p1 = sum_y / n_real
        f0 = jnp.log(p1 / (1.0 - p1))

        def cumb(v):  # [F_loc, n_local] → global left-of-boundary sums [F_loc, B-1]
            return jax.lax.psum(hist_ops.cumulative_boundary_sums(v, lc), DATA_AXIS)

        if weighted:
            CL = cumb(ws)  # weights don't change: hoisted out of the loop
        else:
            # Unweighted counts are exactly the positional boundaries.
            CL = jax.lax.psum(lc.astype(dtype), DATA_AXIS)

        def stage(t, carry):
            raw, feats, thrs_o, vals, splits, devs = carry  # raw [F_loc, n_local]
            p = jax.scipy.special.expit(raw)
            if weighted:
                g = (ys - p) * ws
                h = p * (1.0 - p) * ws
            else:
                g = ys - p
                h = p * (1.0 - p)
            GL = cumb(g)
            HL = cumb(h)
            GT = gsum(g[0])
            HT = gsum(h[0])
            G2 = gsum(g[0] * g[0])

            # local split scoring over this shard's features
            GR = GT - GL
            CR = n_real - CL
            valid = (
                (CL >= min_samples_leaf)
                & (CR >= min_samples_leaf)
                & jnp.isfinite(thr)
            )
            diff = GL / jnp.maximum(CL, 1) - GR / jnp.maximum(CR, 1)
            proxy = jnp.where(valid, diff * diff * CL * CR, -jnp.inf)
            flat = proxy.reshape(-1)
            best_local = jnp.argmax(flat).astype(jnp.int32)
            best_gain = flat[best_local]
            # global best across the model axis (tie → lower shard index, which
            # preserves first-feature-in-order tie-breaking)
            gains = jax.lax.all_gather(best_gain, MODEL_AXIS)          # [M]
            locs = jax.lax.all_gather(best_local, MODEL_AXIS)          # [M]
            winner = jnp.argmax(gains).astype(jnp.int32)
            w_loc = locs[winner]
            f_local = w_loc // Bm1
            bstar = w_loc % Bm1
            fstar = (winner * F_loc + f_local).astype(jnp.int32)       # global feature id

            # gather the winning boundary stats (every shard recomputes from
            # its replicated GL/HL? GL is sharded by feature — all_gather the
            # single winning row's scalars instead)
            on_winner = winner == m_idx
            sel = jnp.where(on_winner, 1.0, 0.0).astype(dtype)
            num_l = jax.lax.psum(GL[f_local, bstar] * sel, MODEL_AXIS)
            den_l = jax.lax.psum(HL[f_local, bstar] * sel, MODEL_AXIS)
            # thr can be +inf off-winner; inf·0 = NaN, so mask before the psum
            thr_star = jax.lax.psum(
                jnp.where(on_winner, thr[f_local, bstar], 0.0), MODEL_AXIS
            )
            gain_star = gains[winner]
            num_r, den_r = GT - num_l, HT - den_l

            mean = GT / jnp.maximum(n_real, 1)
            impurity = jnp.maximum(G2 / jnp.maximum(n_real, 1) - mean * mean, 0.0)
            do = (
                (n_real >= min_samples_split)
                & (impurity > IMPURITY_EPS)
                & jnp.isfinite(gain_star)
            )

            v_root = newton_leaf_value(GT, HT)
            v_l = newton_leaf_value(num_l, den_l)
            v_r = newton_leaf_value(num_r, den_r)

            split_bins = jax.lax.dynamic_index_in_dim(
                bx, fstar, axis=0, keepdims=False
            )  # [F_loc, n_local]
            go_left = split_bins <= bstar.astype(split_bins.dtype)
            contrib = jnp.where(do, jnp.where(go_left, v_l, v_r), v_root)
            raw = raw + learning_rate * contrib

            ll_terms = ys[0] * raw[0] - jnp.logaddexp(0.0, raw[0])
            ll = gsum(ll_terms * ws[0] if weighted else ll_terms)
            dev = -2.0 * ll / n_real

            feat_t = jnp.where(do, fstar, 0) * jnp.array([1, 0, 0], jnp.int32)
            thr_t = jnp.stack(
                [jnp.where(do, thr_star, jnp.inf),
                 jnp.asarray(jnp.inf, dtype), jnp.asarray(jnp.inf, dtype)]
            )
            val_t = jnp.stack(
                [jnp.where(do, 0.0, v_root),
                 jnp.where(do, v_l, 0.0), jnp.where(do, v_r, 0.0)]
            ).astype(dtype)
            split_t = jnp.stack([do, jnp.array(False), jnp.array(False)])
            return (
                raw,
                feats.at[t].set(feat_t),
                thrs_o.at[t].set(thr_t),
                vals.at[t].set(val_t),
                splits.at[t].set(split_t),
                devs.at[t].set(dev),
            )

        init = (
            jnp.full((F_loc, n_local), f0, dtype),
            jnp.zeros((n_stages, 3), jnp.int32),
            jnp.full((n_stages, 3), jnp.inf, dtype),
            jnp.zeros((n_stages, 3), dtype),
            jnp.zeros((n_stages, 3), bool),
            jnp.zeros(n_stages, dtype),
        )
        _, feats, thrs_o, vals, splits, devs = jax.lax.fori_loop(
            0, n_stages, stage, init
        )
        # identical on every shard (computed from psum'd quantities)
        return feats, thrs_o, vals, splits, devs

    feats, thrs_o, vals, splits, devs = shard_map(
        local_loop,
        mesh=mesh,
        in_specs=(
            P(DATA_AXIS, None),
            P(DATA_AXIS),
            P(DATA_AXIS),
            P(),
        ),
        out_specs=(P(), P(), P(), P(), P()),
        check_vma=False,
    )(bl_ext, y_pad, w_pad, thresholds)
    return feats, thrs_o, vals, splits, devs
