"""Distributed level-wise GBDT training (any depth) over the data axis.

The sharded counterpart of ``models.gbdt._fit_binned`` (SURVEY.md §2.5
"histogram partials all-reduced over ICI"): rows are sharded contiguously
over the mesh's 'data' axis; each boosting stage grows its tree
level-synchronously —

  1. every shard builds per-(node, feature, bin) histograms from its local
     rows (the Pallas MXU kernel on TPU, XLA segment_sum elsewhere);
  2. one ``psum`` over 'data' replicates the global histograms
     (``[K, F, B]·4`` floats — the only per-level communication);
  3. every shard runs the identical friedman split selection and routes its
     own rows to child nodes.

Leaf Newton values come from a psum'd segment-sum over final node ids, and
the deviance from psum'd log-likelihood partials — nothing crosses the host
boundary inside the stage loop. The 'model' axis is left replicated here
(feature tiling pays off only in the depth-1 stump trainer's per-tile
histogram/scoring split — ``stump_trainer``); outputs are replicated on
every shard by construction.

Padding contract: rows appended to even out shards carry weight 0 and node
−1 forever; their gradients are zeroed so every reduction ignores them.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from machine_learning_replications_tpu.config import GBDTConfig
from machine_learning_replications_tpu.models import gbdt
from machine_learning_replications_tpu.models.tree import TreeEnsembleParams
from machine_learning_replications_tpu.ops import binning
from machine_learning_replications_tpu.parallel.mesh import DATA_AXIS


def fit(
    mesh: jax.sharding.Mesh,
    X: np.ndarray,
    y: np.ndarray,
    cfg: GBDTConfig = GBDTConfig(),
    bins: binning.BinnedFeatures | None = None,
    sample_weight: np.ndarray | None = None,
) -> tuple[TreeEnsembleParams, dict[str, Any]]:
    """GBDT fit of any depth with rows sharded over ``mesh``'s 'data' axis.

    ``sample_weight`` (0/1 fold masks or real weights) rides the padding
    contract: weight-0 rows are parked at node −1 with zero gradient, so a
    masked fold fit is the same program as a full fit — this is how the
    stacking CV's fold fits run under the mesh (VERDICT r2 item 5)."""
    if bins is None:
        bins = binning.bin_features(np.asarray(X), gbdt.bin_budget(cfg))
    n_data = mesh.shape[DATA_AXIS]
    n = bins.binned.shape[0]
    n_pad = -(-n // n_data) * n_data
    fdt = np.float64 if jax.config.jax_enable_x64 else np.float32

    # Padding on device: bins.binned may already live there (device binning
    # in the scaled regime) — jnp.pad avoids a device→host→device bounce.
    binned = jnp.pad(
        jnp.asarray(bins.binned).astype(jnp.int32), ((0, n_pad - n), (0, 0))
    )
    w_real = (
        jnp.ones(n, fdt) if sample_weight is None
        else jnp.asarray(sample_weight).astype(fdt)
    )
    w = jnp.pad(w_real, (0, n_pad - n))
    yp = jnp.pad(jnp.asarray(y).astype(fdt), (0, n_pad - n))

    def put(a, spec):
        return jax.device_put(a, NamedSharding(mesh, spec))

    feats, thrs, vals, splits, devs = _fit_sharded(
        mesh,
        put(binned, P(DATA_AXIS, None)),
        put(w, P(DATA_AXIS)),
        put(yp, P(DATA_AXIS)),
        put(jnp.asarray(bins.thresholds).astype(fdt), P()),
        n_stages=cfg.n_estimators,
        depth=cfg.max_depth,
        max_bins=bins.max_bins,
        learning_rate=cfg.learning_rate,
        min_samples_split=cfg.min_samples_split,
        min_samples_leaf=cfg.min_samples_leaf,
        backend=gbdt.resolve_backend(cfg),
        feature_bins=binning.feature_bin_counts(bins),
    )
    params = gbdt.forest_to_params(
        feats, thrs, vals, splits,
        init_raw=gbdt._prior_log_odds(y, sample_weight),
        learning_rate=cfg.learning_rate,
        max_depth=cfg.max_depth,
    )
    return params, {"train_deviance": np.asarray(devs)}


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "n_stages", "depth", "max_bins", "learning_rate",
        "min_samples_split", "min_samples_leaf", "backend", "feature_bins",
    ),
)
def _fit_sharded(
    mesh,
    binned,      # [n_pad, F] int32, sharded over 'data'
    w,           # [n_pad] — 1 real / 0 padding
    y,           # [n_pad]
    thresholds,  # [F, B-1] replicated
    *,
    n_stages: int,
    depth: int,
    max_bins: int,
    learning_rate: float,
    min_samples_split: int,
    min_samples_leaf: int,
    backend: str,
    feature_bins: tuple[int, ...] | None = None,
):
    from jax import shard_map

    NN = 2 ** (depth + 1) - 1

    def local_loop(bl, wl, yl, thr):
        n_loc, F = bl.shape
        dtype = thr.dtype

        def gsum(v):
            return jax.lax.psum(jnp.sum(v), DATA_AXIS)

        n_real = gsum(wl)
        p1 = gsum(yl * wl) / n_real
        f0 = jnp.log(p1 / (1.0 - p1))

        # One copy of the growth algorithm (models.gbdt.make_tree_grower);
        # sharding enters only through reduce_fn and the −1-parked padding.
        grow_tree = gbdt.make_tree_grower(
            bl, thr,
            depth=depth, max_bins=max_bins,
            min_samples_split=min_samples_split,
            min_samples_leaf=min_samples_leaf,
            hist_fn=gbdt.resolve_hist_fn(backend, feature_bins),
            node_init=jnp.where(wl > 0, 0, -1).astype(jnp.int32),
            reduce_fn=lambda a: jax.lax.psum(a, DATA_AXIS),
        )

        def stage(t, carry):
            raw, feats, thrs_o, vals, splits, devs = carry
            p = jax.scipy.special.expit(raw)
            g = (yl - p) * wl
            h = p * (1.0 - p) * wl
            feat_t, thr_t, val_t, split_t, node = grow_tree(g, h)
            raw = raw + learning_rate * val_t[jnp.maximum(node, 0)] * wl
            ll = gsum((yl * raw - jnp.logaddexp(0.0, raw)) * wl)
            dev = -2.0 * ll / n_real
            return (
                raw,
                feats.at[t].set(feat_t),
                thrs_o.at[t].set(thr_t),
                vals.at[t].set(val_t),
                splits.at[t].set(split_t),
                devs.at[t].set(dev),
            )

        init = (
            jnp.full(n_loc, f0, dtype),
            jnp.zeros((n_stages, NN), jnp.int32),
            jnp.full((n_stages, NN), jnp.inf, dtype),
            jnp.zeros((n_stages, NN), dtype),
            jnp.zeros((n_stages, NN), bool),
            jnp.zeros(n_stages, dtype),
        )
        _, feats, thrs_o, vals, splits, devs = jax.lax.fori_loop(
            0, n_stages, stage, init
        )
        return feats, thrs_o, vals, splits, devs

    return shard_map(
        local_loop,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(DATA_AXIS), P()),
        out_specs=(P(), P(), P(), P(), P()),
        check_vma=False,
    )(binned, w, y, thresholds)
