"""Parallelism & communication (SURVEY.md §2.5 — all ABSENT in the reference).

The reference is single-process/single-threaded; its implicit parallel axes
(cohort rows, boosting-stage histogram work, feature tiles in split search,
CV folds, ensemble members) are promoted here to first-class mesh axes:

  data  — rows sharded across chips; histogram/metric partials psum over ICI
  model — feature/bin tiles of the split search; fold/member fan-out

Communication is whatever XLA emits for the collectives (`psum`,
`all_gather`, ...) over ICI within a slice and DCN across slices — no
NCCL/MPI analogue is hand-rolled. Multi-host bring-up goes through
``distributed.initialize_distributed``.
"""

from machine_learning_replications_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    make_mesh,
    single_device_mesh,
)
from machine_learning_replications_tpu.parallel import (
    distributed,
    hist_trainer,
    stump_trainer,
)


def fit_gbdt_sharded(mesh, X, y, cfg):
    """Mesh-sharded GBDT fit, dispatching like ``models.gbdt.fit``: the
    replicated-sorted stump trainer at depth 1 (sklearn-exact splits, rows
    over 'data', feature tiles over 'model'), the level-wise histogram
    trainer otherwise (per-level psum'd partials). Returns (params, aux)."""
    if cfg.max_depth == 1 and cfg.splitter == "exact":
        return stump_trainer.fit(mesh, X, y, cfg)
    return hist_trainer.fit(mesh, X, y, cfg)


__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "make_mesh",
    "single_device_mesh",
    "distributed",
    "fit_gbdt_sharded",
    "hist_trainer",
    "stump_trainer",
]
