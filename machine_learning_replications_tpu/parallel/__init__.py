"""Parallelism & communication (SURVEY.md §2.5 — all ABSENT in the reference).

The reference is single-process/single-threaded; its implicit parallel axes
(cohort rows, boosting-stage histogram work, feature tiles in split search,
CV folds, ensemble members) are promoted here to first-class mesh axes:

  data  — rows sharded across chips; histogram/metric partials psum over ICI
  model — feature/bin tiles of the split search; fold/member fan-out

Communication is whatever XLA emits for the collectives (`psum`,
`all_gather`, ...) over ICI within a slice and DCN across slices — no
NCCL/MPI analogue is hand-rolled. Multi-host bring-up goes through
``distributed.initialize_distributed``.
"""

from machine_learning_replications_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    make_mesh,
    single_device_mesh,
)
from machine_learning_replications_tpu.parallel import (
    distributed,
    hist_trainer,
    stump_trainer,
)


def fit_gbdt_sharded(mesh, X, y, cfg, sample_weight=None, bins=None):
    """Mesh-sharded GBDT fit, dispatching like ``models.gbdt.fit``: the
    histogram stump trainer at depth 1 (rows over 'data', feature tiles
    over 'model' — per-stage grad/hess histogram partials psum'd over
    ICI), the level-wise histogram trainer at depth ≥ 2 (per-level psum'd
    partials), or as the depth-1 fallback when the stump trainer's
    per-shard working set would blow the memory budget (rare since the
    r5 reformulation — the guard covers pathological meshes).
    Returns (params, aux)."""
    from machine_learning_replications_tpu.models import gbdt as _gbdt

    if bins is None:
        bins = _gbdt.default_bins(X, cfg)
    if cfg.max_depth == 1:
        n, F = bins.binned.shape
        _, _, _, per_shard = stump_trainer._layout_plan(
            n, F, int(bins.max_bins),
            mesh.shape[DATA_AXIS], mesh.shape[MODEL_AXIS],
        )
        if per_shard <= stump_trainer.MAX_LAYOUT_BYTES:
            return stump_trainer.fit(
                mesh, X, y, cfg, bins=bins, sample_weight=sample_weight
            )
    return hist_trainer.fit(mesh, X, y, cfg, bins=bins, sample_weight=sample_weight)


__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "make_mesh",
    "single_device_mesh",
    "distributed",
    "fit_gbdt_sharded",
    "hist_trainer",
    "stump_trainer",
]
