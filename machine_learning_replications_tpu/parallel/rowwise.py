"""Row-sharded application of pure per-row functions.

The cohort's row axis is the framework's universal parallel dimension
(SURVEY.md §2.5 "Rows of the cohort … all fits/predicts"): imputation of a
query block, batch prediction of any fitted member, and the stacked
ensemble's probability pass are all embarrassingly row-parallel. This module
is the one implementation of that pattern: pad the row axis to a multiple of
the mesh's 'data' axis, ``device_put`` with ``NamedSharding(P('data', …))``,
replicate the (small) parameter pytree, and let GSPMD partition the jitted
computation — no collectives are needed because nothing crosses rows.

Chunking bounds device memory for O(rows · donors/support) intermediates
(the imputer's distance matrix, the SVC kernel block): each chunk shares one
static shape, so the whole loop reuses a single compiled program.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from machine_learning_replications_tpu.parallel.mesh import DATA_AXIS


# One jitted wrapper per fn object, so repeated apply_rows_sharded calls
# (batch prediction in a loop, chunked transforms) reuse the compiled
# program instead of re-tracing each call. Bounded LRU rather than weak
# keys: a jit wrapper strongly references its fn, so weak-key entries
# could never be collected — the LRU instead evicts old wrappers (and
# whatever their closures captured) once fresh-lambda callers exceed the
# cap.
@functools.lru_cache(maxsize=32)
def _jitted_cached(fn: Callable) -> Callable:
    return jax.jit(fn)


def _jitted(fn: Callable) -> Callable:
    try:
        return _jitted_cached(fn)
    except TypeError:  # unhashable callable
        return jax.jit(fn)


def replicate(mesh: jax.sharding.Mesh, params: Any) -> Any:
    """Copy a parameter pytree onto every device of ``mesh`` (fully
    replicated sharding), so sharded-row computations can close over it
    without device-mismatch errors."""
    return jax.device_put(params, NamedSharding(mesh, P()))


def apply_rows_sharded(
    mesh: jax.sharding.Mesh,
    fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
    params: Any,
    X: np.ndarray,
    *,
    chunk_rows: int | None = None,
    pad_value: float = 0.0,
) -> jnp.ndarray:
    """``fn(params, X_block)`` with rows of ``X`` sharded over 'data'.

    ``fn`` must be pure and row-wise (row i of the output depends only on
    row i of ``X`` and on ``params``); its output's leading axis must match
    the block's. Padding rows (``pad_value``) flow through ``fn`` and are
    sliced off, so ``fn`` must tolerate them without poisoning real rows —
    true for any row-wise map.

    ``chunk_rows`` caps the rows per compiled call (rounded up to a multiple
    of the data-axis size so every shard stays equal); None processes all
    rows in one call.
    """
    X_np = np.asarray(X)
    n = X_np.shape[0]
    S = mesh.shape[DATA_AXIS]
    chunk = n if chunk_rows is None else min(chunk_rows, n)
    chunk = max(-(-chunk // S) * S, S)
    spec = P(DATA_AXIS, *([None] * (X_np.ndim - 1)))
    sharding = NamedSharding(mesh, spec)
    params_r = replicate(mesh, params)
    jfn = _jitted(fn)

    outs = []
    for s in range(0, n, chunk):
        block = X_np[s : s + chunk]
        real = block.shape[0]
        if real < chunk:  # tail: pad so every block shares one shape
            pad = np.full((chunk - real,) + X_np.shape[1:], pad_value, X_np.dtype)
            block = np.concatenate([block, pad])
        out = jfn(params_r, jax.device_put(block, sharding))
        if n <= chunk:  # single block: stay on device
            return out[:real]
        outs.append(np.asarray(out)[:real])
    return jnp.asarray(np.concatenate(outs, axis=0))
