"""Multi-host bring-up — the distributed communication backend (SURVEY.md §5).

The reference has no communication layer at all (§2.5: no NCCL/MPI/Gloo,
single process). The TPU-native equivalent is JAX's built-in runtime:
``jax.distributed.initialize`` connects the hosts of a pod slice (or
several slices over DCN), after which ``jax.devices()`` spans every chip
and the framework's meshes/collectives (``mesh.make_mesh`` + psum/
all_gather inside ``shard_map``) ride ICI within a slice and DCN across
slices — XLA emits the transport; nothing NCCL-like is hand-rolled here.

On a single host this module is a no-op: every entry point degrades to
local devices.
"""

from __future__ import annotations

import os

import jax

from machine_learning_replications_tpu.parallel.mesh import make_mesh

_initialized = False


def initialize_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    *,
    auto: bool = True,
) -> bool:
    """Connect this host to the distributed runtime.

    Explicit arguments default to the standard env vars
    (``JAX_COORDINATOR_ADDRESS``, ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``).
    When neither arguments nor env vars are present and ``auto`` is True,
    ``jax.distributed.initialize()`` is attempted with no arguments — the
    Cloud-TPU-pod path, where the runtime discovers all three from TPU
    metadata; a machine with no cluster environment fails that probe and
    degrades to the single-host no-op. Returns True when a multi-process
    runtime was brought up, False for the no-op. Safe to call twice.
    """
    global _initialized
    if _initialized:
        return True
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    num_processes = num_processes if num_processes is not None else (
        int(env_np) if env_np else None
    )
    env_pid = os.environ.get("JAX_PROCESS_ID")
    process_id = process_id if process_id is not None else (
        int(env_pid) if env_pid else None
    )
    if coordinator_address is None and num_processes is None:
        if not auto:
            return False
        try:
            jax.distributed.initialize()  # cluster auto-detection
        except (RuntimeError, ValueError):
            return False  # no cluster environment: single-host no-op
        _initialized = True
        return True
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    return True


def global_mesh(data: int | None = None, model: int = 1):
    """A mesh over every device the runtime can see (all hosts after
    ``initialize_distributed``; the local chip(s) otherwise)."""
    return make_mesh(data=data, model=model, devices=jax.devices())


def process_info() -> tuple[int, int]:
    """(process_index, process_count) of this host."""
    return jax.process_index(), jax.process_count()
