"""Row-sharded sufficient statistics for LassoCV feature selection.

The covariance-form LassoCV (``models.solvers.lasso_cv_from_stats``) needs
only per-test-fold second-order statistics — Σ x xᵀ [F, F], Σ x y [F], and
scalars — so scaling feature selection to the full sharded cohort
(reference: ``train_ensemble_public.py:51-55`` runs LassoCV over every row)
is one ``shard_map``: each device contracts its local row block against the
fold-membership masks of its *global* row range, and a single ``psum`` over
the 'data' axis replicates the [K, F, F] statistics everywhere. No
collective ever carries more than K·F² floats; the CV path solve that
follows is row-free.

This is the same stats → replicated-solve split the stump and histogram
trainers use (SURVEY.md §2.5 "Rows of the cohort … all fits"), applied to
the selection stage — the one fit that previously had no sharded path
(VERDICT r3 missing #2).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from machine_learning_replications_tpu.models import solvers
from machine_learning_replications_tpu.parallel.mesh import DATA_AXIS


def lasso_fold_stats_sharded(
    mesh: jax.sharding.Mesh,
    X,               # [n, F] host or device array
    y,               # [n]
    cv_folds: int,
) -> dict:
    """Per-TEST-fold statistics with rows sharded over 'data' — output
    identical (up to float reassociation) to ``solvers.lasso_fold_stats``.

    Rows are padded to a multiple of the data-axis size; padding rows fall
    outside every fold's [start, end) global-index window, so they
    contribute zero to every statistic by construction.
    """
    fdt = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    X = jnp.asarray(X).astype(fdt)
    y = jnp.asarray(y).astype(fdt)
    n = X.shape[0]
    n_data = mesh.shape[DATA_AXIS]
    n_pad = -(-n // n_data) * n_data
    Xp = jnp.pad(X, ((0, n_pad - n), (0, 0)))
    yp = jnp.pad(y, (0, n_pad - n))

    bounds = solvers.fold_bounds(n, cv_folds)
    starts = tuple(s for s, _ in bounds)
    ends = tuple(e for _, e in bounds)

    def put(a, spec):
        return jax.device_put(a, NamedSharding(mesh, spec))

    return _stats_sharded(
        mesh,
        put(Xp, P(DATA_AXIS, None)),
        put(yp, P(DATA_AXIS)),
        starts=starts,
        ends=ends,
    )


@functools.partial(jax.jit, static_argnames=("mesh", "starts", "ends"))
def _stats_sharded(mesh, Xp, yp, *, starts: tuple, ends: tuple):
    from jax import shard_map

    n = ends[-1]  # real (unpadded) row count — static
    # Global mean shift before accumulating Grams — the f32 cancellation
    # guard (see solvers.lasso_fold_stats). Padding rows are zero, so the
    # sums are exact; after shifting they become −mu, but the fold masks
    # below exclude them by global index. GSPMD inserts the cross-device
    # reduction for these sums automatically.
    mu = jnp.sum(Xp, axis=0) / n
    nu = jnp.sum(yp) / n
    Xp = Xp - mu
    yp = yp - nu

    starts_a = jnp.asarray(np.array(starts), jnp.int32)
    ends_a = jnp.asarray(np.array(ends), jnp.int32)

    def local_stats(Xl, yl, st, en):
        n_loc = Xl.shape[0]
        offset = jax.lax.axis_index(DATA_AXIS) * n_loc
        gidx = offset + jnp.arange(n_loc, dtype=jnp.int32)
        # [K, n_loc] fold membership of this device's global row range;
        # padding rows (gidx >= n = ends[-1]) are in no fold.
        mask = (
            (gidx[None, :] >= st[:, None]) & (gidx[None, :] < en[:, None])
        ).astype(Xl.dtype)

        def ps(a):
            return jax.lax.psum(a, DATA_AXIS)

        my = mask * yl[None, :]                       # [K, n_loc]
        return {
            "sxx": ps(jnp.einsum("kn,nf,ng->kfg", mask, Xl, Xl)),
            "sx": ps(mask @ Xl),                      # [K, F]
            "sxy": ps(my @ Xl),                       # [K, F]
            "sy": ps(jnp.sum(my, axis=1)),            # [K]
            "syy": ps(my @ yl),                       # [K]
            "m": ps(jnp.sum(mask, axis=1)),           # [K]
        }

    stats = shard_map(
        local_stats,
        mesh=mesh,
        in_specs=(P(DATA_AXIS, None), P(DATA_AXIS), P(), P()),
        out_specs={k: P() for k in ("sxx", "sx", "sxy", "sy", "syy", "m")},
        check_vma=False,
    )(Xp, yp, starts_a, ends_a)
    stats["mu"] = mu
    stats["nu"] = nu
    return stats
