"""Environment hygiene for the flaky ambient TPU plugin — shared, jax-free.

The driver machine's sitecustomize registers an 'axon' TPU backend in every
interpreter whose env carries ``PALLAS_AXON_POOL_IPS``, and that plugin can
hang forever at ``import jax`` / backend init (VERDICT.md round 1). This
module is the single copy of the two defenses every entry point needs, and
deliberately imports nothing heavy so the orchestrating processes
(``bench.py``, ``__graft_entry__``) can use it without touching jax:

  * ``clean_cpu_env`` — a child env the sitecustomize cannot wedge;
  * ``force_host_device_flag`` — XLA_FLAGS surgery for an N-device CPU mesh.
"""

from __future__ import annotations

import os

# The sitecustomize's guard variable (its first line checks this) and the
# PYTHONPATH entry that makes Python find it.
_PLUGIN_GUARD_VAR = "PALLAS_AXON_POOL_IPS"


def force_host_device_flag(flags: str, n_devices: int) -> str:
    """Return ``flags`` with exactly one
    ``--xla_force_host_platform_device_count=n_devices`` (read by jax's CPU
    backend at init time, so setting it pre-init is sufficient even when jax
    is already imported)."""
    parts = [
        p for p in flags.split()
        if "xla_force_host_platform_device_count" not in p
    ]
    parts.append(f"--xla_force_host_platform_device_count={n_devices}")
    return " ".join(parts)


def clean_cpu_env(n_devices: int | None = None) -> dict:
    """A child-process env in which ``import jax`` cannot hang: the plugin
    guard variable is stripped (sitecustomize no-ops), the sitecustomize's
    PYTHONPATH entry is dropped, and JAX_PLATFORMS pins the CPU backend.
    With ``n_devices``, also forces an N-device virtual CPU mesh."""
    env = dict(os.environ)
    env.pop(_PLUGIN_GUARD_VAR, None)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        env["XLA_FLAGS"] = force_host_device_flag(env.get("XLA_FLAGS", ""), n_devices)
    return env
