"""End-to-end training orchestration — the reference's whole program.

``train_ensemble_public.py`` __main__ (SURVEY.md §3.1): load → KNN-impute →
LassoCV-select 17 of 64 → fit the stacking ensemble → evaluate. This module
is that pipeline as explicit functional stages over parameter pytrees.

Stacking fit replicates ``StackingClassifier.fit`` (SURVEY.md §3.2): each
base member is fitted once on the full data (those become the predict-time
members), and 5-fold stratified ``cross_val_predict`` produces out-of-fold
P(class 1) meta-features on which the final LR is trained. Fold fits
currently run as a host-side loop with per-fold row subsets (two compiled
shapes — fold sizes differ by ≤1 row); inside each SVC fit, the Platt CV
sub-solves are vmapped. Fully vmapping the member-level fan-out is tracked
as a TPU optimization, not done here.
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax.numpy as jnp
import numpy as np

from machine_learning_replications_tpu.config import ExperimentConfig
from machine_learning_replications_tpu.models import (
    feature_selection,
    gbdt,
    knn_impute,
    linear,
    scaler,
    solvers,
    stacking,
    svm,
    tree,
)
from machine_learning_replications_tpu.utils.cv import stratified_kfold_test_masks


@flax.struct.dataclass
class PipelineParams:
    """Everything needed to go from a raw 64-feature row to a probability."""

    imputer: knn_impute.KNNImputerParams
    support_mask: jnp.ndarray  # [64] bool — Lasso-selected features
    ensemble: stacking.StackingParams


def fit_stacking(
    X: np.ndarray, y: np.ndarray, cfg: ExperimentConfig = ExperimentConfig()
) -> stacking.StackingParams:
    """Fit the stacking ensemble on (already imputed + selected) ``X[n, 17]``."""
    Xj = jnp.asarray(X)
    yj = jnp.asarray(y)

    # --- full-data member fits (the predict-time estimators_) -------------
    scaler_p = scaler.fit(Xj)
    svc_p = svm.svc_fit(
        scaler.transform(scaler_p, Xj),
        yj,
        C=cfg.svc.C,
        gamma=None if cfg.svc.gamma == "scale" else cfg.svc.gamma,
        balanced=cfg.svc.class_weight == "balanced",
        probability=cfg.svc.probability,
        platt_cv=cfg.svc.platt_cv,
    )
    gbdt_p, _ = gbdt.fit(np.asarray(X), np.asarray(y), cfg.gbdt)
    lg_p = solvers.logreg_l1_fit(
        Xj, yj, C=cfg.logreg.C, balanced=cfg.logreg.class_weight == "balanced"
    )

    # --- cross_val_predict meta-features ----------------------------------
    meta_X = cross_val_member_probas(X, y, cfg)

    meta_p = solvers.logreg_l2_fit(jnp.asarray(meta_X), yj, C=cfg.meta.C)

    return stacking.StackingParams(
        scaler=scaler_p, svc=svc_p, gbdt=gbdt_p, logreg=lg_p, meta=meta_p
    )


def cross_val_member_probas(
    X: np.ndarray, y: np.ndarray, cfg: ExperimentConfig
) -> np.ndarray:
    """Out-of-fold P(class 1) per member — the ``[n, 3]`` meta-feature matrix
    (sklearn: ``cross_val_predict(est, X, y, cv=5, method='predict_proba')``
    per member, first column dropped)."""
    X = np.asarray(X)
    y = np.asarray(y)
    n = X.shape[0]
    test_masks = stratified_kfold_test_masks(y, cfg.stacking.cv_folds)
    meta = np.zeros((n, 3))
    for tm in test_masks:
        tr = tm < 0.5
        te = ~tr
        Xtr, ytr, Xte = X[tr], y[tr], X[te]
        # svc pipeline (scaler refit per fold, as sklearn clones the Pipeline)
        sp = scaler.fit(jnp.asarray(Xtr))
        vp = svm.svc_fit(
            scaler.transform(sp, jnp.asarray(Xtr)),
            jnp.asarray(ytr),
            C=cfg.svc.C,
            gamma=None if cfg.svc.gamma == "scale" else cfg.svc.gamma,
            balanced=cfg.svc.class_weight == "balanced",
            probability=True,
            platt_cv=cfg.svc.platt_cv,
        )
        meta[te, 0] = np.asarray(
            svm.predict_proba1(vp, scaler.transform(sp, jnp.asarray(Xte)))
        )
        # gbdt
        gp, _ = gbdt.fit(Xtr, ytr, cfg.gbdt)
        meta[te, 1] = np.asarray(tree.predict_proba1(gp, jnp.asarray(Xte)))
        # l1 logreg
        lp = solvers.logreg_l1_fit(
            jnp.asarray(Xtr), jnp.asarray(ytr), C=cfg.logreg.C,
            balanced=cfg.logreg.class_weight == "balanced",
        )
        meta[te, 2] = np.asarray(linear.predict_proba1(lp, jnp.asarray(Xte)))
    return meta


def fit_pipeline(
    X64: np.ndarray, y: np.ndarray, cfg: ExperimentConfig = ExperimentConfig()
) -> tuple[PipelineParams, dict[str, Any]]:
    """The full reference program: impute → select → stack.

    ``X64`` is the raw 64-variable cohort (NaNs allowed); returns fitted
    params plus selection diagnostics.
    """
    imp_p, X_imp = knn_impute.fit_transform(jnp.asarray(X64))
    X_imp = np.asarray(X_imp)
    mask, info = feature_selection.fit_select(X_imp, y, cfg.select)
    ens = fit_stacking(X_imp[:, mask], y, cfg)
    return (
        PipelineParams(
            imputer=imp_p, support_mask=jnp.asarray(mask), ensemble=ens
        ),
        {"selection": info, "n_selected": int(mask.sum())},
    )


def pipeline_predict_proba1(params: PipelineParams, X64: np.ndarray) -> jnp.ndarray:
    """Raw 64-feature rows (NaNs allowed) → stacked P(class 1)."""
    X_imp = knn_impute.transform(params.imputer, jnp.asarray(X64))
    mask = np.asarray(params.support_mask)
    X17 = X_imp[:, np.where(mask)[0]]
    return stacking.predict_proba1(params.ensemble, X17)
