"""End-to-end training orchestration — the reference's whole program.

``train_ensemble_public.py`` __main__ (SURVEY.md §3.1): load → KNN-impute →
LassoCV-select 17 of 64 → fit the stacking ensemble → evaluate. This module
is that pipeline as explicit functional stages over parameter pytrees.

Stacking fit replicates ``StackingClassifier.fit`` (SURVEY.md §3.2): each
base member is fitted once on the full data (those become the predict-time
members), and 5-fold stratified ``cross_val_predict`` produces out-of-fold
P(class 1) meta-features on which the final LR is trained.

The fold fan-out is vmapped (SURVEY.md §3.2: the reference's 6× member
refit is "embarrassingly parallel — in the reference it is strictly
sequential"): all k fold fits of a member compile to ONE XLA program
over ``[k, n]`` row masks — masked SVC duals (``svm.svc_fit_masked``),
mask-parked GBDT growth (``gbdt.fit_folds``), masked FISTA L1-LR — so
fold parallelism is batch parallelism the hardware already exploits.
``cross_val_member_probas_loop`` keeps the sequential per-fold-subset
construction as the differential oracle (tests prove the vmapped path
matches it).
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax.numpy as jnp
import numpy as np

from machine_learning_replications_tpu.config import ExperimentConfig
from machine_learning_replications_tpu.models import (
    feature_selection,
    gbdt,
    knn_impute,
    linear,
    scaler,
    solvers,
    stacking,
    svm,
    tree,
)
from machine_learning_replications_tpu.utils.cv import (
    stratified_kfold_test_masks,
    stratified_kfold_test_masks_within,
)


@flax.struct.dataclass
class PipelineParams:
    """Everything needed to go from a raw 64-feature row to a probability.

    ``quality`` is the model's training-time reference profile
    (``obs.quality.build_reference_profile`` — per-feature histograms/
    moments/quantiles over the post-impute post-select ``X[n, 17]`` plus
    the training score distribution), a plain dict-of-arrays pytree so the
    checkpoint sidecar carries it with no new registry class. It defaults
    to ``None`` so checkpoints written before the profile existed restore
    into the same class (``persist.orbax_io`` journals the gap); serving
    disables quality monitoring when it is absent."""

    imputer: knn_impute.KNNImputerParams
    support_mask: jnp.ndarray  # [64] bool — Lasso-selected features
    ensemble: stacking.StackingParams
    quality: Any = None  # dict[str, array] reference profile, or None


class _NullStages:
    """Stage runner used when no checkpoint dir is given: straight through,
    with per-stage progress/span/journal telemetry via the shared
    ``obs.journal.stage_scope`` (the one code path both this runner and the
    checkpointed ``persist.orbax_io.StageCheckpointer`` report through —
    see its docstring for the stderr-line contract and the opt-out)."""

    def run(self, name: str, compute):
        from machine_learning_replications_tpu.obs.journal import stage_scope

        # Block on device completion before the stage clock stops (the
        # span exit blocks on work registered via the handle): jitted
        # stage outputs dispatch asynchronously, and unblocked timing
        # would attribute a stage's device work to the NEXT stage's first
        # data-dependent op — the opposite of what this line is for.
        with stage_scope(name) as sp:
            out = sp.block(compute())
        return out


# Memory budget for running SVC fold fits as vmapped lanes: each lane
# materializes its own [m, m] kernel AND dual matrix, so k lanes cost
# ~2·k·m²·itemsize at once (a 20k-row cap × 5 folds measured as a ~16 GB
# on-chip OOM). At the shipped SVCConfig.max_rows=8192 and k=5, f32, the
# lanes total ~2.7 GB — still above this budget, so the scaled regime
# deliberately takes the sequential lax.map branch (one lane's ~0.5 GB at
# a time); the vmapped branch serves the small-n regime (reference-cohort
# sizes), where lane fan-out is the latency win.
_SVC_VMAP_BYTES_BUDGET = 2 << 30


def _svc_fold_map(one_fold, args: tuple, m: int, k: int, itemsize: int):
    """vmap when all k lanes' kernel/dual matrices fit the budget, else a
    sequential lax.map — identical math either way."""
    import jax

    if 2 * k * m * m * itemsize <= _SVC_VMAP_BYTES_BUDGET:
        return jax.vmap(one_fold)(*args)
    return jax.lax.map(lambda a: one_fold(*a), args)


def _fit_fingerprint(X64, y, cfg) -> str:
    """Cheap input digest binding a stage-checkpoint dir to (X, y, cfg):
    shapes/dtypes, the config JSON, and a deterministic row sample of X/y
    (full-matrix hashing would cost seconds at the 10M-row scale; a 4096-row
    stride sample still catches any accidental dir reuse)."""
    import hashlib

    X64 = np.asarray(X64)
    y = np.asarray(y)
    h = hashlib.sha256()
    h.update(repr((X64.shape, str(X64.dtype), y.shape, str(y.dtype))).encode())
    h.update(cfg.to_json().encode())
    step = max(1, X64.shape[0] // 4096)
    h.update(np.ascontiguousarray(X64[::step]).tobytes())
    h.update(np.ascontiguousarray(y[::step]).tobytes())
    return h.hexdigest()


def _run_array_stage(stages, name: str, compute):
    """``stages.run`` for a stage whose output is ONE array: wraps it in a
    one-key dict (orbax's standard handler refuses bare array items) and
    unwraps on the way out, so call sites can't forget the dance."""
    return stages.run(name, lambda: {"oof": compute()})["oof"]


def _make_stages(checkpoint_dir, _interrupt_after, fingerprint=None):
    if checkpoint_dir is None:
        return _NullStages()
    from machine_learning_replications_tpu.persist.orbax_io import (
        StageCheckpointer,
    )

    return StageCheckpointer(
        checkpoint_dir, _interrupt_after=_interrupt_after,
        fingerprint=fingerprint,
    )


def fit_stacking(
    X: np.ndarray,
    y: np.ndarray,
    cfg: ExperimentConfig = ExperimentConfig(),
    mesh=None,
    stages=None,
) -> stacking.StackingParams:
    """Fit the stacking ensemble on (already imputed + selected) ``X[n, 17]``.

    Above ``cfg.svc.max_rows`` rows the SVC member (O(n² ) kernel matrix)
    follows ``cfg.svc.scale_policy``: a deterministic stratified subsample
    of ``max_rows`` rows (scaler included — it lives inside the member's
    pipeline), or a refusal with a clear message. The GBDT and LR members
    always train on every row (they scale), and they carry the dominant
    meta weights (SURVEY.md §2.3: 1.837 + 2.880 vs the SVC's 0.410).

    With ``mesh`` (a ``jax.sharding.Mesh``), the GBDT member trains through
    the row-sharded trainers (``parallel.fit_gbdt_sharded`` — histogram
    partials psum over the 'data' axis); a 1-device mesh is the same code
    path (BASELINE config 5's contract).

    ``stages`` (a ``StageCheckpointer`` or None) makes each member fit and
    the meta pass a resumable checkpointed stage (SURVEY.md §5 failure
    detection); stage outputs are deterministic, so a resumed fit equals an
    unbroken one.
    """
    if stages is None:
        stages = _NullStages()
    Xj = jnp.asarray(X)
    yj = jnp.asarray(y)

    # --- full-data member fits (the predict-time estimators_) -------------
    def _fit_svc():
        svc_rows = _svc_fit_rows(y, cfg, fold=None)
        Xsvc = Xj if svc_rows is None else Xj[svc_rows]
        ysvc = yj if svc_rows is None else yj[svc_rows]
        scaler_p = scaler.fit(Xsvc)
        svc_p = svm.svc_fit(
            scaler.transform(scaler_p, Xsvc),
            ysvc,
            C=cfg.svc.C,
            gamma=None if cfg.svc.gamma == "scale" else cfg.svc.gamma,
            balanced=cfg.svc.class_weight == "balanced",
            probability=cfg.svc.probability,
            platt_cv=cfg.svc.platt_cv,
            tol=cfg.svc.tol,
            max_iter=cfg.svc.max_iter,
        )
        return scaler_p, svc_p

    def _fit_gbdt():
        # At device-binning scale the exact splitter's candidate set is
        # unbounded (≈ n candidates per continuous column, r5 OOM):
        # the member switches to the capped hist protocol there — see
        # gbdt.scaled_member_cfg.
        X_np = np.asarray(X)
        gcfg = gbdt.scaled_member_cfg(cfg.gbdt, X_np.shape[0], X_np.shape[1])
        if mesh is not None:
            from machine_learning_replications_tpu.parallel import (
                fit_gbdt_sharded,
            )

            return fit_gbdt_sharded(mesh, np.asarray(X), np.asarray(y), gcfg)[0]
        return gbdt.fit(np.asarray(X), np.asarray(y), gcfg)[0]

    def _fit_lg():
        return solvers.logreg_l1_fit(
            Xj, yj, C=cfg.logreg.C,
            balanced=cfg.logreg.class_weight == "balanced",
            tol=cfg.logreg.tol, max_iter=cfg.logreg.max_iter,
        )

    scaler_p, svc_p = stages.run("member_svc", _fit_svc)
    gbdt_p = stages.run("member_gbdt", _fit_gbdt)
    lg_p = stages.run("member_lg", _fit_lg)

    # --- cross_val_predict meta-features ----------------------------------
    def _fit_meta():
        # The CV pass checkpoints per-member OOF columns itself (its
        # docstring has the cost/benefit math); this outer stage holds
        # only the cheap meta-LR Newton fit.
        meta_X = cross_val_member_probas(X, y, cfg, mesh=mesh, stages=stages)
        return solvers.logreg_l2_fit(
            jnp.asarray(meta_X), yj, C=cfg.meta.C,
            tol=cfg.meta.tol, max_iter=cfg.meta.max_iter,
        )

    meta_p = stages.run("meta", _fit_meta)

    return stacking.StackingParams(
        scaler=scaler_p, svc=svc_p, gbdt=gbdt_p, logreg=lg_p, meta=meta_p
    )


def _svc_fit_rows(
    y: np.ndarray, cfg: ExperimentConfig, fold: int | None
) -> np.ndarray | None:
    """Scaled-regime guard for the SVC member: None (all rows fit), sorted
    subsample indices, or a refusal per ``cfg.svc.scale_policy``."""
    n = np.asarray(y).shape[0]
    if n <= cfg.svc.max_rows:
        return None
    if cfg.svc.scale_policy == "error":
        raise RuntimeError(
            f"SVC member: {n} rows exceeds SVCConfig.max_rows="
            f"{cfg.svc.max_rows} (the RBF kernel matrix is O(n²)); set "
            "scale_policy='subsample' (stratified subsample, default), "
            "raise max_rows, or drop the SVC member"
        )
    if cfg.svc.scale_policy != "subsample":
        raise ValueError(
            f"unknown SVCConfig.scale_policy {cfg.svc.scale_policy!r}; "
            "expected 'subsample' or 'error'"
        )
    from machine_learning_replications_tpu.utils.cv import (
        stratified_subsample_indices,
    )

    seed = cfg.seed if fold is None else cfg.seed + 1 + fold
    return stratified_subsample_indices(y, cfg.svc.max_rows, seed=seed)


def cross_val_member_probas(
    X: np.ndarray, y: np.ndarray, cfg: ExperimentConfig, mesh=None,
    stages=None,
) -> np.ndarray:
    """Out-of-fold P(class 1) per member — the ``[n, 3]`` meta-feature matrix
    (sklearn: ``cross_val_predict(est, X, y, cv=5, method='predict_proba')``
    per member, first column dropped) — all k folds of each member as one
    vmapped XLA program.

    With ``mesh``, the GBDT fold fits (the member that scales with rows) run
    sequentially through the row-sharded level-wise trainer instead of the
    single-device vmap — same masked-fold semantics (weight-0 rows parked at
    node −1), same bins (``bin_budget_capped``), so the meta-features match
    the single-device path; all k folds share one compiled program.

    Fold membership is a ``[k, n]`` mask, never a row subset, so every fold
    shares one static shape (SURVEY.md §7 "fold-size padding with masked
    reductions"): the SVC fold fit zeroes excluded rows' box constraints
    (``C_i = 0`` ⇒ α_i = 0), the GBDT fold fit parks them at node −1 with
    zero gradient, and the L1-LR fold fit zeroes their loss weight.

    ``stages`` (a ``StageCheckpointer``) makes each member's out-of-fold
    column its own durable sub-stage: the CV pass is the longest stage of
    ``fit_pipeline`` at scale (five SVC fold fits dominate), and as one
    monolithic stage a preemption anywhere inside it lost everything — a
    measured 1M-row CPU run restored its five earlier stages in under a
    second and then re-ran the whole 40-minute CV from zero, twice. One
    ``[n]`` f32 column per member (~40 MB at 10M rows) is the write cost.
    """
    import jax

    if stages is None:
        stages = _NullStages()
    X = np.asarray(X)
    y = np.asarray(y)
    n = X.shape[0]
    k = cfg.stacking.cv_folds
    test_masks_np = stratified_kfold_test_masks(y, k)
    train_masks_np = 1.0 - test_masks_np
    if n > cfg.svc.max_rows:
        _svc_fit_rows(y, cfg, fold=0)  # policy check (may raise)

    Xj = jnp.asarray(X)
    yj = jnp.asarray(y)
    dtype = Xj.dtype
    test_masks = jnp.asarray(test_masks_np, dtype)
    train_masks = jnp.asarray(train_masks_np, dtype)

    # --- SVC pipeline: fold scaler refit + masked dual + nested Platt CV ---
    # (sklearn clones the whole Pipeline per fold, so the scaler refits on
    # the fold's train rows; the nested Platt folds stratify *within* them.)
    # Every sub-stage closure below does ALL its prep inside the closure, so
    # a restored stage skips the prep too, not just the fits.
    if n > cfg.svc.max_rows:
        # Scaled regime: the masked path still materializes the full [n, n]
        # kernel, so fold fits move to physical stratified subsets of
        # ``max_rows`` rows each (one static shape → still one vmapped
        # program) with chunked out-of-fold prediction.
        def _svc_oof_fn():
            return jnp.asarray(
                _svc_oof_subsampled(X, y, test_masks_np, train_masks_np, cfg),
                dtype,
            )
    else:
        def _svc_oof_fn():
            platt_masks = jnp.asarray(
                np.stack([
                    stratified_kfold_test_masks_within(y, cfg.svc.platt_cv, tm)
                    for tm in train_masks_np
                ]),
                dtype,
            )  # [k, platt_cv, n]

            def one_fold_svc(tm, pm):
                sp = scaler.fit(Xj, sample_weight=tm)
                Xt = scaler.transform(sp, Xj)
                vp = svm.svc_fit_masked(
                    Xt, yj, tm, pm,
                    C=cfg.svc.C,
                    gamma=None if cfg.svc.gamma == "scale" else cfg.svc.gamma,
                    balanced=cfg.svc.class_weight == "balanced",
                    tol=cfg.svc.tol, max_iter=cfg.svc.max_iter,
                )
                return svm.predict_proba1(vp, Xt)

            p_svc = _svc_fold_map(
                one_fold_svc, (train_masks, platt_masks),
                m=n, k=k, itemsize=Xj.dtype.itemsize,
            )  # [k, n]
            return jnp.sum(p_svc * test_masks, axis=0)

    svc_oof = _run_array_stage(stages, "meta_svc_oof", _svc_oof_fn)

    # --- GBDT: mask-parked fold fits, one program for all k folds ---------
    if mesh is not None:
        def _gbdt_oof():
            from machine_learning_replications_tpu.ops import binning
            from machine_learning_replications_tpu.parallel import (
                fit_gbdt_sharded,
            )

            # Same binning gate as gbdt.default_bins: empirical-quantile
            # device binning only in the scaled 'hist' regime (where host
            # np.unique would dominate); everywhere else — including every
            # parity-test size — host unique-value bins keep the mesh
            # path's candidates identical to fit_folds', so meta-features
            # match bit-for-bit.
            if cfg.gbdt.per_fold_binning:
                # Reference-exact protocol under the mesh too: host-bin
                # each fold's own rows, re-bin all rows against those
                # thresholds (excluded rows carry weight 0 — parked).
                # Threshold widths differ per fold, so each fold may
                # compile its own program.
                budget = gbdt.bin_budget_capped(cfg.gbdt)
                X_np = np.asarray(X)

                def fold_bins_for(j):
                    bf = binning.bin_features(
                        X_np[np.asarray(train_masks_np[j]) > 0], budget
                    )
                    return binning.BinnedFeatures(
                        binned=binning.rebin_with_thresholds(
                            X_np, bf.thresholds, bf.n_bins
                        ),
                        thresholds=bf.thresholds,
                        n_bins=bf.n_bins,
                    )
            elif (
                cfg.gbdt.splitter == "hist"
                and X.shape[0] >= gbdt.DEVICE_BINNING_MIN_ROWS
            ):
                fold_bins = binning.bin_features_device(
                    X, gbdt.bin_budget_capped(cfg.gbdt)
                )
            else:
                fold_bins = binning.bin_features(
                    X, gbdt.bin_budget_capped(cfg.gbdt)
                )
            probas = []
            for j in range(k):  # one compiled program, k reuses (shared bins)
                gp_j, _ = fit_gbdt_sharded(
                    mesh, X, y, cfg.gbdt,
                    bins=(fold_bins_for(j) if cfg.gbdt.per_fold_binning
                          else fold_bins),
                    sample_weight=train_masks_np[j],
                )
                probas.append(tree.predict_proba1(gp_j, Xj))
            return jnp.sum(jnp.stack(probas) * test_masks, axis=0)
    else:
        def _gbdt_oof():
            gp = gbdt.fit_folds(X, y, train_masks_np, cfg.gbdt)
            p_gbdt = jax.vmap(lambda p: tree.predict_proba1(p, Xj))(gp)
            return jnp.sum(p_gbdt * test_masks, axis=0)

    gbdt_oof = _run_array_stage(stages, "meta_gbdt_oof", _gbdt_oof)

    # --- L1 logistic regression: masked FISTA --------------------------
    def one_fold_lg(tm):
        lp = solvers.logreg_l1_fit(
            Xj, yj, C=cfg.logreg.C, sample_mask=tm,
            balanced=cfg.logreg.class_weight == "balanced",
            tol=cfg.logreg.tol, max_iter=cfg.logreg.max_iter,
        )
        return linear.predict_proba1(lp, Xj)

    lg_oof = _run_array_stage(stages, "meta_lg_oof", lambda: jnp.sum(
        jax.vmap(one_fold_lg)(train_masks) * test_masks, axis=0
    ))

    # Out-of-fold assembly: each row's meta-feature comes from the one fold
    # whose test mask contains it (the per-member sums happened inside the
    # checkpointable sub-stages above).
    meta = jnp.stack([svc_oof, gbdt_oof, lg_oof], axis=1)
    return np.asarray(meta)


def _svc_oof_subsampled(
    X: np.ndarray,
    y: np.ndarray,
    test_masks_np: np.ndarray,
    train_masks_np: np.ndarray,
    cfg: ExperimentConfig,
) -> np.ndarray:
    """Out-of-fold SVC probabilities in the scaled regime: each fold fits on
    a stratified ``max_rows`` subset of its train rows (all folds share one
    shape, so the k fits still vmap into one program); test rows are scored
    against the fold's support set in bounded-memory chunks."""
    import jax

    from machine_learning_replications_tpu.utils.cv import (
        stratified_kfold_test_masks,
        stratified_subsample_indices,
    )

    k = len(test_masks_np)
    m = cfg.svc.max_rows
    idxs = np.stack([
        stratified_subsample_indices(
            y, m, rows=np.where(train_masks_np[j] > 0.5)[0],
            seed=cfg.seed + 1 + j,
        )
        for j in range(k)
    ])  # [k, m]
    Xsub = jnp.asarray(X[idxs])   # [k, m, F]
    ysub = jnp.asarray(y[idxs])
    dtype = Xsub.dtype
    platt = jnp.asarray(
        np.stack([
            stratified_kfold_test_masks(y[idxs[j]], cfg.svc.platt_cv)
            for j in range(k)
        ]),
        dtype,
    )  # [k, platt_cv, m]
    full = jnp.ones((k, m), dtype)

    def one_fold(Xs_, ys_, fm, pm):
        sp = scaler.fit(Xs_)
        vp = svm.svc_fit_masked(
            scaler.transform(sp, Xs_), ys_, fm, pm,
            C=cfg.svc.C,
            gamma=None if cfg.svc.gamma == "scale" else cfg.svc.gamma,
            balanced=cfg.svc.class_weight == "balanced",
            tol=cfg.svc.tol, max_iter=cfg.svc.max_iter,
        )
        return sp, vp

    sps, vps = _svc_fold_map(
        one_fold, (Xsub, ysub, full, platt),
        m=m, k=k, itemsize=Xsub.dtype.itemsize,
    )

    oof = np.zeros(y.shape[0])
    for j in range(k):  # host loop: k is 5; the chunked predict dominates
        spj = jax.tree.map(lambda a: a[j], sps)
        vpj = jax.tree.map(lambda a: a[j], vps)
        te = test_masks_np[j] > 0.5
        Xte = np.asarray(scaler.transform(spj, jnp.asarray(X[te])))
        oof[te] = svm.predict_proba1_chunked(
            vpj, Xte, cfg.svc.predict_chunk_rows
        )
    return oof


def cross_val_member_probas_loop(
    X: np.ndarray, y: np.ndarray, cfg: ExperimentConfig
) -> np.ndarray:
    """Sequential per-fold-subset construction of the same meta-features —
    the reference's structure (SURVEY.md §3.2) kept as the differential
    oracle for the vmapped path."""
    X = np.asarray(X)
    y = np.asarray(y)
    n = X.shape[0]
    test_masks = stratified_kfold_test_masks(y, cfg.stacking.cv_folds)
    meta = np.zeros((n, 3))
    for tm in test_masks:
        tr = tm < 0.5
        te = ~tr
        Xtr, ytr, Xte = X[tr], y[tr], X[te]
        # svc pipeline (scaler refit per fold, as sklearn clones the Pipeline)
        sp = scaler.fit(jnp.asarray(Xtr))
        vp = svm.svc_fit(
            scaler.transform(sp, jnp.asarray(Xtr)),
            jnp.asarray(ytr),
            C=cfg.svc.C,
            gamma=None if cfg.svc.gamma == "scale" else cfg.svc.gamma,
            balanced=cfg.svc.class_weight == "balanced",
            probability=True,
            platt_cv=cfg.svc.platt_cv,
            tol=cfg.svc.tol,
            max_iter=cfg.svc.max_iter,
        )
        meta[te, 0] = np.asarray(
            svm.predict_proba1(vp, scaler.transform(sp, jnp.asarray(Xte)))
        )
        # gbdt
        gp, _ = gbdt.fit(Xtr, ytr, cfg.gbdt)
        meta[te, 1] = np.asarray(tree.predict_proba1(gp, jnp.asarray(Xte)))
        # l1 logreg
        lp = solvers.logreg_l1_fit(
            jnp.asarray(Xtr), jnp.asarray(ytr), C=cfg.logreg.C,
            balanced=cfg.logreg.class_weight == "balanced",
            tol=cfg.logreg.tol, max_iter=cfg.logreg.max_iter,
        )
        meta[te, 2] = np.asarray(linear.predict_proba1(lp, jnp.asarray(Xte)))
    return meta


def fit_pipeline(
    X64: np.ndarray,
    y: np.ndarray,
    cfg: ExperimentConfig = ExperimentConfig(),
    mesh=None,
    checkpoint_dir: str | None = None,
    _interrupt_after: str | None = None,
) -> tuple[PipelineParams, dict[str, Any]]:
    """The full reference program: impute → select → stack.

    ``X64`` is the raw 64-variable cohort (NaNs allowed); returns fitted
    params plus selection diagnostics. ``mesh`` routes the row-parallel
    stages (imputer transform, GBDT member + fold fits) through the mesh.

    ``checkpoint_dir`` makes every stage resumable: impute → select →
    member_svc → member_gbdt → member_lg → meta, each durably checkpointed
    on completion (atomic sidecar publish), so a preempted run re-entered
    with the same arguments restores finished stages instead of recomputing
    (SURVEY.md §5 failure-detection row). ``_interrupt_after`` is the test
    hook simulating preemption right after a named stage commits.
    """
    stages = _make_stages(
        checkpoint_dir, _interrupt_after,
        fingerprint=(
            _fit_fingerprint(X64, y, cfg) if checkpoint_dir is not None else None
        ),
    )

    imp_p, X_imp = stages.run(
        "impute",
        lambda: knn_impute.fit_transform(
            jnp.asarray(X64), cfg.imputer, cfg.seed, mesh=mesh, y=y
        ),
    )
    X_imp = np.asarray(X_imp)

    def _select():
        mask, info = feature_selection.fit_select(X_imp, y, cfg.select, mesh=mesh)
        # Flattened to a sidecar-encodable tuple (predates the sidecar's
        # 'mapping' dict support; a keyed dict would be the simpler
        # encoding today); rebuilt below. −1 = no subsampling happened.
        return (
            jnp.asarray(mask), jnp.asarray(info["coef"]), info["intercept"],
            info["alpha_"], jnp.asarray(info["alphas"]),
            jnp.asarray(info["mse_path"]),
            info.get("subsampled_from_rows", -1),
        )

    sel = stages.run("select", _select)
    mask = np.asarray(sel[0])
    info = {
        "coef": np.asarray(sel[1]), "intercept": float(sel[2]),
        "alpha_": float(sel[3]), "alphas": np.asarray(sel[4]),
        "mse_path": np.asarray(sel[5]),
    }
    if len(sel) > 6 and int(sel[6]) >= 0:
        info["subsampled_from_rows"] = int(sel[6])
    X17 = X_imp[:, mask]
    ens = fit_stacking(X17, y, cfg, mesh=mesh, stages=stages)

    def _quality_profile():
        # The model's drift baseline (obs.quality): the SAME post-impute
        # post-select matrix the members trained on, plus the fitted
        # ensemble's training score distribution — computed here because
        # this is the only place both exist before the params leave for a
        # checkpoint. One chunked predict pass over the cohort; at the
        # 10M-row scale that is bounded by the same chunk_rows memory
        # story as batch prediction.
        from machine_learning_replications_tpu.obs import quality

        scores = _ensemble_scores(
            ens, X17, mesh=mesh, chunk_rows=cfg.svc.predict_chunk_rows
        )
        prof = quality.build_reference_profile(X17, scores, y=y)
        return {k: jnp.asarray(v) for k, v in prof.items()}

    qual = stages.run("quality_profile", _quality_profile)
    return (
        PipelineParams(
            imputer=imp_p, support_mask=jnp.asarray(mask), ensemble=ens,
            quality=qual,
        ),
        {"selection": info, "n_selected": int(mask.sum())},
    )


def _ensemble_scores(
    ens: stacking.StackingParams, X17: np.ndarray, mesh=None,
    chunk_rows: int | None = None,
) -> np.ndarray:
    """Training scores for the reference profile: the stacked P(class 1)
    over already-imputed-and-selected rows, through the SAME bounded
    scoring tail as batch inference (callers pass the experiment's own
    ``cfg.svc.predict_chunk_rows``)."""
    return np.asarray(
        _stacked_proba1_bounded(ens, jnp.asarray(X17), mesh, chunk_rows)
    )


def contract_rows_to_x64(
    params: PipelineParams, X17: np.ndarray
) -> np.ndarray:
    """Embed contract-order 17-variable rows (``predict_hf.py:5-27``) at
    their schema positions in full-width NaN rows, ready for
    ``pipeline_predict_proba1``.

    A full-pipeline checkpoint selects its own lasso top-k columns
    (ascending index order) — NOT the contractual 17-variable order — so
    inference front ends route contract rows through the pipeline: the 17
    known variables land at their schema positions, the rest stay NaN for
    the KNN imputer (exactly the pipeline's missing-EHR-value story).
    """
    from machine_learning_replications_tpu.data.schema import selected_indices

    X17 = np.asarray(X17, np.float64)
    if X17.ndim == 1:
        X17 = X17[None, :]
    width = int(params.support_mask.shape[0])
    x64 = np.full((X17.shape[0], width), np.nan)
    x64[:, selected_indices()] = X17
    return x64


def resolve_contract_block_fn(params: PipelineParams):
    """Pre-resolve the imputer's pattern-specialised block fn for
    *contract-shaped* queries — 17 finite variables at their schema
    positions, every other column NaN (``contract_rows_to_x64``). Contract
    rows are all-finite by validation, so the NaN pattern is fixed and the
    resolution — a device reduction plus a blocking device→host fetch of
    the donor NaN mask — is a once-per-process cost instead of a per-batch
    one. Both high-throughput front ends share this: the serving engine
    (per flushed micro-batch) and the bulk-scoring pipeline (per streamed
    chunk)."""
    from machine_learning_replications_tpu.data.examples import (
        EXAMPLE_PATIENT,
    )
    from machine_learning_replications_tpu.models import knn_impute

    return knn_impute.resolve_block_fn(
        params.imputer,
        contract_rows_to_x64(
            params, np.zeros((1, len(EXAMPLE_PATIENT)))
        ),
    )


def support_feature_names(params: PipelineParams) -> list[str]:
    """Schema variable names of the model's OWN lasso-selected columns, in
    support-mask (ascending schema) order — the space ``impute_select``
    emits and the quality reference profile was built over. NOT the
    17-variable contract order: a checkpoint selects its own top-k, so
    front ends labeling per-feature drift series (``serve/server.py``,
    ``score/``) must derive names from the mask or name the wrong
    variables."""
    from machine_learning_replications_tpu.data.schema import variable_names

    names = variable_names()
    return [names[i] for i in np.where(np.asarray(params.support_mask))[0]]


def impute_select(
    params: PipelineParams, X64: np.ndarray, mesh=None, block_fn=None
) -> jnp.ndarray:
    """KNN-impute raw 64-wide rows and gather the lasso support columns —
    the front half of full-pipeline inference, ending at the member
    ensemble's 17-column input. ``pipeline_predict_proba1``, the serving
    engine (``serve/engine.py``, which jits its own
    ``stacking.predict_proba1`` call for the per-bucket compile bound),
    and the dual-path host scorer (``serve/hostpath.py`` — the same
    engine pinned to the CPU backend) all run THIS composition, so none
    of the routes can drift: parity is structural, not tested-in.
    ``block_fn`` is ``knn_impute.resolve_block_fn``'s output for callers
    with a fixed query NaN pattern (the serving hot path resolves it once
    at engine init instead of paying a device→host sync per batch)."""
    # X64 passes through host-side: transform normalizes with np.asarray
    # anyway, and a jnp.asarray here would upload the batch only for
    # transform to immediately fetch it back — a per-batch device→host
    # sync on the serving hot path.
    X_imp = knn_impute.transform(
        params.imputer, X64, mesh=mesh, block_fn=block_fn
    )
    return X_imp[:, np.where(np.asarray(params.support_mask))[0]]


def pipeline_predict_proba1_contract(
    params: PipelineParams, X17: np.ndarray, mesh=None,
    chunk_rows: int | None = None,
) -> jnp.ndarray:
    """Contract-order 17-variable rows → stacked P(class 1) through the
    full pipeline — the ``cli.py predict --model`` route. The serving
    engine runs the same ``contract_rows_to_x64`` → ``impute_select`` →
    ``stacking.predict_proba1`` composition (parity pinned bit-for-bit by
    ``tests/test_serve.py``)."""
    return pipeline_predict_proba1(
        params, contract_rows_to_x64(params, X17),
        mesh=mesh, chunk_rows=chunk_rows,
    )


def pipeline_predict_proba1(
    params: PipelineParams, X64: np.ndarray, mesh=None,
    chunk_rows: int | None = None,
) -> jnp.ndarray:
    """Raw 64-feature rows (NaNs allowed) → stacked P(class 1).

    With ``mesh``, both the imputer transform and the stacked probability
    pass run row-sharded over the 'data' axis (each is a pure per-row map
    given replicated params), so batch prediction scales with the mesh the
    same way training does (VERDICT r2 item 5). ``chunk_rows`` bounds the
    rows per compiled call — the SVC member materializes an
    [rows, n_support] RBF kernel block, which at cohort scale must not be
    built for every row at once (default: ``SVCConfig.predict_chunk_rows``).
    """
    X17 = impute_select(params, X64, mesh=mesh)
    return _stacked_proba1_bounded(params.ensemble, X17, mesh, chunk_rows)


def _stacked_proba1_bounded(
    ens: stacking.StackingParams, X17: jnp.ndarray, mesh,
    chunk_rows: int | None,
) -> jnp.ndarray:
    """The ONE memory-bounded stacked-probability tail (batch inference
    and the fit-time reference-profile scoring pass both run it): with a
    mesh, row-sharded over the 'data' axis; single-device, chunked so the
    SVC member's [rows, n_support] kernel block stays within
    ``chunk_rows`` (default ``SVCConfig.predict_chunk_rows``); blocks
    stay as device arrays until the final concatenate."""
    from machine_learning_replications_tpu.config import SVCConfig

    if chunk_rows is None:
        chunk_rows = SVCConfig().predict_chunk_rows
    if mesh is not None:
        from machine_learning_replications_tpu.parallel.rowwise import (
            apply_rows_sharded,
        )

        return apply_rows_sharded(
            mesh, stacking.predict_proba1, ens, X17, chunk_rows=chunk_rows
        )
    n = int(X17.shape[0])
    if n > chunk_rows:
        return jnp.concatenate([
            stacking.predict_proba1(ens, X17[s : s + chunk_rows])
            for s in range(0, n, chunk_rows)
        ])
    return stacking.predict_proba1(ens, X17)
