"""Convex solvers for the linear members — pure JAX, fixed-shape iterations.

The reference reaches three native optimizers (SURVEY.md §2.4): Cython
coordinate descent (LassoCV, ``train_ensemble_public.py:51``), liblinear's
newGLMNET (L1 logistic regression, ``:46``), and lbfgs (meta learner,
``:48``). All three problems are convex with (essentially) unique optima, so
the TPU build solves the *same objectives* with accelerated proximal
gradient (FISTA) and damped Newton — solver families chosen for the
hardware: constant-shape dense matvecs, no data-dependent control flow,
fold/alpha fan-out via ``vmap``/``scan``. Parity is at the optimum, not the
iterate path (SURVEY.md §7 "rely on convexity").

Objectives replicated exactly:
  * Lasso:    1/(2n)·Σ w_i(y_i − x_i·β)² + α‖β‖₁           (sklearn Lasso)
  * L1-LR:    ‖β̃‖₁ + C·Σ cw_i log(1+exp(−ỹ_i x̃_i·β̃))      (liblinear, which
              *does* penalize the intercept via the appended bias column —
              hence the shipped model's exactly-zero intercept)
  * L2-LR:    ½‖β‖² + C·Σ cw_i log(1+exp(−ỹ_i(x_i·β + b)))  (lbfgs; intercept
              unpenalized)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.scipy.special import expit

from machine_learning_replications_tpu.models.linear import LinearParams


def soft_threshold(x: jnp.ndarray, t) -> jnp.ndarray:
    return jnp.sign(x) * jnp.maximum(jnp.abs(x) - t, 0.0)


def balanced_class_weights(y: jnp.ndarray) -> jnp.ndarray:
    """sklearn's ``class_weight='balanced'``: w_i = n / (2 · n_{class(i)})."""
    n = y.shape[0]
    n1 = jnp.sum(y)
    n0 = n - n1
    return jnp.where(y > 0.5, n / (2.0 * n1), n / (2.0 * n0))


def _power_lmax(G: jnp.ndarray, iters: int = 30) -> jnp.ndarray:
    """Largest eigenvalue of a PSD matrix by power iteration."""
    v = jnp.ones(G.shape[0], G.dtype) / jnp.sqrt(G.shape[0])

    def body(_, v):
        w = G @ v
        return w / jnp.maximum(jnp.linalg.norm(w), 1e-30)

    v = jax.lax.fori_loop(0, iters, body, v)
    return v @ (G @ v)


# ---------------------------------------------------------------------------
# Lasso (weighted, for masked CV folds)
# ---------------------------------------------------------------------------


def _fista_while(prox_step, w0, dtype, tol, max_iter):
    """Shared accelerated-proximal-gradient driver with residual early exit.

    ``prox_step(z) -> w_new`` is one proximal gradient step from the
    extrapolated point. Stops when the iterate change falls below
    ``tol · (1 + ‖w‖∞)`` or at ``max_iter`` (SURVEY.md §5 config row: the
    round-1 build ran fixed iteration counts and ignored the configured
    tol/max_iter — under-converging silently at scale, VERDICT.md weak #6).
    Composes with ``vmap`` (batched lanes run until all converge).
    """

    def cond(state):
        _, _, _, it, delta = state
        return (it < max_iter) & (delta >= tol)

    def body(state):
        w, z, tk, it, _ = state
        w_new = prox_step(z)
        t_new = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * tk * tk))
        z = w_new + ((tk - 1.0) / t_new) * (w_new - w)
        delta = jnp.max(jnp.abs(w_new - w)) / (1.0 + jnp.max(jnp.abs(w_new)))
        return w_new, z, t_new, it + 1, delta

    state = (
        w0, w0, jnp.asarray(1.0, dtype),
        jnp.asarray(0, jnp.int32), jnp.asarray(jnp.inf, dtype),
    )
    w, _, _, n_done, _ = jax.lax.while_loop(cond, body, state)
    return w, n_done


def lasso_fista(
    X: jnp.ndarray,           # [n, F] raw (uncentered)
    y: jnp.ndarray,           # [n]
    alpha,
    sample_mask: jnp.ndarray, # [n] 1.0 = in this fit
    w0: jnp.ndarray,
    lmax,                     # λmax of (X_cᵀ diag(mask) X_c)/n_eff, precomputed
    tol: float = 1e-6,
    max_iter: int = 1000,
) -> jnp.ndarray:
    """Weighted-row Lasso coefficients (no intercept — caller centers).

    Centering under a row mask happens here so CV folds of different sizes
    share one fixed-shape solver (SURVEY.md §7: padded folds with masked
    reductions).
    """
    n_eff = jnp.sum(sample_mask)
    xm = (sample_mask @ X) / n_eff
    ym = (sample_mask @ y) / n_eff
    Xc = (X - xm) * sample_mask[:, None]
    yc = (y - ym) * sample_mask

    step = 1.0 / jnp.maximum(lmax, 1e-12)

    def prox_step(z):
        grad = (Xc.T @ (Xc @ z - yc)) / n_eff
        return soft_threshold(z - step * grad, step * alpha)

    w, _ = _fista_while(prox_step, w0, X.dtype, tol, max_iter)
    return w


def lasso_intercept(X, y, w, sample_mask):
    n_eff = jnp.sum(sample_mask)
    return (sample_mask @ y) / n_eff - ((sample_mask @ X) / n_eff) @ w


def alpha_grid(X: jnp.ndarray, y: jnp.ndarray, n_alphas: int, eps: float) -> jnp.ndarray:
    """sklearn ``_alpha_grid``: α_max = max|X_cᵀ y_c|/n on the *full* centered
    data; log-spaced down to ``eps·α_max``, descending."""
    n = X.shape[0]
    Xc = X - jnp.mean(X, axis=0)
    yc = y - jnp.mean(y)
    amax = jnp.max(jnp.abs(Xc.T @ yc)) / n
    return jnp.logspace(0.0, jnp.log10(eps), n_alphas) * amax


def lasso_path(
    X, y, alphas, sample_mask, tol: float = 1e-6, max_iter: int = 1000
) -> jnp.ndarray:
    """Warm-started path over a descending alpha grid → coefs ``[A, F]``."""
    n_eff = jnp.sum(sample_mask)
    xm = (sample_mask @ X) / n_eff
    Xc = (X - xm) * sample_mask[:, None]
    lmax = _power_lmax(Xc.T @ Xc) / n_eff

    def step(w, alpha):
        w = lasso_fista(X, y, alpha, sample_mask, w, lmax, tol, max_iter)
        return w, w

    w0 = jnp.zeros(X.shape[1], X.dtype)
    _, coefs = jax.lax.scan(step, w0, alphas)
    return coefs


# ---------------------------------------------------------------------------
# LassoCV in covariance (sufficient-statistics) form
#
# The weighted-lasso objective touches the data only through second-order
# statistics: Σ x xᵀ, Σ x y, Σ x, Σ y, Σ y², per train fold. Precomputing
# those per TEST fold (train = total − test, since contiguous KFold
# partitions the rows) collapses the whole 10-fold × 100-alpha CV path to
# F-dimensional work — no [n, A] prediction matrix, no [K, n] masks, no
# per-iteration pass over the rows (VERDICT r3 missing #2: the old fold MSE
# materialized ~40 GB at 10M rows). The n-dependent work is K slice-Gram
# contractions ([F, m_k] @ [m_k, F] — MXU-shaped), which shard over the
# mesh's data axis with a single psum (parallel/select_trainer.py).
# ---------------------------------------------------------------------------


def fold_bounds(n: int, k: int) -> list[tuple[int, int]]:
    """sklearn ``KFold(shuffle=False)`` boundaries: first ``n % k`` folds get
    one extra row; contiguous, partitioning ``range(n)``. Static python ints
    so slice shapes stay compile-time constants."""
    base, extra = divmod(n, k)
    bounds, start = [], 0
    for i in range(k):
        end = start + base + (1 if i < extra else 0)
        bounds.append((start, end))
        start = end
    return bounds


def _slice_stats(Xs: jnp.ndarray, ys: jnp.ndarray) -> dict:
    """Second-order statistics of one row block (uncentered)."""
    return {
        "sxx": Xs.T @ Xs,             # [F, F]
        "sx": jnp.sum(Xs, axis=0),    # [F]
        "sxy": Xs.T @ ys,             # [F]
        "sy": jnp.sum(ys),
        "syy": ys @ ys,
        "m": jnp.asarray(Xs.shape[0], Xs.dtype),
    }


@functools.partial(jax.jit, static_argnames=("cv_folds",))
def lasso_fold_stats(X: jnp.ndarray, y: jnp.ndarray, cv_folds: int) -> dict:
    """Per-TEST-fold sufficient statistics, stacked on a leading [K] axis,
    of the MEAN-SHIFTED data, plus the shift itself (``mu`` [F], ``nu``).

    The shift is load-bearing for float32 (the TPU production dtype): the
    centered Gram ``sxx − m·x̄x̄ᵀ`` cancels catastrophically when column
    means dominate the spread (measured ~8.6 RELATIVE error at 1M rows,
    mean/std ≈ 10, f32). Shifting by the global column means first makes
    x̄ ≈ 0 in every fold, so the subtraction is benign. A common shift is
    exact for everything downstream — centered Grams, cross-moments, the
    alpha grid, and held-out residuals are all shift-invariant; only the
    final intercept needs the un-shift correction (``lasso_cv_from_stats``).

    Single-device path: K static contiguous slices (no masks materialized).
    The mesh path with identical output lives in
    ``parallel.select_trainer.lasso_fold_stats_sharded``.
    """
    mu = jnp.mean(X, axis=0)
    nu = jnp.mean(y)
    Xs, ys = X - mu, y - nu
    per_fold = [
        _slice_stats(Xs[s:e], ys[s:e]) for s, e in fold_bounds(X.shape[0], cv_folds)
    ]
    stats = jax.tree.map(lambda *leaves: jnp.stack(leaves), *per_fold)
    stats["mu"] = mu
    stats["nu"] = nu
    return stats


def _centered_form(st: dict) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(Gc, c, xm, ym) of a stats dict: the centered Gram ``XcᵀXc``, the
    centered cross-moment ``Xcᵀyc``, and the means — the only quantities the
    masked-row FISTA objective needs."""
    m = jnp.maximum(st["m"], 1.0)
    xm = st["sx"] / m
    ym = st["sy"] / m
    Gc = st["sxx"] - st["m"] * jnp.outer(xm, xm)
    c = st["sxy"] - st["m"] * xm * ym
    return Gc, c, xm, ym


def lasso_fista_stats(
    Gc: jnp.ndarray, c: jnp.ndarray, alpha, m, w0: jnp.ndarray, lmax,
    tol: float, max_iter: int,
) -> jnp.ndarray:
    """``lasso_fista`` on the centered covariance form: identical objective
    (1/(2m)·‖yc − Xc β‖² + α‖β‖₁ has gradient (Gc β − c)/m), F-dimensional
    per-iteration cost."""
    step = 1.0 / jnp.maximum(lmax, 1e-12)

    def prox_step(z):
        grad = (Gc @ z - c) / m
        return soft_threshold(z - step * grad, step * alpha)

    w, _ = _fista_while(prox_step, w0, Gc.dtype, tol, max_iter)
    return w


def _lasso_path_stats(train_st: dict, alphas, tol, max_iter) -> jnp.ndarray:
    """Warm-started descending-alpha path on one train fold's stats → [A, F]."""
    Gc, cvec, _, _ = _centered_form(train_st)
    m = jnp.maximum(train_st["m"], 1.0)
    lmax = _power_lmax(Gc) / m

    def step(w, alpha):
        w = lasso_fista_stats(Gc, cvec, alpha, m, w, lmax, tol, max_iter)
        return w, w

    w0 = jnp.zeros(Gc.shape[0], Gc.dtype)
    _, coefs = jax.lax.scan(step, w0, alphas)
    return coefs


def _holdout_mse(test_st: dict, coefs: jnp.ndarray, intercepts: jnp.ndarray):
    """Held-out MSE of (coefs [A, F], intercepts [A]) from test-fold stats:
    Σ(x·w + b − y)² expands into the second-order statistics exactly."""
    quad = jnp.einsum("af,fg,ag->a", coefs, test_st["sxx"], coefs)
    sse = (
        quad
        + 2.0 * intercepts * (coefs @ test_st["sx"])
        - 2.0 * (coefs @ test_st["sxy"])
        + test_st["m"] * intercepts**2
        - 2.0 * intercepts * test_st["sy"]
        + test_st["syy"]
    )
    return sse / jnp.maximum(test_st["m"], 1.0)


@functools.partial(jax.jit, static_argnames=("n_alphas", "max_iter"))
def lasso_cv_from_stats(
    test_stats: dict,
    *,
    n_alphas: int = 100,
    eps: float = 1e-3,
    tol: float = 1e-6,
    max_iter: int = 1000,
):
    """The CV-path/selection half of ``lasso_cv``, from per-test-fold stats
    ([K, ...] leading axis) of mean-shifted data. Everything here is
    F-dimensional — rows never appear — so it runs identically for 1k or
    10M-row cohorts. All fold arithmetic happens in the shifted frame
    (exactly equivalent); the returned intercept is un-shifted at the end."""
    test_stats = dict(test_stats)
    mu = test_stats.pop("mu", None)
    nu = test_stats.pop("nu", None)
    totals = jax.tree.map(lambda a: jnp.sum(a, axis=0), test_stats)
    n = totals["m"]

    # alpha grid from full-data centered cross-moments (sklearn _alpha_grid).
    _, c_full, _, _ = _centered_form(totals)
    amax = jnp.max(jnp.abs(c_full)) / n
    alphas = jnp.logspace(0.0, jnp.log10(eps), n_alphas).astype(c_full.dtype) * amax

    train_stats = jax.tree.map(lambda tot, te: tot[None] - te, totals, test_stats)

    def fold_mse(train_st, test_st):
        coefs = _lasso_path_stats(train_st, alphas, tol, max_iter)   # [A, F]
        _, _, xm, ym = _centered_form(train_st)
        intercepts = ym - coefs @ xm                                  # [A]
        return _holdout_mse(test_st, coefs, intercepts)               # [A]

    mse_path = jax.vmap(fold_mse)(train_stats, test_stats).T          # [A, K]
    best = jnp.argmin(jnp.mean(mse_path, axis=1))
    alpha_ = alphas[best]

    Gc, cvec, xm, ym = _centered_form(totals)
    lmax = _power_lmax(Gc) / n
    coef = lasso_fista_stats(
        Gc, cvec, alpha_, n, jnp.zeros(Gc.shape[0], Gc.dtype), lmax,
        tol, 2 * max_iter,
    )
    intercept = ym - coef @ xm
    if mu is not None:
        # Un-shift: b = (ym' − x̄'·w) + ν − μ·w for X' = X − μ, y' = y − ν.
        intercept = intercept + nu - coef @ mu
    return coef, intercept, alpha_, alphas, mse_path


def lasso_cv(
    X: jnp.ndarray,
    y: jnp.ndarray,
    *,
    cv_folds: int = 10,
    n_alphas: int = 100,
    eps: float = 1e-3,
    tol: float = 1e-6,
    max_iter: int = 1000,
):
    """LassoCV (reference ``train_ensemble_public.py:51``): contiguous
    unshuffled K-folds, shared full-data alpha grid, per-fold held-out MSE,
    best alpha by mean MSE, final refit on all rows.

    Returns ``(coef [F], intercept, alpha_, alphas [A], mse_path [A, K])``.
    """
    stats = lasso_fold_stats(X, y, cv_folds)
    return lasso_cv_from_stats(
        stats, n_alphas=n_alphas, eps=eps, tol=tol, max_iter=max_iter
    )


# ---------------------------------------------------------------------------
# Logistic regressions
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("balanced", "max_iter"))
def logreg_l1_fit(
    X: jnp.ndarray,
    y: jnp.ndarray,
    C: float = 1.0,
    sample_mask: jnp.ndarray | None = None,
    balanced: bool = True,
    tol: float = 1e-5,
    max_iter: int = 2000,
) -> LinearParams:
    """liblinear-equivalent L1 logistic regression (bias column penalized)."""
    n, F = X.shape
    mask = jnp.ones(n, X.dtype) if sample_mask is None else sample_mask
    cw = balanced_class_weights_masked(y, mask) if balanced else jnp.ones(n, X.dtype)
    cw = cw * mask
    Xt = jnp.concatenate([X, jnp.ones((n, 1), X.dtype)], axis=1)  # bias column
    s = 2.0 * y - 1.0  # ±1 labels

    G = Xt.T @ (Xt * (C * cw)[:, None])
    lmax = 0.25 * _power_lmax(G)
    step = 1.0 / jnp.maximum(lmax, 1e-12)

    def grad_fn(w):
        m = s * (Xt @ w)
        sig = expit(-m)  # d/dm log(1+e^{-m}) = -σ(-m)
        return Xt.T @ (-(C * cw) * sig * s)

    def prox_step(z):
        return soft_threshold(z - step * grad_fn(z), step)

    w0 = jnp.zeros(F + 1, X.dtype)
    w, _ = _fista_while(prox_step, w0, X.dtype, tol, max_iter)
    return LinearParams(coef=w[:F], intercept=w[F])


def balanced_class_weights_masked(y: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    n = jnp.sum(mask)
    n1 = jnp.sum(y * mask)
    n0 = n - n1
    return jnp.where(y > 0.5, n / (2.0 * n1), n / (2.0 * n0))


@functools.partial(jax.jit, static_argnames=("balanced", "max_iter"))
def logreg_l2_fit(
    X: jnp.ndarray,
    y: jnp.ndarray,
    C: float = 1.0,
    sample_mask: jnp.ndarray | None = None,
    balanced: bool = True,
    tol: float = 1e-8,
    max_iter: int = 60,
) -> LinearParams:
    """lbfgs-equivalent L2 logistic regression via damped Newton
    (dimensions here are tiny — 3 meta-features + intercept). Stops on the
    Newton step's ∞-norm (quadratic convergence makes step size a faithful
    error proxy) or at ``max_iter``."""
    n, F = X.shape
    mask = jnp.ones(n, X.dtype) if sample_mask is None else sample_mask
    cw = (balanced_class_weights_masked(y, mask) if balanced else jnp.ones(n, X.dtype)) * mask
    Xt = jnp.concatenate([X, jnp.ones((n, 1), X.dtype)], axis=1)
    s = 2.0 * y - 1.0
    reg = jnp.concatenate([jnp.ones(F, X.dtype), jnp.zeros(1, X.dtype)])  # no bias penalty

    def cond(state):
        _, it, delta = state
        return (it < max_iter) & (delta >= tol)

    def body(state):
        w, it, _ = state
        m = s * (Xt @ w)
        sig = expit(-m)
        grad = Xt.T @ (-(C * cw) * sig * s) + reg * w
        D = (C * cw) * sig * (1.0 - sig)
        H = Xt.T @ (Xt * D[:, None]) + jnp.diag(reg)
        H = H + 1e-12 * jnp.eye(F + 1, dtype=X.dtype)
        step = jnp.linalg.solve(H, grad)
        return w - step, it + 1, jnp.max(jnp.abs(step))

    w, _, _ = jax.lax.while_loop(
        cond, body,
        (jnp.zeros(F + 1, X.dtype), jnp.asarray(0, jnp.int32),
         jnp.asarray(jnp.inf, X.dtype)),
    )
    return LinearParams(coef=w[:F], intercept=w[F])
