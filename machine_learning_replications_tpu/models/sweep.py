"""5-fold CV hyperparameter sweep over the GBDT grid (BASELINE.json config 4).

The reference has no sweep code — BASELINE.json names "5-fold CV
hyperparameter sweep (n_estimators × max_depth grid)" as a benchmark config
the framework must provide (SURVEY.md §2.5 row "5-fold CV hyperparameter
sweep"). The TPU-native design exploits the boosting prefix property: a
forest trained for M stages *contains* the forest for every m ≤ M (stage
fits are independent of the total), so the sweep fits **one** model per
(max_depth, fold) at ``max(n_estimators_grid)`` stages and evaluates all
``n_estimators`` grid points from per-tree contribution cumsums — the
sklearn-equivalent sweep (``GridSearchCV``) re-fits every grid cell from
scratch.

Fold assignment replicates sklearn's default for classifiers
(``StratifiedKFold(k, shuffle=False)`` — ``utils.cv``), so fold-level AUCs
are comparable against a ``GridSearchCV(scoring='roc_auc')`` differential.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from machine_learning_replications_tpu.config import GBDTConfig, SweepConfig
from machine_learning_replications_tpu.models import gbdt, tree
from machine_learning_replications_tpu.utils.cv import stratified_kfold_test_masks
from machine_learning_replications_tpu.utils.metrics import roc_auc_batch_host


def staged_proba1(
    params: tree.TreeEnsembleParams, X: jnp.ndarray, stages: Any
) -> jnp.ndarray:
    """P(class 1) after the first ``m`` boosting stages, for each m in
    ``stages`` → ``[len(stages), n]`` (sklearn ``staged_predict_proba``
    sampled at the grid points, in one pass)."""
    contrib = tree.apply(params, X)                      # [T, n]
    cum = jnp.cumsum(contrib, axis=0)
    idx = jnp.asarray(np.asarray(stages, dtype=np.int32) - 1)
    raw = params.init_raw + params.learning_rate * cum[idx]
    return jax.scipy.special.expit(raw)


@functools.lru_cache(maxsize=None)
def _staged_fold_jit(est_grid: tuple):
    """Jitted (params, X_te, kk) → staged fold probabilities ``[E, n_te]``.

    Cached per estimator grid so repeated sweeps reuse the compilation;
    distinct test-fold sizes (n % k ≠ 0 gives two) compile once each."""

    def f(params: tree.TreeEnsembleParams, X_te, kk):
        p_k = jax.tree.map(lambda a: a[kk], params)
        return staged_proba1(p_k, X_te, est_grid)

    return jax.jit(f)


@dataclasses.dataclass(frozen=True)
class SweepResult:
    """Grid AUCs and the selected cell.

    ``fold_auc[d, e, k]`` = holdout AUC of depth ``max_depth_grid[d]`` with
    ``n_estimators_grid[e]`` stages on fold ``k``; ``mean_auc`` averages over
    folds; best cell = argmax of ``mean_auc`` (ties → first in grid order,
    like ``GridSearchCV``).
    """

    n_estimators_grid: tuple[int, ...]
    max_depth_grid: tuple[int, ...]
    fold_auc: np.ndarray   # [n_depths, n_estimators, k]
    mean_auc: np.ndarray   # [n_depths, n_estimators]
    best_n_estimators: int
    best_max_depth: int
    best_mean_auc: float


def cv_sweep(
    X: np.ndarray,
    y: np.ndarray,
    sweep: SweepConfig = SweepConfig(),
    base: GBDTConfig = GBDTConfig(),
) -> SweepResult:
    """Run the grid: ONE vmapped fit per depth covering all folds
    (``gbdt.fit_folds`` — mask-parked rows, fold axis batched), staged
    evaluation over the ``n_estimators`` axis. The whole sweep compiles
    ``len(max_depth_grid)`` programs; the reference-equivalent
    ``GridSearchCV`` refits every (cell × fold) from scratch."""
    import jax

    X = np.asarray(X)
    y = np.asarray(y)
    est_grid = tuple(sweep.n_estimators_grid)
    depth_grid = tuple(sweep.max_depth_grid)
    m_max = max(est_grid)
    test_masks = stratified_kfold_test_masks(y, sweep.cv_folds)
    train_masks = 1.0 - test_masks
    k = sweep.cv_folds

    fold_auc = np.zeros((len(depth_grid), len(est_grid), k))
    staged_fold = _staged_fold_jit(est_grid)
    for di, depth in enumerate(depth_grid):
        cfg = dataclasses.replace(base, n_estimators=m_max, max_depth=depth)
        params = gbdt.fit_folds(X, y, train_masks, cfg)
        for kk, tm in enumerate(test_masks):
            te = tm > 0.5
            # Score each fold's HELD-OUT rows only: staging over the full
            # matrix then masking threw away 1−1/k of the tree-apply work
            # (measured ~4 s of an 8.6 s sweep at 20k rows). The fold
            # slice of the batched params happens inside the jit — eager
            # per-leaf indexing costs a dispatch round trip per leaf.
            probs = np.asarray(staged_fold(params, X[te], kk))  # [E, n_te]
            # Grid selection is a host-side decision (GridSearchCV's
            # cv_results_ analogue); the vectorized rank AUC evaluates all
            # n_estimators cells in one pass and matches
            # metrics.roc_auc's tie-averaged U statistic exactly.
            fold_auc[di, :, kk] = roc_auc_batch_host(y[te], probs)

    mean_auc = fold_auc.mean(axis=-1)
    di, ei = np.unravel_index(np.argmax(mean_auc), mean_auc.shape)
    return SweepResult(
        n_estimators_grid=est_grid,
        max_depth_grid=depth_grid,
        fold_auc=fold_auc,
        mean_auc=mean_auc,
        best_n_estimators=est_grid[ei],
        best_max_depth=depth_grid[di],
        best_mean_auc=float(mean_auc[di, ei]),
    )


def refit_best(
    X: np.ndarray,
    y: np.ndarray,
    result: SweepResult,
    base: GBDTConfig = GBDTConfig(),
) -> tuple[tree.TreeEnsembleParams, GBDTConfig]:
    """Refit the winning cell on the full data (``GridSearchCV(refit=True)``)."""
    cfg = dataclasses.replace(
        base,
        n_estimators=result.best_n_estimators,
        max_depth=result.best_max_depth,
    )
    params, _ = gbdt.fit(X, y, cfg)
    return params, cfg
