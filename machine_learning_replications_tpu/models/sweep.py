"""5-fold CV hyperparameter sweep over the GBDT grid (BASELINE.json config 4).

The reference has no sweep code — BASELINE.json names "5-fold CV
hyperparameter sweep (n_estimators × max_depth grid)" as a benchmark config
the framework must provide (SURVEY.md §2.5 row "5-fold CV hyperparameter
sweep"). The TPU-native design exploits the boosting prefix property: a
forest trained for M stages *contains* the forest for every m ≤ M (stage
fits are independent of the total), so the sweep fits **one** model per
(max_depth, fold) at ``max(n_estimators_grid)`` stages and evaluates all
``n_estimators`` grid points from per-tree contribution cumsums — the
sklearn-equivalent sweep (``GridSearchCV``) re-fits every grid cell from
scratch.

Fold assignment replicates sklearn's default for classifiers
(``StratifiedKFold(k, shuffle=False)`` — ``utils.cv``), so fold-level AUCs
are comparable against a ``GridSearchCV(scoring='roc_auc')`` differential.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from machine_learning_replications_tpu.config import GBDTConfig, SweepConfig
from machine_learning_replications_tpu.models import gbdt, tree
from machine_learning_replications_tpu.utils.cv import stratified_kfold_test_masks
from machine_learning_replications_tpu.utils.metrics import roc_auc_batch_host


def staged_proba1(
    params: tree.TreeEnsembleParams, X: jnp.ndarray, stages: Any
) -> jnp.ndarray:
    """P(class 1) after the first ``m`` boosting stages, for each m in
    ``stages`` → ``[len(stages), n]`` (sklearn ``staged_predict_proba``
    sampled at the grid points, in one pass)."""
    contrib = tree.apply(params, X)                      # [T, n]
    cum = jnp.cumsum(contrib, axis=0)
    idx = jnp.asarray(np.asarray(stages, dtype=np.int32) - 1)
    raw = params.init_raw + params.learning_rate * cum[idx]
    return jax.scipy.special.expit(raw)


@functools.lru_cache(maxsize=None)
def _staged_fold_jit(est_grid: tuple):
    """Jitted (params, X_te, kk) → staged fold probabilities ``[E, n_te]``.

    Cached per estimator grid so repeated sweeps reuse the compilation;
    distinct test-fold sizes (n % k ≠ 0 gives two) compile once each."""

    def f(params: tree.TreeEnsembleParams, X_te, kk):
        p_k = jax.tree.map(lambda a: a[kk], params)
        return staged_proba1(p_k, X_te, est_grid)

    return jax.jit(f)


@functools.lru_cache(maxsize=None)
def _staged_rows_fn(est_grid: tuple):
    """Row-leading staged probabilities ``(params, X_rows) → [n, E]`` — the
    per-row map shape ``parallel.rowwise.apply_rows_sharded`` consumes (the
    mesh path's scorer; zero-pad rows flow through and are sliced off)."""

    def f(params: tree.TreeEnsembleParams, X_rows):
        return staged_proba1(params, X_rows, est_grid).T

    return f


@functools.lru_cache(maxsize=None)
def _staged_allfolds_jit(est_grid: tuple):
    """Jitted (batched params, X_te_all [k, n_pad, F]) → ``[k, E, n_pad]``:
    every fold's staged holdout probabilities in ONE dispatch (the per-fold
    variant above costs a host round trip per (depth, fold) — 15 dispatches
    for the 3×5 bench grid; on a tunneled backend each is ~RTT-bound)."""

    def f(params: tree.TreeEnsembleParams, X_te_all):
        return jax.vmap(
            lambda p, X_te: staged_proba1(p, X_te, est_grid)
        )(params, X_te_all)

    return jax.jit(f)


@dataclasses.dataclass(frozen=True)
class SweepResult:
    """Grid AUCs and the selected cell.

    ``fold_auc[d, e, k]`` = holdout AUC of depth ``max_depth_grid[d]`` with
    ``n_estimators_grid[e]`` stages on fold ``k``; ``mean_auc`` averages over
    folds; best cell = argmax of ``mean_auc`` (ties → first in grid order,
    like ``GridSearchCV``).
    """

    n_estimators_grid: tuple[int, ...]
    max_depth_grid: tuple[int, ...]
    fold_auc: np.ndarray   # [n_depths, n_estimators, k]
    mean_auc: np.ndarray   # [n_depths, n_estimators]
    best_n_estimators: int
    best_max_depth: int
    best_mean_auc: float


def cv_sweep(
    X: np.ndarray,
    y: np.ndarray,
    sweep: SweepConfig = SweepConfig(),
    base: GBDTConfig = GBDTConfig(),
    mesh=None,
) -> SweepResult:
    """Run the grid: ONE vmapped fit per depth covering all folds
    (``gbdt.fit_folds`` — mask-parked rows, fold axis batched), staged
    evaluation over the ``n_estimators`` axis. The whole sweep compiles
    ``len(max_depth_grid)`` fit programs; the reference-equivalent
    ``GridSearchCV`` refits every (cell × fold) from scratch.

    Dispatch structure (r4): every depth's fit is enqueued before any
    scoring transfer, so the device works through the fits back-to-back
    while the host computes earlier depths' AUCs; in the default
    shared-bins protocol, candidate bins are derived once and reused
    across depths (the bin budget is depth-independent — re-binning per
    depth repeated identical host work; the opt-in ``per_fold_binning``
    protocol still derives its per-fold candidates inside each depth's
    ``fit_folds`` call); scoring is ONE dispatch per depth covering all
    folds (``_staged_allfolds_jit``), with test folds padded to a common
    length and the pad rows sliced off before the host-side AUC.

    With ``mesh``, each (depth, fold) fit runs row-sharded through
    ``parallel.fit_gbdt_sharded`` (fold masks ride the trainers' weight
    path; SURVEY §2.5's "grid sharded across chips" axis), the fold
    results are stacked into the same batched-params layout the
    single-device path produces, and the staged holdout scoring runs
    row-sharded too (``apply_rows_sharded`` per fold). The mesh path uses
    the shared-bins protocol only."""
    import jax

    X = np.asarray(X)
    y = np.asarray(y)
    est_grid = tuple(sweep.n_estimators_grid)
    depth_grid = tuple(sweep.max_depth_grid)
    m_max = max(est_grid)
    test_masks = stratified_kfold_test_masks(y, sweep.cv_folds)
    train_masks = 1.0 - test_masks
    k = sweep.cv_folds

    if mesh is not None and base.per_fold_binning:
        raise ValueError(
            "cv_sweep(mesh=...) runs the shared-bins protocol only; "
            "per_fold_binning is a single-device option (fit_folds)"
        )

    # Shared candidate bins: bin_budget_capped depends on the bin config
    # only, not max_depth, so one host binning serves every depth. The
    # per-fold-binning protocol derives candidates inside fit_folds.
    bins = None
    if not base.per_fold_binning:
        from machine_learning_replications_tpu.ops import binning

        bins = binning.bin_features(X, gbdt.bin_budget_capped(base))

    # Phase 1: enqueue all depth fits (jitted → async); nothing below
    # forces a result until scoring, so the device queue never drains.
    params_by_depth = []
    for depth in depth_grid:
        cfg = dataclasses.replace(base, n_estimators=m_max, max_depth=depth)
        if mesh is None:
            params_by_depth.append(
                gbdt.fit_folds(X, y, train_masks, cfg, bins=bins)
            )
        else:
            from machine_learning_replications_tpu.parallel import (
                fit_gbdt_sharded,
            )

            per_fold = [
                fit_gbdt_sharded(
                    mesh, X, y, cfg,
                    sample_weight=np.asarray(train_masks[kk]), bins=bins,
                )[0]
                for kk in range(k)
            ]
            params_by_depth.append(
                jax.tree.map(lambda *leaves: jnp.stack(leaves), *per_fold)
            )

    # Phase 2: score each fold's HELD-OUT rows only (staging over the full
    # matrix then masking threw away 1−1/k of the tree-apply work —
    # measured ~4 s of an 8.6 s sweep at 20k rows), all folds in one
    # dispatch per depth. Fold sizes differ by ≤1 row (StratifiedKFold);
    # padding with row 0 keeps the batch rectangular and is sliced off
    # before the AUC.
    te_idx = [np.flatnonzero(tm > 0.5) for tm in test_masks]
    n_te = np.array([len(ix) for ix in te_idx])
    n_pad = int(n_te.max())
    padded = np.stack(
        [np.pad(ix, (0, n_pad - len(ix))) for ix in te_idx]
    )                                   # [k, n_pad] row ids (pad = row 0)
    X_te_all = X[padded]                # [k, n_pad, F]

    fold_auc = np.zeros((len(depth_grid), len(est_grid), k))
    staged_all = _staged_allfolds_jit(est_grid)
    for di, params in enumerate(params_by_depth):
        if mesh is None:
            probs = np.asarray(staged_all(params, X_te_all))  # [k, E, n_pad]
        else:
            # Mesh scoring: each fold's held-out rows sharded over 'data'
            # (the single-device [k, E, n_pad] batch would materialize the
            # whole held-out cohort — ~GBs at multi-million-row sweeps —
            # on one chip). Replicated per-fold params, per-row map.
            from machine_learning_replications_tpu.parallel.rowwise import (
                apply_rows_sharded,
            )

            # Enqueue every fold's dispatch before the first transfer —
            # a fold-by-fold np.asarray would serialize k RTT round trips
            # (the pattern _staged_allfolds_jit exists to avoid).
            pending = [
                apply_rows_sharded(
                    mesh, _staged_rows_fn(est_grid),
                    jax.tree.map(lambda a, kk=kk: a[kk], params),
                    X_te_all[kk],
                )
                for kk in range(k)
            ]
            probs = np.stack([np.asarray(p).T for p in pending])
        for kk in range(k):
            # Grid selection is a host-side decision (GridSearchCV's
            # cv_results_ analogue); the vectorized rank AUC evaluates all
            # n_estimators cells in one pass and matches
            # metrics.roc_auc's tie-averaged U statistic exactly.
            fold_auc[di, :, kk] = roc_auc_batch_host(
                y[te_idx[kk]], probs[kk][:, : n_te[kk]]
            )

    mean_auc = fold_auc.mean(axis=-1)
    di, ei = np.unravel_index(np.argmax(mean_auc), mean_auc.shape)
    return SweepResult(
        n_estimators_grid=est_grid,
        max_depth_grid=depth_grid,
        fold_auc=fold_auc,
        mean_auc=mean_auc,
        best_n_estimators=est_grid[ei],
        best_max_depth=depth_grid[di],
        best_mean_auc=float(mean_auc[di, ei]),
    )


def refit_best(
    X: np.ndarray,
    y: np.ndarray,
    result: SweepResult,
    base: GBDTConfig = GBDTConfig(),
    mesh=None,
) -> tuple[tree.TreeEnsembleParams, GBDTConfig]:
    """Refit the winning cell on the full data (``GridSearchCV(refit=True)``).

    With ``mesh`` the refit runs row-sharded (``parallel.fit_gbdt_sharded``)
    — a sweep that needed sharding to fit must not funnel its final refit
    through one device."""
    cfg = dataclasses.replace(
        base,
        n_estimators=result.best_n_estimators,
        max_depth=result.best_max_depth,
    )
    if mesh is not None:
        from machine_learning_replications_tpu.parallel import fit_gbdt_sharded

        params, _ = fit_gbdt_sharded(mesh, X, y, cfg)
    else:
        params, _ = gbdt.fit(X, y, cfg)
    return params, cfg
