"""L4 — the model layer (ensemble graph).

Each member is a pair of pure functions over an explicit parameter pytree
(``fit(...) -> params``, ``predict_proba(params, X) -> p``) — the functional
JAX re-design of the sklearn estimator objects the reference composes at
``train_ensemble_public.py:43-48``. Parameter pytrees are ``flax.struct``
dataclasses: jit-traceable, shardable, Orbax-serializable.
"""

from machine_learning_replications_tpu.models.scaler import ScalerParams
from machine_learning_replications_tpu.models.linear import LinearParams
from machine_learning_replications_tpu.models.svm import SVCParams
from machine_learning_replications_tpu.models.tree import TreeEnsembleParams
from machine_learning_replications_tpu.models.stacking import StackingParams

__all__ = [
    "ScalerParams",
    "LinearParams",
    "SVCParams",
    "TreeEnsembleParams",
    "StackingParams",
]
