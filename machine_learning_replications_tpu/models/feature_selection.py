"""L3 — Lasso-based feature selection.

Reference: ``LassoCV(random_state=2020, cv=10)`` wrapped in
``SelectFromModel(threshold=-inf, max_features=17)``
(``train_ensemble_public.py:51-55``): pick the top-17 features of 64 by
|lasso coefficient| at the CV-chosen alpha, then column-subset X and the
feature-name row. ``random_state`` is dead weight in the reference — with
``cv=10`` an int, KFold doesn't shuffle, so the procedure is deterministic;
our replication is deterministic by construction.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np

from machine_learning_replications_tpu.config import LassoSelectConfig
from machine_learning_replications_tpu.models import solvers


def _guard_rows(X, y, cfg: LassoSelectConfig, scale: int = 1):
    """Scaled-regime guard (pattern: ``SVCConfig.max_rows``): cap the
    device-resident cohort at ``cfg.max_rows × scale`` rows (``scale`` =
    data-axis size when a mesh shards the stats), by policy."""
    n = X.shape[0]
    cap = cfg.max_rows * scale
    if n <= cap:
        return X, y, None
    if cfg.scale_policy == "error":
        raise ValueError(
            f"Lasso selection: {n} rows exceeds LassoSelectConfig.max_rows="
            f"{cfg.max_rows} × {scale} device(s); set scale_policy="
            "'subsample', raise max_rows, or pass a larger mesh"
        )
    from machine_learning_replications_tpu.utils.cv import (
        stratified_subsample_indices,
    )

    idx = stratified_subsample_indices(np.asarray(y), cap, seed=2020)
    return np.asarray(X)[idx], np.asarray(y)[idx], int(n)


def fit_select(
    X: np.ndarray,
    y: np.ndarray,
    cfg: LassoSelectConfig = LassoSelectConfig(),
    mesh=None,
) -> tuple[np.ndarray, dict[str, Any]]:
    """Returns ``(support_mask [F] bool, info)`` like ``sfm.get_support()``.

    With ``mesh``, the O(n) Gram passes run row-sharded over 'data'
    (``parallel.select_trainer``); the CV path solve is row-free either way.
    """
    if mesh is not None:
        from machine_learning_replications_tpu.parallel.mesh import DATA_AXIS

        X, y, n_orig = _guard_rows(X, y, cfg, scale=mesh.shape[DATA_AXIS])
        from machine_learning_replications_tpu.parallel.select_trainer import (
            lasso_fold_stats_sharded,
        )

        stats = lasso_fold_stats_sharded(mesh, X, y, cfg.cv_folds)
        coef, intercept, alpha_, alphas, mse_path = solvers.lasso_cv_from_stats(
            stats, n_alphas=cfg.n_alphas, eps=cfg.eps,
            tol=cfg.tol, max_iter=cfg.max_iter,
        )
    else:
        X, y, n_orig = _guard_rows(X, y, cfg)
        coef, intercept, alpha_, alphas, mse_path = solvers.lasso_cv(
            jnp.asarray(X),
            jnp.asarray(y),
            cv_folds=cfg.cv_folds,
            n_alphas=cfg.n_alphas,
            eps=cfg.eps,
            tol=cfg.tol, max_iter=cfg.max_iter,
        )
    mask = select_top_k(np.asarray(coef), cfg.max_features)
    info = {
        "coef": np.asarray(coef),
        "intercept": float(intercept),
        "alpha_": float(alpha_),
        "alphas": np.asarray(alphas),
        "mse_path": np.asarray(mse_path),
    }
    if n_orig is not None:
        info["subsampled_from_rows"] = n_orig
    return mask, info


def select_top_k(coef: np.ndarray, k: int) -> np.ndarray:
    """sklearn SelectFromModel(threshold=-inf, max_features=k): top-k by
    |coef|, stable argsort (ties → higher index wins, as in sklearn)."""
    scores = np.abs(coef)
    mask = np.zeros(scores.shape[0], dtype=bool)
    mask[np.argsort(scores, kind="stable")[-k:]] = True
    return mask
