"""L3 — Lasso-based feature selection.

Reference: ``LassoCV(random_state=2020, cv=10)`` wrapped in
``SelectFromModel(threshold=-inf, max_features=17)``
(``train_ensemble_public.py:51-55``): pick the top-17 features of 64 by
|lasso coefficient| at the CV-chosen alpha, then column-subset X and the
feature-name row. ``random_state`` is dead weight in the reference — with
``cv=10`` an int, KFold doesn't shuffle, so the procedure is deterministic;
our replication is deterministic by construction.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
import numpy as np

from machine_learning_replications_tpu.config import LassoSelectConfig
from machine_learning_replications_tpu.models import solvers


def fit_select(
    X: np.ndarray,
    y: np.ndarray,
    cfg: LassoSelectConfig = LassoSelectConfig(),
) -> tuple[np.ndarray, dict[str, Any]]:
    """Returns ``(support_mask [F] bool, info)`` like ``sfm.get_support()``."""
    coef, intercept, alpha_, alphas, mse_path = solvers.lasso_cv(
        jnp.asarray(X),
        jnp.asarray(y),
        cv_folds=cfg.cv_folds,
        n_alphas=cfg.n_alphas,
        eps=cfg.eps,
        tol=cfg.tol, max_iter=cfg.max_iter,
    )
    mask = select_top_k(np.asarray(coef), cfg.max_features)
    info = {
        "coef": np.asarray(coef),
        "intercept": float(intercept),
        "alpha_": float(alpha_),
        "alphas": np.asarray(alphas),
        "mse_path": np.asarray(mse_path),
    }
    return mask, info


def select_top_k(coef: np.ndarray, k: int) -> np.ndarray:
    """sklearn SelectFromModel(threshold=-inf, max_features=k): top-k by
    |coef|, stable argsort (ties → higher index wins, as in sklearn)."""
    scores = np.abs(coef)
    mask = np.zeros(scores.shape[0], dtype=bool)
    mask[np.argsort(scores, kind="stable")[-k:]] = True
    return mask
