"""Logistic-regression members (inference side).

Covers both the L1/liblinear base member (``train_ensemble_public.py:46``)
and the L2/lbfgs meta learner (``:48``): at predict time both are
``σ(X·coef + intercept)`` (SURVEY.md §3.4). Training lives in
``models.solvers`` (FISTA for L1, Newton for L2).
"""

from __future__ import annotations

import flax.struct
import jax.numpy as jnp
import jax.scipy.special


@flax.struct.dataclass
class LinearParams:
    coef: jnp.ndarray       # [F]
    intercept: jnp.ndarray  # scalar


def decision_function(params: LinearParams, X: jnp.ndarray) -> jnp.ndarray:
    return X @ params.coef + params.intercept


def predict_proba1(params: LinearParams, X: jnp.ndarray) -> jnp.ndarray:
    """P(class 1); the [1−p, p] pairing happens at the stacking layer."""
    return jax.scipy.special.expit(decision_function(params, X))
