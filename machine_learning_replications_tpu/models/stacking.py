"""Stacking ensemble — the reference's L4 model graph.

``StackingClassifier(estimators=[svc-pipeline, gbc, lg],
final_estimator=LogisticRegression(class_weight='balanced'))``
(``train_ensemble_public.py:43-48``). Inference composes the members exactly
as SURVEY.md §3.4: each binary member contributes its P(class 1) as one
meta-feature column (sklearn drops the class-0 column), and the meta
logistic regression maps ``[p_svc, p_gbc, p_lg]`` to the final probability.

Everything here is a pure jittable function of a ``StackingParams`` pytree;
training orchestration (5-fold cross_val_predict meta-features) lives in
``fit.py``.
"""

from __future__ import annotations

from typing import Any

import flax.struct
import jax.numpy as jnp

from machine_learning_replications_tpu.models import linear, scaler, svm, tree


@flax.struct.dataclass
class StackingParams:
    scaler: scaler.ScalerParams      # inside the SVC pipeline only
    svc: svm.SVCParams
    gbdt: tree.TreeEnsembleParams
    logreg: linear.LinearParams      # L1 base member
    meta: linear.LinearParams        # final estimator over 3 meta-features
    # Optional training reference profile for drift monitoring
    # (``obs.quality.build_reference_profile`` over the contract-order
    # ``X[n, 17]`` this family scores), the same dict-of-arrays pytree
    # ``PipelineParams.quality`` carries. Defaults to ``None`` so
    # pre-profile checkpoints (and the sklearn import path, which has no
    # training matrix) restore unchanged; the continual-learning refit
    # (``learn.retrain``) attaches one so a promoted candidate ships its
    # own drift baseline.
    quality: Any = None


def member_probas(params: StackingParams, X: jnp.ndarray) -> jnp.ndarray:
    """Meta-feature matrix ``[n, 3]`` = P(class 1) per member, in the
    reference's estimator order (svc, gbc, lg)."""
    p_svc = svm.predict_proba1(params.svc, scaler.transform(params.scaler, X))
    p_gbc = tree.predict_proba1(params.gbdt, X)
    p_lg = linear.predict_proba1(params.logreg, X)
    return jnp.stack([p_svc, p_gbc, p_lg], axis=-1)


def predict_proba1(params: StackingParams, X: jnp.ndarray) -> jnp.ndarray:
    """Final P(class 1) for each row of ``X[n, 17]``."""
    return linear.predict_proba1(params.meta, member_probas(params, X))


def predict_proba1_with_members(
    params: StackingParams, X: jnp.ndarray
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """``(p1[n], members[n, 3])`` — the blended probability plus the member
    meta-features it was blended from. Member outputs are already computed
    on the way to ``p1``; exposing them costs nothing extra and feeds the
    serving quality monitor's ensemble-agreement tracking
    (``obs.quality``): mean pairwise member disagreement is a drift signal
    the blended probability alone hides (members can move in opposite
    directions and cancel)."""
    m = member_probas(params, X)
    return linear.predict_proba1(params.meta, m), m


def predict_proba(params: StackingParams, X: jnp.ndarray) -> jnp.ndarray:
    """``[n, 2]`` = [1−p, p], matching sklearn's column layout
    (``predict_hf.py:36-40`` reads column 1)."""
    p = predict_proba1(params, X)
    return jnp.stack([1.0 - p, p], axis=-1)
