"""Decision-tree ensembles as dense SoA tensors.

The reference's trees are Cython ``sklearn.tree._tree.Tree`` objects (node
structs with pointers, reached from ``GradientBoostingClassifier`` at
``train_ensemble_public.py:45``). Here a forest is five same-shaped arrays —
``feature/threshold/left/right/value``, each ``[n_trees, n_nodes]`` — so
applying all trees to all rows is a pair of vectorized gathers, batched over
trees with ``vmap``, with no data-dependent control flow (SURVEY.md §2.4:
"tree arrays as dense JAX tensors (SoA)").

Routing convention (sklearn-compatible): go left iff ``x[feature] <= threshold``.
Leaves are self-loops (``left == right == self``), so descending ``max_depth``
steps from the root always lands on — and stays at — the correct leaf.
"""

from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp


@flax.struct.dataclass
class TreeEnsembleParams:
    feature: jnp.ndarray    # [T, N] int32 — split feature (0 at leaves)
    threshold: jnp.ndarray  # [T, N] float — split threshold (+inf at leaves)
    left: jnp.ndarray       # [T, N] int32 — child if x[f] <= thr (self at leaves)
    right: jnp.ndarray      # [T, N] int32 — child otherwise (self at leaves)
    value: jnp.ndarray      # [T, N] float — leaf prediction (0 at internals)
    init_raw: jnp.ndarray   # scalar — F₀ (prior log-odds for binomial deviance)
    learning_rate: jnp.ndarray  # scalar — stage shrinkage (0.1 in the reference)
    max_depth: int = flax.struct.field(pytree_node=False, default=1)


def apply_one_tree(
    feature: jnp.ndarray,
    threshold: jnp.ndarray,
    left: jnp.ndarray,
    right: jnp.ndarray,
    value: jnp.ndarray,
    X: jnp.ndarray,
    max_depth: int,
) -> jnp.ndarray:
    """Evaluate one tree on ``X[n, F]`` → leaf values ``[n]``.

    ``max_depth`` unrolled descent steps; each step is two gathers and a
    select — branch-free, so XLA vectorizes it across the whole batch.
    """
    idx = jnp.zeros(X.shape[0], dtype=jnp.int32)
    rows = jnp.arange(X.shape[0])
    for _ in range(max_depth):
        f = feature[idx]
        go_left = X[rows, f] <= threshold[idx]
        idx = jnp.where(go_left, left[idx], right[idx])
    return value[idx]


def apply(params: TreeEnsembleParams, X: jnp.ndarray) -> jnp.ndarray:
    """All trees on all rows → ``[T, n]`` leaf values (vmapped over trees).

    Depth 1 (the reference's flagship shape) takes a specialized route: one
    ``[n, T]`` gather of each stump's root-split column, a broadcast
    compare, and a two-way select — no per-row node indices at all. The
    generic unrolled descent costs a ``[T, n]`` row-gather per level, which
    TPU serializes far more aggressively (measured 1.3 s vs ~ms for 100
    stumps on 200k rows on v5e).
    """
    X = jnp.asarray(X)
    if params.max_depth == 1:
        f0 = params.feature[:, 0]                  # [T] root split features
        thr0 = params.threshold[:, 0]              # [T]
        lchild = params.left[:, 0]                 # [T] (self-loop 0 if no split)
        rchild = params.right[:, 0]
        t_idx = jnp.arange(f0.shape[0])
        lv = params.value[t_idx, lchild]           # [T] left-leaf values
        rv = params.value[t_idx, rchild]           # [T]
        Xg = X[:, f0]                              # [n, T] single gather
        return jnp.where(Xg <= thr0[None, :], lv[None, :], rv[None, :]).T
    return jax.vmap(
        lambda f, t, l, r, v: apply_one_tree(f, t, l, r, v, X, params.max_depth)
    )(params.feature, params.threshold, params.left, params.right, params.value)


def raw_score(params: TreeEnsembleParams, X: jnp.ndarray) -> jnp.ndarray:
    """Boosted raw score: ``F₀ + lr · Σ_t tree_t(X)`` (SURVEY.md §3.4)."""
    contrib = apply(params, X)  # [T, n]
    return params.init_raw + params.learning_rate * jnp.sum(contrib, axis=0)


def predict_proba1(params: TreeEnsembleParams, X: jnp.ndarray) -> jnp.ndarray:
    """P(class 1) = σ(raw) — binomial-deviance link."""
    return jax.scipy.special.expit(raw_score(params, X))
