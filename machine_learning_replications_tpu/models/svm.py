"""RBF support-vector classifier (inference side).

Reference member: ``SVC(class_weight='balanced', probability=True)`` inside a
StandardScaler pipeline (``train_ensemble_public.py:44``), solved by libsvm
(C++). Here the kernel evaluation is one MXU matmul against the support set
(``ops.linalg.rbf_kernel``) and the probability path reproduces libsvm's
binary semantics *exactly* — including its two quirks:

  1. the pairwise Platt probability is clipped to ``[1e-7, 1 - 1e-7]``;
  2. binary class probabilities still go through libsvm's iterative
     pairwise-coupling solver (``multiclass_probability``), which stops at
     tolerance ``0.005/k`` — so its output differs from the plain sigmoid by
     up to ~3e-3. We replicate the iteration (vectorized over samples, with
     per-sample converged-lane masking) rather than the closed form, to hold
     bitwise-level parity with sklearn/libsvm ``predict_proba``.

Sign conventions (verified empirically against sklearn on both label
orderings): with the *public* pickled fields,
``dec = K(X, SV) @ dual_coef + intercept`` and libsvm's internal decision
value is ``f = -dec`` with internal label order ``[classes_[0], classes_[1]]``.
Platt then gives ``r₀ = σ(-(A·f + B))`` as the pairwise probability of class 0.

Training (dual QP + Platt calibration) is the second half of this module
(``svc_fit`` and friends).
"""

from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy.special import expit

from machine_learning_replications_tpu.ops.linalg import rbf_kernel

_MIN_PROB = 1e-7  # libsvm svm_predict_probability clipping
_COUPLING_MAX_ITER = 100  # libsvm: max(100, k)
_COUPLING_EPS = 0.005 / 2  # libsvm: 0.005 / k, k = 2


@flax.struct.dataclass
class SVCParams:
    support_vectors: jnp.ndarray  # [S, F] (in scaler-transformed space)
    dual_coef: jnp.ndarray        # [S] — public-convention y_i α_i
    intercept: jnp.ndarray        # scalar — public convention
    gamma: jnp.ndarray            # scalar — fitted γ (1/(F·var) for 'scale')
    prob_a: jnp.ndarray           # scalar — libsvm _probA
    prob_b: jnp.ndarray           # scalar — libsvm _probB


def decision_function(params: SVCParams, Xt: jnp.ndarray) -> jnp.ndarray:
    """``dec[n]`` over *scaler-transformed* inputs; positive → class 1."""
    K = rbf_kernel(Xt, params.support_vectors, params.gamma)
    return K @ params.dual_coef + params.intercept


@jax.jit
def _binary_coupling(r0: jnp.ndarray) -> jnp.ndarray:
    """libsvm ``multiclass_probability`` specialized to k=2, vectorized.

    ``r0`` is the clipped pairwise probability of class 0. Returns P(class 1).
    The exact optimum is ``p0 = r0``; libsvm stops the iteration early at
    ``eps = 0.0025``, and parity requires replicating that trajectory from
    the ``p = [0.5, 0.5]`` start, including the mid-update renormalizations.

    Jitted at module level: called eagerly, the ``fori_loop``'s body is a
    fresh closure per call, and JAX's control-flow jaxpr cache keys on the
    body function's identity — every *call* paid a full XLA re-compile of
    the same scan (~90 ms on the bench CPU, found driving the bulk-scoring
    pipeline where it recompiled once per streamed chunk, and silently
    taxing every serving flush the same way). The jit caches on ``r0``'s
    shape, so the coupling iteration compiles once per batch shape like
    every other op in the predict tail.
    """
    r1 = 1.0 - r0
    q00, q01, q11 = r1 * r1, -r1 * r0, r0 * r0

    def body(_, state):
        p0, p1, done = state
        qp0 = q00 * p0 + q01 * p1
        qp1 = q01 * p0 + q11 * p1
        pqp = p0 * qp0 + p1 * qp1
        err = jnp.maximum(jnp.abs(qp0 - pqp), jnp.abs(qp1 - pqp))
        done = done | (err < _COUPLING_EPS)

        # t = 0 update (libsvm also updates Qp[0] here; it is recomputed from
        # p at the top of the next iteration, so we don't carry it)
        diff = (-qp0 + pqp) / q00
        n_p0 = p0 + diff
        n_pqp = (pqp + diff * (2 * qp0 + diff * q00)) / ((1 + diff) ** 2)
        n_qp1 = (qp1 + diff * q01) / (1 + diff)
        n_p0, n_p1 = n_p0 / (1 + diff), p1 / (1 + diff)
        # t = 1 update
        diff = (-n_qp1 + n_pqp) / q11
        n_p1 = n_p1 + diff
        n_p0, n_p1 = n_p0 / (1 + diff), n_p1 / (1 + diff)

        p0 = jnp.where(done, p0, n_p0)
        p1 = jnp.where(done, p1, n_p1)
        return p0, p1, done

    p0 = jnp.full_like(r0, 0.5)
    p1 = jnp.full_like(r0, 0.5)
    done = jnp.zeros_like(r0, dtype=bool)
    p0, p1, _ = jax.lax.fori_loop(0, _COUPLING_MAX_ITER, body, (p0, p1, done))
    return p1


def predict_proba1(params: SVCParams, Xt: jnp.ndarray) -> jnp.ndarray:
    """P(class 1), exact libsvm binary semantics (see module docstring)."""
    dec = decision_function(params, Xt)
    f = -dec  # libsvm internal orientation
    r0 = expit(-(params.prob_a * f + params.prob_b))
    r0 = jnp.clip(r0, _MIN_PROB, 1.0 - _MIN_PROB)
    return _binary_coupling(r0)


_predict_proba1_jit = jax.jit(predict_proba1)


def predict_proba1_chunked(
    params: SVCParams, Xt, chunk_rows: int = 65_536
) -> np.ndarray:
    """``predict_proba1`` over row chunks, bounding the ``[chunk, n_sv]``
    kernel block in memory (the scaled-regime predict path — at 10M rows a
    single kernel evaluation against even a trimmed support set would not
    fit). The last chunk is zero-padded so every block shares one compiled
    shape. Host-side by design: returns numpy."""
    Xt = np.asarray(Xt)
    n = Xt.shape[0]
    if n <= chunk_rows:
        return np.asarray(_predict_proba1_jit(params, jnp.asarray(Xt)))
    out = np.empty(n, dtype=Xt.dtype)
    for s in range(0, n, chunk_rows):
        block = Xt[s : s + chunk_rows]
        if block.shape[0] < chunk_rows:  # pad the tail to the shared shape
            block = np.pad(block, ((0, chunk_rows - block.shape[0]), (0, 0)))
        out[s : s + chunk_rows] = np.asarray(
            _predict_proba1_jit(params, jnp.asarray(block))
        )[: n - s]
    return out


def predict_proba1_sigmoid(params: SVCParams, Xt: jnp.ndarray) -> jnp.ndarray:
    """Closed-form Platt probability (the coupling fixed point).

    Within 3e-3 of ``predict_proba1`` and cheaper; use where sklearn-bitwise
    parity is not required.
    """
    dec = decision_function(params, Xt)
    return expit(params.prob_b - params.prob_a * dec)


# ---------------------------------------------------------------------------
# Training: dual QP + Platt calibration (replaces libsvm's SMO — SURVEY.md §2.4)
# ---------------------------------------------------------------------------
#
# libsvm's SMO updates two coordinates per iteration — inherently sequential.
# The TPU-native solver is accelerated projected gradient on the same dual
#       max_α 1ᵀα − ½ αᵀ(ssᵀ⊙K)α   s.t. 0 ≤ α_i ≤ C_i,  sᵀα = 0,
# whose every iteration is one n×n matvec (MXU) plus a vectorized projection
# onto the box∩hyperplane (bisection on the hyperplane multiplier). The
# problem is convex ⇒ same optimum; parity is at the decision-function /
# metric level (SURVEY.md §7 "SVC on TPU").
#
# Per-sample C_i doubles as the fold mask: rows with C_i = 0 are frozen at
# α = 0, so Platt's CV sub-solves vmap over masks with one static shape.


def _project_box_hyperplane(v, s, C, iters: int = 64):
    """Project v onto {0 ≤ α ≤ C} ∩ {sᵀα = 0} (Euclidean).

    α(λ) = clip(v − λ s, 0, C); g(λ) = sᵀα(λ) is nonincreasing — bisect.
    """
    bound = jnp.max(jnp.abs(v)) + jnp.max(C) + 1.0
    lo = jnp.full((), -1.0, v.dtype) * bound
    hi = jnp.full((), 1.0, v.dtype) * bound

    def body(_, state):
        lo, hi = state
        mid = 0.5 * (lo + hi)
        g = jnp.sum(s * jnp.clip(v - mid * s, 0.0, C))
        return jnp.where(g > 0, mid, lo), jnp.where(g > 0, hi, mid)

    lo, hi = jax.lax.fori_loop(0, iters, body, (lo, hi))
    lam = 0.5 * (lo + hi)
    return jnp.clip(v - lam * s, 0.0, C)


_KKT_CHECK_EVERY = 8  # optimality matvec every k iterations (~12% overhead)


def solve_dual(K, s, C, tol: float = 1e-5, max_iter: int = 3000):
    """Accelerated projected-gradient ascent on the SVC dual.

    Returns α. ``C`` is per-sample (class weights × C × fold mask).
    Stops on libsvm's own optimality measure — the maximal KKT violation
    ``m(α) − M(α)`` over the working sets (``svm.cpp select_working_set``) —
    evaluated every ``_KKT_CHECK_EVERY`` iterations, so ``SVCConfig.tol``
    means exactly what sklearn's ``SVC(tol=...)`` means rather than a
    looser iterate-change proxy (ADVICE r2). Composes with ``vmap`` (the
    Platt CV lanes run until all converge).
    """
    from machine_learning_replications_tpu.models.solvers import _power_lmax

    Q = (s[:, None] * s[None, :]) * K
    step = 1.0 / jnp.maximum(_power_lmax(Q), 1e-12)
    inf = jnp.asarray(jnp.inf, s.dtype)

    def kkt_violation(a):
        # libsvm minimizes f(α) = ½αᵀQα − 1ᵀα over {0≤α≤C, sᵀα=0};
        # v_i = −s_i ∇f_i; stop when max_{I_up} v − min_{I_low} v ≤ tol.
        v = -s * (Q @ a - 1.0)
        active = C > 0  # fold-masked rows are frozen at α=0, outside both sets
        up = (((s > 0) & (a < C)) | ((s < 0) & (a > 0))) & active
        low = (((s > 0) & (a > 0)) | ((s < 0) & (a < C))) & active
        m = jnp.max(jnp.where(up, v, -inf))
        M = jnp.min(jnp.where(low, v, inf))
        return m - M

    def cond(state):
        _, _, _, it, viol = state
        return (it < max_iter) & (viol >= tol)

    def fista_step(_, carry):
        a, z, tk = carry
        grad = 1.0 - Q @ z
        a_new = _project_box_hyperplane(z + step * grad, s, C)
        t_new = 0.5 * (1.0 + jnp.sqrt(1.0 + 4.0 * tk * tk))
        z = a_new + ((tk - 1.0) / t_new) * (a_new - a)
        # keep the extrapolated point feasible enough: re-clip the box
        z = jnp.clip(z, 0.0, C)
        return a_new, z, t_new

    def body(state):
        # A fixed 8-step inner block followed by ONE optimality matvec —
        # rather than a lax.cond on the iteration count, which under vmap
        # (the Platt-CV / fold-fit lanes) lowers to a both-branches select
        # and would pay the KKT matvec every iteration.
        a, z, tk, it, _ = state
        a, z, tk = jax.lax.fori_loop(
            0, _KKT_CHECK_EVERY, fista_step, (a, z, tk)
        )
        return a, z, tk, it + _KKT_CHECK_EVERY, kkt_violation(a)

    a0 = jnp.zeros_like(s)
    a, _, _, _, _ = jax.lax.while_loop(
        cond, body,
        (a0, a0, jnp.asarray(1.0, s.dtype), jnp.asarray(0, jnp.int32), inf),
    )
    return a


def _intercept_from_alpha(K, s, C, alpha):
    """b from KKT: mean of s_i − f_i over free SVs; midpoint fallback."""
    f = K @ (alpha * s)
    tau = 1e-8 * jnp.maximum(jnp.max(C), 1.0)
    free = (alpha > tau) & (alpha < C - tau) & (C > 0)
    n_free = jnp.sum(free)
    b_free = jnp.sum(jnp.where(free, s - f, 0.0)) / jnp.maximum(n_free, 1)
    # fallback (no free SVs): midpoint of the KKT-feasible interval for b
    # (libsvm calculate_rho). Lower bounds b >= s-f come from rows that could
    # still increase their contribution (α<C, s=+1) or decrease it (α>0, s=−1);
    # upper bounds b <= s-f from the mirrored sets.
    lower = (((alpha < C - tau) & (s > 0)) | ((alpha > tau) & (s < 0))) & (C > 0)
    upper = (((alpha < C - tau) & (s < 0)) | ((alpha > tau) & (s > 0))) & (C > 0)
    lo_b = jnp.max(jnp.where(lower, s - f, -jnp.inf))
    hi_b = jnp.min(jnp.where(upper, s - f, jnp.inf))
    b_mid = 0.5 * (lo_b + hi_b)
    return jnp.where(n_free > 0, b_free, b_mid)


def platt_sigmoid_train(dec, y, sample_mask=None, n_iter: int = 100):
    """libsvm ``sigmoid_train``: Newton fit of (A, B) on held-out decision
    values with Platt's smoothed targets. Deterministic given (dec, y)."""
    mask = jnp.ones_like(dec) if sample_mask is None else sample_mask
    prior1 = jnp.sum(jnp.where(y > 0.5, mask, 0.0))
    prior0 = jnp.sum(mask) - prior1
    hi = (prior1 + 1.0) / (prior1 + 2.0)
    lo = 1.0 / (prior0 + 2.0)
    t = jnp.where(y > 0.5, hi, lo)
    sigma = 1e-12

    def nll(ab):
        A, B = ab[0], ab[1]
        fApB = dec * A + B
        # log(1 + e^{fApB}) − t·fApB, numerically stable
        l = jnp.logaddexp(0.0, fApB) - t * fApB
        return jnp.sum(l * mask)

    grad_fn = jax.grad(nll)

    def body(_, ab):
        A, B = ab[0], ab[1]
        fApB = dec * A + B
        p = expit(fApB)
        d1 = (p - t) * mask
        d2 = p * (1.0 - p) * mask
        g = jnp.stack([jnp.sum(dec * d1), jnp.sum(d1)])
        h11 = jnp.sum(dec * dec * d2) + sigma
        h22 = jnp.sum(d2) + sigma
        h12 = jnp.sum(dec * d2)
        det = h11 * h22 - h12 * h12
        dA = -(h22 * g[0] - h12 * g[1]) / det
        dB = -(-h12 * g[0] + h11 * g[1]) / det
        step = jnp.stack([dA, dB])
        # backtracking line search (libsvm halves until decrease)
        f0 = nll(ab)

        def ls_body(state):
            stepsize, _ = state
            return stepsize * 0.5, nll(ab + stepsize * 0.5 * step)

        def ls_cond(state):
            stepsize, fnew = state
            return (fnew > f0 + 1e-4 * stepsize * (g @ step)) & (stepsize > 1e-10)

        stepsize, _ = jax.lax.while_loop(
            ls_cond, ls_body, (jnp.asarray(2.0, dec.dtype), jnp.asarray(jnp.inf, dec.dtype))
        )
        return ab + stepsize * step

    # Our orientation is P(t=1) = σ(A·dec + B) (libsvm fits the mirrored
    # σ(-(A·f+B))), so the prior-matching init is log((n₊+1)/(n₋+1)).
    ab0 = jnp.stack(
        [jnp.asarray(0.0, dec.dtype), jnp.log((prior1 + 1.0) / (prior0 + 1.0))]
    )
    ab = jax.lax.fori_loop(0, n_iter, body, ab0)
    return ab[0], ab[1]


def scale_gamma(Xt: jnp.ndarray) -> jnp.ndarray:
    """sklearn ``gamma='scale'``: 1 / (n_features · X.var()) over all entries."""
    return 1.0 / (Xt.shape[1] * jnp.var(Xt))


def svc_fit(
    Xt: jnp.ndarray,
    y: jnp.ndarray,
    C: float = 1.0,
    gamma=None,
    balanced: bool = True,
    probability: bool = True,
    platt_cv: int = 5,
    tol: float = 1e-5,
    max_iter: int = 20_000,
) -> SVCParams:
    """Fit the RBF SVC on *scaler-transformed* data.

    One full dual solve plus (for Platt) ``platt_cv`` masked fold solves,
    vmapped — the reference runs these six libsvm solves sequentially
    (SURVEY.md §3.2 "HOT LOOP #2"). Platt's CV uses deterministic
    stratified-contiguous folds where libsvm shuffles with its own C rand();
    probability parity is therefore metric-level (SURVEY.md §7).

    All rows are kept as "support vectors" (zero-coefficient rows are inert
    in the decision function); callers can compact with ``trim_support``.
    """
    from machine_learning_replications_tpu.utils.cv import (
        stratified_kfold_test_masks,
    )

    Xt = jnp.asarray(Xt)
    y = jnp.asarray(y)
    dtype = Xt.dtype
    n = Xt.shape[0]
    s = (2.0 * y - 1.0).astype(dtype)
    if gamma is None:
        gamma = scale_gamma(Xt)
    from machine_learning_replications_tpu.models.solvers import balanced_class_weights

    K = rbf_kernel(Xt, Xt, gamma)
    cw = (
        balanced_class_weights(y).astype(dtype) if balanced else jnp.ones(n, dtype)
    )
    Cvec = C * cw

    alpha = solve_dual(K, s, Cvec, tol, max_iter)
    b = _intercept_from_alpha(K, s, Cvec, alpha)

    if probability:
        test_masks = jnp.asarray(
            stratified_kfold_test_masks(np.asarray(y), platt_cv), dtype
        )
        train_masks = 1.0 - test_masks

        def fold_dec(train_mask, test_mask):
            Cf = Cvec * train_mask
            af = solve_dual(K, s, Cf, tol, max_iter)
            bf = _intercept_from_alpha(K, s, Cf, af)
            return (K @ (af * s) + bf) * test_mask

        dec_cv = jnp.sum(jax.vmap(fold_dec)(train_masks, test_masks), axis=0)
        A_fit, B_fit = platt_sigmoid_train(dec_cv, y.astype(dtype))
        # Stored convention (see predict_proba1): P(class 0) = σ(A·dec − B)
        prob_a, prob_b = -A_fit, B_fit
    else:
        prob_a = jnp.asarray(jnp.nan, dtype)
        prob_b = jnp.asarray(jnp.nan, dtype)

    return SVCParams(
        support_vectors=Xt,
        dual_coef=alpha * s,
        intercept=b,
        gamma=jnp.asarray(gamma, dtype),
        prob_a=prob_a,
        prob_b=prob_b,
    )


def svc_fit_masked(
    Xt: jnp.ndarray,            # [n, F] scaler-transformed (fold scaler)
    y: jnp.ndarray,             # [n]
    train_mask: jnp.ndarray,    # [n] 1.0 = row in this fit
    platt_test_masks: jnp.ndarray,  # [k, n] Platt-CV test masks ⊂ train_mask
    C: float = 1.0,
    gamma=None,
    balanced: bool = True,
    tol: float = 1e-5,
    max_iter: int = 20_000,
) -> SVCParams:
    """``svc_fit`` over a masked row subset with static shapes — the unit of
    the stacking CV's vmapped fold fan-out (SURVEY.md §3.2: the reference
    runs its 5 fold fits + nested Platt solves strictly sequentially).

    Masking rides the dual formulation: a row with ``C_i = 0`` can never
    receive dual weight, so ``Cvec · train_mask`` excludes it from the fit
    while keeping every shape fold-independent. Excluded rows stay in the
    support-vector array with zero coefficient (inert at predict time).
    ``gamma=None`` reproduces sklearn's ``'scale'`` from the masked rows.
    """
    from machine_learning_replications_tpu.models.solvers import (
        balanced_class_weights_masked,
    )

    Xt = jnp.asarray(Xt)
    y = jnp.asarray(y)
    dtype = Xt.dtype
    n = Xt.shape[0]
    m = train_mask.astype(dtype)
    s = (2.0 * y - 1.0).astype(dtype)
    if gamma is None:
        # masked 'scale': 1 / (F · var(train rows, all entries))
        n_eff = jnp.sum(m) * Xt.shape[1]
        mu = jnp.sum(Xt * m[:, None]) / n_eff
        var = jnp.sum(((Xt - mu) ** 2) * m[:, None]) / n_eff
        gamma = 1.0 / (Xt.shape[1] * var)

    K = rbf_kernel(Xt, Xt, gamma)
    cw = (
        balanced_class_weights_masked(y, m).astype(dtype)
        if balanced
        else jnp.ones(n, dtype)
    )
    Cvec = C * cw * m

    alpha = solve_dual(K, s, Cvec, tol, max_iter)
    b = _intercept_from_alpha(K, s, Cvec, alpha)

    def fold_dec(test_mask):
        Cf = Cvec * (1.0 - test_mask)
        af = solve_dual(K, s, Cf, tol, max_iter)
        bf = _intercept_from_alpha(K, s, Cf, af)
        return (K @ (af * s) + bf) * test_mask

    dec_cv = jnp.sum(jax.vmap(fold_dec)(platt_test_masks.astype(dtype)), axis=0)
    A_fit, B_fit = platt_sigmoid_train(dec_cv, y.astype(dtype), sample_mask=m)
    return SVCParams(
        support_vectors=Xt,
        dual_coef=alpha * s,
        intercept=b,
        gamma=jnp.asarray(gamma, dtype),
        prob_a=-A_fit,
        prob_b=B_fit,
    )


def trim_support(params: SVCParams, tol: float = 1e-10) -> SVCParams:
    """Drop zero-coefficient rows (host-side; dynamic shapes)."""
    keep = np.abs(np.asarray(params.dual_coef)) > tol
    return SVCParams(
        support_vectors=jnp.asarray(np.asarray(params.support_vectors)[keep]),
        dual_coef=jnp.asarray(np.asarray(params.dual_coef)[keep]),
        intercept=params.intercept,
        gamma=params.gamma,
        prob_a=params.prob_a,
        prob_b=params.prob_b,
    )
