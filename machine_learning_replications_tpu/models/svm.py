"""RBF support-vector classifier (inference side).

Reference member: ``SVC(class_weight='balanced', probability=True)`` inside a
StandardScaler pipeline (``train_ensemble_public.py:44``), solved by libsvm
(C++). Here the kernel evaluation is one MXU matmul against the support set
(``ops.linalg.rbf_kernel``) and the probability path reproduces libsvm's
binary semantics *exactly* — including its two quirks:

  1. the pairwise Platt probability is clipped to ``[1e-7, 1 - 1e-7]``;
  2. binary class probabilities still go through libsvm's iterative
     pairwise-coupling solver (``multiclass_probability``), which stops at
     tolerance ``0.005/k`` — so its output differs from the plain sigmoid by
     up to ~3e-3. We replicate the iteration (vectorized over samples, with
     per-sample converged-lane masking) rather than the closed form, to hold
     bitwise-level parity with sklearn/libsvm ``predict_proba``.

Sign conventions (verified empirically against sklearn on both label
orderings): with the *public* pickled fields,
``dec = K(X, SV) @ dual_coef + intercept`` and libsvm's internal decision
value is ``f = -dec`` with internal label order ``[classes_[0], classes_[1]]``.
Platt then gives ``r₀ = σ(-(A·f + B))`` as the pairwise probability of class 0.

Training (dual QP + Platt calibration) lives in ``models.solvers.svc_fit``.
"""

from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp
from jax.scipy.special import expit

from machine_learning_replications_tpu.ops.linalg import rbf_kernel

_MIN_PROB = 1e-7  # libsvm svm_predict_probability clipping
_COUPLING_MAX_ITER = 100  # libsvm: max(100, k)
_COUPLING_EPS = 0.005 / 2  # libsvm: 0.005 / k, k = 2


@flax.struct.dataclass
class SVCParams:
    support_vectors: jnp.ndarray  # [S, F] (in scaler-transformed space)
    dual_coef: jnp.ndarray        # [S] — public-convention y_i α_i
    intercept: jnp.ndarray        # scalar — public convention
    gamma: jnp.ndarray            # scalar — fitted γ (1/(F·var) for 'scale')
    prob_a: jnp.ndarray           # scalar — libsvm _probA
    prob_b: jnp.ndarray           # scalar — libsvm _probB


def decision_function(params: SVCParams, Xt: jnp.ndarray) -> jnp.ndarray:
    """``dec[n]`` over *scaler-transformed* inputs; positive → class 1."""
    K = rbf_kernel(Xt, params.support_vectors, params.gamma)
    return K @ params.dual_coef + params.intercept


def _binary_coupling(r0: jnp.ndarray) -> jnp.ndarray:
    """libsvm ``multiclass_probability`` specialized to k=2, vectorized.

    ``r0`` is the clipped pairwise probability of class 0. Returns P(class 1).
    The exact optimum is ``p0 = r0``; libsvm stops the iteration early at
    ``eps = 0.0025``, and parity requires replicating that trajectory from
    the ``p = [0.5, 0.5]`` start, including the mid-update renormalizations.
    """
    r1 = 1.0 - r0
    q00, q01, q11 = r1 * r1, -r1 * r0, r0 * r0

    def body(_, state):
        p0, p1, done = state
        qp0 = q00 * p0 + q01 * p1
        qp1 = q01 * p0 + q11 * p1
        pqp = p0 * qp0 + p1 * qp1
        err = jnp.maximum(jnp.abs(qp0 - pqp), jnp.abs(qp1 - pqp))
        done = done | (err < _COUPLING_EPS)

        # t = 0 update (libsvm also updates Qp[0] here; it is recomputed from
        # p at the top of the next iteration, so we don't carry it)
        diff = (-qp0 + pqp) / q00
        n_p0 = p0 + diff
        n_pqp = (pqp + diff * (2 * qp0 + diff * q00)) / ((1 + diff) ** 2)
        n_qp1 = (qp1 + diff * q01) / (1 + diff)
        n_p0, n_p1 = n_p0 / (1 + diff), p1 / (1 + diff)
        # t = 1 update
        diff = (-n_qp1 + n_pqp) / q11
        n_p1 = n_p1 + diff
        n_p0, n_p1 = n_p0 / (1 + diff), n_p1 / (1 + diff)

        p0 = jnp.where(done, p0, n_p0)
        p1 = jnp.where(done, p1, n_p1)
        return p0, p1, done

    p0 = jnp.full_like(r0, 0.5)
    p1 = jnp.full_like(r0, 0.5)
    done = jnp.zeros_like(r0, dtype=bool)
    p0, p1, _ = jax.lax.fori_loop(0, _COUPLING_MAX_ITER, body, (p0, p1, done))
    return p1


def predict_proba1(params: SVCParams, Xt: jnp.ndarray) -> jnp.ndarray:
    """P(class 1), exact libsvm binary semantics (see module docstring)."""
    dec = decision_function(params, Xt)
    f = -dec  # libsvm internal orientation
    r0 = expit(-(params.prob_a * f + params.prob_b))
    r0 = jnp.clip(r0, _MIN_PROB, 1.0 - _MIN_PROB)
    return _binary_coupling(r0)


def predict_proba1_sigmoid(params: SVCParams, Xt: jnp.ndarray) -> jnp.ndarray:
    """Closed-form Platt probability (the coupling fixed point).

    Within 3e-3 of ``predict_proba1`` and cheaper; use where sklearn-bitwise
    parity is not required.
    """
    dec = decision_function(params, Xt)
    return expit(params.prob_b - params.prob_a * dec)
