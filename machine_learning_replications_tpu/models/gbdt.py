"""Gradient-boosted trees — training (the north-star centerpiece).

Reference member: ``GradientBoostingClassifier(n_estimators=100, max_depth=1,
random_state=2020)`` (``train_ensemble_public.py:45``), solved by sklearn's
Cython tree builder. This is the TPU-native re-design (SURVEY.md §7.4):

  * features quantized once (``ops.binning``; exact-midpoint regime on the
    HF cohort ⇒ sklearn-identical thresholds);
  * each boosting stage builds its tree level-by-level with vectorized
    per-(node, feature, bin) histograms and friedman split selection
    (``ops.histogram``) — no data-dependent Python control flow;
  * the stage loop is a ``lax.fori_loop`` writing into preallocated
    ``[n_stages, n_nodes]`` forest tensors, so the whole fit is one XLA
    program (device round-trips stay out of the loop — SURVEY.md §7
    "latency-bound at 713 rows");
  * trees live in heap layout (root 0, children 2i+1/2i+2); non-split nodes
    self-loop, matching ``models.tree``'s fixed-depth descent.

Numerics match sklearn's binomial-deviance GBC: F₀ = prior log-odds,
residual r = y − σ(F), leaves re-valued by the Newton step Σr / Σp(1−p)
(guarded at |den| < 1e-150 like ``_update_terminal_region``), stage update
F += lr·leaf, and ``train_deviance[m] = −2·mean(y·F − log(1+eᶠ))`` — the
pickle's ``train_score_`` trajectory definition (0.23-era full deviance;
modern sklearn records half of it).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from machine_learning_replications_tpu.config import GBDTConfig
from machine_learning_replications_tpu.models.tree import TreeEnsembleParams
from machine_learning_replications_tpu.ops import binning, histogram


# Host single-stump engine: quantile candidates come from a systematic
# subsample above this many rows (quantiles stabilize long before 10^5;
# see _fit_stump_host's docstring for the parity argument).
_STUMP_CANDIDATE_SAMPLE = 131_072

# 'hist'-mode fits at or above this row count quantize on device
# (``binning.bin_features_device``): host ``np.unique`` binning costs more
# than the whole boosted fit there. Below it (every parity-test regime) the
# host build keeps sklearn's unique-value midpoints exactly.
DEVICE_BINNING_MIN_ROWS = 100_000


def default_bins(X, cfg: GBDTConfig) -> binning.BinnedFeatures:
    """Binning policy for a fit that wasn't handed bins explicitly."""
    if cfg.splitter == "hist" and X.shape[0] >= DEVICE_BINNING_MIN_ROWS:
        return binning.bin_features_device(X, cfg.n_bins)
    return binning.bin_features(np.asarray(X), bin_budget(cfg))


def uses_fused_hist1(cfg: GBDTConfig, n_rows: int) -> bool:
    """``fit``'s fused-path gate — config/shape only, labels play no part
    (the r5 unsorted formulation histograms ``g = y − p`` directly, so
    soft labels ride the fused path too). Shared with
    ``bench._utilization`` so the reported stage model can never drift
    from the path the fit actually takes."""
    return (
        cfg.splitter == "hist"
        and cfg.max_depth == 1
        and n_rows >= DEVICE_BINNING_MIN_ROWS
    )


def fit(
    X: np.ndarray,
    y: np.ndarray,
    cfg: GBDTConfig = GBDTConfig(),
    bins: binning.BinnedFeatures | None = None,
    max_layout_bytes: int | None = None,
) -> tuple[TreeEnsembleParams, dict[str, Any]]:
    """Fit the boosted ensemble; returns (params, aux) with the deviance path.

    ``max_layout_bytes`` overrides the depth-1 sorted-layout memory guard
    (``_guard_stump_layout``) for hosts with headroom beyond the default
    4 GiB budget.

    Contract note (ADVICE r3): on the fused hist/depth-1 path (>=
    ``DEVICE_BINNING_MIN_ROWS`` rows; labels may be soft)
    ``aux['train_deviance']``
    is a DEVICE array — fetching [n_estimators] floats costs a full host
    round trip (~70 ms tunneled), which would be pure overhead inside the
    timed fit. Every other path returns host ``np.ndarray``. Callers that
    serialize aux (JSON etc.) should ``np.asarray`` it first.
    """
    resolve_backend(cfg)  # validate eagerly, even on paths that ignore it
    if bins is None:
        if (
            cfg.n_estimators == 1
            and uses_fused_hist1(cfg, X.shape[0])
            and isinstance(X, np.ndarray)
            and isinstance(y, np.ndarray)
        ):
            # y must be host-resident too: a device y would be pulled
            # through the tunnel by the np.asarray below, contradicting
            # the 'device-resident inputs skip this' rationale (ADVICE r5).
            # One-shot single-stump fits never earn their XLA compile: a
            # fresh process pays a ~20 s trace+compile for ~0.4 s of
            # device work (BENCH.md config-2 cold row, VERDICT r4 weak
            # #3). At stage 0 the raw score is the constant prior, so the
            # stump needs only a label histogram + counts per feature —
            # host numpy, threaded over columns, with the exact
            # device-binning candidate semantics. Device-resident inputs
            # skip this (pulling X back through a ~18 MB/s tunnel would
            # cost more than the compile).
            return _fit_stump_host(X, np.asarray(y), cfg)
        if uses_fused_hist1(cfg, X.shape[0]):
            # Fused regime: binning + all boosting stages in ONE jitted
            # program. The pieces are individually cheap at this scale but
            # each separate blocking dispatch pays a full host round trip
            # (~70 ms on the tunneled backend — measured r3); unfused,
            # dispatch overhead exceeded the actual device work
            # severalfold. aux carries the deviance as a device array for
            # the same reason (callers np.asarray it if they want it).
            # Soft (non-binary) labels take this path too since the r5
            # unsorted formulation: no label packing remains — each stage
            # histograms g = y − p directly (ADVICE r5 dropped the gate
            # and the status bit that used to route them off it).
            fused = _fit_hist1_fused(
                jnp.asarray(X), jnp.asarray(y),
                n_bins=cfg.n_bins,
                n_stages=cfg.n_estimators,
                learning_rate=cfg.learning_rate,
                min_samples_split=cfg.min_samples_split,
                min_samples_leaf=cfg.min_samples_leaf,
                backend=resolve_backend(cfg),
            )
            feature, threshold, value, is_split, deviance, f0, status = fused
            # One sync for the whole fit: a traced program cannot raise,
            # so the binning core's NaN flag rides along as an output.
            if int(status):
                raise ValueError("input contains NaN; impute before binning")
            params = forest_to_params(
                feature, threshold, value, is_split,
                init_raw=f0, learning_rate=cfg.learning_rate, max_depth=1,
            )
            return params, {"train_deviance": deviance}
        bins = default_bins(X, cfg)
    if cfg.max_depth == 1:
        # Gather/scatter-free fast path: replicated sorted layout
        # (ops.histogram.StumpData) — every stage is dense [F, n] math.
        # Built on device: the host build's argsort + layout loop was the
        # dominant cost of the whole fit at bench scale (same result —
        # stable argsort matches numpy's).
        _guard_stump_layout(
            bins, int(bins.binned.shape[0]), budget=max_layout_bytes
        )
        sd = histogram.build_stump_data_device(bins, y)
        feature, threshold, value, is_split, deviance = _fit_stumps(
            sd,
            n_stages=cfg.n_estimators,
            learning_rate=cfg.learning_rate,
            min_samples_split=cfg.min_samples_split,
            min_samples_leaf=cfg.min_samples_leaf,
        )
    else:
        feature, threshold, value, is_split, deviance = _fit_binned(
            jnp.asarray(bins.binned),
            jnp.asarray(bins.thresholds),
            jnp.asarray(y),
            n_stages=cfg.n_estimators,
            depth=cfg.max_depth,
            max_bins=bins.max_bins,
            learning_rate=cfg.learning_rate,
            min_samples_split=cfg.min_samples_split,
            min_samples_leaf=cfg.min_samples_leaf,
            backend=resolve_backend(cfg),
            feature_bins=binning.feature_bin_counts(bins),
        )
    params = forest_to_params(
        feature, threshold, value, is_split,
        init_raw=_prior_log_odds(y), learning_rate=cfg.learning_rate,
        max_depth=cfg.max_depth,
    )
    return params, {"train_deviance": np.asarray(deviance)}


def bin_budget(cfg: GBDTConfig) -> int | None:
    """Bin cap implied by ``cfg.splitter``: 'exact' enumerates every
    unique-value midpoint (sklearn ``BestSplitter`` parity, None = no cap);
    'hist' quantizes to ``cfg.n_bins`` quantile bins (the scalable path).

    Exact enumeration is only unbounded on the depth-1 fast path, whose
    per-stage cost is independent of the candidate count. The level-wise
    histogram path (depth ≥ 2) allocates O(2^depth · F · bins) per stage, so
    it stays quantile-capped even under 'exact' — identical anyway whenever
    feature cardinality ≤ ``n_bins``, which covers the reference cohort.
    """
    if cfg.splitter == "exact":
        return None if cfg.max_depth == 1 else cfg.n_bins
    if cfg.splitter == "hist":
        return cfg.n_bins
    raise ValueError(
        f"unknown splitter {cfg.splitter!r}; expected 'exact' or 'hist'"
    )


def resolve_backend(cfg: GBDTConfig) -> str:
    """'auto' → the one-hot MXU matmul contraction on TPU (composes with
    vmap and exploits per-feature bin widths — measured fastest on-chip),
    XLA segment_sum elsewhere (compiled scatter-adds win on CPU). 'pallas'
    selects the VMEM-accumulating kernel explicitly."""
    if cfg.histogram_backend == "auto":
        return "matmul" if jax.default_backend() == "tpu" else "xla"
    if cfg.histogram_backend in ("pallas", "xla", "matmul"):
        return cfg.histogram_backend
    raise ValueError(
        f"unknown histogram_backend {cfg.histogram_backend!r}; "
        "expected 'auto', 'matmul', 'pallas' or 'xla'"
    )


def resolve_backend_vmap_safe(cfg: GBDTConfig) -> str:
    """``resolve_backend`` for paths that run under ``vmap`` (fold
    fan-outs): honors an explicit 'xla'/'matmul' choice, remapping only
    'pallas' — which has no batching rule — to the platform's 'auto' pick."""
    b = resolve_backend(cfg)
    if b != "pallas":
        return b
    return "matmul" if jax.default_backend() == "tpu" else "xla"


def fit_resumable(
    X: np.ndarray,
    y: np.ndarray,
    cfg: GBDTConfig = GBDTConfig(),
    *,
    checkpoint_dir: str,
    checkpoint_every: int = 10,
    bins: binning.BinnedFeatures | None = None,
    _interrupt_after_chunks: int | None = None,
) -> tuple[TreeEnsembleParams, dict[str, Any]]:
    """``fit`` with Orbax checkpoint-and-restart every ``checkpoint_every``
    boosting stages (SURVEY.md §5 "Failure detection" — the reference has no
    recovery story at all; its scripts crash and restart from zero).

    The checkpoint unit is the boosting carry (raw scores + forest tensors
    + stage index). On entry, the newest step in ``checkpoint_dir`` is
    restored and training continues from there; the chunk runner takes
    dynamic stage bounds, so every chunk reuses one compiled program.
    Deterministic stages ⇒ a resumed fit is bit-identical to an unbroken one.

    ``_interrupt_after_chunks`` is a test hook: raise ``SimulatedInterrupt``
    after that many chunks to emulate preemption.
    """
    from machine_learning_replications_tpu.persist import orbax_io

    if bins is None:
        bins = binning.bin_features(np.asarray(X), bin_budget(cfg))
    n_stages = cfg.n_estimators

    if cfg.max_depth == 1:
        sd = histogram.build_stump_data_device(bins, y)
        carry = _stump_init(sd, n_stages)

        def run(carry, s, e):
            return _run_stumps(
                sd, carry, s, e,
                learning_rate=cfg.learning_rate,
                min_samples_split=cfg.min_samples_split,
                min_samples_leaf=cfg.min_samples_leaf,
            )
    else:
        binned = jnp.asarray(bins.binned)
        thresholds = jnp.asarray(bins.thresholds)
        yj = jnp.asarray(y)
        carry = _binned_init(thresholds, yj, n_stages, cfg.max_depth)

        def run(carry, s, e):
            return _run_binned(
                binned, thresholds, yj, carry, s, e,
                depth=cfg.max_depth, max_bins=bins.max_bins,
                learning_rate=cfg.learning_rate,
                min_samples_split=cfg.min_samples_split,
                min_samples_leaf=cfg.min_samples_leaf,
                backend=resolve_backend(cfg),
                feature_bins=binning.feature_bin_counts(bins),
            )

    with orbax_io.boosting_manager(checkpoint_dir) as mgr:
        start = orbax_io.latest_step(mgr) or 0
        if start:
            carry = orbax_io.restore_step(mgr, start, carry)
        chunks_done = 0
        for s in range(start, n_stages, checkpoint_every):
            e = min(s + checkpoint_every, n_stages)
            carry = jax.block_until_ready(run(carry, s, e))
            orbax_io.save_step(mgr, e, carry)
            chunks_done += 1
            if (
                _interrupt_after_chunks is not None
                and chunks_done >= _interrupt_after_chunks
                and e < n_stages
            ):
                mgr.wait_until_finished()
                raise orbax_io.SimulatedInterrupt(f"after stage {e}")
        mgr.wait_until_finished()

    _, feats, thrs, vals, splits, devs = carry
    params = forest_to_params(
        feats, thrs, vals, splits,
        init_raw=_prior_log_odds(y), learning_rate=cfg.learning_rate,
        max_depth=cfg.max_depth,
    )
    return params, {"train_deviance": np.asarray(devs)}


def _prior_log_odds(
    y, sample_weight=None
) -> "np.ndarray | jax.Array":
    """F₀ = log-odds of the (weighted) class prior — the single source of
    the boosting init score. The sharded trainers' in-loop f0 must agree
    with this (their psum'd weighted means compute the same quantity);
    keeping one copy here is what keeps them in lockstep. Host inputs
    return a numpy scalar; device-resident inputs return a device scalar
    (no synchronous pull through the host link mid-fit)."""
    if isinstance(y, jax.Array) or isinstance(sample_weight, jax.Array):
        # device-resident labels: reduce on device and RETURN a device
        # scalar — a float() here would be a synchronous round-trip through
        # the (possibly slow) host link in the middle of an otherwise
        # fully-async fit
        yj = jnp.asarray(y)
        if sample_weight is None:
            p1 = jnp.mean(yj)
        else:
            wj = jnp.asarray(sample_weight)
            p1 = jnp.sum(wj * yj) / jnp.sum(wj)
        return jnp.log(p1 / (1.0 - p1))
    if sample_weight is None:
        p1 = float(np.mean(y))
    else:
        w = np.asarray(sample_weight, np.float64)
        p1 = float((w * np.asarray(y, np.float64)).sum() / w.sum())
    return np.asarray(np.log(p1 / (1.0 - p1)))


def forest_to_params(
    feature: jnp.ndarray,    # [M, NN] int32
    threshold: jnp.ndarray,  # [M, NN]
    value: jnp.ndarray,      # [M, NN]
    is_split: jnp.ndarray,   # [M, NN] bool
    init_raw: np.ndarray,
    learning_rate: float,
    max_depth: int,
) -> TreeEnsembleParams:
    """Heap-layout forest tensors → the inference pytree (self-loop leaves)."""
    M, NN = feature.shape
    idx = jnp.arange(NN, dtype=jnp.int32)[None, :]
    left = jnp.where(is_split, 2 * idx + 1, idx).astype(jnp.int32)
    right = jnp.where(is_split, 2 * idx + 2, idx).astype(jnp.int32)
    return TreeEnsembleParams(
        feature=feature,
        threshold=threshold,
        left=left,
        right=right,
        value=value,
        init_raw=jnp.asarray(init_raw),
        learning_rate=jnp.asarray(learning_rate),
        max_depth=max_depth,
    )


def _fit_stumps(
    sd: histogram.StumpData,
    *,
    n_stages: int,
    learning_rate: float,
    min_samples_split: int,
    min_samples_leaf: int,
):
    """Depth-1 boosting over the full stage range (single XLA program)."""
    carry = _run_stumps(
        sd,
        _stump_init(sd, n_stages),
        0,
        n_stages,
        learning_rate=learning_rate,
        min_samples_split=min_samples_split,
        min_samples_leaf=min_samples_leaf,
    )
    return carry[1:]


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_bins", "n_stages", "learning_rate",
        "min_samples_split", "min_samples_leaf", "backend",
    ),
)
def _fit_hist1_fused(
    Xj: jnp.ndarray,
    yj: jnp.ndarray,
    *,
    n_bins: int,
    n_stages: int,
    learning_rate: float,
    min_samples_split: int,
    min_samples_leaf: int,
    backend: str = "xla",
):
    """Quantile binning → all boosting stages, fused into a single XLA
    program (one dispatch, one device sync for the whole fit).

    UNSORTED histogram formulation (r5): the sorted replicated layout that
    the unfused path uses (``StumpData``, F copies of every row vector)
    spent ~70% of each on-chip stage on pad/reshape/copy data formatting
    feeding the blocked boundary sums, and its ``[F, F, n]`` bin tensor
    dominated HBM residency (trace analysis, docs/SCALING.md "Roofline").
    Here the stage state is a single ``[n]`` score vector and each stage's
    split statistics come from ``histogram.stump_histograms`` over the
    loop-invariant ``[n, F]`` u8 bin matrix (MXU one-hot contraction /
    Pallas VMEM kernel on TPU), with boundary sums as tiny ``[F, B]``
    cumsums. Same math as the sorted path up to f32 summation regrouping —
    pinned forest-identical on the contract cohort by
    ``tests/test_gbdt_train.py::test_fused_hist1_matches_unfused``.

    NaN handling: a traced program cannot raise, so the binning core's
    ``nan_flag`` rides along as an output and ``fit`` checks it once at the
    end (by then the answer is already computed — the check costs nothing
    extra on top of the sync the caller needs anyway).
    """
    binned, mids, nan_flag = binning.device_binning_core(Xj, n_bins)
    if n_bins <= 256:
        # the only O(n·F) array each stage reads — keep it one byte wide
        binned = binned.astype(jnp.uint8)
    thresholds = mids.T                                      # [F, B-1]
    dtype = thresholds.dtype
    n, F = Xj.shape
    # Static left-of-boundary counts (one pass, loop-invariant): same
    # compare+sum as the sorted layout's left_count — bin B-1 exceeds every
    # boundary, so chunk padding is reduction-neutral by construction.
    boundaries = jnp.arange(n_bins - 1, dtype=jnp.int32)
    mapped, _ = binning.chunked_row_reduce(
        binned.astype(jnp.int32),
        lambda bc: jnp.sum(
            bc[:, None, :] <= boundaries[None, :, None],
            axis=0, dtype=jnp.int32,
        ),
        pad_value=n_bins - 1,
    )
    left_count = jnp.sum(mapped, axis=0).T                   # [F, B-1]

    ys = yj.astype(dtype)
    f0 = _prior_log_odds(ys)  # the one copy of the init-score formula
    CL = left_count.astype(dtype)[None]                      # [1, F, B-1]
    CT = jnp.asarray([n], dtype)

    carry = (
        jnp.full((n,), f0, dtype),
        jnp.zeros((n_stages, 3), jnp.int32),
        jnp.full((n_stages, 3), jnp.inf, dtype),
        jnp.zeros((n_stages, 3), dtype),
        jnp.zeros((n_stages, 3), bool),
        jnp.zeros(n_stages, dtype),
    )

    def stage(t, carry):
        raw, feats, thrs, vals, splits, devs = carry         # raw: [n]
        p = jax.scipy.special.expit(raw)
        g = ys - p
        h = p * (1.0 - p)
        hist = histogram.stump_histograms(
            binned, g, h, n_bins, backend=backend
        )                                                    # [2, F, B]
        GL = jnp.cumsum(hist[0], axis=1)[:, :-1][None]       # [1, F, B-1]
        HL = jnp.cumsum(hist[1], axis=1)[:, :-1]             # [F, B-1]
        GT = jnp.sum(g)
        HT = jnp.sum(h)
        sp = histogram.select_splits(
            GL, CL, GT[None], CT, jnp.sum(g * g)[None], thresholds,
            min_samples_split, min_samples_leaf,
        )
        do = sp.do_split[0]
        fstar, bstar = sp.feature[0], sp.boundary[0]
        num_l = GL[0, fstar, bstar]
        den_l = HL[fstar, bstar]
        num_r, den_r = GT - num_l, HT - den_l

        newton = histogram.newton_leaf_value
        v_root = newton(GT, HT)  # unsplit stage: single-leaf Newton value
        v_l, v_r = newton(num_l, den_l), newton(num_r, den_r)

        split_bins = jax.lax.dynamic_index_in_dim(
            binned, fstar, axis=1, keepdims=False
        )                                                    # [n]
        go_left = split_bins <= bstar.astype(split_bins.dtype)
        contrib = jnp.where(do, jnp.where(go_left, v_l, v_r), v_root)
        raw = raw + learning_rate * contrib
        dev = -2.0 * jnp.mean(ys * raw - jnp.logaddexp(0.0, raw))

        feat_t = jnp.where(do, fstar, 0) * jnp.array([1, 0, 0], jnp.int32)
        thr_t = jnp.stack([jnp.where(do, sp.threshold[0], jnp.inf),
                           jnp.array(jnp.inf, dtype), jnp.array(jnp.inf, dtype)])
        val_t = jnp.stack([jnp.where(do, 0.0, v_root),
                           jnp.where(do, v_l, 0.0), jnp.where(do, v_r, 0.0)])
        split_t = jnp.stack([do, jnp.array(False), jnp.array(False)])
        return (
            raw,
            feats.at[t].set(feat_t),
            thrs.at[t].set(thr_t.astype(dtype)),
            vals.at[t].set(val_t.astype(dtype)),
            splits.at[t].set(split_t),
            devs.at[t].set(dev),
        )

    carry = jax.lax.fori_loop(0, n_stages, stage, carry)
    _, feature, threshold, value, is_split, deviance = carry
    # One scalar status (each bool() fetch is a full host round trip on a
    # tunneled backend): nonzero = NaN input. The non-binary-label bit is
    # gone — the unsorted formulation histograms g = y − p directly, so
    # soft labels are first-class here (ADVICE r5).
    status = nan_flag.astype(jnp.int32)
    return feature, threshold, value, is_split, deviance, f0, status


def _fit_stump_host(
    X: np.ndarray, y: np.ndarray, cfg: GBDTConfig
) -> tuple[TreeEnsembleParams, dict[str, Any]]:
    """Single-stump fit entirely in host numpy, threaded over columns.

    The one-shot regime (``n_estimators=1`` at device-binning scale,
    BASELINE config 2) cannot amortize an XLA trace+compile — ~20 s of
    compile for ~0.4 s of device work made ``vs_baseline_cold`` 0.05
    (BENCH.md r4). At stage 0 the raw score is the constant prior
    ``f0``, so ``p = expit(f0) = mean(y)`` exactly, the hessian
    ``p(1-p)`` is one scalar, and the whole split search reduces to a
    per-feature label histogram + count histogram — no gradient vectors,
    no device, no compile. Candidate semantics follow
    ``binning.device_binning_core`` (empirical-quantile candidates, same
    midpoint rounding guard, bins = ``#{mids < v}``) with two honest
    deviations, both standard hist-GBDT practice and inside the ±0.005
    AUC parity budget: above ``_STUMP_CANDIDATE_SAMPLE`` rows the
    quantile candidates come from a systematic row subsample (LightGBM-
    style — quantiles of 128k rows track quantiles of millions; only the
    continuous columns' thresholds can shift, by less than a bin width),
    and duplicate midpoints are deduped (a binary column keeps 1
    candidate instead of 255 identical ones — identical partition,
    ~8× less searchsorted work on the reference's mostly-binary
    cohort). Selection/leaf/deviance use the same friedman proxy, Newton
    guard, and binomial deviance formulas, accumulated in f64 — at least
    as accurate as the device f32 sums. Columns fan out over host
    threads (numpy releases the GIL in partition/searchsorted/bincount).
    """
    import os
    from concurrent.futures import ThreadPoolExecutor

    n, F = X.shape
    B = cfg.n_bins
    if np.isnan(X).any():
        raise ValueError("input contains NaN; impute before binning")
    fdt = np.float64 if jax.config.jax_enable_x64 else np.float32
    y64 = np.asarray(y, np.float64)
    p1 = float(y64.mean())
    f0 = float(np.log(p1 / (1.0 - p1)))
    h_const = p1 * (1.0 - p1)
    binary_y = bool(histogram.is_binary_labels(np.asarray(y)))
    y_bool = np.asarray(y) > 0.5 if binary_y else None
    # round-based: keeps the sample NEAR the documented 128k target
    # (floor division left 131k < n < 262k paying a full-cohort partition;
    # ceil division would halve the sample just past the threshold)
    step = max(1, round(n / _STUMP_CANDIDATE_SAMPLE))

    def col_stats(f):
        col = X[:, f]
        src = col[::step] if step > 1 else col
        m = src.shape[0]
        q_idx = np.round(np.linspace(0.0, 1.0, B) * (m - 1)).astype(np.int64)
        cs = np.partition(src, q_idx)      # kth-element == full-sort[q_idx]
        u = cs[q_idx]
        mids = ((u[:-1] + u[1:]) / 2.0).astype(col.dtype)
        # sklearn BestSplitter guard, as in device_binning_core: a midpoint
        # that rounds up to the upper value would mis-route it
        mids = np.where(mids == u[1:], u[:-1], mids)
        mids = np.unique(mids)             # dedupe: same partition, less work
        b = np.searchsorted(mids, col, side="left")    # == #{mids < v}
        cnt = np.bincount(b, minlength=B).astype(np.float64)
        if binary_y:
            sy = np.bincount(b[y_bool], minlength=B).astype(np.float64)
        else:
            sy = np.bincount(b, weights=y64, minlength=B)
        thr = np.full(B - 1, np.inf)
        thr[: mids.shape[0]] = mids.astype(np.float64)
        return thr, cnt, sy

    workers = max(1, min(F, os.cpu_count() or 1))
    with ThreadPoolExecutor(workers) as ex:
        per_col = list(ex.map(col_stats, range(F)))
    thresholds = np.stack([r[0] for r in per_col])         # [F, B-1]
    CNT = np.stack([r[1] for r in per_col])                # [F, B]
    SY = np.stack([r[2] for r in per_col])                 # [F, B]

    # select_splits' math, f64 host edition (K=1)
    hist_g = SY - p1 * CNT
    GL = np.cumsum(hist_g, axis=1)[:, :-1]                 # [F, B-1]
    CL = np.cumsum(CNT, axis=1)[:, :-1]
    SYL = np.cumsum(SY, axis=1)[:, :-1]
    GT = float(hist_g[0].sum())
    HT = n * h_const
    CR = n - CL
    GR = GT - GL
    valid = (
        (CL >= cfg.min_samples_leaf)
        & (CR >= cfg.min_samples_leaf)
        & np.isfinite(thresholds)
    )
    diff = GL / np.maximum(CL, 1) - GR / np.maximum(CR, 1)
    proxy = np.where(valid, diff * diff * CL * CR, -np.inf)
    best = int(np.argmax(proxy))                           # flat (f, b) order
    Bm1 = B - 1
    fstar, bstar = best // Bm1, best % Bm1
    best_gain = proxy[fstar, bstar]

    sum_g2 = float(np.dot(y64 - p1, y64 - p1))
    impurity = max(sum_g2 / max(n, 1) - (GT / max(n, 1)) ** 2, 0.0)
    do = bool(
        (n >= cfg.min_samples_split)
        and (impurity > histogram.IMPURITY_EPS)
        and np.isfinite(best_gain)
    )

    def newton(num, den):
        return 0.0 if abs(den) < histogram.NEWTON_DEN_GUARD else num / den

    num_l, den_l = GL[fstar, bstar], h_const * CL[fstar, bstar]
    v_root = newton(GT, HT)
    v_l = newton(num_l, den_l)
    v_r = newton(GT - num_l, HT - den_l)

    # binomial deviance of the updated scores — raw takes only two values
    # (or one, unsplit), so the mean reduces to histogram aggregates
    lr = cfg.learning_rate
    if do:
        n_l, sum_y_l = CL[fstar, bstar], SYL[fstar, bstar]
        raw_l, raw_r = f0 + lr * v_l, f0 + lr * v_r
        ll = (
            sum_y_l * raw_l + (y64.sum() - sum_y_l) * raw_r
            - n_l * np.logaddexp(0.0, raw_l)
            - (n - n_l) * np.logaddexp(0.0, raw_r)
        )
    else:
        raw0 = f0 + lr * v_root
        ll = y64.sum() * raw0 - n * np.logaddexp(0.0, raw0)
    dev = -2.0 * ll / n

    feature = np.array([[fstar if do else 0, 0, 0]], np.int32)
    thr_t = np.array(
        [[thresholds[fstar, bstar] if do else np.inf, np.inf, np.inf]], fdt
    )
    value = np.array(
        [[0.0, v_l, v_r] if do else [v_root, 0.0, 0.0]], fdt
    )
    is_split = np.array([[do, False, False]])
    params = forest_to_params(
        jnp.asarray(feature), jnp.asarray(thr_t), jnp.asarray(value),
        jnp.asarray(is_split),
        init_raw=np.asarray(f0, fdt), learning_rate=lr, max_depth=1,
    )
    return params, {"train_deviance": np.asarray([dev], fdt)}


def _stump_init(sd: histogram.StumpData, n_stages: int):
    """Boosting carry at stage 0: replicated raw scores + preallocated
    forest tensors. This carry is the unit of checkpoint/resume
    (``persist.orbax_io`` saves it every k stages)."""
    F, n = sd.y_sorted.shape
    dtype = sd.thresholds.dtype
    p1 = jnp.mean(sd.y_sorted[0].astype(dtype))
    f0 = jnp.log(p1 / (1.0 - p1))
    return (
        jnp.full((F, n), f0, dtype),
        jnp.zeros((n_stages, 3), jnp.int32),
        jnp.full((n_stages, 3), jnp.inf, dtype),
        jnp.zeros((n_stages, 3), dtype),
        jnp.zeros((n_stages, 3), bool),
        jnp.zeros(n_stages, dtype),
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "learning_rate", "min_samples_split", "min_samples_leaf"
    ),
)
def _run_stumps(
    sd: histogram.StumpData,
    carry,
    start,
    stop,
    *,
    learning_rate: float,
    min_samples_split: int,
    min_samples_leaf: int,
):
    """Run boosting stages ``[start, stop)`` on the replicated sorted layout:
    each stage is a handful of dense [F, n] passes — expit, boundary sums
    (blocked decomposition above 16k rows, inside the wrapper), static
    lookups, one compare — with no dynamic gather/scatter anywhere (TPU
    serializes those onto the scalar unit). ``start``/``stop`` are dynamic
    so checkpoint-resume chunks share one compilation.

    Deliberately FLAT loop carry: keeping the stage arrays block-resident
    (``[F, nb, blk]`` for the whole ``fori_loop``, per-stage pad+reshape
    hoisted out) was ablated on v5e in r3 and re-confirmed neutral on CPU
    in r4 — zero runtime gain (XLA fuses the relayout into the stage's
    elementwise chain) and an O(n) compile blowup when a large pad+reshape
    feeds a while loop (~60 s at 600k rows; docs/SCALING.md "Lowerings",
    memory note tpu-stump-loop-floor). Do not re-introduce it.
    """
    F, n = sd.y_sorted.shape
    dtype = sd.thresholds.dtype
    CL = sd.left_count.astype(dtype)[None]        # [1, F, B-1] — static counts
    CT = jnp.asarray([n], dtype)
    ys = sd.y_sorted.astype(dtype)                # [F, n]
    bx = sd.bins_x

    def stage(t, carry):
        raw, feats, thrs, vals, splits, devs = carry   # raw: [F, n] replicated
        p = jax.scipy.special.expit(raw)
        g = ys - p                                      # [F, n]
        h = p * (1.0 - p)
        GL = histogram.cumulative_boundary_sums(g, sd.left_count)[None]
        HL = histogram.cumulative_boundary_sums(h, sd.left_count)[None]
        GT = jnp.sum(g[0])
        HT = jnp.sum(h[0])
        sp = histogram.select_splits(
            GL, CL, GT[None], CT, jnp.sum(g[0] * g[0])[None], sd.thresholds,
            min_samples_split, min_samples_leaf,
        )
        do = sp.do_split[0]
        fstar, bstar = sp.feature[0], sp.boundary[0]
        num_l = GL[0, fstar, bstar]
        den_l = HL[0, fstar, bstar]
        num_r, den_r = GT - num_l, HT - den_l

        newton = histogram.newton_leaf_value
        v_root = newton(GT, HT)  # unsplit stage: single-leaf Newton value
        v_l, v_r = newton(num_l, den_l), newton(num_r, den_r)

        # bins of feature f* in every sort order: dense dynamic-slice + compare
        split_bins = jax.lax.dynamic_index_in_dim(
            bx, fstar, axis=0, keepdims=False
        )  # [F, …] — bin ids (uint8/16/32 per cardinality)
        go_left = split_bins <= bstar.astype(split_bins.dtype)
        contrib = jnp.where(do, jnp.where(go_left, v_l, v_r), v_root)
        raw = raw + learning_rate * contrib
        dev = -2.0 * jnp.mean(ys[0] * raw[0] - jnp.logaddexp(0.0, raw[0]))

        feat_t = jnp.where(do, fstar, 0) * jnp.array([1, 0, 0], jnp.int32)
        thr_t = jnp.stack([jnp.where(do, sp.threshold[0], jnp.inf),
                           jnp.array(jnp.inf, dtype), jnp.array(jnp.inf, dtype)])
        val_t = jnp.stack([jnp.where(do, 0.0, v_root),
                           jnp.where(do, v_l, 0.0), jnp.where(do, v_r, 0.0)])
        split_t = jnp.stack([do, jnp.array(False), jnp.array(False)])
        return (
            raw,
            feats.at[t].set(feat_t),
            thrs.at[t].set(thr_t.astype(dtype)),
            vals.at[t].set(val_t.astype(dtype)),
            splits.at[t].set(split_t),
            devs.at[t].set(dev),
        )

    return jax.lax.fori_loop(start, stop, stage, carry)


def fit_folds(
    X: np.ndarray,
    y: np.ndarray,
    train_masks: np.ndarray,  # [k, n] 1.0 = row in that fold's fit
    cfg: GBDTConfig = GBDTConfig(),
    bins: binning.BinnedFeatures | None = None,
) -> TreeEnsembleParams:
    """All k masked fold fits as ONE vmapped XLA program — the stacking CV's
    GBDT fan-out (SURVEY.md §3.2: sklearn refits the member per fold,
    sequentially). Returns batched params with a leading fold axis on the
    forest tensors and ``init_raw``.

    Fold masking rides the shared grower: excluded rows park at node −1 and
    carry zero gradient/hessian, so shapes are fold-independent. By default
    candidate thresholds come from the full matrix's bins (a superset of
    each fold's value midpoints — partitions searchable by sklearn per fold
    remain searchable here; only the real-valued threshold of a chosen
    split can differ inside a gap, metric-level parity per SURVEY.md §7);
    ``cfg.per_fold_binning=True`` instead re-derives candidates from each
    fold's own rows, removing the deviation described below entirely
    (verified fold-for-fold against standalone subset fits in
    ``tests/test_gbdt_train.py::test_per_fold_binning_matches_subset_fits``).

    This is a deliberate, bounded deviation from the reference protocol
    (ADVICE r2): deriving candidates from all rows lets a fold's held-out
    values position a threshold inside a gap — no label information leaks
    (thresholds depend on X only), but it is milder than sklearn's
    train-fold-only candidate derivation. Measured magnitude: the
    out-of-fold GBDT meta-feature differs from the per-fold-subset oracle
    by < 6e-3 max on the contractual 17-column cohort
    (``tests/test_pipeline.py::test_vmapped_meta_features_match_loop``),
    absorbed by the ±0.005 AUC parity budget with observed end-to-end
    deltas ~5e-4 (BENCH artifacts).
    """
    masks = jnp.asarray(np.asarray(train_masks))
    k = masks.shape[0]
    if bins is None and cfg.per_fold_binning:
        # Reference-exact CV protocol: each fold derives its candidate
        # thresholds from its OWN rows (sklearn re-bins per refit). Closes
        # the documented full-matrix-candidates deviation below at the cost
        # of a [k, n, F] binned tensor (ADVICE r2 item 3 / VERDICT r3
        # next-round item 8). Gated by config because the shared-bins path
        # is cheaper and its measured effect is inside the parity budget.
        binned_pf, thr_pf, feature_bins, max_bins = _per_fold_bins(
            X, train_masks, cfg
        )
        feature, threshold, value, is_split, f0 = _run_binned_folds(
            jnp.asarray(binned_pf),
            jnp.asarray(thr_pf),
            jnp.asarray(y),
            masks,
            n_stages=cfg.n_estimators,
            depth=cfg.max_depth,
            max_bins=max_bins,
            learning_rate=cfg.learning_rate,
            min_samples_split=cfg.min_samples_split,
            min_samples_leaf=cfg.min_samples_leaf,
            backend=resolve_backend_vmap_safe(cfg),
            feature_bins=feature_bins,
        )
        return _fold_params(feature, threshold, value, is_split, f0, cfg, k)
    if bins is None:
        bins = binning.bin_features(np.asarray(X), bin_budget_capped(cfg))
    feature, threshold, value, is_split, f0 = _run_binned_folds(
        jnp.asarray(bins.binned),
        jnp.asarray(bins.thresholds),
        jnp.asarray(y),
        masks,
        n_stages=cfg.n_estimators,
        depth=cfg.max_depth,
        max_bins=bins.max_bins,
        learning_rate=cfg.learning_rate,
        min_samples_split=cfg.min_samples_split,
        min_samples_leaf=cfg.min_samples_leaf,
        backend=resolve_backend_vmap_safe(cfg),
        feature_bins=binning.feature_bin_counts(bins),
    )
    return _fold_params(feature, threshold, value, is_split, f0, cfg, k)


def _fold_params(feature, threshold, value, is_split, f0, cfg, k):
    NN = feature.shape[2]
    idx = jnp.arange(NN, dtype=jnp.int32)[None, None, :]
    left = jnp.where(is_split, 2 * idx + 1, idx).astype(jnp.int32)
    right = jnp.where(is_split, 2 * idx + 2, idx).astype(jnp.int32)
    # Every array leaf carries the leading fold axis (learning_rate included)
    # so the result vmaps directly, e.g. ``jax.vmap(lambda p:
    # tree.predict_proba1(p, X))(params)``.
    return TreeEnsembleParams(
        feature=feature, threshold=threshold, left=left, right=right,
        value=value, init_raw=f0,
        learning_rate=jnp.full((k,), cfg.learning_rate, threshold.dtype),
        max_depth=cfg.max_depth,
    )


def _per_fold_bins(X, train_masks, cfg: GBDTConfig):
    """Host-side per-fold candidate derivation: bin each fold's OWN rows
    (``bin_features`` on the physical subset — byte-for-byte sklearn's
    per-refit enumeration in the exact regime), then re-bin ALL rows
    against each fold's thresholds so shapes stay fold-independent
    (excluded rows carry valid ids but zero gradient/hessian — parked).

    Returns ``(binned [k, n, F] int32, thresholds [k, F, Wmax] (+inf
    padded), feature_bins tuple (per-feature max over folds), max_bins)``.
    """
    X = np.asarray(X)
    budget = bin_budget_capped(cfg)
    per_fold = [
        binning.bin_features(X[np.asarray(wk) > 0], budget)
        for wk in np.asarray(train_masks)
    ]
    k, (n, F) = len(per_fold), X.shape
    W = max(bf.thresholds.shape[1] for bf in per_fold)
    thr = np.full((k, F, W), np.inf)
    binned = np.zeros((k, n, F), np.int32)
    for i, bf in enumerate(per_fold):
        thr[i, :, : bf.thresholds.shape[1]] = bf.thresholds
        binned[i] = binning.rebin_with_thresholds(X, bf.thresholds, bf.n_bins)
    feature_bins = tuple(
        int(max(int(bf.n_bins[f]) for bf in per_fold)) for f in range(F)
    )
    return binned, thr, feature_bins, W + 1


def bin_budget_capped(cfg: GBDTConfig) -> int:
    """``bin_budget`` but always bounded (the fold-vmapped path runs the
    level-wise grower, whose allocation scales with the bin count)."""
    b = bin_budget(cfg)
    return cfg.n_bins if b is None else b


# Rough per-fit budget for the depth-1 sorted layout's dominant
# allocations; the exact splitter's unbounded candidate set can push these
# to TBs on continuous columns at scale, and an explicit refusal with
# sizing advice beats an allocator OOM mid-fit. Overridable per fit via
# ``fit(..., max_layout_bytes=...)`` (mirrors the sharded trainer's guard).
_STUMP_LAYOUT_BYTES_BUDGET = 4 << 30


def _stump_layout_bytes(n: int, F: int, B: int) -> int:
    """Estimated dominant allocations of the depth-1 sorted layout at
    ``B`` split candidates: the ``[F, F, n]`` bins_x tensor plus (above
    the blocked-boundary threshold) the per-stage ``[F, B-1, block]``
    boundary-partial buffer."""
    itemsize = 1 if B <= 256 else 2 if B <= 65536 else 4
    est = F * F * n * itemsize
    if n >= histogram._BLOCKED_BOUNDARY_MIN_N:
        est += F * max(B - 1, 1) * histogram._BOUNDARY_BLOCK * 8
    return est


def scaled_member_cfg(
    cfg: GBDTConfig, n_rows: int, n_features: int
) -> GBDTConfig:
    """The pipeline's full-data GBDT member fit at scale: depth-1 exact
    enumeration's candidate set is the column's unique midpoints — a
    continuous column contributes ~n candidates, and the sorted layout
    plus the boundary machinery scale with the candidate count (a 2M-row
    cohort OOM'd a multi-TB intermediate this way, r5). The member
    switches to the quantile-binned 'hist' protocol — the same bounded,
    AUC-parity-budgeted deviation the CV fold fits already document via
    ``bin_budget_capped`` — when either gate trips: device-binning scale,
    or a worst-case (B ≈ n) layout estimate past the guard budget (the
    region below 100k rows where ``fit`` would otherwise refuse).
    Depth ≥ 2 configs pass through: their exact budget is already
    quantile-capped (``bin_budget``) and the layout guard never runs.

    ``n_features`` is required (ADVICE r5): the worst-case layout estimate
    scales with the column count, and a silent 17-column default would let
    a wider cohort under-estimate it and skip the hist switch."""
    import dataclasses

    if cfg.splitter != "exact" or cfg.max_depth != 1:
        return cfg
    if n_rows >= DEVICE_BINNING_MIN_ROWS or (
        _stump_layout_bytes(n_rows, n_features, n_rows)
        > _STUMP_LAYOUT_BYTES_BUDGET
    ):
        return dataclasses.replace(cfg, splitter="hist")
    return cfg


def _guard_stump_layout(
    bins: binning.BinnedFeatures, n: int, budget: int | None = None
) -> None:
    F = bins.binned.shape[1]
    B = int(bins.max_bins)
    est = _stump_layout_bytes(n, F, B)
    budget = _STUMP_LAYOUT_BYTES_BUDGET if budget is None else budget
    if est > budget:
        hint = (
            "the 'exact' splitter enumerates every unique midpoint, which "
            "is unbounded on continuous columns at this row count — use "
            "splitter='hist' (quantile candidates, AUC-parity-budgeted), "
            if B > 1024 else
            "the [F, F, n] sorted layout scales with feature count "
            "squared — "
        )
        raise RuntimeError(
            f"depth-1 sorted layout would need ~{est:,} bytes "
            f"(F={F}, n={n}, candidates={B}) > budget {budget:,}: {hint}"
            "raise max_layout_bytes, or fit fewer rows."
        )


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_stages", "depth", "max_bins", "learning_rate",
        "min_samples_split", "min_samples_leaf", "backend", "feature_bins",
    ),
)
def _run_binned_folds(
    binned, thresholds, y, train_masks, *,
    n_stages, depth, max_bins, learning_rate,
    min_samples_split, min_samples_leaf, backend, feature_bins=None,
):
    dtype = thresholds.dtype
    yf = y.astype(dtype)
    n = yf.shape[0]
    NN = 2 ** (depth + 1) - 1
    hist_fn = resolve_hist_fn(backend, feature_bins)

    def one_fold(w, binned_f, thresholds_f):
        w = w.astype(dtype)
        p1 = jnp.sum(yf * w) / jnp.sum(w)
        f0 = jnp.log(p1 / (1.0 - p1))
        grow_tree = make_tree_grower(
            binned_f, thresholds_f,
            depth=depth, max_bins=max_bins,
            min_samples_split=min_samples_split,
            min_samples_leaf=min_samples_leaf,
            hist_fn=hist_fn,
            node_init=jnp.where(w > 0, 0, -1).astype(jnp.int32),
        )

        def stage(t, carry):
            raw, feats, thrs, vals, splits = carry
            p = jax.scipy.special.expit(raw)
            g = (yf - p) * w
            h = p * (1.0 - p) * w
            feat_t, thr_t, val_t, split_t, node = grow_tree(g, h)
            raw = raw + learning_rate * val_t[jnp.maximum(node, 0)] * w
            return (
                raw,
                feats.at[t].set(feat_t),
                thrs.at[t].set(thr_t),
                vals.at[t].set(val_t),
                splits.at[t].set(split_t),
            )

        init = (
            jnp.full(n, f0, dtype),
            jnp.zeros((n_stages, NN), jnp.int32),
            jnp.full((n_stages, NN), jnp.inf, dtype),
            jnp.zeros((n_stages, NN), dtype),
            jnp.zeros((n_stages, NN), bool),
        )
        _, feats, thrs, vals, splits = jax.lax.fori_loop(0, n_stages, stage, init)
        return feats, thrs, vals, splits, f0

    if binned.ndim == 3:
        # Per-fold candidates: binned [k, n, F] / thresholds [k, F, B-1]
        # vmap alongside the masks (cfg.per_fold_binning).
        return jax.vmap(one_fold)(train_masks, binned, thresholds)
    return jax.vmap(lambda w: one_fold(w, binned, thresholds))(train_masks)


def _fit_binned(
    binned: jnp.ndarray,      # [n, F] int32
    thresholds: jnp.ndarray,  # [F, B-1]
    y: jnp.ndarray,           # [n] ∈ {0, 1}
    *,
    n_stages: int,
    depth: int,
    max_bins: int,
    learning_rate: float,
    min_samples_split: int,
    min_samples_leaf: int,
    backend: str = "xla",
    feature_bins: tuple[int, ...] | None = None,
):
    carry = _run_binned(
        binned, thresholds, y,
        _binned_init(thresholds, y, n_stages, depth),
        0, n_stages,
        depth=depth, max_bins=max_bins, learning_rate=learning_rate,
        min_samples_split=min_samples_split, min_samples_leaf=min_samples_leaf,
        backend=backend, feature_bins=feature_bins,
    )
    return carry[1:]


def _binned_init(thresholds: jnp.ndarray, y: jnp.ndarray, n_stages: int, depth: int):
    """Boosting carry at stage 0 for the general-depth path (the
    checkpoint/resume unit, as ``_stump_init`` is for depth 1)."""
    n = y.shape[0]
    NN = 2 ** (depth + 1) - 1
    dtype = thresholds.dtype
    p1 = jnp.mean(y.astype(dtype))
    f0 = jnp.log(p1 / (1.0 - p1))
    return (
        jnp.full(n, f0, dtype),
        jnp.zeros((n_stages, NN), jnp.int32),
        jnp.full((n_stages, NN), jnp.inf, dtype),
        jnp.zeros((n_stages, NN), dtype),
        jnp.zeros((n_stages, NN), bool),
        jnp.zeros(n_stages, dtype),
    )


def resolve_hist_fn(backend: str, feature_bins: tuple[int, ...] | None = None):
    """Histogram-statistics implementation for a resolved backend name.

    ``feature_bins`` (static per-feature bin counts) only affects the
    matmul backend, where it cuts the one-hot traffic to Σ_f B_f instead
    of F·max_bins — the dominant cost on mostly-binary cohorts."""
    if backend == "pallas":
        from machine_learning_replications_tpu.ops.pallas_histogram import (
            node_histograms_pallas,
        )

        return node_histograms_pallas
    if backend == "matmul":
        return functools.partial(
            histogram.node_histograms_matmul, feature_bins=feature_bins
        )
    return histogram.node_histograms


def make_tree_grower(
    binned: jnp.ndarray,      # [n_local, F] int32
    thresholds: jnp.ndarray,  # [F, B-1]
    *,
    depth: int,
    max_bins: int,
    min_samples_split: int,
    min_samples_leaf: int,
    hist_fn,
    node_init: jnp.ndarray | None = None,  # [n_local] int32, −1 ⇒ inactive row
    reduce_fn=lambda a: a,    # cross-shard reduction (lax.psum in shard_map)
):
    """Build the level-synchronous tree-growth step shared by the
    single-device trainer and the sharded trainer (``parallel.hist_trainer``)
    — one copy of the split bookkeeping, routing, and Newton-leaf math; the
    sharded caller differs only in ``reduce_fn`` (histogram/leaf partials
    psum'd over the data axis) and ``node_init`` (padding rows parked at −1).

    Returns ``grow_tree(g, h) -> (feat_t, thr_t, val_t, split_t, node)``.
    """
    n, F = binned.shape
    NN = 2 ** (depth + 1) - 1
    dtype = thresholds.dtype
    rows = jnp.arange(n)
    if node_init is None:
        node_init = jnp.zeros(n, jnp.int32)

    def grow_tree(g, h):
        node = node_init
        feat_t = jnp.zeros(NN, jnp.int32)
        thr_t = jnp.full(NN, jnp.inf, dtype)
        split_t = jnp.zeros(NN, bool)
        for level in range(depth):
            base = 2**level - 1
            K = 2**level
            node_local = jnp.where(node >= base, node - base, -1)
            hl = hist_fn(binned, node_local, g, h, K, max_bins)
            hists = histogram.NodeHistograms(*(reduce_fn(a) for a in hl))
            sp = histogram.best_splits(
                hists, thresholds, min_samples_split, min_samples_leaf
            )
            feat_t = jax.lax.dynamic_update_slice(
                feat_t, jnp.where(sp.do_split, sp.feature, 0), (base,)
            )
            thr_t = jax.lax.dynamic_update_slice(
                thr_t, jnp.where(sp.do_split, sp.threshold, jnp.inf).astype(dtype), (base,)
            )
            split_t = jax.lax.dynamic_update_slice(split_t, sp.do_split, (base,))
            # Route rows of split nodes to their children; others park.
            k = jnp.maximum(node_local, 0)
            splits_here = (node_local >= 0) & sp.do_split[k]
            go_left = binned[rows, sp.feature[k]] <= sp.boundary[k]
            child = jnp.where(go_left, 2 * node + 1, 2 * node + 2)
            node = jnp.where(splits_here, child, node)
        # Newton leaf values over final row positions (inactive rows → dump
        # segment NN, which is dropped)
        seg = jnp.where(node >= 0, node, NN)
        num = reduce_fn(jax.ops.segment_sum(g, seg, num_segments=NN + 1)[:NN])
        den = reduce_fn(jax.ops.segment_sum(h, seg, num_segments=NN + 1)[:NN])
        val_t = histogram.newton_leaf_value(num, den)
        return feat_t, thr_t, val_t, split_t, node

    return grow_tree


@functools.partial(
    jax.jit,
    static_argnames=(
        "depth", "max_bins", "learning_rate",
        "min_samples_split", "min_samples_leaf", "backend", "feature_bins",
    ),
)
def _run_binned(
    binned: jnp.ndarray,      # [n, F] int32
    thresholds: jnp.ndarray,  # [F, B-1]
    y: jnp.ndarray,           # [n] ∈ {0, 1}
    carry,
    start,
    stop,
    *,
    depth: int,
    max_bins: int,
    learning_rate: float,
    min_samples_split: int,
    min_samples_leaf: int,
    backend: str = "xla",
    feature_bins: tuple[int, ...] | None = None,
):
    dtype = thresholds.dtype
    yf = y.astype(dtype)
    grow_tree = make_tree_grower(
        binned, thresholds,
        depth=depth, max_bins=max_bins,
        min_samples_split=min_samples_split,
        min_samples_leaf=min_samples_leaf,
        hist_fn=resolve_hist_fn(backend, feature_bins),
    )

    def stage(t, carry):
        raw, feats, thrs, vals, splits, devs = carry
        p = jax.scipy.special.expit(raw)
        g = yf - p          # residual (negative gradient of deviance)
        h = p * (1.0 - p)   # Newton denominator terms
        feat_t, thr_t, val_t, split_t, node = grow_tree(g, h)
        raw = raw + learning_rate * val_t[node]
        dev = -2.0 * jnp.mean(yf * raw - jnp.logaddexp(0.0, raw))
        return (
            raw,
            feats.at[t].set(feat_t),
            thrs.at[t].set(thr_t),
            vals.at[t].set(val_t),
            splits.at[t].set(split_t),
            devs.at[t].set(dev),
        )

    return jax.lax.fori_loop(start, stop, stage, carry)
