"""Gradient-boosted trees — training (the north-star centerpiece).

Reference member: ``GradientBoostingClassifier(n_estimators=100, max_depth=1,
random_state=2020)`` (``train_ensemble_public.py:45``), solved by sklearn's
Cython tree builder. This is the TPU-native re-design (SURVEY.md §7.4):

  * features quantized once (``ops.binning``; exact-midpoint regime on the
    HF cohort ⇒ sklearn-identical thresholds);
  * each boosting stage builds its tree level-by-level with vectorized
    per-(node, feature, bin) histograms and friedman split selection
    (``ops.histogram``) — no data-dependent Python control flow;
  * the stage loop is a ``lax.fori_loop`` writing into preallocated
    ``[n_stages, n_nodes]`` forest tensors, so the whole fit is one XLA
    program (device round-trips stay out of the loop — SURVEY.md §7
    "latency-bound at 713 rows");
  * trees live in heap layout (root 0, children 2i+1/2i+2); non-split nodes
    self-loop, matching ``models.tree``'s fixed-depth descent.

Numerics match sklearn's binomial-deviance GBC: F₀ = prior log-odds,
residual r = y − σ(F), leaves re-valued by the Newton step Σr / Σp(1−p)
(guarded at |den| < 1e-150 like ``_update_terminal_region``), stage update
F += lr·leaf, and ``train_deviance[m] = −2·mean(y·F − log(1+eᶠ))`` — the
pickle's ``train_score_`` trajectory definition (0.23-era full deviance;
modern sklearn records half of it).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from machine_learning_replications_tpu.config import GBDTConfig
from machine_learning_replications_tpu.models.tree import TreeEnsembleParams
from machine_learning_replications_tpu.ops import binning, histogram

_NEWTON_DEN_GUARD = histogram.NEWTON_DEN_GUARD


def fit(
    X: np.ndarray,
    y: np.ndarray,
    cfg: GBDTConfig = GBDTConfig(),
    bins: binning.BinnedFeatures | None = None,
) -> tuple[TreeEnsembleParams, dict[str, Any]]:
    """Fit the boosted ensemble; returns (params, aux) with the deviance path."""
    if bins is None:
        bins = binning.bin_features(np.asarray(X), cfg.n_bins)
    if cfg.max_depth == 1:
        # Gather/scatter-free fast path: replicated sorted layout
        # (ops.histogram.StumpData) — every stage is dense [F, n] math.
        sd = histogram.build_stump_data(bins, y)
        feature, threshold, value, is_split, deviance = _fit_stumps(
            sd,
            n_stages=cfg.n_estimators,
            learning_rate=cfg.learning_rate,
            min_samples_split=cfg.min_samples_split,
            min_samples_leaf=cfg.min_samples_leaf,
        )
    else:
        feature, threshold, value, is_split, deviance = _fit_binned(
            jnp.asarray(bins.binned),
            jnp.asarray(bins.thresholds),
            jnp.asarray(y),
            n_stages=cfg.n_estimators,
            depth=cfg.max_depth,
            max_bins=bins.max_bins,
            learning_rate=cfg.learning_rate,
            min_samples_split=cfg.min_samples_split,
            min_samples_leaf=cfg.min_samples_leaf,
        )
    params = forest_to_params(
        feature, threshold, value, is_split,
        init_raw=_prior_log_odds(y), learning_rate=cfg.learning_rate,
        max_depth=cfg.max_depth,
    )
    return params, {"train_deviance": np.asarray(deviance)}


def _prior_log_odds(y: np.ndarray) -> np.ndarray:
    p1 = float(np.mean(y))
    return np.asarray(np.log(p1 / (1.0 - p1)))


def forest_to_params(
    feature: jnp.ndarray,    # [M, NN] int32
    threshold: jnp.ndarray,  # [M, NN]
    value: jnp.ndarray,      # [M, NN]
    is_split: jnp.ndarray,   # [M, NN] bool
    init_raw: np.ndarray,
    learning_rate: float,
    max_depth: int,
) -> TreeEnsembleParams:
    """Heap-layout forest tensors → the inference pytree (self-loop leaves)."""
    M, NN = feature.shape
    idx = jnp.arange(NN, dtype=jnp.int32)[None, :]
    left = jnp.where(is_split, 2 * idx + 1, idx).astype(jnp.int32)
    right = jnp.where(is_split, 2 * idx + 2, idx).astype(jnp.int32)
    return TreeEnsembleParams(
        feature=feature,
        threshold=threshold,
        left=left,
        right=right,
        value=value,
        init_raw=jnp.asarray(init_raw),
        learning_rate=jnp.asarray(learning_rate),
        max_depth=max_depth,
    )


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_stages", "learning_rate", "min_samples_split", "min_samples_leaf"
    ),
)
def _fit_stumps(
    sd: histogram.StumpData,
    *,
    n_stages: int,
    learning_rate: float,
    min_samples_split: int,
    min_samples_leaf: int,
):
    """Depth-1 boosting (the reference's exact config) on the replicated
    sorted layout: each stage is a handful of dense [F, n] passes — expit,
    cumsum, static boundary lookups, one compare — with no dynamic
    gather/scatter anywhere (TPU serializes those onto the scalar unit)."""
    F, n = sd.y_sorted.shape
    dtype = sd.thresholds.dtype
    ys = sd.y_sorted.astype(dtype)                # [F, n]
    p1 = jnp.mean(ys[0])
    f0 = jnp.log(p1 / (1.0 - p1))
    CL = sd.left_count.astype(dtype)[None]        # [1, F, B-1] — static counts
    CT = jnp.asarray([n], dtype)

    def stage(t, carry):
        raw, feats, thrs, vals, splits, devs = carry   # raw: [F, n] replicated
        p = jax.scipy.special.expit(raw)
        g = ys - p                                      # [F, n]
        h = p * (1.0 - p)
        GL = histogram.cumulative_boundary_sums(g, sd.left_count)[None]
        HL = histogram.cumulative_boundary_sums(h, sd.left_count)[None]
        GT = jnp.sum(g[0])
        HT = jnp.sum(h[0])
        sp = histogram.select_splits(
            GL, CL, GT[None], CT, jnp.sum(g[0] * g[0])[None], sd.thresholds,
            min_samples_split, min_samples_leaf,
        )
        do = sp.do_split[0]
        fstar, bstar = sp.feature[0], sp.boundary[0]
        num_l = GL[0, fstar, bstar]
        den_l = HL[0, fstar, bstar]
        num_r, den_r = GT - num_l, HT - den_l

        newton = histogram.newton_leaf_value
        v_root = newton(GT, HT)  # unsplit stage: single-leaf Newton value
        v_l, v_r = newton(num_l, den_l), newton(num_r, den_r)

        # bins of feature f* in every sort order: dense dynamic-slice + compare
        split_bins = jax.lax.dynamic_index_in_dim(
            sd.bins_x, fstar, axis=0, keepdims=False
        )  # [F, n] uint8
        go_left = split_bins <= bstar.astype(jnp.uint8)
        contrib = jnp.where(do, jnp.where(go_left, v_l, v_r), v_root)
        raw = raw + learning_rate * contrib
        dev = -2.0 * jnp.mean(ys[0] * raw[0] - jnp.logaddexp(0.0, raw[0]))

        feat_t = jnp.where(do, fstar, 0) * jnp.array([1, 0, 0], jnp.int32)
        thr_t = jnp.stack([jnp.where(do, sp.threshold[0], jnp.inf),
                           jnp.array(jnp.inf, dtype), jnp.array(jnp.inf, dtype)])
        val_t = jnp.stack([jnp.where(do, 0.0, v_root),
                           jnp.where(do, v_l, 0.0), jnp.where(do, v_r, 0.0)])
        split_t = jnp.stack([do, jnp.array(False), jnp.array(False)])
        return (
            raw,
            feats.at[t].set(feat_t),
            thrs.at[t].set(thr_t.astype(dtype)),
            vals.at[t].set(val_t.astype(dtype)),
            splits.at[t].set(split_t),
            devs.at[t].set(dev),
        )

    init = (
        jnp.full((F, n), f0, dtype),
        jnp.zeros((n_stages, 3), jnp.int32),
        jnp.full((n_stages, 3), jnp.inf, dtype),
        jnp.zeros((n_stages, 3), dtype),
        jnp.zeros((n_stages, 3), bool),
        jnp.zeros(n_stages, dtype),
    )
    _, feats, thrs, vals, splits, devs = jax.lax.fori_loop(0, n_stages, stage, init)
    return feats, thrs, vals, splits, devs


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_stages", "depth", "max_bins",
        "min_samples_split", "min_samples_leaf",
    ),
)
def _fit_binned(
    binned: jnp.ndarray,      # [n, F] int32
    thresholds: jnp.ndarray,  # [F, B-1]
    y: jnp.ndarray,           # [n] ∈ {0, 1}
    *,
    n_stages: int,
    depth: int,
    max_bins: int,
    learning_rate: float,
    min_samples_split: int,
    min_samples_leaf: int,
):
    n, F = binned.shape
    NN = 2 ** (depth + 1) - 1
    dtype = thresholds.dtype
    yf = y.astype(dtype)
    p1 = jnp.mean(yf)
    f0 = jnp.log(p1 / (1.0 - p1))
    rows = jnp.arange(n)

    def grow_tree(g, h):
        """One stage's tree: level-synchronous growth over static depth."""
        node = jnp.zeros(n, jnp.int32)
        feat_t = jnp.zeros(NN, jnp.int32)
        thr_t = jnp.full(NN, jnp.inf, dtype)
        split_t = jnp.zeros(NN, bool)
        for level in range(depth):
            base = 2**level - 1
            K = 2**level
            node_local = jnp.where(node >= base, node - base, -1)
            hists = histogram.node_histograms(binned, node_local, g, h, K, max_bins)
            sp = histogram.best_splits(
                hists, thresholds, min_samples_split, min_samples_leaf
            )
            feat_t = jax.lax.dynamic_update_slice(
                feat_t, jnp.where(sp.do_split, sp.feature, 0), (base,)
            )
            thr_t = jax.lax.dynamic_update_slice(
                thr_t, jnp.where(sp.do_split, sp.threshold, jnp.inf).astype(dtype), (base,)
            )
            split_t = jax.lax.dynamic_update_slice(split_t, sp.do_split, (base,))
            # Route rows of split nodes to their children; others park.
            k = jnp.maximum(node_local, 0)
            splits_here = (node_local >= 0) & sp.do_split[k]
            go_left = binned[rows, sp.feature[k]] <= sp.boundary[k]
            child = jnp.where(go_left, 2 * node + 1, 2 * node + 2)
            node = jnp.where(splits_here, child, node)
        # Newton leaf values over final row positions
        num = jax.ops.segment_sum(g, node, num_segments=NN)
        den = jax.ops.segment_sum(h, node, num_segments=NN)
        val_t = jnp.where(jnp.abs(den) < _NEWTON_DEN_GUARD, 0.0, num / jnp.maximum(den, _NEWTON_DEN_GUARD))
        return feat_t, thr_t, val_t, split_t, node

    def stage(t, carry):
        raw, feats, thrs, vals, splits, devs = carry
        p = jax.scipy.special.expit(raw)
        g = yf - p          # residual (negative gradient of deviance)
        h = p * (1.0 - p)   # Newton denominator terms
        feat_t, thr_t, val_t, split_t, node = grow_tree(g, h)
        raw = raw + learning_rate * val_t[node]
        dev = -2.0 * jnp.mean(yf * raw - jnp.logaddexp(0.0, raw))
        return (
            raw,
            feats.at[t].set(feat_t),
            thrs.at[t].set(thr_t),
            vals.at[t].set(val_t),
            splits.at[t].set(split_t),
            devs.at[t].set(dev),
        )

    init = (
        jnp.full(n, f0, dtype),
        jnp.zeros((n_stages, NN), jnp.int32),
        jnp.full((n_stages, NN), jnp.inf, dtype),
        jnp.zeros((n_stages, NN), dtype),
        jnp.zeros((n_stages, NN), bool),
        jnp.zeros(n_stages, dtype),
    )
    _, feats, thrs, vals, splits, devs = jax.lax.fori_loop(
        0, n_stages, stage, init
    )
    return feats, thrs, vals, splits, devs
