"""StandardScaler — z-scoring inside the SVC pipeline.

Reference: ``make_pipeline(StandardScaler(), SVC(...))`` at
``train_ensemble_public.py:44``; fitted stats live in the shipped pickle
(``mean_`` / ``scale_`` over 17 features, n_samples_seen_=713).
"""

from __future__ import annotations

import flax.struct
import jax.numpy as jnp


@flax.struct.dataclass
class ScalerParams:
    mean: jnp.ndarray   # [F]
    scale: jnp.ndarray  # [F] — stddev, with zero-variance columns forced to 1


def fit(X: jnp.ndarray, sample_weight: jnp.ndarray | None = None) -> ScalerParams:
    """Population (ddof=0) moments, matching sklearn's StandardScaler."""
    if sample_weight is None:
        mean = jnp.mean(X, axis=0)
        var = jnp.mean((X - mean) ** 2, axis=0)
    else:
        w = sample_weight / jnp.sum(sample_weight)
        mean = w @ X
        var = w @ (X - mean) ** 2
    # sklearn maps zero variance → scale 1 so constant columns pass through.
    scale = jnp.where(var > 0, jnp.sqrt(var), 1.0)
    return ScalerParams(mean=mean, scale=scale)


def transform(params: ScalerParams, X: jnp.ndarray) -> jnp.ndarray:
    return (X - params.mean) / params.scale
