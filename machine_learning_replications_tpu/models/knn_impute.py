"""1-nearest-neighbor imputation of missing clinical values.

Reference: ``KNNImputer(missing_values=nan, n_neighbors=1, copy=True)`` fit
on the development cohort and applied to both cohorts
(``train_ensemble_public.py:37-40``). sklearn semantics replicated:

  * distances are ``nan_euclidean`` — squared distance over mutually present
    coordinates, rescaled by F / n_present (``ops.linalg.masked_pairwise_sq_dists``,
    one masked-matmul triple on the MXU instead of sklearn's Cython loops);
  * a donor for feature f must have f present;
  * with no eligible donor (or all-NaN distance) the fit-column mean is used;
  * n_neighbors=1 ⇒ the value of the single nearest donor.

Functional API: ``fit`` captures the donor matrix; ``transform`` is pure and
jittable (static feature count drives an unrolled per-feature argmin).
"""

from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp

from machine_learning_replications_tpu.ops.linalg import masked_pairwise_sq_dists


@flax.struct.dataclass
class KNNImputerParams:
    donors: jnp.ndarray     # [n_fit, F] — the fit cohort, NaNs included
    col_means: jnp.ndarray  # [F] — nan-mean fallback per column


def fit(X_fit: jnp.ndarray) -> KNNImputerParams:
    X_fit = jnp.asarray(X_fit)
    return KNNImputerParams(
        donors=X_fit, col_means=jnp.nanmean(X_fit, axis=0)
    )


@jax.jit
def transform(params: KNNImputerParams, X: jnp.ndarray) -> jnp.ndarray:
    """Impute every NaN in ``X[nq, F]`` from the nearest eligible donor."""
    X = jnp.asarray(X)
    D = masked_pairwise_sq_dists(X, params.donors)      # [nq, n_fit]
    D = jnp.where(jnp.isnan(D), jnp.inf, D)
    donor_has = ~jnp.isnan(params.donors)                # [n_fit, F]
    out_cols = []
    for f in range(X.shape[1]):  # static F: one argmin pass per feature
        Df = jnp.where(donor_has[:, f][None, :], D, jnp.inf)
        idx = jnp.argmin(Df, axis=1)                     # [nq] nearest donor
        has_any = jnp.isfinite(jnp.min(Df, axis=1))
        donated = jnp.where(
            has_any, params.donors[idx, f], params.col_means[f]
        )
        col = X[:, f]
        out_cols.append(jnp.where(jnp.isnan(col), donated, col))
    return jnp.stack(out_cols, axis=1)


def fit_transform(X_fit: jnp.ndarray) -> tuple[KNNImputerParams, jnp.ndarray]:
    params = fit(X_fit)
    return params, transform(params, X_fit)
