"""1-nearest-neighbor imputation of missing clinical values.

Reference: ``KNNImputer(missing_values=nan, n_neighbors=1, copy=True)`` fit
on the development cohort and applied to both cohorts
(``train_ensemble_public.py:37-40``). sklearn semantics replicated:

  * distances are ``nan_euclidean`` — squared distance over mutually present
    coordinates, rescaled by F / n_present (``ops.linalg.masked_pairwise_sq_dists``,
    one masked-matmul triple on the MXU instead of sklearn's Cython loops);
  * a donor for feature f must have f present;
  * with no eligible donor (or all-NaN distance) the fit-column mean is used;
  * n_neighbors=1 ⇒ the value of the single nearest donor.

Functional API: ``fit`` captures the donor matrix; ``transform`` is pure and
jittable (static feature count drives an unrolled per-feature argmin).

Scaled regime (``ImputerConfig``): the distance matrix is
O(n_query · n_fit), so ``fit`` caps the donor cohort at ``max_donors`` rows
(deterministic uniform subsample) and ``transform`` processes queries in
``chunk_rows`` blocks — each block one compiled program, the tail block
zero-padded to the shared shape.
"""

from __future__ import annotations

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from machine_learning_replications_tpu.config import ImputerConfig
from machine_learning_replications_tpu.ops.linalg import masked_pairwise_sq_dists


@flax.struct.dataclass
class KNNImputerParams:
    donors: jnp.ndarray     # [n_fit, F] — the fit cohort, NaNs included
    col_means: jnp.ndarray  # [F] — nan-mean fallback per column


def fit(
    X_fit: jnp.ndarray,
    cfg: ImputerConfig = ImputerConfig(),
    seed: int = 2020,
    y: np.ndarray | None = None,
) -> KNNImputerParams:
    X_np = np.asarray(X_fit)
    if X_np.shape[0] > cfg.max_donors:
        if y is not None:
            # Label-stratified cap: keeps the donor pool's outcome mix equal
            # to the cohort's, so rare-class rows keep same-class donors at
            # the same rate as the full 1-NN reference semantics (ADVICE r2).
            from machine_learning_replications_tpu.utils.cv import (
                stratified_subsample_indices,
            )

            keep = stratified_subsample_indices(
                np.asarray(y), cfg.max_donors, seed=seed
            )
        else:
            keep = np.sort(
                np.random.default_rng(seed).choice(
                    X_np.shape[0], size=cfg.max_donors, replace=False
                )
            )
        donors = jnp.asarray(X_np[keep])
    else:
        donors = jnp.asarray(X_fit)
    # Fallback means come from the FULL fit cohort (cheap; one pass).
    return KNNImputerParams(
        donors=donors, col_means=jnp.asarray(np.nanmean(X_np, axis=0))
    )


@jax.jit
def _transform_block(params: KNNImputerParams, X: jnp.ndarray) -> jnp.ndarray:
    """Impute every NaN in ``X[nq, F]`` from the nearest eligible donor."""
    X = jnp.asarray(X)
    D = masked_pairwise_sq_dists(X, params.donors)      # [nq, n_fit]
    D = jnp.where(jnp.isnan(D), jnp.inf, D)
    donor_has = ~jnp.isnan(params.donors)                # [n_fit, F]
    out_cols = []
    for f in range(X.shape[1]):  # static F: one argmin pass per feature
        Df = jnp.where(donor_has[:, f][None, :], D, jnp.inf)
        idx = jnp.argmin(Df, axis=1)                     # [nq] nearest donor
        has_any = jnp.isfinite(jnp.min(Df, axis=1))
        donated = jnp.where(
            has_any, params.donors[idx, f], params.col_means[f]
        )
        col = X[:, f]
        out_cols.append(jnp.where(jnp.isnan(col), donated, col))
    return jnp.stack(out_cols, axis=1)


def transform(
    params: KNNImputerParams,
    X: jnp.ndarray,
    chunk_rows: int | None = None,
    mesh=None,
) -> jnp.ndarray:
    """``_transform_block`` over query chunks; single block when the query
    fits (``chunk_rows=None`` → ``ImputerConfig().chunk_rows``).

    With ``mesh``, query rows are sharded over the 'data' axis — the
    imputation of a row depends only on the (replicated) donor matrix, so
    the transform is embarrassingly row-parallel (VERDICT r2 item 5: at 10M
    rows this was the next single-device wall after the GBDT member).

    Complete rows (no NaN) are imputation fixed points, so only the
    incomplete rows travel through the O(rows × donors) distance machinery
    — at the cohort's ~3% row missingness that is ~30× less imputer work,
    with bit-identical output (sklearn's KNNImputer computes distances
    only for receivers too)."""
    chunk = ImputerConfig().chunk_rows if chunk_rows is None else chunk_rows
    X_np = np.asarray(X)
    incomplete = np.isnan(X_np).any(axis=1)
    n_inc = int(incomplete.sum())
    if n_inc == 0:
        return jnp.asarray(X_np)
    if n_inc < X_np.shape[0]:
        out = np.array(X_np, dtype=X_np.dtype)
        out[incomplete] = np.asarray(
            transform(params, X_np[incomplete], chunk_rows, mesh=mesh)
        )
        return jnp.asarray(out)
    if mesh is not None:
        from machine_learning_replications_tpu.parallel.rowwise import (
            apply_rows_sharded,
        )

        return apply_rows_sharded(
            mesh, _transform_block, params, X,
            chunk_rows=chunk, pad_value=np.nan,
        )
    n = int(X.shape[0])
    if n <= chunk:
        return _transform_block(params, X)
    blocks = []
    for s in range(0, n, chunk):
        block = X_np[s : s + chunk]
        real = block.shape[0]
        if real < chunk:  # pad the tail so every block shares one shape
            block = np.pad(
                block, ((0, chunk - real), (0, 0)), constant_values=np.nan
            )
        blocks.append(np.asarray(_transform_block(params, jnp.asarray(block)))[:real])
    return jnp.asarray(np.concatenate(blocks, axis=0))


def fit_transform(
    X_fit: jnp.ndarray,
    cfg: ImputerConfig = ImputerConfig(),
    seed: int = 2020,
    mesh=None,
    y: np.ndarray | None = None,
) -> tuple[KNNImputerParams, jnp.ndarray]:
    params = fit(X_fit, cfg, seed, y=y)
    return params, transform(params, X_fit, cfg.chunk_rows, mesh=mesh)
