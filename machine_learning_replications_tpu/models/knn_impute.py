"""1-nearest-neighbor imputation of missing clinical values.

Reference: ``KNNImputer(missing_values=nan, n_neighbors=1, copy=True)`` fit
on the development cohort and applied to both cohorts
(``train_ensemble_public.py:37-40``). sklearn semantics replicated:

  * distances are ``nan_euclidean`` — squared distance over mutually present
    coordinates, rescaled by F / n_present (``ops.linalg.masked_pairwise_sq_dists``,
    one masked-matmul triple on the MXU instead of sklearn's Cython loops);
  * a donor for feature f must have f present;
  * with no eligible donor (or all-NaN distance) the fit-column mean is used;
  * n_neighbors=1 ⇒ the value of the single nearest donor.

Functional API: ``fit`` captures the donor matrix; ``transform`` is pure and
jittable (the block program is specialised to the query's statically-known
NaN columns — see ``_block_fn``).

Scaled regime (``ImputerConfig``): the distance matrix is
O(n_query · n_fit), so ``fit`` caps the donor cohort at ``max_donors`` rows
(deterministic uniform subsample) and ``transform`` processes queries in
``chunk_rows`` blocks — each block one compiled program, the tail block
zero-padded to the shared shape.
"""

from __future__ import annotations

import functools

import flax.struct
import jax
import jax.numpy as jnp
import numpy as np

from machine_learning_replications_tpu.config import ImputerConfig
from machine_learning_replications_tpu.ops.linalg import masked_pairwise_sq_dists


@flax.struct.dataclass
class KNNImputerParams:
    donors: jnp.ndarray     # [n_fit, F] — the fit cohort, NaNs included
    col_means: jnp.ndarray  # [F] — nan-mean fallback per column


def fit(
    X_fit: jnp.ndarray,
    cfg: ImputerConfig = ImputerConfig(),
    seed: int = 2020,
    y: np.ndarray | None = None,
) -> KNNImputerParams:
    X_np = np.asarray(X_fit)
    if X_np.shape[0] > cfg.max_donors:
        if y is not None:
            # Label-stratified cap: keeps the donor pool's outcome mix equal
            # to the cohort's, so rare-class rows keep same-class donors at
            # the same rate as the full 1-NN reference semantics (ADVICE r2).
            from machine_learning_replications_tpu.utils.cv import (
                stratified_subsample_indices,
            )

            keep = stratified_subsample_indices(
                np.asarray(y), cfg.max_donors, seed=seed
            )
        else:
            keep = np.sort(
                np.random.default_rng(seed).choice(
                    X_np.shape[0], size=cfg.max_donors, replace=False
                )
            )
        donors = jnp.asarray(X_np[keep])
    else:
        donors = jnp.asarray(X_fit)
    # Fallback means come from the FULL fit cohort (cheap; one pass).
    return KNNImputerParams(
        donors=donors, col_means=jnp.asarray(np.nanmean(X_np, axis=0))
    )


#: Masked-donor-column count at or below which the block runs one masked
#: argmin pass per such column instead of the shared top-K scan. XLA:CPU's
#: ``top_k`` on a [8192, donors] block measured 233 ms — the single
#: hottest op of the bulk-scoring pipeline — while a masked argmin pass is
#: ~4 ms, so a handful of per-column passes beats one top-K by ~5×; the
#: top-K form keeps winning when most donor columns are incomplete (the
#: training-fit workload the r4 rework measured at 64 passes = 743 s).
_ARGMIN_MAX_MASKED_COLS = 16


@functools.lru_cache(maxsize=64)
def _block_fn(
    nan_cols: tuple, masked_donor_cols: tuple, dist_cols: tuple | None = None
):
    """Jitted imputation block specialised to the query's NaN columns.

    The generic form pays one ``[nq, n_fit]`` masked argmin per feature —
    64 full passes over the distance matrix, though typically only the few
    continuous columns ever hold NaN (Table S1 schema: binaries are fully
    observed; measured 743 s of a 50k-row CPU pipeline fit in the generic
    form). Two static specialisations, both semantics-preserving:

      * only ``nan_cols`` (features with ≥1 NaN in the query block) get a
        pass at all — every other column is copied through unchanged;
      * features whose DONOR column is complete share literally the same
        masked distances (``where(all-True, D, inf) == D``), so one shared
        argmin serves them all; only ``masked_donor_cols`` (donor column
        itself has NaN) need their own eligibility-masked pass.

    Two further static specialisations, both selection-preserving (the
    imputed value is a *copied donor value*, so identical selections mean
    bit-identical output):

      * ``dist_cols`` (set when every NaN column of the query is FULLY
        missing — the contract-row shape every serving and bulk-scoring
        batch has) restricts the distance computation to those columns:
        mutual presence can only live there, so the restricted masked
        distances are the full ones times the global constant
        ``F_sub / F`` — argmin/top-K order, ties, and finiteness are
        unchanged — and, the query side being fully observed there, they
        run through ``masked_pairwise_sq_dists_dense_query`` (one matmul
        + rank-1 corrections instead of the three-masked-matmul triple
        over all 64 columns: 197 → 12 ms per 2048-row block);
      * at most ``_ARGMIN_MAX_MASKED_COLS`` eligibility-masked donor
        columns → per-column masked argmin passes replace the shared
        top-K scan (same first-eligible-donor selection by construction;
        the top-K path exists because many-column patterns amortize one
        scan across all of them).

    Keyed lru_cache keeps the returned function's identity stable per
    specialisation so downstream jit caches (``apply_rows_sharded``) hit;
    bounded at 64 patterns — a long-lived server seeing varied query
    missingness patterns must not retain compiled executables without
    bound, and a re-trace on rare eviction is cheap (ADVICE r4).
    """
    use_argmin = len(masked_donor_cols) <= _ARGMIN_MAX_MASKED_COLS

    def f(params: KNNImputerParams, X: jnp.ndarray) -> jnp.ndarray:
        from machine_learning_replications_tpu.ops.linalg import (
            masked_pairwise_sq_dists_dense_query,
        )

        X = jnp.asarray(X)
        if dist_cols is None:
            D = masked_pairwise_sq_dists(X, params.donors)  # [nq, n_fit]
        else:
            # Restricted to the fully-present columns, the query side of
            # the masked-distance machinery collapses — the dense-query
            # kernel (one matmul + rank-1 corrections; all-NaN pad rows
            # propagate to NaN → inf below).
            cols = np.asarray(dist_cols)
            D = masked_pairwise_sq_dists_dense_query(
                X[:, cols], params.donors[:, cols]
            )
        D = jnp.where(jnp.isnan(D), jnp.inf, D)
        donor_has = ~jnp.isnan(params.donors)            # [n_fit, F]
        if use_argmin:
            # Small-pattern exact path: one global argmin (shared by every
            # donor-complete column) plus one masked argmin per
            # NaN-bearing donor column — the definitionally exact
            # semantics the top-K scan below reproduces.
            idx0 = jnp.argmin(D, axis=1)
            ok0 = jnp.isfinite(jnp.min(D, axis=1))
            out = X
            for fcol in nan_cols:
                if fcol in masked_donor_cols:
                    Df = jnp.where(
                        donor_has[:, fcol][None, :], D, jnp.inf
                    )
                    idx = jnp.argmin(Df, axis=1)
                    ok = jnp.isfinite(jnp.min(Df, axis=1))
                else:
                    idx, ok = idx0, ok0
                donated = jnp.where(
                    ok, params.donors[idx, fcol], params.col_means[fcol]
                )
                col = X[:, fcol]
                out = out.at[:, fcol].set(
                    jnp.where(jnp.isnan(col), donated, col)
                )
            return out
        nq, nd = D.shape
        K = min(8, nd)
        # ONE global top-K pass replaces a full [nq, nd] masked argmin per
        # feature. ``lax.top_k`` breaks ties in favor of lower indices, so
        # scanning its (distance, index)-lexicographic order for the first
        # eligible donor reproduces ``argmin`` over the masked distances
        # exactly — the per-feature exact pass survives only as a
        # ``lax.cond``-gated fallback, executed when some row has NO
        # eligible donor among the K (probability ~miss_rate^K per row
        # under MCAR; the cond branch keeps the program exact either way).
        neg_vals, topk_idx = jax.lax.top_k(-D, K)        # [nq, K] ascending D
        topk_finite = jnp.isfinite(neg_vals)
        # Rows with NO finite distance at all (e.g. the all-NaN pad rows the
        # chunked/sharded paths append) impute to col_means in both
        # branches, so they must not force the exact fallback.
        no_finite = ~topk_finite[:, 0]
        rows = jnp.arange(nq)
        out = X
        for fcol in nan_cols:
            if fcol in masked_donor_cols:
                elig = donor_has[topk_idx, fcol] & topk_finite   # [nq, K]
                any_elig = elig.any(axis=1)
                first = jnp.argmax(elig, axis=1)         # first True in order
                idx_fast = topk_idx[rows, first]

                def exact(_, fcol=fcol):
                    Df = jnp.where(donor_has[:, fcol][None, :], D, jnp.inf)
                    # match top_k's index dtype (argmin gives i64 under x64)
                    return (
                        jnp.argmin(Df, axis=1).astype(topk_idx.dtype),
                        jnp.isfinite(jnp.min(Df, axis=1)),
                    )

                # Only rows whose query value in fcol is actually missing
                # consume the imputation result — a present-value row with
                # no eligible top-K donor must not revert the whole block
                # to the exact pass (ADVICE r4: block-global gating decayed
                # as (1-miss^K)^chunk_rows at high donor missingness). The
                # fast path stays exact for every consuming row.
                needs = jnp.isnan(X[:, fcol])
                idx, ok = jax.lax.cond(
                    jnp.all(any_elig | no_finite | ~needs),
                    lambda _: (idx_fast, any_elig),
                    exact,
                    None,
                )
            else:
                # Donor column complete: nearest eligible = global nearest.
                idx, ok = topk_idx[:, 0], topk_finite[:, 0]
            donated = jnp.where(
                ok, params.donors[idx, fcol], params.col_means[fcol]
            )
            col = X[:, fcol]
            out = out.at[:, fcol].set(jnp.where(jnp.isnan(col), donated, col))
        return out

    return jax.jit(f)


def _block_fn_for(params: KNNImputerParams, X_np: np.ndarray):
    """Resolve the specialised block fn for this query matrix: NaN columns
    from the query, eligibility-masked subset from the donor matrix (the
    donor NaN mask is reduced ON device — [F] bools home, not the whole
    donor matrix). When every NaN column is FULLY missing in the query —
    the contract-row pattern, and exactly the property that stays true
    for any row subset — the distance computation is restricted to the
    complement columns (``dist_cols``; see ``_block_fn``)."""
    isnan = np.isnan(X_np)
    nan_cols = tuple(int(c) for c in np.flatnonzero(isnan.any(axis=0)))
    donor_nan = np.asarray(jnp.any(jnp.isnan(params.donors), axis=0))
    masked = tuple(int(c) for c in nan_cols if donor_nan[c])
    dist_cols = None
    if nan_cols and bool(isnan[:, list(nan_cols)].all()):
        complement = tuple(
            c for c in range(X_np.shape[1]) if c not in set(nan_cols)
        )
        if complement:  # degenerate all-NaN queries keep the full form
            dist_cols = complement
    return _block_fn(nan_cols, masked, dist_cols)


def resolve_block_fn(params: KNNImputerParams, X_np: np.ndarray):
    """Resolve the block fn for ``X_np``'s NaN-column pattern ONCE, for
    callers whose pattern is fixed across many ``transform`` calls (the
    serving engine: contract rows always miss the same columns). The
    resolution pays a device reduction plus a blocking device→host fetch
    of the donor NaN mask — per *pattern* cost, not per-batch cost; pass
    the result back via ``transform(..., block_fn=...)``."""
    return _block_fn_for(params, np.asarray(X_np))


def transform(
    params: KNNImputerParams,
    X: jnp.ndarray,
    chunk_rows: int | None = None,
    mesh=None,
    block_fn=None,
) -> jnp.ndarray:
    """The specialised block fn (``_block_fn_for``; or a pre-resolved
    ``block_fn`` from ``resolve_block_fn`` — correct whenever its query
    pattern column-matches ``X``'s) over query chunks; single block when
    the query fits (``chunk_rows=None`` → ``ImputerConfig().chunk_rows``).

    With ``mesh``, query rows are sharded over the 'data' axis — the
    imputation of a row depends only on the (replicated) donor matrix, so
    the transform is embarrassingly row-parallel (VERDICT r2 item 5: at 10M
    rows this was the next single-device wall after the GBDT member).

    Complete rows (no NaN) are imputation fixed points, so only the
    incomplete rows travel through the O(rows × donors) distance machinery
    — at the cohort's ~3% row missingness that is ~30× less imputer work,
    with bit-identical output (sklearn's KNNImputer computes distances
    only for receivers too). The block program is additionally specialised
    to the query's NaN columns (``_block_fn``): fully-observed columns are
    copied through, and donor-complete columns share one argmin pass."""
    chunk = ImputerConfig().chunk_rows if chunk_rows is None else chunk_rows
    X_np = np.asarray(X)
    incomplete = np.isnan(X_np).any(axis=1)
    n_inc = int(incomplete.sum())
    if n_inc == 0:
        return jnp.asarray(X_np)
    if n_inc < X_np.shape[0]:
        out = np.array(X_np, dtype=X_np.dtype)
        # Dropping complete rows cannot change which COLUMNS hold NaN, so
        # a caller-supplied block_fn stays valid for the subset.
        out[incomplete] = np.asarray(
            transform(
                params, X_np[incomplete], chunk_rows, mesh=mesh,
                block_fn=block_fn,
            )
        )
        return jnp.asarray(out)
    if block_fn is None:
        block_fn = _block_fn_for(params, X_np)
    if mesh is not None:
        from machine_learning_replications_tpu.parallel.rowwise import (
            apply_rows_sharded,
        )

        # NaN pad rows impute to column means and are sliced off; columns
        # outside the query's nan_cols stay NaN in pad rows, harmlessly.
        return apply_rows_sharded(
            mesh, block_fn, params, X,
            chunk_rows=chunk, pad_value=np.nan,
        )
    n = int(X.shape[0])
    if n <= chunk:
        return block_fn(params, X)
    blocks = []
    for s in range(0, n, chunk):
        block = X_np[s : s + chunk]
        real = block.shape[0]
        if real < chunk:  # pad the tail so every block shares one shape
            block = np.pad(
                block, ((0, chunk - real), (0, 0)), constant_values=np.nan
            )
        blocks.append(np.asarray(block_fn(params, jnp.asarray(block)))[:real])
    return jnp.asarray(np.concatenate(blocks, axis=0))


def fit_transform(
    X_fit: jnp.ndarray,
    cfg: ImputerConfig = ImputerConfig(),
    seed: int = 2020,
    mesh=None,
    y: np.ndarray | None = None,
) -> tuple[KNNImputerParams, jnp.ndarray]:
    params = fit(X_fit, cfg, seed, y=y)
    return params, transform(params, X_fit, cfg.chunk_rows, mesh=mesh)
