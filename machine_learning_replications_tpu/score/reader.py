"""Streaming cohort ingest for bulk scoring: raw blocks in, parsed chunks out.

Two source formats, one contract:

  * **JSONL** — one patient JSON object per line, the 17-variable
    inference contract (``data.examples.validate_patient``), the same
    format ``tools/loadgen.py --patients`` drives serving with. Parsed
    chunks are contract-order ``[n, 17]`` rows.
  * **.mat** — the reference cohort layout (``data.matloader``): raw
    64-wide rows (NaNs allowed — the KNN imputer's job) when the file
    carries the full schema, contract rows when it carries exactly the
    17 model inputs. The outcome column, if present, is ignored: scoring
    is label-free by definition.

**Malformed-row policy.** ``validate_patient`` raises on the first bad
variable — correct for an interactive ``predict`` and fatal for a bulk
run: an hours-long cohort score must not die at row 1,999,999 because one
EHR export line was truncated. Streaming ingest therefore *quarantines*:
a bad line (unparseable JSON, missing/unknown/non-numeric variables) is
recorded with its 1-based line number, the error, and a bounded raw
snippet, the row is excluded from the chunk, and the run continues.
The error budget is bounded (``ScorePipeline(max_bad_rows=...)``): a
cohort that is mostly garbage aborts loudly instead of silently scoring
its parseable minority.

Blocks are *fixed line-count* slices of the input (``chunk_rows`` lines
per block), so the input→chunk mapping is deterministic: a resumed run
skips exactly the committed lines and re-enters at the same block
boundary the killed run would have used.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Iterator

import numpy as np

from machine_learning_replications_tpu.data.examples import validate_patient

#: Longest raw-line prefix a quarantine record keeps (whole lines could be
#: megabytes of garbage; the sidecar must stay proportionate to the error
#: count, not the error size).
QUARANTINE_SNIPPET_CHARS = 200


@dataclass
class RawBlock:
    """One input slice, pre-parse: ``seq`` is the 0-based chunk index over
    the whole input (resume-stable), ``start_line`` the 1-based input line
    (or row, for .mat) of its first entry."""

    seq: int
    start_line: int
    lines: list[str] | None = None   # JSONL payload
    rows: np.ndarray | None = None   # .mat payload

    def __len__(self) -> int:
        return len(self.lines) if self.lines is not None else len(self.rows)


@dataclass
class ParsedChunk:
    """One scoring-ready chunk: ``X[n, width]`` valid rows (n ≤ block
    lines), each row's 1-based input line number (``line_nos[n]`` — the
    output's join key back to the source file), the lines consumed from
    the input, and the quarantined entries ``(line_no, error, snippet)``
    in input order."""

    seq: int
    start_line: int
    X: np.ndarray
    line_nos: np.ndarray
    lines_consumed: int
    bad: list[tuple[int, str, str]] = field(default_factory=list)

    @property
    def n_rows(self) -> int:
        return int(self.X.shape[0])


def parse_patient_lines(
    lines: list[str], start_line: int
) -> tuple[np.ndarray, np.ndarray, list[tuple[int, str, str]]]:
    """Validate a block of JSONL patient lines against the 17-variable
    contract: ``(X[n, 17], line_nos[n], bad)``. A pure module-level
    function on purpose — it is the process-pool entry point for
    ``ScorePipeline(parse_procs=...)``, where ingest parsing runs in
    spawned worker processes so the GIL-bound JSON work stops competing
    with the parent's XLA dispatch (workers never touch a JAX device;
    everything here is stdlib + numpy and pickles cheaply)."""
    rows: list[np.ndarray] = []
    line_nos: list[int] = []
    bad: list[tuple[int, str, str]] = []
    for i, raw in enumerate(lines):
        line_no = start_line + i
        stripped = raw.strip()
        if not stripped:
            bad.append((line_no, "empty line", ""))
            continue
        try:
            patient = json.loads(stripped)
            rows.append(validate_patient(patient)[0])
            line_nos.append(line_no)
        except (ValueError, TypeError) as exc:
            # json.JSONDecodeError is a ValueError; validate_patient
            # raises ValueError with the variable-level diagnosis.
            bad.append((
                line_no,
                f"{type(exc).__name__}: {exc}",
                stripped[:QUARANTINE_SNIPPET_CHARS],
            ))
    X = np.stack(rows) if rows else np.empty((0, 17), np.float64)
    return X, np.asarray(line_nos, np.int64), bad


def parse_patient_lines_timed(lines: list[str], start_line: int):
    """``parse_patient_lines`` plus the worker-side elapsed seconds, so the
    parent's per-stage accounting can attribute remote parse time without
    conflating it with pool queueing."""
    import time

    t0 = time.perf_counter()
    X, line_nos, bad = parse_patient_lines(lines, start_line)
    return X, line_nos, bad, time.perf_counter() - t0


class JsonlCohortSource:
    """A JSONL patient cohort: sequential raw-line blocks + a parse step
    safe to run from several worker threads at once (pure function of the
    block) — or, via ``parse_patient_lines``, from worker processes."""

    kind = "contract"
    width = 17
    supports_process_parse = True

    def __init__(self, path: str, chunk_rows: int, limit: int | None = None):
        if chunk_rows < 1:
            raise ValueError(f"chunk_rows must be >= 1, got {chunk_rows}")
        self.path = os.path.abspath(path)
        self.chunk_rows = int(chunk_rows)
        self.limit = None if limit is None else int(limit)

    def blocks(
        self, skip_lines: int = 0, start_seq: int = 0
    ) -> Iterator[RawBlock]:
        """Sequential block reader (the single ingest thread): skips the
        already-committed prefix line-by-line without parsing, then yields
        ``chunk_rows``-line blocks until EOF (or ``limit`` input lines,
        counted from the file start)."""
        budget = None if self.limit is None else self.limit - skip_lines
        if budget is not None and budget <= 0:
            return
        seq = start_seq
        line_no = 0
        with open(self.path, "r", encoding="utf-8", errors="replace") as f:
            for _ in range(skip_lines):
                if not f.readline():
                    return
                line_no += 1
            while True:
                take = self.chunk_rows
                if budget is not None:
                    take = min(take, budget)
                    if take <= 0:
                        return
                lines: list[str] = []
                start = line_no + 1
                for _ in range(take):
                    line = f.readline()
                    if not line:
                        break
                    line_no += 1
                    lines.append(line)
                if not lines:
                    return
                if budget is not None:
                    budget -= len(lines)
                yield RawBlock(seq=seq, start_line=start, lines=lines)
                seq += 1

    def parse(self, block: RawBlock) -> ParsedChunk:
        """Validate every line of the block against the 17-variable
        contract; bad lines are quarantined, good rows packed into one
        ``[n, 17]`` float64 matrix."""
        X, line_nos, bad = parse_patient_lines(block.lines, block.start_line)
        return ParsedChunk(
            seq=block.seq, start_line=block.start_line, X=X,
            line_nos=line_nos, lines_consumed=len(block.lines), bad=bad,
        )


class MatCohortSource:
    """A reference-layout ``.mat`` cohort. The MAT-v5 container is not
    streamable (both backends materialize the matrix), so the file loads
    once on first use and blocks are row slices; at the multi-million-row
    scale the matrix is hundreds of MB — bounded — while the *output* side
    of the pipeline still streams. ``data.matloader.load_feature_matrix``
    owns the format details (outcome-column handling included)."""

    supports_process_parse = False  # parse is a free dtype view — threads

    def __init__(self, path: str, chunk_rows: int, limit: int | None = None):
        if chunk_rows < 1:
            raise ValueError(f"chunk_rows must be >= 1, got {chunk_rows}")
        self.path = os.path.abspath(path)
        self.chunk_rows = int(chunk_rows)
        self.limit = None if limit is None else int(limit)
        self._X: np.ndarray | None = None

    def _matrix(self) -> np.ndarray:
        if self._X is None:
            from machine_learning_replications_tpu.data import matloader

            self._X = matloader.load_feature_matrix(self.path)
            if self.limit is not None:
                self._X = self._X[: self.limit]
        return self._X

    @property
    def kind(self) -> str:
        return "contract" if self._matrix().shape[1] == 17 else "x64"

    @property
    def width(self) -> int:
        return int(self._matrix().shape[1])

    def blocks(
        self, skip_lines: int = 0, start_seq: int = 0
    ) -> Iterator[RawBlock]:
        X = self._matrix()
        seq = start_seq
        for s in range(skip_lines, X.shape[0], self.chunk_rows):
            rows = X[s : s + self.chunk_rows]
            yield RawBlock(seq=seq, start_line=s + 1, rows=rows)
            seq += 1

    def parse(self, block: RawBlock) -> ParsedChunk:
        # Matrix rows cannot be malformed (fixed width; NaN is a legal
        # missing value for the imputer) — parse is a dtype normalization.
        n = len(block.rows)
        return ParsedChunk(
            seq=block.seq, start_line=block.start_line,
            X=np.asarray(block.rows, np.float64),
            line_nos=np.arange(
                block.start_line, block.start_line + n, dtype=np.int64
            ),
            lines_consumed=n,
        )


def open_cohort(
    path: str, chunk_rows: int, fmt: str = "auto", limit: int | None = None
):
    """Resolve a cohort path to its source: ``.jsonl``/``.json``/``.ndjson``
    → JSONL patient dicts, ``.mat`` → the reference matrix layout; ``fmt``
    overrides the extension sniff."""
    if fmt not in ("auto", "jsonl", "mat"):
        raise ValueError(f"unknown cohort format {fmt!r}; use auto|jsonl|mat")
    if fmt == "auto":
        ext = os.path.splitext(path)[1].lower()
        fmt = "mat" if ext == ".mat" else "jsonl"
    if fmt == "mat":
        return MatCohortSource(path, chunk_rows, limit=limit)
    return JsonlCohortSource(path, chunk_rows, limit=limit)
