"""Resumable bulk-scoring progress: an atomic manifest + output truncation.

The training side solved preemption with stage checkpoints
(``persist.orbax_io.StageCheckpointer``); a cohort score is one long
"stage" whose output is a stream, so the durable unit here is the *chunk*:
after the writer has flushed a chunk's score lines (and its quarantine
entries), the progress manifest is atomically replaced
(``persist.atomicio.atomic_json_write`` — the integrity-publish style: a
crash leaves either the previous complete manifest or the new one) with
the new committed prefix: chunks, rows, input lines consumed, per-shard
row/byte counts, quarantine bytes, and a rolling sha256 over the emitted
score lines.

Resume re-enters through ``load()``:

  * the stored **fingerprint** (input path/size, route, params digest,
    chunk/shard geometry) must match this run's — a manifest written by a
    different cohort, model, or chunking must fail loudly
    (``ScoreResumeError``), never silently splice two runs' outputs (the
    ``StageCheckpointer`` fingerprint contract);
  * output files are **truncated back to the committed byte counts** —
    whatever a killed run wrote past its last commit is discarded, so the
    restarted run's appends continue byte-identically to an uninterrupted
    run (no duplicate rows, no missing rows);
  * the reader skips exactly ``lines`` committed input lines and the next
    chunk takes ``chunks`` as its sequence number.

The rolling digest makes "byte-identical" checkable without re-reading
shards: an uninterrupted run and a kill+resume run over the same input
must commit the same final ``output_sha256``.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Any

from machine_learning_replications_tpu.persist.atomicio import (
    atomic_json_write,
)

PROGRESS_FILE = "progress.json"
_FORMAT = 1


class ScoreResumeError(RuntimeError):
    """The output directory's progress manifest cannot serve this run."""


def params_digest(model: str | None = None, pkl: str | None = None) -> str:
    """Cheap identity of the scoring model for the resume fingerprint.
    Checkpoint dirs hash their integrity manifest (content-derived, the
    ``orbax_io`` publish wrote it over every payload file); pickles hash
    path + size + mtime. Same spirit as ``pipeline._fit_fingerprint``:
    catch accidental reuse, stay O(KB)."""
    h = hashlib.sha256()
    if model:
        path = os.path.abspath(model)
        h.update(b"model:" + path.encode())
        manifest = os.path.join(path, "integrity.json")
        try:
            with open(manifest, "rb") as f:
                h.update(f.read())
        except OSError:
            pass  # legacy checkpoint: path-only identity
    else:
        path = os.path.abspath(pkl) if pkl else "<reference-pkl>"
        h.update(b"pkl:" + str(path).encode())
        try:
            st = os.stat(path)
            h.update(f":{st.st_size}:{st.st_mtime_ns}".encode())
        except OSError:
            pass
    return h.hexdigest()


def make_fingerprint(
    input_path: str,
    route: str,
    params: str,
    chunk_rows: int,
    rows_per_shard: int,
    limit: int | None,
) -> dict:
    """The (input, model, geometry) identity a progress manifest binds to.
    Geometry is part of it on purpose: chunk boundaries define the commit
    points and shard boundaries define the output layout, so resuming with
    different values could not continue byte-identically."""
    input_path = os.path.abspath(input_path)
    try:
        input_bytes = os.path.getsize(input_path)
    except OSError:
        input_bytes = None
    return {
        "input": input_path,
        "input_bytes": input_bytes,
        "route": route,
        "params": params,
        "chunk_rows": int(chunk_rows),
        "rows_per_shard": int(rows_per_shard),
        "limit": limit,
    }


class ScoreProgress:
    """The committed-prefix ledger of one output directory."""

    def __init__(self, out_dir: str, fingerprint: dict) -> None:
        self.out_dir = os.path.abspath(out_dir)
        os.makedirs(self.out_dir, exist_ok=True)
        self.path = os.path.join(self.out_dir, PROGRESS_FILE)
        self.fingerprint = fingerprint
        self.chunks = 0
        self.rows = 0
        self.lines = 0
        self.bad_rows = 0
        self.quarantine_bytes = 0
        self.shards: list[dict] = []
        self.done = False
        self._hasher = hashlib.sha256()

    # -- load / init --------------------------------------------------------

    def load(self, fresh: bool = False) -> bool:
        """Adopt an existing manifest (returns True — a resume) or start
        clean (False). ``fresh`` discards any prior state instead of
        resuming it; a *finished* manifest also starts clean (re-scoring a
        cohort into the same directory is a new run, not a resume).
        Fingerprint mismatch raises ``ScoreResumeError``."""
        if fresh or not os.path.exists(self.path):
            self._reset_outputs()
            return False
        try:
            with open(self.path) as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError) as exc:
            raise ScoreResumeError(
                f"unreadable progress manifest {self.path!r}: "
                f"{type(exc).__name__}: {exc}; pass --fresh to discard"
            ) from exc
        if rec.get("format") != _FORMAT:
            raise ScoreResumeError(
                f"progress manifest {self.path!r} has unknown format "
                f"{rec.get('format')!r}; pass --fresh to discard"
            )
        stored = rec.get("fingerprint") or {}
        if stored != self.fingerprint:
            diff = sorted(
                k for k in set(stored) | set(self.fingerprint)
                if stored.get(k) != self.fingerprint.get(k)
            )
            raise ScoreResumeError(
                f"output dir {self.out_dir!r} holds progress for a "
                f"different run (fields differing: {', '.join(diff)}); "
                "pass --fresh to discard it or use a new --out"
            )
        if rec.get("done"):
            self._reset_outputs()
            return False
        self.chunks = int(rec["chunks"])
        self.rows = int(rec["rows"])
        self.lines = int(rec["lines"])
        self.bad_rows = int(rec.get("bad_rows", 0))
        self.quarantine_bytes = int(rec.get("quarantine_bytes", 0))
        self.shards = list(rec.get("shards", []))
        # The rolling output digest cannot be resumed from a hash state —
        # rebuild it from the committed (truncated) shard bytes. Bounded
        # by the already-scored output, a read-only pass.
        self._hasher = hashlib.sha256()
        for shard in self.shards:
            fp = os.path.join(self.out_dir, shard["name"])
            with open(fp, "rb") as f:
                remaining = int(shard["bytes"])
                while remaining > 0:
                    buf = f.read(min(1 << 20, remaining))
                    if not buf:
                        raise ScoreResumeError(
                            f"shard {shard['name']!r} is shorter than its "
                            f"committed {shard['bytes']} bytes"
                        )
                    self._hasher.update(buf)
                    remaining -= len(buf)
        return True

    def _reset_outputs(self) -> None:
        """A clean start must not inherit stray outputs from an abandoned
        or finished run in the same directory — summary/quality included:
        a leftover ``summary.json`` from a prior completed run would
        attribute that run's rows, digest, and quality verdict to this
        one if this one aborts before writing its own."""
        for name in sorted(os.listdir(self.out_dir)):
            if name.startswith("scores-") and name.endswith(".jsonl"):
                os.unlink(os.path.join(self.out_dir, name))
        for name in (
            PROGRESS_FILE, "quarantine.jsonl", "summary.json", "quality.json",
        ):
            fp = os.path.join(self.out_dir, name)
            if os.path.exists(fp):
                os.unlink(fp)

    # -- commit -------------------------------------------------------------

    def absorb_output(self, data: bytes) -> None:
        """Feed committed score bytes into the rolling output digest (the
        writer calls this with exactly what it appended)."""
        self._hasher.update(data)

    def commit(
        self,
        *,
        rows: int,
        lines: int,
        bad_rows: int,
        shards: list[dict],
        quarantine_bytes: int,
    ) -> None:
        """Advance the committed prefix by one chunk and atomically
        publish. Call ONLY after the chunk's output bytes are flushed
        durable — the manifest must never run ahead of the data."""
        self.chunks += 1
        self.rows += int(rows)
        self.lines += int(lines)
        self.bad_rows += int(bad_rows)
        self.shards = shards
        self.quarantine_bytes = int(quarantine_bytes)
        atomic_json_write(self.path, self._record())

    def finish(self, summary: dict | None = None) -> None:
        self.done = True
        rec = self._record()
        if summary is not None:
            rec["summary"] = summary
        atomic_json_write(self.path, rec)

    def output_sha256(self) -> str:
        return self._hasher.hexdigest()

    def _record(self) -> dict[str, Any]:
        return {
            "format": _FORMAT,
            "fingerprint": self.fingerprint,
            "chunks": self.chunks,
            "rows": self.rows,
            "lines": self.lines,
            "bad_rows": self.bad_rows,
            "quarantine_bytes": self.quarantine_bytes,
            "shards": self.shards,
            "output_sha256": self.output_sha256(),
            "done": self.done,
        }
