"""Population-scale bulk scoring (L7 — the "nightly rescore every patient"
workload; docs/SCORING.md).

The serving layer (``serve/``) answers *requests*: single patients and
micro-batches under a latency SLO. This package answers *cohorts*: stream
a multi-million-row patient file (JSONL patient dicts or a reference-layout
``.mat``) through the same mesh-shardable predict tail as ``cli predict``,
with

  * a pipelined producer/consumer architecture — reader + parse workers
    doing host work (parse, validate, quarantine, impute-route) feed a
    bounded prefetch queue; the device stage double-buffers ``device_put``
    so chunk N+1 transfers while chunk N computes at one fixed padded
    chunk shape (one XLA compile for the whole run); an ordered writer
    drains results to sharded output files;
  * resumability — per-chunk journal events plus an atomic progress
    manifest (``score/progress.py``, the ``persist/orbax_io.py`` integrity-
    publish style), so a killed run restarts at the last committed chunk
    with zero re-scored and zero skipped rows, byte-identical to an
    uninterrupted run;
  * observability — per-stage spans (``obs/spans.py``), ``score_*`` metric
    families (``obs/registry.py``), and the model-quality monitor
    (``obs/quality.py``) running over the full scored population instead
    of a serving window.

Entry point: ``cli.py score``; bench: ``tools/score_bench.py``.
"""

from machine_learning_replications_tpu.score.pipeline import (  # noqa: F401
    ScorePipeline,
    ScoreBudgetExceeded,
    ScoreInterrupted,
)
from machine_learning_replications_tpu.score.reader import (  # noqa: F401
    JsonlCohortSource,
    MatCohortSource,
    open_cohort,
)
from machine_learning_replications_tpu.score.progress import (  # noqa: F401
    ScoreProgress,
    ScoreResumeError,
)
