"""Population-scale bulk scoring (L7 — the "nightly rescore every patient"
workload; docs/SCORING.md).

The serving layer (``serve/``) answers *requests*: single patients and
micro-batches under a latency SLO. This package answers *cohorts*: stream
a multi-million-row patient file (JSONL patient dicts or a reference-layout
``.mat``) through the same mesh-shardable predict tail as ``cli predict``,
with

  * a pipelined producer/consumer architecture — reader + parse workers
    doing host work (parse, validate, quarantine, impute-route) feed a
    bounded prefetch queue; the device stage double-buffers ``device_put``
    so chunk N+1 transfers while chunk N computes at one fixed padded
    chunk shape (one XLA compile for the whole run); an ordered writer
    drains results to sharded output files;
  * resumability — per-chunk journal events plus an atomic progress
    manifest (``score/progress.py``, the ``persist/orbax_io.py`` integrity-
    publish style), so a killed run restarts at the last committed chunk
    with zero re-scored and zero skipped rows, byte-identical to an
    uninterrupted run;
  * observability — per-stage spans (``obs/spans.py``), ``score_*`` metric
    families (``obs/registry.py``), and the model-quality monitor
    (``obs/quality.py``) running over the full scored population instead
    of a serving window.

Entry point: ``cli.py score``; bench: ``tools/score_bench.py``.
"""

# Re-exports resolve lazily (PEP 562): this ``__init__`` executes before
# any ``score.*`` submodule, and ``score.reader``'s parse path is declared
# jax-free (graftcheck rule import-purity) — an eager ``pipeline`` import
# here would put the whole device stage into the reader's import-time
# closure.
from machine_learning_replications_tpu.lazyimport import lazy_exports

_EXPORTS = {
    "ScorePipeline": "pipeline",
    "ScoreBudgetExceeded": "pipeline",
    "ScoreInterrupted": "pipeline",
    "JsonlCohortSource": "reader",
    "MatCohortSource": "reader",
    "open_cohort": "reader",
    "ScoreProgress": "progress",
    "ScoreResumeError": "progress",
}

__all__ = sorted(_EXPORTS)
__getattr__, __dir__ = lazy_exports(__name__, _EXPORTS)
