"""Ordered, sharded output for bulk scoring — the pipeline's sink stage.

Scores land as JSONL shards (``scores-00000.jsonl``, ...): one
``{"row": <global 0-based input row>, "p1": <float repr>}`` object per
scored row, in input order, rotating every ``rows_per_shard`` rows.
``repr(float)`` is the shortest round-trip representation, so parity
checks (``json.loads(line)["p1"] == float(expected)``) are exact, and the
byte stream is a pure function of the scores — the property the resume
contract's "byte-identical to an uninterrupted run" rides on.

Durability protocol (one chunk = one transaction, driven by the
pipeline): ``append`` buffers through the OS, ``sync`` flushes+fsyncs and
returns the committed state (per-shard rows/bytes + the bytes appended
since the last sync, which the progress ledger folds into its rolling
digest) — only then is the progress manifest advanced. On resume,
``restore`` truncates every shard back to its committed byte count and
deletes shards the manifest never committed, discarding whatever a killed
run wrote past its last commit.

The quarantine sidecar (``quarantine.jsonl``) follows the same protocol
with line-numbered records — the malformed-row policy's audit trail.
"""

from __future__ import annotations

import glob
import json
import os


class _AppendFile:
    """One append-only file with explicit sync/truncate-restore."""

    def __init__(self, path: str) -> None:
        self.path = path
        self._f = None
        self._pending = bytearray()

    def _handle(self):
        if self._f is None:
            self._f = open(self.path, "ab")
        return self._f

    def append(self, data: bytes) -> None:
        self._handle().write(data)
        self._pending += data

    def sync(self, durable: bool = True) -> bytes:
        """Flush (+fsync when ``durable``) and return the bytes appended
        since the previous sync."""
        if self._f is not None:
            self._f.flush()
            if durable:
                os.fsync(self._f.fileno())
        out = bytes(self._pending)
        self._pending.clear()
        return out

    def truncate_to(self, n_bytes: int) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
        size = os.path.getsize(self.path) if os.path.exists(self.path) else 0
        if size < n_bytes:
            raise ValueError(
                f"{self.path!r} is {size} bytes, shorter than the "
                f"committed {n_bytes}"
            )
        if size > n_bytes:
            with open(self.path, "r+b") as f:
                f.truncate(n_bytes)

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class ShardedScoreWriter:
    """Rotating score shards, append-committed in input order."""

    SHARD_FMT = "scores-{:05d}.jsonl"

    def __init__(
        self, out_dir: str, rows_per_shard: int, durable: bool = True
    ) -> None:
        if rows_per_shard < 1:
            raise ValueError(
                f"rows_per_shard must be >= 1, got {rows_per_shard}"
            )
        self.out_dir = os.path.abspath(out_dir)
        os.makedirs(self.out_dir, exist_ok=True)
        self.rows_per_shard = int(rows_per_shard)
        self.durable = durable
        self.shards: list[dict] = []  # [{"name", "rows", "bytes"}]
        self._current: _AppendFile | None = None
        # Bytes flushed since the last sync() across EVERY shard touched —
        # a chunk can span a rotation, and the rolling output digest must
        # see the closing shard's tail too, in append order.
        self._synced = bytearray()

    # -- resume -------------------------------------------------------------

    def restore(self, shards: list[dict]) -> None:
        """Adopt the committed shard state: truncate each to its committed
        bytes, delete uncommitted stragglers, reopen the tail shard."""
        committed = {s["name"] for s in shards}
        for fp in glob.glob(os.path.join(self.out_dir, "scores-*.jsonl")):
            if os.path.basename(fp) not in committed:
                os.unlink(fp)
        self.shards = [dict(s) for s in shards]
        for s in self.shards:
            _AppendFile(os.path.join(self.out_dir, s["name"])).truncate_to(
                int(s["bytes"])
            )
        self._current = None

    # -- write --------------------------------------------------------------

    def _shard_for_append(self) -> tuple[dict, _AppendFile]:
        if not self.shards or self.shards[-1]["rows"] >= self.rows_per_shard:
            name = self.SHARD_FMT.format(len(self.shards))
            self.shards.append({"name": name, "rows": 0, "bytes": 0})
            if self._current is not None:
                # Rotation: the closing shard's unsynced tail must reach
                # both disk (durability follows the same per-commit
                # policy) and the pending-bytes ledger (digest ordering).
                self._synced += self._current.sync(durable=self.durable)
                self._current.close()
            self._current = None
        if self._current is None:
            self._current = _AppendFile(
                os.path.join(self.out_dir, self.shards[-1]["name"])
            )
        return self.shards[-1], self._current

    def append_chunk(self, start_row: int, line_nos, p1) -> None:
        """Append one chunk's scores: ``row`` is the global 0-based scored
        ordinal (``start_row`` onward), ``line`` the row's 1-based input
        line — the join key that survives quarantined gaps."""
        i = int(start_row)
        vals = [float(v) for v in p1]
        lines = [int(v) for v in line_nos]
        if len(vals) != len(lines):
            raise ValueError(
                f"{len(vals)} scores for {len(lines)} line numbers"
            )
        off = 0
        while off < len(vals):
            shard, f = self._shard_for_append()
            take = min(len(vals) - off, self.rows_per_shard - shard["rows"])
            data = "".join(
                '{"row":%d,"line":%d,"p1":%r}\n'
                % (i + k, lines[off + k], vals[off + k])
                for k in range(take)
            ).encode()
            f.append(data)
            shard["rows"] += take
            shard["bytes"] += len(data)
            i += take
            off += take

    def sync(self) -> tuple[list[dict], bytes]:
        """Commit point: flush the open shard; returns (deep-copied shard
        state, bytes appended since the last sync — every shard touched,
        in append order)."""
        if self._current is not None:
            self._synced += self._current.sync(durable=self.durable)
        data = bytes(self._synced)
        self._synced.clear()
        return [dict(s) for s in self.shards], data

    def close(self) -> None:
        if self._current is not None:
            self._current.close()
            self._current = None

    def shard_paths(self) -> list[str]:
        return [os.path.join(self.out_dir, s["name"]) for s in self.shards]


class QuarantineWriter:
    """The malformed-row sidecar: line-numbered, append-committed with the
    same truncate-on-resume protocol as the score shards."""

    FILE = "quarantine.jsonl"

    def __init__(self, out_dir: str, durable: bool = True) -> None:
        self.path = os.path.join(os.path.abspath(out_dir), self.FILE)
        self.durable = durable
        self._f = _AppendFile(self.path)
        self.bytes = 0

    def restore(self, committed_bytes: int) -> None:
        self._f.truncate_to(int(committed_bytes))
        self.bytes = int(committed_bytes)

    def append(self, entries) -> None:
        """``entries``: (line_no, error, snippet) triples from one chunk."""
        if not entries:
            return
        data = "".join(
            json.dumps(
                {"line": line, "error": err, "raw": snippet},
                separators=(",", ":"),
            ) + "\n"
            for line, err, snippet in entries
        ).encode()
        self._f.append(data)
        self.bytes += len(data)

    def sync(self) -> int:
        self._f.sync(durable=self.durable)
        return self.bytes

    def close(self) -> None:
        self._f.close()
