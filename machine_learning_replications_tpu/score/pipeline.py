"""The overlapped ingest→device scoring pipeline (docs/SCORING.md).

Sequentially, scoring a streamed cohort is four serialized stages per
chunk — read, parse/prep (host), device (transfer + compute), write — and
the device sits idle during every host stage. This module runs them as a
software pipeline instead:

  * one **reader** thread slices the input into fixed-size raw blocks and
    feeds a bounded prefetch queue (backpressure: ingest can never run
    more than ``prefetch`` chunks ahead of the device);
  * ``parse_workers`` **parse threads** do the per-chunk host work —
    JSON parse, contract validation with malformed-row quarantine, the
    impute-route prep (``contract_rows_to_x64`` → ``impute_select`` with
    the pre-resolved contract block fn) — and hand chunks to a reorder
    buffer (workers finish out of order; everything downstream is
    strictly ordered);
  * one **device** thread double-buffers: chunk N+1 is ``device_put`` and
    its compute *dispatched* (JAX dispatch is async) before chunk N's
    result is fetched, so host→device transfer and XLA compute overlap
    with result fetch — and, because XLA releases the GIL, with the parse
    workers' pure-Python work. Every chunk is padded to ONE static shape
    (``data.sharding.pad_rows_to``, edge mode — the serving engine's
    padding), so the predict tail compiles exactly once per run (see
    ``ChunkScorer`` for why the tail is the eager oracle composition,
    not a donated re-jitted program);
  * one **writer** thread drains results in order into the sharded output
    (``score/writer.py``), feeds the cohort-level quality monitor, and
    commits the progress manifest per chunk (``score/progress.py``) — the
    durable unit a killed run resumes at.

``parse_procs > 0`` swaps the parse threads for spawned worker
*processes* (JSONL sources only; ``_run_overlapped_procs``): ingest
parsing then runs free of the parent's GIL entirely — the right trade on
many-core hosts where a single interpreter lock is the ingest ceiling;
on the ~2-core bench sandbox, where *total* CPU binds, the in-process
thread mode measures best and stays the default.

The sequential path (``overlap=False``) runs the identical stage
functions in one loop with no threads — the ablation ``tools/
score_bench.py`` measures the overlap against, and the honest fallback
for debugging.

Telemetry: per-stage spans (``score:read`` / ``score:parse`` /
``score:device`` / ``score:write``), ``score_*`` families on the global
registry, a ``score_chunk`` journal event per committed chunk, and
``score_resume`` / ``score_done`` run events.
"""

from __future__ import annotations

import contextlib
import os
import queue
import threading
import time
from typing import Any

import numpy as np

from machine_learning_replications_tpu.obs import journal, spans
from machine_learning_replications_tpu.obs.registry import REGISTRY
from machine_learning_replications_tpu.persist.atomicio import (
    atomic_json_write,
)
from machine_learning_replications_tpu.score.progress import (
    ScoreProgress,
    make_fingerprint,
)
from machine_learning_replications_tpu.score.reader import ParsedChunk
from machine_learning_replications_tpu.score.writer import (
    QuarantineWriter,
    ShardedScoreWriter,
)

DEFAULT_CHUNK_ROWS = 2048
DEFAULT_PREFETCH = 4
DEFAULT_PARSE_WORKERS = 2
DEFAULT_ROWS_PER_SHARD = 500_000
DEFAULT_MAX_BAD_ROWS = 1000
#: Cohort-scale quality window: drift/calibration judged over the whole
#: scored population (bounded at ~60 MB of rings), not a serving window.
DEFAULT_QUALITY_WINDOW = 1 << 20

_M_ROWS = REGISTRY.counter(
    "score_rows_total", "Cohort rows scored and committed to output shards."
)
_M_QUAR = REGISTRY.counter(
    "score_quarantined_rows_total",
    "Malformed cohort rows quarantined to the sidecar instead of scored.",
)
_M_CHUNKS = REGISTRY.counter(
    "score_chunks_total", "Scoring chunks committed to the progress manifest."
)
_M_CHUNK_S = REGISTRY.histogram(
    "score_chunk_seconds",
    "Wall seconds from a chunk leaving the reader to its durable commit.",
    buckets=(0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0),
)
_M_QDEPTH = REGISTRY.gauge(
    "score_queue_depth",
    "Chunks queued between pipeline stages (bounded by the prefetch "
    "budget).",
    labels=("stage",),
)
_M_STAGE_S = REGISTRY.counter(
    "score_stage_seconds_total",
    "Busy seconds per pipeline stage (read/parse/device/write); in "
    "overlapped mode stages run concurrently, so the sum can exceed wall "
    "time.",
    labels=("stage",),
)


class ScoreBudgetExceeded(RuntimeError):
    """The malformed-row error budget ran out: the cohort is garbage at a
    rate no quarantine policy should paper over. ``bad_rows`` carries the
    triggering chunk's quarantine entries so the abort path can flush
    them to the sidecar the operator is pointed at (they would otherwise
    be dropped with the uncommitted chunk)."""

    def __init__(self, message: str, bad_rows=None) -> None:
        super().__init__(message)
        self.bad_rows = list(bad_rows or [])


class ScoreInterrupted(RuntimeError):
    """Test hook: simulated preemption right after a chunk commit (the
    ``StageCheckpointer._interrupt_after`` idiom at chunk granularity)."""


class _StageClock:
    """Per-stage busy-seconds accounting shared by both modes; every
    timed scope is also a span on the active tracer."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.seconds: dict[str, float] = {}

    @contextlib.contextmanager
    def stage(self, name: str, **span_args):
        t0 = time.perf_counter()
        with spans.span(f"score:{name}", **span_args):
            yield
        dt = time.perf_counter() - t0
        with self._lock:
            self.seconds[name] = self.seconds.get(name, 0.0) + dt
        _M_STAGE_S.inc(dt, stage=name)

    def add(self, name: str, dt: float) -> None:
        """Account externally-timed work (process-pool parse workers
        report their own elapsed seconds)."""
        with self._lock:
            self.seconds[name] = self.seconds.get(name, 0.0) + dt
        _M_STAGE_S.inc(dt, stage=name)

    def snapshot(self) -> dict[str, float]:
        with self._lock:
            return {k: round(v, 3) for k, v in sorted(self.seconds.items())}


class _Pending:
    """One in-flight device chunk: dispatched, not yet fetched."""

    __slots__ = ("p1", "members", "X", "n")

    def __init__(self, p1, members, X, n):
        self.p1 = p1
        self.members = members
        self.X = X
        self.n = n


class ChunkScorer:
    """Fixed-shape, double-bufferable scoring of streamed chunks through
    THE predict tail ``cli predict`` runs.

    ``submit`` pads the prepped chunk to the one static ``[chunk_rows, F]``
    shape (``pad_rows_to``, edge mode), places it on device
    (``obs.jaxmon.device_put`` — h2d bytes accounted), and *dispatches*
    the stacked compute without blocking (JAX async dispatch); ``finish``
    fetches and slices pads off. The caller overlaps by submitting chunk
    N+1 before finishing chunk N.

    **Why the compute is eager, not re-jitted.** Wrapping the stacked
    pass in its own ``jax.jit`` (with donated input buffers, the serving
    engine's shape) was measured to shift ~14% of a cohort's
    probabilities by 1–2 ulp relative to the eager
    ``stacking.predict_proba1`` the CLI oracle runs — XLA fuses the
    whole-program graph differently from the per-op executables, and
    "bit-identical to ``cli predict``" is this workload's acceptance
    gate (tests/test_score.py pins it). So the scorer calls the SAME
    eager composition as ``pipeline_predict_proba1[_contract]``:
    per-op executables are cached by shape, the fixed chunk shape bounds
    them to one compile each for the whole run (asserted via
    ``obs.jaxmon.compile_count`` deltas), and the padded chunk buffer is
    dropped right after fetch so the double-buffered steady state holds
    two chunk buffers. Input donation is the one engine trick this
    deliberately gives up — it requires the re-jitted program whose
    rounding breaks the parity contract.
    """

    def __init__(self, params, chunk_rows: int, route: str, mesh=None):
        from machine_learning_replications_tpu.models import (
            pipeline, stacking, tree,
        )
        from machine_learning_replications_tpu.obs import jaxmon

        if chunk_rows < 1:
            raise ValueError(f"chunk_rows must be >= 1, got {chunk_rows}")
        self.chunk_rows = int(chunk_rows)
        self.route = route
        self.mesh = mesh

        is_pipeline = isinstance(params, pipeline.PipelineParams)
        if route == "x64" and not is_pipeline:
            raise TypeError(
                f"a 64-wide raw cohort needs a full PipelineParams "
                f"checkpoint (impute → select → ensemble); got "
                f"{type(params).__name__}"
            )
        if route not in ("contract", "x64"):
            raise ValueError(f"unknown route {route!r}")

        # Bare ensembles (no imputer) score contract rows verbatim: a NaN
        # row would flow through the SVM kernel to a NaN probability,
        # which repr-serializes as invalid JSON in the shards. The
        # pipeline quarantines such rows (the .mat route is the only one
        # that can produce them — JSONL validation already rejects
        # non-finite values) instead of silently corrupting output.
        self.requires_finite_rows = route == "contract" and not is_pipeline

        if is_pipeline:
            # Params on device once (engine discipline), support mask
            # host-resident for impute_select's np.where.
            dparams = jaxmon.device_put(params).replace(
                support_mask=np.asarray(params.support_mask)
            )
            contract_fn = (
                pipeline.resolve_contract_block_fn(params)
                if route == "contract" else None
            )

            def prep(X: np.ndarray) -> np.ndarray:
                if route == "contract":
                    x64 = pipeline.contract_rows_to_x64(params, X)
                    # Contract cohorts are all-finite post-validation, so
                    # the pre-resolved pattern fn applies; a wider pattern
                    # (direct API callers) falls back to per-pattern
                    # resolution rather than mis-imputing.
                    fn = None if np.isnan(X).any() else contract_fn
                else:
                    x64, fn = np.asarray(X, np.float64), None
                return np.asarray(
                    pipeline.impute_select(dparams, x64, block_fn=fn)
                )

            ens = dparams.ensemble

            def compute(Xd):
                return stacking.predict_proba1_with_members(ens, Xd)

        elif isinstance(params, tree.TreeEnsembleParams):
            dparams = jaxmon.device_put(params)

            def prep(X: np.ndarray) -> np.ndarray:
                return np.asarray(X, np.float64)

            def compute(Xd):
                return tree.predict_proba1(dparams, Xd), None

        elif isinstance(params, stacking.StackingParams):
            dparams = jaxmon.device_put(params)

            def prep(X: np.ndarray) -> np.ndarray:
                return np.asarray(X, np.float64)

            def compute(Xd):
                return stacking.predict_proba1_with_members(dparams, Xd)

        else:
            raise TypeError(
                f"cannot score params of type {type(params).__name__}; "
                "expected PipelineParams, TreeEnsembleParams, or "
                "StackingParams"
            )

        if mesh is not None:
            # Mesh-sharded predict tail (_stacked_proba1_bounded's sharded
            # branch): apply_rows_sharded owns placement and shard
            # padding; the fixed chunk shape still bounds compiles at one
            # program. Member outputs are not plumbed through the sharded
            # tail, so cohort member-disagreement is unavailable under a
            # mesh (quality handles members=None).
            ens_or_params = dparams.ensemble if is_pipeline else dparams
            proba1 = (
                tree.predict_proba1
                if isinstance(params, tree.TreeEnsembleParams)
                else stacking.predict_proba1
            )

            def compute(Xd):  # noqa: F811 — mesh override of the eager path
                from machine_learning_replications_tpu.parallel.rowwise import (
                    apply_rows_sharded,
                )

                return apply_rows_sharded(
                    mesh, proba1, ens_or_params, Xd,
                    chunk_rows=self.chunk_rows,
                ), None

        self._prep = prep
        self._compute = compute
        self._device_put = (
            (lambda x: x) if mesh is not None else jaxmon.device_put
        )

    def prep(self, X: np.ndarray) -> np.ndarray:
        """Host/impute-route work for one chunk's raw rows — safe from
        parse-worker threads (JAX dispatch is thread-safe; the imputer's
        block fns are lru-resolved per NaN pattern)."""
        return self._prep(X)

    def submit(self, X_prepped: np.ndarray) -> _Pending:
        """Pad to the run's one static shape, place on device, dispatch
        the stacked compute; returns without blocking on the result."""
        from machine_learning_replications_tpu.data.sharding import (
            pad_rows_to,
        )

        n = int(X_prepped.shape[0])
        if n == 0:
            return _Pending(None, None, X_prepped, 0)
        Xp, _ = pad_rows_to(
            np.asarray(X_prepped, np.float64), self.chunk_rows, mode="edge"
        )
        p1, members = self._compute(self._device_put(Xp))
        return _Pending(p1, members, X_prepped, n)

    def finish(self, pending: _Pending):
        """Block on a submitted chunk; returns ``(p1[n], members[n, M] |
        None, X_prepped[n])`` with pad rows sliced off before anything
        downstream can see them."""
        if pending.n == 0:
            return np.empty(0, np.float64), None, pending.X
        p1 = np.asarray(pending.p1, np.float64)[: pending.n]
        members = (
            None if pending.members is None
            else np.asarray(pending.members, np.float64)[: pending.n]
        )
        return p1, members, pending.X


class _PipeControl:
    """The stop/error/bounded-queue protocol BOTH overlapped modes run on
    (one definition so a fix to the shutdown semantics cannot silently
    diverge the two): any stage failure stops every stage; queue puts and
    gets poll with a short timeout so no thread can block forever on a
    dead peer; ``run`` starts, joins, and re-raises the first failure."""

    STOPPED = object()  # returned by get() when the pipeline is stopping

    def __init__(self) -> None:
        self.stop = threading.Event()
        self._lock = threading.Lock()
        self._errors: list[BaseException] = []

    def fail(self, exc: BaseException) -> None:
        with self._lock:
            self._errors.append(exc)
        self.stop.set()

    def put(self, q: queue.Queue, item) -> bool:
        """Bounded put honoring stop; False means the caller should exit."""
        while not self.stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def get(self, q: queue.Queue):
        """Bounded get honoring stop; ``STOPPED`` means exit."""
        while not self.stop.is_set():
            try:
                return q.get(timeout=0.1)
            except queue.Empty:
                continue
        return _PipeControl.STOPPED

    def run(self, threads: list[threading.Thread]) -> None:
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        with self._lock:
            if self._errors:
                raise self._errors[0]


class _OrderedBuffer:
    """Reorder point between out-of-order parse workers and the strictly
    ordered device stage. Capacity is bounded transitively (the raw-block
    queue upstream is bounded), so this holds at most
    ``prefetch + parse_workers`` chunks."""

    def __init__(self, next_seq: int, n_producers: int,
                 stop: threading.Event) -> None:
        self._cond = threading.Condition()
        self._items: dict[int, Any] = {}
        self._next = next_seq
        self._eof = 0
        self._n_producers = n_producers
        self._stop = stop

    def put(self, seq: int, item) -> None:
        with self._cond:
            self._items[seq] = item
            self._cond.notify_all()

    def producer_done(self) -> None:
        with self._cond:
            self._eof += 1
            self._cond.notify_all()

    def get(self):
        """Next chunk in sequence order; None at end-of-stream or stop."""
        with self._cond:
            while True:
                if self._stop.is_set():
                    return None
                if self._next in self._items:
                    item = self._items.pop(self._next)
                    self._next += 1
                    _M_QDEPTH.set(float(len(self._items)), stage="device")
                    return item
                if self._eof >= self._n_producers and not self._items:
                    return None
                self._cond.wait(timeout=0.1)


class ScorePipeline:
    """One bulk-scoring run over a cohort source into an output directory.

    ``run()`` returns the machine-readable summary (also written to
    ``<out>/summary.json``): rows, chunks, per-stage seconds, end-to-end
    rows/s, resume provenance, the rolling output sha256, and the cohort
    quality snapshot digest. Raises ``ScoreBudgetExceeded`` /
    ``ScoreResumeError`` / ``ScoreInterrupted``; an interrupted run leaves
    a resumable output directory behind.
    """

    def __init__(
        self,
        params,
        source,
        out_dir: str,
        *,
        overlap: bool = True,
        parse_workers: int = DEFAULT_PARSE_WORKERS,
        parse_procs: int = 0,
        prefetch: int = DEFAULT_PREFETCH,
        rows_per_shard: int = DEFAULT_ROWS_PER_SHARD,
        max_bad_rows: int = DEFAULT_MAX_BAD_ROWS,
        mesh=None,
        fresh: bool = False,
        durable: bool = True,
        quality: bool = True,
        quality_window: int = DEFAULT_QUALITY_WINDOW,
        drift_warn_psi: float | None = None,
        drift_alert_psi: float | None = None,
        model_digest: str = "",
        _interrupt_after_chunks: int | None = None,
    ) -> None:
        if parse_workers < 1 or prefetch < 1:
            raise ValueError("parse_workers and prefetch must be >= 1")
        if max_bad_rows < 0:
            raise ValueError("max_bad_rows must be >= 0")
        self.params = params
        self.source = source
        self.out_dir = os.path.abspath(out_dir)
        self.overlap = overlap
        self.parse_workers = int(parse_workers)
        # Process-pool ingest parsing (JSONL sources only): spawned
        # workers do the GIL-bound JSON/validate work, so it stops
        # competing with the parent's XLA dispatch for the one
        # interpreter lock — on a 2-core CPU host this is the difference
        # between overlap hiding ~25% and ~40% of the sequential wall.
        self.parse_procs = int(parse_procs) if getattr(
            source, "supports_process_parse", False
        ) else 0
        self.prefetch = int(prefetch)
        self.rows_per_shard = int(rows_per_shard)
        self.max_bad_rows = int(max_bad_rows)
        self.mesh = mesh
        self.fresh = fresh
        self.durable = durable
        self.quality = quality
        self.quality_window = int(quality_window)
        self.drift_warn_psi = drift_warn_psi
        self.drift_alert_psi = drift_alert_psi
        self.model_digest = model_digest
        self._interrupt_after_chunks = _interrupt_after_chunks
        self._clock = _StageClock()
        self._bad_lock = threading.Lock()
        self._monitor = None

    # -- construction helpers ----------------------------------------------

    def _build_monitor(self):
        """Cohort-level quality: the model's own reference profile over a
        population-sized window, statistics computed once at the end
        (``snapshot()`` forces a refresh; the huge interval keeps per-chunk
        PSI math off the run)."""
        if not self.quality:
            return None
        prof = getattr(self.params, "quality", None)
        if prof is None:
            return None
        from machine_learning_replications_tpu.models.pipeline import (
            support_feature_names,
        )
        from machine_learning_replications_tpu.obs import quality as qmod

        kwargs: dict[str, Any] = {}
        if self.drift_warn_psi is not None:
            kwargs["warn_psi"] = self.drift_warn_psi
        if self.drift_alert_psi is not None:
            kwargs["alert_psi"] = self.drift_alert_psi
        return qmod.QualityMonitor(
            prof,
            window=self.quality_window,
            feature_names=support_feature_names(self.params),
            refresh_interval_s=3600.0,
            **kwargs,
        )

    # -- the run ------------------------------------------------------------

    def run(self) -> dict:
        t_run0 = time.perf_counter()
        fingerprint = make_fingerprint(
            self.source.path, self.source.kind, self.model_digest,
            self.source.chunk_rows, self.rows_per_shard, self.source.limit,
        )
        progress = ScoreProgress(self.out_dir, fingerprint)
        resumed = progress.load(fresh=self.fresh)
        writer = ShardedScoreWriter(
            self.out_dir, self.rows_per_shard, durable=self.durable
        )
        quarantine = QuarantineWriter(self.out_dir, durable=self.durable)
        from machine_learning_replications_tpu.obs import jaxmon

        # Compile/transfer accounting before the first device op (the
        # make_server discipline): the run summary states its XLA compile
        # count, the fixed-chunk-shape compile bound's witness.
        jaxmon.install()
        resumed_chunks = resumed_rows = 0
        if resumed:
            writer.restore(progress.shards)
            quarantine.restore(progress.quarantine_bytes)
            resumed_chunks, resumed_rows = progress.chunks, progress.rows
            journal.event(
                "score_resume", chunks=resumed_chunks, rows=resumed_rows,
                lines=progress.lines, bad_rows=progress.bad_rows,
            )
        scorer = ChunkScorer(
            self.params, self.source.chunk_rows, self.source.kind,
            mesh=self.mesh,
        )
        self._monitor = self._build_monitor()
        self._progress = progress
        self._writer = writer
        self._quarantine = quarantine
        self._scorer = scorer
        self._committed_this_run = 0
        self._bad_seen = progress.bad_rows  # committed prefix incl. resume
        try:
            if self.overlap and self.parse_procs > 0:
                self._run_overlapped_procs()
            elif self.overlap:
                self._run_overlapped()
            else:
                self._run_sequential()
        except ScoreBudgetExceeded as exc:
            # The triggering chunk never reaches a commit, but the abort
            # message points the operator at the sidecar — flush the rows
            # that blew the budget there (single-threaded here: every
            # pipeline thread has exited). They sit past the committed
            # quarantine_bytes, so a later resume truncates them cleanly.
            try:
                quarantine.append(exc.bad_rows)
                quarantine.sync()
            except OSError:
                pass  # best-effort: the abort itself must surface
            raise
        finally:
            writer.close()
            quarantine.close()
        wall = time.perf_counter() - t_run0
        rows_this_run = progress.rows - resumed_rows
        summary = {
            "kind": "score_run",
            "route": self.source.kind,
            "overlap": self.overlap,
            "chunk_rows": self.source.chunk_rows,
            "parse_workers": (
                self.parse_workers
                if self.overlap and not self.parse_procs else 0
            ),
            "parse_procs": self.parse_procs if self.overlap else 0,
            "prefetch": self.prefetch if self.overlap else 0,
            "mesh": self.mesh is not None,
            "resumed": resumed,
            "resumed_chunks": resumed_chunks,
            "resumed_rows": resumed_rows,
            "rows": progress.rows,
            "chunks": progress.chunks,
            "bad_rows": progress.bad_rows,
            "rows_this_run": rows_this_run,
            "wall_seconds": round(wall, 3),
            "rows_per_second": (
                round(rows_this_run / wall, 1) if wall > 0 else None
            ),
            "stage_seconds": self._clock.snapshot(),
            "shards": progress.shards,
            "output_sha256": progress.output_sha256(),
            "quality": self._quality_summary(),
            "jax_compiles": jaxmon.compile_count(),
            "jax_compile_seconds": round(jaxmon.compile_seconds(), 3),
        }
        jrn = journal.get_journal()
        summary["manifest"] = (
            jrn.manifest if jrn is not None
            else journal.run_manifest(command="score")
        )
        progress.finish({
            k: summary[k] for k in (
                "wall_seconds", "rows_per_second", "stage_seconds", "overlap",
            )
        })
        atomic_json_write(
            os.path.join(self.out_dir, "summary.json"), summary
        )
        journal.event(
            "score_done", rows=progress.rows, chunks=progress.chunks,
            bad_rows=progress.bad_rows, wall_seconds=summary["wall_seconds"],
            rows_per_second=summary["rows_per_second"],
            output_sha256=summary["output_sha256"],
        )
        return summary

    def _quality_summary(self) -> dict | None:
        if self._monitor is None:
            return None
        try:
            snap = self._monitor.snapshot(detail=True)
        except Exception as exc:  # telemetry must not fail the run
            return {"enabled": False, "reason": f"snapshot failed: {exc}"}
        atomic_json_write(os.path.join(self.out_dir, "quality.json"), snap)
        worst = (snap.get("features") or [{}])[0]
        return {
            "enabled": snap.get("enabled", True),
            "status": snap.get("status"),
            "rows": snap.get("rows_total"),
            "window_rows": snap.get("window_rows"),
            "score_psi": snap.get("score_psi"),
            "worst_feature": worst.get("name"),
            "worst_psi": worst.get("psi"),
            "snapshot": "quality.json",
        }

    # -- shared stage bodies -------------------------------------------------

    def _check_budget(self, chunk: ParsedChunk) -> None:
        """Enforce the malformed-row error budget at parse time (before
        hours of compute happen behind a rotting input), counting the
        committed prefix plus everything parsed this run — parse workers
        race, so the tally is locked."""
        if not chunk.bad:
            return
        with self._bad_lock:
            self._bad_seen += len(chunk.bad)
            total = self._bad_seen
        if total > self.max_bad_rows:
            first = chunk.bad[0]
            raise ScoreBudgetExceeded(
                f"malformed-row budget exhausted: {total} quarantined rows "
                f"exceed max_bad_rows={self.max_bad_rows} (latest: line "
                f"{first[0]}: {first[1]})",
                bad_rows=chunk.bad,
            )

    def _sanitize_chunk(self, chunk: ParsedChunk) -> ParsedChunk:
        """Route-level row validation the format parser cannot do: when
        the scorer requires finite rows (bare-ensemble contract route —
        see ``ChunkScorer.requires_finite_rows``), non-finite rows are
        quarantined with their line numbers instead of flowing through to
        NaN probabilities and invalid JSON shard lines."""
        if not self._scorer.requires_finite_rows or not chunk.n_rows:
            return chunk
        finite = np.isfinite(chunk.X).all(axis=1)
        if finite.all():
            return chunk
        for line in chunk.line_nos[~finite]:
            chunk.bad.append((
                int(line),
                "non-finite values: a bare-ensemble checkpoint scores "
                "contract rows verbatim (no imputer); NaN/Inf inputs "
                "need a full pipeline checkpoint",
                "",
            ))
        chunk.bad.sort(key=lambda entry: entry[0])  # keep input order
        chunk.X = chunk.X[finite]
        chunk.line_nos = chunk.line_nos[finite]
        return chunk

    def _parse_and_prep(self, block) -> tuple[ParsedChunk, np.ndarray]:
        chunk = self._sanitize_chunk(self.source.parse(block))
        self._check_budget(chunk)
        X = self._scorer.prep(chunk.X) if chunk.n_rows else chunk.X
        return chunk, X

    def _commit_chunk(self, chunk: ParsedChunk, p1, members, X, t0) -> None:
        """The writer-stage transaction: append output + quarantine, flush
        durable, advance the manifest, account, journal — then (and only
        then) feed the quality monitor and honor the interrupt hook."""
        self._writer.append_chunk(self._progress.rows, chunk.line_nos, p1)
        self._quarantine.append(chunk.bad)
        shards, data = self._writer.sync()
        qbytes = self._quarantine.sync()
        self._progress.absorb_output(data)
        self._progress.commit(
            rows=len(p1), lines=chunk.lines_consumed,
            bad_rows=len(chunk.bad), shards=shards, quarantine_bytes=qbytes,
        )
        _M_ROWS.get().inc(len(p1))
        if chunk.bad:
            _M_QUAR.get().inc(len(chunk.bad))
        _M_CHUNKS.get().inc(1)
        dt = time.perf_counter() - t0
        _M_CHUNK_S.get().observe(dt)
        journal.event(
            "score_chunk", seq=chunk.seq, rows=len(p1),
            bad=len(chunk.bad), seconds=round(dt, 4),
        )
        if self._monitor is not None and len(p1):
            try:
                self._monitor.observe_batch(X, p1, members)
            except Exception as exc:
                # The engine's quarantine contract: telemetry must never
                # take the workload down.
                msg = f"{type(exc).__name__}: {exc}"
                journal.event("quality_feed_disabled", error=msg)
                self._monitor.disable(f"feed quarantined: {msg}")
                self._monitor = None
        self._committed_this_run += 1
        if (
            self._interrupt_after_chunks is not None
            and self._committed_this_run >= self._interrupt_after_chunks
        ):
            raise ScoreInterrupted(
                f"after {self._committed_this_run} committed chunks"
            )

    # -- sequential mode -----------------------------------------------------

    def _run_sequential(self) -> None:
        blocks = self.source.blocks(
            skip_lines=self._progress.lines, start_seq=self._progress.chunks
        )
        while True:
            t0 = time.perf_counter()
            with self._clock.stage("read"):
                block = next(blocks, None)
            if block is None:
                return
            with self._clock.stage("parse", seq=block.seq):
                chunk, X = self._parse_and_prep(block)
            with self._clock.stage("device", seq=block.seq):
                p1, members, X = self._scorer.finish(self._scorer.submit(X))
            with self._clock.stage("write", seq=block.seq):
                self._commit_chunk(chunk, p1, members, X, t0)

    # -- overlapped modes: shared plumbing -----------------------------------

    def _finish_to_writer(self, ctl: "_PipeControl", write_q, pending) -> bool:
        """Block on an in-flight device chunk and hand it to the writer;
        False when the pipeline is stopping (the caller exits)."""
        chunk, handle, t0 = pending
        with self._clock.stage("device", seq=chunk.seq):
            out = self._scorer.finish(handle)
        if not ctl.put(write_q, (chunk, out, t0)):
            return False
        _M_QDEPTH.set(float(write_q.qsize()), stage="write")
        return True

    def _writer_thread(self, ctl: "_PipeControl", write_q) -> threading.Thread:
        """The one writer stage both overlapped modes share: drain results
        in order, commit each chunk durably."""

        def writer_loop() -> None:
            try:
                while True:
                    item = ctl.get(write_q)
                    if item is _PipeControl.STOPPED or item is None:
                        return
                    chunk, (p1, members, X), t0 = item
                    with self._clock.stage("write", seq=chunk.seq):
                        self._commit_chunk(chunk, p1, members, X, t0)
            except BaseException as exc:
                ctl.fail(exc)

        return threading.Thread(
            target=writer_loop, name="score-write", daemon=True
        )

    # -- overlapped mode, in-process parse threads ---------------------------

    def _run_overlapped(self) -> None:
        ctl = _PipeControl()
        raw_q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        ready = _OrderedBuffer(
            self._progress.chunks, self.parse_workers, ctl.stop
        )
        write_q: queue.Queue = queue.Queue(maxsize=self.prefetch)

        def reader() -> None:
            try:
                blocks = self.source.blocks(
                    skip_lines=self._progress.lines,
                    start_seq=self._progress.chunks,
                )
                while True:
                    with self._clock.stage("read"):
                        block = next(blocks, None)
                    if block is None:
                        break
                    block._t0 = time.perf_counter()
                    if not ctl.put(raw_q, block):
                        return
                    _M_QDEPTH.set(float(raw_q.qsize()), stage="parse")
                for _ in range(self.parse_workers):
                    if not ctl.put(raw_q, None):
                        return
            except BaseException as exc:
                ctl.fail(exc)

        def parser() -> None:
            try:
                while True:
                    block = ctl.get(raw_q)
                    if block is _PipeControl.STOPPED:
                        return
                    if block is None:
                        ready.producer_done()
                        return
                    with self._clock.stage("parse", seq=block.seq):
                        chunk, X = self._parse_and_prep(block)
                    ready.put(block.seq, (chunk, X, block._t0))
            except BaseException as exc:
                ctl.fail(exc)
                ready.producer_done()

        def device() -> None:
            pending: tuple | None = None
            try:
                while True:
                    item = ready.get()
                    if item is None:
                        break
                    chunk, X, t0 = item
                    # Double buffer: N+1's transfer + dispatch BEFORE
                    # blocking on N's result.
                    with self._clock.stage("device", seq=chunk.seq):
                        handle = self._scorer.submit(X)
                    if pending is not None and not self._finish_to_writer(
                        ctl, write_q, pending
                    ):
                        return
                    pending = (chunk, handle, t0)
                if pending is not None and not ctl.stop.is_set():
                    if not self._finish_to_writer(ctl, write_q, pending):
                        return
                ctl.put(write_q, None)
            except BaseException as exc:
                ctl.fail(exc)

        ctl.run([
            threading.Thread(target=reader, name="score-read", daemon=True),
            *[
                threading.Thread(
                    target=parser, name=f"score-parse-{i}", daemon=True
                )
                for i in range(self.parse_workers)
            ],
            threading.Thread(target=device, name="score-device", daemon=True),
            self._writer_thread(ctl, write_q),
        ])

    # -- overlapped mode, process-pool ingest --------------------------------

    def _run_overlapped_procs(self) -> None:
        """The GIL-free ingest variant (``parse_procs > 0``, JSONL
        sources): spawned worker processes run the JSON/validate stage
        (``reader.parse_patient_lines`` — pure stdlib+numpy, no JAX
        device contact), so the interpreter lock stops serializing ingest
        against the parent's XLA dispatch. The impute-route prep moves
        into the device thread (it IS device work), which still
        double-buffers submit-ahead-of-finish; reader-submission order
        makes the future stream inherently ordered, so no reorder buffer
        is needed. Worker spawn (not fork: the parent's JAX runtime must
        never be forked) costs a few seconds once per run — amortized at
        cohort scale, which is the only scale this mode targets."""
        import concurrent.futures as cf
        import multiprocessing as mp

        from machine_learning_replications_tpu.score.reader import (
            parse_patient_lines_timed,
        )

        ctl = _PipeControl()
        fut_q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        write_q: queue.Queue = queue.Queue(maxsize=self.prefetch)

        pool = cf.ProcessPoolExecutor(
            max_workers=self.parse_procs,
            mp_context=mp.get_context("spawn"),
        )

        def reader() -> None:
            try:
                blocks = self.source.blocks(
                    skip_lines=self._progress.lines,
                    start_seq=self._progress.chunks,
                )
                while True:
                    with self._clock.stage("read"):
                        block = next(blocks, None)
                    if block is None:
                        break
                    block._t0 = time.perf_counter()
                    fut = pool.submit(
                        parse_patient_lines_timed, block.lines,
                        block.start_line,
                    )
                    block._n_lines = len(block.lines)
                    block.lines = None  # the worker owns the payload now
                    if not ctl.put(fut_q, (block, fut)):
                        return
                    _M_QDEPTH.set(float(fut_q.qsize()), stage="parse")
                ctl.put(fut_q, None)
            except BaseException as exc:
                ctl.fail(exc)

        def device() -> None:
            pending: tuple | None = None
            try:
                while True:
                    item = ctl.get(fut_q)
                    if item is _PipeControl.STOPPED:
                        return
                    if item is None:
                        break
                    block, fut = item
                    X, line_nos, bad, parse_s = fut.result()
                    self._clock.add("parse", parse_s)
                    chunk = self._sanitize_chunk(ParsedChunk(
                        seq=block.seq, start_line=block.start_line, X=X,
                        line_nos=line_nos,
                        lines_consumed=block._n_lines, bad=bad,
                    ))
                    self._check_budget(chunk)
                    with self._clock.stage("device", seq=chunk.seq):
                        Xp = (
                            self._scorer.prep(chunk.X)
                            if chunk.n_rows else chunk.X
                        )
                        handle = self._scorer.submit(Xp)
                    if pending is not None and not self._finish_to_writer(
                        ctl, write_q, pending
                    ):
                        return
                    pending = (chunk, handle, block._t0)
                if pending is not None and not ctl.stop.is_set():
                    if not self._finish_to_writer(ctl, write_q, pending):
                        return
                ctl.put(write_q, None)
            except BaseException as exc:
                ctl.fail(exc)

        try:
            ctl.run([
                threading.Thread(
                    target=reader, name="score-read", daemon=True
                ),
                threading.Thread(
                    target=device, name="score-device", daemon=True
                ),
                self._writer_thread(ctl, write_q),
            ])
        finally:
            pool.shutdown(wait=False, cancel_futures=True)
