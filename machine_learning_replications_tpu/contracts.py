"""Thread-ownership annotations for the event-loop transport stack.

The transport's one-loop-thread-owns-every-socket architecture
(``serve/transport.py``, ``fleet/router.py``) rests on two contracts that
used to live only in docstrings:

  * ``@loop_only`` — the function runs ON the event-loop thread, and only
    there. It may touch selector state and connection objects without
    locks, and it must never block: no ``time.sleep``, no blocking
    connects, no ``http.client``, no un-timed ``Lock.acquire`` (one slow
    call stalls every connection the loop owns).
  * ``@cross_thread`` — the function is safe to call from ANY thread
    (it marshals onto the loop via the wake pipe / ``_post``). It must
    not call ``@loop_only`` functions directly.

The decorators are runtime no-ops — they tag the function and return it
unchanged. graftcheck's ``loop-discipline`` rule (docs/ANALYSIS.md)
enforces both contracts statically over the AST, so a blocking call
introduced into a loop-side method fails CI instead of collapsing p99s
in production.
"""

from __future__ import annotations


def loop_only(fn):
    """Mark ``fn`` as event-loop-thread-only (see module docstring)."""
    fn.__loop_only__ = True
    return fn


def cross_thread(fn):
    """Mark ``fn`` as safe from any thread (see module docstring)."""
    fn.__cross_thread__ = True
    return fn
