"""Replica lifecycle manager — spawn, drain-first retire, crash-replace.

The registry (``fleet.registry``) answers *who may receive traffic*; this
module answers *who exists at all*. It owns a set of local replica
processes (real ``cli serve`` subprocesses in production, injectable
launchers in tests) and drives each through one explicit arc:

  spawn      launch the process on an allocated port with ``--register``
             pointed at the router; the replica self-enrols and warms.
             The manager probes ``/readyz`` directly — rotation-in stays
             the router prober's decision, but the manager must know
             when a spawn *landed* (and when it never will).
  ready      first ready probe within ``ready_deadline_s``. A spawn that
             never becomes ready (crashed child, corrupt checkpoint,
             injected ``lifecycle.spawn`` fault) is killed, deregistered,
             and retried under capped exponential backoff — it fails
             closed: the unready replica never entered rotation, so the
             fleet it was meant to grow is merely not grown yet.
  drain      retirement is **drain-first** by contract: an admin *hold*
             through the router removes the replica from rotation while
             it keeps serving in-flight work, then the manager waits for
             its queue to empty (bounded by ``drain_settle_s``).
  term       graceful SIGTERM — the replica's own drain machinery
             (docs/RESILIENCE.md liveness/readiness split) finishes
             in-flight replies and deregisters itself.
  kill       only after ``term_deadline_s``: a replica that refuses to
             drain (wedged loop, injected ``lifecycle.drain`` fault) is
             SIGKILLed — bounded retirement, never a zombie holding a
             port.
  respawn    crash replacement: a managed process that exits (or a
             replica the registry reports persistently unresponsive
             while its process claims to live) is detected on the next
             tick, deregistered, and respawned on the same id/port with
             backoff — the idempotent re-registration path the kill
             drill already proved brings it back through probes.

Every transition is journaled (``lifecycle_*`` events) and counted
(``lifecycle_transitions_total{event=}``, ``lifecycle_replicas{state=}``)
so the surge drill can assert the whole arc from one journal.

The manager is tick-driven and never blocks: ``tick()`` advances every
replica's state machine by at most one step and returns — the autoscale
daemon calls it once per poll, and tests drive it directly with fake
clocks and launchers. jax-free like the rest of ``fleet/``.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

from machine_learning_replications_tpu.obs import journal
from machine_learning_replications_tpu.obs.registry import REGISTRY
from machine_learning_replications_tpu.fleet.health import probe_replica
from machine_learning_replications_tpu.resilience import faults

LIFECYCLE_TRANSITIONS = REGISTRY.counter(
    "lifecycle_transitions_total",
    "Replica lifecycle transitions by event (spawn, ready, spawn_failed, "
    "drain, term, kill, exit, crash).",
    labels=("event",),
)
LIFECYCLE_REPLICAS = REGISTRY.gauge(
    "lifecycle_replicas",
    "Managed replicas by lifecycle state (pending: awaiting a backoff "
    "respawn slot).",
    labels=("state",),
)
# Materialize the full label space at import (the registry convention:
# a zero is a fact, an absent series is a mystery).
for _event in ("spawn", "ready", "spawn_failed", "drain", "term", "kill",
               "exit", "crash"):
    LIFECYCLE_TRANSITIONS.labels(event=_event)

#: Lifecycle states (``ManagedReplica.state``).
PENDING, SPAWNING, READY, DRAINING, TERMINATING = (
    "pending", "spawning", "ready", "draining", "terminating",
)
_STATES = (PENDING, SPAWNING, READY, DRAINING, TERMINATING)
for _state in _STATES:
    LIFECYCLE_REPLICAS.labels(state=_state)


def free_port(host: str = "127.0.0.1") -> int:
    """An OS-allocated free TCP port. The momentary bind/release race is
    acceptable here: the replica binds it back within milliseconds, and a
    lost race surfaces as a failed spawn the backoff path already owns."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


def replica_queue_depth(url: str, timeout_s: float = 2.0) -> int | None:
    """The replica's ``/healthz`` queue depth — the drain-settle signal.
    None when unreachable (a dead replica has, by definition, drained)."""
    try:
        with urllib.request.urlopen(
            url.rstrip("/") + "/healthz", timeout=timeout_s
        ) as resp:
            body = json.loads(resp.read())
        depth = body.get("queue_depth")
        return int(depth) if isinstance(depth, (int, float)) else None
    except Exception:
        return None


class RouterClient:
    """The manager's (and autoscaler's) thin HTTP view of the router's
    control plane. Every call is best-effort and never raises — the
    control loop must keep ticking through a router blip, and each
    operation is retried implicitly by the next tick."""

    def __init__(self, router_url: str, timeout_s: float = 5.0) -> None:
        self.base = router_url.rstrip("/")
        self.timeout_s = float(timeout_s)

    def _post(self, body: dict) -> dict | None:
        try:
            req = urllib.request.Request(
                self.base + "/fleet/replicas",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return json.loads(r.read())
        except Exception:
            return None

    def snapshot(self) -> list[dict] | None:
        """The registry snapshot, or None when the router is unreachable
        (callers must distinguish 'empty fleet' from 'no answer')."""
        try:
            with urllib.request.urlopen(
                self.base + "/fleet/replicas", timeout=self.timeout_s
            ) as r:
                return json.loads(r.read())["replicas"]
        except Exception:
            return None

    def hold(self, replica_id: str) -> bool:
        out = self._post({"hold": replica_id})
        return bool(out and out.get("held"))

    def release(self, replica_id: str) -> bool:
        out = self._post({"release": replica_id})
        return bool(out and out.get("released"))

    def deregister(self, replica_id: str) -> bool:
        out = self._post({"deregister": replica_id})
        return bool(out and out.get("deregistered"))


class ReplicaSpec:
    """How to launch one replica: the checkpoint it serves, the serve
    flags it runs under, and where its journal goes. ``command`` builds
    the real ``cli serve`` invocation; tests inject a launcher instead of
    a different command."""

    def __init__(
        self,
        model: str,
        register_url: str,
        host: str = "127.0.0.1",
        serve_args: tuple[str, ...] | list[str] = (),
        journal_dir: str | None = None,
        python: str = sys.executable,
        no_aot: bool = False,
    ) -> None:
        self.model = model
        self.register_url = register_url.rstrip("/")
        self.host = host
        self.serve_args = tuple(serve_args)
        self.journal_dir = journal_dir
        self.python = python
        # Fleet-wide AOT escape hatch (docs/AOT.md): force every spawned
        # replica onto the tracing path — `cli fleet autoscale --no-aot`.
        # Scale-out reaction time then pays the full ladder compile
        # again, but a bad published executable bundle cannot touch the
        # fleet at all.
        self.no_aot = bool(no_aot)

    def command(self, replica_id: str, port: int,
                model: str | None = None) -> list[str]:
        cmd = [
            self.python, "-m", "machine_learning_replications_tpu",
            "serve", "--model", model or self.model,
            "--host", self.host, "--port", str(port),
            "--replica-id", replica_id,
            "--register", self.register_url,
            *(("--no-aot",) if self.no_aot else ()),
            *self.serve_args,
        ]
        if self.journal_dir:
            cmd += [
                "--journal",
                os.path.join(
                    self.journal_dir, f"replica_{replica_id}.jsonl"
                ),
            ]
        return cmd


def _default_launcher(cmd: list[str]):
    return subprocess.Popen(
        cmd, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


class ManagedReplica:
    """One lifecycle slot. The id and port are stable across respawns —
    the registry's idempotent re-registration (same id, same url) is what
    lets a replacement probe straight back into rotation."""

    __slots__ = (
        "id", "seq", "port", "url", "proc", "state", "spawned_at",
        "ready_at", "ready_deadline", "drain_deadline", "term_deadline",
        "attempts", "next_spawn_at", "respawn", "skip_term",
        "retire_reason", "host",
    )

    def __init__(self, replica_id: str, port: int, host: str,
                 seq: int = 0) -> None:
        self.id = replica_id
        self.seq = seq
        self.host = host
        self.port = port
        self.url = f"http://{host}:{port}"
        self.proc = None
        self.state = PENDING
        self.spawned_at = 0.0
        self.ready_at: float | None = None
        self.ready_deadline = 0.0
        self.drain_deadline = 0.0
        self.term_deadline = 0.0
        self.attempts = 0          # consecutive failed spawn/crash cycles
        self.next_spawn_at = 0.0   # backoff gate for the next attempt
        self.respawn = False
        self.skip_term = False     # lifecycle.drain corrupt: TERM suppressed
        self.retire_reason = ""

    def as_dict(self) -> dict:
        return {
            "id": self.id,
            "port": self.port,
            "url": self.url,
            "state": self.state,
            "pid": self.proc.pid if self.proc is not None else None,
            "attempts": self.attempts,
            "respawn": self.respawn,
        }


class LifecycleManager:
    """The spawn/retire/replace state machine over a set of
    ``ManagedReplica`` slots (see module docstring).

    ``scale_to(n)`` sets the desired non-draining replica count (clamped
    to ``[min_replicas, max_replicas]``); ``tick()`` reconciles toward it
    one bounded step per call. Retirement is newest-first (the surge
    capacity leaves first; the steady-state fleet keeps its warm
    veterans).
    """

    def __init__(
        self,
        spec: ReplicaSpec,
        router: RouterClient,
        min_replicas: int = 1,
        max_replicas: int = 4,
        ready_deadline_s: float = 300.0,
        drain_settle_s: float = 10.0,
        term_deadline_s: float = 30.0,
        respawn_backoff_s: float = 1.0,
        respawn_backoff_max_s: float = 30.0,
        unresponsive_probe_fails: int = 8,
        launcher=_default_launcher,
        clock=time.monotonic,
        say=None,
    ) -> None:
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas}..{max_replicas}"
            )
        self.spec = spec
        self.router = router
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.ready_deadline_s = float(ready_deadline_s)
        self.drain_settle_s = float(drain_settle_s)
        self.term_deadline_s = float(term_deadline_s)
        self.respawn_backoff_s = float(respawn_backoff_s)
        self.respawn_backoff_max_s = float(respawn_backoff_max_s)
        self.unresponsive_probe_fails = int(unresponsive_probe_fails)
        self._launcher = launcher
        self._clock = clock
        self._say = say
        self._seq = 0
        self.desired = 0
        self._replicas: dict[str, ManagedReplica] = {}

    # -- public surface -------------------------------------------------------

    def say(self, msg: str) -> None:
        if self._say is not None:
            self._say(msg)

    def scale_to(self, n: int) -> int:
        """Set the desired replica count (clamped to bounds); the tick
        loop converges toward it. Returns the clamped target."""
        self.desired = max(self.min_replicas, min(self.max_replicas, int(n)))
        return self.desired

    def counts(self) -> dict:
        out = {state: 0 for state in _STATES}
        for rep in self._replicas.values():
            out[rep.state] += 1
        out["desired"] = self.desired
        # "active" slots are the ones counted against the target: every
        # slot not already on its way out.
        out["active"] = sum(
            out[s] for s in (PENDING, SPAWNING, READY)
        )
        return out

    def replicas(self) -> list[dict]:
        return [r.as_dict() for _, r in sorted(self._replicas.items())]

    def get(self, replica_id: str) -> ManagedReplica | None:
        return self._replicas.get(replica_id)

    def tick(self) -> None:
        """One reconciliation pass: advance every slot's arc, detect
        crashes, then spawn/retire toward ``desired``."""
        now = self._clock()
        snapshot = self.router.snapshot()
        by_id = {
            r["id"]: r for r in snapshot or []
        } if snapshot is not None else None
        for rep in list(self._replicas.values()):
            self._advance(rep, now, by_id)
        counts = self.counts()
        if counts["active"] < self.desired:
            for _ in range(self.desired - counts["active"]):
                self._new_slot(now)
        elif counts["active"] > self.desired:
            # Retire newest READY slots first; a slot still spawning is
            # cheaper to abandon than a warm veteran, but abandoning a
            # half-warm process is still a retire arc (drain is a no-op
            # on a replica that never took traffic).
            excess = counts["active"] - self.desired
            # Numeric creation order, not id strings: "as-10" must sort
            # after "as-9", or a long-lived daemon retires the veteran.
            candidates = sorted(
                (r for r in self._replicas.values()
                 if r.state in (READY, SPAWNING, PENDING)),
                key=lambda r: r.seq, reverse=True,
            )
            for rep in candidates[:excess]:
                self._retire(rep, now, reason="scale_in")
        # Backoff-gated (re)spawns.
        for rep in self._replicas.values():
            if rep.state == PENDING and now >= rep.next_spawn_at:
                self._spawn(rep, now)
        self._refresh_gauge()

    def close(self, kill: bool = True) -> None:
        """Drill/daemon teardown: stop every managed child. ``kill=False``
        sends SIGTERM only (graceful, may outlive the manager)."""
        for rep in self._replicas.values():
            if rep.proc is not None and rep.proc.poll() is None:
                try:
                    rep.proc.terminate()
                except OSError:
                    pass
        if kill:
            deadline = time.monotonic() + self.term_deadline_s
            for rep in self._replicas.values():
                if rep.proc is None:
                    continue
                while rep.proc.poll() is None and \
                        time.monotonic() < deadline:
                    time.sleep(0.05)
                if rep.proc.poll() is None:
                    try:
                        rep.proc.kill()
                    except OSError:
                        pass
            for rep in self._replicas.values():
                self.router.deregister(rep.id)

    # -- per-slot state machine ----------------------------------------------

    def _advance(self, rep: ManagedReplica, now: float,
                 by_id: dict | None) -> None:
        exited = (
            rep.proc is not None and rep.proc.poll() is not None
        )
        if rep.state in (SPAWNING, READY) and exited:
            self._on_crash(rep, now, f"process exited {rep.proc.poll()}")
            return
        if rep.state == READY and by_id is not None:
            # Registry-observed zombie: the process claims to live but
            # stopped answering probes (wedged interpreter, blackholed
            # socket). The registry already rotated it out; the manager
            # replaces it.
            reg = by_id.get(rep.id)
            if reg is not None and reg.get("state") == "out" and \
                    reg.get("probe_fails", 0) >= self.unresponsive_probe_fails:
                self._kill_proc(rep, reason="unresponsive")
                self._on_crash(
                    rep, now,
                    f"unresponsive ({reg['probe_fails']} failed probes "
                    "with a live process)",
                )
                return
        if rep.state == SPAWNING:
            verdict = probe_replica(rep.url)
            if verdict["ok"] and verdict["ready"]:
                rep.state = READY
                rep.ready_at = now
                rep.attempts = 0
                seconds = round(now - rep.spawned_at, 3)
                LIFECYCLE_TRANSITIONS.inc(event="ready")
                journal.event(
                    "lifecycle_ready", replica=rep.id, url=rep.url,
                    seconds=seconds, respawn=rep.respawn,
                )
                self.say(f"replica {rep.id} ready in {seconds}s")
            elif now >= rep.ready_deadline:
                # The fail-closed branch: an unready spawn never entered
                # rotation (rotation-in is probe-gated), so the only
                # cleanup is the process itself.
                self._kill_proc(rep, reason="ready_timeout")
                self._spawn_failed(
                    rep, now,
                    f"not ready within {self.ready_deadline_s:g}s",
                )
        elif rep.state == DRAINING:
            depth = replica_queue_depth(rep.url)
            if depth in (None, 0) or now >= rep.drain_deadline:
                self._term(rep, now, drained=depth in (None, 0))
        elif rep.state == TERMINATING:
            if exited or rep.proc is None:
                code = rep.proc.poll() if rep.proc is not None else None
                LIFECYCLE_TRANSITIONS.inc(event="exit")
                journal.event(
                    "lifecycle_exit", replica=rep.id, code=code,
                    reason=rep.retire_reason,
                )
                self.router.deregister(rep.id)
                del self._replicas[rep.id]
                self.say(f"replica {rep.id} retired (exit {code})")
            elif now >= rep.term_deadline:
                self._kill_proc(rep, reason="term_deadline")

    def _on_crash(self, rep: ManagedReplica, now: float,
                  detail: str) -> None:
        LIFECYCLE_TRANSITIONS.inc(event="crash")
        journal.event(
            "lifecycle_crash", replica=rep.id, state=rep.state,
            detail=detail,
        )
        self.say(f"replica {rep.id} crashed ({detail})")
        self.router.deregister(rep.id)
        if rep.state == SPAWNING:
            self._spawn_failed(rep, now, f"crashed while warming: {detail}")
            return
        rep.attempts += 1
        rep.respawn = True
        rep.state = PENDING
        rep.proc = None
        self._maybe_move_port(rep)
        rep.next_spawn_at = now + self._backoff(rep.attempts)

    def _spawn_failed(self, rep: ManagedReplica, now: float,
                      reason: str) -> None:
        rep.attempts += 1
        backoff = self._backoff(rep.attempts)
        LIFECYCLE_TRANSITIONS.inc(event="spawn_failed")
        journal.event(
            "lifecycle_spawn_failed", replica=rep.id, reason=reason,
            attempts=rep.attempts, retry_in_s=round(backoff, 3),
        )
        self.say(
            f"replica {rep.id} spawn failed ({reason}); retry in "
            f"{backoff:.1f}s"
        )
        self.router.deregister(rep.id)
        rep.state = PENDING
        rep.proc = None
        self._maybe_move_port(rep)
        rep.next_spawn_at = now + backoff

    def _maybe_move_port(self, rep: ManagedReplica) -> None:
        """Same-id/same-port respawn is the contract for the common
        crash (the idempotent re-registration path) — but a port stolen
        during the backoff window would otherwise EADDRINUSE every
        retry forever. After 3 consecutive failures, move the slot to a
        fresh port; same-id-new-url re-registration is already a
        journaled, supported registry transition."""
        if rep.attempts >= 3:
            rep.port = free_port(rep.host)
            rep.url = f"http://{rep.host}:{rep.port}"

    def _backoff(self, attempts: int) -> float:
        # Clamped exponent (the supervisor's overflow lesson): attempts
        # can grow unboundedly across a long outage.
        return min(
            self.respawn_backoff_max_s,
            self.respawn_backoff_s * (2.0 ** min(attempts - 1, 16)),
        )

    def _new_slot(self, now: float) -> None:
        self._seq += 1
        rep = ManagedReplica(f"as-{self._seq}", free_port(self.spec.host),
                             self.spec.host, seq=self._seq)
        self._replicas[rep.id] = rep
        self._spawn(rep, now)

    def _spawn(self, rep: ManagedReplica, now: float) -> None:
        model = self.spec.model
        try:
            if faults.fire("lifecycle.spawn"):
                # corrupt mode: launch a replica that can never become
                # ready (nonexistent checkpoint — the child dies or never
                # warms; either way the ready-deadline branch owns it).
                model = self.spec.model + ".__corrupt__"
        except faults.InjectedFault as exc:
            self._spawn_failed(rep, now, f"injected: {exc}")
            return
        cmd = self.spec.command(rep.id, rep.port, model=model)
        try:
            rep.proc = self._launcher(cmd)
        except OSError as exc:
            self._spawn_failed(rep, now, f"launch error: {exc}")
            return
        rep.state = SPAWNING
        rep.spawned_at = now
        rep.ready_deadline = now + self.ready_deadline_s
        rep.skip_term = False
        LIFECYCLE_TRANSITIONS.inc(event="spawn")
        journal.event(
            "lifecycle_spawn", replica=rep.id, port=rep.port,
            pid=rep.proc.pid if rep.proc is not None else None,
            attempt=rep.attempts + 1, respawn=rep.respawn,
        )
        self.say(
            f"replica {rep.id} spawning on port {rep.port}"
            + (" (respawn)" if rep.respawn else "")
        )

    def _retire(self, rep: ManagedReplica, now: float,
                reason: str) -> None:
        skip_term = False
        try:
            # corrupt = simulate a replica that ignores its SIGTERM: the
            # graceful signal is suppressed so the kill-deadline
            # escalation below is forced to carry the retirement.
            skip_term = faults.fire("lifecycle.drain")
        except faults.InjectedFault as exc:
            # Fail closed: the replica stays in rotation and serving;
            # the retirement is simply not started this tick (re-decided
            # on the next one).
            journal.event(
                "lifecycle_drain_error", replica=rep.id,
                error=f"injected: {exc}",
            )
            return
        if rep.state == PENDING:
            # Never launched (still in a backoff window): nothing to
            # drain, nothing to kill — drop the slot.
            journal.event(
                "lifecycle_exit", replica=rep.id, code=None, reason=reason,
            )
            LIFECYCLE_TRANSITIONS.inc(event="exit")
            del self._replicas[rep.id]
            return
        self.router.hold(rep.id)  # out of rotation, still serving
        rep.state = DRAINING
        rep.retire_reason = reason
        rep.skip_term = skip_term
        rep.drain_deadline = now + self.drain_settle_s
        LIFECYCLE_TRANSITIONS.inc(event="drain")
        journal.event(
            "lifecycle_drain", replica=rep.id, reason=reason,
            settle_deadline_s=self.drain_settle_s,
        )
        self.say(f"replica {rep.id} draining ({reason})")

    def _term(self, rep: ManagedReplica, now: float,
              drained: bool) -> None:
        delivered = False
        if not rep.skip_term and rep.proc is not None and \
                rep.proc.poll() is None:
            try:
                rep.proc.terminate()
                delivered = True
            except OSError:
                pass
        rep.state = TERMINATING
        rep.term_deadline = now + self.term_deadline_s
        LIFECYCLE_TRANSITIONS.inc(event="term")
        journal.event(
            "lifecycle_term", replica=rep.id, drained=drained,
            delivered=delivered,
            kill_deadline_s=self.term_deadline_s,
        )

    def _kill_proc(self, rep: ManagedReplica, reason: str) -> None:
        if rep.proc is not None and rep.proc.poll() is None:
            try:
                rep.proc.kill()
            except OSError:
                pass
            LIFECYCLE_TRANSITIONS.inc(event="kill")
            journal.event("lifecycle_kill", replica=rep.id, reason=reason)
            self.say(f"replica {rep.id} SIGKILLed ({reason})")

    def _refresh_gauge(self) -> None:
        counts = {state: 0 for state in _STATES}
        for rep in self._replicas.values():
            counts[rep.state] += 1
        for state, n in counts.items():
            LIFECYCLE_REPLICAS.set(float(n), state=state)


def kill_replica(rep: ManagedReplica) -> None:
    """Drill helper: SIGKILL a managed replica's process directly (the
    chaos scenario's murder weapon — the manager must *detect* this, so
    it goes around the manager on purpose)."""
    if rep.proc is not None and rep.proc.poll() is None:
        os.kill(rep.proc.pid, signal.SIGKILL)
