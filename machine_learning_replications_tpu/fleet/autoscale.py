"""Load-driven autoscaler: the fleet's size becomes a control loop.

The stack already *emits* every signal an autoscaler needs and acts on
none of them: the router counts sheds and latency per request
(``fleet_requests_total`` / ``fleet_request_latency_seconds``), every
replica's ``/healthz`` carries its admission-queue depth, and the SLO
trackers (``obs.slo``) export burn-rate gauges. This module closes the
loop with the same shape as the continual-learning trigger
(``learn.trigger``): a jax-free poller feeding a pure, debounced policy
that drives the lifecycle manager (``fleet.lifecycle``).

Signals, per poll (all best-effort; an unreachable surface is a
``None`` that simply doesn't vote):

  ``queue_depth``   max replica admission-queue depth (``/healthz``)
  ``latency_ms``    router-side mean /predict latency over the polls
                    since the last tick (histogram sum/count deltas)
  ``shed_rate``     shed fraction of routed requests since the last
                    tick (``fleet_requests_total`` outcome deltas;
                    ``no_replica`` counts as shed — an empty rotation
                    is the worst overload there is)
  ``burn_rate``     max SLO burn rate across replicas (``slo_burn_rate``
                    from each replica's ``/metrics?format=json``)
  ``alerts_active`` count of alert rules currently *firing* on the
                    router's own alert engine (``/fleet/alerts``;
                    docs/OBSERVABILITY.md "Alerting & incidents").
                    Disabled by default (``None`` thresholds) — wire
                    ``out_alerts_active=1`` to make any page-severity
                    firing alert a scale-out vote; either way the
                    reading is journaled with every decision

Policy (``AutoscalePolicy``), tuned against the failure modes a naive
"scale on threshold" loop has:

  * **Debounce** — ``breach_polls`` consecutive polls with ANY scale-out
    signal over its threshold before a scale-out fires; ``idle_polls``
    consecutive polls with EVERY signal under its scale-in threshold
    before a scale-in fires. One hot poll is a batch flush; one quiet
    poll is a gap between bursts.
  * **Cooldown** — ``cooldown_s`` after *any* action, both directions.
    A spawned replica takes tens of seconds to warm; re-deciding before
    the last decision landed would thrash the fleet against its own
    startup transient. Flapping load therefore costs at most one
    spawn/retire per cooldown window.
  * **Bounds** — ``min_replicas``/``max_replicas`` (owned by the
    lifecycle manager, mirrored here for suppression journaling): the
    loop can neither scale the service to zero nor fork-bomb the host.

Every decision that could act journals an ``autoscale_decision`` —
fired or suppressed, with the readings that drove it — and the raw
readings ride ``autoscale_signal{signal=}`` gauges continuously, so the
journal answers "why did/didn't the fleet grow at t?" and the metrics
page shows what the controller saw (docs/FLEET.md "Elastic fleet").
"""

from __future__ import annotations

import json
import math
import time
import urllib.request

from machine_learning_replications_tpu.obs import journal
from machine_learning_replications_tpu.obs.registry import REGISTRY

AUTOSCALE_DECISIONS = REGISTRY.counter(
    "autoscale_decisions_total",
    "Autoscaler decisions by outcome (scale_out / scale_in fired; "
    "suppressed_cooldown / suppressed_at_max / suppressed_at_min: a "
    "debounced breach or idle streak that did not act).",
    labels=("decision",),
)
for _d in ("scale_out", "scale_in", "suppressed_cooldown",
           "suppressed_at_max", "suppressed_at_min"):
    AUTOSCALE_DECISIONS.labels(decision=_d)
AUTOSCALE_SIGNAL = REGISTRY.gauge(
    "autoscale_signal",
    "The load readings the autoscaler last observed (NaN = surface "
    "unreachable this poll).",
    labels=("signal",),
)
AUTOSCALE_STREAK = REGISTRY.gauge(
    "autoscale_streak",
    "Consecutive breach/idle polls toward the debounce thresholds.",
    labels=("kind",),
)
AUTOSCALE_DESIRED = REGISTRY.gauge(
    "autoscale_desired_replicas",
    "The autoscaler's current desired replica count.",
)
for _k in ("breach", "idle"):
    AUTOSCALE_STREAK.set(0.0, kind=_k)

SIGNALS = (
    "queue_depth", "latency_ms", "shed_rate", "burn_rate",
    "alerts_active",
)


class AutoscaleThresholds:
    """Scale-out fires when ANY ``out_*`` signal is breached (sustained);
    scale-in only when EVERY available signal sits at or under its
    ``in_*`` twin — growing the fleet is cheap insurance, shrinking it
    must be provably safe. A ``None`` threshold disables that signal."""

    def __init__(
        self,
        out_queue_depth: float | None = 8.0,
        out_latency_ms: float | None = 250.0,
        out_shed_rate: float | None = 0.02,
        out_burn_rate: float | None = 4.0,
        out_alerts_active: float | None = None,
        in_queue_depth: float | None = 1.0,
        in_latency_ms: float | None = 50.0,
        in_shed_rate: float | None = 0.0,
        in_burn_rate: float | None = 1.0,
        in_alerts_active: float | None = None,
    ) -> None:
        self.out = {
            "queue_depth": out_queue_depth,
            "latency_ms": out_latency_ms,
            "shed_rate": out_shed_rate,
            "burn_rate": out_burn_rate,
            # Off by default: the alert plane is an operator surface
            # first; opting it into the control loop is a deliberate
            # coupling (a paging alert then both wakes a human AND adds
            # capacity).
            "alerts_active": out_alerts_active,
        }
        self.scale_in = {
            "queue_depth": in_queue_depth,
            "latency_ms": in_latency_ms,
            "shed_rate": in_shed_rate,
            "burn_rate": in_burn_rate,
            "alerts_active": in_alerts_active,
        }
        for name in SIGNALS:
            hi, lo = self.out[name], self.scale_in[name]
            if hi is not None and lo is not None and lo > hi:
                raise ValueError(
                    f"in_{name} ({lo}) must not exceed out_{name} ({hi})"
                )

    def describe(self) -> dict:
        return {"out": dict(self.out), "in": dict(self.scale_in)}


class AutoscalePolicy:
    """The debounce/cooldown/bounds state machine (see module
    docstring). Pure of I/O: feed it one ``observe(signals, ...)`` per
    poll; it returns an action dict (``{"decision", "target", ...}``)
    when the fleet should change size, else ``None``."""

    def __init__(
        self,
        thresholds: AutoscaleThresholds | None = None,
        min_replicas: int = 1,
        max_replicas: int = 4,
        breach_polls: int = 3,
        idle_polls: int = 10,
        cooldown_s: float = 30.0,
        step: int = 1,
        clock=time.monotonic,
    ) -> None:
        if breach_polls < 1 or idle_polls < 1:
            raise ValueError("breach_polls and idle_polls must be >= 1")
        if cooldown_s < 0:
            raise ValueError("cooldown_s must be >= 0")
        if step < 1:
            raise ValueError("step must be >= 1")
        if not 1 <= min_replicas <= max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"{min_replicas}..{max_replicas}"
            )
        self.thresholds = thresholds or AutoscaleThresholds()
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.breach_polls = int(breach_polls)
        self.idle_polls = int(idle_polls)
        self.cooldown_s = float(cooldown_s)
        self.step = int(step)
        self._clock = clock
        self._breach = 0
        self._idle = 0
        self._last_action_t: float | None = None

    # -- policy ---------------------------------------------------------------

    def cooldown_remaining_s(self) -> float:
        if self._last_action_t is None:
            return 0.0
        return max(
            0.0, self.cooldown_s - (self._clock() - self._last_action_t)
        )

    def observe(self, signals: dict, desired: int, ready: int) -> dict | None:
        """One poll: ``signals`` maps each of ``SIGNALS`` to a float or
        None (surface unreachable). ``desired`` is the lifecycle
        manager's current target, ``ready`` the in-rotation count (both
        journaled with the decision)."""
        now = self._clock()
        for name in SIGNALS:
            v = signals.get(name)
            AUTOSCALE_SIGNAL.set(
                float(v) if v is not None else math.nan, signal=name
            )
        breaches = [
            name for name in SIGNALS
            if self.thresholds.out[name] is not None
            and signals.get(name) is not None
            and signals[name] >= self.thresholds.out[name]
        ]
        readings = {
            name: signals.get(name) for name in SIGNALS
        }
        available = [
            name for name in SIGNALS
            if self.thresholds.scale_in[name] is not None
            and signals.get(name) is not None
        ]
        idle = bool(available) and not breaches and all(
            signals[name] <= self.thresholds.scale_in[name]
            for name in available
        )
        if breaches:
            self._breach += 1
            self._idle = 0
        elif idle:
            self._idle += 1
            self._breach = 0
        else:
            # The in-between zone (or a blind poll): neither streak may
            # ride through it — debounce means *consecutive* evidence.
            self._breach = 0
            self._idle = 0
        AUTOSCALE_STREAK.set(float(self._breach), kind="breach")
        AUTOSCALE_STREAK.set(float(self._idle), kind="idle")

        if breaches and self._breach >= self.breach_polls:
            return self._decide(
                now, "scale_out", desired, ready, readings,
                reason="breach: " + ",".join(breaches),
                at_bound=desired >= self.max_replicas,
                bound_name="suppressed_at_max",
                target=min(self.max_replicas, desired + self.step),
                first_crossing=self._breach == self.breach_polls,
            )
        if idle and self._idle >= self.idle_polls:
            return self._decide(
                now, "scale_in", desired, ready, readings,
                reason="idle: all signals under scale-in thresholds",
                at_bound=desired <= self.min_replicas,
                bound_name="suppressed_at_min",
                target=max(self.min_replicas, desired - self.step),
                first_crossing=self._idle == self.idle_polls,
            )
        return None

    # -- internals ------------------------------------------------------------

    def _decide(
        self, now: float, decision: str, desired: int, ready: int,
        readings: dict, reason: str, at_bound: bool, bound_name: str,
        target: int, first_crossing: bool,
    ) -> dict | None:
        if at_bound:
            # A lasting breach at max (or the quiet steady state at min)
            # would otherwise journal once per poll forever: journal at
            # the debounce crossing only, count always.
            AUTOSCALE_DECISIONS.inc(decision=bound_name)
            if first_crossing:
                self._journal(
                    decision=None, suppressed_by=bound_name,
                    reason=reason, desired=desired, ready=ready,
                    target=None, readings=readings,
                )
            return None
        if self.cooldown_remaining_s() > 0:
            AUTOSCALE_DECISIONS.inc(decision="suppressed_cooldown")
            if first_crossing:
                self._journal(
                    decision=None, suppressed_by="cooldown",
                    reason=reason, desired=desired, ready=ready,
                    target=None, readings=readings,
                )
            return None
        self._last_action_t = now
        self._breach = 0
        self._idle = 0
        AUTOSCALE_STREAK.set(0.0, kind="breach")
        AUTOSCALE_STREAK.set(0.0, kind="idle")
        AUTOSCALE_DECISIONS.inc(decision=decision)
        self._journal(
            decision=decision, suppressed_by=None, reason=reason,
            desired=desired, ready=ready, target=target,
            readings=readings,
        )
        return {
            "decision": decision, "target": target, "reason": reason,
            "signals": readings,
        }

    def _journal(self, decision, suppressed_by, reason, desired, ready,
                 target, readings) -> None:
        journal.event(
            "autoscale_decision",
            decision=decision,
            suppressed_by=suppressed_by,
            reason=reason,
            desired=desired,
            ready=ready,
            target=target,
            breach_streak=self._breach,
            idle_streak=self._idle,
            breach_polls_needed=self.breach_polls,
            idle_polls_needed=self.idle_polls,
            cooldown_remaining_s=round(self.cooldown_remaining_s(), 3),
            signals={
                k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in readings.items()
            },
        )


def _fetch_json(url: str, timeout_s: float):
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return json.loads(resp.read())


class AutoscaleDaemon:
    """The poller: collect signals from the router and replicas, feed
    the policy, drive the lifecycle manager, tick its state machine.
    ``tick()`` is the unit tests drive; ``run`` is the daemon loop
    ``cli fleet autoscale`` wraps."""

    def __init__(
        self,
        router_url: str,
        manager,
        policy: AutoscalePolicy | None = None,
        poll_interval_s: float = 1.0,
        poll_timeout_s: float = 5.0,
        say=None,
    ) -> None:
        self.router_url = router_url.rstrip("/")
        self.manager = manager
        self.policy = policy or AutoscalePolicy(
            min_replicas=manager.min_replicas,
            max_replicas=manager.max_replicas,
        )
        self.poll_interval_s = float(poll_interval_s)
        self.poll_timeout_s = float(poll_timeout_s)
        self.say = say
        self._prev_outcomes: dict[str, float] | None = None
        self._prev_latency: tuple[float, float] | None = None

    # -- signal collection ----------------------------------------------------

    def collect_signals(self) -> dict:
        """One poll's readings (each None when its surface is
        unreachable). Router counters are turned into *recent* rates by
        differencing against the previous poll — the policy reacts to
        what is happening, not to the lifetime average."""
        signals: dict = {name: None for name in SIGNALS}
        replicas: list[dict] = []
        try:
            page = _fetch_json(
                self.router_url + "/metrics?format=json",
                self.poll_timeout_s,
            )
        except Exception:
            return signals
        runtime = page.get("runtime") or {}
        replicas = page.get("replicas") or []

        # The router's own alert engine (docs/OBSERVABILITY.md): count
        # rules in the *firing* state — a resolving alert's condition
        # has already cleared and must not keep voting for capacity. A
        # router without the alert plane (disabled, pre-alerting) just
        # leaves the signal None.
        try:
            alerts_page = _fetch_json(
                self.router_url + "/fleet/alerts", self.poll_timeout_s
            )
            if alerts_page.get("enabled"):
                signals["alerts_active"] = float(sum(
                    1 for a in alerts_page.get("active") or []
                    if a.get("state") == "firing"
                ))
        except Exception:
            pass

        outcomes = runtime.get("fleet_requests_total")
        if isinstance(outcomes, dict):
            flat = {k: float(v) for k, v in outcomes.items()}
            if self._prev_outcomes is not None:
                d_total = sum(flat.values()) - sum(
                    self._prev_outcomes.values()
                )
                shed_keys = ("outcome=shed", "outcome=no_replica")
                d_shed = sum(
                    flat.get(k, 0.0) - self._prev_outcomes.get(k, 0.0)
                    for k in shed_keys
                )
                if d_total > 0:
                    signals["shed_rate"] = max(0.0, d_shed) / d_total
                else:
                    signals["shed_rate"] = 0.0
            self._prev_outcomes = flat

        lat = runtime.get("fleet_request_latency_seconds")
        if isinstance(lat, dict) and "sum" in lat and "count" in lat:
            cur = (float(lat["sum"]), float(lat["count"]))
            if self._prev_latency is not None:
                d_sum = cur[0] - self._prev_latency[0]
                d_count = cur[1] - self._prev_latency[1]
                if d_count > 0:
                    signals["latency_ms"] = 1000.0 * d_sum / d_count
            self._prev_latency = cur

        # Per-replica surfaces are polled serially: a wedged replica
        # must cost this tick a bounded, SHORT stall, not poll_timeout_s
        # × fleet size × 2 fetches — the debounce window would stretch
        # from seconds to minutes exactly when the fleet is overloaded.
        # (The registry prober rotates a truly wedged replica out within
        # a few probes, after which it is skipped here entirely.)
        from machine_learning_replications_tpu.fleet.lifecycle import (
            replica_queue_depth,
        )

        rep_timeout = min(2.0, self.poll_timeout_s)
        depths, burns = [], []
        for rep in replicas:
            if not rep.get("in_rotation"):
                continue
            url = (rep.get("url") or "").rstrip("/")
            if not url:
                continue
            # Queue depth rides the router page since r17: the registry's
            # per-replica load block carries the /readyz-probed depth the
            # least-loaded balancer picks on, so the autoscaler reads the
            # SAME view (docs/FLEET.md "Router data plane") and skips one
            # HTTP fetch per replica per tick. The direct /healthz fetch
            # stays as the fallback for a pre-r17 router page.
            depth = (rep.get("load") or {}).get("last_queue_depth")
            if depth is None:
                depth = replica_queue_depth(url, timeout_s=rep_timeout)
            if depth is not None:
                depths.append(float(depth))
            try:
                rmetrics = _fetch_json(
                    url + "/metrics?format=json", rep_timeout
                )
                burn = (rmetrics.get("runtime") or {}).get("slo_burn_rate")
                if isinstance(burn, dict):
                    vals = [
                        float(v) for v in burn.values()
                        if isinstance(v, (int, float))
                        and not math.isnan(float(v))
                    ]
                    if vals:
                        burns.append(max(vals))
            except Exception:
                pass
        if depths:
            signals["queue_depth"] = max(depths)
        if burns:
            signals["burn_rate"] = max(burns)
        signals["ready"] = sum(
            1 for r in replicas if r.get("in_rotation")
        )
        return signals

    # -- the loop -------------------------------------------------------------

    def tick(self) -> dict | None:
        signals = self.collect_signals()
        ready = signals.get("ready") or 0
        action = self.policy.observe(
            signals, desired=self.manager.desired, ready=ready,
        )
        if action is not None:
            self.manager.scale_to(action["target"])
            if self.say:
                self.say(
                    f"{action['decision']} → {self.manager.desired} "
                    f"replicas ({action['reason']})"
                )
        AUTOSCALE_DESIRED.get().set(float(self.manager.desired))
        self.manager.tick()
        return action

    def run(self, stop_check=None, max_ticks: int | None = None) -> int:
        ticks = 0
        while max_ticks is None or ticks < max_ticks:
            if stop_check is not None and stop_check():
                break
            try:
                self.tick()
            except Exception as exc:
                # The control loop must outlive any one bad poll: a
                # router restart mid-tick becomes a journaled blip, not
                # a dead autoscaler and a frozen fleet.
                journal.event("autoscale_tick_error", error=str(exc))
                if self.say:
                    self.say(f"tick failed: {exc}")
            ticks += 1
            time.sleep(self.poll_interval_s)
        return ticks
