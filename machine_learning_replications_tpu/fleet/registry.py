"""Replica registry — the front-door router's source of routing truth.

One serving replica is a single point of failure; a fleet of N is only a
*service* once something tracks which of them may receive traffic right
now. This module is that something: a thread-safe table of replicas with
an explicit rotation state machine, fed by two independent signals —

  * **probes** (``fleet.health.HealthProber``): periodic ``/readyz``
    GETs. A replica enters rotation after a successful ready probe and
    leaves it after ``fail_threshold`` consecutive failed ones; a
    replica that left (for any reason) re-enters only after
    ``recover_probes`` consecutive ready probes, so a flapping replica
    cannot oscillate into rotation on a single lucky probe. The probe
    also carries the replica's served checkpoint version (``/readyz``
    echoes it), which is how the deploy controller observes a rollout
    landing.
  * **request outcomes** (the router's data path): ``breaker_failures``
    consecutive transport/5xx failures open the replica's breaker —
    rotation out *now*, without waiting for the next probe tick, because
    the requests ARE the probe when traffic is flowing. Recovery is
    probe-driven like any other out state.

**Least-loaded rotation** (power-of-two-choices): ``pick`` no longer
walks a round-robin ring. Each replica carries three live load signals —

  * ``outstanding``: upstream attempts dispatched by THIS router and not
    yet answered (``note_dispatch`` / ``note_complete``),
  * ``ewma_latency_ms``: an exponentially weighted moving average of this
    router's observed attempt latencies (``note_complete``),
  * ``last_queue_depth``: the replica's own admission-queue depth, read
    off ``/readyz`` by the prober (``observe_probe``) — the shared
    signal that also sees load from OTHER routers (``--workers N``
    router processes each run their own registry).

``pick`` samples TWO distinct in-rotation candidates uniformly at random
and takes the lower-scored one (``score = ewma_latency × (1 +
outstanding + queue_depth)``); ties (e.g. an idle fleet with no signal
yet) break to the replica picked least recently, so cold fleets still
spread. Two random choices instead of a global arg-min is deliberate:
full least-loaded herds every router worker onto the same momentarily
idle replica between signal refreshes, while two choices gets
exponentially better load balance than random for one extra sample
(Mitzenmacher) with no herding — and never scans the fleet under the
lock.

An **admin hold** (``hold`` / ``release``) is orthogonal to probe state:
the rolling-deploy controller holds a replica while its new version
warms, which removes it from ``pick`` without touching the probe state
machine — release puts it back the moment probes agree it is ready.

Every transition is journaled (``fleet_replica_registered`` /
``fleet_replica_deregistered`` / ``fleet_rotation`` with direction and
reason) and mirrored on the process registry (``fleet_replicas{state=}``
gauge, ``fleet_rotations_total{direction=}``), so a chaos run can assert
the kill → out → recover → in arc from the journal and one scrape.

No jax anywhere in ``fleet/``: the router is a pure-Python front door
and must start in milliseconds, not after an XLA backend init.
"""

from __future__ import annotations

import random
import threading
import time

from machine_learning_replications_tpu.obs import journal
from machine_learning_replications_tpu.obs.registry import REGISTRY

FLEET_REPLICAS = REGISTRY.gauge(
    "fleet_replicas",
    "Registered replicas by rotation state (probing: awaiting first "
    "ready probe; ready: in rotation; out: rotated out).",
    labels=("state",),
)
FLEET_ROTATIONS = REGISTRY.counter(
    "fleet_rotations_total",
    "Rotation transitions by direction (in: replica began receiving "
    "traffic; out: replica stopped).",
    labels=("direction",),
)
FLEET_PROBES = REGISTRY.counter(
    "fleet_probe_total",
    "Health probes by result (ok: HTTP 200 ready; not_ready: explicit "
    "503; error: transport failure).",
    labels=("result",),
)
# Materialize the fixed label sets at import so the first scrape shows
# the full state space (a zero is a fact; an absent series is a mystery).
for _state in ("probing", "ready", "out"):
    FLEET_REPLICAS.labels(state=_state)
for _direction in ("in", "out"):
    FLEET_ROTATIONS.labels(direction=_direction)

#: Rotation states (``Replica.state``).
PROBING, READY, OUT = "probing", "ready", "out"


class Replica:
    """One registered serving replica. Mutated only under the registry
    lock; ``as_dict`` is the externally visible snapshot."""

    __slots__ = (
        "id", "url", "state", "reason", "version", "held",
        "probe_fails", "probe_oks", "request_fails",
        "registered_at", "last_probe_at", "last_change_at",
        "outstanding", "ewma_latency_ms", "last_queue_depth",
        "last_pick_seq", "clock_offset_ms",
    )

    def __init__(self, replica_id: str, url: str) -> None:
        self.id = replica_id
        self.url = url.rstrip("/")
        self.state = PROBING
        self.reason = "registered"
        self.version: int | None = None
        self.held = False
        self.probe_fails = 0
        self.probe_oks = 0
        self.request_fails = 0
        # Wall-clock by intent: these are display timestamps in the
        # /fleet/replicas payload, never duration operands.
        self.registered_at = time.time()  # graftcheck: disable=monotonic-clock
        self.last_probe_at: float | None = None
        self.last_change_at = self.registered_at
        # Load signals driving least-loaded picking (module docstring).
        self.outstanding = 0
        self.ewma_latency_ms: float | None = None
        self.last_queue_depth: int | None = None
        self.last_pick_seq = 0  # LRU tie-break for the cold fleet
        # Smoothed replica-minus-router monotonic-clock offset (from the
        # prober's ClockSync feed); None until the first clock-carrying
        # probe. Surfaced on /fleet/replicas for trace-join debugging.
        self.clock_offset_ms: float | None = None

    #: Latency prior (ms) for a replica with no sample yet: low enough
    #: that exploration beats any realistically-warm replica's score, so
    #: a fresh replica is never starved — but NOT near-zero, so the
    #: load factor still caps the exploration burst. Against a warm
    #: replica idling at W ms, a cold replica stops winning once its
    #: outstanding count passes ~W/0.25 (e.g. ~20 in-flight at 5 ms,
    #: ~400 at 100 ms): a bounded probe window, not the whole in-flight
    #: load of a 1000-connection router piling onto one cold engine.
    LATENCY_PRIOR_MS = 0.25

    def score(self) -> float:
        """Expected-cost score for power-of-two-choices: recent latency
        scaled by everything already queued at (or in flight to) the
        replica. A replica with no latency sample yet scores on the
        exploration prior above — sampled quickly, never starved, and
        never handed an unbounded cold-start burst."""
        lat = (
            max(self.ewma_latency_ms, 1e-3)
            if self.ewma_latency_ms is not None else self.LATENCY_PRIOR_MS
        )
        return lat * (
            1.0 + self.outstanding + (self.last_queue_depth or 0)
        )

    def as_dict(self) -> dict:
        return {
            "id": self.id,
            "url": self.url,
            "state": self.state,
            "reason": self.reason,
            "in_rotation": self.state == READY and not self.held,
            "held": self.held,
            "version": self.version,
            "probe_fails": self.probe_fails,
            "request_fails": self.request_fails,
            "registered_at": self.registered_at,
            "last_probe_at": self.last_probe_at,
            "clock_offset_ms": (
                None if self.clock_offset_ms is None
                else round(self.clock_offset_ms, 3)
            ),
            # The load view the balancer picks on (docs/FLEET.md "Router
            # data plane") — operators and the autoscaler read the same
            # numbers that drive rotation.
            "load": {
                "ewma_latency_ms": (
                    None if self.ewma_latency_ms is None
                    else round(self.ewma_latency_ms, 3)
                ),
                "outstanding": self.outstanding,
                "last_queue_depth": self.last_queue_depth,
                "score": round(self.score(), 3),
            },
        }


class ReplicaRegistry:
    """The fleet's rotation table (see module docstring).

    ``fail_threshold`` — consecutive failed probes before rotation out;
    ``recover_probes`` — consecutive ready probes before an ``out``
    replica re-enters; ``breaker_failures`` — consecutive request
    failures that rotate a replica out immediately.
    """

    #: EWMA smoothing for observed attempt latency: ~the last 10
    #: attempts dominate, so one slow outlier decays within a dozen
    #: requests instead of poisoning the replica's score for minutes.
    EWMA_ALPHA = 0.2

    def __init__(
        self,
        fail_threshold: int = 2,
        recover_probes: int = 2,
        breaker_failures: int = 3,
        rng: random.Random | None = None,
    ) -> None:
        if min(fail_threshold, recover_probes, breaker_failures) < 1:
            raise ValueError("thresholds must be >= 1")
        self.fail_threshold = int(fail_threshold)
        self.recover_probes = int(recover_probes)
        self.breaker_failures = int(breaker_failures)
        self._lock = threading.Lock()
        self._replicas: dict[str, Replica] = {}
        self._rng = rng or random.Random()
        self._pick_seq = 0  # monotonic pick stamp (LRU tie-break)
        self._retire_listeners: list = []

    # -- membership ---------------------------------------------------------

    def add_retire_listener(self, fn) -> None:
        """``fn(replica_id)`` runs whenever a replica's *process* is
        gone for good — deregistration, or replacement by a same-id
        re-registration with a new url. The telemetry plane hooks this
        to retire the replica's per-replica gauge series
        (``fleet_scrape_stale``, ``fleet_clock_offset_ms``) instead of
        letting them linger forever at their last value. Listeners run
        outside the lock; exceptions are swallowed (telemetry hygiene
        must never break membership)."""
        self._retire_listeners.append(fn)

    def _notify_retire(self, replica_id: str) -> None:
        for fn in self._retire_listeners:
            try:
                fn(replica_id)
            except Exception:
                pass

    def register(self, replica_id: str, url: str) -> dict:
        """Add (or re-add) a replica. Re-registration with the same id is
        idempotent when the url matches (a replica retrying its
        registration must not reset its rotation state) and a fresh
        start otherwise (the process behind the id was replaced)."""
        with self._lock:
            old = self._replicas.get(replica_id)
            if old is not None and old.url == url.rstrip("/"):
                return old.as_dict()
            # Same id, different url: the replacement starts in PROBING,
            # so an in-rotation predecessor leaves rotation RIGHT HERE —
            # account it like deregister does, or fleet_rotations_total
            # drifts in>out and the journal arc has a silent capacity
            # drop at exactly this transition.
            replaced_in = (
                old is not None and old.state == READY and not old.held
            )
            self._replicas[replica_id] = rep = Replica(replica_id, url)
            self._refresh_gauge_locked()
        if old is not None:
            # The process behind the id was replaced: the OLD process's
            # per-replica series must not survive as the new one's.
            self._notify_retire(replica_id)
        if replaced_in:
            FLEET_ROTATIONS.inc(direction="out")
            journal.event(
                "fleet_rotation", replica=replica_id, direction="out",
                reason="replaced by re-registration with a new url",
            )
        journal.event(
            "fleet_replica_registered", replica=replica_id, url=rep.url,
        )
        return rep.as_dict()

    def deregister(self, replica_id: str) -> bool:
        with self._lock:
            rep = self._replicas.pop(replica_id, None)
            if rep is None:
                return False
            was_in = rep.state == READY and not rep.held
            self._refresh_gauge_locked()
        self._notify_retire(replica_id)
        if was_in:
            FLEET_ROTATIONS.inc(direction="out")
        journal.event(
            "fleet_replica_deregistered", replica=replica_id, url=rep.url,
        )
        return True

    def get(self, replica_id: str) -> dict | None:
        with self._lock:
            rep = self._replicas.get(replica_id)
            return rep.as_dict() if rep is not None else None

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [
                rep.as_dict()
                for _, rep in sorted(self._replicas.items())
            ]

    def urls(self) -> list[tuple[str, str]]:
        """(id, url) for every registered replica — the prober's worklist."""
        with self._lock:
            return [
                (rep.id, rep.url)
                for _, rep in sorted(self._replicas.items())
            ]

    # -- routing ------------------------------------------------------------

    def ready_count(self) -> int:
        with self._lock:
            return sum(
                1 for rep in self._replicas.values()
                if rep.state == READY and not rep.held
            )

    def pick(self, exclude: set[str] | None = None) -> dict | None:
        """The least-loaded of two random in-rotation choices (module
        docstring), preferring replicas not in ``exclude`` (the retry
        path's already-tried set). Falls back to an excluded-but-ready
        replica when nothing else is in rotation — against a shrunken
        fleet, retrying the same replica beats failing the request
        outright. None when nothing is ready."""
        with self._lock:
            ready = [
                rep for rep in self._replicas.values()
                if rep.state == READY and not rep.held
            ]
            if not ready:
                return None
            pool = [
                rep for rep in ready
                if not exclude or rep.id not in exclude
            ] or ready
            if len(pool) == 1:
                chosen = pool[0]
            else:
                a, b = self._rng.sample(pool, 2)
                sa, sb = a.score(), b.score()
                if sa != sb:
                    chosen = a if sa < sb else b
                else:
                    # No signal separates them (cold fleet): take the
                    # one picked least recently so traffic still spreads.
                    chosen = a if a.last_pick_seq <= b.last_pick_seq \
                        else b
            self._pick_seq += 1
            chosen.last_pick_seq = self._pick_seq
            return chosen.as_dict()

    def note_dispatch(self, replica_id: str) -> None:
        """An upstream attempt is in flight to the replica: its
        ``outstanding`` count — the most immediate load signal there is
        — rises until ``note_complete``."""
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is not None:
                rep.outstanding += 1

    def note_complete(self, replica_id: str,
                      latency_s: float | None = None) -> None:
        """The attempt finished (any outcome). ``latency_s`` feeds the
        EWMA only when the replica actually answered — a conn-error's
        instant failure or a timeout's capped wait says nothing about
        how fast the replica serves."""
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is None:
                return
            rep.outstanding = max(0, rep.outstanding - 1)
            if latency_s is not None:
                ms = latency_s * 1000.0
                if rep.ewma_latency_ms is None:
                    rep.ewma_latency_ms = ms
                else:
                    a = self.EWMA_ALPHA
                    rep.ewma_latency_ms += a * (ms - rep.ewma_latency_ms)

    def mark_success(self, replica_id: str) -> None:
        """A routed request succeeded: the failure streak resets."""
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is not None:
                rep.request_fails = 0

    def mark_failure(self, replica_id: str, reason: str) -> None:
        """A routed request failed at the transport or with a 5xx. After
        ``breaker_failures`` consecutive ones the replica's breaker opens
        — rotation out immediately, recovery via probes."""
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is None:
                return
            rep.request_fails += 1
            if rep.request_fails < self.breaker_failures or \
                    rep.state != READY:
                return
            self._transition_locked(
                rep, OUT, f"breaker open ({rep.request_fails} consecutive "
                f"request failures; last: {reason})",
            )

    # -- admin hold (rolling deploys) ---------------------------------------

    def hold(self, replica_id: str) -> bool:
        """Remove the replica from ``pick`` without touching probe state
        — the deploy controller's parking brake."""
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is None or rep.held:
                return False
            was_in = rep.state == READY
            rep.held = True
            self._refresh_gauge_locked()
        if was_in:
            # Only an in-rotation replica LEAVES rotation here: holding
            # a probing/out replica (a lifecycle retire racing a crash)
            # must not journal a rotation that never happened.
            FLEET_ROTATIONS.inc(direction="out")
            journal.event(
                "fleet_rotation", replica=replica_id, direction="out",
                reason="admin_hold",
            )
        return True

    def release(self, replica_id: str) -> bool:
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is None or not rep.held:
                return False
            rep.held = False
            now_in = rep.state == READY
            self._refresh_gauge_locked()
        if now_in:
            # A replica that went OUT while held (stopped heartbeating
            # mid-drain) does NOT re-enter rotation on release — probes
            # own that door; journaling direction=in here would claim a
            # rotation the router never made.
            FLEET_ROTATIONS.inc(direction="in")
            journal.event(
                "fleet_rotation", replica=replica_id, direction="in",
                reason="admin_release",
            )
        return True

    # -- probe feedback ------------------------------------------------------

    def observe_probe(
        self, replica_id: str, ok: bool, ready: bool,
        version: int | None = None,
        queue_depth: int | None = None,
        clock_offset_ms: float | None = None,
    ) -> None:
        """Prober feedback for one replica: ``ok`` means the probe got an
        HTTP answer at all, ``ready`` the replica's own readiness verdict
        (an explicit 503 is a *healthy* not-ready, e.g. draining — it
        still counts against rotation, but as ``not_ready`` rather than
        a transport failure). ``queue_depth`` is the replica's own
        admission-queue depth off the same probe — the cross-router load
        signal ``pick`` folds into its score. ``clock_offset_ms`` is the
        smoothed clock offset the prober's ClockSync derived from the
        same probe (display-only here; the join reads ClockSync)."""
        FLEET_PROBES.inc(
            result="ok" if ok and ready else
            "not_ready" if ok else "error"
        )
        with self._lock:
            rep = self._replicas.get(replica_id)
            if rep is None:
                return
            rep.last_probe_at = time.time()  # graftcheck: disable=monotonic-clock
            if ok and version is not None:
                rep.version = version
            if ok and queue_depth is not None:
                # The field arrives off an UNTRUSTED /readyz body (any
                # process can register via the control plane): a
                # non-numeric value must not abort the probe pass — it
                # would freeze probing for every replica behind this one
                # in the tick, including rotated-out ones waiting to
                # recover.
                try:
                    rep.last_queue_depth = max(0, int(queue_depth))
                except (TypeError, ValueError):
                    pass
            if ok and clock_offset_ms is not None:
                rep.clock_offset_ms = float(clock_offset_ms)
            if ok and ready:
                rep.probe_fails = 0
                rep.probe_oks += 1
                if rep.state == PROBING or (
                    rep.state == OUT
                    and rep.probe_oks >= self.recover_probes
                ):
                    rep.request_fails = 0
                    self._transition_locked(rep, READY, "ready probe")
                return
            rep.probe_oks = 0
            rep.probe_fails += 1
            if rep.state == READY and (
                not ok and rep.probe_fails >= self.fail_threshold
                or ok and not ready
            ):
                # An explicit not-ready rotates out on the FIRST probe —
                # the replica itself said so (draining, degraded, cold);
                # transport silence needs fail_threshold strikes, since a
                # single dropped probe packet should not empty a fleet.
                self._transition_locked(
                    rep, OUT,
                    "replica reports not ready" if ok else
                    f"{rep.probe_fails} consecutive probe failures",
                )

    # -- internals -----------------------------------------------------------

    def _transition_locked(self, rep: Replica, state: str,
                           reason: str) -> None:
        """State change + journal + metrics, under the registry lock so
        published order matches transition order (the supervisor's
        breaker lesson)."""
        was_in = rep.state == READY and not rep.held
        rep.state = state
        rep.reason = reason
        rep.last_change_at = time.time()  # graftcheck: disable=monotonic-clock
        if state == OUT:
            # Recovery hysteresis starts from zero at the moment of the
            # outage: ok-probes accumulated while READY must not let a
            # breaker-opened replica skip the recover_probes gate on its
            # first post-outage probe.
            rep.probe_oks = 0
        now_in = rep.state == READY and not rep.held
        self._refresh_gauge_locked()
        if was_in != now_in:
            FLEET_ROTATIONS.inc(direction="in" if now_in else "out")
        journal.event(
            "fleet_rotation", replica=rep.id,
            direction="in" if now_in else "out", reason=reason,
            state=state, version=rep.version,
        )

    def _refresh_gauge_locked(self) -> None:
        counts = {PROBING: 0, READY: 0, OUT: 0}
        for rep in self._replicas.values():
            if rep.held and rep.state == READY:
                # A held-ready replica is effectively out of rotation;
                # the gauge reflects what the router would route to.
                counts[OUT] += 1
            else:
                counts[rep.state] += 1
        for state, n in counts.items():
            FLEET_REPLICAS.set(float(n), state=state)
