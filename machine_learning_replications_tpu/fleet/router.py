"""Front-door HTTP router: N replicas behind one address, zero-downtime.

The router reuses the serving stack's proven transport — the same
``serve.protocol`` parse rules and ``serve.transport`` non-blocking
event loop the 1332-qps front end runs on — with a different
application behind it: instead of an engine, a **replica registry**
(``fleet.registry``), a **health prober** (``fleet.health``), and a
**proxy data path** with per-request retry and hedging.

Data path (``POST /predict``):

  * The handler (event-loop thread) picks an in-rotation replica
    (round-robin, per-replica breakers skipped) and hands the attempt to
    a small forwarder thread pool — upstream I/O never blocks the loop.
    Each forwarder keeps one persistent keep-alive connection per
    replica (the loadgen lesson: no per-request TCP handshake on the
    hot path), with one transparent fresh-connection resend when a
    reused socket died idle.
  * The client's deadline (``--request-timeout``, tightened by an
    inbound ``X-Request-Deadline-Ms``, never loosened) rides DOWN to the
    replica as the remaining budget and is enforced router-side by a
    loop timer: a request is answered or 504'd in bounded time, never
    hung — the same contract the replicas make individually.
  * **Retry**: a transport failure or 5xx marks the replica
    (``registry.mark_failure`` — the per-replica breaker) and re-sends
    the request to the next replica, up to ``max_attempts`` and always
    within the deadline. A 503 shed retries on a *different* replica
    immediately; when only the shedding replica exists, the upstream's
    ``Retry-After`` is honored (bounded by the remaining budget) before
    one same-replica retry — and passed through to the client when the
    budget cannot cover it. ``/predict`` is a pure function, so
    re-sends and duplicates cannot double-apply anything.
  * **Hedging** (``hedge_ms`` > 0): when the first attempt has not
    answered within the hedge delay and a second in-rotation replica
    exists, a duplicate fires; the first reply wins, the loser is
    discarded. Tail latency from one slow replica costs one duplicate
    request instead of a client-visible stall.
  * Replies pass through the replica's body and identity headers
    (``X-Replica`` / ``X-Model-Version`` / ``X-Serve-Path``) — the
    rolling-deploy crossover is provable from the client side.

Control plane: ``/fleet/replicas`` (GET snapshot; POST register /
deregister — ``cli serve --register`` posts here), ``/fleet/deploy``
(POST starts a rolling deploy through ``fleet.deploy``; GET status),
``/healthz`` / ``/readyz`` (a router with zero in-rotation replicas is
alive but not ready), ``/metrics`` (``fleet_*`` families through the
process registry, strict-exposition clean), and ``/debug/requests``
(the router's own flight-recorded traces: route → upstream → respond
phase attribution per sampled request).

No jax imports anywhere on this path — the router starts in
milliseconds and runs fine on a host with no accelerator stack at all.
"""

from __future__ import annotations

import json
import queue
import threading
import time

from machine_learning_replications_tpu.obs import journal, reqtrace
from machine_learning_replications_tpu.obs.registry import REGISTRY
from machine_learning_replications_tpu.fleet.health import HealthProber
from machine_learning_replications_tpu.fleet.registry import ReplicaRegistry
from machine_learning_replications_tpu.serve.metrics import LATENCY_BUCKETS_S
from machine_learning_replications_tpu.serve.transport import (
    EventLoopHttpServer,
)

FLEET_REQUESTS = REGISTRY.counter(
    "fleet_requests_total",
    "Routed /predict requests by terminal outcome (ok, shed, error, "
    "timeout, no_replica, bad_request).",
    labels=("outcome",),
)
FLEET_UPSTREAM = REGISTRY.counter(
    "fleet_upstream_attempts_total",
    "Upstream /predict attempts by result (ok, shed, server_error, "
    "conn_error, client_error).",
    labels=("result",),
)
FLEET_RETRIES = REGISTRY.counter(
    "fleet_retries_total",
    "Requests re-sent to another replica, by what failed the previous "
    "attempt.",
    labels=("reason",),
)
FLEET_HEDGES = REGISTRY.counter(
    "fleet_hedges_total",
    "Hedged duplicate attempts fired against a second replica.",
)
FLEET_HEDGE_WINS = REGISTRY.counter(
    "fleet_hedge_wins_total",
    "Hedged duplicates that answered before the original attempt.",
)
FLEET_REPLICA_REQUESTS = REGISTRY.counter(
    "fleet_replica_requests_total",
    "Upstream attempts per replica by result.",
    labels=("replica", "result"),
)
FLEET_LATENCY = REGISTRY.histogram(
    "fleet_request_latency_seconds",
    "Router-side /predict latency, admission to reply enqueue.",
    LATENCY_BUCKETS_S,
)
FLEET_DEPLOYS = REGISTRY.counter(
    "fleet_deploys_total",
    "Rolling deploys driven through this router by result.",
    labels=("result",),
)
for _outcome in ("ok", "shed", "error", "timeout", "no_replica"):
    FLEET_REQUESTS.labels(outcome=_outcome)
FLEET_HEDGES.get()
FLEET_HEDGE_WINS.get()


class _Forwarders:
    """Small pool of daemon threads running upstream calls — the proxy's
    answer to 'handlers must not block the loop'. Each thread caches one
    persistent keep-alive connection per (replica id, url)."""

    def __init__(self, workers: int = 8) -> None:
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self._local = threading.local()
        self._threads = [
            threading.Thread(
                target=self._loop, name=f"fleet-forward-{i}", daemon=True
            )
            for i in range(max(1, int(workers)))
        ]
        for t in self._threads:
            t.start()

    def submit(self, fn) -> None:
        self._q.put(fn)

    def _loop(self) -> None:
        while True:
            fn = self._q.get()
            if fn is None:
                self._q.put(None)  # let the other workers see it too
                return
            try:
                fn()
            except Exception:
                pass  # a forwarded attempt must never kill a worker

    def close(self) -> None:
        self._q.put(None)

    # -- per-thread keep-alive connections ----------------------------------

    def call(
        self, replica_id: str, url: str, method: str, path: str,
        body: bytes | None, headers: dict[str, str], timeout_s: float,
    ) -> tuple[int, dict[str, str], bytes]:
        """One upstream HTTP call over this thread's cached connection to
        the replica; a dead reused socket gets one transparent fresh
        connection. Raises ``OSError``/``http.client`` errors on
        transport failure (the caller classifies)."""
        import http.client
        import urllib.parse

        cache = getattr(self._local, "conns", None)
        if cache is None:
            cache = self._local.conns = {}
        key = (replica_id, url)
        conn = cache.get(key)
        fresh = conn is None
        if fresh:
            u = urllib.parse.urlparse(url)
            conn = http.client.HTTPConnection(
                u.hostname or "127.0.0.1", u.port or 80, timeout=timeout_s
            )
            cache[key] = conn
        conn.timeout = timeout_s
        try:
            return self._once(conn, method, path, body, headers)
        except (http.client.HTTPException, OSError):
            conn.close()
            if fresh:
                cache.pop(key, None)
                raise
            # Reused socket died (idle reap, replica restart): one resend
            # on a fresh connection before the failure becomes real.
            try:
                return self._once(conn, method, path, body, headers)
            except (http.client.HTTPException, OSError):
                conn.close()
                cache.pop(key, None)
                raise

    @staticmethod
    def _once(conn, method, path, body, headers):
        conn.request(method, path, body=body, headers=headers)
        resp = conn.getresponse()
        data = resp.read()
        hdrs = {k.lower(): v for k, v in resp.getheaders()}
        if hdrs.get("connection", "").lower() == "close" or resp.will_close:
            conn.close()
        return resp.status, hdrs, data


_PASSTHROUGH_HEADERS = ("x-replica", "x-model-version", "x-serve-path")


class _ProxyJob:
    """One routed /predict request: the race between upstream attempts
    (forwarder threads), the hedge timer, and the deadline timer (loop
    thread) resolves under one lock — exactly one of them replies."""

    __slots__ = (
        "app", "trace", "responder", "body", "pin", "deadline_mono",
        "deadline_s", "tried", "first_replica", "attempts", "hedged",
        "t_route0", "deadline_timer", "hedge_timer", "_done", "_lock",
        "last_retry_after",
    )

    def __init__(self, app, trace, responder, body: bytes,
                 pin: str | None, deadline_s: float) -> None:
        self.app = app
        self.trace = trace
        self.responder = responder
        self.body = body
        self.pin = pin
        self.deadline_s = deadline_s
        self.deadline_mono = time.monotonic() + deadline_s
        self.tried: set[str] = set()
        self.first_replica: str | None = None
        self.attempts = 0
        self.hedged = False
        self.t_route0 = time.perf_counter()
        self.deadline_timer = None
        self.hedge_timer = None
        self.last_retry_after: str | None = None
        self._done = False
        self._lock = threading.Lock()

    def _claim(self) -> bool:
        with self._lock:
            if self._done:
                return False
            self._done = True
            return True

    # -- admission / dispatch (loop thread first, then any thread) -----------

    def start(self) -> None:
        rep = self.app.registry.pick()
        if rep is None:
            self.finish_no_replica()
            return
        self.deadline_timer = self.app.httpd.call_later(
            self.deadline_s, self.on_deadline
        )
        if self.app.hedge_s > 0:
            self.hedge_timer = self.app.httpd.call_later(
                self.app.hedge_s, self.on_hedge
            )
        self.dispatch(rep)

    def finish_no_replica(self) -> None:
        if not self._claim():
            return
        self._cancel_timers()
        self.app.finish(
            self, "no_replica", 503,
            body=json.dumps({"error": "no ready replicas"}).encode(),
            headers={"Retry-After": "1"},
        )

    def dispatch(self, rep: dict) -> None:
        with self._lock:
            if self._done:
                return
            self.attempts += 1
            if self.first_replica is None:
                self.first_replica = rep["id"]
            self.tried.add(rep["id"])
        self.app.forwarders.submit(lambda: self.attempt(rep))

    def retry(self, reason: str, failed: dict) -> bool:
        """Pick another replica and re-send; False when the retry budget
        (attempts, candidates, deadline) is exhausted."""
        if self.attempts >= self.app.max_attempts:
            return False
        if time.monotonic() >= self.deadline_mono:
            return False  # the deadline timer is about to answer
        rep = self.app.registry.pick(exclude=self.tried)
        if rep is None:
            return False
        FLEET_RETRIES.inc(reason=reason)
        self.trace.note(retried=reason)
        self.dispatch(rep)
        return True

    # -- timers (loop thread) ------------------------------------------------

    def on_deadline(self) -> None:
        if not self._claim():
            return
        if self.hedge_timer is not None:
            self.hedge_timer.cancel()
        self.app.finish(
            self, "timeout", 504,
            body=json.dumps({
                "error": f"timed out after {self.deadline_s:g}s "
                "(no replica answered in budget)",
            }).encode(),
        )

    def on_hedge(self) -> None:
        """Hedge delay expired with no reply: fire a duplicate against a
        replica not yet tried (if one is in rotation). ``pick`` falls
        back to already-tried replicas when nothing else is ready —
        right for retries, wrong here: hedging a slow replica with a
        duplicate to ITSELF would double the load on the one struggling
        server, so an exhausted pool means no hedge. The hedge is an
        upstream attempt like any other and counts against
        ``max_attempts`` — with the cap already spent, firing one would
        exceed the operator's per-request attempt budget exactly when
        the fleet is slow."""
        with self._lock:
            if self._done or self.hedged:
                return
            if self.attempts >= self.app.max_attempts:
                return
            rep = self.app.registry.pick(exclude=self.tried)
            if rep is None or rep["id"] in self.tried:
                return
            self.hedged = True
        FLEET_HEDGES.inc()
        self.trace.note(hedged=True)
        self.dispatch(rep)

    # -- the upstream attempt (forwarder thread) ------------------------------

    def attempt(self, rep: dict) -> None:
        if self._done:
            return
        remaining = self.deadline_mono - time.monotonic()
        if remaining <= 0.005:
            return  # the deadline timer answers
        headers = {
            "Content-Type": "application/json",
            "X-Request-Id": self.trace.request_id,
            # The remaining budget rides down so the replica's own
            # deadline machinery (504 + cancel-unflushed) is in play for
            # exactly the time the client is still listening.
            "X-Request-Deadline-Ms": str(int(remaining * 1000)),
        }
        if self.pin:
            headers["X-Serve-Path"] = self.pin
        try:
            code, up_headers, data = self.app.forwarders.call(
                rep["id"], rep["url"], "POST", "/predict", self.body,
                headers, timeout_s=remaining,
            )
        except Exception as exc:
            self._upstream_result(rep, "conn_error")
            self.app.registry.mark_failure(
                rep["id"], f"{type(exc).__name__}: {exc}"
            )
            if not self.retry("conn_error", rep) and self._claim():
                self._cancel_timers()
                self.app.finish(
                    self, "error", 503,
                    body=json.dumps({
                        "error": "no replica answered "
                        f"(last: {type(exc).__name__})",
                    }).encode(),
                    headers={"Retry-After": "1"}, replica=rep["id"],
                )
            return
        if code == 200:
            self._upstream_result(rep, "ok")
            self.app.registry.mark_success(rep["id"])
            won_hedge = self.hedged and rep["id"] != self.first_replica
            if not self._claim():
                return  # the other attempt (or the deadline) answered
            if won_hedge:
                FLEET_HEDGE_WINS.inc()
            self._cancel_timers()
            self.app.finish(
                self, "ok", 200, body=data, upstream_headers=up_headers,
                replica=rep["id"],
            )
            return
        if code == 503:
            self._upstream_result(rep, "shed")
            self.last_retry_after = up_headers.get("retry-after")
            # A shedding replica is HEALTHY (explicit admission control
            # or degraded mode) — not a breaker strike; the prober
            # rotates it out if /readyz agrees. Prefer another replica
            # right now.
            if self.retry("shed", rep):
                return
            if self._try_backoff_retry(rep):
                return
            if self._claim():
                self._cancel_timers()
                self.app.finish(
                    self, "shed", 503, body=data,
                    upstream_headers=up_headers, replica=rep["id"],
                )
            return
        if code >= 500:
            result = "server_error"
            self._upstream_result(rep, result)
            if code != 504:
                # A 504 is the replica's own deadline verdict on THIS
                # request — most of the budget is gone, and the miss says
                # nothing about the replica's health.
                self.app.registry.mark_failure(rep["id"], f"http_{code}")
                if self.retry("server_error", rep):
                    return
            if self._claim():
                self._cancel_timers()
                self.app.finish(
                    self, "timeout" if code == 504 else "error", code,
                    body=data, upstream_headers=up_headers,
                    replica=rep["id"],
                )
            return
        # 4xx: the client's fault travels back unchanged — a malformed
        # patient stays malformed on every replica; retrying would just
        # burn fleet capacity on garbage.
        self._upstream_result(rep, "client_error")
        if self._claim():
            self._cancel_timers()
            self.app.finish(
                self, "bad_request", code, body=data,
                upstream_headers=up_headers, replica=rep["id"],
            )

    def _try_backoff_retry(self, rep: dict) -> bool:
        """Everything in rotation already shed this request: honor the
        upstream ``Retry-After`` (bounded by the remaining budget) and
        try once more — the router-side version of loadgen's patient
        client. False when the budget cannot cover the wait."""
        if self.attempts >= self.app.max_attempts:
            return False
        try:
            wait_s = float(self.last_retry_after or 0.0)
        except ValueError:
            wait_s = 0.0
        wait_s = max(0.05, wait_s)
        if time.monotonic() + wait_s >= self.deadline_mono - 0.05:
            return False
        with self._lock:
            if self._done:
                return True
            self.attempts += 1
        FLEET_RETRIES.inc(reason="shed_backoff")

        def fire():
            target = self.app.registry.pick() or rep
            self.app.forwarders.submit(lambda: self.attempt(target))

        self.app.call_later_threadsafe(wait_s, fire)
        return True

    def _cancel_timers(self) -> None:
        if self.deadline_timer is not None:
            self.deadline_timer.cancel()
        if self.hedge_timer is not None:
            self.hedge_timer.cancel()

    @staticmethod
    def _upstream_result(rep: dict, result: str) -> None:
        FLEET_UPSTREAM.inc(result=result)
        FLEET_REPLICA_REQUESTS.inc(replica=rep["id"], result=result)


class _RouterApp:
    """The application behind the router's event loop (see module
    docstring for the endpoint map)."""

    def __init__(self, handle: "RouterHandle", request_timeout_s: float,
                 hedge_s: float, max_attempts: int, quiet: bool) -> None:
        self.handle = handle
        self.registry = handle.registry
        self.forwarders = handle.forwarders
        self.recorder = handle.recorder
        self.request_timeout_s = float(request_timeout_s)
        self.hedge_s = float(hedge_s)
        self.max_attempts = int(max_attempts)
        self.quiet = quiet
        self.httpd = None  # bound by make_router after the listener exists
        self.started_at = time.time()

    # -- loop helpers --------------------------------------------------------

    def call_later_threadsafe(self, delay_s: float, fn) -> None:
        """``call_later`` from any thread: posted onto the loop, where
        timer creation is legal."""
        self.httpd._post(lambda: self.httpd.call_later(delay_s, fn))

    # -- transport interface -------------------------------------------------

    def handle_request(self, req, rsp) -> None:
        if not self.quiet:
            import sys

            print(f"router {req.method} {req.target}", file=sys.stderr)
        if req.method == "POST":
            if req.path == "/predict":
                self._predict(req, rsp)
            elif req.path == "/fleet/replicas":
                self._post_replicas(req, rsp)
            elif req.path == "/fleet/deploy":
                self._post_deploy(req, rsp)
            else:
                rsp.send_json(
                    404, {"error": f"no such path: {req.target}"},
                    close=True,
                )
        elif req.method == "GET":
            self._get(req, rsp)
        else:
            rsp.send_json(
                501, {"error": f"unsupported method {req.method}"},
                close=True,
            )

    def handle_protocol_error(self, exc, rsp) -> None:
        rsp.send_json(exc.code, {"error": exc.message}, close=True)

    # -- data path -----------------------------------------------------------

    def _predict(self, req, rsp) -> None:
        trace = reqtrace.RequestTrace(
            reqtrace.sanitize_request_id(req.get_header("x-request-id"))
        )
        trace.add_phase("parse", trace.t_start, time.perf_counter())
        deadline_s = self.request_timeout_s
        raw_deadline = req.get_header("x-request-deadline-ms")
        if raw_deadline:
            try:
                client_s = float(raw_deadline) / 1000.0
            except ValueError:
                client_s = 0.0
            if client_s > 0.0:
                deadline_s = min(deadline_s, client_s)
        pin = (req.get_header("x-serve-path") or "").strip().lower() or None
        job = _ProxyJob(self, trace, rsp, req.body, pin, deadline_s)
        job.start()

    def finish(
        self, job: _ProxyJob, outcome: str, code: int, body: bytes,
        upstream_headers: dict[str, str] | None = None,
        headers: dict[str, str] | None = None,
        replica: str | None = None,
    ) -> None:
        """The single exit for a routed request: reply, stamp the trace
        (route = admission → first dispatch is folded into upstream
        here; the phases partition admission → reply), count, record."""
        trace = job.trace
        t_up_end = time.perf_counter()
        trace.add_phase("upstream", job.t_route0, t_up_end)
        out_headers = dict(headers or {})
        if upstream_headers:
            for name in _PASSTHROUGH_HEADERS:
                if name in upstream_headers:
                    out_headers[_canonical(name)] = upstream_headers[name]
            if "retry-after" in upstream_headers and code == 503:
                out_headers["Retry-After"] = upstream_headers["retry-after"]
        if replica is not None:
            out_headers.setdefault("X-Replica", replica)
            trace.note(replica=replica)
        trace.note(attempts=job.attempts)
        job.responder.send(
            code, body, "application/json",
            headers=out_headers, request_id=trace.request_id,
        )
        trace.add_phase("respond", t_up_end, time.perf_counter())
        trace.finish(
            "ok" if outcome == "ok" else outcome,
            error=None if outcome == "ok" else f"http_{code}",
        )
        FLEET_REQUESTS.inc(outcome=outcome)
        FLEET_LATENCY.get().observe(trace.total_s)
        self.recorder.record(trace)
        if self.handle.capture is not None and outcome == "ok":
            # Continual-learning tap (learn.capture): every SERVED row
            # lands in the bounded recent-cohort window. Raw bytes, no
            # parse — validation happens once, at refit time. After the
            # reply is written: capture latency is never client latency.
            try:
                self.handle.capture.append_line(job.body)
            except Exception:
                pass  # the data tap must never take the data path down

    # -- control plane --------------------------------------------------------

    def _get(self, req, rsp) -> None:
        path = req.path
        if path == "/healthz":
            snap = self.registry.snapshot()
            ready = sum(1 for r in snap if r["in_rotation"])
            rsp.send_json(200, {
                "status": "ok" if ready else "no_ready_replicas",
                "role": "fleet-router",
                "replicas_total": len(snap),
                "replicas_ready": ready,
                "deploy": self.handle.deploy_status,
                # Continual-learning tap state (learn.capture), so `cli
                # learn status` can see the refit's data window from the
                # same probe it already polls. None when capture is off.
                "capture": (
                    self.handle.capture.stats()
                    if self.handle.capture is not None else None
                ),
                "uptime_seconds": round(time.time() - self.started_at, 3),
            })
        elif path == "/readyz":
            ready = self.registry.ready_count()
            rsp.send_json(
                200 if ready else 503,
                {
                    "ready": ready > 0,
                    "reasons": [] if ready else ["no ready replicas"],
                    "replicas_ready": ready,
                },
            )
        elif path == "/fleet/replicas":
            rsp.send_json(200, {"replicas": self.registry.snapshot()})
        elif path == "/fleet/deploy":
            rsp.send_json(200, {"deploy": self.handle.deploy_status})
        elif path == "/debug/requests":
            try:
                n = int(req.query_param("n", "64"))
            except ValueError:
                rsp.send_json(400, {"error": "n must be an integer"})
                return
            rsp.send_json(200, {
                "stats": self.recorder.stats(),
                "requests": self.recorder.snapshot(n),
            })
        elif path == "/metrics":
            if req.query_param("format", "prometheus") == "json":
                rsp.send_json(200, {
                    "runtime": REGISTRY.snapshot(),
                    "replicas": self.registry.snapshot(),
                })
            else:
                rsp.send(
                    200, REGISTRY.render_prometheus().encode(),
                    "text/plain; version=0.0.4",
                )
        else:
            rsp.send_json(404, {"error": f"no such path: {path}"})

    def _post_replicas(self, req, rsp) -> None:
        """Registration endpoint (``cli serve --register`` posts here):
        ``{"id", "url"}`` adds a replica, ``{"deregister": id}`` removes
        one, ``{"hold": id}`` / ``{"release": id}`` toggle the admin
        hold — the out-of-process face of ``registry.hold`` the
        lifecycle manager's drain-first retirement needs (an in-process
        deploy controller calls the registry directly). Probing begins
        on the next prober tick; rotation in follows the first ready
        probe — a registered-but-cold replica never receives traffic."""
        try:
            body = json.loads(req.body or b"{}")
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
            if "deregister" in body:
                found = self.registry.deregister(str(body["deregister"]))
                rsp.send_json(200, {"deregistered": found})
                return
            if "hold" in body:
                rsp.send_json(200, {
                    "held": self.registry.hold(str(body["hold"])),
                })
                return
            if "release" in body:
                rsp.send_json(200, {
                    "released": self.registry.release(
                        str(body["release"])
                    ),
                })
                return
            rid, url = body.get("id"), body.get("url")
            if not rid or not url:
                raise ValueError(
                    'expected {"id": ..., "url": ...}, {"deregister": id}, '
                    '{"hold": id}, or {"release": id}'
                )
        except (ValueError, json.JSONDecodeError) as exc:
            rsp.send_json(400, {"error": str(exc)})
            return
        rsp.send_json(200, {"replica": self.registry.register(
            str(rid), str(url)
        )})

    def _post_deploy(self, req, rsp) -> None:
        """Start a rolling deploy (``fleet.deploy.rolling_deploy``) over
        every registered replica; replies when the rollout is DONE.
        Single-flight — a rollout in progress answers 409."""
        try:
            body = json.loads(req.body or b"{}")
            model = body.get("model") if isinstance(body, dict) else None
            if not model or not isinstance(model, str):
                raise ValueError('expected {"model": "checkpoint path"}')
        except (ValueError, json.JSONDecodeError) as exc:
            rsp.send_json(400, {"error": str(exc)})
            return
        if not self.handle._deploy_lock.acquire(blocking=False):
            rsp.send_json(409, {
                "error": "a rolling deploy is already in progress",
                "deploy": self.handle.deploy_status,
            })
            return

        def run():
            from machine_learning_replications_tpu.fleet.deploy import (
                rolling_deploy,
            )

            try:
                report = rolling_deploy(
                    self.registry, model,
                    status_cb=self.handle._set_deploy_status,
                )
            except Exception as exc:
                report = {
                    "result": "failed",
                    "error": f"{type(exc).__name__}: {exc}",
                }
                self.handle._set_deploy_status(report)
            finally:
                self.handle._deploy_lock.release()
            FLEET_DEPLOYS.inc(result=report.get("result", "failed"))
            rsp.send_json(
                200 if report.get("result") == "ok" else 500,
                {"deploy": report},
            )

        threading.Thread(
            target=run, name="fleet-deploy", daemon=True
        ).start()


def _canonical(lower_name: str) -> str:
    """lower-cased wire header name → canonical echo casing."""
    return {
        "x-replica": "X-Replica",
        "x-model-version": "X-Model-Version",
        "x-serve-path": "X-Serve-Path",
    }.get(lower_name, lower_name)


class RouterHandle:
    """A running front-door router: registry + prober + forwarder pool +
    event-loop HTTP listener."""

    def __init__(self, registry, prober, forwarders, recorder,
                 httpd=None, capture=None) -> None:
        self.registry = registry
        self.prober = prober
        self.forwarders = forwarders
        self.recorder = recorder
        self.httpd = httpd
        self.capture = capture  # learn.capture.CohortCapture or None
        self.deploy_status: dict | None = None
        self._deploy_lock = threading.Lock()
        self._thread: threading.Thread | None = None

    def _set_deploy_status(self, status: dict) -> None:
        self.deploy_status = status

    @property
    def address(self) -> tuple[str, int]:
        return self.httpd.server_address[:2]

    def serve_forever(self) -> None:
        self.httpd.serve_forever()

    def start_background(self) -> "RouterHandle":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="fleet-router",
            daemon=True,
        )
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self.prober.close()
        self.httpd.shutdown()
        self.httpd.server_close()
        self.forwarders.close()
        if self.capture is not None:
            self.capture.close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None


def make_router(
    host: str = "127.0.0.1",
    port: int = 8080,
    replicas: list[tuple[str, str]] | None = None,
    request_timeout_s: float = 30.0,
    hedge_ms: float = 0.0,
    max_attempts: int = 3,
    probe_interval_s: float = 0.5,
    probe_timeout_s: float = 2.0,
    fail_threshold: int = 2,
    recover_probes: int = 2,
    breaker_failures: int = 3,
    forward_workers: int = 8,
    trace_capacity: int = 256,
    tail_quantile: float = 0.99,
    idle_timeout_s: float = 5.0,
    max_connections: int = 8192,
    quiet: bool = True,
    start_prober: bool = True,
    capture_dir: str | None = None,
    capture_rows_per_shard: int = 4096,
    capture_max_shards: int = 8,
) -> RouterHandle:
    """Assemble the front-door router and bind its listener (not yet
    serving — call ``serve_forever`` or ``start_background``).
    ``replicas`` seeds the registry with static ``(id, url)`` members;
    dynamic members register themselves over ``POST /fleet/replicas``
    (``cli serve --register``). ``hedge_ms`` > 0 enables tail hedging;
    ``max_attempts`` bounds retry fan-out per request. ``start_prober``
    exists for tests that drive ``prober.tick()`` by hand.
    ``capture_dir`` enables the continual-learning cohort tap
    (``learn.capture``): every served /predict body lands in a bounded
    rotating JSONL window there (~``capture_rows_per_shard`` ×
    ``capture_max_shards`` recent rows) — the retrain's data source
    (docs/CONTINUAL.md)."""
    registry = ReplicaRegistry(
        fail_threshold=fail_threshold,
        recover_probes=recover_probes,
        breaker_failures=breaker_failures,
    )
    for rid, url in replicas or []:
        registry.register(rid, url)
    prober = HealthProber(
        registry, interval_s=probe_interval_s, timeout_s=probe_timeout_s
    )
    forwarders = _Forwarders(workers=forward_workers)
    recorder = reqtrace.FlightRecorder(
        capacity=trace_capacity, tail_quantile=tail_quantile
    )
    capture = None
    if capture_dir is not None:
        from machine_learning_replications_tpu.learn.capture import (
            CohortCapture,
        )

        capture = CohortCapture(
            capture_dir,
            rows_per_shard=capture_rows_per_shard,
            max_shards=capture_max_shards,
        )
    handle = RouterHandle(
        registry, prober, forwarders, recorder, capture=capture
    )
    app = _RouterApp(
        handle, request_timeout_s,
        hedge_s=hedge_ms / 1000.0, max_attempts=max_attempts, quiet=quiet,
    )
    try:
        handle.httpd = EventLoopHttpServer(
            (host, port), app,
            idle_timeout_s=idle_timeout_s,
            max_connections=max_connections,
        )
    except BaseException:
        forwarders.close()
        raise
    app.httpd = handle.httpd
    journal.event(
        "fleet_router_started",
        address=list(handle.httpd.server_address[:2]),
        replicas=[rid for rid, _ in (replicas or [])],
    )
    if start_prober:
        prober.start()
    return handle
