"""Front-door HTTP router: N replicas behind one address, zero-downtime.

The router reuses the serving stack's proven transport — the same
``serve.protocol`` parse rules and ``serve.transport`` non-blocking
event loop the 1332-qps front end runs on — with a different
application behind it: instead of an engine, a **replica registry**
(``fleet.registry``), a **health prober** (``fleet.health``), and a
**proxy data path** with per-request retry and hedging.

Data path (``POST /predict``) — ONE loop thread owns every socket end
to end, client side and replica side:

  * The handler (event-loop thread) picks an in-rotation replica —
    **least-loaded, power-of-two-choices** over the registry's live
    per-replica signals (EWMA attempt latency × (1 + outstanding
    attempts + queue depth); see ``fleet.registry``) — and fires the
    attempt through the transport's ``UpstreamPool``: non-blocking
    connect, per-replica keep-alive connection reuse, incremental
    response parsing, write backpressure, and the strict
    poisoned-connection rules a proxy needs. No thread hand-off per
    request anywhere on the path: the attempt completes as a loop
    callback, exactly like the timers it races. (The previous data
    plane proxied through a small pool of forwarder threads holding
    blocking ``http.client`` upstreams — the same thread-per-request
    architecture whose removal replica-side bought 10.1×.)
  * The client's deadline (``--request-timeout``, tightened by an
    inbound ``X-Request-Deadline-Ms``, never loosened) rides DOWN to the
    replica as the remaining budget and is enforced router-side by a
    loop timer: a request is answered or 504'd in bounded time, never
    hung — the same contract the replicas make individually.
  * **Retry**: a transport failure or 5xx marks the replica
    (``registry.mark_failure`` — the per-replica breaker) and re-sends
    the request to the next replica, up to ``max_attempts`` and always
    within the deadline. A 503 shed retries on a *different* replica
    immediately; when only the shedding replica exists, the upstream's
    ``Retry-After`` is honored (bounded by the remaining budget) before
    one same-replica retry — and passed through to the client when the
    budget cannot cover it. ``/predict`` is a pure function, so
    re-sends and duplicates cannot double-apply anything.
  * **Hedging** (``hedge_ms`` > 0): when the first attempt has not
    answered within the hedge delay and a second in-rotation replica
    exists, a duplicate fires; the first reply wins, the loser's
    attempt is cancelled (its connection closes — a half-spoken
    exchange can never be pooled). Tail latency from one slow replica
    costs one duplicate request instead of a client-visible stall.
  * Replies pass through the replica's body and identity headers
    (``X-Replica`` / ``X-Model-Version`` / ``X-Serve-Path``) — the
    rolling-deploy crossover is provable from the client side.

For many-core hosts, ``cli fleet router --workers N`` forks N router
processes sharing one ``SO_REUSEPORT`` port (``make_router(reuse_port=
True)``), each with its own registry converging through the replicas'
periodic registration heartbeats; the replica-side queue-depth probe
signal keeps their load views consistent.

Control plane: ``/fleet/replicas`` (GET snapshot; POST register /
deregister — ``cli serve --register`` posts here), ``/fleet/deploy``
(POST starts a rolling deploy through ``fleet.deploy``; GET status),
``/healthz`` / ``/readyz`` (a router with zero in-rotation replicas is
alive but not ready), ``/metrics`` (``fleet_*`` families through the
process registry, strict-exposition clean), ``/fleet/metrics`` (the
aggregated fleet exposition: in-rotation replicas scraped and merged
per ``obs.fleetmetrics``, stale replicas marked, the router's own
families appended), ``/fleet/trace`` (the cross-process joined
timeline: the router's tail-sampled traces with each serving replica's
phases fetched by request id and offset-corrected into the upstream
span, per ``obs.fleettrace``), and ``/debug/requests`` (the router's
own flight-recorded traces: route → upstream → respond phase
attribution per sampled request; ``?id=`` exact lookup over the
all-completions index).

No jax imports anywhere on this path (graftcheck rule
``import-purity`` proves it transitively in CI) — the router starts in
milliseconds and runs fine on a host with no accelerator stack at all.
The one-loop-thread socket-ownership contract is annotated with
``@loop_only`` / ``@cross_thread`` (``contracts.py``) and enforced by
rule ``loop-discipline``.
"""

from __future__ import annotations

import json
import queue
import threading
import time
import urllib.parse

from machine_learning_replications_tpu.obs import (
    alerts as obs_alerts,
    fleetmetrics,
    fleettrace,
    incident as obs_incident,
    journal,
    reqtrace,
    timeseries as obs_timeseries,
)
from machine_learning_replications_tpu.obs.registry import REGISTRY
from machine_learning_replications_tpu.fleet.health import HealthProber
from machine_learning_replications_tpu.fleet.registry import ReplicaRegistry
from machine_learning_replications_tpu.serve import protocol
from machine_learning_replications_tpu.serve.metrics import LATENCY_BUCKETS_S
from machine_learning_replications_tpu.serve.transport import (
    EventLoopHttpServer,
    UpstreamPool,
)
from machine_learning_replications_tpu.contracts import (
    cross_thread,
    loop_only,
)

FLEET_REQUESTS = REGISTRY.counter(
    "fleet_requests_total",
    "Routed /predict requests by terminal outcome (ok, shed, error, "
    "timeout, no_replica, bad_request).",
    labels=("outcome",),
)
FLEET_UPSTREAM = REGISTRY.counter(
    "fleet_upstream_attempts_total",
    "Upstream /predict attempts by result (ok, shed, server_error, "
    "conn_error, client_error).",
    labels=("result",),
)
FLEET_RETRIES = REGISTRY.counter(
    "fleet_retries_total",
    "Requests re-sent to another replica, by what failed the previous "
    "attempt.",
    labels=("reason",),
)
FLEET_HEDGES = REGISTRY.counter(
    "fleet_hedges_total",
    "Hedged duplicate attempts fired against a second replica.",
)
FLEET_HEDGE_WINS = REGISTRY.counter(
    "fleet_hedge_wins_total",
    "Hedged duplicates that answered before the original attempt.",
)
FLEET_REPLICA_REQUESTS = REGISTRY.counter(
    "fleet_replica_requests_total",
    "Upstream attempts per replica by result.",
    labels=("replica", "result"),
)
FLEET_LATENCY = REGISTRY.histogram(
    "fleet_request_latency_seconds",
    "Router-side /predict latency, admission to reply enqueue.",
    LATENCY_BUCKETS_S,
)
FLEET_DEPLOYS = REGISTRY.counter(
    "fleet_deploys_total",
    "Rolling deploys driven through this router by result.",
    labels=("result",),
)
FLEET_UPSTREAM_CONNS = REGISTRY.counter(
    "fleet_upstream_connections_total",
    "Upstream connection events on the router's loop-owned pool "
    "(opened: fresh TCP connect; reused: attempt rode a pooled "
    "keep-alive connection).",
    labels=("event",),
)
for _outcome in ("ok", "shed", "error", "timeout", "no_replica"):
    FLEET_REQUESTS.labels(outcome=_outcome)
for _event in ("opened", "reused"):
    FLEET_UPSTREAM_CONNS.labels(event=_event)
FLEET_HEDGES.get()
FLEET_HEDGE_WINS.get()

# Child instruments resolved ONCE: labels() takes the family lock and
# rebuilds the key tuple per call — measurable on the loop at four-digit
# qps (the r11 SLOTracker lesson, applied to the router's hot counters).
_REQ_OUTCOME = {
    o: FLEET_REQUESTS.labels(outcome=o)
    for o in ("ok", "shed", "error", "timeout", "no_replica",
              "bad_request")
}
_UP_RESULT = {
    r: FLEET_UPSTREAM.labels(result=r)
    for r in ("ok", "shed", "server_error", "conn_error", "client_error")
}
_CONN_EVENT = {
    e: FLEET_UPSTREAM_CONNS.labels(event=e) for e in ("opened", "reused")
}
_LATENCY = FLEET_LATENCY.get()
_REPLICA_RESULT: dict = {}  # (replica, result) -> child counter


def _replica_counter(replica: str, result: str):
    child = _REPLICA_RESULT.get((replica, result))
    if child is None:
        child = _REPLICA_RESULT[(replica, result)] = \
            FLEET_REPLICA_REQUESTS.labels(replica=replica, result=result)
    return child


FLEET_CAPTURE_DROPPED = REGISTRY.counter(
    "fleet_capture_dropped_total",
    "Served bodies dropped by the capture feed because the writer "
    "thread fell behind (bounded hand-off queue; the capture window is "
    "a bounded recent-cohort ring, so shedding is semantically fine).",
)


class _CaptureFeed:
    """The continual-learning tap's hand-off: the loop thread must not
    pay shard-rotation fsyncs, so captured bodies queue to one daemon
    writer thread (the same reasoning as serve's AsyncQualityFeed).
    The queue is BOUNDED: a disk slower than the request rate sheds
    capture rows (counted) instead of growing router memory without
    bound — the tap must never take the data path down, including by
    OOM."""

    MAX_PENDING = 8192

    def __init__(self, capture) -> None:
        self.capture = capture
        self._q: queue.Queue = queue.Queue(maxsize=self.MAX_PENDING)
        self._thread = threading.Thread(
            target=self._loop, name="fleet-capture", daemon=True
        )
        self._thread.start()

    @loop_only
    def append(self, body: bytes) -> None:
        try:
            self._q.put_nowait(body)
        except queue.Full:
            FLEET_CAPTURE_DROPPED.get().inc()

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            try:
                self.capture.append_line(item)
            except Exception:
                pass  # the data tap must never take the data path down

    def close(self) -> None:
        self._q.put(None)
        self._thread.join(timeout=10.0)
        self.capture.close()


_PASSTHROUGH_HEADERS = ("x-replica", "x-model-version", "x-serve-path")


class _ProxyJob:
    """One routed /predict request — a state machine that lives entirely
    ON the loop thread: dispatches are ``UpstreamPool`` attempts whose
    completions come back as loop callbacks, racing the hedge and
    deadline timers on the same clock. No locks — admission, every
    retry, the hedge, the deadline, and the reply are serialized by the
    loop by construction; exactly one path flips ``done``."""

    __slots__ = (
        "app", "trace", "responder", "body", "pin", "deadline_mono",
        "deadline_s", "tried", "first_replica", "attempts", "hedged",
        "t_route0", "deadline_timer", "hedge_timer", "done",
        "last_retry_after", "pending",
    )

    def __init__(self, app, trace, responder, body: bytes,
                 pin: str | None, deadline_s: float) -> None:
        self.app = app
        self.trace = trace
        self.responder = responder
        self.body = body
        self.pin = pin
        self.deadline_s = deadline_s
        self.deadline_mono = time.monotonic() + deadline_s
        self.tried: set[str] = set()
        self.first_replica: str | None = None
        self.attempts = 0
        self.hedged = False
        self.t_route0 = time.perf_counter()
        self.deadline_timer = None
        self.hedge_timer = None
        self.last_retry_after: str | None = None
        self.pending: list = []  # in-flight UpstreamAttempts
        self.done = False

    @loop_only
    def _claim(self) -> bool:
        if self.done:
            return False
        self.done = True
        self._settle()
        return True

    @loop_only
    def _settle(self) -> None:
        """Terminal cleanup: stop the timers and cancel the losing
        in-flight attempts (their connections close — a reply may be
        mid-flight on them). A cancelled attempt's completion never
        fires, so its replica's outstanding count is released here."""
        if self.deadline_timer is not None:
            self.deadline_timer.cancel()
        if self.hedge_timer is not None:
            self.hedge_timer.cancel()
        for att in self.pending:
            if att.cancel():
                self.app.registry.note_complete(att.key, None)
        self.pending.clear()

    # -- admission / dispatch (loop thread) ----------------------------------

    @loop_only
    def start(self) -> None:
        rep = self.app.registry.pick()
        if rep is None:
            self.finish_no_replica()
            return
        self.deadline_timer = self.app.httpd.call_later(
            self.deadline_s, self.on_deadline
        )
        if self.app.hedge_s > 0:
            self.hedge_timer = self.app.httpd.call_later(
                self.app.hedge_s, self.on_hedge
            )
        self.dispatch(rep)

    @loop_only
    def finish_no_replica(self) -> None:
        if not self._claim():
            return
        self.app.finish(
            self, "no_replica", 503,
            body=json.dumps({"error": "no ready replicas"}).encode(),
            headers={"Retry-After": "1"},
        )

    @loop_only
    def dispatch(self, rep: dict) -> None:
        if self.done:
            return
        self.attempts += 1
        if self.first_replica is None:
            self.first_replica = rep["id"]
        self.tried.add(rep["id"])
        self._send(rep)

    @loop_only
    def _send(self, rep: dict) -> None:
        """Fire one upstream attempt through the loop-owned pool."""
        remaining = self.deadline_mono - time.monotonic()
        if remaining <= 0.005:
            return  # the deadline timer answers
        headers = {
            "Content-Type": "application/json",
            "X-Request-Id": self.trace.request_id,
            # The remaining budget rides down so the replica's own
            # deadline machinery (504 + cancel-unflushed) is in play for
            # exactly the time the client is still listening.
            "X-Request-Deadline-Ms": str(int(remaining * 1000)),
        }
        if self.pin:
            headers["X-Serve-Path"] = self.pin
        data = protocol.build_request(
            "POST", "/predict", headers, self.body,
            host=f"{rep['id']}",
        )
        self.app.registry.note_dispatch(rep["id"])
        t0 = time.monotonic()
        cell: list = []
        att = self.app.upstream.request(
            rep["id"], self.app.replica_addr(rep["url"]), data,
            timeout_s=remaining,
            on_done=lambda result: self.on_upstream(
                rep, t0, cell[0] if cell else None, result
            ),
        )
        cell.append(att)
        self.pending.append(att)

    @loop_only
    def retry(self, reason: str, failed: dict) -> bool:
        """Pick another replica and re-send; False when the retry budget
        (attempts, candidates, deadline) is exhausted."""
        if self.attempts >= self.app.max_attempts:
            return False
        if time.monotonic() >= self.deadline_mono:
            return False  # the deadline timer is about to answer
        rep = self.app.registry.pick(exclude=self.tried)
        if rep is None:
            return False
        FLEET_RETRIES.inc(reason=reason)
        self.trace.note(retried=reason)
        self.dispatch(rep)
        return True

    # -- timers (loop thread) ------------------------------------------------

    @loop_only
    def on_deadline(self) -> None:
        if not self._claim():
            return
        self.app.finish(
            self, "timeout", 504,
            body=json.dumps({
                "error": f"timed out after {self.deadline_s:g}s "
                "(no replica answered in budget)",
            }).encode(),
        )

    @loop_only
    def on_hedge(self) -> None:
        """Hedge delay expired with no reply: fire a duplicate against a
        replica not yet tried (if one is in rotation). ``pick`` falls
        back to already-tried replicas when nothing else is ready —
        right for retries, wrong here: hedging a slow replica with a
        duplicate to ITSELF would double the load on the one struggling
        server, so an exhausted pool means no hedge. The hedge is an
        upstream attempt like any other and counts against
        ``max_attempts`` — with the cap already spent, firing one would
        exceed the operator's per-request attempt budget exactly when
        the fleet is slow."""
        if self.done or self.hedged:
            return
        if self.attempts >= self.app.max_attempts:
            return
        rep = self.app.registry.pick(exclude=self.tried)
        if rep is None or rep["id"] in self.tried:
            return
        self.hedged = True
        FLEET_HEDGES.inc()
        self.trace.note(hedged=True)
        self.dispatch(rep)

    # -- the upstream completion (loop thread) --------------------------------

    @loop_only
    def on_upstream(self, rep: dict, t0: float, att, result) -> None:
        """One attempt resolved: ``result`` is a ``protocol.
        HttpResponse`` or an ``UpstreamError``. The replica's load
        signals settle first (outstanding always; latency only when it
        actually answered), then the retry/hedge/deadline race."""
        rid = rep["id"]
        answered = not isinstance(result, Exception)
        self.app.registry.note_complete(
            rid, (time.monotonic() - t0) if answered else None
        )
        if att is not None:
            if att in self.pending:
                self.pending.remove(att)
            # One pooled ride per reused attempt; one fresh TCP connect
            # per non-reused start AND per transparent resend (a fresh
            # attempt that got resent opened TWO connections) — kept
            # equal to the pool's own opened/reused totals so /metrics
            # and /healthz tell one story.
            if att.reused:
                _CONN_EVENT["reused"].inc()
            opened = (0 if att.reused else 1) + (1 if att.resent else 0)
            if opened:
                _CONN_EVENT["opened"].inc(opened)
        if not answered:
            self._upstream_result(rep, "conn_error")
            self.app.registry.mark_failure(
                rid, f"{type(result).__name__}: {result}"
            )
            if self.done:
                return
            if not self.retry("conn_error", rep) and self._claim():
                self.app.finish(
                    self, "error", 503,
                    body=json.dumps({
                        "error": "no replica answered "
                        f"(last: {type(result).__name__})",
                    }).encode(),
                    headers={"Retry-After": "1"}, replica=rid,
                )
            return
        code, up_headers, data = result.code, result.headers, result.body
        if code == 200:
            self._upstream_result(rep, "ok")
            self.app.registry.mark_success(rid)
            won_hedge = self.hedged and rid != self.first_replica
            if not self._claim():
                return  # the other attempt (or the deadline) answered
            if won_hedge:
                FLEET_HEDGE_WINS.inc()
            self.app.finish(
                self, "ok", 200, body=data, upstream_headers=up_headers,
                replica=rid,
            )
            return
        if code == 503:
            self._upstream_result(rep, "shed")
            self.last_retry_after = up_headers.get("retry-after")
            # A shedding replica is HEALTHY (explicit admission control
            # or degraded mode) — not a breaker strike; the prober
            # rotates it out if /readyz agrees. Prefer another replica
            # right now.
            if self.done:
                return
            if self.retry("shed", rep):
                return
            if self._try_backoff_retry(rep):
                return
            if self._claim():
                self.app.finish(
                    self, "shed", 503, body=data,
                    upstream_headers=up_headers, replica=rid,
                )
            return
        if code >= 500:
            self._upstream_result(rep, "server_error")
            if code != 504:
                # A 504 is the replica's own deadline verdict on THIS
                # request — most of the budget is gone, and the miss says
                # nothing about the replica's health.
                self.app.registry.mark_failure(rid, f"http_{code}")
                if self.done:
                    return
                if self.retry("server_error", rep):
                    return
            if self._claim():
                self.app.finish(
                    self, "timeout" if code == 504 else "error", code,
                    body=data, upstream_headers=up_headers,
                    replica=rid,
                )
            return
        # 4xx: the client's fault travels back unchanged — a malformed
        # patient stays malformed on every replica; retrying would just
        # burn fleet capacity on garbage.
        self._upstream_result(rep, "client_error")
        if self._claim():
            self.app.finish(
                self, "bad_request", code, body=data,
                upstream_headers=up_headers, replica=rid,
            )

    @loop_only
    def _try_backoff_retry(self, rep: dict) -> bool:
        """Everything in rotation already shed this request: honor the
        upstream ``Retry-After`` (bounded by the remaining budget) and
        try once more — the router-side version of loadgen's patient
        client. False when the budget cannot cover the wait."""
        if self.attempts >= self.app.max_attempts:
            return False
        try:
            wait_s = float(self.last_retry_after or 0.0)
        except ValueError:
            wait_s = 0.0
        wait_s = max(0.05, wait_s)
        if time.monotonic() + wait_s >= self.deadline_mono - 0.05:
            return False
        self.attempts += 1
        FLEET_RETRIES.inc(reason="shed_backoff")

        def fire():
            if self.done:
                return
            target = self.app.registry.pick() or rep
            self._send(target)

        self.app.httpd.call_later(wait_s, fire)
        return True

    @staticmethod
    def _upstream_result(rep: dict, result: str) -> None:
        _UP_RESULT[result].inc()
        _replica_counter(rep["id"], result).inc()


class _RouterApp:
    """The application behind the router's event loop (see module
    docstring for the endpoint map)."""

    def __init__(self, handle: "RouterHandle", request_timeout_s: float,
                 hedge_s: float, max_attempts: int, quiet: bool) -> None:
        self.handle = handle
        self.registry = handle.registry
        self.recorder = handle.recorder
        self.request_timeout_s = float(request_timeout_s)
        self.hedge_s = float(hedge_s)
        self.max_attempts = int(max_attempts)
        self.quiet = quiet
        # Both bound by make_router after the listener exists.
        self.httpd = None
        self.upstream: UpstreamPool | None = None
        self._addrs: dict[str, tuple[str, int]] = {}
        # Monotonic: feeds /healthz uptime_seconds, which is duration
        # arithmetic (rule monotonic-clock).
        self.started_monotonic = time.monotonic()

    def replica_addr(self, url: str) -> tuple[str, int]:
        """Replica url → (host, port), cached — one urlparse per replica
        lifetime instead of one per attempt on the loop."""
        addr = self._addrs.get(url)
        if addr is None:
            u = urllib.parse.urlparse(url)
            addr = self._addrs[url] = (u.hostname or "127.0.0.1",
                                       u.port or 80)
        return addr

    # -- transport interface -------------------------------------------------

    @loop_only
    def handle_request(self, req, rsp) -> None:
        if not self.quiet:
            import sys

            print(f"router {req.method} {req.target}", file=sys.stderr)
        if req.method == "POST":
            if req.path == "/predict":
                self._predict(req, rsp)
            elif req.path == "/fleet/replicas":
                self._post_replicas(req, rsp)
            elif req.path == "/fleet/deploy":
                self._post_deploy(req, rsp)
            else:
                rsp.send_json(
                    404, {"error": f"no such path: {req.target}"},
                    close=True,
                )
        elif req.method == "GET":
            self._get(req, rsp)
        else:
            rsp.send_json(
                501, {"error": f"unsupported method {req.method}"},
                close=True,
            )

    @loop_only
    def handle_protocol_error(self, exc, rsp) -> None:
        rsp.send_json(exc.code, {"error": exc.message}, close=True)

    # -- data path -----------------------------------------------------------

    @loop_only
    def _predict(self, req, rsp) -> None:
        trace = reqtrace.RequestTrace(
            reqtrace.sanitize_request_id(req.get_header("x-request-id"))
        )
        trace.add_phase("parse", trace.t_start, time.perf_counter())
        deadline_s = self.request_timeout_s
        raw_deadline = req.get_header("x-request-deadline-ms")
        if raw_deadline:
            try:
                client_s = float(raw_deadline) / 1000.0
            except ValueError:
                client_s = 0.0
            if client_s > 0.0:
                deadline_s = min(deadline_s, client_s)
        pin = (req.get_header("x-serve-path") or "").strip().lower() or None
        job = _ProxyJob(self, trace, rsp, req.body, pin, deadline_s)
        job.start()

    @loop_only
    def finish(
        self, job: _ProxyJob, outcome: str, code: int, body: bytes,
        upstream_headers: dict[str, str] | None = None,
        headers: dict[str, str] | None = None,
        replica: str | None = None,
    ) -> None:
        """The single exit for a routed request: reply, stamp the trace
        (route = admission → first dispatch is folded into upstream
        here; the phases partition admission → reply), count, record."""
        trace = job.trace
        t_up_end = time.perf_counter()
        trace.add_phase("upstream", job.t_route0, t_up_end)
        out_headers = dict(headers or {})
        if upstream_headers:
            for name in _PASSTHROUGH_HEADERS:
                if name in upstream_headers:
                    out_headers[_canonical(name)] = upstream_headers[name]
            if "retry-after" in upstream_headers and code == 503:
                out_headers["Retry-After"] = upstream_headers["retry-after"]
        if replica is not None:
            out_headers.setdefault("X-Replica", replica)
            trace.note(replica=replica)
        trace.note(attempts=job.attempts)
        job.responder.send(
            code, body, "application/json",
            headers=out_headers, request_id=trace.request_id,
        )
        trace.add_phase("respond", t_up_end, time.perf_counter())
        trace.finish(
            "ok" if outcome == "ok" else outcome,
            error=None if outcome == "ok" else f"http_{code}",
        )
        _REQ_OUTCOME[outcome].inc()
        _LATENCY.observe(trace.total_s)
        if outcome != "bad_request":
            # Fleet-level SLO: burn accounted where clients experience
            # it. A malformed request is the client's fault — it spends
            # no server error budget (same exclusion the replica-side
            # tracker applies to non-admitted requests).
            self.handle.fleet_slo.observe(trace.total_s, outcome == "ok")
        self.recorder.record(trace)
        if self.handle.capture_feed is not None and outcome == "ok":
            # Continual-learning tap (learn.capture): every SERVED row
            # lands in the bounded recent-cohort window. Raw bytes, no
            # parse — validation happens once, at refit time. Queued to
            # the feed's writer thread: the loop never pays a shard
            # rotation's fsync, and capture latency is never client
            # latency.
            self.handle.capture_feed.append(job.body)

    # -- control plane --------------------------------------------------------

    @loop_only
    def _get(self, req, rsp) -> None:
        path = req.path
        if path == "/healthz":
            snap = self.registry.snapshot()
            ready = sum(1 for r in snap if r["in_rotation"])
            rsp.send_json(200, {
                "status": "ok" if ready else "no_ready_replicas",
                "role": "fleet-router",
                "replicas_total": len(snap),
                "replicas_ready": ready,
                "deploy": self.handle.deploy_status,
                # Continual-learning tap state (learn.capture), so `cli
                # learn status` can see the refit's data window from the
                # same probe it already polls. None when capture is off.
                "capture": (
                    self.handle.capture.stats()
                    if self.handle.capture is not None else None
                ),
                # The loop-owned upstream pool: connection reuse is the
                # data plane's health in one glance (opened ≈ replicas
                # means keep-alive held; opened ≈ requests means it
                # didn't).
                "upstream": (
                    self.upstream.stats()
                    if self.upstream is not None else None
                ),
                # Alerting plane summary (obs.alerts): rule counts and
                # the worst firing severity, so the probe every
                # supervisor already polls carries "is anything paging".
                # None when the alert engine is disabled.
                "alerts": (
                    self.handle.alerts.summary()
                    if self.handle.alerts is not None else None
                ),
                "uptime_seconds": round(
                    time.monotonic() - self.started_monotonic, 3
                ),
            })
        elif path == "/readyz":
            ready = self.registry.ready_count()
            rsp.send_json(
                200 if ready else 503,
                {
                    "ready": ready > 0,
                    "reasons": [] if ready else ["no ready replicas"],
                    "replicas_ready": ready,
                },
            )
        elif path == "/fleet/replicas":
            rsp.send_json(200, {"replicas": self.registry.snapshot()})
        elif path == "/fleet/deploy":
            rsp.send_json(200, {"deploy": self.handle.deploy_status})
        elif path == "/debug/requests":
            rid = req.query_param("id", "")
            if rid:
                snap = self.recorder.lookup(rid)
                if snap is None:
                    rsp.send_json(404, {
                        "error": f"request id not indexed: {rid}",
                    })
                else:
                    rsp.send_json(200, {"request": snap})
                return
            try:
                n = int(req.query_param("n", "64"))
            except ValueError:
                rsp.send_json(400, {"error": "n must be an integer"})
                return
            rsp.send_json(200, {
                "stats": self.recorder.stats(),
                "requests": self.recorder.snapshot(n),
            })
        elif path == "/fleet/alerts":
            # In-memory read — inline is fine (the engine state is a
            # handful of dicts under no I/O).
            if self.handle.alerts is None:
                rsp.send_json(200, {
                    "enabled": False, "active": [], "summary": None,
                })
                return
            snap = self.handle.alerts.snapshot()
            rsp.send_json(200, {
                "enabled": True,
                "active": snap["active"],
                "summary": self.handle.alerts.summary(),
                "rules": snap["rules"],
            })
        elif path == "/debug/history":
            store = self.handle.history
            if store is None:
                rsp.send_json(200, {"enabled": False, "families": {}})
                return
            family = req.query_param("family", "")
            if not family:
                rsp.send_json(200, {
                    "enabled": True,
                    "families": store.families(),
                    "stats": store.stats(),
                })
                return
            try:
                window = float(req.query_param("window", "0") or 0)
            except ValueError:
                rsp.send_json(400, {"error": "window must be a number"})
                return
            now = time.time()  # graftcheck: disable=monotonic-clock
            rsp.send_json(200, store.query(
                family, window if window > 0 else None, now,
            ))
        elif path == "/fleet/metrics":
            # The scrape blocks up to timeout_s per replica — on its own
            # short-lived thread (the /debug/profile pattern), never the
            # event loop that carries the data plane.
            threading.Thread(
                target=self._fleet_metrics,
                args=(req.query_param("format", "prometheus"), rsp),
                name="fleet-metrics-scrape", daemon=True,
            ).start()
        elif path == "/fleet/trace":
            try:
                n = int(req.query_param("n", "64"))
            except ValueError:
                rsp.send_json(400, {"error": "n must be an integer"})
                return
            # Same off-loop discipline: the join fetches one replica
            # trace per sampled request over blocking HTTP.
            threading.Thread(
                target=self._fleet_trace, args=(n, rsp),
                name="fleet-trace-join", daemon=True,
            ).start()
        elif path == "/metrics":
            if req.query_param("format", "prometheus") == "json":
                rsp.send_json(200, {
                    "runtime": REGISTRY.snapshot(),
                    "replicas": self.registry.snapshot(),
                })
            else:
                rsp.send(
                    200, REGISTRY.render_prometheus().encode(),
                    "text/plain; version=0.0.4",
                )
        else:
            rsp.send_json(404, {"error": f"no such path: {path}"})

    def _fleet_metrics(self, fmt: str, rsp) -> None:
        """Thread target for GET /fleet/metrics (off-loop; the Responder
        is thread-safe and exactly-once)."""
        try:
            text, summary = self.handle.scraper.render_fleet_page()
        except Exception as exc:
            rsp.send_json(500, {"error": f"fleet scrape failed: {exc}"})
            return
        if fmt == "json":
            rsp.send_json(200, {"summary": summary, "page": text})
        else:
            rsp.send(200, text.encode(), "text/plain; version=0.0.4")

    def _fleet_trace(self, n: int, rsp) -> None:
        """Thread target for GET /fleet/trace: join the router's last
        ``n`` tail-sampled traces with their replica-side phases into
        one Perfetto-loadable export (the response body IS the trace
        JSON — save it to a file and load it)."""
        try:
            samples = self.recorder.snapshot(n)
            urls = {
                r["id"]: r["url"] for r in self.registry.snapshot()
            }
            export = fleettrace.join_fleet_trace(
                samples, urls, self.handle.clock_sync,
            )
        except Exception as exc:
            rsp.send_json(500, {
                "error": f"fleet trace join failed: {exc}",
            })
            return
        rsp.send_json(200, export)

    @loop_only
    def _post_replicas(self, req, rsp) -> None:
        """Registration endpoint (``cli serve --register`` posts here):
        ``{"id", "url"}`` adds a replica, ``{"deregister": id}`` removes
        one, ``{"hold": id}`` / ``{"release": id}`` toggle the admin
        hold — the out-of-process face of ``registry.hold`` the
        lifecycle manager's drain-first retirement needs (an in-process
        deploy controller calls the registry directly). Probing begins
        on the next prober tick; rotation in follows the first ready
        probe — a registered-but-cold replica never receives traffic."""
        try:
            body = json.loads(req.body or b"{}")
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
            if "deregister" in body:
                found = self.registry.deregister(str(body["deregister"]))
                rsp.send_json(200, {"deregistered": found})
                return
            if "hold" in body:
                rsp.send_json(200, {
                    "held": self.registry.hold(str(body["hold"])),
                })
                return
            if "release" in body:
                rsp.send_json(200, {
                    "released": self.registry.release(
                        str(body["release"])
                    ),
                })
                return
            rid, url = body.get("id"), body.get("url")
            if not rid or not url:
                raise ValueError(
                    'expected {"id": ..., "url": ...}, {"deregister": id}, '
                    '{"hold": id}, or {"release": id}'
                )
        except (ValueError, json.JSONDecodeError) as exc:
            rsp.send_json(400, {"error": str(exc)})
            return
        rsp.send_json(200, {"replica": self.registry.register(
            str(rid), str(url)
        )})

    @loop_only
    def _post_deploy(self, req, rsp) -> None:
        """Start a rolling deploy (``fleet.deploy.rolling_deploy``) over
        every registered replica; replies when the rollout is DONE.
        Single-flight — a rollout in progress answers 409."""
        try:
            body = json.loads(req.body or b"{}")
            model = body.get("model") if isinstance(body, dict) else None
            if not model or not isinstance(model, str):
                raise ValueError('expected {"model": "checkpoint path"}')
        except (ValueError, json.JSONDecodeError) as exc:
            rsp.send_json(400, {"error": str(exc)})
            return
        if not self.handle._deploy_lock.acquire(blocking=False):
            rsp.send_json(409, {
                "error": "a rolling deploy is already in progress",
                "deploy": self.handle.deploy_status,
            })
            return

        def run():
            from machine_learning_replications_tpu.fleet.deploy import (
                rolling_deploy,
            )

            try:
                report = rolling_deploy(
                    self.registry, model,
                    status_cb=self.handle._set_deploy_status,
                )
            except Exception as exc:
                report = {
                    "result": "failed",
                    "error": f"{type(exc).__name__}: {exc}",
                }
                self.handle._set_deploy_status(report)
            finally:
                self.handle._deploy_lock.release()
            FLEET_DEPLOYS.inc(result=report.get("result", "failed"))
            rsp.send_json(
                200 if report.get("result") == "ok" else 500,
                {"deploy": report},
            )

        threading.Thread(
            target=run, name="fleet-deploy", daemon=True
        ).start()


def _canonical(lower_name: str) -> str:
    """lower-cased wire header name → canonical echo casing."""
    return {
        "x-replica": "X-Replica",
        "x-model-version": "X-Model-Version",
        "x-serve-path": "X-Serve-Path",
    }.get(lower_name, lower_name)


class RouterHandle:
    """A running front-door router: registry + prober + loop-owned
    upstream pool + event-loop HTTP listener."""

    def __init__(self, registry, prober, recorder,
                 httpd=None, capture=None, clock_sync=None,
                 scraper=None, fleet_slo=None) -> None:
        self.registry = registry
        self.prober = prober
        self.recorder = recorder
        self.httpd = httpd
        self.upstream: UpstreamPool | None = None
        # The fleet telemetry plane (obs.fleettrace / obs.fleetmetrics):
        # per-replica clock-offset estimator, /fleet/metrics scraper,
        # and the fleet-level SLO tracker fed from finish().
        self.clock_sync = clock_sync or fleettrace.ClockSync()
        self.scraper = scraper or fleetmetrics.FleetScraper(registry)
        self.fleet_slo = fleet_slo or fleetmetrics.fleet_slo_tracker()
        self.capture = capture  # learn.capture.CohortCapture or None
        self.capture_feed: _CaptureFeed | None = (
            _CaptureFeed(capture) if capture is not None else None
        )
        # The alerting plane (obs.timeseries / obs.alerts /
        # obs.incident): history ring store, its sampler thread, the
        # rule engine the sampler ticks, and the incident capturer
        # firings trigger. All optional; wired by make_router.
        self.history = None
        self.sampler = None
        self.alerts = None
        self.incidents = None
        self.deploy_status: dict | None = None
        self._deploy_lock = threading.Lock()
        self._thread: threading.Thread | None = None

    @cross_thread
    def _set_deploy_status(self, status: dict) -> None:
        self.deploy_status = status

    @property
    def address(self) -> tuple[str, int]:
        return self.httpd.server_address[:2]

    def serve_forever(self) -> None:
        self.httpd.serve_forever()

    def start_background(self) -> "RouterHandle":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, name="fleet-router",
            daemon=True,
        )
        self._thread.start()
        return self

    @cross_thread
    def shutdown(self) -> None:
        if self.sampler is not None:
            self.sampler.close()
        self.prober.close()
        self.httpd.shutdown()
        self.httpd.server_close()  # teardown closes the upstream pool too
        if self.capture_feed is not None:
            self.capture_feed.close()
        if self.incidents is not None:
            self.incidents.close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None


def make_router(
    host: str = "127.0.0.1",
    port: int = 8080,
    replicas: list[tuple[str, str]] | None = None,
    request_timeout_s: float = 30.0,
    hedge_ms: float = 0.0,
    max_attempts: int = 3,
    probe_interval_s: float = 0.5,
    probe_timeout_s: float = 2.0,
    fail_threshold: int = 2,
    recover_probes: int = 2,
    breaker_failures: int = 3,
    trace_capacity: int = 256,
    tail_quantile: float = 0.99,
    idle_timeout_s: float = 5.0,
    max_connections: int = 8192,
    backlog: int = 1024,
    reuse_port: bool = False,
    quiet: bool = True,
    start_prober: bool = True,
    capture_dir: str | None = None,
    capture_rows_per_shard: int = 4096,
    capture_max_shards: int = 8,
    history_interval_s: float = 10.0,
    history_fleet_page: bool = True,
    alert_rules: list | None = None,
    alerts_enabled: bool = True,
    incident_dir: str | None = None,
    incident_min_interval_s: float = 60.0,
    incident_retention: int = 8,
) -> RouterHandle:
    """Assemble the front-door router and bind its listener (not yet
    serving — call ``serve_forever`` or ``start_background``).
    ``replicas`` seeds the registry with static ``(id, url)`` members;
    dynamic members register themselves over ``POST /fleet/replicas``
    (``cli serve --register``). ``hedge_ms`` > 0 enables tail hedging;
    ``max_attempts`` bounds retry fan-out per request. ``reuse_port``
    binds with ``SO_REUSEPORT`` for the multi-worker router
    (``cli fleet router --workers N``). ``start_prober`` exists for
    tests that drive ``prober.tick()`` by hand. ``capture_dir`` enables
    the continual-learning cohort tap (``learn.capture``): every served
    /predict body lands in a bounded rotating JSONL window there
    (~``capture_rows_per_shard`` × ``capture_max_shards`` recent rows)
    — the retrain's data source (docs/CONTINUAL.md).

    ``history_interval_s`` > 0 starts the telemetry history sampler
    (``obs.timeseries``): every tick snapshots the router's registry —
    and, with ``history_fleet_page``, the scraped+merged fleet page —
    into the bounded ring store behind ``GET /debug/history``.
    ``alerts_enabled`` evaluates ``alert_rules`` (Rule objects; None →
    ``obs.alerts.default_rules("router")``) on the same tick, served on
    ``GET /fleet/alerts``; ``incident_dir`` additionally captures a
    flight-recorder bundle when a rule fires (``obs.incident``,
    docs/OBSERVABILITY.md "Alerting & incidents")."""
    registry = ReplicaRegistry(
        fail_threshold=fail_threshold,
        recover_probes=recover_probes,
        breaker_failures=breaker_failures,
    )
    for rid, url in replicas or []:
        registry.register(rid, url)
    clock_sync = fleettrace.ClockSync()
    prober = HealthProber(
        registry, interval_s=probe_interval_s, timeout_s=probe_timeout_s,
        clock_sync=clock_sync,
    )
    recorder = reqtrace.FlightRecorder(
        capacity=trace_capacity, tail_quantile=tail_quantile
    )
    capture = None
    if capture_dir is not None:
        from machine_learning_replications_tpu.learn.capture import (
            CohortCapture,
        )

        capture = CohortCapture(
            capture_dir,
            rows_per_shard=capture_rows_per_shard,
            max_shards=capture_max_shards,
        )
    handle = RouterHandle(
        registry, prober, recorder, capture=capture,
        clock_sync=clock_sync,
        scraper=fleetmetrics.FleetScraper(
            registry, timeout_s=probe_timeout_s,
        ),
    )
    # Stale-series hygiene: a deregistered (or replaced) replica's
    # per-replica gauge series retire with it instead of lingering at
    # their last value (docs/OBSERVABILITY.md "Fleet telemetry").
    registry.add_retire_listener(handle.scraper.forget)
    registry.add_retire_listener(clock_sync.forget)
    if history_interval_s > 0:
        handle.history = obs_timeseries.TimeSeriesStore(
            interval_s=history_interval_s,
        )
        if alerts_enabled:
            rules = (
                alert_rules if alert_rules is not None
                else obs_alerts.default_rules("router")
            )
            handle.alerts = obs_alerts.AlertEngine(rules, handle.history)
        if incident_dir is not None and handle.alerts is not None:
            handle.incidents = obs_incident.IncidentCapturer(
                incident_dir,
                store=handle.history,
                collectors={
                    "requests": lambda: recorder.snapshot(64),
                    "replicas": registry.snapshot,
                    "metrics": REGISTRY.snapshot,
                    "fleet_trace": lambda: fleettrace.join_fleet_trace(
                        recorder.snapshot(64),
                        {
                            r["id"]: r["url"]
                            for r in registry.snapshot()
                        },
                        clock_sync,
                    ),
                },
                min_interval_s=incident_min_interval_s,
                retention=incident_retention,
            )
    app = _RouterApp(
        handle, request_timeout_s,
        hedge_s=hedge_ms / 1000.0, max_attempts=max_attempts, quiet=quiet,
    )
    # Backlog 1024, not the replica-side 128: a replica keeps its
    # backlog small so bursts hit the batcher's explicit admission
    # decision (the r6 lesson), but the router IS the front door — a
    # thousand keep-alive clients connecting at once is its normal
    # startup, its admission control is the deadline/shed machinery
    # after accept, and a refused SYN costs the client a ~1 s
    # retransmit stall that reads as router latency.
    try:
        handle.httpd = EventLoopHttpServer(
            (host, port), app,
            idle_timeout_s=idle_timeout_s,
            max_connections=max_connections,
            backlog=backlog,
            reuse_port=reuse_port,
        )
    except BaseException:
        # A bind failure must not leak the already-started capture feed
        # thread and its open shard — a supervisor retrying startup on
        # a contended port would accumulate one orphan per attempt.
        if handle.capture_feed is not None:
            handle.capture_feed.close()
        raise
    app.httpd = handle.httpd
    # The upstream leg lives on the same loop as the listener: one
    # thread owns every socket end to end (module docstring).
    handle.upstream = app.upstream = UpstreamPool(
        handle.httpd, idle_timeout_s=idle_timeout_s,
    )
    journal.event(
        "fleet_router_started",
        address=list(handle.httpd.server_address[:2]),
        replicas=[rid for rid, _ in (replicas or [])],
    )
    if start_prober:
        prober.start()
    if handle.history is not None:
        scraper = handle.scraper
        engine, capturer = handle.alerts, handle.incidents

        def _collect() -> dict:
            fams = obs_timeseries.collect_registry()
            if history_fleet_page:
                # The merged fleet page rides the same tick: summed
                # counters and per-replica appended gauges become
                # history too, and the scrape's staleness marking runs
                # even when nobody polls /fleet/metrics — which is what
                # keeps the fleet_replica_stale rule honest.
                try:
                    pages, _summary = scraper.scrape()
                    merged, _rejected = fleetmetrics.merge_expositions(
                        pages,
                        drop=frozenset(
                            fam.name for fam in REGISTRY.families()
                        ),
                    )
                    fams.update(merged)
                except Exception:
                    pass  # absence IS the signal staleness rules watch
            return fams

        def _tick(now: float) -> None:
            if engine is None:
                return
            for transition in engine.evaluate(now):
                if capturer is not None:
                    capturer.maybe_capture(transition)

        handle.sampler = obs_timeseries.HistorySampler(
            handle.history, _collect,
            interval_s=history_interval_s, on_tick=_tick,
        ).start()
    return handle
