"""Rolling deploys: a new checkpoint version across the fleet, one
replica at a time, with zero client-visible downtime.

The lifecycle per replica (docs/FLEET.md "Deploy lifecycle"):

  1. **Capacity gate.** Refuse to touch a replica unless at least
     ``min_in_rotation`` (default 1) OTHER replicas stay in rotation
     (waiting up to ``capacity_timeout_s`` for capacity to appear) — a
     rollout must never take the last server out from under live
     traffic. Up to ``concurrency`` replicas are held and warmed **at
     once** inside that gate: a one-at-a-time rollout pays O(N) serial
     warmups on a large fleet, while the gate is about how much
     capacity may be *missing*, not about how many swaps are in flight
     — so waves of ``min(concurrency, in_rotation − min_in_rotation)``
     replicas swap together, and the rotation capacity observed by the
     router never drops below the gate.
  2. **Hold.** ``registry.hold`` removes the replica from routing while
     it keeps serving its in-flight work; new traffic flows to the rest
     of the fleet.
  3. **Warm swap.** One long ``POST /admin/deploy`` to the replica
     (``serve.server`` — load with integrity verification and the
     last-known-good rollback net, build + warm the new engine off the
     request path, parity-probe, atomic swap). The reply carries the
     achieved version and whether the restore rolled back. When the
     target checkpoint ships an AOT executable bundle (docs/AOT.md) the
     warm step restores serialized executables instead of compiling the
     ladder, so the per-replica hold window — what paces the whole
     rollout — is deserialize-scale, not compile-scale.
  4. **Verify + release.** Poll the replica's ``/readyz`` until it
     reports ready AT the achieved version, release the hold, and wait
     for the registry (probe-fed) to rotate it back in before moving on.

A replica that reports ``rolled_back`` (corrupt target checkpoint → it
restored the retained last-known-good) or a version other than the
rollout target **stops the rollout**: the remaining replicas keep the
old version, the report says ``rolled_back``, and the journal carries
the full arc (``fleet_deploy_start`` → per-replica
``fleet_deploy_replica`` → ``fleet_deploy_done``). A replica whose swap
fails outright keeps its previous engine (the replica-side contract)
and the rollout stops with ``result="failed"`` — in every case the
fleet is left serving *some* consistent, parity-verified version.

The rollout's target version is read from the checkpoint's
``integrity.json`` when the controller can see the path (a local JSON
read — deliberately NOT ``persist.orbax_io``, which imports jax and
orbax; the router process stays accelerator-free); on a router without
filesystem access to the checkpoint, the first replica's achieved
version becomes the target the rest must match.
"""

from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.request

from machine_learning_replications_tpu.obs import journal


def manifest_version(path: str | os.PathLike) -> int | None:
    """The monotonic version id in a checkpoint's ``integrity.json`` —
    the jax-free mirror of ``persist.checkpoint_version`` for the
    router process. None when unreadable or unversioned."""
    try:
        with open(os.path.join(os.fspath(path), "integrity.json")) as f:
            v = json.load(f).get("version")
        return int(v) if v is not None else None
    except (OSError, ValueError, json.JSONDecodeError, TypeError):
        return None


def _post_admin_deploy(url: str, model: str, timeout_s: float) -> dict:
    """The replica-side warm swap; returns its final deploy status dict.
    Raises ``RuntimeError`` with the replica's error on failure."""
    req = urllib.request.Request(
        url.rstrip("/") + "/admin/deploy",
        data=json.dumps({"model": model}).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return json.loads(resp.read())["deploy"]
    except urllib.error.HTTPError as exc:
        try:
            body = json.loads(exc.read() or b"{}")
        except (ValueError, OSError):
            body = {}
        raise RuntimeError(
            f"replica deploy failed (http {exc.code}): "
            f"{body.get('error', 'no detail')}"
        ) from exc


def _wait(pred, timeout_s: float, what: str, poll_s: float = 0.1) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(poll_s)
    raise RuntimeError(f"timed out waiting for {what}")


def _deploy_one(
    registry, rid: str, url: str, model_path: str,
    admin_timeout_s: float, ready_timeout_s: float,
) -> dict:
    """One replica's hold → warm swap → verify → release arc (steps 2–4
    of the lifecycle). The capacity gate (step 1) is the caller's wave
    planner. Returns the step dict; never raises — the hold is released
    on every exit path so a failed swap cannot strand a healthy replica
    out of rotation."""
    from machine_learning_replications_tpu.fleet.health import probe_replica

    step: dict = {"replica": rid, "result": "ok"}
    try:
        # 2. Hold: out of routing, still serving in-flight work.
        registry.hold(rid)
        # 3. The replica-side warm swap (load → warm → parity → swap).
        status = _post_admin_deploy(url, model_path, admin_timeout_s)
        achieved = status.get("version")
        step.update(
            achieved_version=achieved,
            rolled_back=bool(status.get("rolled_back")),
            seconds=status.get("seconds"),
        )
        # 4. Ready at the achieved version, then back into rotation.
        _wait(
            lambda: (
                lambda p: p["ok"] and p["ready"]
                and p["version"] == achieved
            )(probe_replica(url)),
            ready_timeout_s,
            f"{rid!r} ready at version {achieved}",
        )
        registry.release(rid)
        _wait(
            lambda: (registry.get(rid) or {}).get("in_rotation"),
            ready_timeout_s, f"{rid!r} back in rotation",
        )
    except Exception as exc:
        registry.release(rid)
        step.update(
            result="failed", error=f"{type(exc).__name__}: {exc}"
        )
    return step


def rolling_deploy(
    registry,
    model_path: str,
    admin_timeout_s: float = 600.0,
    ready_timeout_s: float = 60.0,
    capacity_timeout_s: float = 30.0,
    concurrency: int = 1,
    min_in_rotation: int = 1,
    status_cb=None,
) -> dict:
    """Drive the checkpoint at ``model_path`` across every registered
    replica (see module docstring). Up to ``concurrency`` replicas are
    warm-swapped per wave, never leaving fewer than ``min_in_rotation``
    replicas in rotation. Returns the rollout report; never raises for
    per-replica failures — the report's ``result`` is ``ok`` /
    ``rolled_back`` / ``failed``."""
    import threading

    if concurrency < 1 or min_in_rotation < 1:
        raise ValueError("concurrency and min_in_rotation must be >= 1")
    target = manifest_version(model_path)
    t0 = time.perf_counter()  # duration base; "started" is display-only
    report: dict = {
        "kind": "fleet_deploy",
        "model": model_path,
        "target_version": target,
        "concurrency": int(concurrency),
        "replicas": [],
        "result": "ok",
        "started": time.time(),  # graftcheck: disable=monotonic-clock
    }

    def publish(state: str) -> None:
        report["state"] = state
        if status_cb is not None:
            status_cb(dict(report))

    members = registry.snapshot()
    journal.event(
        "fleet_deploy_start", model=model_path, target_version=target,
        concurrency=int(concurrency),
        replicas=[r["id"] for r in members],
    )
    publish("running")
    pending = list(members)
    while pending and report["result"] == "ok":
        # 1. Capacity gate, per WAVE: holding a not-in-rotation replica
        # (probing, out) costs no capacity; each in-rotation member of
        # the wave spends one unit of the headroom above the floor.
        wave: list[dict] = []

        def plan_wave() -> bool:
            wave.clear()
            in_rotation = {
                r["id"] for r in registry.snapshot() if r["in_rotation"]
            }
            headroom = len(in_rotation) - min_in_rotation
            for member in pending:
                if len(wave) >= concurrency:
                    break
                if member["id"] in in_rotation:
                    if headroom <= 0:
                        continue
                    headroom -= 1
                wave.append(member)
            return bool(wave)

        try:
            _wait(
                plan_wave, capacity_timeout_s,
                f"{min_in_rotation} in-rotation replica(s) of spare "
                "capacity before the next deploy wave",
            )
        except RuntimeError as exc:
            report["result"] = "failed"
            report["error"] = str(exc)
            break
        publish(
            "deploying " + ",".join(m["id"] for m in wave)
        )
        steps: list[dict | None] = [None] * len(wave)
        threads = []
        for i, member in enumerate(wave):
            rid, url = member["id"], member["url"]
            if registry.get(rid) is None:
                steps[i] = {
                    "replica": rid, "result": "skipped",
                    "error": "deregistered mid-rollout",
                }
                continue

            def run(i=i, rid=rid, url=url):
                steps[i] = _deploy_one(
                    registry, rid, url, model_path,
                    admin_timeout_s, ready_timeout_s,
                )

            t = threading.Thread(
                target=run, name=f"fleet-deploy-{rid}", daemon=True,
            )
            threads.append(t)
            t.start()
        for t in threads:
            t.join()
        for member, step in zip(wave, steps):
            pending.remove(member)
            if step is None:  # a thread died before writing — treat failed
                step = {
                    "replica": member["id"], "result": "failed",
                    "error": "deploy worker died",
                }
            report["replicas"].append(step)
            achieved = step.get("achieved_version")
            if step["result"] == "ok" and target is None and \
                    achieved is not None:
                # No filesystem view of the checkpoint: the first
                # replica's achieved version defines the rollout target.
                target = report["target_version"] = achieved
            if step["result"] == "ok" and (
                step.get("rolled_back")
                or (target is not None and achieved != target)
            ):
                step["result"] = "rolled_back"
            # First bad outcome wins, as in the serial rollout: a later
            # wave member's rollback must not relabel an earlier hard
            # failure (callers branch on failed vs rolled_back).
            if step["result"] == "rolled_back" and report["result"] == "ok":
                report["result"] = "rolled_back"
                report["error"] = (
                    f"replica {step['replica']!r} restored version "
                    f"{achieved} instead of the target {target} "
                    "(corrupt checkpoint rolled back to last-known-good); "
                    "rollout stopped"
                )
            elif step["result"] == "failed" and report["result"] == "ok":
                report["result"] = "failed"
                report["error"] = step["error"]
            journal.event("fleet_deploy_replica", model=model_path, **step)
        # A failure/rollback anywhere in the wave leaves the REST of the
        # fleet on the known-good version (the wave that observed it has
        # already finished its swaps — those replicas stay where their
        # own arc left them, exactly like the serial rollout's).
    report["seconds"] = round(time.perf_counter() - t0, 3)
    journal.event(
        "fleet_deploy_done", model=model_path,
        target_version=report["target_version"],
        result=report["result"], error=report.get("error"),
        seconds=report["seconds"],
    )
    publish("done")
    return report
