"""Rolling deploys: a new checkpoint version across the fleet, one
replica at a time, with zero client-visible downtime.

The lifecycle per replica (docs/FLEET.md "Deploy lifecycle"):

  1. **Capacity gate.** Refuse to touch a replica unless at least one
     OTHER replica is in rotation (waiting up to ``capacity_timeout_s``
     for one to appear) — a rollout must never take the last server out
     from under live traffic.
  2. **Hold.** ``registry.hold`` removes the replica from routing while
     it keeps serving its in-flight work; new traffic flows to the rest
     of the fleet.
  3. **Warm swap.** One long ``POST /admin/deploy`` to the replica
     (``serve.server`` — load with integrity verification and the
     last-known-good rollback net, build + warm the new engine off the
     request path, parity-probe, atomic swap). The reply carries the
     achieved version and whether the restore rolled back.
  4. **Verify + release.** Poll the replica's ``/readyz`` until it
     reports ready AT the achieved version, release the hold, and wait
     for the registry (probe-fed) to rotate it back in before moving on.

A replica that reports ``rolled_back`` (corrupt target checkpoint → it
restored the retained last-known-good) or a version other than the
rollout target **stops the rollout**: the remaining replicas keep the
old version, the report says ``rolled_back``, and the journal carries
the full arc (``fleet_deploy_start`` → per-replica
``fleet_deploy_replica`` → ``fleet_deploy_done``). A replica whose swap
fails outright keeps its previous engine (the replica-side contract)
and the rollout stops with ``result="failed"`` — in every case the
fleet is left serving *some* consistent, parity-verified version.

The rollout's target version is read from the checkpoint's
``integrity.json`` when the controller can see the path (a local JSON
read — deliberately NOT ``persist.orbax_io``, which imports jax and
orbax; the router process stays accelerator-free); on a router without
filesystem access to the checkpoint, the first replica's achieved
version becomes the target the rest must match.
"""

from __future__ import annotations

import json
import os
import time
import urllib.error
import urllib.request

from machine_learning_replications_tpu.obs import journal


def manifest_version(path: str | os.PathLike) -> int | None:
    """The monotonic version id in a checkpoint's ``integrity.json`` —
    the jax-free mirror of ``persist.checkpoint_version`` for the
    router process. None when unreadable or unversioned."""
    try:
        with open(os.path.join(os.fspath(path), "integrity.json")) as f:
            v = json.load(f).get("version")
        return int(v) if v is not None else None
    except (OSError, ValueError, json.JSONDecodeError, TypeError):
        return None


def _post_admin_deploy(url: str, model: str, timeout_s: float) -> dict:
    """The replica-side warm swap; returns its final deploy status dict.
    Raises ``RuntimeError`` with the replica's error on failure."""
    req = urllib.request.Request(
        url.rstrip("/") + "/admin/deploy",
        data=json.dumps({"model": model}).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return json.loads(resp.read())["deploy"]
    except urllib.error.HTTPError as exc:
        try:
            body = json.loads(exc.read() or b"{}")
        except (ValueError, OSError):
            body = {}
        raise RuntimeError(
            f"replica deploy failed (http {exc.code}): "
            f"{body.get('error', 'no detail')}"
        ) from exc


def _wait(pred, timeout_s: float, what: str, poll_s: float = 0.1) -> None:
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(poll_s)
    raise RuntimeError(f"timed out waiting for {what}")


def rolling_deploy(
    registry,
    model_path: str,
    admin_timeout_s: float = 600.0,
    ready_timeout_s: float = 60.0,
    capacity_timeout_s: float = 30.0,
    status_cb=None,
) -> dict:
    """Drive the checkpoint at ``model_path`` across every registered
    replica (see module docstring). Returns the rollout report; never
    raises for per-replica failures — the report's ``result`` is
    ``ok`` / ``rolled_back`` / ``failed``."""
    from machine_learning_replications_tpu.fleet.health import probe_replica

    target = manifest_version(model_path)
    report: dict = {
        "kind": "fleet_deploy",
        "model": model_path,
        "target_version": target,
        "replicas": [],
        "result": "ok",
        "started": time.time(),
    }

    def publish(state: str) -> None:
        report["state"] = state
        if status_cb is not None:
            status_cb(dict(report))

    members = registry.snapshot()
    journal.event(
        "fleet_deploy_start", model=model_path, target_version=target,
        replicas=[r["id"] for r in members],
    )
    publish("running")
    for member in members:
        rid, url = member["id"], member["url"]
        step: dict = {"replica": rid, "result": "ok"}
        report["replicas"].append(step)
        try:
            if registry.get(rid) is None:
                step.update(result="skipped", error="deregistered mid-rollout")
                continue
            # 1. Capacity gate: someone ELSE must be carrying traffic.
            _wait(
                lambda: any(
                    r["in_rotation"] for r in registry.snapshot()
                    if r["id"] != rid
                ),
                capacity_timeout_s,
                f"another in-rotation replica before deploying {rid!r}",
            )
            # 2. Hold: out of routing, still serving in-flight work.
            registry.hold(rid)
            publish(f"deploying {rid}")
            # 3. The replica-side warm swap (load → warm → parity → swap).
            status = _post_admin_deploy(url, model_path, admin_timeout_s)
            achieved = status.get("version")
            rolled_back = bool(status.get("rolled_back"))
            step.update(
                achieved_version=achieved, rolled_back=rolled_back,
                seconds=status.get("seconds"),
            )
            # 4. Ready at the achieved version, then back into rotation.
            _wait(
                lambda: (
                    lambda p: p["ok"] and p["ready"]
                    and p["version"] == achieved
                )(probe_replica(url)),
                ready_timeout_s,
                f"{rid!r} ready at version {achieved}",
            )
            registry.release(rid)
            _wait(
                lambda: (registry.get(rid) or {}).get("in_rotation"),
                ready_timeout_s, f"{rid!r} back in rotation",
            )
            if target is None:
                # No filesystem view of the checkpoint: the first
                # replica's achieved version defines the rollout target.
                target = report["target_version"] = achieved
            if rolled_back or (
                target is not None and achieved != target
            ):
                step["result"] = "rolled_back"
                report["result"] = "rolled_back"
                report["error"] = (
                    f"replica {rid!r} restored version {achieved} instead "
                    f"of the target {target} "
                    "(corrupt checkpoint rolled back to last-known-good); "
                    "rollout stopped"
                )
        except Exception as exc:
            registry.release(rid)
            step.update(
                result="failed", error=f"{type(exc).__name__}: {exc}"
            )
            report["result"] = "failed"
            report["error"] = step["error"]
        finally:
            journal.event("fleet_deploy_replica", model=model_path, **step)
        if report["result"] != "ok":
            break  # leave the rest of the fleet on the known-good version
    report["seconds"] = round(time.time() - report["started"], 3)
    journal.event(
        "fleet_deploy_done", model=model_path,
        target_version=report["target_version"],
        result=report["result"], error=report.get("error"),
        seconds=report["seconds"],
    )
    publish("done")
    return report
