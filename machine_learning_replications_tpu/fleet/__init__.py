"""L7 — the fleet tier: N replicas as one service (docs/FLEET.md).

Everything below this package is replica-side plumbing — the
liveness/readiness split, drain-first shutdown, breaker-aware
``/readyz``, last-known-good rollback, the event-loop transport. The
fleet tier is the layer that composes them into a *service*:

  * ``fleet.registry`` — the replica rotation table: probe-driven
    in/out, per-replica request breakers, admin holds, every transition
    journaled and on ``fleet_*`` metrics.
  * ``fleet.health`` — the ``/readyz`` prober feeding the registry.
  * ``fleet.router`` — the front-door HTTP router (``make_router``):
    the serve transport reused, with per-request retry/hedging, deadline
    propagation, and replica/version header passthrough.
  * ``fleet.deploy`` — rolling deploys of versioned checkpoints
    (``persist.checkpoint_version``), in capacity-gated waves through
    the replica-side ``/admin/deploy`` warm swap, with the
    last-known-good rollback as the safety net.
  * ``fleet.lifecycle`` — the replica lifecycle manager: spawn →
    ready → drain-first retire (hold → settle → SIGTERM → deadline
    SIGKILL) → crash replacement with backoff, every arc journaled.
  * ``fleet.autoscale`` — the load-driven control loop over it:
    router/replica load signals → debounced, cooled-down, bounded
    scale decisions (``cli fleet autoscale``).

Deliberately jax-free: a router process starts in milliseconds and
needs no accelerator stack. Enforced statically — the whole package is
in the import-purity manifest (``analysis/project.py``; graftcheck rule
``import-purity``, docs/ANALYSIS.md), so an import-time jax edge
anywhere in its transitive closure fails CI.
"""

from machine_learning_replications_tpu.fleet.autoscale import (
    AutoscaleDaemon,
    AutoscalePolicy,
    AutoscaleThresholds,
)
from machine_learning_replications_tpu.fleet.deploy import (
    manifest_version,
    rolling_deploy,
)
from machine_learning_replications_tpu.fleet.lifecycle import (
    LifecycleManager,
    ReplicaSpec,
    RouterClient,
)
from machine_learning_replications_tpu.fleet.health import (
    HealthProber,
    probe_replica,
)
from machine_learning_replications_tpu.fleet.registry import (
    Replica,
    ReplicaRegistry,
)
from machine_learning_replications_tpu.fleet.router import (
    RouterHandle,
    make_router,
)

__all__ = [
    "AutoscaleDaemon",
    "AutoscalePolicy",
    "AutoscaleThresholds",
    "HealthProber",
    "LifecycleManager",
    "Replica",
    "ReplicaRegistry",
    "ReplicaSpec",
    "RouterClient",
    "RouterHandle",
    "make_router",
    "manifest_version",
    "probe_replica",
    "rolling_deploy",
]
