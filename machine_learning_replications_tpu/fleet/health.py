"""Periodic ``/readyz`` prober feeding the replica registry.

The replicas already publish exactly the signal a load balancer needs
(PR 5's liveness/readiness split): ``/readyz`` answers 200 only when the
engine is warm, the server is not draining, and the breaker is closed —
and since the fleet tier it also echoes the replica's id and served
checkpoint version. This thread closes the loop: every ``interval_s`` it
GETs each registered replica's ``/readyz`` (bounded by ``timeout_s``)
and reports the verdict to ``ReplicaRegistry.observe_probe``, which owns
all rotation policy. The prober itself decides nothing — it is a clock
plus an HTTP client, so the rotation rules live (and are tested) in one
place.

Runs on its own daemon thread with plain blocking ``urllib`` — probing
is off the router's event loop by construction, and at fleet sizes where
sequential probing would lag the tick, the interval is the knob (or run
several probers over disjoint registries).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request


def probe_replica(url: str, timeout_s: float = 2.0) -> dict:
    """One ``/readyz`` probe: ``{"ok", "ready", "version", "queue_depth",
    "clock_perf", "t_send", "t_recv"}``. ``ok`` is HTTP-level success (an
    explicit 503 is ok=True, ready=False — the replica answered, and said
    no); transport failures are ok=False. ``queue_depth`` (None when the
    replica predates the field) feeds the registry's least-loaded score —
    the probe the rotation already pays for doubles as the cross-router
    load signal. ``clock_perf`` (the replica's monotonic clock echoed in
    the body, None on older replicas) plus the local send/receive stamps
    around the call feed the router's per-replica clock-offset estimator
    (``obs.fleettrace.ClockSync``) from the same GET. Never raises."""
    t_send = time.perf_counter()
    try:
        with urllib.request.urlopen(
            url.rstrip("/") + "/readyz", timeout=timeout_s
        ) as resp:
            body = json.loads(resp.read())
        ok = True
    except urllib.error.HTTPError as exc:
        try:
            body = json.loads(exc.read() or b"{}")
        except (ValueError, OSError):
            body = {}
        ok = True
    except Exception:
        body, ok = {}, False
    t_recv = time.perf_counter()
    clock = body.get("clock_perf")
    return {
        "ok": ok, "ready": bool(body.get("ready")),
        "version": body.get("version"),
        "queue_depth": body.get("queue_depth"),
        "clock_perf": clock if isinstance(clock, (int, float)) else None,
        "t_send": t_send, "t_recv": t_recv,
    }


class HealthProber:
    """Daemon thread probing every registered replica each tick."""

    def __init__(
        self,
        registry,
        interval_s: float = 0.5,
        timeout_s: float = 2.0,
        clock_sync=None,
    ) -> None:
        self.registry = registry
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        # Optional obs.fleettrace.ClockSync: probes double as NTP-style
        # offset samples for the fleet trace join.
        self.clock_sync = clock_sync
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-prober", daemon=True
        )

    def start(self) -> "HealthProber":
        self._thread.start()
        return self

    def tick(self) -> None:
        """One probe pass over the current membership (also the unit the
        tests drive directly, without the thread)."""
        for replica_id, url in self.registry.urls():
            if self._stop.is_set():
                return
            verdict = probe_replica(url, timeout_s=self.timeout_s)
            offset_ms = None
            if (
                self.clock_sync is not None and verdict["ok"]
                and verdict.get("clock_perf") is not None
            ):
                offset_ms = 1000.0 * self.clock_sync.observe(
                    replica_id, verdict["t_send"], verdict["t_recv"],
                    verdict["clock_perf"],
                )
            self.registry.observe_probe(
                replica_id, ok=verdict["ok"], ready=verdict["ready"],
                version=verdict["version"],
                queue_depth=verdict.get("queue_depth"),
                clock_offset_ms=offset_ms,
            )

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                pass  # a probe pass must never kill the prober

    def close(self, timeout: float | None = 5.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout)
