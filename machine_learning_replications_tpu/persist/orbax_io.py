"""Orbax checkpointing — the TPU-native replacement for the reference's pickle.

The reference persists its fitted model as one opaque pickle
(``predict_hf.py:33-34``; ``HF/hf_predict_model.pkl``) and has **no**
mid-training checkpointing or restart story at all (SURVEY.md §5 "Failure
detection": scripts crash on any error). Here:

  * ``save_params`` / ``restore_params`` — whole-model pytree checkpoints
    (``StackingParams``, ``TreeEnsembleParams``, …) via
    ``orbax.checkpoint.StandardCheckpointer``. Restore takes a *template*
    pytree supplying structure, dtypes, and non-array static fields
    (e.g. ``TreeEnsembleParams.max_depth``); use ``abstract_like`` to turn a
    concrete pytree into a shape/dtype-only template.
  * ``boosting_manager`` — a ``CheckpointManager`` over the boosting carry,
    used by ``models.gbdt.fit_resumable`` to checkpoint every k stages and
    resume after preemption (SURVEY.md §5 "Orbax checkpoint-and-restart per
    boosting stage").

Checkpoints are directories of tensorstore arrays — sharded arrays save and
restore with their ``NamedSharding`` preserved, so the same code path serves
single-chip and mesh-sharded state.

**Integrity and rollback (docs/RESILIENCE.md).** ``save_params`` /
``save_model`` publish *atomically*: the whole checkpoint tree is built in
a same-parent temp directory, a content-checksum manifest
(``integrity.json``: sha256 + size per file) is written over it, any
existing checkpoint at the path is rotated to its last-known-good slot
(``resilience.lastgood``), and only then is the temp dir renamed into
place — a crash at any point leaves either the old checkpoint or the new
one, never a torn mix. ``restore_params`` verifies the manifest before
handing the directory to Orbax, so corruption fails loudly
(``CheckpointIntegrityError``) instead of deserializing garbage weights;
``load_model`` additionally falls back to the retained last-known-good on
any restore failure (journaled ``checkpoint_rollback``). The
``persist.save`` / ``persist.restore`` faultpoints
(``resilience.faults``) tear these paths on demand for chaos tests.
"""

from __future__ import annotations

import hashlib
import os
import shutil
from typing import Any

import jax
import orbax.checkpoint as ocp

from machine_learning_replications_tpu.resilience import faults, lastgood


class SimulatedInterrupt(RuntimeError):
    """Raised by test hooks to emulate preemption mid-training."""


class CheckpointIntegrityError(RuntimeError):
    """The checkpoint's content does not match its integrity manifest."""


def abstract_like(params: Any, *, keep_sharding: bool = True) -> Any:
    """Shape/dtype template of a pytree (statics kept by the tree structure).

    With ``keep_sharding`` (the default), sharding is carried over from
    concrete ``jax.Array`` leaves so a mesh-sharded checkpoint restores onto
    the *caller's* topology rather than whatever layout the checkpoint file
    recorded. ``save_model`` turns it off: shardings reference live device
    objects and cannot be pickled into the sidecar template."""

    def leaf(x):
        sharding = (
            x.sharding if keep_sharding and isinstance(x, jax.Array) else None
        )
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

    return jax.tree.map(leaf, params)


_INTEGRITY_FILE = "integrity.json"


def checkpoint_version(path: str | os.PathLike) -> int | None:
    """The monotonic version id stamped into the checkpoint's integrity
    manifest at publish time, or ``None`` for a checkpoint that predates
    versioned manifests (or has no manifest at all). Cheap — one small
    JSON read; never raises (an unreadable manifest reads as unversioned;
    the restore path still fails loudly on real corruption)."""
    import json

    manifest_path = os.path.join(
        os.path.abspath(os.fspath(path)), _INTEGRITY_FILE
    )
    try:
        with open(manifest_path) as f:
            v = json.load(f).get("version")
        return int(v) if v is not None else None
    except (OSError, ValueError, json.JSONDecodeError, TypeError):
        return None


def _next_version(path: str) -> int:
    """The version the checkpoint about to publish at ``path`` gets:
    one past the largest version either slot (primary or its retained
    last-known-good) carries. Consulting BOTH slots keeps the sequence
    monotonic across the rotation itself — right after a publish the
    previous version lives in the lastgood slot, and a deploy pipeline
    comparing ids must never see the counter move backwards."""
    prev = [
        v for v in (
            checkpoint_version(path),
            checkpoint_version(lastgood.lastgood_path(path)),
        )
        if v is not None
    ]
    return (max(prev) if prev else 0) + 1


def _file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _payload_files(path: str) -> list[str]:
    """Every file under the checkpoint dir except the integrity manifest
    itself, as sorted relpaths — the checksum domain."""
    out = []
    for root, _dirs, names in os.walk(path):
        for name in names:
            rel = os.path.relpath(os.path.join(root, name), path)
            if rel != _INTEGRITY_FILE:
                out.append(rel)
    return sorted(out)


def _write_integrity(path: str, version: int | None = None) -> None:
    """Content-checksum manifest over the finished checkpoint tree
    (sha256 + byte size per file), plus the checkpoint's monotonic
    ``version`` id and publish timestamp when given (the deploy
    pipeline's identity — ``checkpoint_version`` reads it back). Written
    last in the temp dir, before the atomic publish rename."""
    from machine_learning_replications_tpu.obs.journal import utc_now_iso
    from machine_learning_replications_tpu.persist.atomicio import (
        fsync_json_dump,
    )

    files = {}
    for rel in _payload_files(path):
        fp = os.path.join(path, rel)
        files[rel] = {
            "sha256": _file_sha256(fp), "bytes": os.path.getsize(fp),
        }
    manifest: dict = {"format": 1, "files": files}
    if version is not None:
        manifest["version"] = int(version)
        manifest["published"] = utc_now_iso()
    fsync_json_dump(os.path.join(path, _INTEGRITY_FILE), manifest)


def verify_checkpoint(path: str | os.PathLike, *, deep: bool = True) -> bool:
    """Check the checkpoint's files against its integrity manifest.

    True when verified; False when the checkpoint predates integrity
    manifests (no ``integrity.json`` — tolerated so legacy checkpoints
    keep restoring). Raises ``CheckpointIntegrityError`` on any missing,
    truncated, or content-mismatched file — BEFORE Orbax deserializes
    anything from it. ``deep=False`` skips the sha256 pass (existence +
    size only): the cheap tier for guards that run per save, where a full
    re-read of the previous checkpoint would roughly triple checkpoint
    I/O — content-level rot is still caught loudly by the deep check
    every restore runs."""
    import json

    path = os.path.abspath(os.fspath(path))
    manifest_path = os.path.join(path, _INTEGRITY_FILE)
    if not os.path.exists(manifest_path):
        return False
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
        files = manifest["files"]
    except (OSError, json.JSONDecodeError, KeyError) as exc:
        raise CheckpointIntegrityError(
            f"unreadable integrity manifest in {path!r}: "
            f"{type(exc).__name__}: {exc}"
        ) from exc
    for rel, spec in sorted(files.items()):
        fp = os.path.join(path, rel)
        if not os.path.exists(fp):
            raise CheckpointIntegrityError(
                f"checkpoint {path!r} is missing {rel!r}"
            )
        size = os.path.getsize(fp)
        if size != spec["bytes"]:
            raise CheckpointIntegrityError(
                f"checkpoint file {rel!r} is {size} bytes, manifest says "
                f"{spec['bytes']} (torn write?)"
            )
        if not deep:
            continue
        # Size matched: hash the content (the expensive check last).
        digest = _file_sha256(fp)
        if digest != spec["sha256"]:
            raise CheckpointIntegrityError(
                f"checkpoint file {rel!r} content hash mismatch "
                f"({digest[:16]}… != manifest {spec['sha256'][:16]}…)"
            )
    return True


def _corrupt_payload(path: str) -> None:
    """Chaos-only (``persist.*:corrupt`` faultpoints): flip the first byte
    of the largest payload file so integrity verification must catch it."""
    best, best_size = None, -1
    for rel in _payload_files(path):
        size = os.path.getsize(os.path.join(path, rel))
        if size > best_size:
            best, best_size = os.path.join(path, rel), size
    if best is None:
        return
    with open(best, "r+b") as f:
        first = f.read(1)
        f.seek(0)
        f.write(bytes([first[0] ^ 0xFF]) if first else b"\x00")


def _orbax_save(path: str, params: Any) -> None:
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, params, force=True)


def _publish_tree(path: str, write_tree, *, force: bool = True) -> None:
    """Atomic checkpoint publish. ``write_tree(tmp)`` builds the complete
    checkpoint in a same-parent temp directory; the integrity manifest is
    written over it; the checkpoint previously at ``path`` (if any) is
    rotated to its last-known-good slot; then one ``os.rename`` makes the
    new tree visible. A crash anywhere leaves the old checkpoint intact
    (or, in the narrow window after rotation, the last-known-good — which
    ``load_model``'s rollback path finds)."""
    path = os.path.abspath(os.fspath(path))
    if not force and os.path.exists(path):
        raise FileExistsError(f"checkpoint already exists at {path!r}")
    version = _next_version(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    try:
        write_tree(tmp)
        # Faultpoint BETWEEN the tree write and the publish: raise =
        # "save interrupted mid-write" (tmp discarded, the published
        # checkpoint untouched); corrupt = bytes torn after checksumming
        # (detected at restore).
        corrupt = faults.fire("persist.save")
        _write_integrity(tmp, version=version)
        if corrupt:
            _corrupt_payload(tmp)
        # Rotate the outgoing primary into the lastgood slot ONLY if it
        # still verifies: rotating a primary that rotted on disk since
        # publish would destroy a genuinely good lastgood — the exact
        # rollback net this transaction exists to maintain. A failed
        # verification keeps the old lastgood and discards the bad
        # primary (it is being replaced anyway), journaled. Shallow
        # (size-only) on purpose: this guard runs on EVERY save, and a
        # full re-hash of the previous checkpoint would roughly triple
        # checkpoint I/O; content-level rot that slips through still
        # fails loudly at restore time (every restore hash-verifies, and
        # restore_with_fallback lets a bad lastgood raise).
        if os.path.isdir(path):
            try:
                verify_checkpoint(path, deep=False)
            except CheckpointIntegrityError as exc:
                from machine_learning_replications_tpu.obs import journal

                journal.event(
                    "checkpoint_retain_skipped", path=path,
                    error=f"{type(exc).__name__}: {exc}",
                )
                shutil.rmtree(path)
            else:
                lastgood.retain(path)
        os.rename(tmp, path)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    from machine_learning_replications_tpu.obs import journal

    journal.event("checkpoint_publish", path=path, version=version)


def save_params(path: str | os.PathLike, params: Any, *, force: bool = True) -> None:
    """Write ``params`` (any pytree of arrays) as an Orbax checkpoint at
    ``path``, published atomically with an integrity manifest; an existing
    checkpoint there is retained as last-known-good (``force``) rather
    than destroyed. Blocks until durable."""
    _publish_tree(
        os.path.abspath(os.fspath(path)),
        lambda tmp: _orbax_save(tmp, params),
        force=force,
    )


def restore_params(path: str | os.PathLike, template: Any) -> Any:
    """Read the checkpoint at ``path`` into the structure of ``template``
    (a concrete pytree or one from ``abstract_like``), verifying its
    integrity manifest first (``CheckpointIntegrityError`` on corruption;
    manifest-less legacy checkpoints restore unverified)."""
    path = os.path.abspath(os.fspath(path))
    if faults.fire("persist.restore"):
        _corrupt_payload(path)
    verify_checkpoint(path)
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(path, template)


_TEMPLATE_FILE = "pytree_template.json"


def _class_registry() -> dict[str, type]:
    """The closed set of param pytree classes a sidecar may name. Keyed by
    class name so the sidecar can be plain JSON — loading a checkpoint can
    only ever instantiate these, never run code from the checkpoint dir
    (the reason the sidecar is NOT a pickle: ``predict --model <dir>`` on an
    untrusted directory must not be an arbitrary-code-execution vector,
    matching ``sklearn_import``'s decode-without-executing design)."""
    from machine_learning_replications_tpu.models import (
        knn_impute, linear, pipeline, scaler, stacking, svm, tree,
    )

    classes = [
        pipeline.PipelineParams,
        stacking.StackingParams,
        scaler.ScalerParams,
        svm.SVCParams,
        tree.TreeEnsembleParams,
        linear.LinearParams,
        knn_impute.KNNImputerParams,
    ]
    return {c.__name__: c for c in classes}


def _encode_template(node: Any) -> Any:
    """Pytree → JSON-able sidecar node (shapes/dtypes/statics only)."""
    import dataclasses

    import jax.numpy as jnp
    import numpy as np

    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        cls_name = type(node).__name__
        if cls_name not in _class_registry():
            raise TypeError(
                f"cannot sidecar {cls_name}: not in the checkpoint class registry"
            )
        return {
            "cls": cls_name,
            "fields": {
                f.name: _encode_template(getattr(node, f.name))
                for f in dataclasses.fields(node)
            },
        }
    if isinstance(node, (jax.Array, np.ndarray, jax.ShapeDtypeStruct, np.generic)):
        arr = jnp.asarray(node) if isinstance(node, np.generic) else node
        return {"array": {"shape": list(arr.shape), "dtype": str(np.dtype(arr.dtype))}}
    if isinstance(node, (tuple, list)):
        return {"seq": [_encode_template(x) for x in node],
                "tuple": isinstance(node, tuple)}
    if isinstance(node, dict):
        # 'mapping' holds the children. Decode preserves JSON insertion
        # order; that is immaterial because jax flattens dict pytrees in
        # sorted-key order regardless (string keys only — a sidecar is
        # JSON).
        if not all(isinstance(k, str) for k in node):
            raise TypeError("cannot sidecar a dict with non-string keys")
        return {"mapping": {k: _encode_template(v) for k, v in node.items()}}
    if node is None or isinstance(node, (bool, int, float, str)):
        return {"static": node}
    raise TypeError(f"cannot sidecar a {type(node).__name__} leaf")


def _decode_template(node: Any) -> Any:
    """Sidecar node → abstract template pytree (ShapeDtypeStruct leaves)."""
    import numpy as np

    if "cls" in node:
        cls = _class_registry()[node["cls"]]
        kwargs = {k: _decode_template(v) for k, v in node["fields"].items()}
        return cls(**kwargs)
    if "array" in node:
        spec = node["array"]
        return jax.ShapeDtypeStruct(tuple(spec["shape"]), np.dtype(spec["dtype"]))
    if "seq" in node:
        items = [_decode_template(x) for x in node["seq"]]
        return tuple(items) if node.get("tuple", True) else items
    if "mapping" in node:
        return {k: _decode_template(v) for k, v in node["mapping"].items()}
    if "static" in node:
        return node["static"]
    raise ValueError(f"malformed sidecar node: {sorted(node)}")


def save_model(
    path: str | os.PathLike, params: Any, *, aot: bool = False
) -> None:
    """``save_params`` plus a self-describing sidecar so the checkpoint can
    be restored *without* the caller reconstructing a template pytree (the
    CLI's load path). The sidecar is JSON: the params' dataclass structure
    by *name* (resolved against a fixed registry at load) plus shape/dtype
    per array leaf and plain values for static fields.

    The sidecar is part of the same atomic publish as the arrays (one temp
    tree, one rename): its existence is the durability marker
    (``StageCheckpointer.completed``), and it is covered by the integrity
    manifest, so a present sidecar implies a complete, checksummed
    checkpoint.

    ``aot=True`` additionally compiles and serializes every serving
    bucket's executable into the same publish (``persist.aot``,
    docs/AOT.md): the replicas that restore this checkpoint load
    executables instead of tracing them. The export pays the full ladder
    compile bill HERE, once, at publish time — which is the point."""
    from machine_learning_replications_tpu.persist.atomicio import (
        fsync_json_dump,
    )

    def write_tree(tmp: str) -> None:
        _orbax_save(tmp, params)
        fsync_json_dump(
            os.path.join(tmp, _TEMPLATE_FILE),
            {"format": 1, "root": _encode_template(params)},
        )
        if aot:
            from machine_learning_replications_tpu.persist import (
                aot as aot_mod,
            )

            aot_mod.export_aot(tmp, params)

    _publish_tree(os.path.abspath(os.fspath(path)), write_tree)


def load_model(path: str | os.PathLike) -> Any:
    """Restore a checkpoint written by ``save_model`` using its JSON sidecar
    template (no code from the checkpoint directory ever runs). Arrays land
    on the default device; re-shard afterwards for mesh use
    (``data.shard_rows`` / ``NamedSharding``).

    When the checkpoint fails to restore — integrity mismatch, torn or
    missing files — and a retained last-known-good sibling exists
    (``resilience.lastgood``), the load falls back to it with a journaled
    ``checkpoint_rollback``: a bad deploy serves the previous model
    instead of killing the process. Without a retained fallback the
    failure propagates.

    Full-pipeline checkpoints written before the quality reference profile
    existed (their sidecar's ``PipelineParams`` node has no ``quality``
    field) restore cleanly — the dataclass default fills ``None`` — with a
    single journaled warning, so a serving process built on one says *why*
    its drift monitoring is off instead of silently lacking it."""
    return lastgood.restore_with_fallback(path, _load_model_at)


def load_model_versioned(path: str | os.PathLike) -> tuple[Any, dict]:
    """``load_model`` plus provenance: returns ``(params, info)`` where
    ``info`` states which directory actually restored and under which
    version id — ``{"path", "version", "rolled_back"}``. The deploy
    pipeline keys off this: a corrupt new checkpoint restores the
    retained last-known-good (``rolled_back=True``, the PREVIOUS
    version), and the caller must report the rollout as rolled back
    instead of claiming the target version shipped."""
    info: dict = {}

    def loader(p: str):
        out = _load_model_at(p)
        # Only the loader invocation that SUCCEEDED writes the record.
        info.update(
            path=p,
            version=checkpoint_version(p),
            rolled_back=os.path.abspath(p)
            != os.path.abspath(os.fspath(path)),
        )
        return out

    params = lastgood.restore_with_fallback(path, loader)
    return params, info


def _load_model_at(path: str) -> Any:
    import json

    path = os.path.abspath(os.fspath(path))
    with open(os.path.join(path, _TEMPLATE_FILE)) as f:
        sidecar = json.load(f)
    if sidecar.get("format") != 1:
        raise ValueError(f"unknown sidecar format {sidecar.get('format')!r}")
    root = sidecar["root"]
    if root.get("cls") == "PipelineParams" and not _has_quality_profile(root):
        from machine_learning_replications_tpu.obs import journal
        from machine_learning_replications_tpu.utils.trace import stage_say

        stage_say(
            f"checkpoint {path!r} predates quality reference profiles — "
            "drift monitoring will be disabled for models served from it"
        )
        journal.event("quality_profile_missing", path=path)
    return restore_params(path, _decode_template(root))


def _has_quality_profile(root: dict) -> bool:
    """True when a sidecar ``PipelineParams`` node carries a non-null
    reference profile (pre-profile checkpoints lack the field entirely;
    a profile explicitly saved as None encodes as a static null)."""
    q = root.get("fields", {}).get("quality")
    return q is not None and q != {"static": None}


class StageCheckpointer:
    """Stage-level checkpoint/resume for multi-stage fits (SURVEY.md §5
    "Failure detection": the reference restarts from zero on any error; the
    round-1 build could resume only the GBDT boosting loop). Each named
    stage's output pytree is written via ``save_model`` (JSON sidecar last,
    so the sidecar's existence marks the stage durable); on re-entry a
    completed stage restores instead of recomputing. Stage outputs are
    deterministic, so a resumed pipeline equals an unbroken one.
    """

    def __init__(
        self,
        root: str | os.PathLike,
        _interrupt_after: str | None = None,
        fingerprint: str | None = None,
    ) -> None:
        self.root = os.path.abspath(os.fspath(root))
        os.makedirs(self.root, exist_ok=True)
        self._interrupt_after = _interrupt_after  # test hook (preemption)
        if fingerprint is not None:
            self._check_fingerprint(fingerprint)

    def _check_fingerprint(self, fingerprint: str) -> None:
        """Stage checkpoints are only valid for the inputs that produced
        them; re-entering a directory with different (X, y, cfg) must fail
        loudly instead of silently restoring a stale model."""
        import json
        import tempfile

        fp_path = os.path.join(self.root, "fingerprint.json")
        stored = None
        if os.path.exists(fp_path):
            try:
                with open(fp_path) as f:
                    stored = json.load(f)["fingerprint"]
            except (OSError, json.JSONDecodeError, KeyError):
                stored = None  # torn write — resolved below
        if stored is not None:
            if stored != fingerprint:
                raise RuntimeError(
                    f"checkpoint dir {self.root!r} was written by a fit with "
                    f"different inputs (stored fingerprint {stored[:16]}…, "
                    f"this fit {fingerprint[:16]}…); pass a fresh "
                    "checkpoint_dir or delete the stale one"
                )
            return
        # No (readable) fingerprint: if the dir already holds completed
        # stages, they are of unknown provenance — adopting this run's
        # fingerprint would silently restore them. Refuse instead.
        stray = [
            d for d in sorted(os.listdir(self.root))
            if os.path.exists(os.path.join(self.root, d, _TEMPLATE_FILE))
        ]
        if stray:
            raise RuntimeError(
                f"checkpoint dir {self.root!r} holds completed stages "
                f"({', '.join(stray)}) but no fingerprint recording which "
                "inputs produced them; pass a fresh checkpoint_dir or delete "
                "the stale one"
            )
        fd, tmp = tempfile.mkstemp(prefix="fingerprint.", dir=self.root)
        with os.fdopen(fd, "w") as f:
            json.dump({"fingerprint": fingerprint}, f)
        os.replace(tmp, fp_path)

    def _path(self, name: str) -> str:
        return os.path.join(self.root, name)

    def completed(self, name: str) -> bool:
        return os.path.exists(os.path.join(self._path(name), _TEMPLATE_FILE))

    def run(self, name: str, compute):
        """Return the stage's output: restored if previously completed,
        else ``compute()`` then checkpointed (durably, before the optional
        simulated-preemption hook fires). ``save_model`` publishes the
        sidecar atomically, so a present sidecar implies a complete one;
        should a corrupt checkpoint nonetheless surface (e.g. torn tensorstore
        files from a crash mid-``save_params``), the stage falls back to
        recomputing rather than wedging the resume.

        Stage timing/stderr/journal telemetry is the shared
        ``obs.journal.stage_scope`` code path (same lines as the
        straight-through runner, " (checkpointed)" suffixed)."""
        import jax

        from machine_learning_replications_tpu.obs import journal
        from machine_learning_replications_tpu.utils.trace import stage_say

        if self.completed(name):
            try:
                out = load_model(self._path(name))
                stage_say(f"stage {name!r} restored from checkpoint")
                journal.event("checkpoint_restore", stage=name)
                return out
            except Exception as e:
                import shutil

                shutil.rmtree(self._path(name), ignore_errors=True)
                stage_say(
                    f"stage {name!r}: checkpoint corrupt "
                    f"({type(e).__name__}) — discarded, recomputing"
                )
                journal.event(
                    "checkpoint_corrupt", stage=name,
                    error=type(e).__name__,
                )
        with journal.stage_scope(name, done_suffix=" (checkpointed)"):
            # Block explicitly (not via the span handle): save_model must
            # only run on completed outputs, and its durable write belongs
            # inside the stage's timing, as before.
            out = jax.block_until_ready(compute())
            save_model(self._path(name), out)
        if self._interrupt_after == name:
            raise SimulatedInterrupt(f"after stage {name!r}")
        return out


def boosting_manager(
    directory: str | os.PathLike, *, max_to_keep: int = 2
) -> ocp.CheckpointManager:
    """Step-indexed manager for the boosting carry (step = stages completed).

    Keeps the newest ``max_to_keep`` steps — enough to survive a failure
    during a save — and cleans up older ones.
    """
    return ocp.CheckpointManager(
        os.path.abspath(os.fspath(directory)),
        options=ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep, create=True
        ),
    )


def save_step(mgr: ocp.CheckpointManager, step: int, carry: Any) -> None:
    mgr.save(step, args=ocp.args.StandardSave(carry))


def latest_step(mgr: ocp.CheckpointManager) -> int | None:
    return mgr.latest_step()


def restore_step(mgr: ocp.CheckpointManager, step: int, template: Any) -> Any:
    return mgr.restore(step, args=ocp.args.StandardRestore(abstract_like(template)))
