"""Orbax checkpointing — the TPU-native replacement for the reference's pickle.

The reference persists its fitted model as one opaque pickle
(``predict_hf.py:33-34``; ``HF/hf_predict_model.pkl``) and has **no**
mid-training checkpointing or restart story at all (SURVEY.md §5 "Failure
detection": scripts crash on any error). Here:

  * ``save_params`` / ``restore_params`` — whole-model pytree checkpoints
    (``StackingParams``, ``TreeEnsembleParams``, …) via
    ``orbax.checkpoint.StandardCheckpointer``. Restore takes a *template*
    pytree supplying structure, dtypes, and non-array static fields
    (e.g. ``TreeEnsembleParams.max_depth``); use ``abstract_like`` to turn a
    concrete pytree into a shape/dtype-only template.
  * ``boosting_manager`` — a ``CheckpointManager`` over the boosting carry,
    used by ``models.gbdt.fit_resumable`` to checkpoint every k stages and
    resume after preemption (SURVEY.md §5 "Orbax checkpoint-and-restart per
    boosting stage").

Checkpoints are directories of tensorstore arrays — sharded arrays save and
restore with their ``NamedSharding`` preserved, so the same code path serves
single-chip and mesh-sharded state.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import orbax.checkpoint as ocp


class SimulatedInterrupt(RuntimeError):
    """Raised by test hooks to emulate preemption mid-training."""


def abstract_like(params: Any, *, keep_sharding: bool = True) -> Any:
    """Shape/dtype template of a pytree (statics kept by the tree structure).

    With ``keep_sharding`` (the default), sharding is carried over from
    concrete ``jax.Array`` leaves so a mesh-sharded checkpoint restores onto
    the *caller's* topology rather than whatever layout the checkpoint file
    recorded. ``save_model`` turns it off: shardings reference live device
    objects and cannot be pickled into the sidecar template."""

    def leaf(x):
        sharding = (
            x.sharding if keep_sharding and isinstance(x, jax.Array) else None
        )
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)

    return jax.tree.map(leaf, params)


def save_params(path: str | os.PathLike, params: Any, *, force: bool = True) -> None:
    """Write ``params`` (any pytree of arrays) as an Orbax checkpoint at
    ``path`` (created; overwritten when ``force``). Blocks until durable."""
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(os.path.abspath(os.fspath(path)), params, force=force)


def restore_params(path: str | os.PathLike, template: Any) -> Any:
    """Read the checkpoint at ``path`` into the structure of ``template``
    (a concrete pytree or one from ``abstract_like``)."""
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(os.path.abspath(os.fspath(path)), template)


_TEMPLATE_FILE = "pytree_template.pkl"


def save_model(path: str | os.PathLike, params: Any) -> None:
    """``save_params`` plus a self-describing sidecar so the checkpoint can
    be restored *without* the caller reconstructing a template pytree (the
    CLI's load path). The sidecar pickles only ``jax.ShapeDtypeStruct``
    leaves inside the params' own dataclass structure — written and read
    exclusively by this module, never by sklearn-era code."""
    import pickle

    path = os.path.abspath(os.fspath(path))
    save_params(path, params)
    template = abstract_like(params, keep_sharding=False)
    with open(os.path.join(path, _TEMPLATE_FILE), "wb") as f:
        pickle.dump(template, f)


def load_model(path: str | os.PathLike) -> Any:
    """Restore a checkpoint written by ``save_model`` using its sidecar
    template. Arrays land on the default device; re-shard afterwards for
    mesh use (``data.shard_rows`` / ``NamedSharding``)."""
    import pickle

    path = os.path.abspath(os.fspath(path))
    with open(os.path.join(path, _TEMPLATE_FILE), "rb") as f:
        template = pickle.load(f)
    return restore_params(path, template)


def boosting_manager(
    directory: str | os.PathLike, *, max_to_keep: int = 2
) -> ocp.CheckpointManager:
    """Step-indexed manager for the boosting carry (step = stages completed).

    Keeps the newest ``max_to_keep`` steps — enough to survive a failure
    during a save — and cleans up older ones.
    """
    return ocp.CheckpointManager(
        os.path.abspath(os.fspath(directory)),
        options=ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep, create=True
        ),
    )


def save_step(mgr: ocp.CheckpointManager, step: int, carry: Any) -> None:
    mgr.save(step, args=ocp.args.StandardSave(carry))


def latest_step(mgr: ocp.CheckpointManager) -> int | None:
    return mgr.latest_step()


def restore_step(mgr: ocp.CheckpointManager, step: int, template: Any) -> Any:
    return mgr.restore(step, args=ocp.args.StandardRestore(abstract_like(template)))
