"""Legacy sklearn pickle import — the parity-oracle loader.

The shipped model (``HF/hf_predict_model.pkl``, sklearn 0.23.2, pickle
protocol 3) cannot be loaded by a modern sklearn, and executing 15-year-old
pickled object graphs is unnecessary anyway: we only need the fitted arrays.
``decode_pickle`` deserializes with a *class-stubbing* unpickler — numpy
globals resolve for real (so ndarrays reconstruct), every sklearn class
becomes an inert attribute bag — and the ``import_*`` converters duck-type
those bags into our pytrees.

The same converters accept live fitted sklearn estimators (they read the
same attributes), which is how the differential tests translate
sklearn-1.9-fitted models into JAX parameters.

Field conventions handled here (verified empirically; see models/svm.py):
  * binary SVC's public ``dual_coef_``/``intercept_`` are the negation of the
    private ``_dual_coef_``/``_intercept_``; the public pair satisfies
    ``dec = K @ dual_coef + intercept``;
  * GBC trees store sklearn node structs ``(left_child, right_child, feature,
    threshold, ...)``; leaves have children == -1 and are converted to
    self-loops for the branch-free descent in ``models.tree``.
"""

from __future__ import annotations

import builtins
import io
import pickle
from typing import Any

import numpy as np

from machine_learning_replications_tpu.models.linear import LinearParams
from machine_learning_replications_tpu.models.scaler import ScalerParams
from machine_learning_replications_tpu.models.stacking import StackingParams
from machine_learning_replications_tpu.models.svm import SVCParams
from machine_learning_replications_tpu.models.tree import TreeEnsembleParams

REFERENCE_PKL_PATH = (
    "/root/reference/Machine Learning for Predicting Heart Failure Progression/"
    "hf_predict_model.pkl"
)


class _Stub(dict):
    """Inert stand-in for a pickled class: records ctor args and state.

    Subclasses ``dict`` so dict-subclass pickles (e.g. ``sklearn.utils.Bunch``)
    replay their SETITEMS opcodes; attribute lookup falls back to dict keys,
    matching Bunch semantics.
    """

    def __init__(self, *args: Any, **kwargs: Any) -> None:
        super().__init__()
        self._ctor_args = args
        self._ctor_kwargs = kwargs

    def __setstate__(self, state: Any) -> None:
        if isinstance(state, dict):
            self.__dict__.update(state)
        else:
            self._state = state

    def __getattr__(self, name: str) -> Any:
        try:
            return self[name]
        except KeyError:
            raise AttributeError(name) from None

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<stub {type(self).__module__}.{type(self).__name__}>"


# Only array-reconstruction machinery and inert containers resolve for real —
# notably NOT builtins.* wholesale (builtins.exec/eval would make the
# "no pickled code executes" guarantee false for a crafted pickle).
_SAFE_GLOBALS: dict[tuple[str, str], Any] = {
    ("builtins", n): getattr(builtins, n)
    for n in (
        "object", "tuple", "list", "dict", "set", "frozenset",
        "bytearray", "complex", "bytes", "str", "int", "float", "bool",
        "slice", "range",
    )
}


class _StubUnpickler(pickle.Unpickler):
    """Resolve numpy/scipy + inert builtins for real; stub everything else."""

    def __init__(self, f: io.IOBase) -> None:
        super().__init__(f)
        self._stubs: dict[tuple[str, str], type] = {}

    def find_class(self, module: str, name: str) -> Any:
        if module.split(".")[0] in ("numpy", "scipy"):
            return super().find_class(module, name)
        if (module, name) in _SAFE_GLOBALS:
            return _SAFE_GLOBALS[(module, name)]
        if (module, name) == ("collections", "OrderedDict"):
            import collections

            return collections.OrderedDict
        key = (module, name)
        if key not in self._stubs:
            cls = type(name, (_Stub,), {"__module__": module})
            self._stubs[key] = cls
        return self._stubs[key]


def decode_pickle(path: str = REFERENCE_PKL_PATH) -> Any:
    """Decode a (possibly ancient) sklearn pickle into stub attribute bags."""
    with open(path, "rb") as f:
        return _StubUnpickler(f).load()


# ---------------------------------------------------------------------------
# Converters: stub bag OR live sklearn estimator → parameter pytree
# ---------------------------------------------------------------------------


def _arr(x: Any) -> np.ndarray:
    return np.asarray(x, dtype=np.float64)


def import_scaler(obj: Any) -> ScalerParams:
    return ScalerParams(mean=_arr(obj.mean_), scale=_arr(obj.scale_))


def import_svc(obj: Any) -> SVCParams:
    try:
        dual = _arr(obj.dual_coef_)[0]
        intercept = _arr(obj.intercept_).reshape(())
    except AttributeError:  # only the private (libsvm-orientation) fields present
        dual = -_arr(obj._dual_coef_)[0]
        intercept = -_arr(obj._intercept_).reshape(())
    return SVCParams(
        support_vectors=_arr(obj.support_vectors_),
        dual_coef=dual,
        intercept=intercept,
        gamma=_arr(obj._gamma).reshape(()),
        prob_a=_arr(obj._probA).reshape(()),
        prob_b=_arr(obj._probB).reshape(()),
    )


def _tree_arrays(tree_obj: Any) -> dict[str, np.ndarray]:
    """Node arrays from a live ``sklearn.tree._tree.Tree`` or its stub.

    Stubs hold the pickled state dict: ``nodes`` is the structured node
    array, ``values`` is ``[node_count, 1, 1]``.
    """
    if hasattr(tree_obj, "nodes"):  # stub path
        nodes = tree_obj.nodes
        return {
            "feature": np.asarray(nodes["feature"], np.int32),
            "threshold": _arr(nodes["threshold"]),
            "left": np.asarray(nodes["left_child"], np.int32),
            "right": np.asarray(nodes["right_child"], np.int32),
            "value": _arr(tree_obj.values)[:, 0, 0],
        }
    return {
        "feature": np.asarray(tree_obj.feature, np.int32),
        "threshold": _arr(tree_obj.threshold),
        "left": np.asarray(tree_obj.children_left, np.int32),
        "right": np.asarray(tree_obj.children_right, np.int32),
        "value": _arr(tree_obj.value)[:, 0, 0],
    }


def import_gbdt(obj: Any) -> TreeEnsembleParams:
    """GradientBoostingClassifier (binary) → dense SoA forest.

    Leaves (children == -1) become self-loops with +inf thresholds so the
    fixed-depth descent parks on them; shorter trees are padded with inert
    nodes to the ensemble-wide max node count.
    """
    estimators = np.asarray(obj.estimators_).ravel()
    trees = [_tree_arrays(e.tree_) for e in estimators]
    n_nodes = max(t["feature"].shape[0] for t in trees)
    T = len(trees)
    feature = np.zeros((T, n_nodes), np.int32)
    threshold = np.full((T, n_nodes), np.inf)
    left = np.tile(np.arange(n_nodes, dtype=np.int32), (T, 1))
    right = left.copy()
    value = np.zeros((T, n_nodes))
    max_depth = 1
    for i, t in enumerate(trees):
        k = t["feature"].shape[0]
        is_leaf = t["left"] < 0
        idx = np.arange(k, dtype=np.int32)
        feature[i, :k] = np.where(is_leaf, 0, t["feature"])
        threshold[i, :k] = np.where(is_leaf, np.inf, t["threshold"])
        left[i, :k] = np.where(is_leaf, idx, t["left"])
        right[i, :k] = np.where(is_leaf, idx, t["right"])
        value[i, :k] = t["value"]
        # depth of this tree = longest root→leaf path
        depth = _tree_depth(t["left"], t["right"])
        max_depth = max(max_depth, depth)

    prior1 = _class_prior1(obj.init_)
    init_raw = np.log(prior1 / (1.0 - prior1))
    return TreeEnsembleParams(
        feature=feature,
        threshold=threshold,
        left=left,
        right=right,
        value=value,
        init_raw=np.float64(init_raw),
        learning_rate=np.float64(obj.learning_rate),
        max_depth=int(max_depth),
    )


def _tree_depth(left: np.ndarray, right: np.ndarray) -> int:
    depth = np.zeros(left.shape[0], np.int32)
    order = range(left.shape[0])  # sklearn stores parents before children
    for i in order:
        for c in (left[i], right[i]):
            if c >= 0 and c != i:
                depth[c] = depth[i] + 1
    return int(depth.max()) if depth.size else 0


def _class_prior1(init_obj: Any) -> float:
    prior = _arr(init_obj.class_prior_)
    return float(prior[1])


def import_linear(obj: Any) -> LinearParams:
    return LinearParams(
        coef=_arr(obj.coef_)[0], intercept=_arr(obj.intercept_).reshape(())
    )


def _pipeline_steps(obj: Any) -> list[Any]:
    return [s[1] for s in obj.steps]


def import_stacking(obj: Any) -> StackingParams:
    """StackingClassifier (fitted, reference topology) → StackingParams.

    Expects the reference's member order (``train_ensemble_public.py:43-47``):
    [Pipeline(StandardScaler, SVC), GradientBoostingClassifier, LogisticRegression].
    """
    pipe, gbc, lg = list(obj.estimators_)
    sc, svc = _pipeline_steps(pipe)
    return StackingParams(
        scaler=import_scaler(sc),
        svc=import_svc(svc),
        gbdt=import_gbdt(gbc),
        logreg=import_linear(lg),
        meta=import_linear(obj.final_estimator_),
    )
