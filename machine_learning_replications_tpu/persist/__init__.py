"""L1 — persistence.

The reference persists one opaque pickle of the whole fitted sklearn object
graph (``predict_hf.py:33-34``; ``HF/hf_predict_model.pkl``). Here the model
state is an explicit ``StackingParams`` pytree checkpointed with Orbax
(``orbax_io``), plus a one-way import tool (``sklearn_import``) that decodes
legacy sklearn pickles — including the shipped 0.23.2 artifact — *without
executing any pickled code* and converts them (or live sklearn estimators)
into pytrees, seeding the numerical parity oracle of SURVEY.md §2.3.
"""

from machine_learning_replications_tpu.persist.sklearn_import import (
    REFERENCE_PKL_PATH,
    decode_pickle,
    import_stacking,
    import_gbdt,
    import_linear,
    import_scaler,
    import_svc,
)

def load_inference_params(model: str | None = None, pkl: str | None = None):
    """Resolve the inference param source every front end shares
    (``cli.py predict``, ``serve``): an Orbax checkpoint dir when ``model``
    is given (``PipelineParams`` / ``TreeEnsembleParams`` /
    ``StackingParams``, per the sidecar), else a legacy sklearn pickle
    (``pkl``, defaulting to the shipped reference artifact) decoded without
    executing pickled code."""
    if model:
        from machine_learning_replications_tpu.persist import orbax_io

        return orbax_io.load_model(model)
    return import_stacking(decode_pickle(pkl or REFERENCE_PKL_PATH))


# Orbax names resolve lazily (PEP 562) so the pickle-import path stays usable
# in environments without orbax-checkpoint installed.
_ORBAX_NAMES = (
    "abstract_like", "restore_params", "save_params",
    "checkpoint_version", "load_model_versioned",
)


def __getattr__(name):
    if name in _ORBAX_NAMES:
        from machine_learning_replications_tpu.persist import orbax_io

        return getattr(orbax_io, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "REFERENCE_PKL_PATH",
    "load_inference_params",
    "decode_pickle",
    "import_stacking",
    "import_gbdt",
    "import_linear",
    "import_scaler",
    "import_svc",
    "abstract_like",
    "restore_params",
    "save_params",
    "checkpoint_version",
    "load_model_versioned",
]
