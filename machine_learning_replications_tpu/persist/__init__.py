"""L1 — persistence.

The reference persists one opaque pickle of the whole fitted sklearn object
graph (``predict_hf.py:33-34``; ``HF/hf_predict_model.pkl``). Here the model
state is an explicit ``StackingParams`` pytree checkpointed with Orbax
(``orbax_io``), plus a one-way import tool (``sklearn_import``) that decodes
legacy sklearn pickles — including the shipped 0.23.2 artifact — *without
executing any pickled code* and converts them (or live sklearn estimators)
into pytrees, seeding the numerical parity oracle of SURVEY.md §2.3.
"""

def load_inference_params(model: str | None = None, pkl: str | None = None):
    """Resolve the inference param source every front end shares
    (``cli.py predict``, ``serve``): an Orbax checkpoint dir when ``model``
    is given (``PipelineParams`` / ``TreeEnsembleParams`` /
    ``StackingParams``, per the sidecar), else a legacy sklearn pickle
    (``pkl``, defaulting to the shipped reference artifact) decoded without
    executing pickled code."""
    if model:
        from machine_learning_replications_tpu.persist import orbax_io

        return orbax_io.load_model(model)
    from machine_learning_replications_tpu.persist.sklearn_import import (
        REFERENCE_PKL_PATH,
        decode_pickle,
        import_stacking,
    )

    return import_stacking(decode_pickle(pkl or REFERENCE_PKL_PATH))


# All re-exports resolve lazily (PEP 562, shared ``lazyimport`` helper).
# Orbax names: the pickle-import path stays usable without
# orbax-checkpoint installed. sklearn_import names: that module's pytree
# types pull flax (hence jax) at import time, and this ``__init__``
# executes for every ``persist.*`` consumer — ``score.pipeline`` imports
# ``persist.atomicio``, whose import-time closure is declared jax-free
# through ``score.reader`` (graftcheck rule import-purity, manifest in
# analysis/project.py).
from machine_learning_replications_tpu.lazyimport import lazy_exports

_EXPORTS = {
    "abstract_like": "orbax_io",
    "restore_params": "orbax_io",
    "save_params": "orbax_io",
    "checkpoint_version": "orbax_io",
    "load_model_versioned": "orbax_io",
    "REFERENCE_PKL_PATH": "sklearn_import",
    "decode_pickle": "sklearn_import",
    "import_stacking": "sklearn_import",
    "import_gbdt": "sklearn_import",
    "import_linear": "sklearn_import",
    "import_scaler": "sklearn_import",
    "import_svc": "sklearn_import",
}

__getattr__, __dir__ = lazy_exports(__name__, _EXPORTS)


__all__ = [
    "REFERENCE_PKL_PATH",
    "load_inference_params",
    "decode_pickle",
    "import_stacking",
    "import_gbdt",
    "import_linear",
    "import_scaler",
    "import_svc",
    "abstract_like",
    "restore_params",
    "save_params",
    "checkpoint_version",
    "load_model_versioned",
]
