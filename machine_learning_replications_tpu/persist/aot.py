"""AOT-serialized engines — compile at publish time, restore at start time.

Every replica start and every rolling deploy used to pay a full
trace+compile of the whole bucket ladder (BENCH.md's cold cells: 20–27 s
fit compiles; serve warmup covers a 7-bucket ladder × dual paths) — the
single largest fixed cost left in the serving stack, pacing one-at-a-time
deploy holds, the learn loop's promotion window, and the autoscaler's
reaction time. This module removes it at the source: the per-bucket
executables the engine would trace at startup are compiled ONCE at
checkpoint publish time and shipped *inside* the versioned checkpoint
tree, so a replica restores executables instead of tracing them
(docs/AOT.md).

**Artifact layout.** ``export_aot`` writes an ``aot/`` subtree into the
checkpoint directory being published (it runs inside ``save_model``'s
atomic ``_publish_tree`` transaction, so the blobs are covered by the
``integrity.json`` content manifest like every other checkpoint file)::

    <checkpoint>/aot/manifest.json      fingerprints + blob index
    <checkpoint>/aot/<backend>_b<N>.bin serialized executable, one per
                                        (backend, bucket)

Each blob is ``jax.experimental.serialize_executable.serialize`` over the
jit-compiled per-bucket core — the SAME pure function
(``serve.engine.family_core``) the engine jits at warmup, lowered at the
same shapes, so a restored executable is *bit-identical* to a traced one
(asserted by tests/test_aot.py; re-proved at restore time by the engine's
parity probe against the eager oracle before ``warm`` is set).

**Fingerprints.** A serialized XLA executable is only valid on the
platform that compiled it. Every backend's blobs carry a fingerprint —
jax/jaxlib version, backend name, device kind, the x64 flag (the dtype
regime), and the model family — checked once per restore;
any mismatch journals a fallback and the engine traces instead.

**Fails open.** Nothing in this module can brick a replica: a missing
``aot/`` tree, an unreadable manifest, a fingerprint mismatch, a corrupt
blob, or a deserialization error each journal ``aot_fallback`` (counted
in ``serve_aot_fallback_total{reason=…}``) and the engine falls back to
tracing that bucket — the pre-AOT behavior, just slower. ``cli serve
--no-aot`` (and the fleet passthrough) forces the tracing path outright.
The ``persist.aot_restore`` faultpoint tears the restore path on demand
for chaos drills (docs/RESILIENCE.md).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any

from machine_learning_replications_tpu.resilience import faults

AOT_DIRNAME = "aot"
_MANIFEST = "manifest.json"


def platform_fingerprint(backend: str) -> dict:
    """The compatibility key a serialized executable is valid under:
    jax/jaxlib versions, backend name, the concrete device kind, and the
    x64 flag (which decides every aval dtype the engine compiles at)."""
    import jax
    import jaxlib

    try:
        kind = jax.devices(backend)[0].device_kind
    except RuntimeError:
        kind = None
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "backend": backend,
        "device_kind": kind,
        "x64": bool(jax.config.jax_enable_x64),
    }


def _fingerprint_diff(want: dict, have: dict) -> str | None:
    """Human-readable mismatch between a manifest fingerprint and the
    current platform (None when compatible)."""
    bad = [
        f"{k}={have.get(k)!r} (blob built for {want.get(k)!r})"
        for k in sorted(set(want) | set(have))
        if want.get(k) != have.get(k)
    ]
    return ", ".join(bad) if bad else None


def _example_core_inputs(params) -> tuple[str, Any, Any]:
    """``(family, core_arg, example_row)`` for the per-bucket core: the
    non-batch argument the engine passes (the ensemble for pipeline
    checkpoints, the params themselves otherwise) and ONE example row in
    the core's input space, replicated per bucket at lowering time. Runs
    the same pre-batch host composition the engine runs
    (``contract_rows_to_x64`` → ``impute_select``) so the lowered avals
    equal the served ones exactly."""
    import jax
    import numpy as np

    from machine_learning_replications_tpu.data.examples import patient_row
    from machine_learning_replications_tpu.models import pipeline
    from machine_learning_replications_tpu.serve.engine import family_core

    family, _core, _n_out = family_core(params)
    dparams = jax.device_put(params)
    if family == "pipeline":
        dparams = dparams.replace(
            support_mask=np.asarray(params.support_mask)
        )
        x64 = pipeline.contract_rows_to_x64(params, patient_row())
        row = np.asarray(pipeline.impute_select(dparams, x64))
        return family, dparams.ensemble, row
    return family, dparams, np.asarray(patient_row(), np.float64)


def export_aot(
    tree_dir: str | os.PathLike,
    params,
    device_buckets=None,
    host_buckets=None,
) -> dict:
    """Compile and serialize every bucket's executable into
    ``<tree_dir>/aot/``. Called inside ``save_model``'s publish
    transaction (``tree_dir`` is the pre-rename temp tree), so the blobs
    land in the integrity manifest with everything else.

    Two ladders are exported: the device ladder on the default backend
    (the engine's buckets) and the host fast-path ladder on the CPU
    backend (``serve.hostpath``); on a CPU-only deployment they merge
    into one set of CPU blobs. Returns the written aot manifest."""
    import jax
    import numpy as np
    from jax.experimental import serialize_executable

    from machine_learning_replications_tpu.obs import journal
    from machine_learning_replications_tpu.persist.atomicio import (
        fsync_json_dump,
    )
    from machine_learning_replications_tpu.serve.engine import (
        DEFAULT_BUCKETS, family_core,
    )
    from machine_learning_replications_tpu.serve.hostpath import (
        DEFAULT_HOST_BUCKETS,
    )

    t0 = time.perf_counter()
    if device_buckets is None:
        device_buckets = DEFAULT_BUCKETS
    if host_buckets is None:
        host_buckets = DEFAULT_HOST_BUCKETS
    default_backend = jax.default_backend()
    plan: dict[str, set[int]] = {
        default_backend: {int(b) for b in device_buckets},
    }
    plan.setdefault("cpu", set()).update(int(b) for b in host_buckets)

    family, _core_fn, _n_out = family_core(params)
    aot_dir = os.path.join(os.fspath(tree_dir), AOT_DIRNAME)
    os.makedirs(aot_dir, exist_ok=True)
    blobs: list[dict] = []
    fingerprints: dict[str, dict] = {}
    for backend, buckets in sorted(plan.items()):
        if not buckets:
            continue
        device = jax.devices(backend)[0]
        with jax.default_device(device):
            fam, core_arg, row = _example_core_inputs(params)
            _fam, core_fn, _n = family_core(params)
            jitted = jax.jit(core_fn)
            for bucket in sorted(buckets):
                X = np.repeat(row, bucket, axis=0)
                compiled = jitted.lower(core_arg, X).compile()
                payload, _in_tree, _out_tree = serialize_executable.serialize(
                    compiled
                )
                name = f"{backend}_b{bucket}.bin"
                with open(os.path.join(aot_dir, name), "wb") as f:
                    f.write(payload)
                blobs.append({
                    "backend": backend,
                    "bucket": bucket,
                    "file": name,
                    "bytes": len(payload),
                    "width": int(X.shape[1]),
                })
        fingerprints[backend] = platform_fingerprint(backend)
    seconds = round(time.perf_counter() - t0, 3)
    manifest = {
        "format": 1,
        "family": family,
        "fingerprints": fingerprints,
        "blobs": blobs,
    }
    fsync_json_dump(os.path.join(aot_dir, _MANIFEST), manifest)
    journal.event(
        "aot_export", path=os.fspath(tree_dir), blobs=len(blobs),
        seconds=seconds,
    )
    return manifest


def load_bundle(checkpoint_dir: str | os.PathLike) -> "AotBundle | None":
    """The checkpoint's AOT bundle, or None when it ships none (or its
    manifest is unreadable — journaled, fails open: the engine simply
    traces, exactly as it would for a pre-AOT checkpoint)."""
    from machine_learning_replications_tpu.obs import journal

    path = os.path.join(
        os.path.abspath(os.fspath(checkpoint_dir)), AOT_DIRNAME
    )
    manifest_path = os.path.join(path, _MANIFEST)
    if not os.path.exists(manifest_path):
        return None
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
        if manifest.get("format") != 1:
            raise ValueError(
                f"unknown aot manifest format {manifest.get('format')!r}"
            )
        manifest["blobs"] = list(manifest["blobs"])
        dict(manifest["fingerprints"])
    except (OSError, ValueError, KeyError, TypeError) as exc:
        # The fallback counter lives with its siblings in serve.engine
        # (one import-time registration site per family); load_bundle's
        # callers are all serving-side, so the import is already paid.
        from machine_learning_replications_tpu.serve.engine import (
            AOT_FALLBACKS,
        )

        AOT_FALLBACKS.inc(reason="manifest_unreadable")
        journal.event(
            "aot_fallback", reason="manifest_unreadable", path=path,
            detail=f"{type(exc).__name__}: {exc}",
        )
        return None
    return AotBundle(path, manifest)


class AotBundle:
    """A loaded ``aot/`` tree: the manifest plus lazy per-bucket blob
    access. ``for_backend`` narrows it to the view one engine consumes
    (the device engine its backend's blobs, the host scorer the CPU
    ones)."""

    def __init__(self, path: str, manifest: dict) -> None:
        self.path = path
        self.manifest = manifest

    @property
    def family(self) -> str | None:
        return self.manifest.get("family")

    def for_backend(self, backend: str) -> "AotView":
        return AotView(self, str(backend))


class AotView:
    """One engine's restore interface (duck-typed by
    ``serve.engine.BucketedPredictEngine``): fingerprint gate +
    per-bucket executable loads. All failure modes raise or return None —
    the ENGINE owns the journaled fails-open fallback policy."""

    def __init__(self, bundle: AotBundle, backend: str) -> None:
        self._bundle = bundle
        self.backend = backend
        self._blobs = {
            int(b["bucket"]): b
            for b in bundle.manifest.get("blobs", ())
            if b.get("backend") == backend
        }

    def unusable_reason(
        self, family: str | None = None
    ) -> tuple[str, str] | None:
        """Why this view cannot restore anything (None = usable), as a
        ``(reason_code, detail)`` pair — the code is the bounded
        ``serve_aot_fallback_total{reason}`` label (missing_backend /
        family_mismatch / fingerprint_mismatch), the detail is free
        text for the journal. Checked ONCE per engine warmup."""
        if not self._blobs:
            return (
                "missing_backend",
                f"no aot blobs for backend {self.backend!r}",
            )
        if family is not None and self._bundle.family != family:
            return (
                "family_mismatch",
                f"aot blobs are for family {self._bundle.family!r}, "
                f"engine serves {family!r}",
            )
        want = self._bundle.manifest.get("fingerprints", {}).get(
            self.backend
        )
        if not isinstance(want, dict):
            return (
                "fingerprint_mismatch",
                f"no fingerprint recorded for backend {self.backend!r}",
            )
        diff = _fingerprint_diff(want, platform_fingerprint(self.backend))
        if diff:
            return (
                "fingerprint_mismatch",
                f"platform fingerprint mismatch: {diff}",
            )
        return None

    def load_exec(self, bucket: int, in_tree, out_tree):
        """Deserialize the bucket's executable (None when the manifest
        has no blob for it). ``in_tree``/``out_tree`` are the call-tree
        structures the engine reconstructs from its own live params — a
        structural mismatch fails the load loudly (and the engine falls
        back to tracing). The ``persist.aot_restore`` faultpoint fires
        here: raise = a failing restore, corrupt = the blob's bytes torn
        on disk — both must resolve to a journaled tracing fallback."""
        from jax.experimental import serialize_executable

        entry = self._blobs.get(int(bucket))
        if entry is None:
            return None
        with open(os.path.join(self._bundle.path, entry["file"]), "rb") as f:
            payload = f.read()
        if faults.fire("persist.aot_restore"):
            payload = (
                bytes([payload[0] ^ 0xFF]) + payload[1:]
                if payload else b"\x00"
            )
        return serialize_executable.deserialize_and_load(
            payload, in_tree, out_tree
        )
