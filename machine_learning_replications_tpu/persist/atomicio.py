"""Durable small-file writes — the one atomic-JSON code path.

The checkpoint layer already had the discipline (``orbax_io`` writes the
integrity manifest and the sidecar with flush+fsync, and publishes whole
trees via one ``os.rename``); the bulk-scoring progress manifest
(``score/progress.py``) needs exactly the same crash contract for a single
JSON file: a reader sees either the previous complete version or the new
complete version, never a torn mix. This module is that pattern factored
out — stdlib-only, jax-free, importable from anywhere.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any


def fsync_json_dump(path: str | os.PathLike, obj: Any, indent: int = 1) -> None:
    """Write ``obj`` as JSON at ``path`` with flush+fsync — durable but
    NOT atomic (for files inside a tree that is itself published by one
    rename, e.g. a checkpoint temp dir)."""
    with open(os.fspath(path), "w") as f:
        json.dump(obj, f, indent=indent)
        f.flush()
        os.fsync(f.fileno())


def atomic_json_write(path: str | os.PathLike, obj: Any, indent: int = 1) -> None:
    """Atomically replace ``path`` with ``obj`` as JSON: full content into
    a same-directory temp file (fsync'd), then one ``os.replace``. A crash
    at any point leaves the previous version intact."""
    path = os.path.abspath(os.fspath(path))
    fd, tmp = tempfile.mkstemp(
        prefix=os.path.basename(path) + ".", dir=os.path.dirname(path)
    )
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(obj, f, indent=indent)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
