"""``python -m machine_learning_replications_tpu`` → the CLI (see ``cli.py``)."""

from machine_learning_replications_tpu.cli import main

raise SystemExit(main())
