"""Full-pipeline differential test — the reference program end to end.

Mirrors ``train_ensemble_public.py``: impute → select 17 → stacking fit on
the development split, evaluate on the model-selection split; sklearn runs
the same protocol on the same synthetic cohort and AUCs must agree within
the BASELINE.json parity budget (±0.005).
"""

import warnings

import numpy as np
import pytest

from machine_learning_replications_tpu.config import ExperimentConfig, GBDTConfig, LassoSelectConfig
from machine_learning_replications_tpu.data.synthetic import dev_select_split
from machine_learning_replications_tpu.models import pipeline


def _sklearn_reference_pipeline(X_dev, y_dev, X_sel):
    from sklearn.ensemble import GradientBoostingClassifier, StackingClassifier
    from sklearn.feature_selection import SelectFromModel
    from sklearn.impute import KNNImputer
    from sklearn.linear_model import LassoCV, LogisticRegression
    from sklearn.pipeline import make_pipeline
    from sklearn.preprocessing import StandardScaler
    from sklearn.svm import SVC

    imputer = KNNImputer(missing_values=np.nan, n_neighbors=1, copy=True)
    X_dev = imputer.fit_transform(X_dev)
    X_sel = imputer.transform(X_sel)
    lasso = LassoCV(random_state=2020, cv=10)
    sfm = SelectFromModel(lasso, threshold=-np.inf, max_features=17).fit(X_dev, y_dev)
    sup = sfm.get_support()
    clf = StackingClassifier(
        estimators=[
            ("svc", make_pipeline(StandardScaler(), SVC(class_weight="balanced", probability=True, random_state=2020))),
            ("gbc", GradientBoostingClassifier(n_estimators=100, max_depth=1, random_state=2020)),
            ("lg", LogisticRegression(class_weight="balanced", penalty="l1", solver="liblinear")),
        ],
        final_estimator=LogisticRegression(class_weight="balanced"),
    )
    clf.fit(X_dev[:, sup], y_dev)
    return clf.predict_proba(X_sel[:, sup])[:, 1], sup


def test_svc_fold_map_sequential_branch_matches_vmap(cohort_full, monkeypatch):
    """Above the lane-memory budget the SVC fold fan-out runs as a
    sequential lax.map (the on-chip OOM fix at cohort scale); it must
    produce the same meta-features as the vmapped branch."""
    from machine_learning_replications_tpu.config import SVCConfig
    from machine_learning_replications_tpu.data.schema import selected_indices
    from machine_learning_replications_tpu.models import pipeline as pl

    X, y, _ = cohort_full
    Xs = np.asarray(X[:300, selected_indices()])
    ys = np.asarray(y[:300])
    cfg = ExperimentConfig(
        gbdt=GBDTConfig(n_estimators=5), svc=SVCConfig(platt_cv=2, max_iter=400)
    )
    meta_vmap = pl.cross_val_member_probas(Xs, ys, cfg)
    monkeypatch.setattr(pl, "_SVC_VMAP_BYTES_BUDGET", 1)  # force lax.map
    meta_seq = pl.cross_val_member_probas(Xs, ys, cfg)
    np.testing.assert_allclose(meta_seq, meta_vmap, rtol=1e-6, atol=1e-9)

    # ...and in the subsampled scaled regime (physical per-fold subsets)
    cfg_sub = ExperimentConfig(
        gbdt=GBDTConfig(n_estimators=5),
        svc=SVCConfig(platt_cv=2, max_iter=400, max_rows=180),
    )
    meta_sub_seq = pl.cross_val_member_probas(Xs, ys, cfg_sub)
    monkeypatch.setattr(pl, "_SVC_VMAP_BYTES_BUDGET", 2 << 30)
    meta_sub_vmap = pl.cross_val_member_probas(Xs, ys, cfg_sub)
    np.testing.assert_allclose(meta_sub_seq, meta_sub_vmap, rtol=1e-6, atol=1e-9)


def test_vmapped_meta_features_match_loop(cohort_full):
    """The vmapped fold fan-out (one XLA program per member for all k
    folds — ``svc_fit_masked`` / ``gbdt.fit_folds`` / masked FISTA) must
    reproduce the sequential per-fold-subset construction it replaced
    (VERDICT.md round-1 item 3). Differences are solver-path-level only:
    the masked SVC dual solves the same convex QP with a different step
    size (full-matrix λmax vs subset λmax), and the fold GBDT bins on the
    full matrix's candidate superset."""
    from machine_learning_replications_tpu.data.schema import selected_indices

    X, y, _ = cohort_full
    # The contractual 17 columns — what fit_stacking actually feeds the fold
    # fan-out. (On heavily continuous columns the GBDT fold fits can pick
    # different near-tied splits, because fit_folds bins candidates on the
    # full matrix while the loop enumerates per-fold exact midpoints — a
    # documented semantic of the masked path, not a bug; see
    # gbdt.fit_folds' docstring.)
    Xs = np.asarray(X[:400, selected_indices()])
    ys = np.asarray(y[:400])
    from machine_learning_replications_tpu.config import SVCConfig

    # Tight dual tolerance: the two paths take different iterate trajectories
    # (full-matrix vs subset step size), so comparing them near the shared
    # optimum needs both to actually reach it.
    cfg = ExperimentConfig(
        gbdt=GBDTConfig(n_estimators=25), svc=SVCConfig(platt_cv=3, tol=3e-7)
    )
    meta_v = pipeline.cross_val_member_probas(Xs, ys, cfg)
    meta_l = pipeline.cross_val_member_probas_loop(Xs, ys, cfg)
    d = np.abs(meta_v - meta_l)
    assert d[:, 0].max() < 6e-3, f"svc meta diff {d[:, 0].max()}"   # dual-solver path
    assert d[:, 1].max() < 6e-3, f"gbdt meta diff {d[:, 1].max()}"  # bin superset
    assert d[:, 2].max() < 1e-7, f"logreg meta diff {d[:, 2].max()}"
    # probabilities, not garbage
    assert ((meta_v > 0) & (meta_v < 1)).all()


@pytest.mark.slow
def test_full_pipeline_auc_parity(cohort_full):
    from sklearn.metrics import roc_auc_score

    X, y, _ = cohort_full
    # add some missingness to exercise imputation
    rng = np.random.default_rng(3)
    Xm = X.copy()
    miss = rng.random(X.shape) < 0.02
    nonbin = np.std(X, axis=0) > 0.51  # rough: only continuous-ish cols
    Xm[miss & nonbin[None, :]] = np.nan

    X_dev, y_dev, X_sel, y_sel = dev_select_split(Xm, y, seed=2020)

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        p_sk, sup_sk = _sklearn_reference_pipeline(X_dev, y_dev, X_sel)

    params, info = pipeline.fit_pipeline(X_dev, y_dev, ExperimentConfig())
    p_us = np.asarray(pipeline.pipeline_predict_proba1(params, X_sel))

    assert info["n_selected"] == 17
    # selected sets should agree (deterministic protocol both sides)
    sup_us = np.asarray(params.support_mask)
    assert (sup_us == sup_sk).mean() >= 62 / 64, (np.where(sup_us)[0], np.where(sup_sk)[0])

    auc_sk = roc_auc_score(y_sel, p_sk)
    auc_us = roc_auc_score(y_sel, p_us)
    assert abs(auc_sk - auc_us) < 0.005, (auc_sk, auc_us)
    # probabilities track closely, not just rank order
    assert np.corrcoef(p_sk, p_us)[0, 1] > 0.99
