"""The unified telemetry layer (obs/): spans, registry, journal, jaxmon,
and the strict Prometheus exposition validator (docs/OBSERVABILITY.md).

The acceptance contract (ISSUE 2): hierarchical spans exporting valid,
containment-correct Chrome trace JSON; a registry whose exposition a
strict Prometheus parser accepts; a journal whose first record is a
manifest carrying git sha + config hash; jax.monitoring compile counters
that move exactly when XLA compiles (new shape: +1, cached shape: +0);
and the serving /metrics page — serve_* families byte-identical to the
standalone render, global registry appended — passing the validator.
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from machine_learning_replications_tpu.obs import jaxmon, journal, registry, spans

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
try:
    import validate_metrics
finally:
    sys.path.pop(0)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


def _x_events(doc):
    return [e for e in doc["traceEvents"] if e["ph"] == "X"]


def test_span_nesting_and_chrome_trace_export(tmp_path):
    """Nested spans export as containment-correct complete events: the
    child's [ts, ts+dur] lies inside the parent's on the same tid, the
    JSON round-trips strictly, and the file is the Chrome trace shape
    Perfetto loads (traceEvents + metadata records)."""
    tr = spans.Tracer("test-proc")
    with tr.span("outer", stage="fit") as outer:
        outer.note(rows=128)
        time.sleep(0.002)
        with tr.span("inner"):
            time.sleep(0.002)
        with tr.span("inner2"):
            pass

    doc = json.loads(json.dumps(tr.export()))  # strict JSON round-trip
    evs = {e["name"]: e for e in _x_events(doc)}
    assert set(evs) == {"outer", "inner", "inner2"}
    out, inn = evs["outer"], evs["inner"]
    assert inn["tid"] == out["tid"] and inn["pid"] == out["pid"]
    assert inn["ts"] >= out["ts"]
    assert inn["ts"] + inn["dur"] <= out["ts"] + out["dur"]
    assert inn["args"]["parent"] == "outer"
    assert evs["inner2"]["args"]["parent"] == "outer"
    assert out["args"] == {"stage": "fit", "rows": 128}

    meta = [e for e in doc["traceEvents"] if e["ph"] == "M"]
    assert {"process_name", "thread_name"} <= {e["name"] for e in meta}

    path = tr.write(tmp_path / "sub" / "trace.json")
    with open(path) as f:
        on_disk = json.load(f)
    assert on_disk["displayTimeUnit"] == "ms"
    assert len(_x_events(on_disk)) == 3


def test_spans_are_thread_aware():
    """Concurrent threads keep independent span stacks: a thread's span
    must not become the parent of another thread's, and each thread gets
    its own tid track."""
    tr = spans.Tracer()
    barrier = threading.Barrier(2)

    def worker(tag):
        with tr.span(f"root-{tag}"):
            barrier.wait(timeout=5)
            with tr.span(f"leaf-{tag}"):
                pass

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    evs = {e["name"]: e for e in _x_events(tr.export())}
    assert evs["leaf-0"]["args"]["parent"] == "root-0"
    assert evs["leaf-1"]["args"]["parent"] == "root-1"
    assert evs["leaf-0"]["tid"] != evs["leaf-1"]["tid"]


def test_span_stack_survives_block_failure():
    """A raising block_until_ready (device error mid-span) must still pop
    the thread's span stack and record the event — a leaked stack entry
    would mis-parent every later span on the thread."""
    class Boom:
        pass

    def bad_block(pending):
        if pending:
            raise RuntimeError("device error")

    tr = spans.Tracer()
    import machine_learning_replications_tpu.obs.spans as spans_mod

    orig = spans_mod._block_pending
    spans_mod._block_pending = bad_block
    try:
        with pytest.raises(RuntimeError, match="device error"):
            with tr.span("failing") as sp:
                sp.block(Boom())
    finally:
        spans_mod._block_pending = orig
    with tr.span("after"):
        pass
    evs = {e["name"]: e for e in _x_events(tr.export())}
    assert set(evs) == {"failing", "after"}
    assert "parent" not in evs["after"]["args"]  # stack was popped


def test_tracer_event_buffer_is_bounded():
    """A long-lived traced serving process emits spans forever; the buffer
    is a ring of the most recent max_events, evictions counted."""
    tr = spans.Tracer(max_events=10)
    for i in range(25):
        with tr.span(f"s{i}"):
            pass
    doc = tr.export()
    xs = _x_events(doc)
    assert len(xs) == 10
    assert [e["name"] for e in xs] == [f"s{i}" for i in range(15, 25)]
    assert doc["otherData"]["dropped_events"] == 15
    # thread metadata survives eviction
    assert any(e["name"] == "thread_name" for e in doc["traceEvents"])


def test_module_span_no_tracer_still_blocks():
    """Without an active tracer the module-level span records nothing but
    still blocks registered device work at exit (the PhaseTimer timing
    contract with tracing off)."""
    import jax.numpy as jnp

    assert spans.get_tracer() is None
    with spans.span("unrecorded") as sp:
        out = sp.block(jnp.ones(4) * 3)
    assert float(out.sum()) == 12.0


def test_phase_timer_is_a_span_adapter():
    """PhaseTimer keeps its API (seconds/counts/report, block-on-exit) and
    now also lands its phases in the active tracer's trace."""
    from machine_learning_replications_tpu.utils.trace import PhaseTimer

    tr = spans.Tracer()
    spans.set_tracer(tr)
    try:
        t = PhaseTimer()
        with t.phase("fit"):
            time.sleep(0.001)
        with t.phase("fit"):
            pass
    finally:
        spans.set_tracer(None)
    assert t.counts == {"fit": 2}
    assert [e["name"] for e in _x_events(tr.export())] == ["fit", "fit"]


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def test_registry_families_and_exposition():
    reg = registry.MetricsRegistry()
    c = reg.counter("demo_bytes_total", "Bytes.", labels=("direction",))
    c.inc(10, direction="h2d")
    c.inc(5, direction="d2h")
    g = reg.gauge("demo_depth", "Depth.")
    g.get().set(3)
    h = reg.histogram("demo_lat_seconds", "Latency.", buckets=(0.1, 1.0),
                      labels=("route",))
    h.observe(0.05, route="a")
    h.observe(2.0, route="a")

    text = reg.render_prometheus()
    assert 'demo_bytes_total{direction="h2d"} 10' in text
    assert "demo_depth 3" in text
    assert 'demo_lat_seconds_bucket{route="a",le="+Inf"} 2' in text
    assert validate_metrics.validate(text) == []

    snap = reg.snapshot()
    assert snap["demo_bytes_total"]["direction=h2d"] == 10
    assert snap["demo_depth"] == 3  # unlabeled: bare value, no "" key
    assert snap["demo_lat_seconds"]["route=a"]["count"] == 2
    json.dumps(snap)

    # idempotent re-declaration; kind/label mismatch rejected
    assert reg.counter("demo_bytes_total", "Bytes.", labels=("direction",)) is c
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("demo_bytes_total", "clash")
    with pytest.raises(ValueError, match="expected labels"):
        c.inc(1, wrong="x")
    with pytest.raises(ValueError):
        reg.counter("0bad", "name")


def test_registry_counter_rejects_negative_and_labels_escape():
    reg = registry.MetricsRegistry()
    c = reg.counter("neg_total", "n")
    with pytest.raises(ValueError):
        c.get().inc(-1)
    g = reg.gauge("esc", "e", labels=("k",))
    g.set(1.0, k='a"b\\c\nd')
    text = reg.render_prometheus()
    assert 'esc{k="a\\"b\\\\c\\nd"} 1.0' in text
    assert validate_metrics.validate(text) == []


def test_serve_metrics_reexports_registry_primitives():
    """The serving layer's instrument classes ARE the obs primitives —
    the backward-compat contract that keeps serve_* behavior identical."""
    from machine_learning_replications_tpu.serve import metrics as sm

    assert sm.Counter is registry.Counter
    assert sm.Gauge is registry.Gauge
    assert sm.Histogram is registry.Histogram
    # and the serving exposition itself passes the strict validator
    m = sm.ServingMetrics()
    m.requests_total.inc(2)
    m.latency.observe(0.01)
    m.batch_size.observe(4)
    m.padding_waste.observe(0)
    assert validate_metrics.validate(m.render_prometheus()) == []


# ---------------------------------------------------------------------------
# journal
# ---------------------------------------------------------------------------


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f]


def test_journal_manifest_first_with_provenance(tmp_path):
    p = tmp_path / "runs" / "run.jsonl"
    with journal.RunJournal(p, command="train",
                            config_json='{"gbdt": 1}') as j:
        j.event("stage_start", stage="impute")
    recs = _read_jsonl(p)
    man = recs[0]
    assert man["kind"] == "manifest"
    assert man["command"] == "train"
    # provenance: this repo is a git checkout → sha must be present
    assert len(man["git_sha"]) == 40
    assert man["config_hash"] == journal.config_hash('{"gbdt": 1}')
    assert man["versions"]["jax"]  # from importlib.metadata, no jax import
    assert man["ts"].endswith("Z") and "T" in man["ts"]
    assert recs[1]["kind"] == "stage_start"


def test_stage_scope_is_the_shared_stage_path(tmp_path, capsys):
    """One code path: grep-identical stderr lines (the pre-obs runners'
    format, ISO-8601-UTC-stamped), a span, and journal events — including
    the checkpointed suffix and the error path."""
    j = journal.RunJournal(tmp_path / "j.jsonl", command="test")
    journal.set_journal(j)
    tr = spans.Tracer()
    spans.set_tracer(tr)
    try:
        with journal.stage_scope("impute"):
            pass
        with journal.stage_scope("member_gbdt", done_suffix=" (checkpointed)"):
            pass
        with pytest.raises(RuntimeError, match="boom"):
            with journal.stage_scope("select"):
                raise RuntimeError("boom")
    finally:
        spans.set_tracer(None)
        journal.set_journal(None)
        j.close()

    err = capsys.readouterr().err
    assert "stage 'impute' ..." in err
    assert "stage 'impute' done in 0.0s\n" in err
    assert "stage 'member_gbdt' done in 0.0s (checkpointed)" in err
    # ISO-8601 UTC stamps on every line (the stage_say timestamp fix)
    import re

    for line in err.strip().splitlines():
        assert re.match(r"\[pipeline \d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}Z\] ", line)

    kinds = [(r["kind"], r.get("stage")) for r in _read_jsonl(j.path)[1:]]
    assert kinds == [
        ("stage_start", "impute"), ("stage_done", "impute"),
        ("stage_start", "member_gbdt"), ("stage_done", "member_gbdt"),
        ("stage_start", "select"), ("stage_error", "select"),
    ]
    assert [e["name"] for e in _x_events(tr.export())] == [
        "stage:impute", "stage:member_gbdt", "stage:select",
    ]


def test_module_event_noop_without_journal():
    journal.event("flush", rows=1)  # must not raise


def test_run_manifest_importable_without_jax():
    """bench.py's orchestrator builds the manifest in a process that must
    never import jax — prove the import graph stays jax-free."""
    import subprocess
    import sys as _sys

    code = (
        "import sys\n"
        "from machine_learning_replications_tpu.obs.journal import run_manifest\n"
        "m = run_manifest(command='bench', config_json='{}')\n"
        "assert 'jax' not in sys.modules, 'obs.journal pulled in jax'\n"
        "assert m['git_sha'] and m['config_hash']\n"
        "print('OK')\n"
    )
    out = subprocess.run(
        [_sys.executable, "-c", code],
        cwd=os.path.join(os.path.dirname(__file__), ".."),
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "OK" in out.stdout


# ---------------------------------------------------------------------------
# jaxmon: compile accounting
# ---------------------------------------------------------------------------


def test_jaxmon_compile_counter_moves_only_on_new_shapes():
    """The acceptance criterion in one test: a jit call with a new shape
    increments jax_compiles_total (and adds compile seconds); the cached
    shape does not."""
    import jax
    import jax.numpy as jnp

    jaxmon.install()
    x_a = jnp.ones((3, 5))
    x_b = jnp.ones((4, 5))  # created BEFORE counting: jnp.ones compiles too

    @jax.jit
    def f(x):
        return (x * 2.0 + 1.0).sum()

    c0, s0 = jaxmon.compile_count(), jaxmon.compile_seconds()
    jax.block_until_ready(f(x_a))
    c1, s1 = jaxmon.compile_count(), jaxmon.compile_seconds()
    assert c1 == c0 + 1 and s1 > s0
    jax.block_until_ready(f(x_a))  # cached shape: no compile
    c2, s2 = jaxmon.compile_count(), jaxmon.compile_seconds()
    assert (c2, s2) == (c1, s1)
    jax.block_until_ready(f(x_b))  # new shape: one compile
    c3 = jaxmon.compile_count()
    assert c3 == c2 + 1

    text = registry.REGISTRY.render_prometheus()
    assert "jax_compiles_total" in text
    assert "jax_compile_seconds_total" in text
    assert validate_metrics.validate(text) == []


def test_jaxmon_device_put_accounts_transfer_bytes():
    import numpy as _np

    jaxmon.install()
    fam = registry.REGISTRY.counter(
        "jax_transfer_bytes_total", "", labels=("direction",)
    )
    before = fam.labels(direction="h2d").value
    x = _np.ones((100, 10), _np.float32)
    jaxmon.device_put(x)
    assert fam.labels(direction="h2d").value == before + x.nbytes


def test_jaxmon_install_idempotent():
    # the public jax.monitoring namespace has no listener getter in this
    # jax version; the private module's list is the ground truth
    from jax._src import monitoring as _mon

    fams1 = jaxmon.install()
    n = len(_mon._event_duration_secs_listeners)
    fams2 = jaxmon.install()
    assert len(_mon._event_duration_secs_listeners) == n
    assert fams1.keys() == fams2.keys()
    # the listeners bind to ONE registry per process: a later install
    # naming a different registry must fail loudly, not silently redirect
    # the accounting away from the page /metrics serves
    with pytest.raises(ValueError, match="different"):
        jaxmon.install(registry.MetricsRegistry())


# ---------------------------------------------------------------------------
# batcher journal events (the serving layer reports into the journal)
# ---------------------------------------------------------------------------


def test_batcher_flush_journals(tmp_path):
    from machine_learning_replications_tpu.serve import MicroBatcher

    class Stub:
        n_features = 17

        def predict(self, X):
            return X.mean(axis=1)

    j = journal.RunJournal(tmp_path / "serve.jsonl", command="serve")
    journal.set_journal(j)
    try:
        b = MicroBatcher(Stub(), max_batch_size=2, max_wait_ms=1.0)
        futs = [b.submit(np.full(17, i)) for i in range(2)]
        assert [f.result(timeout=5.0) for f in futs] == [0.0, 1.0]
        b.close()
    finally:
        journal.set_journal(None)
        j.close()
    flushes = [r for r in _read_jsonl(j.path) if r["kind"] == "flush"]
    assert flushes and all(r["ok"] for r in flushes)
    assert sum(r["rows"] for r in flushes) == 2


# ---------------------------------------------------------------------------
# the validator itself (it guards /metrics — it needs its own tests)
# ---------------------------------------------------------------------------


def test_validator_accepts_known_good_page():
    page = (
        "# HELP up Is it up.\n"
        "# TYPE up gauge\n"
        "up 1\n"
        "# HELP req_total Requests.\n"
        "# TYPE req_total counter\n"
        'req_total{code="200"} 7\n'
        'req_total{code="503"} 1\n'
        "# HELP lat_seconds Latency.\n"
        "# TYPE lat_seconds histogram\n"
        'lat_seconds_bucket{le="0.1"} 1\n'
        'lat_seconds_bucket{le="+Inf"} 2\n'
        "lat_seconds_sum 0.3\n"
        "lat_seconds_count 2\n"
    )
    assert validate_metrics.validate(page) == []


@pytest.mark.parametrize("page, frag", [
    # samples before their TYPE line (the strict-scraper killer)
    ("m 1\n# TYPE m counter\n", "after its samples"),
    # family re-opened after another family (interleaving)
    ("# TYPE a counter\na 1\n# TYPE b counter\nb 1\na 2\n", "re-opened"),
    # duplicate sample
    ('# TYPE c counter\nc{k="x"} 1\nc{k="x"} 2\n', "duplicate sample"),
    # quantile-style sample inside a histogram family (the pre-PR-1 bug)
    ("# TYPE h histogram\n"
     'h_bucket{le="+Inf"} 1\nh_sum 1\nh_count 1\nh{quantile="0.5"} 2\n',
     "not legal in histogram"),
    # non-monotone cumulative buckets
    ("# TYPE h2 histogram\n"
     'h2_bucket{le="0.1"} 5\nh2_bucket{le="+Inf"} 3\nh2_sum 1\nh2_count 3\n',
     "monotonically"),
    # missing +Inf bucket
    ("# TYPE h3 histogram\n"
     'h3_bucket{le="0.1"} 1\nh3_sum 1\nh3_count 1\n', "+Inf"),
    # _count disagrees with the +Inf bucket
    ("# TYPE h4 histogram\n"
     'h4_bucket{le="+Inf"} 2\nh4_sum 1\nh4_count 3\n', "_count"),
    # negative counter
    ("# TYPE n counter\nn -1\n", "non-negative"),
    # malformed label set
    ("# TYPE l counter\nl{k=unquoted} 1\n", "malformed label"),
    # reserved label name
    ('# TYPE r counter\nr{__name__="x"} 1\n', "reserved label"),
    # missing trailing newline
    ("# TYPE t counter\nt 1", "newline"),
    # bad value token
    ("# TYPE v counter\nv one\n", "bad value"),
])
def test_validator_rejects(page, frag):
    errs = validate_metrics.validate(page)
    assert errs, f"expected rejection for {page!r}"
    assert any(frag in e for e in errs), (frag, errs)


def test_validator_cli_roundtrip(tmp_path):
    good = tmp_path / "good.prom"
    good.write_text("# TYPE x counter\nx 1\n")
    bad = tmp_path / "bad.prom"
    bad.write_text("x 1\n# TYPE x counter\n")
    assert validate_metrics.main([str(good)]) == 0
    assert validate_metrics.main([str(bad)]) == 1
