"""Data layer: schema, synthetic cohort marginals, .mat round-trip, sharding."""

import numpy as np
import pytest

from machine_learning_replications_tpu.data import (
    COHORT_SCHEMA,
    SELECTED_17,
    load_data,
    make_cohort,
    pad_rows,
    save_data,
    selected_indices,
    shard_rows,
)


def test_schema_shape():
    assert len(COHORT_SCHEMA) == 64
    assert len(SELECTED_17) == 17
    idx = selected_indices()
    assert len(set(idx)) == 17 and all(0 <= i < 64 for i in idx)


def test_cohort_contract(cohort_full):
    X, y, names = cohort_full
    assert X.shape == (1427, 64) and X.dtype == np.float64
    assert y.shape == (1427,) and set(np.unique(y)) <= {0.0, 1.0}
    assert names.shape == (1, 64)
    # names[0, mask] indexing must work as at train_ensemble_public.py:55
    mask = np.zeros(64, bool)
    mask[selected_indices()] = True
    assert list(names[0, mask]) == [n for n in names[0] if n in SELECTED_17]


def test_cohort_marginals(cohort_full):
    X, y, _ = cohort_full
    # Class prior near the pickle's 19.776 % positive
    assert abs(y.mean() - 0.19776) < 0.04
    # Binary prevalences near Table S1 (±5 pts at n=1427)
    for j, spec in enumerate(COHORT_SCHEMA):
        if spec.kind == "binary":
            assert abs(X[:, j].mean() - spec.p) < 0.05, spec.name
        elif spec.kind == "continuous":
            assert abs(X[:, j].mean() - spec.mean) < max(1.0, 0.15 * spec.mean + 0.2 * spec.sd), spec.name


def test_missingness():
    X, _, _ = make_cohort(n=400, seed=1, missing_rate=0.1)
    nonbin = [j for j, s in enumerate(COHORT_SCHEMA) if s.kind != "binary"]
    binj = [j for j, s in enumerate(COHORT_SCHEMA) if s.kind == "binary"]
    assert np.isnan(X[:, nonbin]).mean() > 0.05
    assert not np.isnan(X[:, binj]).any()


def test_determinism():
    a = make_cohort(n=100, seed=7)[0]
    b = make_cohort(n=100, seed=7)[0]
    np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("backend", ["scipy", "auto"])
def test_mat_roundtrip(tmp_path, cohort, backend):
    X, y, names = cohort
    p = str(tmp_path / "cohort.mat")
    save_data(p, X, y, names)
    X2, y2, names2 = load_data(p, backend=backend)
    np.testing.assert_allclose(X2, X, equal_nan=True)
    np.testing.assert_allclose(y2, y)
    def unwrap(c):
        return str(np.ravel(c)[0]) if isinstance(c, np.ndarray) else str(c)

    assert [unwrap(n) for n in np.ravel(names2)[:3]] == [str(n) for n in names[0, :3]]


def test_pad_rows():
    x = np.arange(10.0).reshape(5, 2)
    p, n = pad_rows(x, 4)
    assert p.shape == (8, 2) and n == 5
    np.testing.assert_array_equal(p[:5], x)
    assert (p[5:] == 0).all()
    p2, n2 = pad_rows(x, 5)
    assert p2.shape == (5, 2) and n2 == 5


def test_shard_rows_8dev(cohort):
    import jax
    from machine_learning_replications_tpu.parallel import make_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh = make_mesh(data=4, model=2)
    X, y, _ = cohort
    (Xd, yd), n_rows = shard_rows(mesh, X, y)
    assert n_rows == X.shape[0]
    assert Xd.shape[0] % 4 == 0
    np.testing.assert_allclose(np.asarray(Xd)[: X.shape[0]], X, equal_nan=True)
    # Sharded over the data axis only
    assert Xd.sharding.spec[0] == "data" and Xd.sharding.spec[1] is None
