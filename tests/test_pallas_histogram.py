"""Pallas histogram kernel vs. the XLA segment_sum oracle.

SURVEY.md §4 "unit tests per kernel (histogram counts vs. numpy oracle)".
On the CPU test mesh the kernel runs in interpret mode; on TPU the same
code lowers through Mosaic (validated on-chip by bench/driver runs).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from machine_learning_replications_tpu.config import GBDTConfig
from machine_learning_replications_tpu.models import gbdt
from machine_learning_replications_tpu.ops import histogram
from machine_learning_replications_tpu.ops.pallas_histogram import (
    node_histograms_pallas,
)


@pytest.mark.parametrize(
    "n,F,K,B",
    [
        (500, 17, 1, 4),      # stump-level: one node, binary-ish bins
        (1000, 17, 4, 16),    # mid-depth level
        (257, 3, 8, 33),      # non-aligned shapes, bins not a power of 2
        (64, 1, 2, 256),      # single feature, full bin budget
    ],
)
def test_matches_segment_sum(rng, n, F, K, B):
    binned = jnp.asarray(rng.integers(0, B, size=(n, F)).astype(np.int32))
    node = jnp.asarray(rng.integers(-1, K, size=n).astype(np.int32))
    g = jnp.asarray(rng.normal(size=n))
    h = jnp.asarray(rng.uniform(0.01, 0.25, size=n))

    ref = histogram.node_histograms(binned, node, g, h, K, B)
    pal = node_histograms_pallas(binned, node, g, h, K, B)
    for name in ("grad", "hess", "grad2", "count"):
        np.testing.assert_allclose(
            np.asarray(getattr(pal, name)),
            np.asarray(getattr(ref, name)),
            rtol=1e-9,
            atol=1e-9,
            err_msg=name,
        )


def test_all_rows_inactive(rng):
    """Every row parked (node −1): histograms must be exactly zero."""
    n, F, K, B = 100, 5, 2, 8
    binned = jnp.asarray(rng.integers(0, B, size=(n, F)).astype(np.int32))
    node = jnp.full(n, -1, jnp.int32)
    g = jnp.asarray(rng.normal(size=n))
    pal = node_histograms_pallas(binned, node, g, g, K, B)
    for name in ("grad", "hess", "grad2", "count"):
        assert not np.asarray(getattr(pal, name)).any(), name


def test_gbdt_depth2_backend_parity(cohort_full):
    """A depth-2 boosted fit grown with the Pallas kernel must match the
    XLA-histogram fit at the model level. The two backends accumulate in
    different orders (MXU contraction vs. scatter-add), so near-tied split
    gains may legitimately resolve differently in the last ulp — parity is
    asserted on deviance and predictions, not on exact split indices."""
    from machine_learning_replications_tpu.data.schema import selected_indices
    from machine_learning_replications_tpu.models import tree

    X, y, _ = cohort_full
    Xs = X[:, selected_indices()]
    base = dict(n_estimators=8, max_depth=2, splitter="hist", n_bins=32)
    px, ax = gbdt.fit(Xs, y, GBDTConfig(**base, histogram_backend="xla"))
    pp, ap = gbdt.fit(Xs, y, GBDTConfig(**base, histogram_backend="pallas"))
    np.testing.assert_allclose(
        ap["train_deviance"], ax["train_deviance"], rtol=1e-9, atol=1e-12
    )
    np.testing.assert_allclose(
        np.asarray(tree.predict_proba1(pp, Xs)),
        np.asarray(tree.predict_proba1(px, Xs)),
        rtol=1e-9,
        atol=1e-12,
    )


def test_backend_resolution():
    assert gbdt.resolve_backend(GBDTConfig(histogram_backend="xla")) == "xla"
    assert gbdt.resolve_backend(GBDTConfig(histogram_backend="pallas")) == "pallas"
    assert gbdt.resolve_backend(GBDTConfig(histogram_backend="matmul")) == "matmul"
    auto = gbdt.resolve_backend(GBDTConfig(histogram_backend="auto"))
    assert auto == ("matmul" if jax.default_backend() == "tpu" else "xla")
    with pytest.raises(ValueError):
        gbdt.resolve_backend(GBDTConfig(histogram_backend="cuda"))


def test_matmul_histogram_matches_segment_sum(rng):
    """The one-hot MXU contraction backend (vmap-composable, per-feature
    bin widths) must agree with the segment_sum oracle, including inactive
    rows and a ragged feature_bins layout."""
    import functools

    import jax
    import jax.numpy as jnp

    from machine_learning_replications_tpu.ops import histogram

    n, K = 3000, 4
    fb = (2, 16, 2, 7, 5)
    binned = jnp.asarray(
        np.stack([rng.integers(0, b, n) for b in fb], axis=1), jnp.int32
    )
    node = jnp.asarray(rng.integers(-1, K, n), jnp.int32)
    g = jnp.asarray(rng.normal(size=n))
    h = jnp.asarray(rng.uniform(size=n))
    B = max(fb)
    ref = histogram.node_histograms(binned, node, g, h, K, B)
    got = histogram.node_histograms_matmul(
        binned, node, g, h, K, B, chunk=512, feature_bins=fb
    )
    for a, b, name in zip(got, ref, ("grad", "hess", "grad2", "count")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-12, atol=1e-12, err_msg=name
        )
    # composes with vmap over node assignments (the fold fan-out shape)
    nodes2 = jnp.stack([node, jnp.flip(node)])
    fn = functools.partial(
        histogram.node_histograms_matmul, chunk=512, feature_bins=fb
    )
    v = jax.vmap(lambda nd: fn(binned, nd, g, h, K, B).grad)(nodes2)
    np.testing.assert_allclose(np.asarray(v[0]), np.asarray(ref.grad))


def test_stump_histograms_backends_agree():
    """The fused depth-1 stage's statistics pass (K=1, two stats) must be
    backend-independent: 'xla' (segment_sum, the CPU pick), 'matmul'
    (chunked one-hot MXU scan) and 'pallas' (VMEM kernel, interpret mode
    here) — the latter two are what the TPU fused path actually selects,
    so they must not only be covered on the CPU mesh via interpret mode
    but agree with the scatter-add oracle to summation tolerance. Also
    pins the u8 bin-matrix dtype the fused call site uses."""
    import jax.numpy as jnp

    from machine_learning_replications_tpu.ops import histogram

    rng = np.random.default_rng(42)
    n, F, B = 5000, 5, 32
    binned = jnp.asarray(rng.integers(0, B, (n, F)), jnp.uint8)
    g = jnp.asarray(rng.normal(size=n), jnp.float32)
    h = jnp.asarray(rng.uniform(size=n), jnp.float32)

    ref = histogram.stump_histograms(binned, g, h, B, backend="xla")
    assert ref.shape == (2, F, B)
    # oracle: dense numpy accumulation
    bn = np.asarray(binned)
    want = np.zeros((2, F, B))
    for f in range(F):
        for stat, v in enumerate((np.asarray(g), np.asarray(h))):
            np.add.at(want[stat, f], bn[:, f], v)
    np.testing.assert_allclose(np.asarray(ref), want, rtol=1e-4, atol=1e-4)

    got_m = histogram.stump_histograms(binned, g, h, B, backend="matmul",
                                       chunk=512)
    np.testing.assert_allclose(
        np.asarray(got_m), np.asarray(ref), rtol=1e-5, atol=1e-5,
        err_msg="matmul",
    )
    from machine_learning_replications_tpu.ops.pallas_histogram import (
        stump_histograms_pallas,
    )

    got_p = stump_histograms_pallas(binned, g, h, B)
    np.testing.assert_allclose(
        np.asarray(got_p), np.asarray(ref), rtol=1e-5, atol=1e-5,
        err_msg="pallas",
    )
