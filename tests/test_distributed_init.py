"""parallel.distributed — single-host no-op contract, env parsing, mesh."""

import numpy as np
import pytest

from machine_learning_replications_tpu.parallel import distributed, DATA_AXIS


def test_single_host_noop(monkeypatch):
    """No args, no env vars, auto disabled: must be a clean no-op."""
    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "JAX_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    assert distributed.initialize_distributed(auto=False) is False


def test_env_var_parsing_malformed(monkeypatch):
    monkeypatch.setenv("JAX_NUM_PROCESSES", "not-a-number")
    with pytest.raises(ValueError):
        distributed.initialize_distributed(auto=False)


def test_process_info_single_host():
    idx, count = distributed.process_info()
    assert idx == 0 and count == 1


def test_global_mesh_spans_devices():
    mesh = distributed.global_mesh(model=2)
    assert mesh.shape[DATA_AXIS] * mesh.shape["model"] == 8
    assert mesh.shape["model"] == 2
    mesh_all = distributed.global_mesh()
    assert int(np.prod(list(mesh_all.shape.values()))) == 8


def test_two_process_distributed_smoke():
    """Actually execute the multi-process path (VERDICT r3 missing #3):
    two subprocess workers join one jax.distributed coordination service on
    localhost, see a 4-device global view (2 virtual CPU devices each), and
    psum a row-sharded array across processes through
    initialize_distributed + global_mesh — then TRAIN across the boundary
    (VERDICT r4 missing #3): fit_gbdt_sharded over the 2-process global
    mesh, stage-parity vs a local single-device fit, asserted inside each
    worker. Skipped only when the sandbox forbids the localhost socket."""
    import os
    import socket
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # A free localhost port for the coordinator.
    with socket.socket() as s:
        try:
            s.bind(("127.0.0.1", 0))
        except OSError as e:
            pytest.skip(f"sandbox forbids localhost sockets: {e}")
        port = s.getsockname()[1]
    addr = f"127.0.0.1:{port}"

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # the TPU plugin must not load
    env.pop("PYTHONPATH", None)
    env.pop("XLA_FLAGS", None)  # workers set their own device count
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(repo, "tests", "distributed_worker.py"),
             addr, "2", str(i)],
            cwd=repo, env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"distributed smoke timed out; outputs so far: {outs}")

    combined = "\n".join(outs)
    if any(p.returncode for p in procs) and (
        "PERMISSION_DENIED" in combined or "Permission denied" in combined
        or "UNAVAILABLE: Failed to connect" in combined
    ):
        pytest.skip(f"coordination service blocked by sandbox: {combined[-500:]}")
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"worker rc={p.returncode}:\n{out[-2000:]}"
        assert "SMOKE_OK 10.0 2 4" in out, out[-2000:]
        # the cross-process sharded fit ran and matched the local
        # single-device fit inside the worker
        assert "FIT_OK 3 " in out, out[-2000:]
