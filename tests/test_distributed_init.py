"""parallel.distributed — single-host no-op contract, env parsing, mesh."""

import numpy as np
import pytest

from machine_learning_replications_tpu.parallel import distributed, DATA_AXIS


def test_single_host_noop(monkeypatch):
    """No args, no env vars, auto disabled: must be a clean no-op."""
    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES", "JAX_PROCESS_ID"):
        monkeypatch.delenv(var, raising=False)
    assert distributed.initialize_distributed(auto=False) is False


def test_env_var_parsing_malformed(monkeypatch):
    monkeypatch.setenv("JAX_NUM_PROCESSES", "not-a-number")
    with pytest.raises(ValueError):
        distributed.initialize_distributed(auto=False)


def test_process_info_single_host():
    idx, count = distributed.process_info()
    assert idx == 0 and count == 1


def test_global_mesh_spans_devices():
    mesh = distributed.global_mesh(model=2)
    assert mesh.shape[DATA_AXIS] * mesh.shape["model"] == 8
    assert mesh.shape["model"] == 2
    mesh_all = distributed.global_mesh()
    assert int(np.prod(list(mesh_all.shape.values()))) == 8
