"""Inference parity: our JAX predict paths vs sklearn, and vs the shipped pickle.

Strategy (SURVEY.md §4): fit *live* sklearn estimators on synthetic data,
convert their fitted state into our pytrees with the same converters used for
the legacy pickle, and demand (near-)bitwise agreement of predict_proba.
Then decode the shipped sklearn-0.23.2 artifact and check the decoded
constants against SURVEY.md §2.3 plus a closed-form numpy recomputation of
the stacked probability on the reference's example patient.
"""

import warnings

import numpy as np
import pytest

import jax

from machine_learning_replications_tpu.data.examples import EXAMPLE_PATIENT, patient_row
from machine_learning_replications_tpu.models import linear, scaler, stacking, svm, tree
from machine_learning_replications_tpu.persist import (
    REFERENCE_PKL_PATH,
    decode_pickle,
    import_gbdt,
    import_linear,
    import_scaler,
    import_stacking,
    import_svc,
)


@pytest.fixture(scope="module")
def fit_data():
    rng = np.random.default_rng(42)
    n, f = 400, 17
    X = rng.normal(size=(n, f))
    X[:, :10] = (X[:, :10] > 0.3).astype(float)  # mostly-binary like the cohort
    w = rng.normal(size=f)
    y = (X @ w + rng.normal(size=n) > 0.2).astype(float)
    Xq = rng.normal(size=(100, f))
    Xq[:, :10] = (Xq[:, :10] > 0.3).astype(float)
    return X, y, Xq


def test_svc_parity(fit_data):
    from sklearn.pipeline import make_pipeline
    from sklearn.preprocessing import StandardScaler
    from sklearn.svm import SVC

    X, y, Xq = fit_data
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        pipe = make_pipeline(
            StandardScaler(),
            SVC(class_weight="balanced", probability=True, random_state=2020),
        ).fit(X, y)
    sk_sc, sk_svc = pipe.steps[0][1], pipe.steps[1][1]
    sp = import_scaler(sk_sc)
    vp = import_svc(sk_svc)

    Xt = scaler.transform(sp, Xq)
    np.testing.assert_allclose(
        np.asarray(Xt), sk_sc.transform(Xq), rtol=1e-12, atol=1e-12
    )
    dec = svm.decision_function(vp, Xt)
    np.testing.assert_allclose(
        np.asarray(dec), sk_svc.decision_function(sk_sc.transform(Xq)), rtol=1e-9, atol=1e-11
    )
    # Exact libsvm binary probability (incl. coupling iteration + clipping)
    p1 = jax.jit(svm.predict_proba1)(vp, Xt)
    p_ref = pipe.predict_proba(Xq)[:, 1]
    np.testing.assert_allclose(np.asarray(p1), p_ref, rtol=1e-10, atol=1e-12)
    # Closed-form sigmoid within the coupling solver's tolerance
    p_sig = svm.predict_proba1_sigmoid(vp, Xt)
    assert np.abs(np.asarray(p_sig) - p_ref).max() < 5e-3


@pytest.mark.parametrize("max_depth", [1, 3])
def test_gbdt_parity(fit_data, max_depth):
    from sklearn.ensemble import GradientBoostingClassifier

    X, y, Xq = fit_data
    gbc = GradientBoostingClassifier(
        n_estimators=50, max_depth=max_depth, random_state=2020
    ).fit(X, y)
    tp = import_gbdt(gbc)
    assert tp.max_depth == max_depth  # these fits always reach their depth cap
    raw = jax.jit(tree.raw_score)(tp, Xq)
    np.testing.assert_allclose(
        np.asarray(raw), gbc.decision_function(Xq), rtol=1e-12, atol=1e-12
    )
    p1 = tree.predict_proba1(tp, Xq)
    np.testing.assert_allclose(
        np.asarray(p1), gbc.predict_proba(Xq)[:, 1], rtol=1e-12, atol=1e-12
    )


def test_logreg_parity(fit_data):
    from sklearn.linear_model import LogisticRegression

    X, y, Xq = fit_data
    lr = LogisticRegression(
        class_weight="balanced", penalty="l1", solver="liblinear"
    ).fit(X, y)
    lp = import_linear(lr)
    p1 = jax.jit(linear.predict_proba1)(lp, Xq)
    np.testing.assert_allclose(
        np.asarray(p1), lr.predict_proba(Xq)[:, 1], rtol=1e-12, atol=1e-12
    )


@pytest.fixture(scope="module")
def sk_stacking(fit_data):
    from sklearn.ensemble import GradientBoostingClassifier, StackingClassifier
    from sklearn.linear_model import LogisticRegression
    from sklearn.pipeline import make_pipeline
    from sklearn.preprocessing import StandardScaler
    from sklearn.svm import SVC

    X, y, _ = fit_data
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        clf = StackingClassifier(
            estimators=[
                (
                    "svc",
                    make_pipeline(
                        StandardScaler(),
                        SVC(class_weight="balanced", probability=True, random_state=2020),
                    ),
                ),
                ("gbc", GradientBoostingClassifier(n_estimators=50, max_depth=1, random_state=2020)),
                ("lg", LogisticRegression(class_weight="balanced", penalty="l1", solver="liblinear")),
            ],
            final_estimator=LogisticRegression(class_weight="balanced"),
        ).fit(X, y)
    return clf


def test_stacking_parity(fit_data, sk_stacking):
    _, _, Xq = fit_data
    params = import_stacking(sk_stacking)
    p = jax.jit(stacking.predict_proba)(params, Xq)
    p_ref = sk_stacking.predict_proba(Xq)
    np.testing.assert_allclose(np.asarray(p), p_ref, rtol=1e-9, atol=1e-11)


# ---------------------------------------------------------------------------
# The shipped 0.23.2 artifact — the reference's parity oracle (SURVEY.md §2.3)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def shipped_params():
    return import_stacking(decode_pickle(REFERENCE_PKL_PATH))


def test_decoded_constants(shipped_params):
    p = shipped_params
    # Meta-LR weights for [svc, gbc, lg] and intercept (SURVEY.md §2.3)
    np.testing.assert_allclose(
        np.asarray(p.meta.coef), [1.83724, 0.41021, 2.88042], atol=1e-4
    )
    np.testing.assert_allclose(float(p.meta.intercept), -1.98943, atol=1e-4)
    assert p.svc.support_vectors.shape == (434, 17)
    np.testing.assert_allclose(float(p.svc.intercept), -0.09879, atol=1e-4)
    np.testing.assert_allclose(float(p.svc.prob_a), -1.25858, atol=1e-4)
    np.testing.assert_allclose(float(p.svc.prob_b), -1.18972, atol=1e-4)
    np.testing.assert_allclose(float(p.svc.gamma), 1 / 17, atol=1e-6)
    assert p.gbdt.feature.shape[0] == 100 and p.gbdt.max_depth == 1
    np.testing.assert_allclose(float(p.gbdt.init_raw), -1.4005, atol=1e-3)
    np.testing.assert_allclose(float(p.gbdt.learning_rate), 0.1)
    # Stump 0 splits Dyspnea (feature 3) at 0.5 with leaves [-0.77138, +0.97464]
    assert int(p.gbdt.feature[0, 0]) == 3
    np.testing.assert_allclose(float(p.gbdt.threshold[0, 0]), 0.5)
    np.testing.assert_allclose(
        np.sort(np.asarray(p.gbdt.value[0, 1:3])), [-0.77138, 0.97464], atol=1e-4
    )
    # L1-LR coefs
    np.testing.assert_allclose(
        np.asarray(p.logreg.coef)[:3], [1.1247, -0.2490, 0.3900], atol=1e-3
    )


def test_shipped_model_inference(shipped_params):
    """predict_hf.py equivalent: stacked probability for the example patient,
    cross-checked against an independent closed-form numpy recomputation."""
    X = patient_row()
    p = float(stacking.predict_proba1(shipped_params, X)[0])
    assert 0.0 < p < 1.0

    # Independent numpy recomputation (SURVEY.md §3.4) — no JAX involved.
    sp = shipped_params
    z = (X - np.asarray(sp.scaler.mean)) / np.asarray(sp.scaler.scale)
    K = np.exp(
        -float(sp.svc.gamma)
        * ((z[:, None, :] - np.asarray(sp.svc.support_vectors)[None]) ** 2).sum(-1)
    )
    dec = K @ np.asarray(sp.svc.dual_coef) + float(sp.svc.intercept)
    p_svc = 1 / (1 + np.exp(float(sp.svc.prob_a) * dec - float(sp.svc.prob_b)))
    raw = float(sp.gbdt.init_raw)
    for t in range(100):
        f0 = int(sp.gbdt.feature[t, 0])
        thr = float(sp.gbdt.threshold[t, 0])
        lchild = int(sp.gbdt.left[t, 0])
        rchild = int(sp.gbdt.right[t, 0])
        leaf = lchild if X[0, f0] <= thr else rchild
        raw += 0.1 * float(sp.gbdt.value[t, leaf])
    p_gbc = 1 / (1 + np.exp(-raw))
    p_lg = 1 / (1 + np.exp(-(X @ np.asarray(sp.logreg.coef) + float(sp.logreg.intercept))))
    meta = np.array([p_svc[0], p_gbc, p_lg[0]])
    p_np = 1 / (1 + np.exp(-(meta @ np.asarray(sp.meta.coef) + float(sp.meta.intercept))))
    # SVC coupling vs sigmoid differ by <3e-3; meta weights amplify slightly
    assert abs(p - p_np) < 2e-2
    # And the printed contract of predict_hf.py:38-40
    print(f"Probability of progressive HF is: {100 * p:.2f} %")


def test_example_patient_contract():
    row = patient_row()
    assert row.shape == (1, 17)
    assert row[0, 13] == 13.0 and row[0, 16] == 55.0
    assert list(EXAMPLE_PATIENT)[0] == "Obstructive HCM"
