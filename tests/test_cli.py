"""CLI subcommands (L6') driven end-to-end through ``cli.main``.

The reference's entry points are bare scripts with hard-coded inputs
(``train_ensemble_public.py:34-39``, ``predict_hf.py:5-27``); these tests
pin the subcommand equivalents, including the exact inference output
contract "Probability of progressive HF is: XX.XX %" (``predict_hf.py:38-40``).
"""

import json
import os
import re

import numpy as np
import pytest

from machine_learning_replications_tpu import cli

_HAVE_REFERENCE_PKL = os.path.exists(
    "/root/reference/Machine Learning for Predicting Heart Failure "
    "Progression/hf_predict_model.pkl"
)


def _fast_config(tmp_path):
    cfg = {
        "gbdt": {"n_estimators": 5},
        "svc": {"platt_cv": 2, "max_iter": 2000},
        "stacking": {"cv_folds": 2},
        "select": {"cv_folds": 3, "n_alphas": 20},
    }
    p = tmp_path / "cfg.json"
    p.write_text(json.dumps(cfg))
    return str(p)


@pytest.mark.skipif(not _HAVE_REFERENCE_PKL, reason="reference pkl absent")
def test_predict_reference_pickle(capsys):
    assert cli.main(["predict"]) == 0
    out = capsys.readouterr().out
    m = re.search(r"Probability of progressive HF is: (\d+\.\d{2}) %", out)
    assert m, out
    # cross-check against the direct import path
    from machine_learning_replications_tpu.data.examples import patient_row
    from machine_learning_replications_tpu.models import stacking
    from machine_learning_replications_tpu.persist import (
        REFERENCE_PKL_PATH,
        decode_pickle,
        import_stacking,
    )

    params = import_stacking(decode_pickle(REFERENCE_PKL_PATH))
    prob = float(stacking.predict_proba1(params, patient_row().reshape(1, -1))[0])
    assert abs(float(m.group(1)) - 100 * prob) < 0.005


@pytest.mark.skipif(not _HAVE_REFERENCE_PKL, reason="reference pkl absent")
def test_predict_patient_json(tmp_path, capsys):
    from machine_learning_replications_tpu.data.examples import EXAMPLE_PATIENT

    patient = dict(EXAMPLE_PATIENT)
    patient["Dyspnea"] = 1  # stump-0 split feature — must move the output
    pj = tmp_path / "p.json"
    pj.write_text(json.dumps(patient))
    assert cli.main(["predict", "--patient", str(pj)]) == 0
    out1 = capsys.readouterr().out
    assert cli.main(["predict"]) == 0
    out2 = capsys.readouterr().out
    assert out1 != out2

    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"Not_A_Variable": 1}))
    with pytest.raises(SystemExit):
        cli.main(["predict", "--patient", str(bad)])

    partial = tmp_path / "partial.json"
    partial.write_text(json.dumps({"Dyspnea": 1}))
    with pytest.raises(SystemExit, match="missing"):
        cli.main(["predict", "--patient", str(partial)])


def test_train_save_plots_predict_roundtrip(tmp_path, capsys):
    ckpt = tmp_path / "model"
    plots = tmp_path / "plots"
    trace_dir = tmp_path / "traces"
    journal_path = tmp_path / "run.jsonl"
    rc = cli.main([
        "train",
        "--synthetic", "160",
        "--config", _fast_config(tmp_path),
        "--save", str(ckpt),
        "--plots", str(plots),
        "--trace-dir", str(trace_dir),
        "--journal", str(journal_path),
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "AUC-ROC" in out and "precision" in out
    assert (plots / "roc.png").exists() and (plots / "pr.png").exists()

    # --- observability artifacts (ISSUE 2 acceptance: a train run yields a
    # Perfetto-loadable trace and a journal whose first record is a
    # manifest with git sha + config hash) ------------------------------
    import hashlib

    with open(journal_path) as f:
        records = [json.loads(line) for line in f]
    man = records[0]
    assert man["kind"] == "manifest" and man["command"] == "train"
    assert len(man["git_sha"]) == 40
    with open(_fast_config(tmp_path)) as f:
        from machine_learning_replications_tpu.config import ExperimentConfig

        cfg_json = ExperimentConfig.from_json(f.read()).to_json()
    assert man["config_hash"] == hashlib.sha256(cfg_json.encode()).hexdigest()
    kinds = [r["kind"] for r in records[1:]]
    # every pipeline stage journaled, run closed with compile totals
    assert kinds.count("stage_start") >= 6  # impute..meta + sub-stages
    assert kinds[-1] == "run_done"
    assert records[-1]["jax_compiles"] > 0

    with open(trace_dir / "trace.json") as f:
        trace_doc = json.load(f)
    span_names = [e["name"] for e in trace_doc["traceEvents"]
                  if e.get("ph") == "X"]
    assert "train" in span_names and "fit_pipeline" in span_names
    assert any(n.startswith("stage:") for n in span_names)
    # the stage spans nest under the root command span
    stage_ev = next(e for e in trace_doc["traceEvents"]
                    if e.get("ph") == "X" and e["name"] == "stage:impute")
    root_ev = next(e for e in trace_doc["traceEvents"]
                   if e.get("ph") == "X" and e["name"] == "train")
    assert root_ev["ts"] <= stage_ev["ts"]
    assert stage_ev["ts"] + stage_ev["dur"] <= root_ev["ts"] + root_ev["dur"]

    assert cli.main(["predict", "--model", str(ckpt)]) == 0
    out = capsys.readouterr().out
    m = re.search(r"Probability of progressive HF is: (\d+\.\d{2}) %", out)
    assert m

    # The printed probability must equal routing the example patient through
    # the pipeline itself (guards against feature-order mismatches between
    # the contractual 17-variable row and the lasso-selected columns).
    from machine_learning_replications_tpu.data.examples import patient_row
    from machine_learning_replications_tpu.data.schema import selected_indices
    from machine_learning_replications_tpu.models import pipeline
    from machine_learning_replications_tpu.persist import orbax_io

    params = orbax_io.load_model(str(ckpt))
    x64 = np.full((1, int(params.support_mask.shape[0])), np.nan)
    x64[0, selected_indices()] = patient_row().ravel()
    prob = float(pipeline.pipeline_predict_proba1(params, x64)[0])
    assert abs(float(m.group(1)) - 100 * prob) < 0.005


def test_train_mesh_flag_routes_sharded(tmp_path, capsys):
    """`train --mesh 4,2` fits the GBDT member through the row-sharded
    trainers on the virtual CPU mesh and matches the meshless train's
    reported AUC (sharded == single-device parity at the CLI level)."""
    rc = cli.main([
        "train",
        "--synthetic", "160",
        "--config", _fast_config(tmp_path),
        "--mesh", "4,2",
    ])
    assert rc == 0
    captured = capsys.readouterr()
    assert "mesh {'data': 4, 'model': 2}" in captured.err
    m = re.search(r"AUC-ROC (\d+\.\d+)", captured.out)
    assert m
    auc_sharded = float(m.group(1))

    rc = cli.main([
        "train", "--synthetic", "160", "--config", _fast_config(tmp_path),
    ])
    assert rc == 0
    m2 = re.search(r"AUC-ROC (\d+\.\d+)", capsys.readouterr().out)
    assert abs(float(m2.group(1)) - auc_sharded) < 1e-6


def test_sweep_cli(tmp_path, capsys):
    rc = cli.main([
        "sweep",
        "--synthetic", "200",
        "--n-estimators", "5", "10",
        "--max-depth", "1", "2",
        "--folds", "2",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "best: n_estimators=" in out


@pytest.mark.skipif(not _HAVE_REFERENCE_PKL, reason="reference pkl absent")
def test_import_sklearn_roundtrip(tmp_path, capsys):
    ckpt = tmp_path / "imported"
    assert cli.main(["import-sklearn", "--out", str(ckpt)]) == 0
    capsys.readouterr()
    assert cli.main(["predict", "--model", str(ckpt)]) == 0
    out_ckpt = capsys.readouterr().out
    assert cli.main(["predict"]) == 0
    out_pkl = capsys.readouterr().out
    assert out_ckpt == out_pkl
