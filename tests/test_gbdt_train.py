"""GBDT training parity vs sklearn's GradientBoostingClassifier.

The exact-midpoint binning regime (n_unique ≤ n_bins) makes our histogram
split search enumerate the same candidate set as sklearn's BestSplitter, so
on generic data (no exact gain ties) the fitted forests should agree
structurally — features, thresholds, leaf values — and numerically in the
deviance path and predictions. SURVEY.md §4 "training-parity" tests.
"""

import numpy as np
import pytest
from sklearn.ensemble import GradientBoostingClassifier

from machine_learning_replications_tpu.config import GBDTConfig
from machine_learning_replications_tpu.models import gbdt, tree


@pytest.fixture(scope="module")
def train_data():
    rng = np.random.default_rng(7)
    n, f = 500, 17
    X = rng.normal(size=(n, f))
    X[:, :12] = (X[:, :12] > 0.4).astype(float)   # mostly binary, like the cohort
    X[:, 12:] = np.round(X[:, 12:] * 8) / 2       # coarse-grained continuous
    w = rng.normal(size=f)
    y = (X @ w + 0.8 * rng.normal(size=n) > 0.3).astype(float)
    return X, y


@pytest.mark.parametrize("max_depth,n_estimators", [(1, 60), (2, 25)])
def test_structural_and_numeric_parity(train_data, max_depth, n_estimators):
    X, y = train_data
    sk = GradientBoostingClassifier(
        n_estimators=n_estimators, max_depth=max_depth, random_state=2020
    ).fit(X, y)
    params, aux = gbdt.fit(
        X, y, GBDTConfig(n_estimators=n_estimators, max_depth=max_depth)
    )

    # Deviance trajectory — same −2·loglik definition as the 0.23 pickle
    np.testing.assert_allclose(aux["train_deviance"], sk.train_score_, rtol=1e-9)

    # Per-stage root split must match sklearn exactly
    for t in range(n_estimators):
        sk_tree = sk.estimators_[t, 0].tree_
        assert int(params.feature[t, 0]) == int(sk_tree.feature[0]), f"stage {t}"
        np.testing.assert_allclose(
            float(params.threshold[t, 0]), float(sk_tree.threshold[0]), rtol=1e-12,
            err_msg=f"stage {t}",
        )

    # Raw predictions identical ⇒ every leaf value/structure effect matches
    rng = np.random.default_rng(1)
    Xq = X[rng.permutation(len(X))[:200]]
    np.testing.assert_allclose(
        np.asarray(tree.raw_score(params, Xq)),
        sk.decision_function(Xq),
        rtol=1e-9,
        atol=1e-10,
    )


def test_depth3_metric_parity(train_data):
    """At depth 3 this dataset hits *exact* gain ties resolved differently
    (sklearn uses a seeded feature permutation; we take first-in-order —
    verified to be true ties, equal friedman proxies). Demand metric-level
    parity instead of structural parity (SURVEY.md §7 'RNG parity')."""
    from sklearn.metrics import roc_auc_score

    X, y = train_data
    sk = GradientBoostingClassifier(n_estimators=12, max_depth=3, random_state=2020).fit(X, y)
    params, aux = gbdt.fit(X, y, GBDTConfig(n_estimators=12, max_depth=3))
    np.testing.assert_allclose(aux["train_deviance"], sk.train_score_, rtol=0.03)
    a_sk = roc_auc_score(y, sk.predict_proba(X)[:, 1])
    a_us = roc_auc_score(y, np.asarray(tree.predict_proba1(params, X)))
    assert abs(a_sk - a_us) < 0.005


def test_stump_leaf_values_match(train_data):
    X, y = train_data
    sk = GradientBoostingClassifier(n_estimators=5, max_depth=1, random_state=2020).fit(X, y)
    params, _ = gbdt.fit(X, y, GBDTConfig(n_estimators=5, max_depth=1))
    for t in range(5):
        sk_vals = np.sort(sk.estimators_[t, 0].tree_.value[1:3, 0, 0])
        ours = np.sort(np.asarray(params.value[t, 1:3]))
        np.testing.assert_allclose(ours, sk_vals, rtol=1e-9)


def test_auc_parity(train_data):
    from sklearn.metrics import roc_auc_score

    X, y = train_data
    rng = np.random.default_rng(9)
    perm = rng.permutation(len(X))
    tr, te = perm[:350], perm[350:]
    sk = GradientBoostingClassifier(n_estimators=100, max_depth=1, random_state=2020).fit(
        X[tr], y[tr]
    )
    params, _ = gbdt.fit(X[tr], y[tr], GBDTConfig(n_estimators=100, max_depth=1))
    auc_sk = roc_auc_score(y[te], sk.predict_proba(X[te])[:, 1])
    auc_tpu = roc_auc_score(y[te], np.asarray(tree.predict_proba1(params, X[te])))
    assert abs(auc_sk - auc_tpu) < 0.005  # BASELINE.json parity budget


def test_pure_node_becomes_leaf():
    # Constant labels in a region: once residuals are uniform the node must
    # not split (sklearn's impurity <= eps leaf test).
    X = np.array([[0.0]] * 50 + [[1.0]] * 50)
    y = np.array([0.0] * 50 + [1.0] * 50)
    params, _ = gbdt.fit(X, y, GBDTConfig(n_estimators=3, max_depth=3))
    sk = GradientBoostingClassifier(n_estimators=3, max_depth=3, random_state=0).fit(X, y)
    np.testing.assert_allclose(
        np.asarray(tree.raw_score(params, X)), sk.decision_function(X), rtol=1e-9
    )


def test_quantized_regime_close():
    # >n_bins unique values: approximate splits; demand metric-level parity.
    from sklearn.metrics import roc_auc_score

    rng = np.random.default_rng(3)
    X = rng.normal(size=(2000, 5))
    y = (X @ rng.normal(size=5) + rng.normal(size=2000) > 0).astype(float)
    params, _ = gbdt.fit(X, y, GBDTConfig(n_estimators=40, max_depth=2, n_bins=64))
    sk = GradientBoostingClassifier(n_estimators=40, max_depth=2, random_state=0).fit(X, y)
    a1 = roc_auc_score(y, np.asarray(tree.predict_proba1(params, X)))
    a2 = roc_auc_score(y, sk.predict_proba(X)[:, 1])
    assert abs(a1 - a2) < 0.01


def test_exact_splitter_high_cardinality():
    """'exact' enumerates all unique midpoints even past 256 uniques
    (uint16 stump layout) and matches sklearn stump-for-stump; 'hist'
    quantizes. The reference workload never exceeds 256, but the scaled
    configs do."""
    rng = np.random.default_rng(11)
    n = 700
    X = np.stack(
        [rng.normal(size=n), (rng.random(n) > 0.7).astype(float)], axis=1
    )  # feature 0: ~700 unique values
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.6 * rng.normal(size=n) > 0).astype(float)

    sk = GradientBoostingClassifier(
        n_estimators=12, max_depth=1, random_state=0
    ).fit(X, y)
    ours, _ = gbdt.fit(X, y, GBDTConfig(n_estimators=12, splitter="exact"))
    for m in range(12):
        skt = sk.estimators_[m, 0].tree_
        assert int(ours.feature[m, 0]) == int(skt.feature[0])
        # sklearn casts X to float32 before midpoints; we keep float64,
        # so thresholds agree only to float32 resolution.
        np.testing.assert_allclose(
            float(ours.threshold[m, 0]), float(skt.threshold[0]), rtol=1e-6
        )

    # hist (capped) still within the AUC budget, with far fewer candidates
    from sklearn.metrics import roc_auc_score

    h, _ = gbdt.fit(X, y, GBDTConfig(n_estimators=12, splitter="hist", n_bins=64))
    auc_h = roc_auc_score(y, np.asarray(tree.predict_proba1(h, X)))
    auc_sk = roc_auc_score(y, sk.predict_proba(X)[:, 1])
    assert abs(auc_h - auc_sk) < 0.005

    with pytest.raises(ValueError, match="unknown splitter"):
        gbdt.fit(X, y, GBDTConfig(splitter="bogus"))


def test_device_stump_layout_equals_host_build(train_data):
    """``build_stump_data_device`` (what every depth-1 fit now uses) must
    reproduce the host numpy build bit for bit — the host build stays alive
    as this oracle (stable device argsort == numpy stable argsort is the
    correctness argument for moving the layout on-device)."""
    from machine_learning_replications_tpu.ops import binning, histogram

    X, y = train_data
    for budget in (None, 16):  # exact enumeration and capped-quantile regimes
        bins = binning.bin_features(X, budget)
        host = histogram.build_stump_data(bins, y)
        dev = histogram.build_stump_data_device(bins, y)
        for name in ("bins_x", "y_sorted", "left_count", "thresholds"):
            np.testing.assert_array_equal(
                np.asarray(getattr(host, name)), np.asarray(getattr(dev, name)),
                err_msg=f"{name} (bin budget {budget})",
            )


def test_fused_hist1_matches_unfused(train_data, monkeypatch):
    """The one-program fused fit (binning + all boosting stages in a single
    XLA dispatch — the device-binning regime's fast path) must agree with
    the sorted-layout pieces run separately through an explicit ``bins=``
    argument. Since r5 the fused path uses the UNSORTED histogram
    formulation (gbdt._fit_hist1_fused docstring), so the split statistics
    regroup f32 sums per bin: tree STRUCTURE (feature, boundary, topology)
    must still be identical, leaf values and deviance agree to summation-
    order tolerance."""
    from machine_learning_replications_tpu.ops import binning

    X, y = train_data
    # Drop the row threshold so the fused route engages at test size.
    monkeypatch.setattr(gbdt, "DEVICE_BINNING_MIN_ROWS", 1)
    cfg = GBDTConfig(n_estimators=8, splitter="hist", n_bins=32)
    fused, aux_f = gbdt.fit(X, y, cfg)
    unfused, aux_u = gbdt.fit(X, y, cfg, bins=binning.bin_features_device(X, 32))
    for name in ("feature", "threshold", "left", "right"):
        np.testing.assert_array_equal(
            np.asarray(getattr(fused, name)), np.asarray(getattr(unfused, name)),
            err_msg=name,
        )
    np.testing.assert_allclose(
        np.asarray(fused.value), np.asarray(unfused.value),
        rtol=1e-9, atol=1e-12,
    )
    np.testing.assert_allclose(
        float(fused.init_raw), float(unfused.init_raw), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(aux_f["train_deviance"]), np.asarray(aux_u["train_deviance"]),
        rtol=1e-6,
    )
    # NaN contract survives the fusion (the flag is checked post-hoc).
    Xn = X.copy()
    Xn[0, 0] = np.nan
    with pytest.raises(ValueError, match="NaN"):
        gbdt.fit(Xn, y, cfg)


def test_blocked_boundary_sums_match_sequential():
    """Above ``_BLOCKED_BOUNDARY_MIN_N`` the boundary sums switch to the
    two-level block decomposition; it must agree with the sequential-cumsum
    oracle (exactly on integer-valued data, closely on floats)."""
    import jax.numpy as jnp

    from machine_learning_replications_tpu.ops import histogram

    rng = np.random.default_rng(7)
    F, B = 6, 37
    n = histogram._BLOCKED_BOUNDARY_MIN_N + 1234  # force the blocked path
    lc = rng.integers(0, n + 1, size=(F, B)).astype(np.int32)
    lc[0, 0], lc[0, 1] = 0, n  # pin both edge positions
    vi = rng.integers(-3, 4, size=(F, n)).astype(np.float32)
    out = np.asarray(
        histogram.cumulative_boundary_sums(jnp.asarray(vi), jnp.asarray(lc))
    )
    ref = np.stack(
        [np.concatenate([[0], np.cumsum(vi[f].astype(np.int64))])[lc[f]]
         for f in range(F)]
    )
    np.testing.assert_array_equal(out, ref.astype(np.float32))
    vf = rng.normal(size=(F, n)).astype(np.float32)
    out_f = np.asarray(
        histogram.cumulative_boundary_sums(jnp.asarray(vf), jnp.asarray(lc))
    )
    ref_f = np.stack(
        [np.concatenate([[0], np.cumsum(vf[f].astype(np.float64))])[lc[f]]
         for f in range(F)]
    )
    np.testing.assert_allclose(out_f, ref_f, atol=5e-3)


def test_fused_accepts_soft_labels(train_data, monkeypatch):
    """Since the r5 unsorted formulation no label packing remains — each
    stage histograms g = y − p directly — so soft labels (well-defined
    under binomial deviance) train on the fused path itself (ADVICE r5
    dropped the gate that routed them off it) and must match an
    explicit-bins sorted-layout fit: identical tree structure, leaf values
    to summation-order tolerance."""
    from machine_learning_replications_tpu.ops import binning

    X, y = train_data
    monkeypatch.setattr(gbdt, "DEVICE_BINNING_MIN_ROWS", 1)
    y_soft = np.where(y > 0.5, 0.9, 0.1)
    cfg = GBDTConfig(n_estimators=5, splitter="hist", n_bins=32)
    fused, _ = gbdt.fit(X, y_soft, cfg)
    explicit, _ = gbdt.fit(
        X, y_soft, cfg, bins=binning.bin_features_device(X, 32)
    )
    np.testing.assert_array_equal(
        np.asarray(fused.feature), np.asarray(explicit.feature)
    )
    np.testing.assert_allclose(
        np.asarray(fused.value), np.asarray(explicit.value), rtol=1e-6
    )


def test_chunked_row_reduce_rejects_empty():
    """The shared chunked scaffolding must fail loudly on zero-row input
    (the old path died with an opaque ZeroDivisionError in a reshape)."""
    from machine_learning_replications_tpu.ops import binning

    with pytest.raises(ValueError, match="zero-row"):
        binning.bin_features_device(np.empty((0, 4), np.float32), 16)
    with pytest.raises(ValueError, match="zero-row"):
        binning.chunked_row_reduce(
            np.empty((0, 4), np.float32), lambda c: c.sum(0)
        )


def test_block_shape_stage_loop_matches_flat(monkeypatch):
    """Above _BLOCKED_BOUNDARY_MIN_N the stage loop's boundary sums use the
    blocked decomposition (inside cumulative_boundary_sums). A full fit in
    that regime must match one forced onto the flat sequential path — same
    splits/thresholds exactly, leaf values and deviance to float tolerance
    (blocked summation regroups), and the sklearn AUC parity budget must
    hold at this size."""
    import jax

    from machine_learning_replications_tpu.ops import histogram
    from machine_learning_replications_tpu.utils import metrics

    rng = np.random.default_rng(21)
    n = histogram._BLOCKED_BOUNDARY_MIN_N + 4321  # odd: exercises padding
    X = rng.normal(size=(n, 5)).astype(np.float64)
    logits = 1.2 * X[:, 0] - 0.8 * X[:, 2] + 0.3 * rng.normal(size=n)
    y = (logits > 0).astype(np.float64)
    cfg = GBDTConfig(splitter="hist", n_estimators=12)

    params_b, aux_b = gbdt.fit(X, y, cfg)

    # Force the flat sequential loop by raising the threshold past n. The
    # blocked/flat branch is a TRACE-time decision inside a jitted function
    # whose cache keys on shapes only, so the caches must be flushed or the
    # second fit would silently rerun the blocked executable and the
    # comparison would be vacuous (and flushed again in finally so no
    # flat-path executable leaks into later blocked-regime tests).
    monkeypatch.setattr(histogram, "_BLOCKED_BOUNDARY_MIN_N", n + 10_000)
    jax.clear_caches()
    try:
        params_f, aux_f = gbdt.fit(X, y, cfg)
    finally:
        monkeypatch.undo()
        jax.clear_caches()

    np.testing.assert_array_equal(
        np.asarray(params_b.feature), np.asarray(params_f.feature)
    )
    np.testing.assert_allclose(
        np.asarray(params_b.threshold), np.asarray(params_f.threshold)
    )
    np.testing.assert_allclose(
        np.asarray(params_b.value), np.asarray(params_f.value),
        rtol=1e-5, atol=1e-7,
    )
    np.testing.assert_allclose(
        np.asarray(aux_b["train_deviance"]), np.asarray(aux_f["train_deviance"]),
        rtol=1e-5, atol=1e-7,
    )
    p_b = np.asarray(tree.predict_proba1(params_b, X))
    auc = float(metrics.roc_auc(y, p_b))
    sk = GradientBoostingClassifier(
        n_estimators=12, max_depth=1, random_state=2020
    ).fit(X, y)
    auc_sk = float(metrics.roc_auc(y, sk.predict_proba(X)[:, 1]))
    assert abs(auc - auc_sk) <= 0.005


def test_per_fold_binning_matches_subset_fits():
    """cfg.per_fold_binning=True closes the documented candidate-set
    deviation: each fold's candidates come from its OWN rows, so every
    fold's forest must equal a standalone fit on the physical subset
    (which bins its own input — sklearn's per-refit protocol)."""
    from machine_learning_replications_tpu.ops import binning

    rng = np.random.default_rng(5)
    n, f, k = 600, 6, 3
    X = rng.normal(size=(n, f))  # continuous: per-fold candidates DIFFER
    w = rng.normal(size=f)
    y = (X @ w + 0.5 * rng.normal(size=n) > 0.2).astype(float)
    masks = np.ones((k, n))
    for i in range(k):  # contiguous held-out blocks
        masks[i, i * (n // k):(i + 1) * (n // k)] = 0.0

    # rebin_with_thresholds must reproduce bin_features' ids on the fit set.
    bf = binning.bin_features(X[masks[0] > 0], 256)
    np.testing.assert_array_equal(
        binning.rebin_with_thresholds(X[masks[0] > 0], bf.thresholds),
        bf.binned,
    )

    cfg = GBDTConfig(
        splitter="hist", n_estimators=8, max_depth=2, per_fold_binning=True
    )
    batched = gbdt.fit_folds(X, y, masks, cfg)
    for i in range(k):
        sub = masks[i] > 0
        ref, _ = gbdt.fit(X[sub], y[sub], GBDTConfig(
            splitter="hist", n_estimators=8, max_depth=2
        ))
        np.testing.assert_array_equal(
            np.asarray(batched.feature[i]), np.asarray(ref.feature),
            err_msg=f"fold {i} split features",
        )
        np.testing.assert_allclose(
            np.asarray(batched.threshold[i]), np.asarray(ref.threshold),
            rtol=1e-12, err_msg=f"fold {i} thresholds",
        )
        np.testing.assert_allclose(
            np.asarray(batched.value[i]), np.asarray(ref.value),
            rtol=1e-9, atol=1e-12, err_msg=f"fold {i} leaf values",
        )
        np.testing.assert_allclose(
            float(batched.init_raw[i]), float(ref.init_raw), rtol=1e-12
        )


def test_per_fold_binning_defaults_to_shared_bins():
    """The flag is off by default and the shared-bins path is unchanged."""
    assert GBDTConfig().per_fold_binning is False


def test_host_stump_engine_matches_sklearn_and_device():
    """fit() routes one-shot stumps (n_estimators=1, host arrays, hist
    splitter, device-binning scale) through the numpy engine
    (gbdt._fit_stump_host) — no XLA compile. It must pick the same split
    feature as both sklearn's exact stump and the fused device path, hold
    AUC parity, and honor the NaN contract. Thresholds may differ inside
    a bin width (quantile candidates, subsampled above 128k rows — the
    documented hist-splitter deviation)."""
    import jax.numpy as jnp

    from machine_learning_replications_tpu.data import make_cohort
    from machine_learning_replications_tpu.data.schema import selected_indices
    from machine_learning_replications_tpu.models import tree
    from machine_learning_replications_tpu.utils import metrics

    X, y, _ = make_cohort(n=150_000, seed=2020)
    X17 = np.ascontiguousarray(X[:, selected_indices()], dtype=np.float32)
    yf = np.asarray(y, dtype=np.float32)
    cfg = GBDTConfig(splitter="hist", n_estimators=1)
    assert gbdt.uses_fused_hist1(cfg, X17.shape[0])
    params, aux = gbdt.fit(X17, yf, cfg)
    # device-array inputs take the fused XLA path; same structure
    params_dev, _ = gbdt.fit(jnp.asarray(X17), jnp.asarray(yf), cfg)
    np.testing.assert_array_equal(
        np.asarray(params.feature), np.asarray(params_dev.feature)
    )

    from sklearn.ensemble import GradientBoostingClassifier

    sk = GradientBoostingClassifier(
        n_estimators=1, max_depth=1, random_state=2020
    )
    sk.fit(X17, np.asarray(y))
    t = sk.estimators_[0, 0].tree_
    assert int(np.asarray(params.feature)[0, 0]) == int(t.feature[0])

    ours = np.asarray(tree.predict_proba1(params, jnp.asarray(X17)))
    theirs = sk.predict_proba(X17)[:, 1]
    auc_ours = float(metrics.roc_auc(jnp.asarray(yf), jnp.asarray(ours)))
    auc_sk = float(metrics.roc_auc(jnp.asarray(yf), jnp.asarray(theirs)))
    assert abs(auc_ours - auc_sk) < 5e-3
    # deviance against sklearn's own binomial deviance after one stage
    np.testing.assert_allclose(
        float(aux["train_deviance"][0]), float(sk.train_score_[0]), rtol=1e-3
    )

    Xn = X17.copy()
    Xn[0, 0] = np.nan
    with pytest.raises(ValueError, match="NaN"):
        gbdt.fit(Xn, yf, cfg)
