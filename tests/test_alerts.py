"""Alerting + incident flight recorder (obs.timeseries / obs.alerts /
obs.incident + router wiring): tiered downsampling goldens, derived
rate/quantile math against hand-computed values, every rule type's
fire/resolve state machine with hold-down and hysteresis on both sides,
burn-rate analytics, incident-bundle schema/rate-limit/atomicity, the
router's /fleet/alerts + /debug/history endpoints over a live router,
a steady-state no-false-positive soak, and the stale-series retirement
regression (a deregistered replica's per-replica gauges must leave the
exposition).

Store and engine tests inject synthetic `now` values — the whole plane
is pure of clocks by construction, which is what makes hold-down
windows testable in microseconds.
"""

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from machine_learning_replications_tpu.fleet import make_router
from machine_learning_replications_tpu.fleet.registry import ReplicaRegistry
from machine_learning_replications_tpu.obs import alerts, incident, journal
from machine_learning_replications_tpu.obs import fleetmetrics, fleettrace
from machine_learning_replications_tpu.obs import timeseries
from machine_learning_replications_tpu.obs.registry import (
    REGISTRY,
    MetricsRegistry,
)
from machine_learning_replications_tpu.serve.transport import (
    EventLoopHttpServer,
)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
from validate_metrics import diff_counters, validate  # noqa: E402
import loadgen  # noqa: E402


@pytest.fixture
def jrn(tmp_path):
    j = journal.RunJournal(tmp_path / "journal.jsonl", command="test")
    journal.set_journal(j)
    yield j
    journal.set_journal(None)
    j.close()


def _events(j, kind=None):
    with open(j.path) as f:
        evs = [json.loads(line) for line in f if line.strip()]
    evs = [e for e in evs if e.get("kind") != "manifest"]
    if kind is not None:
        evs = [e for e in evs if e.get("kind") == kind]
    return evs


# ---------------------------------------------------------------------------
# collect_registry: the local sampling pass
# ---------------------------------------------------------------------------


def test_collect_registry_normalized_shape():
    reg = MetricsRegistry()
    c = reg.counter("c_total", "c", labels=("k",))
    c.inc(k="a")
    c.inc(k="a")
    g = reg.gauge("g", "g")
    g.set(3.5)
    h = reg.histogram("h_seconds", "h", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)

    fams = timeseries.collect_registry(reg)
    assert fams["c_total"]["kind"] == "counter"
    assert fams["c_total"]["series"][(("k", "a"),)] == 2.0
    assert fams["g"]["series"][()] == 3.5
    snap = fams["h_seconds"]["series"][()]
    assert snap["count"] == 2 and snap["buckets"]["+Inf"] == 2


# ---------------------------------------------------------------------------
# store: raw ring, windows, tiered downsampling
# ---------------------------------------------------------------------------


def _gauge_fam(v):
    return {"g": {"kind": "gauge", "series": {(): float(v)}}}


def _counter_fam(v):
    return {"c_total": {"kind": "counter", "series": {(): float(v)}}}


def test_store_window_latest_and_families():
    st = timeseries.TimeSeriesStore(interval_s=1.0, raw_retention_s=100.0)
    for t in range(5):
        st.ingest(_gauge_fam(t * 10), now=float(t))
    assert st.families() == {"g": 1}
    [(lab, t, v)] = st.latest("g")
    assert lab == {} and t == 4.0 and v == 40.0
    [(_, pts)] = st.window("g", 2.5, now=4.0)
    assert [v for _t, v in pts] == [20.0, 30.0, 40.0]
    assert st.last_sample_age_s("g", now=6.0) == 2.0
    assert st.last_sample_age_s("nope", now=6.0) is None


def test_downsampling_golden_gauge_avg_counter_last():
    """Raw ring of 10 samples; older samples survive only in the agg
    tier — whose points carry the bucket AVERAGE for gauges and the
    bucket-edge LAST value for counters."""
    st = timeseries.TimeSeriesStore(
        interval_s=1.0, raw_retention_s=10.0, agg_bucket_s=5.0,
        agg_retention_s=100.0,
    )
    for t in range(30):
        st.ingest({**_gauge_fam(t), **_counter_fam(2 * t)}, now=float(t))
    # Raw ring capacity 12: raw starts at t=18. Buckets [0..4], [5..9],
    # [10..14] are flushed; gauge avg of [0..4] is 2, counter last is 8.
    [(_, gpts)] = st.window("g", 30.0, now=29.0)
    agg_g = [p for p in gpts if p[0] < 18.0]
    assert agg_g[0] == (0.0, 2.0)
    assert agg_g[1] == (5.0, 7.0)
    [(_, cpts)] = st.window("c_total", 30.0, now=29.0)
    agg_c = [p for p in cpts if p[0] < 18.0]
    assert agg_c[0] == (0.0, 8.0)       # last of bucket [0..4]: 2*4
    assert agg_c[1] == (5.0, 18.0)      # last of bucket [5..9]: 2*9
    # And the raw tail is the verbatim samples.
    assert (29.0, 29.0) == gpts[-1] and (29.0, 58.0) == cpts[-1]


def test_rate_is_reset_safe_and_delta_signed():
    st = timeseries.TimeSeriesStore(interval_s=1.0)
    for t, v in enumerate([10.0, 12.0, 14.0, 1.0, 3.0]):
        st.ingest(_counter_fam(v), now=float(t))
    # Positive increments only: 2+2+0(reset)+2 = 6 over 4 s.
    [(_, r)] = st.rate("c_total", 10.0, now=4.0)
    assert r == pytest.approx(6.0 / 4.0)
    # delta() is newest-oldest, signed — the rate-of-change primitive.
    [(_, d)] = st.delta("c_total", 10.0, now=4.0)
    assert d == pytest.approx(3.0 - 10.0)


def test_nan_gauge_sample_is_skipped():
    st = timeseries.TimeSeriesStore(interval_s=1.0)
    st.ingest(_gauge_fam(1.0), now=0.0)
    st.ingest(_gauge_fam(float("nan")), now=1.0)
    st.ingest(_gauge_fam(3.0), now=2.0)
    [(_, a)] = st.avg("g", 10.0, now=2.0)
    assert a == pytest.approx(2.0)


def _hist_fam(buckets, total, s):
    return {"h": {"kind": "histogram", "series": {(): {
        "buckets": dict(buckets), "sum": s, "count": total,
    }}}}


def test_quantile_golden_vs_hand_computed():
    """Prometheus-style interpolation over the windowed bucket delta:
    {le 0.1: 5, le 1.0: 10, +Inf: 10} → q50 = 0.1 (bucket edge), q75 =
    0.1 + (1.0-0.1) * (7.5-5)/5 = 0.55."""
    st = timeseries.TimeSeriesStore(interval_s=1.0)
    st.ingest(_hist_fam({"0.1": 0, "1.0": 0, "+Inf": 0}, 0, 0.0), now=0.0)
    st.ingest(
        _hist_fam({"0.1": 5, "1.0": 10, "+Inf": 10}, 10, 3.0), now=10.0
    )
    [(_, q50)] = st.quantile("h", 0.5, 20.0, now=10.0)
    [(_, q75)] = st.quantile("h", 0.75, 20.0, now=10.0)
    assert q50 == pytest.approx(0.1)
    assert q75 == pytest.approx(0.55)


def test_quantile_windowed_delta_subtracts_baseline():
    """Observations BEFORE the window must not count: the baseline
    snapshot at the window edge is subtracted."""
    st = timeseries.TimeSeriesStore(interval_s=1.0)
    # 10 fast observations land before the window...
    st.ingest(
        _hist_fam({"0.1": 10, "1.0": 10, "+Inf": 10}, 10, 0.5), now=0.0
    )
    # ...then 4 slow ones inside it.
    st.ingest(
        _hist_fam({"0.1": 10, "1.0": 14, "+Inf": 14}, 14, 3.0), now=10.0
    )
    [(_, q50)] = st.quantile("h", 0.5, 5.0, now=10.0)
    # All 4 windowed observations sit in (0.1, 1.0]: q50 interpolates
    # inside that bucket, far above the lifetime-median 0.1.
    assert q50 == pytest.approx(0.1 + 0.9 * 0.5)
    # +Inf-only mass reports the last finite bound.
    st.ingest(
        _hist_fam({"0.1": 10, "1.0": 14, "+Inf": 16}, 16, 9.0), now=11.0
    )
    [(_, q99)] = st.quantile("h", 0.6, 0.5, now=11.0)
    assert q99 == pytest.approx(1.0)


def test_query_serialization_and_dump():
    st = timeseries.TimeSeriesStore(interval_s=1.0)
    st.ingest({**_gauge_fam(1.0),
               **_hist_fam({"+Inf": 3}, 3, 0.3)}, now=1.0)
    q = st.query("g", None, now=1.0)
    assert q["series"][0]["points"] == [[1.0, 1.0]]
    qh = st.query("h", None, now=1.0)
    assert qh["series"][0]["points"] == [[1.0, 3.0, 0.3]]  # [t, count, sum]
    d = st.dump(60.0, now=1.0)
    assert set(d) == {"g", "h"}


def test_history_sampler_thread_swallows_collect_errors():
    st = timeseries.TimeSeriesStore(interval_s=0.02)
    calls = {"n": 0, "ticks": 0}

    def collect():
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("scrape hiccup")
        return _gauge_fam(calls["n"])

    s = timeseries.HistorySampler(
        st, collect, on_tick=lambda now: calls.__setitem__(
            "ticks", calls["ticks"] + 1
        ),
    ).start()
    deadline = time.monotonic() + 5
    while calls["n"] < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    s.close()
    assert calls["n"] >= 3 and calls["ticks"] >= 3
    assert st.stats()["ticks"] >= 2  # the bad tick ingested nothing


# ---------------------------------------------------------------------------
# rules: each type, both directions, hold-down + hysteresis
# ---------------------------------------------------------------------------


def _engine(rule_spec, st):
    return alerts.AlertEngine([alerts.build_rule(rule_spec)], st)


def test_threshold_rule_holddown_and_resolve_hysteresis(jrn):
    st = timeseries.TimeSeriesStore(interval_s=1.0)
    eng = _engine({
        "type": "threshold", "name": "hot", "severity": "warn",
        "family": "g", "op": ">=", "threshold": 10.0,
        "for_s": 2.0, "resolve_for_s": 2.0,
    }, st)

    def step(t, v):
        st.ingest(_gauge_fam(v), now=float(t))
        return eng.evaluate(float(t))

    assert step(0, 50) == []                     # pending
    assert step(1, 50) == []                     # held down
    [tr] = step(2, 50)                           # fired after for_s
    assert tr["transition"] == "fired" and tr["rule"] == "hot"
    assert alerts.ALERTS_ACTIVE.labels(rule="hot", severity="warn").value \
        == 1.0
    assert step(3, 1) == []                      # resolving, held
    [tr] = step(5, 1)                            # resolved after hold
    assert tr["transition"] == "resolved"
    assert tr["fired_for_s"] == pytest.approx(3.0)
    assert alerts.ALERTS_ACTIVE.labels(rule="hot", severity="warn").value \
        == 0.0
    fired = _events(jrn, "alert_fired")
    resolved = _events(jrn, "alert_resolved")
    assert len(fired) == 1 and fired[0]["rule"] == "hot"
    assert len(resolved) == 1 and \
        resolved[0]["seconds"] == pytest.approx(3.0)


def test_threshold_blip_never_fires(jrn):
    st = timeseries.TimeSeriesStore(interval_s=1.0)
    eng = _engine({
        "type": "threshold", "name": "hot", "family": "g",
        "threshold": 10.0, "for_s": 2.0,
    }, st)
    st.ingest(_gauge_fam(50), now=0.0)
    assert eng.evaluate(0.0) == []
    st.ingest(_gauge_fam(1), now=1.0)            # breach clears early
    assert eng.evaluate(1.0) == []
    st.ingest(_gauge_fam(50), now=2.0)           # hold-down restarts
    assert eng.evaluate(2.0) == []
    assert _events(jrn, "alert_fired") == []
    assert eng.summary()["firing"] == 0


def test_rebreach_during_hysteresis_is_same_incident(jrn):
    st = timeseries.TimeSeriesStore(interval_s=1.0)
    eng = _engine({
        "type": "threshold", "name": "hot", "family": "g",
        "threshold": 10.0, "for_s": 0.0, "resolve_for_s": 5.0,
    }, st)
    st.ingest(_gauge_fam(50), now=0.0)
    assert len(eng.evaluate(0.0)) == 1           # fires immediately
    st.ingest(_gauge_fam(1), now=1.0)
    assert eng.evaluate(1.0) == []               # resolving
    st.ingest(_gauge_fam(50), now=2.0)
    assert eng.evaluate(2.0) == []               # back to firing, silent
    assert len(_events(jrn, "alert_fired")) == 1
    [active] = eng.active()
    assert active["state"] == "firing" and active["since"] == 0.0


def test_threshold_less_than_with_window_avg():
    st = timeseries.TimeSeriesStore(interval_s=1.0)
    eng = _engine({
        "type": "threshold", "name": "low", "family": "g",
        "op": "<", "threshold": 5.0, "window_s": 10.0, "for_s": 0.0,
        "resolve_for_s": 0.0,
    }, st)
    st.ingest(_gauge_fam(9.0), now=0.0)
    st.ingest(_gauge_fam(7.0), now=1.0)          # avg 8 → no breach
    assert eng.evaluate(1.0) == []
    st.ingest(_gauge_fam(0.0), now=2.0)
    st.ingest(_gauge_fam(0.0), now=3.0)          # avg 4 → breach
    [tr] = eng.evaluate(3.0)
    assert tr["transition"] == "fired" and tr["value"] == 4.0


def test_burn_rate_needs_both_windows(jrn):
    """Google-SRE multi-window: the FAST window alone (a blip) must not
    fire; fast AND slow over the factor fires; recovery resolves."""
    st = timeseries.TimeSeriesStore(interval_s=1.0)
    eng = _engine({
        "type": "burn_rate", "name": "burn", "severity": "page",
        "family": "b", "factor": 14.4, "fast_s": 10.0, "slow_s": 100.0,
        "for_s": 0.0, "resolve_for_s": 0.0,
    }, st)

    def feed(t, v):
        st.ingest({"b": {"kind": "gauge", "series": {(): float(v)}}},
                  now=float(t))

    # 90 s of calm, then a 10 s spike: fast avg = 20 >= 14.4 but slow
    # avg = (90*1 + 10*20) / 100 = 2.9 — NOT an emergency yet.
    for t in range(90):
        feed(t, 1.0)
    for t in range(90, 100):
        feed(t, 20.0)
    assert eng.evaluate(99.0) == []
    # Sustained burn: every sample in both windows now reads 20.
    for t in range(100, 200):
        feed(t, 20.0)
    [tr] = eng.evaluate(199.0)
    assert tr["transition"] == "fired"
    # Analytic check: both window averages are exactly 20.
    [(_, fast)] = st.avg("b", 10.0, now=199.0)
    [(_, slow)] = st.avg("b", 100.0, now=199.0)
    assert fast == pytest.approx(20.0) and slow == pytest.approx(20.0)
    # Recovery.
    for t in range(200, 320):
        feed(t, 0.0)
    [tr] = eng.evaluate(319.0)
    assert tr["transition"] == "resolved"


def test_absence_rule_staleness_and_warmup_grace():
    st = timeseries.TimeSeriesStore(interval_s=1.0)
    eng = _engine({
        "type": "absence", "name": "gone", "family": "g",
        "stale_after_s": 5.0, "for_s": 0.0, "resolve_for_s": 0.0,
    }, st)
    # Never sampled: grace until the engine is stale_after_s old.
    assert eng.evaluate(0.0) == []
    assert eng.evaluate(4.0) == []
    [tr] = eng.evaluate(6.0)                     # still absent → fired
    assert tr["transition"] == "fired"
    st.ingest(_gauge_fam(1.0), now=7.0)          # samples resume
    [tr] = eng.evaluate(7.5)
    assert tr["transition"] == "resolved"
    # Goes stale again after samples stop.
    assert eng.evaluate(11.0) == []              # age 4 < 5
    [tr] = eng.evaluate(13.0)                    # age 6 → fired
    assert tr["transition"] == "fired"


def test_rate_of_change_rule_absolute_delta():
    st = timeseries.TimeSeriesStore(interval_s=1.0)
    eng = _engine({
        "type": "rate_of_change", "name": "drift", "family": "psi",
        "max_delta": 0.2, "window_s": 10.0, "for_s": 0.0,
        "resolve_for_s": 0.0,
    }, st)

    def feed(t, v):
        st.ingest({"psi": {"kind": "gauge", "series": {(): v}}},
                  now=float(t))

    feed(0, 0.05)
    feed(1, 0.08)
    assert eng.evaluate(1.0) == []               # |Δ| = 0.03
    feed(2, 0.40)                                # |Δ| = 0.35 → breach
    [tr] = eng.evaluate(2.0)
    assert tr["transition"] == "fired"
    # A downward move of the same magnitude breaches too (abs).
    for t in range(3, 20):
        feed(t, 0.40)
    [tr] = eng.evaluate(19.0)
    assert tr["transition"] == "resolved"
    feed(20, 0.10)
    [tr] = eng.evaluate(20.0)
    assert tr["transition"] == "fired"


def test_rule_check_error_is_contained_per_rule(jrn):
    st = timeseries.TimeSeriesStore(interval_s=1.0)

    class _Broken(alerts.ThresholdRule):
        def check(self, store, now):
            raise RuntimeError("boom")

    broken = _Broken({"type": "threshold", "name": "bad", "family": "g",
                      "threshold": 1.0})
    ok = alerts.build_rule({
        "type": "threshold", "name": "good", "family": "g",
        "threshold": 1.0, "for_s": 0.0,
    })
    eng = alerts.AlertEngine([broken, ok], st)
    st.ingest(_gauge_fam(5.0), now=0.0)
    [tr] = eng.evaluate(0.0)                     # good still fires
    assert tr["rule"] == "good"
    snap = {r["name"]: r for r in eng.snapshot()["rules"]}
    assert snap["bad"]["detail"].startswith("check error:")
    assert snap["bad"]["state"] == "inactive"


def test_rule_spec_validation_and_load_rules(tmp_path):
    with pytest.raises(ValueError, match="unknown rule type"):
        alerts.build_rule({"type": "nope", "name": "x", "family": "g"})
    with pytest.raises(ValueError, match="severity"):
        alerts.build_rule({"type": "threshold", "name": "x",
                           "family": "g", "threshold": 1,
                           "severity": "catastrophic"})
    with pytest.raises(ValueError, match="op"):
        alerts.build_rule({"type": "threshold", "name": "x",
                           "family": "g", "threshold": 1, "op": "~"})
    p = tmp_path / "rules.json"
    p.write_text(json.dumps([
        {"type": "threshold", "name": "a", "family": "g", "threshold": 1},
        {"type": "threshold", "name": "a", "family": "g", "threshold": 2},
    ]))
    with pytest.raises(ValueError, match="duplicate"):
        alerts.load_rules(str(p))
    p.write_text(json.dumps({"not": "a list"}))
    with pytest.raises(ValueError, match="JSON list"):
        alerts.load_rules(str(p))
    p.write_text(json.dumps([
        {"type": "burn_rate", "name": "b", "family": "g"},
        {"type": "absence", "name": "c", "family": "g"},
    ]))
    loaded = alerts.load_rules(str(p))
    assert [r.name for r in loaded] == ["b", "c"]
    for role in ("router", "replica"):
        assert alerts.default_rules(role)
    with pytest.raises(ValueError):
        alerts.default_rules("toaster")


# ---------------------------------------------------------------------------
# incident capturer: schema, admission control, atomicity, retention
# ---------------------------------------------------------------------------


def _transition(at=1000.0, rule="hot"):
    return {"transition": "fired", "rule": rule, "severity": "page",
            "at": at, "value": 9.0, "detail": "g = 9",
            "spec": {"name": rule}}


def test_bundle_schema_manifest_last(tmp_path, jrn):
    st = timeseries.TimeSeriesStore(interval_s=1.0)
    st.ingest(_gauge_fam(9.0), now=999.0)
    cap = incident.IncidentCapturer(
        tmp_path / "inc", store=st,
        collectors={"extra": lambda: {"k": 1}},
    )
    bundle = cap.capture(_transition())
    assert bundle is not None
    with open(os.path.join(bundle, "manifest.json")) as f:
        manifest = json.load(f)
    assert manifest["schema"] == incident.SCHEMA_VERSION
    assert manifest["rule"] == "hot" and manifest["errors"] == {}
    assert sorted(manifest["files"]) == [
        "alert.json", "extra.json", "history.json", "journal_tail.jsonl",
    ]
    for name in manifest["files"]:
        assert os.path.exists(os.path.join(bundle, name))
    with open(os.path.join(bundle, "alert.json")) as f:
        assert json.load(f)["rule"] == "hot"
    assert cap.bundles() == [bundle]
    [ev] = _events(jrn, "incident_captured")
    assert ev["rule"] == "hot" and ev["files"] == 4
    # A failing collector is recorded, not fatal.
    cap2 = incident.IncidentCapturer(
        tmp_path / "inc2",
        collectors={"bad": lambda: 1 / 0},
    )
    b2 = cap2.capture(_transition(at=2000.0))
    with open(os.path.join(b2, "manifest.json")) as f:
        m2 = json.load(f)
    assert "bad.json" in m2["errors"]


def test_capture_rate_limit_and_single_flight(tmp_path):
    cap = incident.IncidentCapturer(tmp_path / "inc", min_interval_s=3600)
    assert cap.maybe_capture({"transition": "resolved"}) is None
    assert cap.maybe_capture(_transition()) == "captured"
    cap.close()
    assert cap.maybe_capture(_transition(at=2000.0)) == "rate_limited"
    # Single-flight: while a capture is in flight, new firings drop.
    cap2 = incident.IncidentCapturer(tmp_path / "inc2", min_interval_s=0)
    with cap2._lock:
        cap2._in_flight = True
    assert cap2.maybe_capture(_transition()) == "in_flight"


def test_crashed_capture_leaves_no_manifest_and_is_swept(
    tmp_path, monkeypatch,
):
    cap = incident.IncidentCapturer(tmp_path / "inc", min_interval_s=0)
    monkeypatch.setattr(
        incident, "atomic_json_write",
        lambda *a, **k: (_ for _ in ()).throw(OSError("disk full")),
    )
    assert cap.capture(_transition()) is None
    # The torn directory exists but has no manifest: readers skip it.
    leftovers = os.listdir(tmp_path / "inc")
    assert leftovers and cap.bundles() == []
    monkeypatch.undo()
    # The next successful capture's retention sweep removes the wreck.
    bundle = cap.capture(_transition(at=2000.0))
    assert cap.bundles() == [bundle]
    assert os.listdir(tmp_path / "inc") == [os.path.basename(bundle)]


def test_bundle_retention_keeps_newest(tmp_path):
    cap = incident.IncidentCapturer(
        tmp_path / "inc", min_interval_s=0, retention=2,
    )
    dirs = [
        cap.capture(_transition(at=1000.0 + 60 * i, rule=f"r{i}"))
        for i in range(3)
    ]
    kept = cap.bundles()
    assert kept == dirs[1:]


# ---------------------------------------------------------------------------
# stale-series hygiene: retirement on deregister/replace
# ---------------------------------------------------------------------------


def test_family_remove_retires_series():
    reg = MetricsRegistry()
    g = reg.gauge("per_replica", "g", labels=("replica",))
    g.set(1.0, replica="a")
    g.set(2.0, replica="b")
    assert g.remove(replica="a") is True
    assert g.remove(replica="a") is False        # already gone
    with pytest.raises(ValueError):
        g.remove(nope="a")
    text = reg.render_prometheus()
    assert 'replica="a"' not in text and 'replica="b"' in text


def test_scraper_and_clocksync_forget_retire_gauges():
    registry = ReplicaRegistry()
    registry.register("ghost-xyz", "http://127.0.0.1:9")  # unreachable
    scraper = fleetmetrics.FleetScraper(registry, timeout_s=0.05)
    registry._replicas["ghost-xyz"].state = "ready"
    scraper.scrape()
    page = REGISTRY.render_prometheus()
    assert 'fleet_scrape_stale{replica="ghost-xyz"} 1' in page
    scraper.forget("ghost-xyz")
    assert 'replica="ghost-xyz"' not in REGISTRY.render_prometheus()

    cs = fleettrace.ClockSync()
    cs.observe("ghost-xyz", t_send=0.0, t_recv=0.01, replica_clock=5.0)
    assert 'fleet_clock_offset_ms{replica="ghost-xyz"}' in \
        REGISTRY.render_prometheus()
    cs.forget("ghost-xyz")
    assert 'replica="ghost-xyz"' not in REGISTRY.render_prometheus()


def test_registry_retire_listeners_fire_on_deregister_and_replace():
    registry = ReplicaRegistry()
    retired = []
    registry.add_retire_listener(retired.append)
    registry.register("p1", "http://127.0.0.1:1111")
    registry.register("p1", "http://127.0.0.1:1111")  # idempotent beat
    assert retired == []
    registry.register("p1", "http://127.0.0.1:2222")  # replacement
    assert retired == ["p1"]
    registry.deregister("p1")
    assert retired == ["p1", "p1"]
    registry.deregister("p1")                         # absent: no event
    assert retired == ["p1", "p1"]
    # A throwing listener must not break registration.
    registry.add_retire_listener(
        lambda rid: (_ for _ in ()).throw(RuntimeError("boom"))
    )
    registry.register("p2", "http://127.0.0.1:3333")
    registry.deregister("p2")
    assert retired[-1] == "p2"


# ---------------------------------------------------------------------------
# live router: /fleet/alerts, /debug/history, healthz, soak, retirement
# ---------------------------------------------------------------------------


PAGE = """\
# HELP stub_requests_total requests
# TYPE stub_requests_total counter
stub_requests_total{outcome="ok"} 10
"""


class _StubReplica:
    def __init__(self, rid):
        self.rid = rid

    def handle_request(self, req, rsp):
        if req.path == "/readyz":
            rsp.send_json(200, {
                "ready": True, "reasons": [], "replica": self.rid,
                "version": 1, "queue_depth": 0,
                "clock_perf": time.perf_counter(),
            })
        elif req.path == "/metrics":
            rsp.send(200, PAGE.encode(), "text/plain; version=0.0.4")
        else:
            rsp.send_json(404, {"error": "nope"})

    def handle_protocol_error(self, exc, rsp):
        rsp.send_json(exc.code, {"error": exc.message}, close=True)


def _get_json(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def test_router_alerts_and_history_endpoints(tmp_path):
    stubs, httpds, members = [], [], []
    for i in range(2):
        stub = _StubReplica(f"alrt{i + 1}")
        httpd = EventLoopHttpServer(("127.0.0.1", 0), stub)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        stubs.append(stub)
        httpds.append(httpd)
        members.append(
            (stub.rid, f"http://127.0.0.1:{httpd.server_address[1]}")
        )
    router = make_router(
        port=0, replicas=members, probe_interval_s=0.1,
        request_timeout_s=5.0, history_interval_s=0.1,
        incident_dir=str(tmp_path / "inc"),
    ).start_background()
    try:
        deadline = time.monotonic() + 10
        while router.registry.ready_count() < 2 and \
                time.monotonic() < deadline:
            time.sleep(0.02)
        assert router.registry.ready_count() == 2
        base = f"http://{router.address[0]}:{router.address[1]}"

        # Let the sampler take a handful of ticks (the soak window).
        deadline = time.monotonic() + 10
        while router.history.stats()["ticks"] < 5 and \
                time.monotonic() < deadline:
            time.sleep(0.05)

        # -- /debug/history ----------------------------------------------
        status, body = _get_json(base + "/debug/history")
        assert status == 200 and body["enabled"]
        assert "fleet_replicas" in body["families"]
        # The merged fleet page rides the same store: replica families
        # appear under their appended replica label.
        assert "stub_requests_total" in body["families"]
        status, body = _get_json(
            base + "/debug/history?family=fleet_replicas&window=60"
        )
        assert status == 200
        states = {s["labels"]["state"]: s["points"]
                  for s in body["series"]}
        assert states["ready"][-1][1] == 2.0
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            _get_json(base + "/debug/history?family=x&window=banana")
        assert exc_info.value.code == 400

        # -- /fleet/alerts + healthz: the steady-state soak ---------------
        status, body = _get_json(base + "/fleet/alerts")
        assert status == 200 and body["enabled"]
        assert body["active"] == [], (
            "false positives in a healthy steady state", body["active"],
        )
        assert body["summary"]["firing"] == 0
        assert {r["name"] for r in body["rules"]} == {
            r.name for r in alerts.default_rules("router")
        }
        assert all(r["state"] == "inactive" for r in body["rules"])
        status, hz = _get_json(base + "/healthz")
        assert hz["alerts"]["firing"] == 0
        assert hz["alerts"]["rules"] == len(body["rules"])

        # And the alert/history families ride the router's exposition.
        with urllib.request.urlopen(
            base + "/metrics", timeout=10.0
        ) as resp:
            page = resp.read().decode()
        assert validate(page) == []
        for fam in ("alerts_active", "alerts_transitions_total",
                    "history_samples_total", "history_series",
                    "incident_captures_total"):
            assert fam in page, fam

        # -- stale-series retirement over the live wire -------------------
        # The scraper has populated per-replica gauges for both stubs;
        # deregistering one must retire its series from the exposition.
        assert 'fleet_scrape_stale{replica="alrt2"} 0' in page
        router.registry.deregister("alrt2")
        page = REGISTRY.render_prometheus()
        assert 'replica="alrt2"' not in page
        assert 'fleet_scrape_stale{replica="alrt1"} 0' in page
    finally:
        router.shutdown()
        for h in httpds:
            h.server_close()
        # Hygiene: retire the surviving stub's series so later tests see
        # a clean registry.
        router.scraper.forget("alrt1")
        router.clock_sync.forget("alrt1")


def test_router_history_disabled():
    router = make_router(
        port=0, history_interval_s=0.0, start_prober=False,
    ).start_background()
    try:
        base = f"http://{router.address[0]}:{router.address[1]}"
        status, body = _get_json(base + "/debug/history")
        assert status == 200 and body["enabled"] is False
        status, body = _get_json(base + "/fleet/alerts")
        assert body == {"enabled": False, "active": [], "summary": None}
        status, hz = _get_json(base + "/healthz")
        assert hz["alerts"] is None
        assert router.history is None and router.alerts is None
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# satellite tools: validate_metrics --diff, loadgen --assert-slo
# ---------------------------------------------------------------------------


PAGE_A = """\
# TYPE a_total counter
a_total{k="x"} 10
# TYPE g gauge
g 100
# TYPE h_seconds histogram
h_seconds_bucket{le="0.1"} 5
h_seconds_bucket{le="+Inf"} 8
h_seconds_sum 1.5
h_seconds_count 8
"""


def test_diff_counters_monotonicity():
    page_b_ok = PAGE_A.replace("a_total{k=\"x\"} 10",
                               "a_total{k=\"x\"} 12")
    page_b_ok = page_b_ok.replace("g 100", "g 1")  # gauges may fall
    assert diff_counters(PAGE_A, page_b_ok) == []
    regressed = PAGE_A.replace("h_seconds_count 8", "h_seconds_count 7")
    errs = diff_counters(PAGE_A, regressed)
    assert errs and "h_seconds_count" in errs[0]
    # A series present on only one side is legitimate (retirement).
    gone = "\n".join(
        line for line in PAGE_A.splitlines()
        if not line.startswith("a_total")
    ) + "\n"
    assert diff_counters(PAGE_A, gone) == []


def test_loadgen_slo_budget_parse_and_check():
    budget = loadgen._parse_slo_budget("P50:10,p99:50,ERR:0.01")
    assert budget == {"p50": 10.0, "p99": 50.0, "err": 0.01}
    for bad in ("p42:1", "p50:1,p50:2", "p50:banana", "p50:-1", ""):
        with pytest.raises(ValueError):
            loadgen._parse_slo_budget(bad)
    art = {"n_sent": 100, "n_ok": 99, "n_shed": 1, "n_err": 0,
           "latency_ms": {"p50": 5.0, "p95": 20.0, "p99": 60.0,
                          "mean": 8.0, "max": 80.0}}
    assert loadgen._check_slo_budget(art, {"p50": 10.0}) == []
    v = loadgen._check_slo_budget(art, {"p99": 50.0, "err": 0.005})
    assert len(v) == 2
    # No successful requests: any latency bound is a violation.
    dead = {"n_sent": 10, "n_ok": 0, "n_shed": 0, "n_err": 10,
            "latency_ms": None}
    assert loadgen._check_slo_budget(dead, {"p50": 10.0})
