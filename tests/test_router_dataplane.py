"""Router data plane (ISSUE 12): the transport's outbound leg and the
registry's least-loaded rotation.

Three layers, mirroring the serving stack's own test split:

  * ``protocol.ResponseParser`` / ``build_request`` — pure byte-level
    rules, no sockets.
  * ``transport.UpstreamPool`` — the loop-owned upstream machinery
    against scripted raw-socket upstreams: keep-alive reuse, premature
    close mid-headers, half-close mid-body, a truncated/over-long reply
    poisoning a pooled connection (must close, never desync the next
    attempt), write backpressure against a slow reader, the transparent
    stale-connection resend, and attempt timeouts.
  * ``fleet`` — least-loaded power-of-two-choices picking on live load
    signals, and connection reuse counted across retries and hedges
    through the real router.
"""

import json
import socket
import threading
import time

import pytest

from machine_learning_replications_tpu.fleet.registry import ReplicaRegistry
from machine_learning_replications_tpu.serve import protocol
from machine_learning_replications_tpu.serve.transport import (
    EventLoopHttpServer,
    UpstreamError,
    UpstreamPool,
    UpstreamTimeout,
)


# ---------------------------------------------------------------------------
# protocol: the response parser and request builder (pure)
# ---------------------------------------------------------------------------


def _resp_bytes(code=200, body=b'{"p": 1}', extra="", keep_alive=True,
                content_length=None):
    cl = len(body) if content_length is None else content_length
    head = (
        f"HTTP/1.1 {code} X\r\nContent-Type: application/json\r\n"
        f"Content-Length: {cl}\r\n{extra}"
    )
    if not keep_alive:
        head += "Connection: close\r\n"
    return head.encode() + b"\r\n" + body


def test_response_parser_single_and_split_reads():
    p = protocol.ResponseParser()
    raw = _resp_bytes(body=b"hello")
    for cut in range(1, len(raw)):
        p = protocol.ResponseParser()
        p.feed(raw[:cut])
        first = p.next_response()
        p.feed(raw[cut:])
        resp = first or p.next_response()
        assert resp is not None
        assert resp.code == 200 and resp.body == b"hello"
        assert resp.keep_alive
        assert p.at_start()


def test_response_parser_connection_close_and_http10():
    p = protocol.ResponseParser()
    p.feed(_resp_bytes(keep_alive=False))
    assert not p.next_response().keep_alive
    p = protocol.ResponseParser()
    p.feed(b"HTTP/1.0 200 OK\r\nContent-Length: 0\r\n\r\n")
    assert not p.next_response().keep_alive  # 1.0 defaults to close


def test_response_parser_missing_content_length_is_unframeable():
    p = protocol.ResponseParser()
    p.feed(b"HTTP/1.1 200 OK\r\nContent-Type: text/plain\r\n\r\nbody")
    with pytest.raises(protocol.ProtocolError):
        p.next_response()


def test_response_parser_garbled_status_line():
    p = protocol.ResponseParser()
    p.feed(b"not http at all\r\n\r\n")
    with pytest.raises(protocol.ProtocolError):
        p.next_response()


def test_response_parser_leftover_bytes_visible_via_at_start():
    # An over-long reply (bytes past the declared Content-Length) parses
    # as a complete response PLUS leftover bytes — at_start() is how the
    # transport detects the poisoned framing and refuses to pool.
    p = protocol.ResponseParser()
    p.feed(_resp_bytes(body=b"okGARBAGE", content_length=2))
    resp = p.next_response()
    assert resp.code == 200 and resp.body == b"ok"
    assert not p.at_start()


def test_build_request_framing_roundtrip():
    data = protocol.build_request(
        "POST", "/predict", {"X-Request-Id": "r1"}, b'{"x": 1}',
        host="rep-1",
    )
    rp = protocol.RequestParser()
    rp.feed(data)
    req = rp.next_request()
    assert req.method == "POST" and req.path == "/predict"
    assert req.body == b'{"x": 1}'
    assert req.get_header("x-request-id") == "r1"
    assert req.get_header("host") == "rep-1"
    assert req.keep_alive


# ---------------------------------------------------------------------------
# transport: the loop-owned upstream pool against scripted raw upstreams
# ---------------------------------------------------------------------------


class _NullApp:
    def handle_request(self, req, rsp):
        rsp.send_json(404, {})

    def handle_protocol_error(self, exc, rsp):
        rsp.send_json(exc.code, {"error": exc.message}, close=True)


class _PoolHarness:
    """An event loop + UpstreamPool driven synchronously from the test
    thread: ``call`` posts one attempt onto the loop and waits for its
    completion."""

    def __init__(self, **pool_kw):
        self.server = EventLoopHttpServer(("127.0.0.1", 0), _NullApp())
        self.thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )
        self.thread.start()
        self.pool = UpstreamPool(self.server, **pool_kw)

    def call(self, addr, key="r", body=b'{"x": 1}', timeout_s=5.0,
             wait_s=10.0):
        data = protocol.build_request(
            "POST", "/predict", {"Content-Type": "application/json"}, body
        )
        done = threading.Event()
        out = []

        def go():
            self.pool.request(
                key, addr, data, timeout_s,
                lambda res: (out.append(res), done.set()),
            )

        self.server._post(go)
        assert done.wait(wait_s), "upstream attempt never completed"
        return out[0]

    def close(self):
        self.server.server_close()


def _read_request(sock) -> bytes:
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(65536)
        if not chunk:
            return buf
        buf += chunk
    head, _, rest = buf.partition(b"\r\n\r\n")
    length = 0
    for line in head.split(b"\r\n"):
        if line.lower().startswith(b"content-length:"):
            length = int(line.split(b":")[1])
    while len(rest) < length:
        chunk = sock.recv(65536)
        if not chunk:
            break
        rest += chunk
    return head + b"\r\n\r\n" + rest


class _ScriptedUpstream:
    """A raw-socket upstream whose Nth accepted connection runs the Nth
    script (the last script repeats). Each script gets the accepted
    socket and drives the exchange however the scenario needs."""

    def __init__(self, scripts, rcvbuf=None):
        self.scripts = scripts
        self.accepted = 0
        self.lock = threading.Lock()
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        if rcvbuf:
            self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, rcvbuf)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.addr = self.sock.getsockname()
        self._stop = False
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def _accept_loop(self):
        while not self._stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            with self.lock:
                i = min(self.accepted, len(self.scripts) - 1)
                self.accepted += 1
            threading.Thread(
                target=self._run, args=(conn, self.scripts[i]), daemon=True
            ).start()

    def _run(self, conn, script):
        try:
            script(conn)
        except Exception:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def close(self):
        self._stop = True
        try:
            self.sock.close()
        except OSError:
            pass


def _serve_ok(conn, n=1000):
    """Well-behaved keep-alive upstream: parse requests, answer each."""
    for _ in range(n):
        req = _read_request(conn)
        if not req or b"\r\n\r\n" not in req:
            return
        conn.sendall(_resp_bytes(body=b'{"ok": true}'))


def test_upstream_keepalive_reuse_and_stats():
    up = _ScriptedUpstream([_serve_ok])
    h = _PoolHarness()
    try:
        for _ in range(5):
            resp = h.call(up.addr)
            assert not isinstance(resp, Exception)
            assert resp.code == 200 and resp.body == b'{"ok": true}'
        stats = h.pool.stats()
        assert stats["opened_total"] == 1 and stats["reused_total"] == 4
        assert up.accepted == 1
    finally:
        h.close()
        up.close()


def test_upstream_premature_close_mid_headers():
    def mid_headers(conn):
        _read_request(conn)
        conn.sendall(b"HTTP/1.1 200 OK\r\nContent-Le")
        # close (script returns)

    up = _ScriptedUpstream([mid_headers])
    h = _PoolHarness()
    try:
        res = h.call(up.addr)
        assert isinstance(res, UpstreamError)
        assert "truncated" in str(res)
    finally:
        h.close()
        up.close()


def test_upstream_half_close_mid_body():
    def mid_body(conn):
        _read_request(conn)
        conn.sendall(_resp_bytes(body=b"short", content_length=100))

    up = _ScriptedUpstream([mid_body])
    h = _PoolHarness()
    try:
        res = h.call(up.addr)
        assert isinstance(res, UpstreamError)
        assert "truncated" in str(res)
    finally:
        h.close()
        up.close()


def test_upstream_overlong_reply_poisons_connection_not_next_attempt():
    # Connection 1 replies with bytes PAST its declared Content-Length:
    # the response itself is served, but the connection must close — a
    # reuse would hand the garbage to the next attempt as its status
    # line. Connection 2 serves correctly; the pool must have opened it
    # fresh rather than desyncing.
    def overlong(conn):
        _read_request(conn)
        conn.sendall(_resp_bytes(body=b'{"a": 1}GARBAGE',
                                 content_length=len(b'{"a": 1}')))
        time.sleep(0.5)  # stay open: a naive pool would reuse us

    up = _ScriptedUpstream([overlong, _serve_ok])
    h = _PoolHarness()
    try:
        r1 = h.call(up.addr)
        assert not isinstance(r1, Exception)
        assert r1.code == 200 and r1.body == b'{"a": 1}'
        r2 = h.call(up.addr)
        assert not isinstance(r2, Exception)
        assert r2.code == 200 and r2.body == b'{"ok": true}'
        assert up.accepted == 2, "poisoned connection was reused"
        assert h.pool.stats()["reused_total"] == 0
    finally:
        h.close()
        up.close()


def test_upstream_write_backpressure_slow_reader():
    # A replica that drains its socket slowly: with the send buffers
    # shrunk below the request size, the request CANNOT be written in
    # one send — the loop must ride partial writes + write-interest
    # until the reader catches up, then still parse the reply.
    body = b"x" * 48 * 1024

    def slow_reader(conn):
        time.sleep(0.3)  # let the client's buffers fill first
        req = _read_request(conn)
        assert req.endswith(body)
        conn.sendall(_resp_bytes(body=b'{"got": "all"}'))

    up = _ScriptedUpstream([slow_reader], rcvbuf=4096)
    h = _PoolHarness(configure_sock=lambda s: s.setsockopt(
        socket.SOL_SOCKET, socket.SO_SNDBUF, 8192
    ))
    try:
        res = h.call(up.addr, body=body)
        assert not isinstance(res, Exception), res
        assert res.code == 200 and res.body == b'{"got": "all"}'
    finally:
        h.close()
        up.close()


def test_upstream_reset_mid_reply_fails_instead_of_resending():
    # An RST after reply bytes have arrived is a TRUNCATED reply, not
    # the stale-keep-alive race: a transparent resend here would
    # silently execute the request twice after the replica already
    # started answering it. The send path and the EOF path must agree.
    import struct

    served = []

    def rst_mid_body(conn):
        served.append(1)
        _read_request(conn)
        conn.sendall(_resp_bytes(body=b"0123456789", content_length=100))
        time.sleep(0.1)
        # SO_LINGER 0 + close → RST, not FIN.
        conn.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))

    up = _ScriptedUpstream([rst_mid_body])
    h = _PoolHarness()
    try:
        res = h.call(up.addr)
        assert isinstance(res, UpstreamError), res
        assert "truncated" in str(res)
        time.sleep(0.2)
        assert len(served) == 1, "request was re-executed after a mid-reply reset"
    finally:
        h.close()
        up.close()


def test_upstream_stale_pooled_connection_transparent_resend():
    # The keep-alive race every proxy has: the pooled connection dies
    # between requests (idle reap, replica restart). The pool resends
    # ONCE on a fresh connection — the attempt succeeds, the failure
    # never surfaces to the retry policy.
    def serve_one_then_die(conn):
        _read_request(conn)
        conn.sendall(_resp_bytes(body=b'{"n": 1}'))
        # close immediately after the reply WITHOUT Connection: close —
        # the client pools it, then finds it dead.

    up = _ScriptedUpstream([serve_one_then_die, _serve_ok])
    h = _PoolHarness()
    try:
        r1 = h.call(up.addr)
        assert r1.code == 200
        time.sleep(0.1)  # let the server's FIN land
        r2 = h.call(up.addr)
        assert not isinstance(r2, Exception), r2
        assert r2.code == 200 and r2.body == b'{"ok": true}'
        assert up.accepted == 2
    finally:
        h.close()
        up.close()


def test_upstream_attempt_timeout_is_bounded():
    def black_hole(conn):
        _read_request(conn)
        time.sleep(5.0)

    up = _ScriptedUpstream([black_hole])
    h = _PoolHarness()
    try:
        t0 = time.monotonic()
        res = h.call(up.addr, timeout_s=0.4)
        assert isinstance(res, UpstreamTimeout)
        assert time.monotonic() - t0 < 2.0
    finally:
        h.close()
        up.close()


def test_upstream_idle_connections_reaped():
    up = _ScriptedUpstream([_serve_ok])
    h = _PoolHarness(idle_timeout_s=0.3)
    try:
        assert h.call(up.addr).code == 200
        assert h.pool.stats()["idle"] == 1
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and h.pool.stats()["idle"]:
            time.sleep(0.05)
        assert h.pool.stats()["idle"] == 0
        assert h.pool.stats()["connections"] == 0
    finally:
        h.close()
        up.close()


# ---------------------------------------------------------------------------
# registry: least-loaded power-of-two-choices
# ---------------------------------------------------------------------------


def _ready_registry(*rids, **kw):
    reg = ReplicaRegistry(**kw)
    for rid in rids:
        reg.register(rid, f"http://{rid}:1")
        reg.observe_probe(rid, ok=True, ready=True)
    return reg


def test_registry_least_loaded_prefers_fewer_outstanding():
    reg = _ready_registry("a", "b")
    # Equal latency on both; a carries in-flight attempts.
    reg.note_complete("a", 0.010)
    reg.note_dispatch("a")  # net: 1 outstanding after the complete
    reg.note_dispatch("a")
    reg.note_complete("b", 0.010)
    for _ in range(8):
        assert reg.pick()["id"] == "b"


def test_registry_least_loaded_prefers_lower_ewma_latency():
    reg = _ready_registry("a", "b")
    for _ in range(4):
        reg.note_dispatch("a")
        reg.note_complete("a", 0.200)  # slow replica
        reg.note_dispatch("b")
        reg.note_complete("b", 0.002)  # fast replica
    picks = [reg.pick()["id"] for _ in range(10)]
    assert picks.count("b") == 10


def test_registry_queue_depth_probe_signal_folds_into_score():
    reg = _ready_registry("a", "b")
    reg.note_complete("a", 0.010)
    reg.note_complete("b", 0.010)
    # Same observed latency, but a's OWN probe reports a deep queue
    # (e.g. load from another router worker this registry never saw).
    reg.observe_probe("a", ok=True, ready=True, queue_depth=20)
    reg.observe_probe("b", ok=True, ready=True, queue_depth=0)
    for _ in range(8):
        assert reg.pick()["id"] == "b"


def test_registry_ewma_update_and_outstanding_floor():
    reg = _ready_registry("a")
    reg.note_dispatch("a")
    reg.note_complete("a", 0.100)
    load = reg.get("a")["load"]
    assert load["ewma_latency_ms"] == pytest.approx(100.0)
    assert load["outstanding"] == 0
    reg.note_complete("a", 0.200)  # EWMA alpha=0.2: 100 + 0.2*100
    load = reg.get("a")["load"]
    assert load["ewma_latency_ms"] == pytest.approx(120.0)
    assert load["outstanding"] == 0  # never below zero
    # Conn-error completions release the slot without poisoning the EWMA.
    reg.note_dispatch("a")
    reg.note_complete("a", None)
    load = reg.get("a")["load"]
    assert load["ewma_latency_ms"] == pytest.approx(120.0)
    assert load["outstanding"] == 0


def test_registry_snapshot_carries_load_block():
    reg = _ready_registry("a")
    reg.note_dispatch("a")
    snap = reg.snapshot()[0]
    assert snap["load"]["outstanding"] == 1
    assert snap["load"]["ewma_latency_ms"] is None
    assert snap["load"]["last_queue_depth"] is None
    assert snap["load"]["score"] >= 0


# ---------------------------------------------------------------------------
# the router end to end: reuse across retries/hedges, load-aware picking
# ---------------------------------------------------------------------------


from test_fleet import _StubReplica, _start_stub, _stub_fleet, _teardown, \
    _post_predict  # noqa: E402
from machine_learning_replications_tpu.fleet.router import (  # noqa: E402
    FLEET_UPSTREAM_CONNS,
)


def test_router_connection_reuse_across_retries():
    # r1's breaker opens on its first 500; every subsequent request
    # lands on r2 over ONE pooled connection — reuse accounting must
    # show the retried request and its successors riding it.
    router, stubs, httpds, base = _stub_fleet(2, breaker_failures=1)
    reused0 = FLEET_UPSTREAM_CONNS.labels(event="reused").value
    try:
        stubs[0].mode = "error"
        for _ in range(6):
            code, headers, _ = _post_predict(base)
            assert code == 200 and headers["X-Replica"] == "r2"
        assert FLEET_UPSTREAM_CONNS.labels(event="reused").value \
            >= reused0 + 4
        stats = router.upstream.stats()
        assert stats["reused_total"] >= 4, stats
    finally:
        _teardown(router, httpds)


def test_router_connection_reuse_across_hedges():
    # The hedge's winning attempt opens (or reuses) the same pooled
    # connection later direct requests ride: the pool is shared across
    # ordinary attempts, retries, and hedges alike.
    router, stubs, httpds, base = _stub_fleet(
        2, hedge_ms=100.0, request_timeout_s=8.0, fail_threshold=50,
    )
    try:
        stubs[0].mode = "stall"
        stubs[0].stall_s = 1.5
        for _ in range(4):
            code, _, _ = _post_predict(base)
            assert code == 200
        stats = router.upstream.stats()
        # 4 ok replies but far fewer fresh connections than attempts:
        # the hedge target's connection was pooled and reused.
        assert stats["reused_total"] >= 2, stats
    finally:
        _teardown(router, httpds)


def test_router_load_signals_on_control_plane():
    router, stubs, httpds, base = _stub_fleet(2)
    try:
        for _ in range(6):
            assert _post_predict(base)[0] == 200
        import urllib.request

        with urllib.request.urlopen(
            base + "/fleet/replicas", timeout=5
        ) as resp:
            replicas = json.loads(resp.read())["replicas"]
        served = [r for r in replicas if r["load"]["ewma_latency_ms"]]
        assert served, replicas
        for r in replicas:
            assert r["load"]["outstanding"] == 0  # all settled
        with urllib.request.urlopen(base + "/healthz", timeout=5) as resp:
            health = json.loads(resp.read())
        assert health["upstream"]["opened_total"] >= 1
    finally:
        _teardown(router, httpds)


def test_cancelled_hedge_loser_releases_outstanding():
    # The losing attempt of a won hedge is CANCELLED (its completion
    # never fires): its replica's outstanding count must be released by
    # the settle path, or every lost hedge leaks +1 forever and the
    # least-loaded score starves the replica monotonically.
    router, stubs, httpds, base = _stub_fleet(
        2, hedge_ms=100.0, request_timeout_s=8.0, fail_threshold=50,
    )
    try:
        stubs[0].mode = "stall"
        stubs[0].stall_s = 2.0
        for _ in range(3):
            code, _, _ = _post_predict(base)
            assert code == 200
        deadline = time.monotonic() + 6
        while time.monotonic() < deadline:
            loads = {
                r["id"]: r["load"]["outstanding"]
                for r in router.registry.snapshot()
            }
            if all(v == 0 for v in loads.values()):
                break
            time.sleep(0.1)
        assert all(v == 0 for v in loads.values()), loads
    finally:
        _teardown(router, httpds)


def test_probe_queue_depth_garbage_does_not_poison_registry():
    # /readyz bodies come from anything that registered itself: a
    # non-numeric queue_depth must be ignored, not raise out of the
    # probe pass (which would freeze probing for every replica behind
    # the bad one).
    reg = _ready_registry("a")
    reg.observe_probe("a", ok=True, ready=True, queue_depth="n/a")
    assert reg.get("a")["load"]["last_queue_depth"] is None
    reg.observe_probe("a", ok=True, ready=True, queue_depth=3)
    assert reg.get("a")["load"]["last_queue_depth"] == 3
    reg.observe_probe("a", ok=True, ready=True, queue_depth=[1])
    assert reg.get("a")["load"]["last_queue_depth"] == 3  # kept, not lost


def test_loadgen_baseline_url_overhead_join(tmp_path):
    # One loadgen run, interleaved through-router and direct-replica
    # slices: the artifact carries both sides and the router-added
    # latency deltas as first-class fields.
    import os
    import subprocess
    import sys

    router, stubs, httpds, base = _stub_fleet(1)
    direct = f"http://127.0.0.1:{httpds[0].server_address[1]}"
    out_path = tmp_path / "bl.json"
    try:
        res = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "..", "tools",
                          "loadgen.py"),
             "--url", base, "--baseline-url", direct,
             "--connections", "4", "--duration", "2",
             "--baseline-segments", "2", "--out", str(out_path)],
            capture_output=True, text=True, timeout=120,
        )
        assert res.returncode == 0, res.stderr
        art = json.loads(out_path.read_text())
        assert art["n_ok"] > 0 and art["n_err"] == 0
        assert art["baseline"]["url"] == direct
        assert art["baseline"]["n_ok"] > 0
        assert art["baseline"]["n_err"] == 0
        ovh = art["router_overhead_ms"]
        assert ovh["segments_per_target"] == 2
        # A stub replica answers in microseconds; the router hop is real
        # but small — the field just has to be a number, both sides
        # having served.
        assert isinstance(ovh["p50"], float)
        assert isinstance(ovh["p99"], float)
    finally:
        _teardown(router, httpds)


def test_loadgen_baseline_url_rejects_perturb_and_open_mode():
    import os
    import subprocess
    import sys

    tool = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "loadgen.py")
    for extra in (["--perturb", "Age+1"], ["--mode", "open"]):
        res = subprocess.run(
            [sys.executable, tool, "--url", "http://127.0.0.1:1",
             "--baseline-url", "http://127.0.0.1:2",
             "--duration", "1"] + extra,
            capture_output=True, text=True, timeout=60,
        )
        assert res.returncode != 0
        assert "--baseline-url" in res.stderr


def test_router_prefers_fast_replica_under_sequential_load():
    # One replica 60 ms slower than the other: once both have a sample,
    # least-loaded picking concentrates sequential traffic on the fast
    # one (round-robin would split 50/50 and pay the slow tax on half).
    router, stubs, httpds, base = _stub_fleet(
        2, hedge_ms=0.0, request_timeout_s=8.0,
    )
    try:
        stubs[0].mode = "stall"
        stubs[0].stall_s = 0.06
        for _ in range(12):
            assert _post_predict(base)[0] == 200
        assert stubs[1].served > stubs[0].served, (
            stubs[0].served, stubs[1].served,
        )
    finally:
        _teardown(router, httpds)
