"""Protocol layer (serve/protocol.py) as pure functions — no sockets —
plus transport-level (serve/transport.py) behavior over live loopback
sockets with a stub application (no jax, no engine).

These are the wire rules the serving contract depends on, previously
reachable only through a live stdlib server: pipelined requests in one
TCP segment, requests split across arbitrary read boundaries, the
Content-Length framing guards and their connection-close semantics,
header/body caps (431/413), and the event loop's idle / slow-loris
reaping and listener lifecycle.
"""

import json
import socket
import threading
import time

import pytest

from machine_learning_replications_tpu.serve import protocol
from machine_learning_replications_tpu.serve.protocol import (
    HttpRequest,
    ProtocolError,
    RequestParser,
    build_response,
)
from machine_learning_replications_tpu.serve.transport import (
    EventLoopHttpServer,
)


def _req_bytes(
    method="POST", target="/predict", body=b'{"x": 1}',
    headers=None, version="HTTP/1.1",
):
    head = [f"{method} {target} {version}", "Host: t"]
    if body is not None:
        head.append(f"Content-Length: {len(body)}")
    for k, v in (headers or {}).items():
        head.append(f"{k}: {v}")
    return ("\r\n".join(head) + "\r\n\r\n").encode() + (body or b"")


# ---------------------------------------------------------------------------
# parser: framing, pipelining, split reads
# ---------------------------------------------------------------------------


def test_parse_single_request_with_body():
    p = RequestParser()
    p.feed(_req_bytes(body=b'{"a": 2}'))
    req = p.next_request()
    assert req.method == "POST" and req.path == "/predict"
    assert req.body == b'{"a": 2}'
    assert req.keep_alive is True  # HTTP/1.1 default
    assert req.get_header("host") == "t"
    assert p.next_request() is None and not p.has_partial()


def test_pipelined_requests_in_one_segment():
    """Two complete requests arriving in ONE feed drain one per call, in
    order — the keep-alive pipelining case the threaded server could only
    exercise through live sockets."""
    p = RequestParser()
    p.feed(_req_bytes(body=b"one") + _req_bytes(body=b"two!"))
    r1 = p.next_request()
    r2 = p.next_request()
    assert (r1.body, r2.body) == (b"one", b"two!")
    assert p.next_request() is None


def test_request_split_across_arbitrary_reads():
    """Byte-at-a-time feeding must produce exactly the same request —
    the parser owns reassembly, whatever fragmentation TCP produces."""
    raw = _req_bytes(body=b'{"split": true}')
    p = RequestParser()
    got = []
    for i in range(len(raw)):
        p.feed(raw[i:i + 1])
        req = p.next_request()
        if req is not None:
            got.append(req)
    assert len(got) == 1
    assert got[0].body == b'{"split": true}'
    # split across the header/body boundary specifically
    p = RequestParser()
    head_end = raw.find(b"\r\n\r\n") + 4
    p.feed(raw[:head_end + 3])
    assert p.next_request() is None  # body incomplete
    p.feed(raw[head_end + 3:])
    assert p.next_request().body == b'{"split": true}'


def test_query_string_parsing():
    p = RequestParser()
    p.feed(_req_bytes(method="GET", target="/metrics?format=json&n=5",
                      body=None))
    req = p.next_request()
    assert req.path == "/metrics"
    assert req.query_param("format", "prometheus") == "json"
    assert req.query_param("missing", "d") == "d"


# ---------------------------------------------------------------------------
# framing guards: Content-Length, caps, desync closes
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("cl", [None, "nope", "-5"])
def test_post_bad_content_length_is_400(cl):
    p = RequestParser()
    head = "POST /predict HTTP/1.1\r\nHost: t\r\n"
    if cl is not None:
        head += f"Content-Length: {cl}\r\n"
    p.feed((head + "\r\n").encode())
    with pytest.raises(ProtocolError) as ei:
        p.next_request()
    assert ei.value.code == 400
    assert ei.value.message == "missing or invalid Content-Length"
    assert ei.value.path == "/predict"  # the app can still trace it


def test_oversized_body_rejected_from_header_alone():
    p = RequestParser(max_body_bytes=1024)
    p.feed(b"POST /predict HTTP/1.1\r\nContent-Length: 999999\r\n\r\n")
    with pytest.raises(ProtocolError) as ei:
        p.next_request()
    assert ei.value.code == 413
    assert "exceeds 1024 bytes" in ei.value.message
    # the body was never required: rejection came from the header
    assert p.buffered < 1024


def test_oversized_headers_431():
    p = RequestParser(max_header_bytes=256)
    # terminated but oversized
    p.feed(b"GET / HTTP/1.1\r\nX-Big: " + b"x" * 300 + b"\r\n\r\n")
    with pytest.raises(ProtocolError) as ei:
        p.next_request()
    assert ei.value.code == 431
    # never-terminating header stream trips the cap too (the slow-loris
    # flood shape)
    p2 = RequestParser(max_header_bytes=256)
    p2.feed(b"GET / HTTP/1.1\r\nX-Drip: " + b"y" * 400)
    with pytest.raises(ProtocolError) as ei:
        p2.next_request()
    assert ei.value.code == 431


def test_transfer_encoding_rejected():
    p = RequestParser()
    p.feed(b"POST /predict HTTP/1.1\r\nTransfer-Encoding: chunked\r\n\r\n")
    with pytest.raises(ProtocolError) as ei:
        p.next_request()
    assert ei.value.code == 400


def test_get_with_declared_body_stays_in_sync():
    """A GET carrying a Content-Length body must be framed (consumed), or
    the body bytes would be parsed as the next request line."""
    p = RequestParser()
    p.feed(_req_bytes(method="GET", target="/healthz", body=b"stray")
           + _req_bytes(method="GET", target="/readyz", body=None))
    r1 = p.next_request()
    r2 = p.next_request()
    assert r1.path == "/healthz" and r1.body == b"stray"
    assert r2.path == "/readyz"


def test_malformed_request_line():
    p = RequestParser()
    p.feed(b"TOTAL GARBAGE\r\n\r\n")
    with pytest.raises(ProtocolError) as ei:
        p.next_request()
    assert ei.value.code == 400


def test_keep_alive_version_semantics():
    for version, conn_header, expected in [
        ("HTTP/1.1", None, True),
        ("HTTP/1.1", "close", False),
        ("HTTP/1.0", None, False),
        ("HTTP/1.0", "keep-alive", True),
    ]:
        p = RequestParser()
        headers = {"Connection": conn_header} if conn_header else {}
        p.feed(_req_bytes(method="GET", target="/", body=None,
                          headers=headers, version=version))
        assert p.next_request().keep_alive is expected, (
            version, conn_header)


# ---------------------------------------------------------------------------
# response building
# ---------------------------------------------------------------------------


def test_build_response_framing():
    out = build_response(200, b'{"ok": 1}', "application/json",
                         request_id="rid-1", keep_alive=True)
    head, _, body = out.partition(b"\r\n\r\n")
    assert head.startswith(b"HTTP/1.1 200 OK\r\n")
    assert b"Content-Length: 9" in head
    assert b"X-Request-Id: rid-1" in head
    assert b"Connection: close" not in head
    assert body == b'{"ok": 1}'

    out = build_response(503, b"{}", "application/json",
                         headers={"Retry-After": "3"}, keep_alive=False)
    assert b"Connection: close" in out
    assert b"Retry-After: 3" in out
    assert b"HTTP/1.1 503 Service Unavailable" in out


# ---------------------------------------------------------------------------
# transport over live sockets (stub app, no jax)
# ---------------------------------------------------------------------------


class _EchoApp:
    """Echoes the request body; /slow responds from another thread after
    a delay (the cross-thread completion path /predict uses)."""

    def __init__(self, marker="A"):
        self.marker = marker
        self.protocol_errors = []

    def handle_request(self, req, rsp):
        if req.path == "/slow":
            def later():
                time.sleep(0.05)
                rsp.send_json(200, {"worker": self.marker})
            threading.Thread(target=later, daemon=True).start()
            return
        if req.path == "/abort":
            rsp.abort()
            return
        rsp.send(200, req.body or self.marker.encode(), "text/plain")

    def handle_protocol_error(self, exc, rsp):
        self.protocol_errors.append(exc.code)
        rsp.send_json(exc.code, {"error": exc.message}, close=True)


def _start(app, port=0, **kw):
    server = EventLoopHttpServer(("127.0.0.1", port), app, **kw)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server, t


def _recv_one_response(sock):
    buf = b""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(65536)
        if not chunk:
            return buf
        buf += chunk
    head, _, rest = buf.partition(b"\r\n\r\n")
    length = 0
    for line in head.split(b"\r\n"):
        if line.lower().startswith(b"content-length:"):
            length = int(line.split(b":")[1])
    while len(rest) < length:
        rest += sock.recv(65536)
    return head, rest[:length], rest[length:]


def test_transport_pipelined_requests_served_in_order():
    server, t = _start(_EchoApp())
    try:
        host, port = server.server_address[:2]
        with socket.create_connection((host, port), timeout=5) as s:
            s.sendall(_req_bytes(body=b"first") + _req_bytes(body=b"second"))
            h1, b1, extra = _recv_one_response(s)
            assert b1 == b"first"
            # second reply rides the same connection
            if len(extra) == 0:
                h2, b2, _ = _recv_one_response(s)
            else:
                s2 = extra
                while b"\r\n\r\n" not in s2:
                    s2 += s.recv(65536)
                h2, _, b2 = s2.partition(b"\r\n\r\n")
                while not b2.endswith(b"second"):
                    b2 += s.recv(65536)
            assert b2.endswith(b"second")
    finally:
        server.shutdown()
        server.server_close()


def test_transport_cross_thread_completion_and_keepalive_reuse():
    server, t = _start(_EchoApp(marker="X"))
    try:
        host, port = server.server_address[:2]
        with socket.create_connection((host, port), timeout=5) as s:
            for _ in range(3):  # same socket, three sequential requests
                s.sendall(_req_bytes(method="GET", target="/slow",
                                     body=None))
                head, body, _ = _recv_one_response(s)
                assert b"200" in head.split(b"\r\n", 1)[0]
                assert json.loads(body) == {"worker": "X"}
    finally:
        server.shutdown()
        server.server_close()


def test_transport_idle_reaper_closes_parked_connections():
    """An idle keep-alive connection and a slow-loris partial request are
    both reaped after idle_timeout_s — EOF on the client side — while a
    fresh connection still gets served."""
    server, t = _start(_EchoApp(), idle_timeout_s=0.3)
    try:
        host, port = server.server_address[:2]
        idle = socket.create_connection((host, port), timeout=5)
        loris = socket.create_connection((host, port), timeout=5)
        loris.sendall(b"POST /predict HTTP/1.1\r\nContent-Le")  # partial
        idle.settimeout(3.0)
        loris.settimeout(3.0)
        assert idle.recv(1) == b""     # reaped: EOF, no bytes written
        assert loris.recv(1) == b""   # slow loris reaped the same way
        idle.close()
        loris.close()
        with socket.create_connection((host, port), timeout=5) as s:
            s.sendall(_req_bytes(body=b"alive"))
            _, body, _ = _recv_one_response(s)
            assert body == b"alive"
    finally:
        server.shutdown()
        server.server_close()


def test_transport_protocol_error_closes_connection():
    app = _EchoApp()
    server, t = _start(app)
    try:
        host, port = server.server_address[:2]
        with socket.create_connection((host, port), timeout=5) as s:
            s.sendall(b"POST /predict HTTP/1.1\r\nHost: t\r\n\r\n")  # no CL
            s.settimeout(5.0)
            buf = b""
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break  # server closed — the desync rule
                buf += chunk
            assert b"400" in buf.split(b"\r\n", 1)[0]
            assert b"missing or invalid Content-Length" in buf
        assert app.protocol_errors == [400]
    finally:
        server.shutdown()
        server.server_close()


def test_transport_abort_drops_connection_without_bytes():
    server, t = _start(_EchoApp())
    try:
        host, port = server.server_address[:2]
        with socket.create_connection((host, port), timeout=5) as s:
            s.sendall(_req_bytes(method="GET", target="/abort", body=None))
            s.settimeout(5.0)
            assert s.recv(1) == b""  # EOF with NOTHING written
    finally:
        server.shutdown()
        server.server_close()


def test_listener_released_without_loop_ever_running():
    """The warmup-failure shape: the listener binds, the loop never runs,
    server_close() must release the port for an immediate rebind."""
    app = _EchoApp()
    s1 = EventLoopHttpServer(("127.0.0.1", 0), app)
    port = s1.server_address[1]
    s1.server_close()
    # rebind the SAME port immediately — EADDRINUSE here is the bug
    s2 = EventLoopHttpServer(("127.0.0.1", port), app)
    assert s2.server_address[1] == port
    s2.server_close()


def test_so_reuseport_two_loops_share_a_port():
    """The pre-fork worker mechanism in one process: two event loops bind
    the same port with SO_REUSEPORT and the kernel spreads connections —
    eventually both workers serve traffic."""
    a1, a2 = _EchoApp(marker="1"), _EchoApp(marker="2")
    s1, t1 = _start(a1, reuse_port=True)
    port = s1.server_address[1]
    s2, t2 = _start(a2, port=port, reuse_port=True)
    try:
        seen = set()
        deadline = time.monotonic() + 20.0
        while len(seen) < 2 and time.monotonic() < deadline:
            with socket.create_connection(
                    ("127.0.0.1", port), timeout=5) as s:
                s.sendall(_req_bytes(method="GET", target="/", body=None))
                _, body, _ = _recv_one_response(s)
                seen.add(body.decode())
        assert seen == {"1", "2"}, (
            f"kernel never spread connections across both workers: {seen}"
        )
    finally:
        s1.shutdown()
        s1.server_close()
        s2.shutdown()
        s2.server_close()
