"""Device-side metrics vs sklearn.metrics (differential tests, SURVEY.md §4)."""

import numpy as np
import pytest
import sklearn.metrics as skm

from machine_learning_replications_tpu.utils import metrics


@pytest.fixture(scope="module")
def scored():
    r = np.random.default_rng(7)
    y = (r.random(400) < 0.3).astype(np.float64)
    s = np.clip(r.normal(0.3 + 0.3 * y, 0.25), 0, 1)
    return y, s


def test_roc_auc_matches_sklearn(scored):
    y, s = scored
    assert float(metrics.roc_auc(y, s)) == pytest.approx(
        skm.roc_auc_score(y, s), abs=1e-12
    )


def test_roc_auc_with_ties():
    r = np.random.default_rng(3)
    y = (r.random(300) < 0.4).astype(np.float64)
    s = np.round(r.random(300), 1)  # heavy ties
    assert float(metrics.roc_auc(y, s)) == pytest.approx(
        skm.roc_auc_score(y, s), abs=1e-12
    )


def test_roc_curve_area_and_points(scored):
    y, s = scored
    rc = metrics.roc_curve(y, s)
    area = np.trapezoid(np.asarray(rc.tpr), np.asarray(rc.fpr))
    assert area == pytest.approx(skm.roc_auc_score(y, s), abs=1e-12)
    # Every sklearn ROC vertex appears in our dense polyline.
    fpr_sk, tpr_sk, _ = skm.roc_curve(y, s)
    ours = {(round(a, 10), round(b, 10)) for a, b in zip(np.asarray(rc.fpr), np.asarray(rc.tpr))}
    for a, b in zip(fpr_sk, tpr_sk):
        assert (round(a, 10), round(b, 10)) in ours


def test_pr_curve_and_average_precision(scored):
    y, s = scored
    pr = metrics.precision_recall_curve(y, s)
    p_sk, r_sk, _ = skm.precision_recall_curve(y, s)
    ours = {(round(a, 10), round(b, 10)) for a, b in zip(np.asarray(pr.precision), np.asarray(pr.recall))}
    for a, b in zip(p_sk, r_sk):
        assert (round(a, 10), round(b, 10)) in ours
    assert float(metrics.average_precision(y, s)) == pytest.approx(
        skm.average_precision_score(y, s), abs=1e-10
    )


def test_classification_report_matches_sklearn(scored):
    y, s = scored
    yp = (s > 0.5).astype(np.float64)
    rep = metrics.classification_report(y, yp)
    sk = skm.classification_report(y, yp, output_dict=True)
    for i, cls in enumerate(("0.0", "1.0")):
        assert float(rep.precision[i]) == pytest.approx(sk[cls]["precision"], abs=1e-6)
        assert float(rep.recall[i]) == pytest.approx(sk[cls]["recall"], abs=1e-6)
        assert float(rep.f1[i]) == pytest.approx(sk[cls]["f1-score"], abs=1e-6)
        assert int(rep.support[i]) == sk[cls]["support"]
    assert float(rep.accuracy) == pytest.approx(sk["accuracy"], abs=1e-6)
    assert float(rep.macro_avg[2]) == pytest.approx(sk["macro avg"]["f1-score"], abs=1e-6)
    assert float(rep.weighted_avg[2]) == pytest.approx(
        sk["weighted avg"]["f1-score"], abs=1e-6
    )
    assert "precision" in metrics.report_text(rep)


def test_wald_ci_matches_reference_formula():
    # train_ensemble_public.py:76 band formula
    p = np.array([0.1, 0.5, 0.9])
    np.testing.assert_allclose(
        np.asarray(metrics.wald_ci_halfwidth(p, 100)),
        1.96 * np.sqrt(p * (1 - p) / 100),
        rtol=1e-12,
    )


def test_roc_auc_batch_host_matches_device_and_sklearn():
    """The host batched rank AUC (sweep's grid evaluator) must agree with
    the device roc_auc and sklearn exactly, ties included, and mirror the
    empty-class NaN contract."""
    import numpy as np
    from sklearn.metrics import roc_auc_score

    from machine_learning_replications_tpu.utils.metrics import (
        roc_auc,
        roc_auc_batch_host,
    )

    rng = np.random.default_rng(11)
    y = (rng.random(400) < 0.3).astype(np.float64)
    scores = np.round(rng.random((6, 400)), 2)  # heavy ties
    batch = roc_auc_batch_host(y, scores)
    for i in range(scores.shape[0]):
        np.testing.assert_allclose(batch[i], roc_auc_score(y, scores[i]), rtol=1e-12)
        np.testing.assert_allclose(
            batch[i], float(roc_auc(y, scores[i])), rtol=1e-6
        )
    assert np.isnan(roc_auc_batch_host(np.zeros(5), scores[:, :5])).all()
