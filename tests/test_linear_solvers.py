"""Linear-member training parity vs sklearn (convex ⇒ same optimum).

SURVEY.md §7: solver iteration paths differ by design (FISTA/Newton instead
of coordinate descent/liblinear/lbfgs); parity is demanded at the optimum:
coefficients to ~1e-4, selections and metrics exact.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from machine_learning_replications_tpu.config import LassoSelectConfig
from machine_learning_replications_tpu.models import feature_selection, solvers


@pytest.fixture(scope="module")
def lin_data():
    rng = np.random.default_rng(11)
    n, f = 300, 20
    X = rng.normal(size=(n, f))
    w = np.zeros(f)
    w[:6] = [2.0, -1.5, 1.0, 0.6, -0.4, 0.25]
    y = X @ w + 0.4 * rng.normal(size=n)
    return X, y


def test_alpha_grid_matches_sklearn(lin_data):
    from sklearn.linear_model import LassoCV

    X, y = lin_data
    cv = LassoCV(cv=10, random_state=2020).fit(X, y)
    ours = np.asarray(solvers.alpha_grid(jnp.asarray(X), jnp.asarray(y), 100, 1e-3))
    np.testing.assert_allclose(ours, cv.alphas_, rtol=1e-10)


def test_lasso_single_fit(lin_data):
    from sklearn.linear_model import Lasso

    X, y = lin_data
    alpha = 0.05
    sk = Lasso(alpha=alpha, tol=1e-10, max_iter=50_000).fit(X, y)
    full = jnp.ones(X.shape[0])
    Xc = jnp.asarray(X) - jnp.asarray(X).mean(0)
    lmax = solvers._power_lmax(Xc.T @ Xc) / X.shape[0]
    w = solvers.lasso_fista(
        jnp.asarray(X), jnp.asarray(y), alpha, full,
        jnp.zeros(X.shape[1]), lmax, tol=1e-10, max_iter=800,
    )
    b = solvers.lasso_intercept(jnp.asarray(X), jnp.asarray(y), w, full)
    np.testing.assert_allclose(np.asarray(w), sk.coef_, atol=2e-5)
    np.testing.assert_allclose(float(b), sk.intercept_, atol=2e-5)


def test_lasso_cv_matches_sklearn(lin_data):
    from sklearn.linear_model import LassoCV

    X, y = lin_data
    sk = LassoCV(cv=10, random_state=2020, tol=1e-8, max_iter=20_000).fit(X, y)
    coef, intercept, alpha_, alphas, mse_path = solvers.lasso_cv(
        jnp.asarray(X), jnp.asarray(y), cv_folds=10, max_iter=400
    )
    np.testing.assert_allclose(float(alpha_), sk.alpha_, rtol=1e-9)
    np.testing.assert_allclose(np.asarray(mse_path), sk.mse_path_, rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(coef), sk.coef_, atol=3e-5)


def test_feature_selection_matches_sklearn(cohort_full):
    from sklearn.feature_selection import SelectFromModel
    from sklearn.impute import KNNImputer
    from sklearn.linear_model import LassoCV

    X, y, _ = cohort_full
    lasso = LassoCV(random_state=2020, cv=10, tol=1e-8, max_iter=20_000)
    sfm = SelectFromModel(lasso, threshold=-np.inf, max_features=17).fit(X, y)
    sk_mask = sfm.get_support()
    mask, info = feature_selection.fit_select(X, y, LassoSelectConfig(max_iter=400))
    assert mask.sum() == 17
    # identical selected set
    assert (mask == sk_mask).all(), (np.where(mask)[0], np.where(sk_mask)[0])


def test_logreg_l1_matches_liblinear(lin_data):
    from sklearn.linear_model import LogisticRegression

    X, _ = lin_data
    rng = np.random.default_rng(5)
    yb = (X @ rng.normal(size=X.shape[1]) + rng.normal(size=X.shape[0]) > 0).astype(float)
    sk = LogisticRegression(
        class_weight="balanced", penalty="l1", solver="liblinear", tol=1e-8, max_iter=5000
    ).fit(X, yb)
    ours = solvers.logreg_l1_fit(jnp.asarray(X), jnp.asarray(yb), tol=1e-8, max_iter=4000)
    np.testing.assert_allclose(np.asarray(ours.coef), sk.coef_[0], atol=2e-3)
    np.testing.assert_allclose(float(ours.intercept), sk.intercept_[0], atol=2e-3)


def test_logreg_l2_matches_lbfgs():
    from sklearn.linear_model import LogisticRegression

    rng = np.random.default_rng(6)
    n = 400
    X = rng.random(size=(n, 3))  # meta-feature-like inputs in [0, 1]
    yb = (X @ np.array([2.0, 0.5, 3.0]) - 2.5 + 0.5 * rng.normal(size=n) > 0).astype(float)
    sk = LogisticRegression(class_weight="balanced", tol=1e-10, max_iter=5000).fit(X, yb)
    ours = solvers.logreg_l2_fit(jnp.asarray(X), jnp.asarray(yb))
    np.testing.assert_allclose(np.asarray(ours.coef), sk.coef_[0], atol=1e-5)
    np.testing.assert_allclose(float(ours.intercept), sk.intercept_[0], atol=1e-5)


def test_select_top_k_tie_behavior():
    coef = np.array([0.5, -0.5, 0.3, 0.0, 0.5])
    mask = feature_selection.select_top_k(coef, 2)
    # stable argsort: among the three |0.5| ties the *later* indices win
    assert list(np.where(mask)[0]) == [1, 4]


def test_lasso_fold_stats_sharded_matches_local(lin_data):
    """The mesh path's psum'd per-fold Grams equal the static-slice ones
    (up to float reassociation) — the parity contract of
    parallel/select_trainer.py on the 8-device CPU mesh."""
    from machine_learning_replications_tpu.parallel import make_mesh
    from machine_learning_replications_tpu.parallel.select_trainer import (
        lasso_fold_stats_sharded,
    )
    import jax

    X, y = lin_data
    local = solvers.lasso_fold_stats(jnp.asarray(X), jnp.asarray(y), 10)
    mesh = make_mesh()  # 8 virtual CPU devices on 'data'
    sharded = lasso_fold_stats_sharded(mesh, X, y, 10)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-9, atol=1e-9
        ),
        dict(local), dict(sharded),
    )


def test_lasso_cv_sharded_end_to_end_matches_local(lin_data):
    """fit_select with a mesh reproduces the single-device selection."""
    from machine_learning_replications_tpu.parallel import make_mesh

    X, y = lin_data
    cfg = LassoSelectConfig(max_features=6)
    mask0, info0 = feature_selection.fit_select(X, y, cfg)
    mask1, info1 = feature_selection.fit_select(X, y, cfg, mesh=make_mesh())
    np.testing.assert_array_equal(mask0, mask1)
    np.testing.assert_allclose(info0["coef"], info1["coef"], rtol=1e-6, atol=1e-9)
    np.testing.assert_allclose(
        info0["mse_path"], info1["mse_path"], rtol=1e-6, atol=1e-9
    )


def test_lasso_select_guard_subsample_and_error(lin_data):
    X, y = lin_data
    cfg = LassoSelectConfig(max_features=6, max_rows=100, scale_policy="error")
    with pytest.raises(ValueError, match="max_rows"):
        feature_selection.fit_select(X, y, cfg)

    cfg = LassoSelectConfig(max_features=6, max_rows=100, scale_policy="subsample")
    mask, info = feature_selection.fit_select(X, y, cfg)
    assert info["subsampled_from_rows"] == X.shape[0]
    assert mask.sum() == 6  # still selects; the guard only caps rows

    # A mesh multiplies the cap by the data-axis size: 8 × 100 < 300 still
    # subsamples, 8 × 50 likewise; 8 × 100 with n=300 does NOT (300 <= 800).
    from machine_learning_replications_tpu.parallel import make_mesh

    mesh = make_mesh()
    mask2, info2 = feature_selection.fit_select(X, y, cfg, mesh=mesh)
    assert "subsampled_from_rows" not in info2  # 300 <= 8 * 100


def test_lasso_cv_float32_with_large_feature_means():
    """f32 is the TPU production dtype; raw clinical features have
    mean/std ratios ~10 (heart rate, lab values). Without the global mean
    shift in lasso_fold_stats, the covariance-form centering
    ``sxx − m·x̄x̄ᵀ`` cancels catastrophically at this scale (measured ~8.6
    relative Gram error at 1M rows) and the selection silently diverges.
    This pins the f32 path to the f64 reference."""
    rng = np.random.default_rng(3)
    n, f = 50_000, 20
    # mean/std = 100 makes the unshifted cancellation measurable at test
    # size (3.4e-3 coef error, vs 0.0 shifted — both measured); the atol
    # below separates them, so removing the shift fails this test.
    X = (100.0 + rng.normal(size=(n, f))).astype(np.float64)
    w = np.zeros(f)
    w[:5] = [2.0, -1.5, 1.0, 0.6, -0.4]
    y = X @ w + 0.5 * rng.normal(size=n)

    ref = solvers.lasso_cv(jnp.asarray(X), jnp.asarray(y), cv_folds=10)
    got = solvers.lasso_cv(
        jnp.asarray(X, jnp.float32), jnp.asarray(y, jnp.float32), cv_folds=10
    )
    assert got[0].dtype == jnp.float32
    np.testing.assert_allclose(
        np.asarray(got[0]), np.asarray(ref[0]), rtol=0, atol=1e-3
    )
    np.testing.assert_allclose(float(got[1]), float(ref[1]), rtol=1e-2)
    mask_ref = feature_selection.select_top_k(np.asarray(ref[0]), 5)
    mask_got = feature_selection.select_top_k(np.asarray(got[0]), 5)
    np.testing.assert_array_equal(mask_ref, mask_got)


def test_lasso_fold_stats_sharded_f32_matches_f64():
    """Same f32 guard for the mesh path (it shares the shift)."""
    from machine_learning_replications_tpu.parallel import make_mesh
    from machine_learning_replications_tpu.parallel.select_trainer import (
        lasso_fold_stats_sharded,
    )
    import jax

    rng = np.random.default_rng(4)
    n, f = 20_000, 12
    X = 10.0 + rng.normal(size=(n, f))
    y = X[:, 0] - X[:, 1] + rng.normal(size=n)
    mesh = make_mesh()
    st64 = solvers.lasso_fold_stats(jnp.asarray(X), jnp.asarray(y), 10)
    try:
        jax.config.update("jax_enable_x64", False)
        st32 = lasso_fold_stats_sharded(mesh, X, y, 10)
    finally:
        jax.config.update("jax_enable_x64", True)
    # Shifted Grams are small numbers; f32 accumulation stays ~1e-4 relative.
    np.testing.assert_allclose(
        np.asarray(st32["sxx"]), np.asarray(st64["sxx"]), rtol=5e-3, atol=5e-2
    )
    np.testing.assert_allclose(
        np.asarray(st32["mu"]), np.asarray(st64["mu"]), rtol=1e-5
    )
