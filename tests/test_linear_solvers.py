"""Linear-member training parity vs sklearn (convex ⇒ same optimum).

SURVEY.md §7: solver iteration paths differ by design (FISTA/Newton instead
of coordinate descent/liblinear/lbfgs); parity is demanded at the optimum:
coefficients to ~1e-4, selections and metrics exact.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from machine_learning_replications_tpu.config import LassoSelectConfig
from machine_learning_replications_tpu.models import feature_selection, solvers


@pytest.fixture(scope="module")
def lin_data():
    rng = np.random.default_rng(11)
    n, f = 300, 20
    X = rng.normal(size=(n, f))
    w = np.zeros(f)
    w[:6] = [2.0, -1.5, 1.0, 0.6, -0.4, 0.25]
    y = X @ w + 0.4 * rng.normal(size=n)
    return X, y


def test_alpha_grid_matches_sklearn(lin_data):
    from sklearn.linear_model import LassoCV

    X, y = lin_data
    cv = LassoCV(cv=10, random_state=2020).fit(X, y)
    ours = np.asarray(solvers.alpha_grid(jnp.asarray(X), jnp.asarray(y), 100, 1e-3))
    np.testing.assert_allclose(ours, cv.alphas_, rtol=1e-10)


def test_lasso_single_fit(lin_data):
    from sklearn.linear_model import Lasso

    X, y = lin_data
    alpha = 0.05
    sk = Lasso(alpha=alpha, tol=1e-10, max_iter=50_000).fit(X, y)
    full = jnp.ones(X.shape[0])
    Xc = jnp.asarray(X) - jnp.asarray(X).mean(0)
    lmax = solvers._power_lmax(Xc.T @ Xc) / X.shape[0]
    w = solvers.lasso_fista(
        jnp.asarray(X), jnp.asarray(y), alpha, full,
        jnp.zeros(X.shape[1]), lmax, tol=1e-10, max_iter=800,
    )
    b = solvers.lasso_intercept(jnp.asarray(X), jnp.asarray(y), w, full)
    np.testing.assert_allclose(np.asarray(w), sk.coef_, atol=2e-5)
    np.testing.assert_allclose(float(b), sk.intercept_, atol=2e-5)


def test_lasso_cv_matches_sklearn(lin_data):
    from sklearn.linear_model import LassoCV

    X, y = lin_data
    sk = LassoCV(cv=10, random_state=2020, tol=1e-8, max_iter=20_000).fit(X, y)
    coef, intercept, alpha_, alphas, mse_path = solvers.lasso_cv(
        jnp.asarray(X), jnp.asarray(y), cv_folds=10, max_iter=400
    )
    np.testing.assert_allclose(float(alpha_), sk.alpha_, rtol=1e-9)
    np.testing.assert_allclose(np.asarray(mse_path), sk.mse_path_, rtol=2e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(coef), sk.coef_, atol=3e-5)


def test_feature_selection_matches_sklearn(cohort_full):
    from sklearn.feature_selection import SelectFromModel
    from sklearn.impute import KNNImputer
    from sklearn.linear_model import LassoCV

    X, y, _ = cohort_full
    lasso = LassoCV(random_state=2020, cv=10, tol=1e-8, max_iter=20_000)
    sfm = SelectFromModel(lasso, threshold=-np.inf, max_features=17).fit(X, y)
    sk_mask = sfm.get_support()
    mask, info = feature_selection.fit_select(X, y, LassoSelectConfig(max_iter=400))
    assert mask.sum() == 17
    # identical selected set
    assert (mask == sk_mask).all(), (np.where(mask)[0], np.where(sk_mask)[0])


def test_logreg_l1_matches_liblinear(lin_data):
    from sklearn.linear_model import LogisticRegression

    X, _ = lin_data
    rng = np.random.default_rng(5)
    yb = (X @ rng.normal(size=X.shape[1]) + rng.normal(size=X.shape[0]) > 0).astype(float)
    sk = LogisticRegression(
        class_weight="balanced", penalty="l1", solver="liblinear", tol=1e-8, max_iter=5000
    ).fit(X, yb)
    ours = solvers.logreg_l1_fit(jnp.asarray(X), jnp.asarray(yb), tol=1e-8, max_iter=4000)
    np.testing.assert_allclose(np.asarray(ours.coef), sk.coef_[0], atol=2e-3)
    np.testing.assert_allclose(float(ours.intercept), sk.intercept_[0], atol=2e-3)


def test_logreg_l2_matches_lbfgs():
    from sklearn.linear_model import LogisticRegression

    rng = np.random.default_rng(6)
    n = 400
    X = rng.random(size=(n, 3))  # meta-feature-like inputs in [0, 1]
    yb = (X @ np.array([2.0, 0.5, 3.0]) - 2.5 + 0.5 * rng.normal(size=n) > 0).astype(float)
    sk = LogisticRegression(class_weight="balanced", tol=1e-10, max_iter=5000).fit(X, yb)
    ours = solvers.logreg_l2_fit(jnp.asarray(X), jnp.asarray(yb))
    np.testing.assert_allclose(np.asarray(ours.coef), sk.coef_[0], atol=1e-5)
    np.testing.assert_allclose(float(ours.intercept), sk.intercept_[0], atol=1e-5)


def test_select_top_k_tie_behavior():
    coef = np.array([0.5, -0.5, 0.3, 0.0, 0.5])
    mask = feature_selection.select_top_k(coef, 2)
    # stable argsort: among the three |0.5| ties the *later* indices win
    assert list(np.where(mask)[0]) == [1, 4]
